"""Overload protection for the serving front: admission, budgets, breakers,
brownout.

The resilience ladder (PRs 2/3/5) protects a single in-flight generation;
nothing protects the *service* when too many generations arrive at once, or
when a sick stage/link makes every request expensive. This module is the
host-side control plane the :class:`~edgellm_tpu.serve.frontend.ServeFront`
composes — four independent controllers, every one driven by the shared
:class:`~edgellm_tpu.utils.clock.Clock` protocol so tests run them on a
:class:`~edgellm_tpu.utils.clock.FakeClock`:

- :class:`AdmissionController` — a bounded queue plus a deadline-feasibility
  check: an EWMA latency model (seconds per prompt token for prefill,
  seconds per generated token for decode — the per-layer profiling stance of
  *MCAP*, measured instead of assumed) prices each request, and a request
  whose queue wait + priced service time cannot fit its deadline is rejected
  *at submit*, before it wastes queue space and compute on a response nobody
  will read.
- :class:`RetryBudget` — a process-wide leaky bucket over *observed* ladder
  retries (the ``retried`` link counters) across ALL requests. One bad link
  under load turns every hop into ``max_retries`` retransmissions — a retry
  storm that multiplies the overload. The budget meters the storm: the front
  charges each call's retries after the fact and refuses to route new work
  onto a faulted path once the bucket is dry (overdraft is therefore bounded
  by a single call's worth), refilling at a configured rate.
- :class:`CircuitBreaker` — the classic closed → open → half-open machine,
  per stage and per link: consecutive failures (``StageLostError``,
  ``DecodeTimeout``, or a :class:`~edgellm_tpu.codecs.fec.LinkHealth` burn
  rate over threshold) open the circuit; while open, the front routes around
  the sick path or rejects instead of feeding it; after ``reset_timeout_s``
  a limited number of half-open probes test recovery.
- :class:`BrownoutController` — graceful degradation under load pressure,
  mirroring ``LinkHealth``'s dwell hysteresis: as the queue fills the level
  climbs and each level sheds quality before capacity — codec tier down,
  hedging off, token caps shrunk, and finally the lowest-priority work shed
  outright; as pressure recedes the level steps back down, one dwell at a
  time, so the service cannot flap between modes.
- :class:`StragglerDetector` — the gray-failure eye: windowed per-key
  latency samples (a key is a replica, a link, any measured peer) judged
  against the pooled fleet median. Crash-stops trip breakers; a peer that
  is merely *slow* passes every health check while silently dragging fleet
  p99 — the detector flags a key whose windowed p95 is a configured
  multiple of the fleet median, with min-sample and min-dwell hysteresis so
  one outlier cannot demote a healthy peer and re-promotion requires fresh
  measurements, never just elapsed time.

Everything here is pure host-side Python — no jax import, no graph residue
(the frontend's graphlint identity contract proves the composed front traces
the exact ``generate`` decode step).

Thread-safety: every controller is read on the obs scrape thread
(``health_summary`` / ``/snapshot.json``) while the decode thread mutates
it, so each owns a ``threading.Lock`` declared via ``@guarded_by``
(threadlint EG101 enforces the discipline package-wide). Public methods
take the lock; ``*_locked`` helpers assume it is held. Properties with
read-side state transitions (``CircuitBreaker.state`` lazily arming
half-open probes, ``RetryBudget.available`` refilling the bucket) are the
reason reads lock too — a scrape used to race those transitions.
"""
from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Optional

from ..utils.clock import MONOTONIC, Clock
from ..utils.concurrency import guarded_by

__all__ = [
    "COMPLETED", "REJECTED", "SHED", "TIMED_OUT", "FAILED_OVER", "FAILED",
    "QUEUED", "OUTCOMES",
    "AdmissionError", "QueueFull", "DeadlineInfeasible", "DeadlineExpired",
    "CircuitOpen", "RetryBudgetExhausted", "ServeFrontConfigError",
    "AdmissionConfig", "AdmissionController",
    "RetryBudgetConfig", "RetryBudget",
    "BreakerConfig", "CircuitBreaker",
    "BrownoutConfig", "BrownoutController",
    "StragglerConfig", "StragglerDetector",
]


# ---------------------------------------------------------------------------
# outcome taxonomy (the per-request records the front emits)
# ---------------------------------------------------------------------------

#: the request finished and its tokens are exact (no substitutions, no
#: failovers) — by construction token-identical to the same-seed direct call
COMPLETED = "completed"
#: refused at submit with a typed reason (queue full, infeasible deadline,
#: open circuit, dry retry budget)
REJECTED = "rejected"
#: dropped by policy under overload (brownout priority shed, or a queued
#: request whose deadline became infeasible before it reached the front)
SHED = "shed"
#: the per-request watchdog fired, or the deadline expired in the queue
TIMED_OUT = "timed_out"
#: the request finished, but only by routing around a failure (stage loss
#: re-plan, or a re-run on a fallback path)
FAILED_OVER = "failed_over"
#: the request ran but its output is not trustworthy (the link ladder
#: substituted a payload) or every path was exhausted
FAILED = "failed"
#: non-terminal: admitted, waiting in the queue for ``drain``
QUEUED = "queued"

#: every terminal outcome, in severity order
OUTCOMES = (COMPLETED, FAILED_OVER, SHED, TIMED_OUT, REJECTED, FAILED)


# ---------------------------------------------------------------------------
# typed admission errors (reason strings land in the outcome records)
# ---------------------------------------------------------------------------


class ServeFrontConfigError(ValueError):
    """A serving-front config field is out of range (raised with the field
    named, so ``run.py`` can surface it verbatim)."""


class AdmissionError(RuntimeError):
    """A request was refused before any device work. ``reason`` is the
    machine-readable tag the front stores in the outcome record."""

    reason = "rejected"


class QueueFull(AdmissionError):
    """The bounded submit queue is at capacity."""

    reason = "queue_full"


class DeadlineInfeasible(AdmissionError):
    """The priced service time (plus the current backlog) cannot fit inside
    the request's deadline — finishing late would waste the compute."""

    reason = "deadline_infeasible"


class DeadlineExpired(AdmissionError):
    """The request's remaining deadline budget reached zero while it was
    parked, queued, or mid-flight. Deadline propagation decrements
    ``Request.deadline_s`` through park → place → queue → prefill →
    migration → decode, and every downstream stage refuses expired work
    with this typed reason instead of burning tokens on an answer nobody
    can use (the record finishes ``timed_out``)."""

    reason = "deadline_expired"


class CircuitOpen(AdmissionError):
    """Every route to the model is behind an open circuit breaker."""

    reason = "circuit_open"


class RetryBudgetExhausted(AdmissionError):
    """The process-wide retry budget is dry and the only available path is
    the faulted link that drained it."""

    reason = "retry_budget_exhausted"


# ---------------------------------------------------------------------------
# admission: bounded queue + deadline feasibility from measured latency
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """Queue bound and the latency model's priors.

    ``init_prefill_s_per_token`` / ``init_decode_s_per_token`` seed the EWMA
    before the first measurement (deliberately pessimistic defaults: a cold
    model should shed load, not promise deadlines it has never measured);
    ``ewma_alpha`` is the update weight of each new measurement;
    ``safety_factor`` inflates the estimate before comparing against the
    deadline, absorbing jitter the EWMA smooths away."""

    max_queue_depth: int = 64
    init_prefill_s_per_token: float = 2e-3
    init_decode_s_per_token: float = 2e-2
    ewma_alpha: float = 0.3
    safety_factor: float = 1.2

    def __post_init__(self):
        if (isinstance(self.max_queue_depth, bool)
                or not isinstance(self.max_queue_depth, int)
                or self.max_queue_depth < 1):
            raise ValueError(f"max_queue_depth must be an integer >= 1, "
                             f"got {self.max_queue_depth!r}")
        for f, lo in (("init_prefill_s_per_token", 0.0),
                      ("init_decode_s_per_token", 0.0),
                      ("ewma_alpha", 0.0), ("safety_factor", 1.0)):
            v = getattr(self, f)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"{f} must be a number, got {v!r}")
            if v <= lo if f != "safety_factor" else v < lo:
                raise ValueError(f"{f} must be > {lo}, got {v!r}")
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError(f"ewma_alpha must be in (0, 1], "
                             f"got {self.ewma_alpha!r}")


@guarded_by("_lock", fields=["_prefill_s_tok", "_decode_s_tok", "admitted",
                             "rejected_queue_full", "rejected_deadline",
                             "measurements"])
class AdmissionController:
    """Prices requests with a measured latency model and refuses infeasible
    or over-capacity work with typed errors.

    The front calls :meth:`admit` at submit time (raises — the front turns
    the typed error into a ``rejected`` record) and :meth:`record` after
    every completed generation so the price tracks the deployed reality
    (codec tier, batch shape, current hardware) instead of a config
    constant."""

    def __init__(self, config: Optional[AdmissionConfig] = None):
        self.cfg = config if config is not None else AdmissionConfig()
        self._lock = threading.Lock()
        self._prefill_s_tok = self.cfg.init_prefill_s_per_token
        self._decode_s_tok = self.cfg.init_decode_s_per_token
        self.admitted = 0
        self.rejected_queue_full = 0
        self.rejected_deadline = 0
        self.measurements = 0

    def _estimate_s_locked(self, prompt_tokens: int, new_tokens: int) -> float:
        return (prompt_tokens * self._prefill_s_tok
                + new_tokens * self._decode_s_tok)

    def estimate_s(self, prompt_tokens: int, new_tokens: int) -> float:
        """Priced service time for one request at the current EWMA rates."""
        with self._lock:
            return self._estimate_s_locked(prompt_tokens, new_tokens)

    def _feasible_locked(self, prompt_tokens: int, new_tokens: int,
                         deadline_s: Optional[float],
                         backlog_s: float) -> bool:
        if deadline_s is None:
            return True
        est = backlog_s + self._estimate_s_locked(prompt_tokens, new_tokens)
        return est * self.cfg.safety_factor <= deadline_s

    def feasible(self, prompt_tokens: int, new_tokens: int,
                 deadline_s: Optional[float],
                 backlog_s: float = 0.0) -> bool:
        """Whether queue backlog + priced service time fits the deadline."""
        with self._lock:
            return self._feasible_locked(prompt_tokens, new_tokens,
                                         deadline_s, backlog_s)

    def admit(self, prompt_tokens: int, new_tokens: int,
              queue_depth: int, deadline_s: Optional[float],
              backlog_s: float = 0.0) -> None:
        """Raise the typed refusal, or count the admission."""
        with self._lock:
            if queue_depth >= self.cfg.max_queue_depth:
                self.rejected_queue_full += 1
                raise QueueFull(
                    f"queue at capacity "
                    f"({queue_depth}/{self.cfg.max_queue_depth})")
            if not self._feasible_locked(prompt_tokens, new_tokens,
                                         deadline_s, backlog_s):
                self.rejected_deadline += 1
                est = backlog_s + self._estimate_s_locked(prompt_tokens,
                                                          new_tokens)
                raise DeadlineInfeasible(
                    f"estimated {est:.3f}s (x{self.cfg.safety_factor:g} "
                    f"safety) cannot fit the {deadline_s:g}s deadline")
            self.admitted += 1

    def record(self, prompt_tokens: int, prefill_s: float,
               decode_steps: int, decode_s: float) -> None:
        """Fold one generation's measured walls into the EWMA price."""
        a = self.cfg.ewma_alpha
        with self._lock:
            if prompt_tokens > 0 and prefill_s > 0:
                self._prefill_s_tok += a * (prefill_s / prompt_tokens
                                            - self._prefill_s_tok)
            if decode_steps > 0 and decode_s > 0:
                self._decode_s_tok += a * (decode_s / decode_steps
                                           - self._decode_s_tok)
            self.measurements += 1

    def summary(self) -> dict:
        with self._lock:
            return {
                "admitted": self.admitted,
                "rejected_queue_full": self.rejected_queue_full,
                "rejected_deadline": self.rejected_deadline,
                "measurements": self.measurements,
                "prefill_s_per_token": self._prefill_s_tok,
                "decode_s_per_token": self._decode_s_tok,
            }


# ---------------------------------------------------------------------------
# retry budget: a process-wide leaky bucket over observed ladder retries
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RetryBudgetConfig:
    """``capacity`` retries may be spent instantly; the bucket refills at
    ``refill_per_s`` (0 = a hard lifetime cap)."""

    capacity: int = 256
    refill_per_s: float = 4.0

    def __post_init__(self):
        if (isinstance(self.capacity, bool)
                or not isinstance(self.capacity, int) or self.capacity < 1):
            raise ValueError(f"capacity must be an integer >= 1, "
                             f"got {self.capacity!r}")
        if (isinstance(self.refill_per_s, bool)
                or not isinstance(self.refill_per_s, (int, float))
                or self.refill_per_s < 0):
            raise ValueError(f"refill_per_s must be a number >= 0, "
                             f"got {self.refill_per_s!r}")


@guarded_by("_lock", fields=["_level", "_last", "spent", "denied"])
class RetryBudget:
    """Meters ladder retries across every request the front serves.

    The graph's retries are statically unrolled (PR 2), so they cannot be
    interrupted mid-call; the enforceable contract is *routing*: the front
    calls :meth:`charge` with each call's observed ``retried`` total, and
    :meth:`exhausted` before dispatching onto a faulted path. Once the
    bucket is dry, faulted-path work is refused (typed
    :class:`RetryBudgetExhausted`) until refill — so the total retry spend
    is bounded by ``capacity + refill + one call's overdraft``, never by
    the (unbounded) arrival rate."""

    def __init__(self, config: Optional[RetryBudgetConfig] = None,
                 clock: Clock = MONOTONIC):
        self.cfg = config if config is not None else RetryBudgetConfig()
        self.clock = clock
        self._lock = threading.Lock()
        self._level = float(self.cfg.capacity)
        self._last: Optional[float] = None
        self.spent = 0
        self.denied = 0

    def _refill_locked(self) -> None:
        now = self.clock()
        if self._last is not None and self.cfg.refill_per_s > 0:
            self._level = min(float(self.cfg.capacity),
                              self._level
                              + (now - self._last) * self.cfg.refill_per_s)
        self._last = now

    @property
    def available(self) -> float:
        """Retries the bucket will currently fund (floored at 0)."""
        with self._lock:
            self._refill_locked()
            return max(self._level, 0.0)

    def exhausted(self) -> bool:
        return self.available < 1.0

    def charge(self, retries: int) -> None:
        """Debit observed retries (post-hoc; may overdraft one call)."""
        if retries < 0:
            raise ValueError(f"cannot charge {retries} retries")
        if retries == 0:
            return
        with self._lock:
            self._refill_locked()
            self._level -= retries
            self.spent += int(retries)

    def deny(self) -> None:
        """Count a routing refusal caused by an empty bucket."""
        with self._lock:
            self.denied += 1

    def summary(self) -> dict:
        with self._lock:
            self._refill_locked()
            return {
                "capacity": self.cfg.capacity,
                "refill_per_s": self.cfg.refill_per_s,
                "available": max(self._level, 0.0),
                "spent": self.spent,
                "denied": self.denied,
            }


# ---------------------------------------------------------------------------
# circuit breaker: closed -> open -> half-open, injectable clock
# ---------------------------------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


@dataclasses.dataclass(frozen=True)
class BreakerConfig:
    """``failure_threshold`` consecutive failures open the circuit;
    ``reset_timeout_s`` later, ``half_open_probes`` trial requests may pass —
    one success closes it, one failure re-opens it. ``burn_threshold`` maps
    a :class:`~edgellm_tpu.codecs.fec.LinkHealth` burn rate onto the
    success/failure signal for link breakers."""

    failure_threshold: int = 3
    reset_timeout_s: float = 30.0
    half_open_probes: int = 1
    burn_threshold: float = 1.0

    def __post_init__(self):
        for f in ("failure_threshold", "half_open_probes"):
            v = getattr(self, f)
            if isinstance(v, bool) or not isinstance(v, int) or v < 1:
                raise ValueError(f"{f} must be an integer >= 1, got {v!r}")
        for f in ("reset_timeout_s", "burn_threshold"):
            v = getattr(self, f)
            if isinstance(v, bool) or not isinstance(v, (int, float)) or v <= 0:
                raise ValueError(f"{f} must be a number > 0, got {v!r}")


@guarded_by("_lock", fields=["_state", "_failures", "_opened_at", "_probes",
                             "opens", "total_failures"])
class CircuitBreaker:
    """One guarded resource (a stage, a link, a whole backend).

    States: *closed* (healthy — every request passes, consecutive failures
    counted), *open* (sick — every request refused until
    ``reset_timeout_s`` elapses on the injected clock), *half-open*
    (probing — up to ``half_open_probes`` requests pass; the first success
    closes, the first failure re-opens and re-arms the timeout)."""

    def __init__(self, name: str, config: Optional[BreakerConfig] = None,
                 clock: Clock = MONOTONIC):
        self.name = name
        self.cfg = config if config is not None else BreakerConfig()
        self.clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._probes = 0
        self.opens = 0
        self.total_failures = 0

    def _state_locked(self) -> str:
        """The open -> half-open clock transition; caller holds the lock.
        The scrape thread calls this through :meth:`summary` concurrently
        with decode-thread ``allow``/``record_failure`` — the transition
        mutating ``_state``/``_probes`` is exactly why reads lock."""
        if (self._state == OPEN and self._opened_at is not None
                and self.clock() - self._opened_at >= self.cfg.reset_timeout_s):
            self._state = HALF_OPEN
            self._probes = self.cfg.half_open_probes
        return self._state

    @property
    def state(self) -> str:
        """Current state; lazily transitions open -> half-open on the clock
        (there is no background thread to do it eagerly)."""
        with self._lock:
            return self._state_locked()

    def allow(self) -> bool:
        """May a request pass right now? Half-open passes consume a probe."""
        with self._lock:
            s = self._state_locked()
            if s == CLOSED:
                return True
            if s == HALF_OPEN and self._probes > 0:
                self._probes -= 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state_locked() == HALF_OPEN:
                self._state = CLOSED
            self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self.total_failures += 1
            s = self._state_locked()
            if s == HALF_OPEN:
                self._open_locked()
                return
            if s == CLOSED:
                self._failures += 1
                if self._failures >= self.cfg.failure_threshold:
                    self._open_locked()

    def trip(self) -> None:
        """Open unconditionally (a stage marked dead needs no vote)."""
        with self._lock:
            if self._state_locked() != OPEN:
                self._open_locked()

    def _open_locked(self) -> None:
        self._state = OPEN
        self._opened_at = self.clock()
        self._failures = 0
        self.opens += 1

    def reset(self) -> None:
        """Back to fresh-closed (a respawned replica starts with a clean
        failure record); ``opens``/``total_failures`` survive as lifetime
        counters so the reset is visible in the summary, not erased."""
        with self._lock:
            self._state = CLOSED
            self._failures = 0
            self._opened_at = None
            self._probes = 0

    def observe_burn(self, burn_rate: float) -> None:
        """Fold a LinkHealth burn-rate reading into the failure signal."""
        if burn_rate >= self.cfg.burn_threshold:
            self.record_failure()
        else:
            self.record_success()

    def summary(self) -> dict:
        with self._lock:
            return {"state": self._state_locked(), "opens": self.opens,
                    "consecutive_failures": self._failures,
                    "total_failures": self.total_failures}


# ---------------------------------------------------------------------------
# brownout: staged quality degradation under load, with dwell hysteresis
# ---------------------------------------------------------------------------

#: what each brownout level turns off, cumulatively
BROWNOUT_LEVELS = (
    "normal",            # 0: full quality
    "tier_down",         # 1: boundary codec one tier lower
    "hedging_off",       # 2: + no hedged duplicate transmissions
    "token_cap",         # 3: + max_new_tokens shrunk
    "shed_low_priority", # 4: + lowest-priority requests shed at submit
)


@dataclasses.dataclass(frozen=True)
class BrownoutConfig:
    """Load thresholds (queue fullness in [0, 1]) with rate + time
    hysteresis, mirroring :class:`~edgellm_tpu.codecs.fec.LinkHealthConfig`:
    ``degrade_load`` must sit strictly above ``promote_load`` (the rate
    band) and ``min_dwell_s`` is the clock floor between level switches."""

    degrade_load: float = 0.8
    promote_load: float = 0.4
    min_dwell_s: float = 0.0
    max_level: int = len(BROWNOUT_LEVELS) - 1
    token_cap_factor: float = 0.5
    shed_below_priority: int = 1

    def __post_init__(self):
        for f in ("degrade_load", "promote_load"):
            v = getattr(self, f)
            if (isinstance(v, bool) or not isinstance(v, (int, float))
                    or not 0.0 < v <= 1.0):
                raise ValueError(f"{f} must be in (0, 1], got {v!r}")
        if self.promote_load >= self.degrade_load:
            raise ValueError(
                f"promote_load ({self.promote_load}) must be below "
                f"degrade_load ({self.degrade_load}) — no hysteresis band")
        if (isinstance(self.min_dwell_s, bool)
                or not isinstance(self.min_dwell_s, (int, float))
                or self.min_dwell_s < 0):
            raise ValueError(f"min_dwell_s must be a number >= 0, "
                             f"got {self.min_dwell_s!r}")
        if (isinstance(self.max_level, bool)
                or not isinstance(self.max_level, int)
                or not 1 <= self.max_level <= len(BROWNOUT_LEVELS) - 1):
            raise ValueError(f"max_level must be an integer in "
                             f"[1, {len(BROWNOUT_LEVELS) - 1}], "
                             f"got {self.max_level!r}")
        if (isinstance(self.token_cap_factor, bool)
                or not isinstance(self.token_cap_factor, (int, float))
                or not 0.0 < self.token_cap_factor <= 1.0):
            raise ValueError(f"token_cap_factor must be in (0, 1], "
                             f"got {self.token_cap_factor!r}")
        if (isinstance(self.shed_below_priority, bool)
                or not isinstance(self.shed_below_priority, int)):
            raise ValueError(f"shed_below_priority must be an integer, "
                             f"got {self.shed_below_priority!r}")


@guarded_by("_lock", fields=["level", "switches", "observations", "sheds",
                             "_last_switch"])
class BrownoutController:
    """Walks the brownout ladder one level per dwell as load crosses the
    hysteresis band; the front consults the properties on every dispatch.

    ``observe(load)`` once per submit/drain tick with the queue fullness.
    ``load >= degrade_load`` steps the level up (more degraded),
    ``load <= promote_load`` steps it back down — each switch arming the
    ``min_dwell_s`` clock so recovering load cannot flap the service
    between quality modes."""

    def __init__(self, config: Optional[BrownoutConfig] = None,
                 clock: Clock = MONOTONIC):
        self.cfg = config if config is not None else BrownoutConfig()
        self.clock = clock
        self._lock = threading.Lock()
        self.level = 0
        self.switches = 0
        self.observations = 0
        self.sheds = 0
        self._last_switch: Optional[float] = None

    def observe(self, load: float) -> int:
        """Fold one load reading (queue fullness in [0, 1]) into the level."""
        with self._lock:
            self.observations += 1
            now = self.clock()
            dwell_ok = (self._last_switch is None
                        or now - self._last_switch >= self.cfg.min_dwell_s)
            if (load >= self.cfg.degrade_load and dwell_ok
                    and self.level < self.cfg.max_level):
                self.level += 1
                self.switches += 1
                self._last_switch = now
            elif (load <= self.cfg.promote_load and dwell_ok
                  and self.level > 0):
                self.level -= 1
                self.switches += 1
                self._last_switch = now
            return self.level

    # -- what the current level turns off ---------------------------------

    @property
    def mode(self) -> str:
        return BROWNOUT_LEVELS[self.level]

    @property
    def tier_bias(self) -> int:
        """Extra codec-ladder steps to apply on top of LinkHealth's tier."""
        return 1 if self.level >= 1 else 0

    @property
    def hedging_enabled(self) -> bool:
        return self.level < 2

    def token_cap(self, requested: int) -> int:
        """The granted ``max_new_tokens`` for a request asking for
        ``requested`` at the current level."""
        if self.level < 3:
            return requested
        return max(1, int(requested * self.cfg.token_cap_factor))

    def should_shed(self, priority: int) -> bool:
        """At the shed level, drop requests below the priority floor."""
        with self._lock:
            if self.level >= 4 and priority < self.cfg.shed_below_priority:
                self.sheds += 1
                return True
            return False

    def summary(self) -> dict:
        with self._lock:
            return {"level": self.level, "mode": BROWNOUT_LEVELS[self.level],
                    "switches": self.switches,
                    "observations": self.observations,
                    "sheds": self.sheds}


# ---------------------------------------------------------------------------
# straggler detection: windowed quantiles vs the fleet median, with dwell
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class StragglerConfig:
    """Gray-failure thresholds. A key is flagged when its windowed
    ``quantile`` (p95 by default) is at least ``p95_multiple`` times the
    pooled fleet median, judged only with ``min_samples`` fresh samples in
    the key's window and at least two measured keys (one peer alone has no
    fleet to be slower than). ``min_dwell_s`` is the hysteresis floor
    between verdict flips in either direction; samples expire after
    ``window_s`` and each key's window is bounded at ``max_samples``."""

    p95_multiple: float = 3.0
    quantile: float = 0.95
    window_s: float = 120.0
    max_samples: int = 256
    min_samples: int = 8
    min_dwell_s: float = 5.0

    def __post_init__(self):
        for f, lo in (("p95_multiple", 1.0), ("window_s", 0.0)):
            v = getattr(self, f)
            if isinstance(v, bool) or not isinstance(v, (int, float)) or v <= lo:
                raise ValueError(f"{f} must be a number > {lo}, got {v!r}")
        if (isinstance(self.quantile, bool)
                or not isinstance(self.quantile, (int, float))
                or not 0.0 < self.quantile < 1.0):
            raise ValueError(f"quantile must be in (0, 1), "
                             f"got {self.quantile!r}")
        for f in ("max_samples", "min_samples"):
            v = getattr(self, f)
            if isinstance(v, bool) or not isinstance(v, int) or v < 1:
                raise ValueError(f"{f} must be an integer >= 1, got {v!r}")
        if self.min_samples > self.max_samples:
            raise ValueError(
                f"min_samples ({self.min_samples}) cannot exceed "
                f"max_samples ({self.max_samples})")
        if (isinstance(self.min_dwell_s, bool)
                or not isinstance(self.min_dwell_s, (int, float))
                or self.min_dwell_s < 0):
            raise ValueError(f"min_dwell_s must be a number >= 0, "
                             f"got {self.min_dwell_s!r}")


def _linear_quantile(ordered: list, q: float) -> float:
    """numpy's default (linear-interpolation) quantile over a sorted list —
    kept bit-compatible with ``np.quantile(..., method="linear")`` so the
    detector's window math is testable against the numpy reference without
    importing numpy into this pure-host module."""
    n = len(ordered)
    if n == 1:
        return float(ordered[0])
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return float(ordered[lo] * (1.0 - frac) + ordered[hi] * frac)


@guarded_by("_lock", fields=["_samples", "_flagged", "_last_flip",
                             "observed", "demotions", "promotions"])
class StragglerDetector:
    """Flags the peers that are *slow*, not dead.

    ``observe(key, latency_s)`` feeds one measured latency (a completed
    request's service time, one migration-page transfer, ...) into the
    key's window on the injected clock. Verdicts are recomputed lazily on
    read (:meth:`is_straggler` / :meth:`stragglers` / :meth:`summary`):

    - **flag** a key whose windowed ``cfg.quantile`` is >=
      ``cfg.p95_multiple`` x the pooled fleet median, once it has
      ``cfg.min_samples`` in-window samples and a fleet (>= 2 keys) exists;
    - **re-promote** the key when fresh measurements bring the quantile
      back under the threshold — a flagged key with an empty window stays
      flagged (re-promotion requires re-measure, never just elapsed time);
    - both flips honor ``cfg.min_dwell_s`` so a borderline peer cannot flap
      in and out of the rotation.

    :meth:`fleet_quantile` exposes the pooled windowed quantile — the
    hedge-delay source (hedge a request once it has been outstanding longer
    than the fleet's observed q-th percentile)."""

    def __init__(self, config: Optional[StragglerConfig] = None,
                 clock: Clock = MONOTONIC):
        self.cfg = config if config is not None else StragglerConfig()
        self.clock = clock
        self._lock = threading.Lock()
        self._samples: dict = {}     # key -> deque[(t, latency_s)]
        self._flagged: dict = {}     # key -> flagged_at
        self._last_flip: dict = {}   # key -> last verdict flip time
        self.observed = 0
        self.demotions = 0
        self.promotions = 0

    # -- sample intake ------------------------------------------------------

    def observe(self, key, latency_s: float) -> None:
        """Record one measured latency for ``key`` at the current time."""
        if latency_s < 0:
            raise ValueError(f"latency_s must be >= 0, got {latency_s!r}")
        with self._lock:
            now = self.clock()
            dq = self._samples.setdefault(key, collections.deque())
            dq.append((now, float(latency_s)))
            while len(dq) > self.cfg.max_samples:
                dq.popleft()
            self.observed += 1
            self._expire_locked(now)

    def _expire_locked(self, now: float) -> None:
        horizon = now - self.cfg.window_s
        for key in list(self._samples):
            dq = self._samples[key]
            while dq and dq[0][0] <= horizon:
                dq.popleft()
            if not dq:
                del self._samples[key]

    # -- windowed quantile math --------------------------------------------

    def quantile(self, key, q: Optional[float] = None) -> Optional[float]:
        """The key's windowed q-th quantile (``cfg.quantile`` by default),
        or None with no in-window samples."""
        with self._lock:
            self._expire_locked(self.clock())
            dq = self._samples.get(key)
            if not dq:
                return None
            vals = sorted(v for _, v in dq)
            return _linear_quantile(vals, self.cfg.quantile if q is None
                                    else q)

    def sample_count(self, key) -> int:
        with self._lock:
            self._expire_locked(self.clock())
            dq = self._samples.get(key)
            return len(dq) if dq else 0

    def fleet_quantile(self, q: Optional[float] = None, *,
                       exclude: Any = ()) -> Optional[float]:
        """The q-th quantile pooled over every key's window, or None when
        nothing has been measured recently. ``exclude`` drops the named
        keys from the pool — the hedge delay derives from HEALTHY peers,
        so a straggler's inflated tail cannot push the trigger past every
        deadline and disarm hedging exactly when it is needed."""
        with self._lock:
            self._expire_locked(self.clock())
            vals = sorted(v for key, dq in self._samples.items()
                          if key not in exclude for _, v in dq)
            if not vals:
                return None
            return _linear_quantile(vals, self.cfg.quantile if q is None
                                    else q)

    # -- verdicts -----------------------------------------------------------

    def _update_locked(self, now: float) -> None:
        self._expire_locked(now)
        pooled = sorted(v for dq in self._samples.values() for _, v in dq)
        if not pooled:
            return   # flagged keys stay flagged: no fresh fleet to re-judge
        med = _linear_quantile(pooled, 0.5)
        for key in sorted(set(self._samples) | set(self._flagged),
                          key=repr):
            dq = self._samples.get(key)
            if dq is None or len(dq) < self.cfg.min_samples:
                continue   # too few fresh samples: verdict stands as-is
            last = self._last_flip.get(key)
            if last is not None and now - last < self.cfg.min_dwell_s:
                continue
            p = _linear_quantile(sorted(v for _, v in dq),
                                 self.cfg.quantile)
            slow = (len(self._samples) >= 2
                    and p >= self.cfg.p95_multiple * med)
            if slow and key not in self._flagged:
                self._flagged[key] = now
                self._last_flip[key] = now
                self.demotions += 1
            elif not slow and key in self._flagged:
                del self._flagged[key]
                self._last_flip[key] = now
                self.promotions += 1

    def is_straggler(self, key) -> bool:
        with self._lock:
            self._update_locked(self.clock())
            return key in self._flagged

    def stragglers(self) -> tuple:
        """Currently flagged keys, sorted for deterministic iteration."""
        with self._lock:
            self._update_locked(self.clock())
            return tuple(sorted(self._flagged, key=repr))

    def summary(self) -> dict:
        with self._lock:
            self._update_locked(self.clock())
            return {
                "keys": len(self._samples),
                "flagged": sorted(self._flagged, key=repr),
                "observed": self.observed,
                "demotions": self.demotions,
                "promotions": self.promotions,
            }
