"""Continuous batching over the paged KV cache: one compiled ragged step.

``serve/decode.py``'s ``generate`` runs ONE request shape per call — mixed
traffic pads to the worst case or recompiles, ROADMAP item 1's gap. This
module schedules many streams through ONE jitted decode step built on
``models/paged_kv.py``:

- a fixed pool of ``max_slots`` slots rides through
  :func:`~edgellm_tpu.models.paged_kv.paged_decode_step` every step; the page
  table, per-slot lengths, last tokens, RNG keys, step indices and
  temperatures are all TRACED inputs, so admitting, evicting, finishing or
  growing a stream never retraces — the steady state is jit-miss-free by
  construction and :func:`batched_step_cache_size` exposes the counter so
  tests assert it;
- prompts are prefetched through the SAME ``_prefill_jit`` executable
  ``generate`` uses, the first token sampled with the same ``fold_in(key, 0)``
  — then the prompt's KV is adopted into the stream's pages;
- sampling inside the batched step reproduces ``decode._sample`` per slot
  bitwise: ``fold_in`` and ``categorical`` are vmapped over per-slot
  (key, step) pairs, greedy rows select the argmax lane — so every stream's
  tokens are bit-identical to running it alone through ``generate`` (the
  ``batching.decode-step-identity`` graphlint contract re-proves this on
  every lint run);
- when the pool runs out of pages the youngest running stream is evicted:
  its pages are gathered back to a contiguous host prefix (byte-identical to
  a contiguous cache) and the stream re-queues; re-admission adopts the
  prefix instead of re-prefilling, and the resumed tokens are bit-identical
  because the per-step keys depend only on (stream key, step index);
- eviction payloads round-trip through
  :class:`~edgellm_tpu.serve.recovery.DecodeCheckpoint` when a
  ``checkpoint_dir`` is configured, so a killed batcher restores mid-flight
  streams from disk; a per-step
  :class:`~edgellm_tpu.serve.recovery.Watchdog` guards wedged steps with the
  same typed :class:`~edgellm_tpu.serve.recovery.DecodeTimeout` the serving
  front already handles;
- a :class:`~edgellm_tpu.models.paged_kv.PrefixCacheConfig` on the
  ``BatchingConfig`` turns on prefix sharing: fresh admits consult a radix
  index of token blocks, map every matched page into the new slot's table
  with ZERO prefill compute (only the unmatched suffix runs, through
  ``decode._prefill_suffix_jit``), and the first in-place write to a shared
  page copy-on-write-forks it; refcount-0 index pages are reclaimed
  LRU-first under pool pressure. Decode output stays token-identical to the
  non-shared path (same pages, same attention span — different bookkeeping);
- passing ``split_runtime=``/``placed_params=`` drives the SAME scheduler
  through ``SplitRuntime.decode_step_paged`` instead of the local pool: the
  host-side :class:`~edgellm_tpu.models.paged_kv.PagedKVCache` runs in
  bookkeeping-only mode (``materialize=False``), the K/V pages live
  per-stage on the mesh (``SplitRuntime.init_paged_pool``), and every ragged
  step crosses the boundary once per cut through the quantized hop ladder —
  batched serving over a split plan, no longer local-pool-only.

``ServeFront`` integration lives in ``serve/frontend.py`` (``batcher=``):
admission control, brownout and breakers all apply before a request reaches
the batcher — this module is only the inner scheduler.
"""
from __future__ import annotations

import functools
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..models.configs import ModelConfig
from ..models.paged_kv import OutOfPages, OutOfSlots, PagedKVCache, \
    PrefixCacheConfig, QuantPagePool, paged_decode_step, \
    paged_decode_step_quant, resolve_kv_codec
from ..models.transformer import KVCache
from ..obs import context as obs_context
from ..obs.flight import flight_dump_for
from ..obs.tracing import span as obs_span
from ..utils.concurrency import guarded_by
from .decode import _prefill_jit, _prefill_suffix_jit, _sample
from .recovery import (CheckpointError, CheckpointTierMismatchError,
                       DecodeCheckpoint, Watchdog)


def _model_sig(cfg: ModelConfig) -> dict:
    """The same model signature ``recovery.runtime_plan_meta`` records, so a
    paged stream checkpoint refuses restore onto a different model."""
    return {"family": cfg.family, "num_layers": cfg.num_layers,
            "hidden_size": cfg.hidden_size, "num_heads": cfg.num_heads,
            "vocab_size": cfg.vocab_size}


@dataclass(frozen=True)
class BatchingConfig:
    """Pool geometry + scheduler knobs. One compiled step per geometry."""

    page_size: int = 16
    num_pages: int = 65          # includes the reserved trash page 0
    max_slots: int = 4
    pages_per_slot: int = 8
    compute_dtype: Any = None
    cache_dtype: Any = jnp.float32
    checkpoint_dir: Optional[str] = None
    step_deadline_s: Optional[float] = None
    # prefix sharing: a PrefixCacheConfig turns on the radix prefix index +
    # copy-on-write pages (models.paged_kv); None = pre-sharing behavior,
    # bit-for-bit (the batching.prefix-disabled-identity graphlint contract)
    prefix_cache: Optional[PrefixCacheConfig] = None
    # KV-at-rest tier (models.paged_kv.KV_PAGE_CODECS): "fp" stores plain
    # cache_dtype pages and traces the exact pre-quantization step (the
    # batching.kvq-disabled-identity graphlint contract); quantized tiers
    # store packed codes + per-row scales, shrinking bytes-per-token so the
    # same HBM budget admits 2-4x the concurrency (use num_pages_for_bytes
    # to size the pool at fixed bytes)
    kv_codec: str = "fp"

    def __post_init__(self):
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {self.page_size}")
        if self.num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is reserved), got "
                f"{self.num_pages}")
        if self.max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {self.max_slots}")
        if self.pages_per_slot < 1:
            raise ValueError(
                f"pages_per_slot must be >= 1, got {self.pages_per_slot}")
        if self.step_deadline_s is not None and self.step_deadline_s <= 0:
            raise ValueError("step_deadline_s must be positive")
        if self.prefix_cache is not None and not isinstance(
                self.prefix_cache, PrefixCacheConfig):
            raise ValueError(
                f"prefix_cache must be a PrefixCacheConfig or None, got "
                f"{type(self.prefix_cache).__name__}")
        resolve_kv_codec(self.kv_codec)  # refuse unknown tier names early

    @property
    def span(self) -> int:
        return self.pages_per_slot * self.page_size


@dataclass
class Stream:
    """One request's host-side state across admit/evict/finish."""

    sid: int
    prompt: np.ndarray            # (S,) int32
    max_new_tokens: int
    temperature: float
    rng_seed: int
    status: str = "waiting"       # waiting | running | finished
    slot: int = -1
    tokens: list = field(default_factory=list)  # sampled ids, host ints
    resume: Optional[dict] = None  # gathered {"k","v","length"} for re-admit
    resume_prefix: bool = False   # re-publish the prompt's pages on adopt
    admit_seq: int = -1           # admission order; youngest = largest
    evictions: int = 0

    @property
    def t(self) -> int:
        """Next decode-step index == tokens sampled so far (token 0 comes
        from the prefill, exactly as in ``generate``)."""
        return len(self.tokens)

    @property
    def key(self) -> jax.Array:
        return jax.random.key(self.rng_seed)


def _batched_sample(logits, keys, steps, temps):
    """Per-slot ``decode._sample``, vectorized bit-identically: slot i's
    token equals ``_sample(logits[i:i+1], fold_in(key_i, step_i), temp_i)``
    — fold_in/categorical vmap to the same draws as their single-row calls,
    argmax rows are batch-invariant, and the where just selects which lane
    slot i uses (temperature stays a TRACED per-slot value, so greedy and
    sampled streams share one executable)."""
    folded = jax.vmap(jax.random.fold_in)(keys, steps)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe = jnp.where(temps > 0.0, temps, 1.0)
    cat = jax.vmap(jax.random.categorical)(
        folded, logits / safe[:, None]).astype(jnp.int32)
    return jnp.where(temps > 0.0, cat, greedy)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "compute_dtype"),
                   donate_argnums=(2, 3))
def _batched_step_jit(cfg: ModelConfig, params: dict, pool_k, pool_v,
                      page_table, lengths, token_ids, keys, steps, temps,
                      compute_dtype):
    logits, pool_k, pool_v = paged_decode_step(
        cfg, params, pool_k, pool_v, page_table, lengths, token_ids,
        compute_dtype=compute_dtype)
    return _batched_sample(logits, keys, steps, temps), pool_k, pool_v


@functools.partial(jax.jit,
                   static_argnames=("cfg", "kv_codec", "compute_dtype"),
                   donate_argnums=(2, 3, 4, 5))
def _batched_step_quant_jit(cfg: ModelConfig, params: dict, pool_k, pool_v,
                            pool_k_scale, pool_v_scale, page_table, lengths,
                            token_ids, keys, steps, temps, kv_codec,
                            compute_dtype):
    """Quantized-tier twin of :func:`_batched_step_jit`: the four
    QuantPagePool arrays are donated, sampling is the same vmapped
    ``_batched_sample``. A SEPARATE jit — the fp tier keeps hitting the
    executable above, whose jaxpr the kvq-disabled-identity contract pins."""
    logits, pool_k, pool_v, pool_k_scale, pool_v_scale = (
        paged_decode_step_quant(
            cfg, params, pool_k, pool_v, pool_k_scale, pool_v_scale,
            page_table, lengths, token_ids, kv_codec=kv_codec,
            compute_dtype=compute_dtype))
    return (_batched_sample(logits, keys, steps, temps),
            pool_k, pool_v, pool_k_scale, pool_v_scale)


def batched_step_cache_size() -> int:
    """Executables compiled for the ragged step so far in this process — the
    jit-miss counter :meth:`ContinuousBatcher.step` reports deltas of.
    Counts BOTH tier executables: a steady-state serve loop must stop
    missing on whichever one its pool uses."""
    return (_batched_step_jit._cache_size()
            + _batched_step_quant_jit._cache_size())


# the split step returns (max_slots, V) logits from decode_step_paged; the
# sampler is the SAME vmapped _batched_sample, jitted standalone so split
# streams keep the local path's per-slot bit-identity guarantee
_split_sample_jit = jax.jit(_batched_sample)


@guarded_by("_stats_lock", fields=["stats"])
class ContinuousBatcher:
    """Admit/evict streams mid-flight into one compiled ragged decode step.

    Lifecycle: :meth:`submit` queues a stream; :meth:`step` admits waiting
    streams into free slots (prefill + page adoption), runs ONE jitted step
    for every running slot, appends each slot's sampled token, retires
    finished streams, and — when the pool cannot cover a growth — evicts the
    youngest running stream back to the waiting queue with its gathered KV
    prefix. :meth:`run` loops :meth:`step` to completion. ``results[sid]``
    holds each finished stream's (max_new_tokens,) int32 tokens.
    """

    def __init__(self, cfg: ModelConfig, params: dict,
                 bcfg: Optional[BatchingConfig] = None, *,
                 split_runtime: Any = None, placed_params: Any = None):
        self.cfg = cfg
        self.params = params
        self.bcfg = bcfg if bcfg is not None else BatchingConfig()
        self.rt = split_runtime
        if split_runtime is not None:
            if placed_params is None:
                raise ValueError(
                    "split_runtime needs placed_params (the SplitRuntime's "
                    "placed parameter tree)")
            if self.bcfg.compute_dtype is not None:
                raise ValueError(
                    "compute_dtype is a local-pool knob; the split runtime "
                    "owns its own dtypes — leave it None")
            if getattr(split_runtime, "pipelined", False):
                m = split_runtime.pipeline.num_microbatches
                if self.bcfg.max_slots % m != 0:
                    raise ValueError(
                        f"max_slots={self.bcfg.max_slots} must be a multiple "
                        f"of num_microbatches={m}: every ragged decode step "
                        f"feeds the full slot set through the pipelined "
                        f"schedule, which splits it into {m} equal µ-batches")
                if self.bcfg.kv_codec != "fp":
                    raise ValueError(
                        f"kv_codec={self.bcfg.kv_codec!r} composes with the "
                        f"unpipelined split runtime only; the pipelined "
                        f"µ-batch schedule has no quantized paged step yet")
        self.placed = placed_params
        # split mode: the host PagedKVCache is the ALLOCATOR only (page
        # table, lengths, free list); the actual K/V pages live per-stage on
        # the mesh and move through the runtime's paged scatter/gather
        self.pool = PagedKVCache(
            cfg, num_pages=self.bcfg.num_pages,
            page_size=self.bcfg.page_size, max_slots=self.bcfg.max_slots,
            pages_per_slot=self.bcfg.pages_per_slot,
            dtype=self.bcfg.cache_dtype,
            materialize=split_runtime is None,
            prefix_cache=self.bcfg.prefix_cache,
            kv_codec=self.bcfg.kv_codec)
        self._split_pool = (
            split_runtime.init_paged_pool(self.bcfg.num_pages,
                                          self.bcfg.page_size,
                                          dtype=self.bcfg.cache_dtype,
                                          kv_codec=self.bcfg.kv_codec)
            if split_runtime is not None else None)
        self._streams: dict[int, Stream] = {}
        self._waiting: deque[int] = deque()
        self._slot_to_sid: dict[int, int] = {}
        self._next_sid = 0
        self._admit_seq = 0
        self.results: dict[int, np.ndarray] = {}
        self._watchdog = (Watchdog(self.bcfg.step_deadline_s)
                          if self.bcfg.step_deadline_s is not None else None)
        # running aggregates only — a long-lived server takes millions of
        # steps, so no per-step sample lists; the obs scrape thread reads
        # report() mid-step, so every write holds _stats_lock
        self._stats_lock = threading.Lock()
        self.stats = {"steps": 0, "submitted": 0, "admitted": 0, "evicted": 0,
                      "finished": 0, "jit_misses": 0, "emitted_tokens": 0,
                      "prefill_s": 0.0, "decode_s": 0.0,
                      "occ_sum": 0.0, "occ_max": 0.0, "slot_sum": 0.0,
                      "alloc_sum": 0.0, "alloc_n": 0}

    # -- submission --------------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens: int, *,
               temperature: float = 0.0, rng_seed: int = 0) -> int:
        """Queue a stream; same argument semantics as ``generate`` with
        ``rng_key = jax.random.key(rng_seed)``. Returns the stream id."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if float(temperature) < 0.0:
            raise ValueError("temperature must be >= 0")
        need = prompt.size + max_new_tokens - 1  # final token is not written
        if need > self.bcfg.span:
            raise ValueError(
                f"prompt {prompt.size} + {max_new_tokens} new tokens needs "
                f"{need} cache positions > slot span {self.bcfg.span} "
                f"(pages_per_slot={self.bcfg.pages_per_slot} x "
                f"page_size={self.bcfg.page_size})")
        sid = self._next_sid
        self._next_sid += 1
        self._streams[sid] = Stream(sid, prompt, int(max_new_tokens),
                                    float(temperature), int(rng_seed))
        self._waiting.append(sid)
        with self._stats_lock:
            self.stats["submitted"] += 1
        with obs_span("batch.submit", sid=sid, prompt_len=int(prompt.size),
                      max_new_tokens=int(max_new_tokens)):
            pass
        return sid

    def pop_result(self, sid: int) -> np.ndarray:
        """Return and forget a finished stream's tokens. Long-lived callers
        (``ServeFront.drain_batched``) consume results through this so
        finished streams don't accumulate in ``results``/``_streams``."""
        toks = self.results.pop(sid)
        self._streams.pop(sid, None)
        return toks

    def probe_prefix(self, prompt_ids) -> int:
        """Router affinity lookup: how many leading tokens of this prompt the
        paged pool's radix index already holds (a pure dry-run — no stats, no
        refcounts). 0 when prefix sharing is off, so a cluster router can
        probe any replica uniformly."""
        if self.pool.prefix is None:
            return 0
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        return int(self.pool.probe_prefix(prompt)["tokens"])

    def discard(self, sid: int) -> None:
        """Drop a stream in any state and forget its result — the orphan
        hatch: an aborted drain would otherwise leave its inflight streams
        queued forever with no caller to collect them, rerunning on the next
        drain. Frees a running stream's slot and pages."""
        st = self._streams.pop(sid, None)
        self.results.pop(sid, None)
        if st is None:
            return
        if st.status == "running":
            self.pool.free_slot(st.slot)
            del self._slot_to_sid[st.slot]
        elif st.status == "waiting":
            try:
                self._waiting.remove(sid)
            except ValueError:
                pass
        st.status = "discarded"

    # -- admission / eviction ----------------------------------------------

    def _cache_len(self, st: Stream) -> int:
        """Positions st's cache holds at the top of step t: the prompt plus
        the t-1 tokens already fed back (token t-1 is pending feed)."""
        return st.prompt.size + max(st.t - 1, 0)

    def _microbatch_of(self, slot: int) -> int:
        """Which µ-batch a slot rides in under the pipelined split schedule
        (0 when pipelining is off or the pool is local) — the attribution
        label admit spans and stream checkpoints both record."""
        pipe = (getattr(self.rt, "pipeline", None)
                if self.rt is not None else None)
        m = int(pipe.num_microbatches) if pipe is not None else 1
        return int(slot // (self.bcfg.max_slots // m)) if m > 1 else 0

    def _try_admit(self, sid: int) -> bool:
        st = self._streams[sid]
        need_len = (int(st.resume["length"]) if st.resume is not None
                    else st.prompt.size)
        # feasibility: +1 because the admitting step itself must be
        # coverable. Prefix sharing shrinks the bill — indexed pages map in
        # for free (minus one fork page when the match ends mid-page) — and
        # index-only pages count as available (``ensure`` reclaims them
        # LRU-first under pressure), which is exactly where the
        # more-admits-at-fixed-pool capacity win comes from.
        need_pages = self.pool.pages_for(need_len + 1)
        if st.resume is None and self.pool.prefix is not None:
            pr = self.pool.probe_prefix(st.prompt,
                                        max_tokens=st.prompt.size - 1)
            need_pages = need_pages - pr["pages"] + pr["forks"]
        if need_pages > (self.pool.num_free_pages
                         + self.pool.reclaimable_index_pages):
            return False
        try:
            slot = self.pool.alloc_slot()
        except OutOfSlots:
            return False
        resumed = st.resume is not None
        t0 = time.monotonic()
        try:
            tok0 = self._admit_fill(st, slot)
        except OutOfPages:
            # the feasibility probe over-promised (an interior index page
            # can be unreclaimable while a descendant is slot-held): undo
            # cleanly — nothing was committed to the stream yet
            self.pool.free_slot(slot)
            return False
        if tok0 is not None:
            st.tokens.append(int(np.asarray(tok0)[0]))
        with self._stats_lock:
            self.stats["prefill_s"] += time.monotonic() - t0
        st.status, st.slot = "running", slot
        st.admit_seq = self._admit_seq
        self._admit_seq += 1
        self._slot_to_sid[slot] = sid
        with self._stats_lock:
            self.stats["admitted"] += 1
        with obs_span("batch.admit", sid=sid, slot=slot,
                      microbatch=self._microbatch_of(slot), resumed=resumed):
            pass
        if st.t >= st.max_new_tokens:  # max_new_tokens == 1: prefill is all
            self._finish(st)
        return True

    def _admit_fill(self, st: Stream, slot: int) -> Optional[jax.Array]:
        """Land one stream's KV into ``slot``'s pages — resume payload,
        full prefill, or (on a prefix-index hit) shared pages plus a
        suffix-only prefill. Returns the sampled token 0 for fresh admits,
        None for resumes. Raises :class:`OutOfPages` with the slot still
        consistent (the caller undoes via ``free_slot``)."""
        if st.resume is not None:
            need_len = int(st.resume["length"])
            # resumes adopt privately: the payload mixes prompt and
            # generated rows, so re-sharing would index decode output.
            # Quantized tiers carry PACKED codes + scales (never fp rows),
            # so evict -> readmit round-trips the pool bytes exactly.
            packed = "k_codes" in st.resume
            if self.rt is not None:
                self.pool.ensure(slot, need_len)
                dest = self.pool._flat_indices(slot, need_len)
                if packed:
                    self._split_pool = self.rt.adopt_paged_rows_packed(
                        self._split_pool, st.resume["k_codes"],
                        st.resume["v_codes"], st.resume["k_scale"],
                        st.resume["v_scale"], dest)
                else:
                    self._split_pool = self.rt.adopt_paged_rows(
                        self._split_pool, st.resume["k"], st.resume["v"],
                        dest)
                self.pool.lengths[slot] = need_len
            elif packed:
                self.pool.adopt_packed(
                    slot, st.resume["k_codes"], st.resume["v_codes"],
                    st.resume["k_scale"], st.resume["v_scale"], need_len)
            else:
                self.pool.adopt(slot, jnp.asarray(st.resume["k"]),
                                jnp.asarray(st.resume["v"]), need_len)
            st.resume = None
            if st.resume_prefix and self.pool.prefix is not None:
                # migration adopts opt in to re-publishing: the payload's
                # first ``prompt.size`` rows are pure prompt KV (the prefill
                # worker hands off at t == 1), so the radix index survives
                # the transfer. register_prefix walks only the prompt
                # tokens — generated rows are never indexed.
                self.pool.register_prefix(slot, st.prompt)
            return None
        s = st.prompt.size
        matched = 0
        if self.pool.prefix is not None:
            # claim at most s-1 positions: at least one suffix token must
            # run so token 0 has logits to sample from
            matched = self.pool.share_prefix(slot, st.prompt,
                                             max_tokens=s - 1)
        if matched > 0:
            tok0 = (self._prefill_suffix_split(st, slot, matched) if
                    self.rt is not None else
                    self._prefill_suffix_local(st, slot, matched))
        elif self.rt is not None:
            # the exact generate_split() prefill: same executable, same
            # token-0 key, then the per-stage cache rows scatter into the
            # mesh pools at this slot's pages
            logits, cache = self.rt.prefill_decode(
                self.placed, jnp.asarray(st.prompt[None, :]),
                self.bcfg.span)
            tok0 = _sample(logits[:, -1], jax.random.fold_in(st.key, 0),
                           st.temperature)
            self.pool.ensure(slot, s)
            dest = self.pool._flat_indices(slot, s)
            self._split_pool = self.rt.adopt_paged(
                self._split_pool, cache, 0, dest, s)
            self.pool.lengths[slot] = s
        else:
            # the exact generate() prefill: same executable, same
            # capacity semantics (KV values are capacity-invariant),
            # same token-0 key
            last_logits, cache = _prefill_jit(
                self.cfg, self.params, jnp.asarray(st.prompt[None, :]),
                self.bcfg.span, self.bcfg.compute_dtype)
            tok0 = _sample(last_logits, jax.random.fold_in(st.key, 0),
                           st.temperature)
            self.pool.adopt(slot, cache.k[:, 0, :s], cache.v[:, 0, :s], s)
        if self.pool.prefix is not None:
            # publish this prompt's pages (full blocks + partial tail) so
            # later admits share them; already-indexed blocks just refresh
            # their LRU stamps
            self.pool.register_prefix(slot, st.prompt)
        return tok0

    def _prefill_suffix_local(self, st: Stream, slot: int,
                              matched: int) -> jax.Array:
        """Prefix-hit admit, local pool: the ``matched`` shared rows are
        already mapped into ``slot``; gather them into a contiguous cache,
        run ``decode._prefill_suffix_jit`` over ONLY the unmatched suffix,
        and scatter the new rows back (COW-forking the shared tail page).
        Token 0 uses the same ``fold_in(key, 0)`` as the full-prefill path —
        parity with it is the executed ``batching.prefix-token-identity``
        contract."""
        s = st.prompt.size
        state = self.pool.gather_slot(slot)  # the matched prefix rows
        cdtype = (self.bcfg.compute_dtype if self.bcfg.compute_dtype
                  is not None else jnp.float32)
        nl, _, kv, hd = state["k"].shape
        kc = jnp.zeros((nl, 1, self.bcfg.span, kv, hd), cdtype)
        vc = jnp.zeros_like(kc)
        cache = KVCache(kc.at[:, 0, :matched].set(state["k"]),
                        vc.at[:, 0, :matched].set(state["v"]),
                        jnp.asarray(matched, jnp.int32))
        logits, cache = _prefill_suffix_jit(
            self.cfg, self.params, jnp.asarray(st.prompt[None, matched:]),
            cache, self.bcfg.compute_dtype)
        tok0 = _sample(logits[:, -1], jax.random.fold_in(st.key, 0),
                       st.temperature)
        self.pool.adopt_rows(slot, cache.k[:, 0, matched:s],
                             cache.v[:, 0, matched:s], matched, s)
        return tok0

    def _prefill_suffix_split(self, st: Stream, slot: int,
                              matched: int) -> jax.Array:
        """The split twin of :meth:`_prefill_suffix_local`: gather the
        matched rows from the per-stage pools, run the runtime's
        ``verify_step`` (the K-position split pass — B=1, sequential
        schedule) over the suffix tokens, apply the COW fork copies to the
        mesh pools, and scatter the suffix rows into this slot's pages."""
        s = st.prompt.size
        idx = self.pool._flat_indices(slot, matched)
        k_seq, v_seq = self.rt.gather_paged(self._split_pool, idx)
        ns, sz = k_seq.shape[:2]
        kv, hd = k_seq.shape[3:]
        kc = np.zeros((ns, sz, 1, self.bcfg.span, kv, hd), k_seq.dtype)
        vc = np.zeros_like(kc)
        kc[:, :, 0, :matched] = k_seq
        vc[:, :, 0, :matched] = v_seq
        cache = {"k": jnp.asarray(kc), "v": jnp.asarray(vc),
                 "length": jnp.asarray(matched, jnp.int32)}
        logits, cache = self.rt.verify_step(
            self.placed, cache, jnp.asarray(st.prompt[None, matched:]))
        tok0 = _sample(logits[:, -1], jax.random.fold_in(st.key, 0),
                       st.temperature)
        pairs = self.pool.ensure_writable(slot, s)  # bookkeeping-only forks
        if pairs:
            self._split_pool = self.rt.copy_paged_pages(
                self._split_pool, [o for o, _ in pairs],
                [n for _, n in pairs])
        dest = self.pool._flat_indices(slot, s)[matched:]
        self._split_pool = self.rt.adopt_paged_rows(
            self._split_pool, cache["k"][:, :, 0, matched:s],
            cache["v"][:, :, 0, matched:s], dest)
        self.pool.lengths[slot] = s
        return tok0

    def _gather_state(self, slot: int) -> dict:
        """One slot's contiguous K/V prefix as the resume/checkpoint payload.
        Local pool: ``gather_slot``'s (L, n, KV, hd) dict. Split: the
        per-stage (n_stages, sz, n, KV, hd) twin from ``gather_paged`` —
        byte-identical to the rows ``adopt_paged`` scattered, so re-admission
        through ``adopt_paged_rows`` resumes token-identically. Quantized
        tiers gather the PACKED form (codes + scales, raw pool bytes) so the
        round-trip is bit-exact with no requantize."""
        quant = self.bcfg.kv_codec != "fp"
        if self.rt is None:
            return (self.pool.gather_slot_packed(slot) if quant
                    else self.pool.gather_slot(slot))
        n = int(self.pool.lengths[slot])
        idx = self.pool._flat_indices(slot, max(n, 1))
        if quant:
            kc, vc, ks, vs = self.rt.gather_paged_packed(
                self._split_pool, idx)
            return {"k_codes": kc[:, :, :n], "v_codes": vc[:, :, :n],
                    "k_scale": ks[:, :, :n], "v_scale": vs[:, :, :n],
                    "length": np.asarray(n, np.int32)}
        k_seq, v_seq = self.rt.gather_paged(self._split_pool, idx)
        return {"k": k_seq[:, :, :n], "v": v_seq[:, :, :n],
                "length": np.asarray(n, np.int32)}

    def evict(self, sid: int) -> None:
        """Push a running stream back to the waiting queue, gathering its
        pages to a contiguous prefix (byte-identical to a contiguous cache,
        so re-admission — here or after a disk round-trip — resumes
        token-identically)."""
        st = self._streams[sid]
        if st.status != "running":
            raise ValueError(f"stream {sid} is not running")
        st.resume = self._gather_state(st.slot)
        self.pool.free_slot(st.slot)
        del self._slot_to_sid[st.slot]
        st.status, st.slot = "waiting", -1
        st.evictions += 1
        self._waiting.appendleft(sid)  # resumed work goes to the head
        with self._stats_lock:
            self.stats["evicted"] += 1
        if self.bcfg.checkpoint_dir is not None:
            # bound so the checkpoint-save span carries the stream id
            with obs_context.bind(sid=sid):
                self.checkpoint_stream(
                    sid, os.path.join(self.bcfg.checkpoint_dir,
                                      f"stream_{sid}.ckpt"))

    # -- disaggregated prefill handoff ------------------------------------

    def prefill_hold(self, sid: int) -> Optional[Stream]:
        """Disaggregated-prefill admission: admit waiting stream ``sid``
        NOW — the exact fresh-admit prefill runs and token 0 is sampled
        with the same ``fold_in(key, 0)`` as colocated serving — then pin
        its slot with a migration hold instead of decoding. The caller
        (``serve.disagg``'s prefill worker) streams the slot's pages out
        via :meth:`gather_rows` and retires it with
        :meth:`release_handoff`. Returns the Stream, or None when the pool
        cannot admit right now. A ``max_new_tokens == 1`` stream finishes
        at admission (token 0 is the whole answer) and comes back already
        ``finished`` with no held slot."""
        st = self._streams[sid]
        if st.status != "waiting":
            raise ValueError(f"stream {sid} is not waiting")
        if not self._try_admit(sid):
            return None
        self._waiting.remove(sid)
        if st.status == "running":
            self.pool.hold_slot(st.slot)
        return st

    def gather_rows(self, slot: int, start: int, stop: int) -> dict:
        """Rows ``[start, stop)`` of ``slot`` in the pool's at-rest form —
        one migrated page's payload chunk (packed codes + scales on
        quantized tiers, fp rows otherwise; split mode gathers the
        per-stage layout). Concatenating every chunk along the row axis
        reproduces :meth:`_gather_state`'s arrays exactly."""
        if self.rt is None:
            if self.bcfg.kv_codec != "fp":
                return self.pool.gather_slot_rows_packed(slot, start, stop)
            return self.pool.gather_slot_rows(slot, start, stop)
        idx = self.pool._flat_indices(slot, stop)[start:]
        if self.bcfg.kv_codec != "fp":
            kc, vc, ks, vs = self.rt.gather_paged_packed(
                self._split_pool, idx)
            return {"k_codes": kc, "v_codes": vc,
                    "k_scale": ks, "v_scale": vs}
        k_seq, v_seq = self.rt.gather_paged(self._split_pool, idx)
        return {"k": k_seq, "v": v_seq}

    def release_handoff(self, sid: int) -> None:
        """Retire a prefill-handoff stream: drop the migration hold and
        free the staging slot (its pages have verifiably landed in the
        decode pool, or the handoff was abandoned). The prompt's pages
        stay in the staging prefix index, if enabled, for later shared
        prefills."""
        st = self._streams.pop(sid)
        if st.status == "running":
            self.pool.release_slot_hold(st.slot)
            self.pool.free_slot(st.slot)
            del self._slot_to_sid[st.slot]
            st.status, st.slot = "finished", -1
        self.results.pop(sid, None)

    def _evict_for_pages(self, needed: int, protect: set) -> bool:
        """Evict youngest-admitted running streams (never ``protect``) until
        ``needed`` pages are free. Youngest-first keeps old streams' work."""
        while self.pool.num_free_pages < needed:
            victims = [st for st in self._streams.values()
                       if st.status == "running" and st.sid not in protect]
            if not victims:
                return False
            self.evict(max(victims, key=lambda s: s.admit_seq).sid)
        return True

    def _finish(self, st: Stream) -> None:
        self.results[st.sid] = np.asarray(st.tokens, np.int32)
        self.pool.free_slot(st.slot)
        del self._slot_to_sid[st.slot]
        st.status, st.slot = "finished", -1
        with self._stats_lock:
            self.stats["finished"] += 1
            self.stats["emitted_tokens"] += len(st.tokens)

    # -- the ragged step ---------------------------------------------------

    def _running(self) -> list[Stream]:
        return [self._streams[sid] for sid in self._slot_to_sid.values()]

    def _grow_writable(self, st: Stream) -> None:
        """Cover this step's write position for ``st`` — allocate growth
        pages AND copy-on-write any shared page the position lands in (the
        first decode write after a prefix-sharing admit forks the shared
        tail page here). With sharing off this is exactly ``pool.ensure``."""
        pairs = self.pool.ensure_writable(st.slot, self._cache_len(st) + 1)
        if pairs and self.rt is not None:
            # bookkeeping-only pool: route the fork copies to the mesh pools
            self._split_pool = self.rt.copy_paged_pages(
                self._split_pool, [o for o, _ in pairs],
                [n for _, n in pairs])

    def _step_cache_size(self) -> int:
        """Executables behind this batcher's ragged step — local: the fused
        step+sample jit; split: the runtime's per-geometry paged step plus
        the standalone sampler. Deltas across a step are the jit misses."""
        if self.rt is not None:
            step_fn = self.rt._paged_decode_fns(self.bcfg.num_pages,
                                                self.bcfg.page_size,
                                                kv_codec=self.bcfg.kv_codec)
            return step_fn._cache_size() + _split_sample_jit._cache_size()
        return batched_step_cache_size()

    def step(self) -> int:
        """Admit what fits, run ONE compiled ragged step over every running
        slot, commit the sampled tokens. Returns the number of streams that
        advanced (0 = nothing running and nothing admittable)."""
        # admit in FIFO order until a stream doesn't fit (no overtaking:
        # admission order stays deterministic)
        while self._waiting:
            sid = self._waiting[0]
            if not self._try_admit(sid):
                break
            self._waiting.popleft()
        running = self._running()
        if not running:
            return 0
        # every running slot must be able to take this step's token; evict
        # youngest streams when the pool can't cover a growth (oldest first
        # keeps them protected longest)
        for st in sorted(running, key=lambda s: s.admit_seq):
            if st.status != "running":
                continue  # already evicted by a predecessor's growth
            try:
                self._grow_writable(st)
            except OutOfPages as e:
                # a growth may need a fresh page (pages_for grew) OR a COW
                # fork page (the write position sits in a shared page) —
                # either way at least one page must come free
                need = max(1, self.pool.pages_for(self._cache_len(st) + 1)
                           - len(self.pool._slot_pages[st.slot]))
                if not self._evict_for_pages(need, {st.sid}):
                    # unservable growth: capture the pool state post-mortem
                    # before the scheduler unwinds (once per instance)
                    flight_dump_for(e, sid=st.sid, slot=st.slot,
                                    free_pages=self.pool.num_free_pages)
                    raise
                self._grow_writable(st)
        running = self._running()
        if not running:
            return 0

        if self._watchdog is not None:
            self._watchdog.arm()
        b = self.bcfg.max_slots
        token_ids = np.zeros((b,), np.int32)
        steps = np.zeros((b,), np.int32)
        temps = np.zeros((b,), np.float32)
        keys = [jax.random.key(0)] * b
        for st in running:
            token_ids[st.slot] = st.tokens[-1]
            steps[st.slot] = st.t
            temps[st.slot] = st.temperature
            keys[st.slot] = st.key
        # the pool's lengths array is the step's write/mask positions: slot
        # i's cache holds prompt + t-1 fed tokens (== pool lengths by
        # construction); inactive slots write the trash page
        page_table, lengths = self.pool.device_tables()
        misses0 = self._step_cache_size()
        t0 = time.monotonic()
        if self.rt is not None:
            # one ragged split step: every cut hops ONE (max_slots, 1, D)
            # quantized activation block, the sampler is the same vmapped
            # _batched_sample the local step fuses in
            logits, self._split_pool = self.rt.decode_step_paged(
                self.placed, self._split_pool, page_table, lengths,
                jnp.asarray(token_ids))
            toks = _split_sample_jit(logits, jnp.stack(keys),
                                     jnp.asarray(steps), jnp.asarray(temps))
        elif self.bcfg.kv_codec != "fp":
            toks, k, v, ks, vs = _batched_step_quant_jit(
                self.cfg, self.params, self.pool.pool.k, self.pool.pool.v,
                self.pool.pool.k_scale, self.pool.pool.v_scale,
                page_table, lengths, jnp.asarray(token_ids),
                jnp.stack(keys), jnp.asarray(steps), jnp.asarray(temps),
                self.bcfg.kv_codec, self.bcfg.compute_dtype)
            self.pool.pool = QuantPagePool(k, v, ks, vs)
        else:
            toks, k, v = _batched_step_jit(
                self.cfg, self.params, self.pool.pool.k, self.pool.pool.v,
                page_table, lengths, jnp.asarray(token_ids),
                jnp.stack(keys), jnp.asarray(steps), jnp.asarray(temps),
                self.bcfg.compute_dtype)
            self.pool.pool = type(self.pool.pool)(k, v)
        toks_host = np.asarray(toks)  # ONE host sync per step
        step_s = time.monotonic() - t0
        misses = self._step_cache_size() - misses0
        with self._stats_lock:
            self.stats["decode_s"] += step_s
            self.stats["jit_misses"] += misses
            self.stats["steps"] += 1
            step_no = int(self.stats["steps"]) - 1
        with obs_span("batch.step", step=step_no,
                      running=len(running), step_ms=round(step_s * 1e3, 3)):
            pass

        advanced = 0
        for st in running:
            # toks_host is already on host (the single np.asarray sync
            # above); this int() is numpy scalar unboxing, not a device sync
            st.tokens.append(int(toks_host[st.slot]))  # graphlint: disable=EG005
            self.pool.lengths[st.slot] = self._cache_len(st)
            advanced += 1
            if st.t >= st.max_new_tokens:
                self._finish(st)
        # unique_live_tokens counts each physical page once: with prefix
        # sharing, summing per-slot lengths would over-count aliased pages
        # against a reserved-capacity denominator that holds them once
        # (identical to live_tokens when nothing is shared)
        occ = self.pool.unique_live_tokens / self.pool.token_capacity
        slot_util = len(self._slot_to_sid) / b
        # live tokens per RESERVED token — the denominator is only the pages
        # actually allocated, the paged answer to static batching's
        # worst-case (batch x capacity) reservation
        reserved = (self.pool.num_pages - 1
                    - self.pool.num_free_pages) * self.pool.page_size
        alloc_util = (self.pool.unique_live_tokens / reserved
                      if reserved else None)
        with self._stats_lock:
            self.stats["occ_sum"] += occ
            self.stats["occ_max"] = max(self.stats["occ_max"], occ)
            self.stats["slot_sum"] += slot_util
            if alloc_util is not None:
                self.stats["alloc_sum"] += alloc_util
                self.stats["alloc_n"] += 1
        if self._watchdog is not None:
            self._watchdog.check()
        return advanced

    def run(self, max_steps: int = 100_000) -> dict[int, np.ndarray]:
        """Drive :meth:`step` until every submitted stream finished."""
        for _ in range(max_steps):
            if not self._waiting and not self._slot_to_sid:
                break
            if self.step() == 0 and self._waiting:
                exc = OutOfPages(
                    "no stream can make progress: the pool cannot hold even "
                    "one waiting stream — shrink prompts or grow the pool")
                flight_dump_for(exc, waiting=len(self._waiting),
                                free_pages=self.pool.num_free_pages)
                raise exc
        return self.results

    # -- checkpoint / restore ----------------------------------------------

    def checkpoint_stream(self, sid: int, path: str) -> str:
        """Snapshot one stream — running (pages gathered) or waiting with a
        resume payload — as a :class:`DecodeCheckpoint`, restorable into ANY
        pool geometry whose span covers it (the payload is the contiguous
        prefix, not pages)."""
        st = self._streams[sid]
        if st.status == "running":
            state = self._gather_state(st.slot)
        elif st.resume is not None:
            state = st.resume
        else:
            raise CheckpointError(
                f"stream {sid} ({st.status}) has no cache state to snapshot")
        if "k_codes" in state:
            # quantized tier: the CRC-framed payload is the PACKED layout
            # (codes + per-row scales) — restore scatters the same bytes
            # back, so the round-trip is bit-exact across pool geometries
            arrays = {"cache/k_codes": state["k_codes"],
                      "cache/v_codes": state["v_codes"],
                      "cache/k_scale": state["k_scale"],
                      "cache/v_scale": state["v_scale"]}
        else:
            arrays = {"cache/k": state["k"], "cache/v": state["v"]}
        arrays.update({"cache/length": state["length"],
                       "prompt_ids": st.prompt[None, :].astype(np.int32),
                       "tokens": np.asarray(st.tokens, np.int32)[None, :]})
        meta = {"mode": self._ckpt_mode(), "model": _model_sig(self.cfg),
                "sid": int(sid),
                "step": int(st.t - 1), "rng_seed": int(st.rng_seed),
                "temperature": float(st.temperature),
                "max_new_tokens": int(st.max_new_tokens)}
        if self.bcfg.kv_codec != "fp":
            # fp checkpoints keep the pre-quantization meta key set, so old
            # snapshots and fp batchers stay mutually restorable
            meta["kv_codec"] = self.bcfg.kv_codec
        if self.rt is not None:
            # split payloads are per-stage rows — refuse restore onto a
            # different placement the same way recovery checkpoints do
            meta["cuts"] = [int(c) for c in self.rt.split.cuts]
            meta["hop_codecs"] = [c.name for c in self.rt.codecs]
            # the pipelined schedule partitions the slot set into µ-batches;
            # record the count (a plan-signature axis, cross-checked on
            # restore) and — for a running stream — which µ-batch its slot
            # currently rides in, so operators can attribute per-µ-batch
            # fault counters back to streams
            pipe = getattr(self.rt, "pipeline", None)
            m = int(pipe.num_microbatches) if pipe is not None else 1
            meta["num_microbatches"] = m
            if st.status == "running" and m > 1:
                meta["microbatch"] = int(st.slot // (self.bcfg.max_slots // m))
        return DecodeCheckpoint(arrays, meta).save(path)

    def _ckpt_mode(self) -> str:
        return "paged" if self.rt is None else "paged_split"

    def restore_stream(self, path: str) -> int:
        """Re-queue a checkpointed stream; its remaining tokens come out
        bit-identical to the uninterrupted run (per-step keys depend only on
        the seed and the step index, the KV prefix is restored bit-exactly)."""
        ckpt = DecodeCheckpoint.load(path)
        meta = ckpt.meta
        if meta.get("mode") != self._ckpt_mode():
            raise CheckpointError(
                f"{path} is a {meta.get('mode')!r} checkpoint, this batcher "
                f"restores {self._ckpt_mode()!r} stream snapshots")
        if meta.get("model") != _model_sig(self.cfg):
            raise CheckpointError(
                f"{path} was written for model {meta.get('model')!r}, this "
                f"batcher runs {_model_sig(self.cfg)!r}")
        ck = meta.get("kv_codec", "fp")
        if ck != self.bcfg.kv_codec:
            # REFUSAL, not transcode: the payload is raw pool bytes at the
            # checkpoint's tier; rewriting them would silently change the
            # stream's numerics mid-flight (paged_kv.load_state_dict makes
            # the same call for whole-pool snapshots)
            raise CheckpointTierMismatchError(
                offered=ck, pool=self.bcfg.kv_codec, where="restore_stream",
                detail=f"{path} stores {ck!r} KV pages; restore into a "
                       f"batcher built at the checkpoint's tier")
        if self.rt is not None:
            pipe = getattr(self.rt, "pipeline", None)
            want = {"cuts": [int(c) for c in self.rt.split.cuts],
                    "hop_codecs": [c.name for c in self.rt.codecs],
                    # default 1 keeps pre-pipeline checkpoints restorable
                    "num_microbatches": (int(pipe.num_microbatches)
                                         if pipe is not None else 1)}
            for k, v in want.items():
                if meta.get(k, 1 if k == "num_microbatches" else None) != v:
                    raise CheckpointError(
                        f"{path} {k}={meta.get(k)!r} does not match this "
                        f"runtime's {k}={v!r}")
        sid = self.submit(ckpt.arrays["prompt_ids"][0],
                          int(meta["max_new_tokens"]),
                          temperature=float(meta["temperature"]),
                          rng_seed=int(meta["rng_seed"]))
        st = self._streams[sid]
        st.tokens = [int(x) for x in ckpt.arrays["tokens"][0]]
        if ck != "fp":
            st.resume = {"k_codes": ckpt.arrays["cache/k_codes"],
                         "v_codes": ckpt.arrays["cache/v_codes"],
                         "k_scale": ckpt.arrays["cache/k_scale"],
                         "v_scale": ckpt.arrays["cache/v_scale"],
                         "length": int(ckpt.arrays["cache/length"])}
        else:
            st.resume = {"k": ckpt.arrays["cache/k"],
                         "v": ckpt.arrays["cache/v"],
                         "length": int(ckpt.arrays["cache/length"])}
        return sid

    # -- reporting ---------------------------------------------------------

    def report(self) -> dict:
        with self._stats_lock:
            stats = dict(self.stats)  # one consistent snapshot for the scrape
        n = stats["steps"]
        alloc_n = stats["alloc_n"]
        dec = stats["decode_s"]
        emitted = stats["emitted_tokens"]
        pipeline = (self.rt.pipeline_summary()
                    if getattr(self.rt, "pipelined", False) else None)
        return {
            **({"pipeline": pipeline} if pipeline is not None else {}),
            "streams": stats["submitted"],
            "finished": stats["finished"],
            "steps": n,
            "admitted": stats["admitted"],
            "evicted": stats["evicted"],
            "jit_misses": stats["jit_misses"],
            "prefill_s": stats["prefill_s"],
            "decode_s": dec,
            "decode_tokens_per_s": (emitted / dec) if dec > 0 else 0.0,
            "occupancy_mean": (stats["occ_sum"] / n) if n else 0.0,
            "occupancy_max": stats["occ_max"],
            "slot_util_mean": (stats["slot_sum"] / n) if n else 0.0,
            "alloc_util_mean": ((stats["alloc_sum"] / alloc_n)
                                if alloc_n else 0.0),
            "span": self.bcfg.span,
            "token_capacity": self.pool.token_capacity,
            **({"prefix": self.pool.prefix_report()}
               if self.pool.prefix is not None else {}),
        }
