"""Speculative decoding across the split boundary: stage-0 draft, k-token
batched verify.

Every vanilla decode token costs a full round of boundary hops, so per-stream
latency is bounded by link round-trips no matter how fast the fused hops get
— the TAH-QUANT regime where activation transfer dominates step time. This
module amortizes the hop k-fold: a cheap DRAFT model — the stage-0 prefix of
the full model's layers, early-exiting through the full model's final norm
and unembedding — proposes k-1 tokens entirely on stage 0 (no hops), and the
split model VERIFIES the whole window in ONE ``SplitRuntime.verify_step``:
each cut moves one quantized (1, k, D) activation block through the
unchanged fused/faulty/FEC hop ladder instead of k single-token payloads.

The burst protocol (committed tokens ``c_0..c_{n-1}``; the target cache
holds the prompt plus ``c_0..c_{n-2}`` — the last sampled token is never fed
back yet, the same invariant the vanilla loop keeps):

1. draft ``d_1..d_{k-1}`` by greedy argmax, feeding ``c_{n-1}`` first;
2. verify inputs ``x = [c_{n-1}, d_1, .., d_{k-1}]`` in one q_len=k pass —
   position j's logits are exactly the distribution for global step
   ``n + j`` given the drafts up to j were right;
3. accept: at ``temperature == 0`` draft j is accepted iff it equals the
   argmax of position j-1's logits, so every emitted token is the argmax the
   vanilla loop would have produced — greedy spec output is TOKEN-IDENTICAL
   to vanilla ``generate_split`` by construction. At ``temperature > 0``
   standard residual resampling applies against the argmax (point-mass)
   draft: accept ``d_j`` with probability ``p(d_j)``, else sample from
   ``p`` with ``p(d_j)`` zeroed and renormalized — the emitted marginal is
   exactly ``p`` (distribution-identical, not bitwise: the accept/reject
   draws use their own ``fold_in`` lanes);
4. commit: the verify pass already wrote all k K/V rows; acceptance is a
   LENGTH rewrite (garbage past the fill level is masked — rollback moves no
   data). The draft cache rolls the same way, plus one catch-up draft step
   on a fully-accepted burst to backfill the row its k-1 draft steps never
   wrote.

Every burst emits 1..k tokens for ONE boundary round-trip, so measured
hops-per-token is ``bursts / emitted`` — below 1.0 whenever the draft agrees
at all (k=1 degenerates to the vanilla cost and serves as the correctness
anchor). Both the draft step and the verify step are compiled once per
(capacity, k): the fill level rides as a traced scalar, so the loop is
jit-miss-free after the first burst.

Checkpointing reuses ``serve.decode._write_checkpoint`` unchanged: a burst
boundary IS the vanilla loop invariant, so the same ``DecodeCheckpoint``
round-trips and :func:`resume_speculative` resumes token-identically (the
draft cache is rebuilt by a draft prefill over the committed prefix; burst
boundaries depend only on the committed prefix, so the resumed burst
sequence matches the uninterrupted run's).
"""
from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.typing import ArrayLike

from ..models.configs import ModelConfig
from ..models.transformer import (KVCache, _slice_layers,
                                  cache_from_state_dict, decode_step, prefill)
from ..obs.latency import LatencyObserver
from ..obs.metrics import (CounterSource, get_registry, record_decode_stats,
                           record_link_counters, record_link_health,
                           record_probe_decisions, record_recovery_counters,
                           record_spec_stats, record_wire_bytes)
from ..obs import context as obs_context
from ..obs.tracing import span as obs_span
from ..obs.tracing import tracing_enabled
from .decode import (_emit_hop_spans, _sample, _validate_decode_args,
                     _write_checkpoint)
from .recovery import (CheckpointError, DecodeCheckpoint, DecodeTimeout,
                       RecoveryConfig, RecoveryCounters, Watchdog,
                       runtime_plan_meta)

MAX_SPEC_K = 16  # verify window ceiling: beyond this the draft rarely holds
DRAFT_SOURCES = ("stage0",)


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculative-decode knobs. ``k`` is the verify window (q_len): each
    burst drafts k-1 tokens and verifies k positions in one split pass.
    ``draft_source`` names where the draft comes from — ``"stage0"`` is the
    truncated-layer early-exit head over the first ``draft_layers`` layers
    (default: everything stage 0 already owns, i.e. first cut + 1). The
    acceptance rule is implied by the temperature: lossless greedy exact
    match at 0, residual resampling above. ``enabled=False`` is the
    contractual no-op: the serving loop never touches the draft or the
    verify executable, so the built graphs are jaxpr-fingerprint-identical
    to the pre-spec ones (graphlint re-proves this every run)."""

    enabled: bool = True
    k: int = 4
    draft_source: str = "stage0"
    draft_layers: Optional[int] = None

    def __post_init__(self):
        if not isinstance(self.k, int) or isinstance(self.k, bool):
            raise ValueError(f"k must be an int, got {self.k!r}")
        if not 1 <= self.k <= MAX_SPEC_K:
            raise ValueError(
                f"k must be in [1, {MAX_SPEC_K}], got {self.k}")
        if self.draft_source not in DRAFT_SOURCES:
            raise ValueError(
                f"unknown draft_source {self.draft_source!r}; "
                f"supported: {DRAFT_SOURCES}")
        if self.draft_layers is not None and (
                not isinstance(self.draft_layers, int)
                or isinstance(self.draft_layers, bool)
                or self.draft_layers < 1):
            raise ValueError(
                f"draft_layers must be a positive int or None, got "
                f"{self.draft_layers!r}")


def draft_from_params(cfg: ModelConfig, raw_params: dict, spec: SpecConfig,
                      cut: Optional[int] = None) -> tuple:
    """Build the stage-0 early-exit draft: the first ``draft_layers`` layers
    of the full model, re-using the FULL model's embedding, final norm and
    unembedding as the exit head (no extra weights, no training — the
    residual stream is read out early). ``cut`` (the first split cut) bounds
    ``draft_layers`` so the draft never needs weights stage 0 doesn't hold.
    Returns (draft_cfg, draft_params) for ``transformer.prefill``/
    ``decode_step``."""
    limit = (cut + 1) if cut is not None else cfg.num_layers
    n = spec.draft_layers if spec.draft_layers is not None else limit
    if not 1 <= n <= limit:
        raise ValueError(
            f"draft_layers={n} must be in [1, {limit}] — stage 0 owns "
            f"layers 0..{limit - 1} and the draft must run hop-free there")
    draft_cfg = dataclasses.replace(cfg, num_layers=n)
    draft_params = {k: v for k, v in raw_params.items() if k != "layers"}
    draft_params["layers"] = _slice_layers(raw_params["layers"], 0, n)
    return draft_cfg, draft_params


# the draft runs the unsplit transformer entry points on stage 0's device —
# no hops, no collectives; cfg/capacity are static, the cache is donated, so
# the whole run compiles exactly one prefill and one step executable
@functools.partial(jax.jit,
                   static_argnames=("cfg", "capacity", "compute_dtype"))
def _draft_prefill_jit(cfg: ModelConfig, params: dict, input_ids, capacity,
                       compute_dtype):
    logits, cache = prefill(cfg, params, input_ids, capacity,
                            compute_dtype=compute_dtype)
    return logits[:, -1], cache


@functools.partial(jax.jit, static_argnames=("cfg", "compute_dtype"),
                   donate_argnames=("cache",))
def _draft_step_jit(cfg: ModelConfig, params: dict, cache: KVCache,
                    token_ids, compute_dtype):
    logits, cache = decode_step(cfg, params, cache, token_ids,
                                compute_dtype=compute_dtype)
    # the draft proposal is always the argmax (a point-mass draft keeps the
    # residual-resampling math exact at any temperature)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache


def draft_step_cache_size() -> int:
    """Executables compiled for the draft step so far in this process — the
    jit-miss counter the spec loop reports deltas of."""
    return _draft_step_jit._cache_size()


def spec_capacity(prompt_len: int, max_new_tokens: int, k: int) -> int:
    """Cache rows a speculative run can touch: the last burst may start with
    ``max_new_tokens - 1`` committed tokens and still write all k verify
    rows past the vanilla fill level."""
    return max(prompt_len + max_new_tokens,
               prompt_len + max_new_tokens + k - 2)


def generate_speculative(rt: Any, placed_params: dict, prompt_ids: ArrayLike,
                         max_new_tokens: int,
                         *,
                         spec: SpecConfig,
                         capacity: Optional[int] = None,
                         temperature: float = 0.0,
                         rng_key: Optional[jax.Array] = None,
                         fault_step: int = 0,
                         stats: Optional[dict] = None,
                         recovery: Optional[RecoveryConfig] = None,
                         raw_params: Optional[dict] = None,
                         link_health: Optional[Any] = None,
                         compute_dtype=None,
                         observe: Optional[LatencyObserver] = None
                         ) -> jnp.ndarray:
    """``generate_split`` with the speculative burst loop. Same contract and
    return shape ((1, max_new_tokens) int32 — speculation is a per-stream
    latency lever, so batch is 1); greedy output is token-identical to the
    vanilla loop on the same seed/plan. ``raw_params`` (the unplaced pytree)
    is required: the stage-0 draft is sliced out of it. ``recovery``
    supports checkpointing/halt/watchdog at burst granularity; stage-failure
    injection is refused (failover re-plans the runtime mid-run, which would
    reshape the verify window — run failover drills on the vanilla loop)."""
    if not spec.enabled:
        raise ValueError("generate_speculative called with spec.enabled="
                         "False; use generate_split (which this disabled "
                         "config leaves byte-identical)")
    if not hasattr(rt, "verify_step"):
        raise ValueError(
            "speculative decoding needs the split runtime's k-token "
            f"verify_step; {type(rt).__name__} has none")
    if raw_params is None:
        raise ValueError(
            "speculative decoding needs raw_params= (the unplaced parameter "
            "pytree) to slice out the stage-0 draft layers")
    if recovery is not None and recovery.stage_failure is not None:
        raise ValueError(
            "speculative decoding does not compose with stage-failure "
            "injection (failover re-plans the runtime mid-run); run "
            "failover drills on the vanilla loop")
    need = spec_capacity(np.asarray(prompt_ids).shape[-1], max_new_tokens,
                         spec.k)
    if capacity is None:
        capacity = need
    elif capacity < need:
        raise ValueError(
            f"speculative cache overflow: the verify burst writes past the "
            f"vanilla fill level, needs capacity >= {need}, got {capacity}")
    prompt_ids, capacity, temperature, key = _validate_decode_args(
        prompt_ids, max_new_tokens, capacity, temperature, rng_key)
    if prompt_ids.shape[0] != 1:
        raise ValueError(
            f"speculative decoding is per-stream (batch=1), got batch "
            f"{prompt_ids.shape[0]}; route batches through the batcher")
    cut = None
    if getattr(rt, "split", None) is not None and rt.split.cuts:
        cut = int(rt.split.cuts[0])
    draft_cfg, draft_params = draft_from_params(rt.cfg, raw_params, spec, cut)
    return _spec_loop(rt, placed_params, prompt_ids, max_new_tokens,
                      capacity, temperature, key, fault_step, spec,
                      draft_cfg, draft_params, compute_dtype, stats,
                      recovery, link_health=link_health, observe=observe)


def _spec_loop(rt, placed, prompt_ids, max_new_tokens: int, capacity: int,
               temperature: float, key, fault_step: int, spec: SpecConfig,
               draft_cfg: ModelConfig, draft_params: dict, compute_dtype,
               stats: Optional[dict], rec: Optional[RecoveryConfig],
               link_health=None, resume_state=None, resumed: bool = False,
               observe: Optional[LatencyObserver] = None) -> jnp.ndarray:
    """The burst loop. ``resume_state`` = (last_done_step, toks, cache)
    continues a checkpointed run from the burst boundary at step
    ``last_done_step`` (the draft cache is rebuilt by a draft prefill over
    the committed prefix)."""
    b, s = prompt_ids.shape
    k = spec.k
    counters = RecoveryCounters()
    wd = (Watchdog(rec.deadline_s, clock=rec.clock)
          if rec is not None and rec.deadline_s is not None else None)
    run_meta = {"capacity": int(capacity), "temperature": float(temperature),
                "max_new_tokens": int(max_new_tokens),
                "fault_step": int(fault_step), "prompt_len": int(s),
                "batch": int(b),
                "speculative": {"k": int(k),
                                "draft_source": spec.draft_source,
                                "draft_layers": int(draft_cfg.num_layers)}}
    counters0 = rt.link_counters() if isinstance(rt, CounterSource) else None
    draft_misses0 = draft_step_cache_size()
    halted_at = None
    if observe is not None:
        observe.start()
    if wd is not None:
        wd.arm()

    def checkpoint(toks, cache, t):
        _write_checkpoint(rec, rt, counters, prompt_ids, toks, cache, key,
                          t, run_meta)

    t0 = time.monotonic()
    if resume_state is None:
        with obs_span("generate_spec.prefill", batch=b, prompt_len=s):
            logits, cache = rt.prefill_decode(placed, prompt_ids, capacity,
                                              fault_step=fault_step)
            tok = _sample(logits[:, -1], jax.random.fold_in(key, 0),
                          temperature)
            # draft prefill over the same prompt: fills the stage-0 cache to
            # the same level (its token-0 logits are discarded — token 0 is
            # the target's, same as vanilla)
            _, dcache = _draft_prefill_jit(draft_cfg, draft_params,
                                           prompt_ids, capacity,
                                           compute_dtype)
            jax.block_until_ready(tok)
        if observe is not None:
            observe.first_token(tok)
        t1 = time.monotonic()
        toks = [np.asarray(tok, np.int32)]
        if rec is not None and rec.halt_at_step == 0:
            checkpoint(toks, cache, 0)
            halted_at = 0
        elif (rec is not None and rec.checkpoint_every
                and rec.checkpoint_path):
            checkpoint(toks, cache, 0)
    else:
        last_done, toks_in, cache = resume_state
        toks = [np.asarray(x, np.int32).reshape(b) for x in toks_in]
        prompt_np = np.asarray(prompt_ids, np.int32)
        fed = (np.concatenate(
            [prompt_np] + [t[:, None] for t in toks[:-1]], axis=1)
            if len(toks) > 1 else prompt_np)
        with obs_span("generate_spec.resume_draft_prefill",
                      prefix_len=int(fed.shape[1])):
            _, dcache = _draft_prefill_jit(draft_cfg, draft_params,
                                           jnp.asarray(fed), capacity,
                                           compute_dtype)
        t1 = t0

    n = len(toks)
    drafted = accepted = rejected = bursts = 0
    emitted_total = 0
    with obs_span("generate_spec.burst_loop", k=k,
                  budget=max_new_tokens - n):
        while halted_at is None and n < max_new_tokens:
            t_prev = n - 1
            # ---- draft k-1 tokens on stage 0, greedy, hop-free ----
            feed = [toks[-1]]  # x_0 = last committed token
            for _ in range(1, k):
                dtok, dcache = _draft_step_jit(
                    draft_cfg, draft_params, dcache,
                    jnp.asarray(feed[-1]), compute_dtype)
                feed.append(np.asarray(dtok, np.int32))
            drafted += k - 1
            # ---- verify all k positions in ONE split pass (one hop round
            # per cut, carrying the (1, k, D) block) ----
            x = jnp.asarray(np.stack(feed, axis=1))  # (1, k)
            vlogits, vcache = rt.verify_step(placed, cache, x)
            bursts += 1
            # ---- accept ----
            emitted = []  # np (1,) int32 per token
            acc = 0
            full = True
            for j in range(1, k):
                pkey = jax.random.fold_in(key, n + j - 1)
                if temperature == 0.0:
                    # greedy exact match: the emitted token IS the vanilla
                    # argmax whether or not the draft agreed
                    ej = np.asarray(_sample(vlogits[:, j - 1], pkey, 0.0),
                                    np.int32)
                    emitted.append(ej)
                    # acceptance IS host control flow: this sync decides the
                    # burst's commit length, it cannot stay on device
                    if int(ej[0]) == int(feed[j][0]):  # graphlint: disable=EG005
                        acc += 1
                    else:
                        full = False
                        break
                else:
                    probs = jax.nn.softmax(vlogits[0, j - 1] / temperature)
                    dj = int(feed[j][0])  # graphlint: disable=EG005
                    u = jax.random.uniform(jax.random.fold_in(pkey, 1))
                    # same: the accept/reject draw gates the python loop
                    if float(u) < float(probs[dj]):  # graphlint: disable=EG005
                        emitted.append(feed[j])
                        acc += 1
                    else:
                        resid = probs.at[dj].set(0.0)
                        rtok = jax.random.categorical(
                            jax.random.fold_in(pkey, 2), jnp.log(resid))
                        emitted.append(
                            np.asarray(rtok, np.int32).reshape(1))
                        full = False
                        break
            if full:
                # every draft held: the bonus token comes free from the last
                # verify position, with the vanilla key for its step index
                bonus = _sample(vlogits[:, k - 1],
                                jax.random.fold_in(key, n + k - 1),
                                temperature)
                emitted.append(np.asarray(bonus, np.int32))
            rejected += (k - 1) - acc
            accepted += acc
            emitted = emitted[:max_new_tokens - n]  # budget clamp
            m = len(emitted)
            emitted_total += m
            if observe is not None:
                for e in emitted:
                    observe.token(e)
            toks.extend(emitted)
            # ---- commit: length rewrites only (masked garbage past the
            # fill level makes rollback exact, no data movement) ----
            n += m
            cache = {"k": vcache["k"], "v": vcache["v"],
                     "length": jnp.asarray(s + n - 1, jnp.int32)}
            if m == k:
                # fully accepted: the draft's k-1 steps never wrote the last
                # fed token's KV row — one catch-up step backfills it (same
                # shapes, same executable, logits discarded)
                _, dcache = _draft_step_jit(
                    draft_cfg, draft_params, dcache,
                    jnp.asarray(feed[k - 1]), compute_dtype)
            dcache = KVCache(dcache.k, dcache.v,
                             jnp.asarray(s + n - 1, jnp.int32))
            # ---- recovery hooks, at burst granularity (bound to the burst
            # index so checkpoint/timeout spans carry spec_burst) ----
            t = n - 1
            if rec is not None:
                with obs_context.bind(spec_burst=bursts):
                    if rec.halt_at_step is not None and t >= rec.halt_at_step:
                        checkpoint(toks, cache, t)
                        halted_at = t
                        break
                    if (rec.checkpoint_every and rec.checkpoint_path
                            and (t_prev // rec.checkpoint_every
                                 < t // rec.checkpoint_every)):
                        checkpoint(toks, cache, t)
                    if wd is not None:
                        ckpt_fn = ((lambda: checkpoint(toks, cache, t))
                                   if rec.checkpoint_path else None)
                        try:
                            wd.check(ckpt_fn)
                        except DecodeTimeout:
                            counters.watchdog_fires += 1
                            if stats is not None:
                                stats["recovery_counters"] = \
                                    counters.as_dict()
                            raise

    out = jnp.asarray(np.stack(toks, axis=1))  # (1, len(toks))
    jax.block_until_ready(out)
    t2 = time.monotonic()
    if resumed and halted_at is None:
        counters.resume_ok += 1

    spec_stats = {
        "k": int(k), "draft_layers": int(draft_cfg.num_layers),
        "bursts": bursts, "drafted": drafted, "accepted": accepted,
        "rejected": rejected,
        "acceptance_rate": (accepted / drafted) if drafted else 0.0,
        "hops_per_token": (bursts / emitted_total) if emitted_total else 0.0,
        "draft_step_cache_misses": draft_step_cache_size() - draft_misses0,
    }
    counters1 = rt.link_counters() if isinstance(rt, CounterSource) else None
    delta = None
    if counters1 is not None:
        delta = {kk: [int(x) for x in (v if counters0 is None
                                       else v - counters0[kk])]
                 for kk, v in counters1.items()}
    if link_health is not None:
        link_health.observe(delta)
    record_link_counters(delta)
    if link_health is not None:
        record_link_health(link_health.summary())
    record_spec_stats(spec_stats)
    if get_registry().enabled and isinstance(rt, CounterSource):
        record_wire_bytes(rt.verify_hop_bytes(b, k), kind="verify",
                          steps=bursts)
        record_probe_decisions(rt.wire_summary(b, k))
    if tracing_enabled() and hasattr(rt, "hop_attribution"):
        # one hop round per burst: the per-hop wire cost is the k-token
        # verify payload times the burst count
        _emit_hop_spans(
            rt, delta, [x * bursts for x in rt.verify_hop_bytes(b, k)],
            link_tier=getattr(link_health, "tier", None),
            spec_bursts=int(bursts))
    if stats is not None:
        stats.update(
            capacity=capacity,
            prefill_s=t1 - t0,
            decode_s=t2 - t1,
            decode_steps=emitted_total,
            decode_tokens_per_s=(emitted_total / (t2 - t1))
            if emitted_total and t2 > t1 else 0.0,
            speculative=spec_stats,
        )
        if halted_at is not None:
            stats["halted_at_step"] = halted_at
        if rec is not None or resumed:
            # resumed runs report counters even recovery-free, matching the
            # vanilla survivable loop (resume_ok is the signal callers read)
            stats["recovery_counters"] = counters.as_dict()
        if delta is not None:
            stats["link_counters"] = delta
        if link_health is not None:
            stats["link_health"] = link_health.summary()
        if observe is not None:
            stats.update(observe.summary())
        record_decode_stats(stats)
    if rec is not None or resumed:
        record_recovery_counters(counters)
    if observe is not None:
        observe.publish()
    return out


def resume_speculative(rt: Any, placed_params: dict, checkpoint_path: str, *,
                       spec: SpecConfig,
                       stats: Optional[dict] = None,
                       recovery: Optional[RecoveryConfig] = None,
                       raw_params: Optional[dict] = None,
                       observe: Optional[LatencyObserver] = None
                       ) -> jnp.ndarray:
    """Resume a checkpointed speculative generation and return the FULL
    (1, max_new) token matrix, token-identical to the uninterrupted run:
    checkpoints land only on burst boundaries, burst boundaries depend only
    on the committed prefix, and the per-step keys depend only on (seed,
    step index). Validates the same plan/model meta as ``resume_split`` plus
    the checkpoint's ``speculative`` block against ``spec`` (a window or
    draft mismatch would re-shape the burst sequence). A vanilla (spec-free)
    checkpoint resumes fine at ``temperature == 0`` — greedy identity does
    not care where the boundaries fall."""
    if not spec.enabled:
        raise ValueError("resume_speculative called with spec.enabled=False;"
                         " use resume_split")
    if raw_params is None:
        raise ValueError(
            "speculative resume needs raw_params= (the unplaced parameter "
            "pytree) to rebuild the stage-0 draft")
    with obs_span("decode.checkpoint_resume", path=checkpoint_path):
        ckpt = DecodeCheckpoint.load(checkpoint_path)
    meta = ckpt.meta
    want = runtime_plan_meta(rt)
    for kk, label in (("mode", "runtime mode"), ("model", "model signature"),
                      ("cuts", "split cuts"), ("hop_codecs", "hop codecs")):
        if meta.get(kk) != want.get(kk):
            raise CheckpointError(
                f"checkpoint {checkpoint_path} was written for {label} "
                f"{meta.get(kk)!r}, the resuming runtime has "
                f"{want.get(kk)!r}; rebuild the runtime to match")
    cut = None
    if getattr(rt, "split", None) is not None and rt.split.cuts:
        cut = int(rt.split.cuts[0])
    draft_cfg, draft_params = draft_from_params(rt.cfg, raw_params, spec, cut)
    sm = meta.get("speculative")
    if sm is not None:
        got = {"k": int(spec.k), "draft_source": spec.draft_source,
               "draft_layers": int(draft_cfg.num_layers)}
        if {kk: sm.get(kk) for kk in got} != got:
            raise CheckpointError(
                f"checkpoint {checkpoint_path} was written with speculative "
                f"config {sm!r}, the resuming run has {got!r}; a window or "
                f"draft mismatch breaks the token-identical-resume "
                f"guarantee")
    prompt_ids = jnp.asarray(ckpt.arrays["prompt_ids"])
    tokens = ckpt.arrays["tokens"]  # (1, step+1)
    key = jax.random.wrap_key_data(jnp.asarray(ckpt.arrays["rng_key"]))
    cache = cache_from_state_dict({"k": ckpt.arrays["cache/k"],
                                   "v": ckpt.arrays["cache/v"],
                                   "length": ckpt.arrays["cache/length"]})
    toks = [tokens[:, i] for i in range(tokens.shape[1])]
    step = int(meta["step"])
    if len(toks) != step + 1:
        raise CheckpointError(
            f"checkpoint {checkpoint_path} is inconsistent: step {step} "
            f"with {len(toks)} sampled tokens")
    rec = recovery
    if rec is not None and rec.stage_failure is not None:
        raise ValueError(
            "speculative decoding does not compose with stage-failure "
            "injection; run failover drills on the vanilla loop")
    if stats is not None:
        stats["resumed_from_step"] = step
        if "link_counters" in meta:
            stats["checkpoint_link_counters"] = meta["link_counters"]
    return _spec_loop(
        rt, placed_params, prompt_ids, int(meta["max_new_tokens"]),
        int(meta["capacity"]), float(meta["temperature"]), key,
        int(meta["fault_step"]), spec, draft_cfg, draft_params, None,
        stats, rec, resume_state=(step, toks, cache), resumed=True,
        observe=observe)
