"""Deterministic chaos soak for the serving front.

An open-loop workload (seeded Poisson arrivals on a virtual clock) pushed
through a :class:`~edgellm_tpu.serve.frontend.ServeFront` while scheduled
chaos fires mid-run — a whole-stage kill, a link-corruption burst — and a
verifiable artifact comes out the other side: goodput, SLO attainment,
reject/shed rates, p99 TTFT, post-kill recovery time, retry-budget
accounting, and a bit-identity audit of every ``completed`` request against
a fault-free reference.

Determinism is the whole point — a chaos run that cannot be replayed
cannot be debugged:

- Time is a :class:`~edgellm_tpu.utils.clock.FakeClock`. Arrivals,
  deadlines, breaker timeouts, and brownout dwells all live on the virtual
  timeline; after each served request the clock advances by that request's
  *measured* service wall time, so the virtual timeline is load-consistent
  without a single real ``sleep``.
- The workload is a seeded ``numpy`` RNG: interarrival gaps, prompts, and
  priorities all replay from ``SoakConfig.seed``.
- Chaos is scheduled by arrival index, not wall time: the kill fires just
  before request ``floor(n * kill_at_frac)`` is submitted, the corruption
  burst spans the ``[burst_start_frac, burst_end_frac)`` arrival window
  (schedule the burst before the kill — after a stage-loss replan the
  pre-kill burst runtime no longer matches the topology, so the restore is
  skipped).
- Fault injection itself is the seeded in-graph machinery of
  ``codecs.faults`` — the same virtual run replays the same corrupted hops.

The identity audit holds ``completed`` to its contract: for each completed
request, the same seed/prompt/shape replays on a *fault-free* runtime of
the same plan (same cuts, same codecs, same mesh — captured when the plan
first served), and the tokens must match bit-for-bit. Verified transport
is only worth building if the service above it cannot quietly serve
garbage with a green status.
"""
from __future__ import annotations

import dataclasses
import math
import struct
import zlib
from typing import Any, Optional

import numpy as np
import jax

from ..obs.flight import get_flight_recorder
from ..obs.metrics import Histogram
from ..utils.clock import FakeClock
from .decode import generate, generate_split
from .frontend import Request, ServeFront
from .overload import COMPLETED, FAILED_OVER, REJECTED, SHED, TIMED_OUT

__all__ = ["ClusterSoakConfig", "DisaggSoakConfig", "SoakConfig",
           "run_cluster_soak", "run_disagg_soak", "run_soak"]


@dataclasses.dataclass(frozen=True)
class SoakConfig:
    """The replayable soak definition. ``arrival_rate`` is requests per
    virtual second; ``deadline_s`` applies to every request (None =
    best-effort); ``priority_levels`` spreads requests uniformly over
    priorities ``0..levels-1``. Chaos: ``kill_stage``/``kill_at_frac``
    schedule the stage kill, the burst window is actuated by the
    ``burst_runtime`` argument of :func:`run_soak`. ``verify_identity``
    re-runs every completed request on a clean reference (the expensive
    half of the soak — turn it off for pure throughput runs)."""

    n_requests: int = 32
    arrival_rate: float = 2.0
    seed: int = 0
    prompt_len: int = 8
    #: first N prompt tokens identical across every request (a seeded
    #: "system prompt") — the workload shape a prefix-enabled batcher turns
    #: into mapped pages instead of prefill compute; 0 = fully random
    shared_prefix_len: int = 0
    max_new_tokens: int = 8
    deadline_s: Optional[float] = 60.0
    temperature: float = 0.7
    priority_levels: int = 2
    kill_stage: Optional[int] = None
    kill_at_frac: float = 0.5
    burst_start_frac: float = 0.15
    burst_end_frac: float = 0.35
    verify_identity: bool = True

    def __post_init__(self):
        if self.n_requests < 1:
            raise ValueError("n_requests must be >= 1")
        if self.arrival_rate <= 0:
            raise ValueError("arrival_rate must be > 0")
        for f in ("kill_at_frac", "burst_start_frac", "burst_end_frac"):
            v = getattr(self, f)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{f} must be in [0, 1], got {v!r}")
        if self.burst_end_frac < self.burst_start_frac:
            raise ValueError("burst_end_frac must be >= burst_start_frac")
        if self.priority_levels < 1:
            raise ValueError("priority_levels must be >= 1")
        if not 0 <= self.shared_prefix_len <= self.prompt_len:
            raise ValueError(
                f"shared_prefix_len must be in [0, prompt_len="
                f"{self.prompt_len}], got {self.shared_prefix_len}")


def _plan_key(plan: Optional[dict]) -> tuple:
    if plan is None or plan.get("mode") != "split":
        return ("local",)
    return ("split", tuple(plan["cuts"]), tuple(plan["hop_codecs"]))


class _IdentityVerifier:
    """Streaming bit-identity audit: each completed record is replayed on a
    clean same-plan runtime *as it drains* and only counters survive — the
    10⁶-request soak never holds a per-request sample list. ``plan_meshes``
    maps split plan keys to the (SplitConfig, Mesh) that served them
    (captured by the soak loop when each plan first serves)."""

    #: keep at most this many mismatching request ids for the artifact —
    #: enough to debug, bounded so a systemic mismatch cannot balloon memory
    MAX_MISMATCH_IDS = 32

    def __init__(self, front: ServeFront, plan_meshes: dict):
        self.front = front
        self.plan_meshes = plan_meshes
        self._ref_runners: dict = {}
        self.checked = 0
        self.matched = 0
        self.mismatched_ids: list = []

    def check(self, r: Any, prompt: np.ndarray, temperature: float) -> None:
        from ..parallel.split import SplitConfig, SplitRuntime

        if r.outcome != COMPLETED or r.tokens is None:
            return
        key = _plan_key(r.plan)
        if key not in self._ref_runners:
            if key[0] == "local":
                self._ref_runners[key] = None
            else:
                split, mesh = self.plan_meshes[key]
                clean = SplitRuntime(
                    self.front.model_cfg,
                    SplitConfig(cuts=split.cuts,
                                hop_codecs=split.hop_codecs),
                    mesh)
                self._ref_runners[key] = (
                    clean, clean.place_params(self.front.params))
        runner = self._ref_runners[key]
        rng = jax.random.key(0)  # the soak submits every request with seed 0
        if runner is None:
            ref = generate(self.front.model_cfg, self.front.params, prompt,
                           r.granted_tokens, capacity=r.capacity,
                           temperature=temperature, rng_key=rng,
                           compute_dtype=self.front.compute_dtype)
        else:
            clean, placed = runner
            # the replay must run the same decode algorithm the front did:
            # a speculative front samples through residual resampling, whose
            # stream matches vanilla sampling only at temperature 0 (spec-vs-
            # vanilla parity is pinned separately, in tests/test_speculative).
            # The capacity bump mirrors ServeFront._run — the record keeps the
            # pre-bump bucketed value.
            spec = getattr(self.front, "speculative", None)
            spec_kw: dict = {}
            cap = r.capacity
            if getattr(spec, "enabled", False):
                spec_kw = {"speculative": spec,
                           "raw_params": self.front.params}
                cap = max(cap, prompt.shape[1] + r.granted_tokens
                          + spec.k - 2)
            ref = generate_split(clean, placed, prompt, r.granted_tokens,
                                 capacity=cap,
                                 temperature=temperature, rng_key=rng,
                                 fault_step=r.request_id, **spec_kw)
        self.checked += 1
        if np.array_equal(np.asarray(ref), r.tokens):
            self.matched += 1
        elif len(self.mismatched_ids) < self.MAX_MISMATCH_IDS:
            self.mismatched_ids.append(r.request_id)

    def summary(self) -> dict:
        return {"checked": self.checked, "matched": self.matched,
                "ok": self.checked == self.matched,
                "mismatched_ids": list(self.mismatched_ids)}


def run_soak(front: ServeFront, soak: SoakConfig, *, clock: FakeClock,
             burst_runtime: Any = None) -> dict:
    """Run one deterministic soak; returns the artifact dict.

    ``front`` must be freshly built on ``clock`` (the soak owns the virtual
    timeline, and the artifact's rates assume the front's records are this
    soak's records). ``burst_runtime``, when given, is a same-topology split
    runtime with burst-level corruption: it is swapped in over the burst
    arrival window (breaker state preserved) and the original runtime is
    restored afterwards — unless a stage-loss replan happened in between,
    in which case the replanned runtime stands."""
    if not isinstance(clock, FakeClock):
        raise TypeError("run_soak needs the front's FakeClock — the soak "
                        "owns the virtual timeline")
    rng = np.random.default_rng(soak.seed)
    n = soak.n_requests
    arrive_t = clock.now + np.cumsum(
        rng.exponential(1.0 / soak.arrival_rate, n))
    vocab = front.model_cfg.vocab_size
    prompts = rng.integers(0, vocab, (n, soak.prompt_len), dtype=np.int32)
    if soak.shared_prefix_len:
        # same seeded block opens every prompt (drawn AFTER the matrix so a
        # shared_prefix_len of 0 replays byte-identical historical soaks)
        prompts[:, :soak.shared_prefix_len] = rng.integers(
            0, vocab, soak.shared_prefix_len, dtype=np.int32)
    priorities = rng.integers(0, soak.priority_levels, n)

    kill_idx = (int(n * soak.kill_at_frac)
                if soak.kill_stage is not None else None)
    burst_on_idx = (int(n * soak.burst_start_frac)
                    if burst_runtime is not None else None)
    burst_off_idx = (int(n * soak.burst_end_frac)
                     if burst_runtime is not None else None)
    normal_rt = front.split_runtime
    failovers_at_burst_on = 0
    kill_at_s: Optional[float] = None
    burst_window_s: list = []

    # streaming state only — a 10⁶-request soak holds memory flat: the
    # per-request dict is popped at each terminal record, and everything
    # the artifact needs is a running aggregate
    submitted: dict = {}       # in-flight request id -> (prompt, temperature)
    plan_meshes: dict = {}     # split plan key -> (SplitConfig, Mesh)
    verifier = (_IdentityVerifier(front, plan_meshes)
                if soak.verify_identity else None)
    max_call = 0               # largest retries_charged on any one record
    first_done_after_kill: Optional[float] = None
    start_s = clock.now

    def fire_events(i: int) -> None:
        nonlocal kill_at_s, failovers_at_burst_on
        if burst_on_idx is not None and i == burst_on_idx:
            failovers_at_burst_on = front.failovers
            burst_window_s.append(clock.now)
            front.set_split_runtime(burst_runtime, keep_breakers=True)
        if burst_off_idx is not None and i == burst_off_idx:
            burst_window_s.append(clock.now)
            if front.failovers == failovers_at_burst_on:
                front.set_split_runtime(normal_rt, keep_breakers=True)
        if kill_idx is not None and i == kill_idx:
            kill_at_s = clock.now
            if front.split_runtime is not None:
                front.split_runtime.mark_stage_lost(soak.kill_stage)

    i = 0
    while i < n or front.queue_depth:
        if front.queue_depth == 0 and i < n and clock.now < arrive_t[i]:
            # host numpy scalar, not a device sync
            clock.set_time(float(arrive_t[i]))  # graphlint: disable=EG005
        while i < n and arrive_t[i] <= clock.now:
            fire_events(i)
            rid, refusal = front.submit_ex(Request(
                prompt_ids=prompts[i], max_new_tokens=soak.max_new_tokens,
                priority=int(priorities[i]),  # graphlint: disable=EG005
                deadline_s=soak.deadline_s,
                temperature=soak.temperature, rng_seed=0))
            if refusal is None:
                # only in-flight requests live in the dict — a refusal is
                # terminal here and stores nothing (memory stays flat under
                # a shedding storm too)
                submitted[rid] = (prompts[i][None, :], soak.temperature)
            i += 1
        for rec in front.drain(max_requests=1):
            if rec.service_s is not None:
                clock.advance(rec.service_s)
            if rec.plan is not None and rec.plan.get("mode") == "split":
                key = _plan_key(rec.plan)
                if key not in plan_meshes:
                    rt = front.split_runtime
                    plan_meshes[key] = (rt.split, rt.mesh)
            max_call = max(max_call, rec.retries_charged)
            if (kill_at_s is not None
                    and rec.outcome in (COMPLETED, FAILED_OVER)
                    and rec.finished_at is not None
                    and rec.finished_at > kill_at_s):
                first_done_after_kill = (
                    rec.finished_at if first_done_after_kill is None
                    else min(first_done_after_kill, rec.finished_at))
            meta = submitted.pop(rec.request_id, None)
            if verifier is not None and meta is not None:
                verifier.check(rec, meta[0], meta[1])
    span_s = max(clock.now - start_s, 1e-9)

    # recovery time: kill -> first request finishing cleanly afterwards
    recovery_s = None
    if kill_at_s is not None and first_done_after_kill is not None:
        recovery_s = first_done_after_kill - kill_at_s

    report = front.report()
    outcomes = report["outcomes"]
    identity = verifier.summary() if verifier is not None else None

    budget = report["retry_budget"]
    budget_bound = (budget["capacity"]
                    + budget["refill_per_s"] * span_s + max_call)
    fl = get_flight_recorder()
    return {
        "soak": dataclasses.asdict(soak),
        "virtual_span_s": span_s,
        "requests": n,
        "outcomes": outcomes,
        "goodput_tokens_per_s": report["tokens_out"] / span_s,
        "slo_attainment": report["slo_attainment"],
        "reject_rate": outcomes.get(REJECTED, 0) / n,
        "shed_rate": outcomes.get(SHED, 0) / n,
        "p99_ttft_s": (report["ttft_s"] or {}).get("p99"),
        "p99_latency_s": (report["latency_s"] or {}).get("p99"),
        "kill": (None if kill_at_s is None else
                 {"stage": soak.kill_stage, "at_s": kill_at_s,
                  "recovery_s": recovery_s}),
        "burst": (None if not burst_window_s else
                  {"start_s": burst_window_s[0],
                   "end_s": (burst_window_s[1]
                             if len(burst_window_s) > 1 else None)}),
        "retry_budget": {**budget, "max_single_call": max_call,
                         "within_budget": budget["spent"] <= budget_bound},
        "token_identity": identity,
        # post-mortems captured during the soak (exactly one per injected
        # failure instance), or None when no flight recorder is armed
        "flight_dumps": (list(fl.dumps()) if fl is not None else None),
        "report": report,
    }


# ---------------------------------------------------------------------------
# cluster-scale chaos soak (~10⁶ requests on the virtual clock)
# ---------------------------------------------------------------------------


def _draw(seed: int, i: int, salt: int) -> int:
    """One deterministic 32-bit workload draw, addressable by (seed, index,
    stream). The cluster soak derives EVERYTHING — interarrival gaps,
    prompts, priorities, sampling temperatures, rng seeds — from this, so
    the identity audit regenerates any request from its index alone instead
    of holding 10⁶ submitted prompts in memory."""
    return zlib.crc32(struct.pack("<qqq", seed, i, salt)) & 0xFFFFFFFF


def _u01(seed: int, i: int, salt: int) -> float:
    return (_draw(seed, i, salt) + 0.5) / 2.0 ** 32


@dataclasses.dataclass(frozen=True)
class ClusterSoakConfig:
    """The replayable cluster-soak definition — the million-request shape.

    Prompts open with one of ``num_prefix_groups`` shared prefixes (the
    "system prompt" population the router's prefix affinity should exploit)
    followed by per-request suffix tokens. ``sampled_frac`` of requests
    sample at ``sample_temperature`` with a per-index recorded seed; the
    rest are greedy — both replay token-identically from the index.
    Chaos: ``kills`` schedules replica kills by arrival fraction,
    ``burst_start_frac``/``burst_end_frac`` bound a link-corruption window
    (``burst_corrupt_rate`` per completing request, seeded) across the
    fleet. ``goodput_bucket_s`` is the resolution of the tokens-per-virtual-
    second series the outage-window goodput gate reads."""

    n_requests: int = 1000
    arrival_rate: float = 200.0
    seed: int = 0
    vocab_size: int = 50_000
    prompt_len: int = 16
    shared_prefix_len: int = 8
    num_prefix_groups: int = 32
    max_new_tokens: int = 16
    deadline_s: Optional[float] = 120.0
    sampled_frac: float = 0.5
    sample_temperature: float = 0.7
    priority_levels: int = 2
    #: ((arrival_frac, replica_id), ...) — each kills that replica just
    #: before the request at ``floor(n * frac)`` is submitted
    kills: tuple = ()
    #: ((arrival_frac, replica_id, service_multiplier), ...) — gray
    #: failures: just before the request at ``floor(n * frac)`` is
    #: submitted, the replica starts serving every phase ``multiplier`` ×
    #: slower. Re-asserted per arrival, so a respawn inherits the slowdown
    #: — gray hardware stays gray across process restarts. Firing AFTER the
    #: fleet has warmed up captures the nasty case: prefix affinity keeps
    #: routing a slow replica's groups at it no matter how its queue grows.
    slowdowns: tuple = ()
    burst_start_frac: float = 0.0
    burst_end_frac: float = 0.0
    burst_corrupt_rate: float = 0.0
    verify_identity: bool = True
    goodput_bucket_s: float = 1.0

    def __post_init__(self):
        if self.n_requests < 1:
            raise ValueError("n_requests must be >= 1")
        if self.arrival_rate <= 0:
            raise ValueError("arrival_rate must be > 0")
        if not 0 <= self.shared_prefix_len <= self.prompt_len:
            raise ValueError(
                f"shared_prefix_len must be in [0, prompt_len="
                f"{self.prompt_len}], got {self.shared_prefix_len}")
        if self.num_prefix_groups < 1:
            raise ValueError("num_prefix_groups must be >= 1")
        if not 0.0 <= self.sampled_frac <= 1.0:
            raise ValueError(
                f"sampled_frac must be in [0, 1], got {self.sampled_frac!r}")
        for f in ("burst_start_frac", "burst_end_frac"):
            v = getattr(self, f)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{f} must be in [0, 1], got {v!r}")
        if self.burst_end_frac < self.burst_start_frac:
            raise ValueError("burst_end_frac must be >= burst_start_frac")
        if not 0.0 <= self.burst_corrupt_rate <= 1.0:
            raise ValueError(
                f"burst_corrupt_rate must be in [0, 1], got "
                f"{self.burst_corrupt_rate!r}")
        for frac, _rid in self.kills:
            if not 0.0 <= frac <= 1.0:
                raise ValueError(
                    f"kill fraction must be in [0, 1], got {frac!r}")
        for frac, _rid, mult in self.slowdowns:
            if not 0.0 <= frac <= 1.0:
                raise ValueError(
                    f"slowdown fraction must be in [0, 1], got {frac!r}")
            if mult < 1.0:
                raise ValueError(
                    f"slowdown multiplier must be >= 1, got {mult!r}")
        if self.goodput_bucket_s <= 0:
            raise ValueError("goodput_bucket_s must be > 0")
        if self.priority_levels < 1:
            raise ValueError("priority_levels must be >= 1")


def _cluster_prompt(soak: ClusterSoakConfig, i: int) -> np.ndarray:
    """Request ``i``'s prompt, regenerated from the index (never stored):
    a shared prefix drawn from one of ``num_prefix_groups`` seeded blocks +
    an affine per-request suffix."""
    h = _draw(soak.seed, i, 1)
    group = _draw(soak.seed, h % soak.num_prefix_groups, 2)
    pre = (group + 7919
           * np.arange(soak.shared_prefix_len, dtype=np.int64)
           ) % soak.vocab_size
    suf_len = soak.prompt_len - soak.shared_prefix_len
    suf = (h + 104729 * (np.arange(suf_len, dtype=np.int64) + 1)
           ) % soak.vocab_size
    return np.concatenate([pre, suf]).astype(np.int32)


def _cluster_request(soak: ClusterSoakConfig, i: int) -> Request:
    sampled = _u01(soak.seed, i, 3) < soak.sampled_frac
    return Request(
        prompt_ids=_cluster_prompt(soak, i),
        max_new_tokens=soak.max_new_tokens,
        priority=_draw(soak.seed, i, 5) % soak.priority_levels,
        deadline_s=soak.deadline_s,
        temperature=soak.sample_temperature if sampled else 0.0,
        rng_seed=_draw(soak.seed, i, 4) if sampled else 0)


def run_cluster_soak(cluster: Any, soak: ClusterSoakConfig, *,
                     clock: FakeClock) -> dict:
    """Push the seeded open-loop workload through a
    :class:`~edgellm_tpu.serve.cluster.ClusterFront` of simulated replicas
    (each advances the shared FakeClock by its virtual service time) while
    scheduled replica kills and link-corruption bursts fire; returns the
    artifact dict.

    Memory is flat in ``n_requests``: every per-request quantity is either
    regenerated from its arrival index (prompts, temperatures, seeds — see
    :func:`_draw`) or folded into a running aggregate (outcome counts,
    log-bucketed TTFT/latency histograms, per-virtual-second goodput
    buckets) the moment its record drains. The identity audit replays each
    completed request against the pure
    :func:`~edgellm_tpu.serve.cluster.sim_reference_tokens` chain — the
    fault-free same-plan reference — as it completes."""
    from .cluster import sim_reference_tokens

    if not isinstance(clock, FakeClock):
        raise TypeError("run_cluster_soak needs the cluster's FakeClock — "
                        "the soak owns the virtual timeline")
    n = soak.n_requests
    kill_sched = sorted((int(n * frac), int(rid))
                        for frac, rid in soak.kills)
    slow_sched = sorted((int(n * frac), int(rid), float(mult))
                        for frac, rid, mult in soak.slowdowns)
    active_slowdowns: dict = {}    # replica_id -> multiplier
    burst_on_idx = (int(n * soak.burst_start_frac)
                    if soak.burst_corrupt_rate > 0
                    and soak.burst_end_frac > soak.burst_start_frac
                    else None)
    burst_off_idx = (int(n * soak.burst_end_frac)
                     if burst_on_idx is not None else None)
    burst_active = False
    burst_window_s: list = []

    outcomes: dict = {}
    reasons: dict = {}
    tokens_out = 0
    met = with_deadline = 0
    ttft_hist = Histogram("serve_ttft_s", lo=1e-6, hi=1e4, n_buckets=400)
    latency_hist = Histogram("serve_latency_s", lo=1e-6, hi=1e4,
                             n_buckets=400)
    goodput_buckets: dict = {}     # int bucket -> tokens completed in it
    checked = matched = 0
    mismatched_ids: list = []
    pending_meta: dict = {}        # cluster rid -> arrival index (in-flight)
    kill_events: list = []         # [{replica, at_s, recovery_s}]
    start_s = clock.now

    def apply_burst() -> None:
        """(Re)assert the corruption rate on every live front — respawned
        replicas join the burst too."""
        rate = soak.burst_corrupt_rate if burst_active else 0.0
        for r in cluster.replicas.values():
            set_rate = getattr(r.front, "set_corrupt_rate", None)
            if set_rate is not None:
                set_rate(rate)

    def apply_slowdowns() -> None:
        """(Re)assert active gray-failure service multipliers — a respawned
        replica inherits its slowdown (the hardware is gray, not the
        process)."""
        for rid, mult in active_slowdowns.items():
            r = cluster.replicas.get(rid)
            if r is None or r.front is None:
                continue
            set_mult = getattr(r.front, "set_service_multiplier", None)
            if set_mult is not None:
                set_mult(mult)

    def fire_events(i: int) -> None:
        nonlocal burst_active
        while kill_sched and kill_sched[0][0] == i:
            _, rid = kill_sched.pop(0)
            cluster.kill_replica(rid, "chaos")
            kill_events.append({"replica": rid, "at_s": clock.now,
                                "recovery_s": None})
        while slow_sched and slow_sched[0][0] <= i:
            _, rid, mult = slow_sched.pop(0)
            active_slowdowns[rid] = mult
        if burst_on_idx is not None and i == burst_on_idx:
            burst_active = True
            burst_window_s.append(clock.now)
        if burst_off_idx is not None and i == burst_off_idx:
            burst_active = False
            burst_window_s.append(clock.now)

    def absorb(rec: Any) -> None:
        nonlocal tokens_out, met, with_deadline, checked, matched
        outcomes[rec.outcome] = outcomes.get(rec.outcome, 0) + 1
        if rec.reason:
            reasons[rec.reason] = reasons.get(rec.reason, 0) + 1
        idx = pending_meta.pop(rec.request_id, None)
        if rec.outcome not in (COMPLETED, FAILED_OVER):
            return
        granted = rec.granted_tokens or 0
        tokens_out += rec.batch * granted
        if rec.ttft_s is not None:
            ttft_hist.observe(rec.ttft_s)
        if rec.latency_s is not None:
            latency_hist.observe(rec.latency_s)
        if rec.deadline_s is not None and rec.deadline_met is not None:
            with_deadline += 1
            met += int(rec.deadline_met)
        if rec.finished_at is not None:
            b = int((rec.finished_at - start_s) / soak.goodput_bucket_s)
            goodput_buckets[b] = (goodput_buckets.get(b, 0)
                                  + rec.batch * granted)
            for ev in kill_events:
                if (ev["recovery_s"] is None
                        and rec.finished_at > ev["at_s"]):
                    ev["recovery_s"] = rec.finished_at - ev["at_s"]
        if (soak.verify_identity and rec.outcome == COMPLETED
                and rec.tokens is not None and idx is not None):
            ref, _ = sim_reference_tokens(
                _cluster_prompt(soak, idx), granted,
                temperature=(soak.sample_temperature
                             if _u01(soak.seed, idx, 3) < soak.sampled_frac
                             else 0.0),
                rng_seed=(_draw(soak.seed, idx, 4)
                          if _u01(soak.seed, idx, 3) < soak.sampled_frac
                          else 0),
                vocab_size=soak.vocab_size)
            checked += 1
            if np.array_equal(np.asarray(rec.tokens).reshape(-1), ref):
                matched += 1
            elif len(mismatched_ids) < 32:
                mismatched_ids.append(rec.request_id)

    i = 0
    next_t = clock.now
    while i < n or cluster.pending or cluster.busy:
        while i < n and next_t <= clock.now:
            fire_events(i)
            apply_burst()
            apply_slowdowns()
            crid = cluster.submit(_cluster_request(soak, i))
            pending_meta[crid] = i
            gap = -math.log(_u01(soak.seed, i, 0)) / soak.arrival_rate
            next_t += gap
            i += 1
        recs = cluster.drain(max_requests=8)
        for rec in recs:
            absorb(rec)
        if not recs:
            # nothing drained: jump the virtual clock to whatever happens
            # next — the next arrival or the next scheduled respawn
            targets = [next_t] if i < n else []
            ev = cluster.next_event_s()
            if ev is not None:
                targets.append(ev)
            if targets and min(targets) > clock.now:
                clock.set_time(min(targets))
            elif i >= n:
                break  # idle fleet, nothing scheduled: drained dry
    span_s = max(clock.now - start_s, 1e-9)

    report = cluster.report()

    def pct(h: Histogram, q: float) -> Optional[float]:
        return float(h.quantile(q)) if h.count else None

    return {
        "soak": dataclasses.asdict(soak),
        "virtual_span_s": span_s,
        "requests": n,
        "outcomes": outcomes,
        "reasons": reasons,
        "goodput_tokens_per_s": tokens_out / span_s,
        "slo_attainment": (met / with_deadline) if with_deadline else None,
        # fleet-level SLO: deadline-met completions over ALL submitted
        # requests, so a timed-out request counts as a miss instead of
        # silently leaving the denominator — the gray bench gates on this
        "slo_goodput": met / n,
        "reject_rate": outcomes.get(REJECTED, 0) / n,
        "shed_rate": outcomes.get(SHED, 0) / n,
        "timeout_rate": outcomes.get(TIMED_OUT, 0) / n,
        "p99_ttft_s": pct(ttft_hist, 0.99),
        "p99_latency_s": pct(latency_hist, 0.99),
        "kills": kill_events,
        "burst": (None if not burst_window_s else
                  {"start_s": burst_window_s[0],
                   "end_s": (burst_window_s[1]
                             if len(burst_window_s) > 1 else None),
                   "corrupt_rate": soak.burst_corrupt_rate}),
        "goodput_buckets": {"width_s": soak.goodput_bucket_s,
                            "tokens": goodput_buckets},
        "token_identity": {"checked": checked, "matched": matched,
                           "ok": checked == matched,
                           "mismatched_ids": mismatched_ids},
        "readmitted": report["totals"]["readmitted"],
        "recompute_tokens": report["totals"]["recompute_tokens"],
        "parked_total": report["totals"]["parked_total"],
        "hedges": report["totals"].get("hedges", 0),
        "hedge_wins": (report["totals"].get("hedge_wins_primary", 0)
                       + report["totals"].get("hedge_wins_hedge", 0)),
        "hedge_discarded": report["totals"].get("hedge_discarded", 0),
        "hedge_fraction": (report["totals"].get("hedges", 0)
                           / max(report["totals"].get("placed", 0), 1)),
        "deadline_expired": report["totals"].get("deadline_expired", 0),
        "gray": report.get("gray"),
        "respawns": sum(r["respawns"]
                        for r in report["replicas"].values()),
        "flight_dumps": cluster.flight_dumps(),
        "report": report,
    }


# ---------------------------------------------------------------------------
# disaggregated prefill/decode chaos soak
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DisaggSoakConfig:
    """Replayable chaos soak for a :class:`~edgellm_tpu.serve.disagg.
    DisaggServer` — the real tiny-model server, not a simulation, so the
    request count stays small and every leg of the failure matrix executes
    for real: prefill and migration on staging workers, verified page
    transfers, pull-queue decode admission.

    Chaos is scheduled by arrival index (``kills`` fires just before
    request ``floor(n * frac)`` is submitted). Targets:

    - ``"prefill"`` — arm a MID-MIGRATION kill: the currently-migrating
      prefill worker dies right after its next page lands (between page
      transfers, the hard case).
    - ``"prefill:<i>"`` — kill worker ``i`` immediately.
    - ``"decode"`` — kill the decode worker (checkpoint / handoff-replay
      re-admission).
    - ``"link"`` — fail the migration link (typed degrade to colocated).

    ``[burst_start_frac, burst_end_frac)`` bounds a seeded link-corruption
    window at ``burst_bitflip_rate`` — the ladder must heal or refuse,
    never adopt garbage. The identity audit replays every completed request
    on a fault-free COLOCATED batcher of the same build: disagg under chaos
    must emit bit-identical tokens."""

    n_requests: int = 16
    seed: int = 0
    vocab_size: int = 128
    min_prompt_len: int = 3
    max_prompt_len: int = 18
    max_new_tokens: int = 6
    sampled_frac: float = 0.5
    sample_temperature: float = 0.7
    #: ((arrival_frac, target), ...) with target as documented above
    kills: tuple = ()
    burst_start_frac: float = 0.0
    burst_end_frac: float = 0.0
    burst_bitflip_rate: float = 0.0
    verify_identity: bool = True
    #: pump the server this many times between arrivals so chaos lands on
    #: a genuinely busy front (prefills in flight, queue non-empty)
    steps_per_arrival: int = 1

    def __post_init__(self):
        if self.n_requests < 1:
            raise ValueError("n_requests must be >= 1")
        if not 1 <= self.min_prompt_len <= self.max_prompt_len:
            raise ValueError(
                f"need 1 <= min_prompt_len <= max_prompt_len, got "
                f"[{self.min_prompt_len}, {self.max_prompt_len}]")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not 0.0 <= self.sampled_frac <= 1.0:
            raise ValueError(
                f"sampled_frac must be in [0, 1], got {self.sampled_frac!r}")
        for f in ("burst_start_frac", "burst_end_frac"):
            if not 0.0 <= getattr(self, f) <= 1.0:
                raise ValueError(
                    f"{f} must be in [0, 1], got {getattr(self, f)!r}")
        if self.burst_end_frac < self.burst_start_frac:
            raise ValueError("burst_end_frac must be >= burst_start_frac")
        if not 0.0 <= self.burst_bitflip_rate <= 1.0:
            raise ValueError(
                f"burst_bitflip_rate must be in [0, 1], got "
                f"{self.burst_bitflip_rate!r}")
        for frac, target in self.kills:
            if not 0.0 <= frac <= 1.0:
                raise ValueError(
                    f"kill fraction must be in [0, 1], got {frac!r}")
            if target != "prefill" and target != "decode" \
                    and target != "link" \
                    and not (isinstance(target, str)
                             and target.startswith("prefill:")):
                raise ValueError(
                    f"unknown kill target {target!r}; expected 'prefill', "
                    f"'prefill:<i>', 'decode', or 'link'")
        if self.steps_per_arrival < 0:
            raise ValueError("steps_per_arrival must be >= 0")


def _disagg_request(soak: "DisaggSoakConfig", i: int) -> tuple:
    """Request ``i`` regenerated from its index: (prompt, max_new_tokens,
    temperature, rng_seed)."""
    span = soak.max_prompt_len - soak.min_prompt_len + 1
    ln = soak.min_prompt_len + _draw(soak.seed, i, 11) % span
    toks = (_draw(soak.seed, i, 12)
            + 104729 * (np.arange(ln, dtype=np.int64) + 1)
            ) % (soak.vocab_size - 1) + 1
    sampled = _u01(soak.seed, i, 13) < soak.sampled_frac
    return (toks.astype(np.int32), soak.max_new_tokens,
            soak.sample_temperature if sampled else 0.0,
            _draw(soak.seed, i, 14) if sampled else 0)


def run_disagg_soak(server: Any, soak: DisaggSoakConfig, *,
                    reference_factory: Any = None) -> dict:
    """Drive the seeded workload through a real DisaggServer while the
    scheduled chaos fires, then audit: ZERO accepted loss (every submitted
    request completes) and bit-identity of every completed request against
    a fault-free colocated reference built by ``reference_factory()``.

    Returns the artifact dict; raises nothing on identity mismatch — the
    caller gates on ``artifact["token_identity"]["ok"]``."""
    from ..codecs.faults import FaultConfig as _FaultConfig

    n = soak.n_requests
    kill_sched = sorted(
        ((int(n * frac), target) for frac, target in soak.kills),
        key=lambda kv: kv[0])
    burst_on = (int(n * soak.burst_start_frac)
                if soak.burst_bitflip_rate > 0
                and soak.burst_end_frac > soak.burst_start_frac else None)
    burst_off = int(n * soak.burst_end_frac) if burst_on is not None else None
    saved_faults = server.link.faults
    kill_events: list = []
    armed_midmig = {"want": 0}

    def page_hook(wid: int, sid: int, page: int) -> None:
        # a pending "prefill" kill fires on the worker that JUST moved a
        # page: it dies mid-ITS-migration, between page transfers
        if armed_midmig["want"] > 0 and server.workers[wid].alive:
            armed_midmig["want"] -= 1
            server.kill_prefill_worker(wid)
            kill_events.append({"target": f"prefill:{wid}",
                                "mid_migration": True, "at_index": None})

    server.page_hook = page_hook

    def fire_events(i: int) -> None:
        while kill_sched and kill_sched[0][0] <= i:
            _, target = kill_sched.pop(0)
            if target == "prefill":
                armed_midmig["want"] += 1
            elif target.startswith("prefill:"):
                wid = int(target.split(":", 1)[1])  # graphlint: disable=EG005
                server.kill_prefill_worker(wid)
                kill_events.append({"target": target,
                                    "mid_migration": False, "at_index": i})
            elif target == "decode":
                server.kill_decode_worker()
                kill_events.append({"target": "decode",
                                    "mid_migration": False, "at_index": i})
            else:  # "link"
                server.fail_link()
                kill_events.append({"target": "link",
                                    "mid_migration": False, "at_index": i})
        if burst_on is not None and i == burst_on:
            server.link.faults = _FaultConfig(
                bitflip_rate=soak.burst_bitflip_rate, seed=soak.seed + 17)
        if burst_off is not None and i == burst_off:
            server.link.faults = saved_faults

    sids = []
    for i in range(n):
        fire_events(i)
        prompt, mnt, temp, seed = _disagg_request(soak, i)
        sids.append(server.submit(prompt, mnt, temperature=temp,
                                  rng_seed=seed))
        for _ in range(soak.steps_per_arrival):
            server.step()
    if burst_off is not None and server.link.faults is not saved_faults:
        server.link.faults = saved_faults  # window past the last arrival
    server.run()
    server.page_hook = None

    completed = sum(1 for s in sids if s in server.results)
    checked = matched = 0
    mismatched: list = []
    if soak.verify_identity and reference_factory is not None:
        ref = reference_factory()
        ref_ids = []
        for i in range(n):
            prompt, mnt, temp, seed = _disagg_request(soak, i)
            ref_ids.append(ref.submit(prompt, mnt, temperature=temp,
                                      rng_seed=seed))
        ref_res = ref.run()
        for i, (s, r) in enumerate(zip(sids, ref_ids)):
            if s not in server.results:
                continue
            checked += 1
            if np.array_equal(server.results[s], ref_res[r]):
                matched += 1
            elif len(mismatched) < 32:
                mismatched.append(i)

    rep = server.report()
    return {
        "soak": dataclasses.asdict(soak),
        "requests": n,
        "completed": completed,
        "accepted_lost": n - completed,
        "kills": kill_events,
        "burst": (None if burst_on is None else
                  {"start_index": burst_on, "end_index": burst_off,
                   "bitflip_rate": soak.burst_bitflip_rate}),
        "token_identity": {"checked": checked, "matched": matched,
                           "ok": checked == matched,
                           "mismatched_indices": mismatched},
        "disagg": rep["disagg"],
        "report": rep,
    }
