"""Deterministic chaos soak for the serving front.

An open-loop workload (seeded Poisson arrivals on a virtual clock) pushed
through a :class:`~edgellm_tpu.serve.frontend.ServeFront` while scheduled
chaos fires mid-run — a whole-stage kill, a link-corruption burst — and a
verifiable artifact comes out the other side: goodput, SLO attainment,
reject/shed rates, p99 TTFT, post-kill recovery time, retry-budget
accounting, and a bit-identity audit of every ``completed`` request against
a fault-free reference.

Determinism is the whole point — a chaos run that cannot be replayed
cannot be debugged:

- Time is a :class:`~edgellm_tpu.utils.clock.FakeClock`. Arrivals,
  deadlines, breaker timeouts, and brownout dwells all live on the virtual
  timeline; after each served request the clock advances by that request's
  *measured* service wall time, so the virtual timeline is load-consistent
  without a single real ``sleep``.
- The workload is a seeded ``numpy`` RNG: interarrival gaps, prompts, and
  priorities all replay from ``SoakConfig.seed``.
- Chaos is scheduled by arrival index, not wall time: the kill fires just
  before request ``floor(n * kill_at_frac)`` is submitted, the corruption
  burst spans the ``[burst_start_frac, burst_end_frac)`` arrival window
  (schedule the burst before the kill — after a stage-loss replan the
  pre-kill burst runtime no longer matches the topology, so the restore is
  skipped).
- Fault injection itself is the seeded in-graph machinery of
  ``codecs.faults`` — the same virtual run replays the same corrupted hops.

The identity audit holds ``completed`` to its contract: for each completed
request, the same seed/prompt/shape replays on a *fault-free* runtime of
the same plan (same cuts, same codecs, same mesh — captured when the plan
first served), and the tokens must match bit-for-bit. Verified transport
is only worth building if the service above it cannot quietly serve
garbage with a green status.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np
import jax

from ..obs.flight import get_flight_recorder
from ..utils.clock import FakeClock
from .decode import generate, generate_split
from .frontend import Request, ServeFront
from .overload import COMPLETED, FAILED_OVER, REJECTED, SHED

__all__ = ["SoakConfig", "run_soak"]


@dataclasses.dataclass(frozen=True)
class SoakConfig:
    """The replayable soak definition. ``arrival_rate`` is requests per
    virtual second; ``deadline_s`` applies to every request (None =
    best-effort); ``priority_levels`` spreads requests uniformly over
    priorities ``0..levels-1``. Chaos: ``kill_stage``/``kill_at_frac``
    schedule the stage kill, the burst window is actuated by the
    ``burst_runtime`` argument of :func:`run_soak`. ``verify_identity``
    re-runs every completed request on a clean reference (the expensive
    half of the soak — turn it off for pure throughput runs)."""

    n_requests: int = 32
    arrival_rate: float = 2.0
    seed: int = 0
    prompt_len: int = 8
    #: first N prompt tokens identical across every request (a seeded
    #: "system prompt") — the workload shape a prefix-enabled batcher turns
    #: into mapped pages instead of prefill compute; 0 = fully random
    shared_prefix_len: int = 0
    max_new_tokens: int = 8
    deadline_s: Optional[float] = 60.0
    temperature: float = 0.7
    priority_levels: int = 2
    kill_stage: Optional[int] = None
    kill_at_frac: float = 0.5
    burst_start_frac: float = 0.15
    burst_end_frac: float = 0.35
    verify_identity: bool = True

    def __post_init__(self):
        if self.n_requests < 1:
            raise ValueError("n_requests must be >= 1")
        if self.arrival_rate <= 0:
            raise ValueError("arrival_rate must be > 0")
        for f in ("kill_at_frac", "burst_start_frac", "burst_end_frac"):
            v = getattr(self, f)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{f} must be in [0, 1], got {v!r}")
        if self.burst_end_frac < self.burst_start_frac:
            raise ValueError("burst_end_frac must be >= burst_start_frac")
        if self.priority_levels < 1:
            raise ValueError("priority_levels must be >= 1")
        if not 0 <= self.shared_prefix_len <= self.prompt_len:
            raise ValueError(
                f"shared_prefix_len must be in [0, prompt_len="
                f"{self.prompt_len}], got {self.shared_prefix_len}")


def _plan_key(plan: Optional[dict]) -> tuple:
    if plan is None or plan.get("mode") != "split":
        return ("local",)
    return ("split", tuple(plan["cuts"]), tuple(plan["hop_codecs"]))


def _verify_completed(front: ServeFront, records: list, submitted: dict,
                      plan_meshes: dict) -> dict:
    """Replay every completed request on a clean same-plan runtime and
    compare tokens bit-for-bit. ``submitted`` maps request id to the exact
    (prompt, temperature) the soak submitted; ``plan_meshes`` maps split
    plan keys to the (SplitConfig, Mesh) that served them."""
    from ..parallel.split import SplitConfig, SplitRuntime

    ref_runners: dict = {}
    checked = matched = 0
    mismatched_ids = []
    for r in records:
        if r.outcome != COMPLETED or r.tokens is None:
            continue
        if r.request_id not in submitted:
            continue
        prompt, temperature = submitted[r.request_id]
        key = _plan_key(r.plan)
        if key not in ref_runners:
            if key[0] == "local":
                ref_runners[key] = None
            else:
                split, mesh = plan_meshes[key]
                clean = SplitRuntime(front.model_cfg,
                                     SplitConfig(cuts=split.cuts,
                                                 hop_codecs=split.hop_codecs),
                                     mesh)
                ref_runners[key] = (clean, clean.place_params(front.params))
        runner = ref_runners[key]
        rng = jax.random.key(0)  # the soak submits every request with seed 0
        if runner is None:
            ref = generate(front.model_cfg, front.params, prompt,
                           r.granted_tokens, capacity=r.capacity,
                           temperature=temperature, rng_key=rng,
                           compute_dtype=front.compute_dtype)
        else:
            clean, placed = runner
            # the replay must run the same decode algorithm the front did:
            # a speculative front samples through residual resampling, whose
            # stream matches vanilla sampling only at temperature 0 (spec-vs-
            # vanilla parity is pinned separately, in tests/test_speculative).
            # The capacity bump mirrors ServeFront._run — the record keeps the
            # pre-bump bucketed value.
            spec = getattr(front, "speculative", None)
            spec_kw: dict = {}
            cap = r.capacity
            if getattr(spec, "enabled", False):
                spec_kw = {"speculative": spec, "raw_params": front.params}
                cap = max(cap, prompt.shape[1] + r.granted_tokens
                          + spec.k - 2)
            ref = generate_split(clean, placed, prompt, r.granted_tokens,
                                 capacity=cap,
                                 temperature=temperature, rng_key=rng,
                                 fault_step=r.request_id, **spec_kw)
        checked += 1
        if np.array_equal(np.asarray(ref), r.tokens):
            matched += 1
        else:
            mismatched_ids.append(r.request_id)
    return {"checked": checked, "matched": matched,
            "ok": checked == matched, "mismatched_ids": mismatched_ids}


def run_soak(front: ServeFront, soak: SoakConfig, *, clock: FakeClock,
             burst_runtime: Any = None) -> dict:
    """Run one deterministic soak; returns the artifact dict.

    ``front`` must be freshly built on ``clock`` (the soak owns the virtual
    timeline, and the artifact's rates assume the front's records are this
    soak's records). ``burst_runtime``, when given, is a same-topology split
    runtime with burst-level corruption: it is swapped in over the burst
    arrival window (breaker state preserved) and the original runtime is
    restored afterwards — unless a stage-loss replan happened in between,
    in which case the replanned runtime stands."""
    if not isinstance(clock, FakeClock):
        raise TypeError("run_soak needs the front's FakeClock — the soak "
                        "owns the virtual timeline")
    rng = np.random.default_rng(soak.seed)
    n = soak.n_requests
    arrive_t = clock.now + np.cumsum(
        rng.exponential(1.0 / soak.arrival_rate, n))
    vocab = front.model_cfg.vocab_size
    prompts = rng.integers(0, vocab, (n, soak.prompt_len), dtype=np.int32)
    if soak.shared_prefix_len:
        # same seeded block opens every prompt (drawn AFTER the matrix so a
        # shared_prefix_len of 0 replays byte-identical historical soaks)
        prompts[:, :soak.shared_prefix_len] = rng.integers(
            0, vocab, soak.shared_prefix_len, dtype=np.int32)
    priorities = rng.integers(0, soak.priority_levels, n)

    kill_idx = (int(n * soak.kill_at_frac)
                if soak.kill_stage is not None else None)
    burst_on_idx = (int(n * soak.burst_start_frac)
                    if burst_runtime is not None else None)
    burst_off_idx = (int(n * soak.burst_end_frac)
                     if burst_runtime is not None else None)
    normal_rt = front.split_runtime
    failovers_at_burst_on = 0
    kill_at_s: Optional[float] = None
    burst_window_s: list = []

    submitted: dict = {}       # request id -> (prompt (1, S), temperature)
    plan_meshes: dict = {}     # split plan key -> (SplitConfig, Mesh)
    records: list = []
    start_s = clock.now

    def fire_events(i: int) -> None:
        nonlocal kill_at_s, failovers_at_burst_on
        if burst_on_idx is not None and i == burst_on_idx:
            failovers_at_burst_on = front.failovers
            burst_window_s.append(clock.now)
            front.set_split_runtime(burst_runtime, keep_breakers=True)
        if burst_off_idx is not None and i == burst_off_idx:
            burst_window_s.append(clock.now)
            if front.failovers == failovers_at_burst_on:
                front.set_split_runtime(normal_rt, keep_breakers=True)
        if kill_idx is not None and i == kill_idx:
            kill_at_s = clock.now
            if front.split_runtime is not None:
                front.split_runtime.mark_stage_lost(soak.kill_stage)

    i = 0
    while i < n or front.queue_depth:
        if front.queue_depth == 0 and i < n and clock.now < arrive_t[i]:
            # host numpy scalar, not a device sync
            clock.set_time(float(arrive_t[i]))  # graphlint: disable=EG005
        while i < n and arrive_t[i] <= clock.now:
            fire_events(i)
            rid = front.submit(Request(
                prompt_ids=prompts[i], max_new_tokens=soak.max_new_tokens,
                priority=int(priorities[i]),  # graphlint: disable=EG005
                deadline_s=soak.deadline_s,
                temperature=soak.temperature, rng_seed=0))
            submitted[rid] = (prompts[i][None, :], soak.temperature)
            i += 1
        for rec in front.drain(max_requests=1):
            records.append(rec)
            if rec.service_s is not None:
                clock.advance(rec.service_s)
            if rec.plan is not None and rec.plan.get("mode") == "split":
                key = _plan_key(rec.plan)
                if key not in plan_meshes:
                    rt = front.split_runtime
                    plan_meshes[key] = (rt.split, rt.mesh)
    span_s = max(clock.now - start_s, 1e-9)

    # recovery time: kill -> first request finishing cleanly afterwards
    recovery_s = None
    if kill_at_s is not None:
        done_after = [r.finished_at for r in records
                      if r.outcome in (COMPLETED, FAILED_OVER)
                      and r.finished_at is not None
                      and r.finished_at > kill_at_s]
        if done_after:
            recovery_s = min(done_after) - kill_at_s

    report = front.report()
    outcomes = report["outcomes"]
    identity = (_verify_completed(front, records, submitted, plan_meshes)
                if soak.verify_identity else None)

    budget = report["retry_budget"]
    max_call = max((r.retries_charged for r in records), default=0)
    budget_bound = (budget["capacity"]
                    + budget["refill_per_s"] * span_s + max_call)
    fl = get_flight_recorder()
    return {
        "soak": dataclasses.asdict(soak),
        "virtual_span_s": span_s,
        "requests": n,
        "outcomes": outcomes,
        "goodput_tokens_per_s": report["tokens_out"] / span_s,
        "slo_attainment": report["slo_attainment"],
        "reject_rate": outcomes.get(REJECTED, 0) / n,
        "shed_rate": outcomes.get(SHED, 0) / n,
        "p99_ttft_s": (report["ttft_s"] or {}).get("p99"),
        "p99_latency_s": (report["latency_s"] or {}).get("p99"),
        "kill": (None if kill_at_s is None else
                 {"stage": soak.kill_stage, "at_s": kill_at_s,
                  "recovery_s": recovery_s}),
        "burst": (None if not burst_window_s else
                  {"start_s": burst_window_s[0],
                   "end_s": (burst_window_s[1]
                             if len(burst_window_s) > 1 else None)}),
        "retry_budget": {**budget, "max_single_call": max_call,
                         "within_budget": budget["spent"] <= budget_bound},
        "token_identity": identity,
        # post-mortems captured during the soak (exactly one per injected
        # failure instance), or None when no flight recorder is armed
        "flight_dumps": (list(fl.dumps()) if fl is not None else None),
        "report": report,
    }
