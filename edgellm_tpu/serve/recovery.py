"""Survivable decode: checkpointed generation state, failover, watchdogs.

PR 2 made the split-boundary *link* survivable; this module makes the
*generation* survivable when a whole stage/device dies or the host hangs:

- :class:`DecodeCheckpoint` — a versioned, atomic on-disk snapshot of
  everything an in-flight decode needs to resume **token-identically**: the
  per-stage KV caches (position offsets ride in ``cache/length``), the
  caller's RNG key (serialized via ``jax.random.key_data``), the sampled
  token prefix, and the PR-2 fault/tier counters.  The file format is
  magic + version + length + CRC32 over the payload, so a truncated or
  bit-flipped checkpoint fails with a typed :class:`CheckpointError`
  naming the problem — never a pytree unflatten traceback.  Writes reuse
  the ``.part``-then-rename pattern of ``hf_loader.fetch_with_retry``.
- :class:`StageFailure` / :class:`StageLostError` — whole-stage loss
  injection, distinct from PR 2's link faults: at a configured decode step
  the stage goes dark and every call into the runtime raises the typed
  error until the caller fails over (``serve.decode`` re-plans the split
  boundary onto the survivors and recomputes the lost KV cache from the
  generation prefix).
- :class:`Watchdog` — a host-side monotonic-clock deadline for decode/eval
  loops: on expiry it writes a best-effort checkpoint and raises
  :class:`DecodeTimeout` instead of hanging forever.  The clock is
  injectable so tests fire it deterministically.
- :class:`LocalRuntime` — a single-device runtime duck-typing
  ``SplitRuntime``'s decode surface (``place_params`` / ``prefill_decode``
  / ``decode_step``), the failover target when only one stage survives.

Nothing here imports ``edgellm_tpu.parallel`` — the split runtimes import
:class:`StageLostError` from here, and the serve loop imports the split
machinery lazily inside its failover path, so the layering stays acyclic.
"""
from __future__ import annotations

import dataclasses
import functools
import json
import os
import struct
import zlib
from typing import Any, Callable, Optional

import numpy as np
import jax

from ..models.paged_kv import KVTierMismatchError
from ..models.transformer import KVCache, decode_step, prefill
from ..obs.flight import flight_dump_for
from ..obs.tracing import span as obs_span
from ..utils.clock import MONOTONIC, Clock


# ---------------------------------------------------------------------------
# typed errors
# ---------------------------------------------------------------------------


class CheckpointError(RuntimeError):
    """A decode checkpoint could not be written or restored (missing file,
    bad magic, truncation, checksum mismatch, or a plan/model signature that
    does not match the resuming runtime)."""


class CheckpointTierMismatchError(KVTierMismatchError, CheckpointError):
    """A checkpoint's KV pages are at a different ``kv_codec`` tier than the
    restoring pool. One error type for both audiences: checkpoint callers
    (``except CheckpointError``) and the unified cross-tier refusal surface
    (``except KVTierMismatchError``) — restore never transcodes."""


class DecodeTimeout(TimeoutError):
    """The host-side watchdog deadline expired mid-loop. A best-effort
    checkpoint was written first when a checkpoint sink was available."""


class StageLostError(RuntimeError):
    """A pipeline stage is dark: every call into the runtime fails until the
    caller fails over to a re-planned runtime."""

    def __init__(self, stage: int):
        super().__init__(
            f"pipeline stage {stage} is dark (marked lost); fail over to a "
            f"re-planned runtime or restore from a checkpoint")
        self.stage = int(stage)


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class StageFailure:
    """Whole-stage loss injection: ``stage`` goes dark at decode step
    ``at_step`` (step 0 = the prefill; in the eval harness the step is the
    chunk index). Distinct from PR 2's link faults — no retry can recover a
    dead device; only failover can."""

    stage: int
    at_step: int

    def __post_init__(self):
        if self.stage < 0:
            raise ValueError(f"stage must be >= 0, got {self.stage}")
        if self.at_step < 0:
            raise ValueError(f"at_step must be >= 0, got {self.at_step}")


@dataclasses.dataclass(frozen=True)
class RecoveryConfig:
    """Everything the survivable decode loop needs, in one knob bundle.

    checkpoint_path: where :class:`DecodeCheckpoint` snapshots land (atomic
        ``.part`` + rename). Required for ``checkpoint_every`` /
        ``halt_at_step`` and for the watchdog's best-effort write.
    checkpoint_every: write a checkpoint every N decode steps (0 = only the
        watchdog's best-effort write and the ``halt_at_step`` hook).
    deadline_s: per-step/per-chunk watchdog deadline (None = no watchdog).
    stage_failure: a :class:`StageFailure` to inject (None = no injection).
    replan: allow the failover path to re-plan the split boundary onto the
        surviving stage(s); with False a lost stage is fatal (the typed
        :class:`StageLostError` propagates).
    max_failovers: hard cap on failovers per generation.
    halt_at_step: test/ops hook — write a checkpoint after decode step k and
        return the partial generation (simulates a kill at an arbitrary
        step without killing the process).
    clock: monotonic time source for the watchdog (a
        :class:`~edgellm_tpu.utils.clock.Clock`; injectable for tests).
    """

    checkpoint_path: Optional[str] = None
    checkpoint_every: int = 0
    deadline_s: Optional[float] = None
    stage_failure: Optional[StageFailure] = None
    replan: bool = True
    max_failovers: int = 1
    halt_at_step: Optional[int] = None
    clock: Clock = MONOTONIC

    def __post_init__(self):
        if self.checkpoint_every < 0:
            raise ValueError("checkpoint_every must be >= 0")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {self.deadline_s}")
        if self.max_failovers < 1:
            raise ValueError("max_failovers must be >= 1")
        if ((self.checkpoint_every or self.halt_at_step is not None)
                and not self.checkpoint_path):
            raise ValueError(
                "checkpoint_every/halt_at_step require checkpoint_path")


@dataclasses.dataclass
class RecoveryCounters:
    """Recovery bookkeeping, reported like PR 2's fault counters: per-call
    totals in the ``stats`` dict / eval result."""

    failovers: int = 0
    replans: int = 0
    recompute_tokens: int = 0
    resume_ok: int = 0
    checkpoints_written: int = 0
    watchdog_fires: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


class Watchdog:
    """Per-chunk deadline on a monotonic clock.

    ``check`` is called at loop boundaries: within the deadline it re-arms
    (pet-the-dog) and returns; past it, it writes a best-effort checkpoint
    through ``checkpoint_fn`` (errors swallowed — the timeout must surface
    even when the disk is also unhappy) and raises :class:`DecodeTimeout`.
    A host that never reaches ``check`` because a device call blocks forever
    is out of scope for a host-side timer; the deadline guards slow steps
    and inter-chunk hangs, which is where eval loops actually stall.
    """

    def __init__(self, deadline_s: float, clock: Clock = MONOTONIC):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self._clock = clock
        self._armed_at: Optional[float] = None

    def arm(self) -> None:
        self._armed_at = self._clock()

    def expired(self) -> bool:
        return (self._armed_at is not None
                and self._clock() - self._armed_at > self.deadline_s)

    def check(self, checkpoint_fn: Optional[Callable[[], None]] = None,
              what: str = "decode step") -> None:
        if self._armed_at is None:
            self.arm()
            return
        elapsed = self._clock() - self._armed_at
        if elapsed <= self.deadline_s:
            self.arm()
            return
        if checkpoint_fn is not None:
            try:
                checkpoint_fn()
            except Exception:  # noqa: BLE001 — best-effort by contract
                pass
        exc = DecodeTimeout(
            f"{what} exceeded the {self.deadline_s:g}s deadline "
            f"(elapsed {elapsed:.3f}s); a best-effort checkpoint was "
            f"attempted — resume from it instead of re-running")
        # post-mortem at the raise site: the recorder (when armed) captures
        # the span ring + counters exactly once per exception instance, no
        # matter how many catch sites also call dump_for
        flight_dump_for(exc, what=what, deadline_s=self.deadline_s,
                        elapsed_s=round(elapsed, 3))
        raise exc


# ---------------------------------------------------------------------------
# the checkpoint container + binary format
# ---------------------------------------------------------------------------

_MAGIC = b"EDGERECV"
_VERSION = 1
# magic(8) | u32 version | u64 payload_len | u32 crc32(payload)
_HEADER = struct.Struct("<8sIQI")


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:  # bfloat16 & friends live in ml_dtypes, which jax always ships
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError) as e:
        raise CheckpointError(f"checkpoint leaf has unknown dtype "
                              f"{name!r}") from e


class DecodeCheckpoint:
    """A flat ``{name: ndarray}`` dict plus a JSON-able ``meta`` dict, with a
    self-verifying binary serialization.

    Leaves are stored as raw bytes (``.tobytes()``) with their dtype string
    and shape — bit-exact round-trips for every dtype including bfloat16,
    with no pickle in the loop. The payload is framed by magic + version +
    length + CRC32, so restore never feeds a damaged file to the unflattener.

    Stream snapshots (``ContinuousBatcher.checkpoint_stream``) store the
    CONTIGUOUS KV prefix, never pages: a stream whose pages were
    prefix-shared gathers to the same bytes as an unshared one, and restore
    adopts the rows privately — sharing is re-established only by the
    destination pool's own radix index, never carried by the checkpoint.
    """

    def __init__(self, arrays: dict, meta: dict):
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}
        self.meta = dict(meta)

    def save(self, path: str) -> str:
        with obs_span("recovery.checkpoint_save", path=path) as sp:
            names = sorted(self.arrays)
            leaves = [{"name": n, "dtype": str(self.arrays[n].dtype),
                       "shape": list(self.arrays[n].shape)} for n in names]
            header = json.dumps({"meta": self.meta, "leaves": leaves},
                                sort_keys=True).encode()
            body = b"".join(np.ascontiguousarray(self.arrays[n]).tobytes()
                            for n in names)
            payload = struct.pack("<I", len(header)) + header + body
            blob = _HEADER.pack(_MAGIC, _VERSION, len(payload),
                                zlib.crc32(payload)) + payload
            if sp is not None:
                sp.args["bytes"] = len(blob)
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = path + ".part"  # atomic, as in hf_loader.fetch_with_retry
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "DecodeCheckpoint":
        with obs_span("recovery.checkpoint_load", path=path):
            try:
                return cls._load_impl(path)
            except CheckpointError as e:
                # a refused restore is a post-mortem moment: snapshot the
                # ring before the caller unwinds (once per instance)
                flight_dump_for(e, path=path)
                raise

    @classmethod
    def _load_impl(cls, path: str) -> "DecodeCheckpoint":
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise CheckpointError(f"cannot read checkpoint {path}: {e}") from e
        if len(blob) < _HEADER.size:
            raise CheckpointError(
                f"checkpoint {path} is truncated ({len(blob)} bytes < "
                f"{_HEADER.size}-byte header)")
        magic, version, length, crc = _HEADER.unpack_from(blob)
        if magic != _MAGIC:
            raise CheckpointError(
                f"{path} is not a decode checkpoint (bad magic {magic!r})")
        if version > _VERSION:
            raise CheckpointError(
                f"checkpoint {path} has version {version}, this build reads "
                f"<= {_VERSION}; upgrade before resuming")
        payload = blob[_HEADER.size:]
        if len(payload) != length:
            raise CheckpointError(
                f"checkpoint {path} is truncated: header promises {length} "
                f"payload bytes, file has {len(payload)}")
        if zlib.crc32(payload) != crc:
            raise CheckpointError(
                f"checkpoint {path} is corrupted (CRC32 mismatch); restore "
                f"refused — delete it and resume from an older snapshot")
        try:
            (hlen,) = struct.unpack_from("<I", payload)
            header = json.loads(payload[4:4 + hlen].decode())
            meta, leaves = header["meta"], header["leaves"]
        except (struct.error, ValueError, KeyError, UnicodeDecodeError) as e:
            raise CheckpointError(
                f"checkpoint {path} has an unreadable header: {e}") from e
        arrays, off = {}, 4 + hlen
        for leaf in leaves:
            dt = _np_dtype(leaf["dtype"])
            shape = tuple(leaf["shape"])
            n = dt.itemsize * int(np.prod(shape, dtype=np.int64)) if shape \
                else dt.itemsize
            raw = payload[off:off + n]
            if len(raw) != n:
                raise CheckpointError(
                    f"checkpoint {path} leaf {leaf['name']!r} is short "
                    f"({len(raw)} of {n} bytes)")
            arrays[leaf["name"]] = np.frombuffer(raw, dt).reshape(shape).copy()
            off += n
        return cls(arrays, meta)


def runtime_plan_meta(rt: Any) -> dict:
    """The plan/model signature a checkpoint records and resume validates:
    enough to refuse resuming split state onto a different cut layout or a
    different model. Duck-typed — any runtime with ``cfg`` (and, for split
    runtimes, ``split``/``codecs``) works."""
    cfg = rt.cfg
    meta = {
        "mode": "split" if hasattr(rt, "split") else "local",
        "model": {"family": cfg.family, "num_layers": cfg.num_layers,
                  "hidden_size": cfg.hidden_size, "num_heads": cfg.num_heads,
                  "vocab_size": cfg.vocab_size},
    }
    if hasattr(rt, "split"):
        meta["cuts"] = [int(c) for c in rt.split.cuts]
        meta["hop_codecs"] = [c.name for c in rt.codecs]
        # µ-batch pipelining changes no tokens, but a resumed runtime with a
        # different schedule would re-trace decode executables mid-stream
        # and, under faults, draw per-µ-batch fault keys differently — so
        # the schedule is part of the plan signature (1 == sequential)
        pipe = getattr(rt, "pipeline", None)
        meta["num_microbatches"] = int(pipe.num_microbatches) if pipe else 1
    return meta


# ---------------------------------------------------------------------------
# single-device fallback runtime
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("cfg", "capacity",
                                             "compute_dtype"))
def _local_prefill(cfg, params, input_ids, capacity, compute_dtype):
    return prefill(cfg, params, input_ids, capacity,
                   compute_dtype=compute_dtype)


@functools.partial(jax.jit, static_argnames=("cfg", "compute_dtype"),
                   donate_argnames=("cache",))
def _local_step(cfg, params, cache, token_ids, compute_dtype):
    # cache donated: the failover runtime updates its KV buffers in place,
    # same as the split step executable (graph contract "decode.step")
    return decode_step(cfg, params, cache, token_ids,
                       compute_dtype=compute_dtype)


class LocalRuntime:
    """Single-device decode runtime with ``SplitRuntime``'s decode surface.

    The failover target when only one stage survives (no cut is left to
    plan), and the recovery-enabled path for unsplit ``generate``: the cache
    is the same ``{"k", "v", "length"}`` dict the split runtime uses, so the
    checkpoint layer and the serve loop treat both identically. No hops, no
    codecs, no counters — ``link_counters`` reports None like a fault-free
    split runtime."""

    def __init__(self, cfg, compute_dtype=None):
        self.cfg = cfg
        self.compute_dtype = compute_dtype
        self.codecs: list = []
        self.faults = None

    def place_params(self, params: dict) -> dict:
        return params  # single device: nothing to shard

    def prefill_decode(self, params: dict, input_ids: jnp.ndarray,
                       capacity: int, fault_step: int = 0) -> tuple:
        logits, kv = _local_prefill(self.cfg, params, input_ids,
                                    int(capacity), self.compute_dtype)
        return logits, {"k": kv.k, "v": kv.v, "length": kv.length}

    def decode_step(self, params: dict, cache: dict,
                    token_ids: jnp.ndarray) -> tuple:
        logits, kv = _local_step(
            self.cfg, params,
            KVCache(cache["k"], cache["v"], cache["length"]), token_ids,
            self.compute_dtype)
        return logits, {"k": kv.k, "v": kv.v, "length": kv.length}

    def mark_stage_lost(self, stage: int) -> None:
        raise ValueError(
            "LocalRuntime runs on a single device — there is no pipeline "
            "stage to lose; stage_failure injection needs a split runtime")

    def link_counters(self, reset: bool = False) -> Optional[dict]:
        return None

    def decode_hop_bytes(self, batch: int) -> list:
        return []  # nothing crosses a wire
