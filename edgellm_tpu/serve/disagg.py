"""Disaggregated prefill/decode serving with fault-hardened KV-page
migration — ROADMAP item 1.

The colocated :class:`~edgellm_tpu.serve.batching.ContinuousBatcher` runs
prefill and decode on the same pool, so one long prompt stalls every decode
step behind it. This module splits the service the way production fleets do:

- :class:`PrefillWorker` — a dedicated worker owning a private staging
  batcher. ``ContinuousBatcher.prefill_hold`` runs the EXACT colocated
  fresh-admit prefill (same executable, token 0 sampled with the same
  ``fold_in(key, 0)``), then pins the slot with a migration hold instead of
  decoding.
- :class:`MigrationLink` — the boundary-hop ladder applied to KV pages: each
  page's at-rest bytes (packed codes + scales on quantized tiers) are sealed
  by :func:`~edgellm_tpu.codecs.wire_format.seal_payload`, optionally FEC
  parity-framed, corrupted by the seeded fault injector, then walked through
  detect (canary + checksum) → repair (in-band XOR parity) → retry → hedge.
  A page that never verifies raises :class:`MigrationError` — corrupt bytes
  are NEVER adopted. Wire bytes are contract-checked per transfer against
  :func:`migration_wire_nbytes`.
- :class:`DisaggServer` — the front: prompts queue for prefill workers, each
  finished prefill migrates page-by-page into a bounded handoff queue, and
  decode admission PULLS from that queue — the adopt is the batcher's resume
  byte move (``adopt_packed`` / ``adopt_paged_rows_packed``), never a
  requantize, so disagg output is token-identical to colocated serving by
  construction (the handoff happens at t == 1, before any decode step).

Failure matrix (every leg keeps accepted requests alive):

- **Prefill worker dies mid-migration** — remaining pages re-drive from the
  server-held prefill checkpoint (``prefill_checkpoint=True``, zero
  recompute), or the prompt re-prefills from scratch on another worker,
  counted in ``recompute_tokens``.
- **Corrupted page transfer** — healed in band by FEC, or re-sent up to
  ``max_retries`` times (hedged when configured); exhaustion falls the one
  request back to colocated prefill (identical tokens) and counts toward the
  degrade threshold.
- **Decode worker dies** — running streams re-admit via the existing
  :class:`~edgellm_tpu.serve.recovery.DecodeCheckpoint` path
  (token-identical restore); admitted-but-unstepped handoffs re-inject from
  the server-held handoff record.
- **Dead or saturated link** — the front degrades gracefully to colocated
  serving with a typed reason (``degrade_reason``), surfaced through
  ``report()`` and the cluster router.
"""
from __future__ import annotations

import dataclasses
import os
from collections import deque
from typing import Any, Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..codecs.faults import FaultConfig, inject_faults
from ..codecs.fec import FECConfig, HedgeConfig, fec_decode, fec_encode
from ..codecs.wire_format import seal_payload, tree_nbytes, verify_payload
from ..obs.flight import flight_dump_for
from ..obs.metrics import get_registry
from ..obs.tracing import span as obs_span
from ..utils.clock import MONOTONIC, Clock
from .batching import BatchingConfig, ContinuousBatcher
from .overload import _linear_quantile


# ---------------------------------------------------------------------------
# typed errors
# ---------------------------------------------------------------------------


class DisaggError(RuntimeError):
    """Base type for disaggregated-serving failures."""


class MigrationError(DisaggError):
    """A KV-page transfer could not be delivered intact: the link is down,
    the wire-byte contract was violated, or every attempt (retries x hedge
    routes) failed integrity. The corrupt bytes were NOT adopted."""


class PrefillWorkerLost(DisaggError):
    """A prefill worker died; its staging pool is unreachable. In-flight
    handoffs re-drive from the prefill checkpoint or re-prefill."""


#: typed degrade reasons (`DisaggServer.degrade_reason` is always one of
#: these or None)
DEGRADE_LINK_DEAD = "migration_link_dead"
DEGRADE_LINK_SLOW = "migration_link_slow"
DEGRADE_MIGRATION_FAILURES = "migration_failures"
DEGRADE_WORKERS_LOST = "prefill_workers_lost"
DEGRADE_REASONS = (DEGRADE_LINK_DEAD, DEGRADE_LINK_SLOW,
                   DEGRADE_MIGRATION_FAILURES, DEGRADE_WORKERS_LOST)


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DisaggConfig:
    """Knobs for the disaggregated front.

    ``num_prefill_workers`` dedicated workers each hold ``prefill_batch``
    staging slots; finished prefills wait in a handoff queue bounded at
    ``queue_bound`` (full queue back-pressures the prefill pump — decode
    admission pulls). The migration ladder re-sends a failed page up to
    ``max_retries`` times (``hedge.routes`` staggered copies per attempt
    when hedging); ``degrade_after`` consecutive migration-fatal failures
    degrade the whole front to colocated serving. ``prefill_checkpoint``
    keeps a server-held snapshot of every handoff so a worker death mid-
    migration re-drives instead of re-prefilling."""

    enabled: bool = True
    num_prefill_workers: int = 2
    prefill_batch: int = 2
    queue_bound: int = 8
    max_retries: int = 2
    degrade_after: int = 3
    prefill_checkpoint: bool = True
    fec: Optional[FECConfig] = None
    hedge: Optional[HedgeConfig] = None
    faults: Optional[FaultConfig] = None
    link_seed: int = 0
    # gray plane: a link that is merely SLOW. ``transfer_s_per_page`` models
    # per-page wire time on the injected clock (0 keeps transfers instant);
    # when ``slow_link_p95_multiple`` > 0 the server watches a rolling
    # window of transfer latencies and degrades to colocated serving with
    # the typed ``migration_link_slow`` reason once the windowed p95
    # reaches that multiple of the frozen healthy baseline median.
    transfer_s_per_page: float = 0.0
    slow_link_p95_multiple: float = 0.0
    slow_link_min_samples: int = 8
    slow_link_window_s: float = 60.0

    def __post_init__(self):
        if not isinstance(self.enabled, bool):
            raise ValueError(f"enabled must be a boolean, got {self.enabled!r}")
        if not isinstance(self.prefill_checkpoint, bool):
            raise ValueError(f"prefill_checkpoint must be a boolean, got "
                             f"{self.prefill_checkpoint!r}")
        for f, lo in (("num_prefill_workers", 1), ("prefill_batch", 1),
                      ("queue_bound", 1), ("max_retries", 0),
                      ("degrade_after", 1)):
            v = getattr(self, f)
            if isinstance(v, bool) or not isinstance(v, int) or v < lo:
                raise ValueError(f"{f} must be an integer >= {lo}, got {v!r}")
        if isinstance(self.transfer_s_per_page, bool) or not isinstance(
                self.transfer_s_per_page, (int, float)) \
                or self.transfer_s_per_page < 0:
            raise ValueError(f"transfer_s_per_page must be a number >= 0, "
                             f"got {self.transfer_s_per_page!r}")
        if isinstance(self.slow_link_p95_multiple, bool) or not isinstance(
                self.slow_link_p95_multiple, (int, float)) \
                or (self.slow_link_p95_multiple != 0
                    and self.slow_link_p95_multiple <= 1.0):
            raise ValueError(f"slow_link_p95_multiple must be 0 (off) or "
                             f"> 1, got {self.slow_link_p95_multiple!r}")
        if isinstance(self.slow_link_min_samples, bool) or not isinstance(
                self.slow_link_min_samples, int) \
                or self.slow_link_min_samples < 2:
            raise ValueError(f"slow_link_min_samples must be an integer "
                             f">= 2, got {self.slow_link_min_samples!r}")
        if isinstance(self.slow_link_window_s, bool) or not isinstance(
                self.slow_link_window_s, (int, float)) \
                or self.slow_link_window_s <= 0:
            raise ValueError(f"slow_link_window_s must be a number > 0, "
                             f"got {self.slow_link_window_s!r}")
        if isinstance(self.link_seed, bool) or not isinstance(
                self.link_seed, int):
            raise ValueError(f"link_seed must be an integer, "
                             f"got {self.link_seed!r}")
        for f, t in (("fec", FECConfig), ("hedge", HedgeConfig),
                     ("faults", FaultConfig)):
            v = getattr(self, f)
            if v is not None and not isinstance(v, t):
                raise ValueError(f"{f} must be a {t.__name__} or None, "
                                 f"got {type(v).__name__}")


def migration_wire_nbytes(payload_nbytes: int,
                          fec: Optional[FECConfig]) -> int:
    """Static wire bytes of one migrated page chunk: the payload plus the
    8-byte integrity sidecar, FEC-framed when parity is on. The link checks
    every built wire tree against this — the runtime half of the
    ``disagg.migration-wire-bytes`` contract."""
    sealed = int(payload_nbytes) + 8
    if fec is not None and fec.enabled:
        return fec.wire_nbytes(sealed)
    return sealed


# ---------------------------------------------------------------------------
# the migration link: detect -> repair -> retry -> hedge, per page
# ---------------------------------------------------------------------------


class MigrationLink:
    """Host-driven page transport over the boundary-hop primitives.

    Each :meth:`send` seals one page payload, frames it (FEC when
    configured), injects seeded faults, and walks the full resilience
    ladder. The ladder NEVER delivers unverified bytes: success returns the
    arrived payload (host numpy), exhaustion raises
    :class:`MigrationError`. Counters mirror the FaultyLink vocabulary
    (pages, transmissions, wire_bytes, detected, repaired, retried,
    hedge_wins, failed)."""

    def __init__(self, *, fec: Optional[FECConfig] = None,
                 hedge: Optional[HedgeConfig] = None,
                 faults: Optional[FaultConfig] = None,
                 max_retries: int = 2, seed: int = 0,
                 clock: Clock = MONOTONIC, transfer_s: float = 0.0):
        self.fec = fec if (fec is not None and fec.enabled) else None
        self.hedge = hedge if (hedge is not None and hedge.enabled) else None
        self.faults = faults
        self.max_retries = int(max_retries)
        self.clock = clock
        #: modeled per-send wire time, burned on the virtual clock when the
        #: injected clock supports ``advance`` (a FakeClock) — the slow-link
        #: chaos knob inflates it via :meth:`set_transfer_multiplier`
        self.transfer_s = float(transfer_s)
        self._transfer_mult = 1.0
        self.alive = True
        self.counters = {"pages": 0, "transmissions": 0, "wire_bytes": 0,
                         "detected": 0, "repaired": 0, "retried": 0,
                         "hedge_wins": 0, "failed": 0}
        self._key = jax.random.key(seed)
        self._sends = 0
        #: test hook: XOR one byte of this FEC chunk on the next
        #: transmission, then clear — the single-corrupt-chunk heal case
        self.corrupt_chunk_once: Optional[int] = None

    def fail(self) -> None:
        """Chaos switch: every later :meth:`send` raises immediately."""
        self.alive = False

    def set_transfer_multiplier(self, mult: float) -> None:
        """Gray-failure chaos switch: inflate every later send's modeled
        wire time by this factor — the link stays up and delivers verified
        bytes, it is merely slow."""
        if mult <= 0:
            raise ValueError(f"transfer multiplier must be > 0, got {mult!r}")
        self._transfer_mult = float(mult)

    def _burn_transfer_time(self) -> None:
        if self.transfer_s <= 0.0:
            return
        advance = getattr(self.clock, "advance", None)
        if advance is not None:
            advance(self.transfer_s * self._transfer_mult)

    def wire_nbytes(self, payload_nbytes: int) -> int:
        return migration_wire_nbytes(payload_nbytes, self.fec)

    def send(self, payload: dict, *, sid: int, page: int) -> dict:
        """One page chunk through the ladder. Returns the verified arrived
        payload as host numpy arrays; raises :class:`MigrationError` when
        the link is down or every attempt fails integrity."""
        if not self.alive:
            raise MigrationError(
                f"migration link is down (sid={sid} page={page})")
        self._burn_transfer_time()
        dev = jax.tree_util.tree_map(jnp.asarray, payload)
        sealed = seal_payload(dev)
        declared = migration_wire_nbytes(tree_nbytes(dev), self.fec)
        send_key = jax.random.fold_in(self._key, self._sends)
        self._sends += 1
        routes = self.hedge.routes if self.hedge is not None else 1
        for attempt in range(self.max_retries + 1):
            if attempt:
                self.counters["retried"] += 1
            for route in range(routes):
                wire = (fec_encode(sealed, self.fec)
                        if self.fec is not None else sealed)
                measured = tree_nbytes(wire)
                if measured != declared:
                    self.counters["failed"] += 1
                    raise MigrationError(
                        f"migration wire-byte contract violated: built "
                        f"{measured} B, declared {declared} B "
                        f"(sid={sid} page={page})")
                key = jax.random.fold_in(
                    jax.random.fold_in(send_key, attempt), route)
                if self.faults is not None and self.faults.enabled:
                    wire = inject_faults(wire, key, self.faults)
                if (self.corrupt_chunk_once is not None
                        and self.fec is not None):
                    c, self.corrupt_chunk_once = self.corrupt_chunk_once, None
                    chunks = np.asarray(wire["chunks"]).copy()
                    chunks[c, 0] ^= 0xFF
                    wire = {"chunks": jnp.asarray(chunks),
                            "words": wire["words"]}
                self.counters["transmissions"] += 1
                self.counters["wire_bytes"] += measured
                get_registry().counter(
                    "edgellm_disagg_wire_bytes_total",
                    "bytes pushed over the migration link").inc(measured)
                if self.fec is not None:
                    arrived, bad, repaired = fec_decode(
                        wire, self.fec, sealed)
                    bad, repaired = bool(bad), bool(repaired)
                else:
                    arrived, bad, repaired = wire, False, False
                ok = bool(verify_payload(arrived))
                if bad or not ok:
                    self.counters["detected"] += 1
                if ok:
                    if repaired:
                        self.counters["repaired"] += 1
                    if route:
                        self.counters["hedge_wins"] += 1
                    self.counters["pages"] += 1
                    return jax.tree_util.tree_map(np.asarray, arrived["p"])
        self.counters["failed"] += 1
        hedged = f" x {routes} hedge routes" if routes > 1 else ""
        raise MigrationError(
            f"page transfer failed integrity after "
            f"{self.max_retries + 1} attempt(s){hedged} "
            f"(sid={sid} page={page}); corrupt bytes are never adopted")


# ---------------------------------------------------------------------------
# prefill workers
# ---------------------------------------------------------------------------


class PrefillWorker:
    """One dedicated prefill worker: a private staging
    :class:`ContinuousBatcher` (same page geometry, kv_codec, and compute
    dtypes as the decode batcher, so staged pool bytes equal colocated pool
    bytes by deterministic quantize-on-append) that admits prompts, samples
    token 0, and holds slots for page-by-page migration. ``kill`` simulates
    the worker dying: every later access raises
    :class:`PrefillWorkerLost`."""

    def __init__(self, wid: int, batcher: ContinuousBatcher):
        self.wid = wid
        self.bat = batcher
        self.alive = True
        self.prefills = 0

    def kill(self) -> None:
        self.alive = False

    def _check(self) -> None:
        if not self.alive:
            raise PrefillWorkerLost(
                f"prefill worker {self.wid} is dead; its staging pool is "
                f"unreachable")

    def prefill(self, prompt: np.ndarray, max_new_tokens: int,
                temperature: float, rng_seed: int):
        """Submit + admit one prompt. Returns ``(staging_sid, Stream)`` with
        the slot held for migration, or None when the staging pool has no
        capacity right now (caller retries next pump)."""
        self._check()
        with obs_span("disagg.prefill", wid=self.wid,
                      prompt_len=int(prompt.size)):
            sid = self.bat.submit(prompt, max_new_tokens,
                                  temperature=temperature, rng_seed=rng_seed)
            st = self.bat.prefill_hold(sid)
        if st is None:
            self.bat.discard(sid)
            return None
        self.prefills += 1
        return sid, st

    def snapshot(self, slot: int) -> dict:
        """The prefill checkpoint: the slot's full at-rest payload, held by
        the SERVER so a worker death mid-migration re-drives from it."""
        self._check()
        return self.bat._gather_state(slot)

    def gather_page(self, slot: int, start: int, stop: int) -> dict:
        """One page's rows from the held staging slot — raises
        :class:`PrefillWorkerLost` the moment the worker is dead, which is
        what makes a mid-migration kill land between pages."""
        self._check()
        return self.bat.gather_rows(slot, start, stop)

    def release(self, sid: int) -> None:
        """Retire a handoff (pages landed, or the handoff was abandoned).
        A dead worker's staging state is unreachable garbage — skip."""
        if self.alive:
            self.bat.release_handoff(sid)


# ---------------------------------------------------------------------------
# the handoff record
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Handoff:
    """One migrated prefill: everything decode admission needs, held
    server-side until the stream finishes (the decode-kill re-admission
    source)."""

    sid: int
    prompt: np.ndarray
    max_new_tokens: int
    temperature: float
    rng_seed: int
    tokens: list
    payload: Optional[dict]   # verified arrived resume payload (host numpy)
    wid: int = -1
    pages: int = 0
    redriven_pages: int = 0


# ---------------------------------------------------------------------------
# the disaggregated server
# ---------------------------------------------------------------------------


class DisaggServer:
    """Disaggregated front duck-typing the ``ContinuousBatcher`` surface
    (``submit/step/run/results/pop_result/discard/probe_prefix/report/
    bcfg/rt/pool``), so :class:`~edgellm_tpu.serve.frontend.ServeFront`'s
    ``drain_batched`` — deadline admission included — drives it unchanged.

    The request path: ``submit`` queues the prompt; the prefill pump hands
    it to a live worker, migrates the finished pages through the
    :class:`MigrationLink` into the bounded handoff queue; decode admission
    pulls a handoff when the decode pool can take it and injects it as a
    resume payload — a verified byte move. After degrade (typed reason),
    every prompt routes straight into the decode batcher: the colocated
    path, trivially token-identical."""

    def __init__(self, cfg, params, bcfg: BatchingConfig,
                 dcfg: DisaggConfig = DisaggConfig(), *,
                 split_runtime=None, placed_params=None,
                 clock: Clock = MONOTONIC):
        self.cfg, self.params = cfg, params
        self.bcfg, self.dcfg = bcfg, dcfg
        self.clock = clock
        self._rt_args = {"split_runtime": split_runtime,
                         "placed_params": placed_params}
        self.decode = ContinuousBatcher(cfg, params, bcfg, **self._rt_args)
        staging_bcfg = dataclasses.replace(
            bcfg, max_slots=dcfg.prefill_batch,
            num_pages=dcfg.prefill_batch * bcfg.pages_per_slot + 1,
            checkpoint_dir=None, step_deadline_s=None)
        self.workers = [
            PrefillWorker(i, ContinuousBatcher(cfg, params, staging_bcfg,
                                               **self._rt_args))
            for i in range(dcfg.num_prefill_workers)]
        self.link = MigrationLink(fec=dcfg.fec, hedge=dcfg.hedge,
                                  faults=dcfg.faults,
                                  max_retries=dcfg.max_retries,
                                  seed=dcfg.link_seed, clock=clock,
                                  transfer_s=dcfg.transfer_s_per_page)
        # slow-link detection state: a rolling (t, elapsed) window plus the
        # healthy baseline median frozen from the first min_samples sends
        self._xfer_window: deque = deque()
        self._xfer_baseline: Optional[float] = None
        # rows axis of every payload array: (L, n, ...) local, per-stage
        # (n_stages, sz, n, ...) split
        self._row_axis = 2 if self.decode.rt is not None else 1
        self.pending: deque = deque()       # our sids awaiting a worker
        self.queue: deque = deque()         # Handoffs awaiting decode pull
        self.handoffs: dict = {}            # our sid -> Handoff (to finish)
        self._reqs: dict = {}               # our sid -> (prompt, n, t, seed)
        self._by_decode: dict = {}          # decode sid -> our sid
        self._to_decode: dict = {}          # our sid -> decode sid
        self.results: dict = {}
        self.degraded = False
        self.degrade_reason: Optional[str] = None
        self._consecutive_failures = 0
        self._rr = 0
        self._next_sid = 0
        self.stats = {"submitted": 0, "migrations": 0, "migrated_pages": 0,
                      "redriven_pages": 0, "recompute_tokens": 0,
                      "colocated_fallbacks": 0, "readmitted": 0,
                      "prefills": 0}
        #: chaos hook: called ``(wid, sid, page_index)`` after each page
        #: lands — soak legs kill workers MID-migration through this
        self.page_hook: Optional[Callable[[int, int, int], None]] = None

    # -- batcher surface ---------------------------------------------------

    @property
    def rt(self):
        return self.decode.rt

    @property
    def pool(self):
        return self.decode.pool

    def probe_prefix(self, prompt_ids) -> int:
        return self.decode.probe_prefix(prompt_ids)

    def submit(self, prompt_ids, max_new_tokens: int, *,
               temperature: float = 0.0, rng_seed: int = 0) -> int:
        """Accept one request (same validation as the colocated batcher).
        Disagg sids are the server's own namespace — results come back
        keyed by them regardless of which decode stream served them."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if float(temperature) < 0.0:
            raise ValueError("temperature must be >= 0")
        need = prompt.size + max_new_tokens - 1
        if need > self.bcfg.span:
            raise ValueError(
                f"prompt {prompt.size} + {max_new_tokens} new tokens needs "
                f"{need} cache positions > slot span {self.bcfg.span}")
        sid = self._next_sid
        self._next_sid += 1
        self._reqs[sid] = (prompt, int(max_new_tokens), float(temperature),
                           int(rng_seed))
        self.stats["submitted"] += 1
        if self.degraded or not self.dcfg.enabled:
            self._submit_colocated(sid)
        else:
            self.pending.append(sid)
        return sid

    def pop_result(self, sid: int) -> np.ndarray:
        return self.results.pop(sid)

    def discard(self, sid: int) -> None:
        """Drop a request in any state (the orphan hatch, mirroring the
        batcher's)."""
        self._reqs.pop(sid, None)
        self.results.pop(sid, None)
        self.handoffs.pop(sid, None)
        try:
            self.pending.remove(sid)
        except ValueError:
            pass
        for i, h in enumerate(self.queue):
            if h.sid == sid:
                del self.queue[i]
                break
        dsid = self._to_decode.pop(sid, None)
        if dsid is not None:
            self._by_decode.pop(dsid, None)
            self.decode.discard(dsid)

    # -- internal plumbing -------------------------------------------------

    def _submit_colocated(self, sid: int) -> None:
        prompt, mnt, temp, seed = self._reqs[sid]
        dsid = self.decode.submit(prompt, mnt, temperature=temp,
                                  rng_seed=seed)
        self._by_decode[dsid] = sid
        self._to_decode[sid] = dsid

    def _degrade(self, reason: str) -> None:
        if self.degraded:
            return
        assert reason in DEGRADE_REASONS, reason
        self.degraded = True
        self.degrade_reason = reason
        with obs_span("disagg.degrade", reason=reason):
            pass
        get_registry().gauge(
            "edgellm_disagg_degraded",
            "1 after the front degraded to colocated serving").set(1.0)
        # nothing accepted is lost: queued handoffs still adopt (their
        # payloads are already verified), pending prompts re-route to the
        # colocated path
        while self.pending:
            sid = self.pending.popleft()
            self.stats["colocated_fallbacks"] += 1
            self._submit_colocated(sid)

    def _observe_transfer(self, elapsed_s: float) -> None:
        """Slow-link detection: freeze a healthy baseline median from the
        first ``slow_link_min_samples`` transfers, then degrade (typed
        ``migration_link_slow``) when the rolling window's p95 reaches
        ``slow_link_p95_multiple`` × that baseline. Symmetric with the
        dead-link path — the router demotes on the same ``degraded`` flag."""
        if self.dcfg.slow_link_p95_multiple == 0 or self.degraded:
            return
        now = self.clock()
        self._xfer_window.append((now, float(elapsed_s)))
        horizon = now - self.dcfg.slow_link_window_s
        while self._xfer_window and self._xfer_window[0][0] <= horizon:
            self._xfer_window.popleft()
        n = len(self._xfer_window)
        if n < self.dcfg.slow_link_min_samples:
            return
        ordered = sorted(v for _, v in self._xfer_window)
        if self._xfer_baseline is None:
            self._xfer_baseline = _linear_quantile(ordered, 0.5)
            return
        if self._xfer_baseline <= 0.0:
            return   # instant-transfer model: nothing to compare against
        p95 = _linear_quantile(ordered, 0.95)
        if p95 >= self.dcfg.slow_link_p95_multiple * self._xfer_baseline:
            with obs_span("gray.demote", link="migration",
                          p95_s=p95, baseline_s=self._xfer_baseline):
                self._degrade(DEGRADE_LINK_SLOW)

    def _live_workers(self) -> list:
        return [w for w in self.workers if w.alive]

    def _count_recompute(self, n: int) -> None:
        if n <= 0:
            return
        self.stats["recompute_tokens"] += int(n)
        get_registry().counter(
            "edgellm_disagg_recompute_tokens_total",
            "tokens re-prefilled/re-decoded after a failure").inc(int(n))

    def _slice_rows(self, payload: dict, start: int, stop: int) -> dict:
        cut = (slice(None),) * self._row_axis + (slice(start, stop),)
        return {k: v[cut] for k, v in payload.items() if k != "length"}

    def _concat_rows(self, chunks: list, length: int) -> dict:
        out = {k: np.concatenate([c[k] for c in chunks],
                                 axis=self._row_axis)
               for k in chunks[0]}
        out["length"] = np.asarray(length, np.int32)
        return out

    def _migrate(self, worker: PrefillWorker, slot: int, sid: int,
                 length: int) -> dict:
        """Ship the held slot page-by-page through the link. Raises
        :class:`PrefillWorkerLost` (source unreadable between pages) or
        :class:`MigrationError` (ladder exhausted)."""
        ps = self.bcfg.page_size
        chunks = []
        for p, start in enumerate(range(0, length, ps)):
            stop = min(start + ps, length)
            with obs_span("disagg.migrate_page", sid=sid, wid=worker.wid,
                          page=p, rows=stop - start):
                chunk = worker.gather_page(slot, start, stop)
                t0 = self.clock()
                chunks.append(self.link.send(chunk, sid=sid, page=p))
                self._observe_transfer(self.clock() - t0)
            if self.page_hook is not None:
                self.page_hook(worker.wid, sid, p)
        return self._concat_rows(chunks, length)

    def _redrive(self, snapshot: dict, sid: int, wid: int) -> dict:
        """Re-send every page from the server-held prefill checkpoint —
        the worker is gone but its finished work is not."""
        length = int(snapshot["length"])
        ps = self.bcfg.page_size
        chunks = []
        pages = 0
        for p, start in enumerate(range(0, length, ps)):
            stop = min(start + ps, length)
            with obs_span("disagg.migrate_page", sid=sid, wid=wid, page=p,
                          rows=stop - start, redriven=True):
                chunk = self._slice_rows(snapshot, start, stop)
                t0 = self.clock()
                chunks.append(self.link.send(chunk, sid=sid, page=p))
                self._observe_transfer(self.clock() - t0)
            pages += 1
        self.stats["redriven_pages"] += pages
        return self._concat_rows(chunks, length)

    def _handle_one(self, sid: int) -> str:
        """Prefill + migrate one pending prompt. Returns "done" (handled:
        queued, finished, or fell back colocated), "blocked" (no staging
        capacity — stop pumping this cycle), or "retry" (try again, e.g.
        on a surviving worker)."""
        prompt, mnt, temp, seed = self._reqs[sid]
        live = self._live_workers()
        if not live:
            self._degrade(DEGRADE_WORKERS_LOST)
            self.stats["colocated_fallbacks"] += 1
            self._submit_colocated(sid)
            return "done"
        worker = live[self._rr % len(live)]
        self._rr += 1
        try:
            got = worker.prefill(prompt, mnt, temp, seed)
        except PrefillWorkerLost:
            return "retry"
        if got is None:
            return "blocked"
        ssid, st = got
        self.stats["prefills"] += 1
        if st.status == "finished":
            # max_new_tokens == 1: token 0 is the whole answer, no pages
            # to move
            self.results[sid] = np.asarray(st.tokens, np.int32)
            self._reqs.pop(sid, None)
            worker.release(ssid)
            return "done"
        length = int(worker.bat.pool.lengths[st.slot])  # == prompt.size
        snapshot = (worker.snapshot(st.slot)
                    if self.dcfg.prefill_checkpoint else None)
        try:
            try:
                payload = self._migrate(worker, st.slot, sid, length)
            except PrefillWorkerLost as e:
                flight_dump_for(e, sid=sid, wid=worker.wid,
                                phase="migration")
                if snapshot is None:
                    # no checkpoint: the prefill is lost with the worker —
                    # re-prefill from scratch, counted
                    self._count_recompute(prompt.size)
                    return "retry"
                payload = self._redrive(snapshot, sid, worker.wid)
        except MigrationError as e:
            # ladder exhausted (or link died mid-handoff): the request
            # falls back to a colocated prefill — identical tokens, the
            # transfer is simply not taken
            flight_dump_for(e, sid=sid, wid=worker.wid, phase="migration")
            self._consecutive_failures += 1
            worker.release(ssid)
            self.stats["colocated_fallbacks"] += 1
            self._count_recompute(prompt.size)
            self._submit_colocated(sid)
            if not self.link.alive:
                self._degrade(DEGRADE_LINK_DEAD)
            elif self._consecutive_failures >= self.dcfg.degrade_after:
                self._degrade(DEGRADE_MIGRATION_FAILURES)
            return "done"
        self._consecutive_failures = 0
        worker.release(ssid)
        h = Handoff(sid=sid, prompt=prompt, max_new_tokens=mnt,
                    temperature=temp, rng_seed=seed,
                    tokens=list(st.tokens), payload=payload,
                    wid=worker.wid,
                    pages=-(-length // self.bcfg.page_size))
        with obs_span("disagg.migrate", sid=sid, wid=worker.wid,
                      pages=h.pages, rows=length):
            pass
        self.stats["migrations"] += 1
        self.stats["migrated_pages"] += h.pages
        reg = get_registry()
        reg.counter("edgellm_disagg_migrations_total",
                    "completed prefill->decode handoffs").inc()
        reg.counter("edgellm_disagg_pages_migrated_total",
                    "KV pages moved prefill->decode").inc(h.pages)
        self.queue.append(h)
        self.handoffs[sid] = h
        return "done"

    def _pump_prefill(self) -> int:
        """Drain pending prompts through live workers into the bounded
        handoff queue. Returns the number of prompts handled."""
        moved = 0
        while self.pending and not self.degraded:
            if len(self.queue) >= self.dcfg.queue_bound:
                break  # back-pressure: decode must pull first
            # pop BEFORE handling: a migration failure inside may degrade
            # the front, which drains pending — the in-flight sid must not
            # be drained (or double-submitted) underneath us
            sid = self.pending.popleft()
            verdict = self._handle_one(sid)
            if verdict == "done":
                moved += 1
                continue
            self.pending.appendleft(sid)
            if verdict == "blocked":
                break
            # "retry" loops with the same sid on the next live worker
        return moved

    def _decode_can_pull(self, h: Handoff) -> bool:
        pool = self.decode.pool
        if len(self.decode._slot_to_sid) >= self.bcfg.max_slots:
            return False
        free = pool.num_free_pages + pool.reclaimable_index_pages
        need = int(h.payload["length"]) if h.payload is not None else 0
        return free >= pool.pages_for(max(need, 1))

    def _pump_admit(self) -> int:
        """Decode admission: PULL verified handoffs from the queue while
        the decode pool can take them — the resume injection is the
        batcher's byte-move adopt path."""
        moved = 0
        while self.queue and self._decode_can_pull(self.queue[0]):
            h = self.queue.popleft()
            self._inject_handoff(h)
            moved += 1
        return moved

    def _inject_handoff(self, h: Handoff) -> None:
        with obs_span("disagg.adopt", sid=h.sid, pages=h.pages):
            dsid = self.decode.submit(h.prompt, h.max_new_tokens,
                                      temperature=h.temperature,
                                      rng_seed=h.rng_seed)
            st = self.decode._streams[dsid]
            st.tokens = list(h.tokens)
            st.resume = dict(h.payload)
            # the payload's rows are pure prompt KV (handoff at t == 1):
            # re-publish them so the decode pool's radix index survives
            # the transfer
            st.resume_prefix = True
        self._by_decode[dsid] = h.sid
        self._to_decode[h.sid] = dsid

    def _collect(self) -> None:
        for dsid in list(self.decode.results):
            our = self._by_decode.pop(dsid, None)
            toks = self.decode.pop_result(dsid)
            if our is None:
                continue
            self._to_decode.pop(our, None)
            self.handoffs.pop(our, None)
            self._reqs.pop(our, None)
            self.results[our] = toks

    # -- the drive loop ----------------------------------------------------

    def _unfinished(self) -> bool:
        return bool(self.pending or self.queue or self._by_decode
                    or self.decode._waiting or self.decode._slot_to_sid)

    def step(self) -> int:
        """One pump cycle: prefill pending prompts (bounded by the handoff
        queue), pull admissions into decode, run one ragged decode step.
        Returns a progress count (0 = fully idle)."""
        moved = self._pump_prefill()
        moved += self._pump_admit()
        stepped = self.decode.step()
        self._collect()
        return moved + stepped

    def run(self, max_steps: int = 100_000) -> dict:
        """Drive :meth:`step` until every accepted request finished."""
        for _ in range(max_steps):
            if not self._unfinished():
                break
            if self.step() == 0 and self._unfinished():
                exc = DisaggError(
                    "disagg server stalled: pending work but no pump "
                    "progress (pool too small for a waiting stream?)")
                flight_dump_for(exc, pending=len(self.pending),
                                queue=len(self.queue),
                                decode_waiting=len(self.decode._waiting))
                raise exc
        return self.results

    # -- failure injection -------------------------------------------------

    def kill_prefill_worker(self, wid: int) -> None:
        """Simulate prefill worker ``wid`` dying — mid-migration when armed
        from :attr:`page_hook`. Nothing accepted is lost: in-flight
        handoffs re-drive or re-prefill; the front degrades only when no
        worker survives."""
        with obs_span("disagg.kill", worker=f"prefill:{wid}"):
            self.workers[wid].kill()
        if not self._live_workers() and not self.degraded:
            self._degrade(DEGRADE_WORKERS_LOST)

    def fail_link(self) -> None:
        """Simulate the disagg link dying: the front degrades to colocated
        serving with the typed reason ``migration_link_dead``."""
        self.link.fail()
        self._degrade(DEGRADE_LINK_DEAD)

    def slow_link(self, mult: float) -> None:
        """Simulate the disagg link going gray: later transfers take
        ``mult`` × the modeled wire time. The front keeps serving and
        degrades only when the detector's windowed p95 crosses the
        configured multiple of the healthy baseline."""
        self.link.set_transfer_multiplier(mult)

    def kill_decode_worker(self) -> None:
        """Simulate the decode worker dying. Running streams re-admit via
        the existing DecodeCheckpoint path (token-identical restore) when
        ``bcfg.checkpoint_dir`` is set; otherwise — and for handoffs
        admitted but not yet progressed — the server-held handoff record
        re-injects and decode replays deterministically (counted in
        ``recompute_tokens``). Colocated streams resubmit from scratch."""
        with obs_span("disagg.kill", worker="decode"):
            pass
        old = self.decode
        ckpt_dir = self.bcfg.checkpoint_dir
        # harvest finished results before the worker state is torn down
        self._collect()
        saved, replay, fresh = {}, [], []
        for dsid, our in list(self._by_decode.items()):
            st = old._streams.get(dsid)
            if st is None or st.status == "finished":
                continue
            if st.status == "running" and ckpt_dir is not None:
                saved[our] = old.checkpoint_stream(
                    dsid, os.path.join(ckpt_dir, f"disagg_{our}.ckpt"))
            elif our in self.handoffs:
                replay.append((our, st.t))
            else:
                fresh.append((our, st.status, st.t))
        self.decode = ContinuousBatcher(self.cfg, self.params, self.bcfg,
                                        **self._rt_args)
        self._by_decode, self._to_decode = {}, {}
        for our, path in saved.items():
            with obs_span("disagg.readmit", sid=our, how="checkpoint"):
                dsid = self.decode.restore_stream(path)
            self._by_decode[dsid] = our
            self._to_decode[our] = dsid
            self.stats["readmitted"] += 1
        for our, t in replay:
            h = self.handoffs[our]
            with obs_span("disagg.readmit", sid=our, how="handoff"):
                self._inject_handoff(h)
            # decode progress past the handoff replays deterministically
            self._count_recompute(t - len(h.tokens))
            self.stats["readmitted"] += 1
        for our, status, t in fresh:
            prompt = self._reqs[our][0]
            with obs_span("disagg.readmit", sid=our, how="resubmit"):
                self._submit_colocated(our)
            if status == "running":
                self._count_recompute(int(prompt.size) + max(t - 1, 0))
            self.stats["readmitted"] += 1

    # -- reporting ---------------------------------------------------------

    def report(self) -> dict:
        rep = self.decode.report()
        live = len(self._live_workers())
        reg = get_registry()
        reg.gauge("edgellm_disagg_prefill_workers",
                  "live prefill workers").set(live)
        reg.gauge("edgellm_disagg_queue_depth",
                  "handoffs awaiting decode pull").set(len(self.queue))
        reg.gauge("edgellm_disagg_degraded",
                  "1 after the front degraded to colocated serving").set(
                      float(self.degraded))
        reg.counter("edgellm_disagg_migrations_total",
                    "completed prefill->decode handoffs").inc(0)
        link = dict(self.link.counters)
        rep["disagg"] = {
            "enabled": self.dcfg.enabled,
            "degraded": self.degraded,
            "degrade_reason": self.degrade_reason,
            "prefill_workers": len(self.workers),
            "live_prefill_workers": live,
            "queue_depth": len(self.queue),
            "pending": len(self.pending),
            "wire_bytes": link["wire_bytes"],
            "link": link,
            "transfer_baseline_s": self._xfer_baseline,
            "transfer_window": len(self._xfer_window),
            **{k: v for k, v in self.stats.items()},
        }
        return rep
