from .decode import (decode_step_cache_size, generate, generate_split,
                     resume_split)
from .recovery import (CheckpointError, DecodeCheckpoint, DecodeTimeout,
                       LocalRuntime, RecoveryConfig, RecoveryCounters,
                       StageFailure, StageLostError, Watchdog)

__all__ = [
    "generate", "generate_split", "resume_split", "decode_step_cache_size",
    "CheckpointError", "DecodeCheckpoint", "DecodeTimeout", "LocalRuntime",
    "RecoveryConfig", "RecoveryCounters", "StageFailure", "StageLostError",
    "Watchdog",
]
