from .decode import generate, decode_step_cache_size

__all__ = ["generate", "decode_step_cache_size"]
