from .batching import (BatchingConfig, ContinuousBatcher,
                       batched_step_cache_size)
from .cluster import (AutoscalerConfig, ClusterConfig, ClusterConfigError,
                      ClusterFront, Replica, ReplicaLostError, RespawnConfig,
                      SimReplicaConfig, SimReplicaFront, drive_cluster,
                      sim_reference_tokens)
from .decode import (decode_step_cache_size, generate, generate_split,
                     resume_split)
from .frontend import Request, RequestRecord, ServeFront, ServeFrontConfig
from .overload import (AdmissionConfig, AdmissionController, AdmissionError,
                       BreakerConfig, BrownoutConfig, BrownoutController,
                       CircuitBreaker, CircuitOpen, DeadlineInfeasible,
                       QueueFull, RetryBudget, RetryBudgetConfig,
                       RetryBudgetExhausted, ServeFrontConfigError)
from .recovery import (CheckpointError, DecodeCheckpoint, DecodeTimeout,
                       LocalRuntime, RecoveryConfig, RecoveryCounters,
                       StageFailure, StageLostError, Watchdog)
from .soak import ClusterSoakConfig, SoakConfig, run_cluster_soak, run_soak

__all__ = [
    "generate", "generate_split", "resume_split", "decode_step_cache_size",
    "CheckpointError", "DecodeCheckpoint", "DecodeTimeout", "LocalRuntime",
    "RecoveryConfig", "RecoveryCounters", "StageFailure", "StageLostError",
    "Watchdog",
    "Request", "RequestRecord", "ServeFront", "ServeFrontConfig",
    "AdmissionConfig", "AdmissionController", "AdmissionError",
    "BreakerConfig", "BrownoutConfig", "BrownoutController",
    "CircuitBreaker", "CircuitOpen", "DeadlineInfeasible", "QueueFull",
    "RetryBudget", "RetryBudgetConfig", "RetryBudgetExhausted",
    "ServeFrontConfigError",
    "SoakConfig", "run_soak",
    "BatchingConfig", "ContinuousBatcher", "batched_step_cache_size",
    "AutoscalerConfig", "ClusterConfig", "ClusterConfigError",
    "ClusterFront", "Replica", "ReplicaLostError", "RespawnConfig",
    "SimReplicaConfig", "SimReplicaFront", "drive_cluster",
    "sim_reference_tokens",
    "ClusterSoakConfig", "run_cluster_soak",
]
