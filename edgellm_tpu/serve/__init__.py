from .decode import generate, generate_split, decode_step_cache_size

__all__ = ["generate", "generate_split", "decode_step_cache_size"]
