"""AOT window-batch preflight: pick the largest batch that FITS, never OOM.

On the tunneled TPU backend a real RESOURCE_EXHAUSTED poisons the process's
device allocator — after one failed launch even a tiny ``device_put`` fails,
so recover-by-retry (``run_with_oom_backoff``) cannot help. The robust order
is reversed: AOT-compile the sweep's two big executables (the stats forward
and the ratio-vmapped suffix sweep) at each candidate batch and read XLA's
``memory_analysis()`` — compilation allocates no HBM — then run only the
batch whose estimated peak fits.

The estimate for one executable is ``argument + output + temp`` bytes; on top
of the worst call the sweep keeps TWO boundary-hidden stacks alive (the
drained group's and the in-flight next group's, from the submit/drain
double-buffering) plus the captured stats, which are added analytically.
``budget_frac`` absorbs what the estimate cannot see (allocator slack,
fragmentation, the small executables).

The lower/compile/``memory_analysis()`` primitive lives in
:mod:`edgellm_tpu.analysis.aot` — shared with the config-lattice verifier
(``lint/lattice.py``) so the two AOT consumers cannot drift.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

from ..analysis.aot import call_total_bytes, is_over_hbm

DEFAULT_HBM_BYTES = int(15.75 * 2 ** 30)  # TPU v5e; override with BENCH_HBM_GB

#: back-compat alias — callers and tests predate the analysis.aot extraction
_is_over_hbm = is_over_hbm


def _budget_bytes(hbm_bytes: Optional[int], budget_frac: float) -> int:
    if hbm_bytes is None:
        hbm_bytes = int(float(os.environ.get("BENCH_HBM_GB", "0")) * 2 ** 30) \
            or DEFAULT_HBM_BYTES
    return int(hbm_bytes * budget_frac)


def estimate_sweep_peak_bytes(cfg, window_batch: int, max_length: int,
                              tail: int, layer: int, codec: str,
                              n_ratios: int, dtype,
                              layers: Optional[Sequence[int]] = None) -> dict:
    """Estimated HBM peak of the token sweep at one window batch (bytes).

    ``layers`` is the full ``layers_of_interest`` tuple (defaults to
    ``(layer,)``) — the stats forward collects hiddens only at those layers
    and captures stats only up to the deepest one, so the estimate mirrors
    the executables ``run_token_sweep`` actually compiles."""
    import jax
    import jax.numpy as jnp

    from ..eval.harness import (DEDUP_ZERO_CODECS, _stats_forward,
                                _suffix_sweep)
    from ..models import init_params

    layers = tuple(int(l) for l in (layers if layers is not None else (layer,)))
    W, S, D = window_batch, max_length, cfg.hidden_size
    n_interest = len(set(layers))
    n_stats = max(layers) + 1
    params_shape = jax.eval_shape(
        lambda k: init_params(cfg, k, dtype=dtype), jax.random.key(0))
    ids = jax.ShapeDtypeStruct((W, S), jnp.int32)
    targets = jax.ShapeDtypeStruct((W, S), jnp.int32)

    # argument+output+temp bytes, or None when the TPU compiler itself
    # rejects the program as over-HBM — a provable doesn't-fit, still with
    # zero allocation (shared driver: analysis/aot.py)
    call_bytes = call_total_bytes

    want_final = codec in DEDUP_ZERO_CODECS
    stats = call_bytes(_stats_forward(cfg, layers, want_final=want_final)
                       .lower(params_shape, ids))

    hidden = jax.ShapeDtypeStruct((W, S, D), dtype)
    imp = jax.ShapeDtypeStruct((W, S), jnp.float32)
    ratios = jax.ShapeDtypeStruct((n_ratios,), jnp.float32)
    ks = jax.ShapeDtypeStruct((n_ratios,), jnp.int32)
    suffix = call_bytes(_suffix_sweep(cfg, layer, codec, tail)
                        .lower(params_shape, hidden, targets, imp, ratios, ks))
    base = 0
    if want_final:
        # the baseline tail scorer is a THIRD executable since round 5 split
        # it out of the stats forward (_base_tail): its streamed-unembed
        # temps must be in the estimate too, or the preflight approves a
        # batch that OOMs at the baseline-scoring call
        from ..eval.harness import _base_tail

        base = call_bytes(_base_tail(cfg, tail)
                          .lower(params_shape, hidden, targets))

    if stats is None or suffix is None or base is None:  # proven over-HBM
        return {"stats_call": stats, "suffix_call": suffix, "base_call": base,
                "hiddens_stack": 0, "peak": float("inf")}
    itemsize = jnp.dtype(dtype).itemsize
    hiddens_stack = n_interest * W * S * D * itemsize  # collected boundaries
    stats_buf = 2 * n_stats * W * cfg.num_heads * S * 4  # col_mean + last_row
    # worst single call + the other live group state the call's args don't hold:
    # the suffix sees one (W,S,D) slice as an arg while BOTH groups' full
    # stacks are alive (submit/drain double buffering)
    peak = max(stats + hiddens_stack,  # stats call + previous group's stack
               suffix + 2 * hiddens_stack + 2 * stats_buf,
               base + 2 * hiddens_stack + 2 * stats_buf)
    return {"stats_call": stats, "suffix_call": suffix, "base_call": base,
            "hiddens_stack": hiddens_stack, "peak": peak}


def preflight_token_sweep_batch(cfg, requested: int, *, max_length: int,
                                stride: int, layers_of_interest: Sequence[int],
                                ratios: Sequence[float], dtype,
                                codec: str = "int4_token_select",
                                hbm_bytes: Optional[int] = None,
                                budget_frac: float = 0.8) -> int:
    """Sweep-shaped wrapper around :func:`largest_fitting_window_batch`,
    shared by bench.py and run.py: sizes the EARLIEST split layer (longest
    suffix = biggest executable) and counts the ratio axis the way
    run_token_sweep compiles it (nonzero ratios only for dedup codecs)."""
    from ..eval.harness import DEDUP_ZERO_CODECS

    n_ratios = (sum(1 for r in ratios if float(r) != 0.0)
                if codec in DEDUP_ZERO_CODECS else len(ratios))
    wb, _ = largest_fitting_window_batch(
        cfg, requested, max_length=max_length, tail=stride + 1,
        layer=min(int(l) for l in layers_of_interest), codec=codec,
        n_ratios=max(n_ratios, 1), dtype=dtype,
        hbm_bytes=hbm_bytes, budget_frac=budget_frac,
        layers=tuple(int(l) for l in layers_of_interest))
    return wb


def largest_fitting_relevance_batch(cfg, requested: int, *, max_length: int,
                                    dtype, hbm_bytes: Optional[int] = None,
                                    budget_frac: float = 0.8,
                                    min_window_batch: int = 1) -> int:
    """Largest window batch whose LRP vjp executable fits — same AOT
    memory-analysis approach as the sweep preflight (the (L, W, H, S, S)
    probs + their cotangents dominate)."""
    import jax
    import jax.numpy as jnp

    from ..importance.relevance import _chunk_relevance
    from ..models import init_params

    budget = _budget_bytes(hbm_bytes, budget_frac)
    params_shape = jax.eval_shape(
        lambda k: init_params(cfg, k, dtype=dtype), jax.random.key(0))
    wb = requested
    while wb > min_window_batch:
        ids = jax.ShapeDtypeStruct((wb, max_length), jnp.int32)
        total = call_total_bytes(_chunk_relevance(cfg).lower(params_shape, ids))
        if total is not None and total <= budget:
            return wb
        wb = max(wb // 2, min_window_batch)
    return wb


def largest_fitting_window_batch(cfg, requested: int, *, max_length: int,
                                 tail: int, layer: int, codec: str,
                                 n_ratios: int, dtype,
                                 hbm_bytes: Optional[int] = None,
                                 budget_frac: float = 0.8,
                                 min_window_batch: int = 1,
                                 layers: Optional[Sequence[int]] = None) -> tuple:
    """Halve ``requested`` until the estimated peak fits -> (wb, estimate)."""
    budget = _budget_bytes(hbm_bytes, budget_frac)
    wb = requested
    while True:
        est = estimate_sweep_peak_bytes(cfg, wb, max_length, tail, layer,
                                        codec, n_ratios, dtype, layers=layers)
        if est["peak"] <= budget or wb <= min_window_batch:
            return wb, est
        wb = max(wb // 2, min_window_batch)
