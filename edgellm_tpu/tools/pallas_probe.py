"""On-silicon proof of the Pallas codec path.

Every ``*_pallas`` wire codec the split runtime auto-substitutes on TPU
(``parallel/split.py``) is exercised here on the REAL backend — no
``interpret=True`` — and compared leaf-by-leaf against its jnp twin:

- integer payload leaves (packed nibbles / crumbs / int8 codes) must be
  bit-identical;
- float leaves (scales, minima, bf16 high-precision slices) and the decoded
  reconstruction are checked to <= 2 ulp (the documented kernel deviation:
  XLA may fuse ``(c / 7) * s`` in a different order than Mosaic);
- encode/decode throughput is measured in GB/s, alongside the jnp twin's, so
  the fused-vs-unfused speedup is recorded per codec.

The result is a JSON-able dict that ``bench.py`` embeds as the ``"pallas"``
block of the bench detail line and sidecar — the artifact VERDICT r2 asked for
(kernels lower through Mosaic, match on hardware, and their throughput is
pinned). The same probe runs in the test suite on CPU (interpret mode) so the
parity logic itself is covered without a chip.

Timing notes (axon tunnel: a jitted call + scalar readback carries a large and
NOISY fixed cost, ~70-105 ms measured — far above any codec kernel):
- DIFFERENTIAL timing cancels it: the same body is scanned at two lengths
  (``N1``/``N2``) and the per-iteration time is ``(t2 - t1) / (N2 - N1)``.
  Validated on this chip against a pure read+write pass: ~685 GB/s, right at
  the v5e HBM ceiling, where single-shot scan timing reported 4 GB/s;
- each iteration indexes a pool of PRE-STAGED DISTINCT inputs via a
  loop-carried index, defeating XLA's loop-invariant hoisting (a hoisted
  ``encode(x)`` would time as a no-op);
- every payload leaf feeds the scan carry (one element each), so no output op
  is dead-code eliminated;
- ``float(...)`` on the carry forces a real readback (``block_until_ready``
  alone is unreliable over the tunnel).

Reference provenance: the kernels replace the per-channel Python loop at
``Experiments/Qwen2-0.5B/qwen_layer_wise.py:125-152`` (SURVEY.md section 3.5);
this probe is the evidence they run on the hardware the loop never targeted.
"""
from __future__ import annotations

import json
import time
from typing import Optional

import numpy as np

#: codec names (registry names) — every codec with a kernel twin. The
#: selective codec is NOT here: its twin was deleted in round 5 on
#: measurement (gather-bound; the pallas boundary broke XLA's gather->quant
#: fusion and probed 0.96-0.97x across rounds) — probe_all() appends the
#: recorded exclusion so the decision stays in every bench artifact.
PROBE_CODECS = (
    "int4_per_token",
    "int8_per_token",
    "int8_per_channel",
    "int4_per_channel",
    "ternary_mean",
    "ternary_max",
)


def _codec_pair(name: str):
    from edgellm_tpu.codecs.packing import get_wire_codec
    from edgellm_tpu.codecs.pallas_kernels import pallas_variant

    jnp_codec = get_wire_codec(name)
    return jnp_codec, pallas_variant(jnp_codec)


def _ulp_diff(got: np.ndarray, want: np.ndarray) -> int:
    """Max distance in representable steps between two same-dtype float arrays."""
    if got.size == 0:
        return 0
    kind = {2: np.int16, 4: np.int32, 8: np.int64}[got.dtype.itemsize]
    lowest = np.int64(np.iinfo(kind).min)  # the bit pattern of -0.0
    gi = got.view(kind).astype(np.int64)
    wi = want.view(kind).astype(np.int64)
    # map the sign-magnitude float encoding onto a monotone integer line:
    # negatives (sign bit set) become -(magnitude), with -0.0 -> 0
    gi = np.where(gi < 0, lowest - gi, gi)
    wi = np.where(wi < 0, lowest - wi, wi)
    return int(np.abs(gi - wi).max())


def _compare_payloads(got: dict, want: dict, max_ulp: int):
    """(n_int_leaves bit-identical, worst float-leaf ulp). Raises on mismatch."""
    assert set(got) == set(want), (sorted(got), sorted(want))
    n_int, worst = 0, 0
    for key in sorted(want):
        g, w = np.asarray(got[key]), np.asarray(want[key])
        assert g.dtype == w.dtype and g.shape == w.shape, \
            f"{key}: {g.dtype}{g.shape} vs {w.dtype}{w.shape}"
        if np.issubdtype(w.dtype, np.integer):
            np.testing.assert_array_equal(g, w, err_msg=key)
            n_int += 1
        else:
            ulp = _ulp_diff(g, w)
            assert ulp <= max_ulp, f"{key}: {ulp} ulp > {max_ulp}"
            worst = max(worst, ulp)
    return n_int, worst


def _nbytes(tree) -> int:
    import jax

    return int(sum(np.prod(l.shape) * l.dtype.itemsize
                   for l in jax.tree_util.tree_leaves(tree)))


#: differential-timing scan lengths; per-iter = (t[N2] - t[N1]) / (N2 - N1).
#: N2 is sized so a ~30 us kernel accumulates >50 ms of work delta — above
#: the tunnel's ~±10 ms per-call noise — and _timed_scan quadruples the
#: lengths (recompiling) when a body is still too fast to resolve.
_N1, _N2 = 128, 2048
#: a measured work delta below this is indistinguishable from call jitter
_MIN_DELTA_S = 0.05

# Bench mode times the encode->decode ROUNDTRIP of every codec (2 scan
# executables per codec — separate encode/decode timing would double the
# compile count and put the probe past the bench's time budget on the
# tunnel). EDGELLM_PROBE_ALL=1 adds the separate encode/decode split.


class _ScanTimer:
    """Differential-scan timer for one body, caching the compiled scan
    executables per length so REPEATED measurements (the interleaved-pair
    medians) cost readbacks, not retrace+recompile."""

    def __init__(self, build_body, pool_tree, pool: int):
        self.build_body = build_body
        self.pool_tree = pool_tree
        self.pool = pool
        self._runs: dict = {}

    def _run_for(self, length):
        import jax
        import jax.numpy as jnp

        if length in self._runs:
            return self._runs[length]
        build_body, pool = self.build_body, self.pool

        @jax.jit
        def run(tree):
            def body(carry, idx):
                x = jax.tree_util.tree_map(
                    lambda a: jax.lax.dynamic_index_in_dim(a, idx, 0,
                                                           keepdims=False), tree)
                out = build_body(x)
                leaves = jax.tree_util.tree_leaves(out)
                # FULL reduction over every leaf: a single-element read would
                # let XLA's slice-pushdown shrink the body (dot(a,b)[0,0]
                # becomes a vector dot and times as a no-op). The reduce fuses
                # into the producer, so it adds no extra HBM round trip.
                acc = sum(jnp.sum(l.astype(jnp.float32)) for l in leaves if l.size)
                return carry + acc, None

            carry, _ = jax.lax.scan(body, jnp.float32(0.0),
                                    jnp.arange(length) % pool)
            return carry

        self._runs[length] = run
        return run

    def _rep_of(self, run, reps=2):
        float(run(self.pool_tree))  # compile + warm
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(run(self.pool_tree))  # forced readback (axon)
            ts.append(time.perf_counter() - t0)
        return min(ts)

    def differential(self, lengths=None) -> float:
        n1, n2 = lengths or (_N1, _N2)
        for _ in range(3):
            t1 = self._rep_of(self._run_for(n1))
            t2 = self._rep_of(self._run_for(n2))
            delta, span = t2 - t1, n2 - n1
            if delta >= _MIN_DELTA_S:
                return delta / span
            n1, n2 = n1 * 4, n2 * 4  # too fast to resolve: quadruple the work
        # still inside the jitter band after escalating: NaN, never a rate
        # made of noise (callers omit the affected fields)
        return float("nan")


def _timed_scan(build_body, pool_tree, pool: int, lengths=None) -> float:
    """Seconds per iteration of ``build_body`` applied to pool entry
    ``i % pool`` (leading axis of every ``pool_tree`` leaf = pool). One element
    of every output leaf is folded into the carry so nothing is DCE'd; the
    loop-carried index defeats hoisting. Differential over two scan lengths
    cancels the axon tunnel's fixed per-call cost."""
    return _ScanTimer(build_body, pool_tree, pool).differential(lengths)


def probe_codec(name: str, *, batch: int = 8, seq: int = 512, dim: int = 896,
                pool: int = 16, timing: bool = True, timing_detail: bool = False,
                max_ulp: int = 2, seed: int = 0) -> dict:
    """Parity + throughput for one codec pair on the CURRENT default backend."""
    import jax
    import jax.numpy as jnp

    jnp_codec, pallas_codec = _codec_pair(name)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((batch, seq, dim)).astype(np.float32))
    imp = jnp.asarray(rng.random(seq).astype(np.float32))
    args = (x, imp) if jnp_codec.needs_importance else (x,)

    want = jax.jit(jnp_codec.encode)(*args)
    got = jax.jit(pallas_codec.encode)(*args)
    jax.block_until_ready((want, got))
    n_int, enc_ulp = _compare_payloads(got, want, max_ulp)

    dec_want = np.asarray(jax.jit(jnp_codec.decode)(want))
    dec_got = np.asarray(jax.jit(pallas_codec.decode)(got))
    dec_ulp = _ulp_diff(dec_got, dec_want)
    assert dec_ulp <= max_ulp, f"{name} decode: {dec_ulp} ulp > {max_ulp}"

    from edgellm_tpu.codecs.pallas_kernels import default_substituted
    from edgellm_tpu.codecs.probe_cache import base_name

    result = {
        "codec": name,
        "backend": jax.default_backend(),
        "shape": [batch, seq, dim],
        # whether the TPU default path substitutes this kernel (the measured-
        # win policy: this chip's probe cache, frozen set as no-data
        # fallback; split.apply_default_codec_backend); non-default twins
        # stay probed for parity and remain pinnable via *_pallas names
        "default_substituted": default_substituted(base_name(name)),
        "int_leaves_bit_identical": n_int,
        "encode_max_ulp": enc_ulp,
        "decode_max_ulp": dec_ulp,
        "payload_bytes": _nbytes(want),
    }
    if not timing:
        return result

    import math

    in_bytes = int(np.prod(x.shape)) * 4
    payload_bytes = result["payload_bytes"]
    moved = 2 * (in_bytes + payload_bytes)  # enc: read+write, dec: read+write
    xs = jnp.asarray(rng.standard_normal((pool,) + x.shape).astype(np.float32))

    def roundtrip_body(codec):
        # return the payload ALONGSIDE the decoded output: _timed_scan folds
        # every leaf of the returned tree into the carry, so even a payload
        # leaf the decode side ignores cannot be dead-code-eliminated out of
        # the timed body
        def body(xi):
            p = (codec.encode(xi, imp) if codec.needs_importance
                 else codec.encode(xi))
            return p, codec.decode(p)

        return body

    # INTERLEAVED pairs, median ratio: the tunnel's timing quality drifts by
    # phase, so timing all pallas scans then all jnp scans lets a phase shift
    # masquerade as a codec speed change (round-4 observed the same codec
    # probe 1.4x and 0.75x an hour apart). Each adjacent (pallas, jnp) pair
    # shares a phase; the per-pair ratio cancels it and the median over pairs
    # rejects a single bad window. Executables cache, so the extra scans cost
    # readbacks, not compiles. A NaN differential (body inside call jitter
    # even after escalation) drops the pair rather than emit a physically
    # impossible rate (NaN would also break the JSON line).
    import statistics

    def paired_medians(make_p, make_j, tree, reps=3):
        """(median pallas time, median per-pair jnp/pallas ratio); the jnp
        side of a pair is only timed when the pallas differential resolved
        (escalating scans for a value that could never be emitted are the
        probe's biggest time sink). One _ScanTimer per side: the compiled
        scan executables are built once and every further rep is readbacks."""
        timer_p = _ScanTimer(make_p, tree, pool)
        timer_j = _ScanTimer(make_j, tree, pool)
        tps, ratios = [], []
        for _ in range(reps):
            tp = timer_p.differential()
            if not math.isfinite(tp):
                continue
            tps.append(tp)
            tj = timer_j.differential()
            if math.isfinite(tj):
                ratios.append(tj / tp)
        return (statistics.median(tps) if tps else float("nan"),
                statistics.median(ratios) if ratios else float("nan"))

    t_rt_p, rt_ratio = paired_medians(roundtrip_body(pallas_codec),
                                      roundtrip_body(jnp_codec), xs)
    if math.isfinite(t_rt_p):
        result["roundtrip_gbps"] = round(moved / t_rt_p / 1e9, 2)
        result["roundtrip_us"] = round(t_rt_p * 1e6, 1)
    if math.isfinite(rt_ratio):
        result["roundtrip_speedup_vs_jnp"] = round(rt_ratio, 2)
        # the UNROUNDED ratio is what the probe cache persists: the
        # WIN_MARGIN=1.05 hysteresis must never compare against a display
        # value a 1.045 reading was rounded up into (ADVICE r5 #3)
        result["roundtrip_speedup_vs_jnp_raw"] = rt_ratio
    if not timing_detail:
        return result

    payloads = jax.vmap(jnp_codec.encode, in_axes=(0, None) if len(args) == 2
                        else 0)(*((xs, imp) if len(args) == 2 else (xs,)))
    jax.block_until_ready(payloads)

    def enc_body(codec):
        if codec.needs_importance:
            return lambda xi: codec.encode(xi, imp)
        return codec.encode

    # same interleaved-pair estimator as the roundtrip: the split numbers
    # must not contradict the roundtrip just because the phase drifted
    # between the pallas and jnp measurements
    t_enc_p, enc_ratio = paired_medians(enc_body(pallas_codec),
                                        enc_body(jnp_codec), xs)
    t_dec_p, dec_ratio = paired_medians(pallas_codec.decode, jnp_codec.decode,
                                        payloads)
    if math.isfinite(t_enc_p):
        result["encode_gbps"] = round((in_bytes + payload_bytes) / t_enc_p / 1e9, 2)
        result["encode_us"] = round(t_enc_p * 1e6, 1)
    if math.isfinite(t_dec_p):
        result["decode_gbps"] = round((payload_bytes + in_bytes) / t_dec_p / 1e9, 2)
        result["decode_us"] = round(t_dec_p * 1e6, 1)
    if math.isfinite(enc_ratio):
        result["encode_speedup_vs_jnp"] = round(enc_ratio, 2)
    if math.isfinite(dec_ratio):
        result["decode_speedup_vs_jnp"] = round(dec_ratio, 2)
    return result


def probe_all(*, timing: Optional[bool] = None, batch: int = 8, seq: int = 512,
              dim: int = 896, pool: int = 16) -> dict:
    """The ``"pallas"`` bench detail block: every substituted codec, parity + GB/s.

    ``timing=None`` enables timing only on a real TPU backend (interpret-mode
    timings would be meaningless).
    """
    import jax

    import os

    on_tpu = jax.default_backend() == "tpu"
    if timing is None:
        timing = on_tpu
    detail = os.environ.get("EDGELLM_PROBE_ALL", "0") == "1"
    codecs = []
    for name in PROBE_CODECS:
        codecs.append(probe_codec(
            name, batch=batch, seq=seq, dim=dim, pool=pool,
            timing=timing, timing_detail=timing and detail))
    from edgellm_tpu.codecs.pallas_kernels import SELECTIVE_EXCLUSION

    codecs.append({
        "codec": "selective_int4",
        "default_substituted": False,
        "excluded": SELECTIVE_EXCLUSION,
        # the measurements the deletion decision rests on (v5e, r4/r5)
        "measured": {"roundtrip_speedup_vs_jnp_r4": 0.97,
                     "roundtrip_speedup_vs_jnp_r5": 0.96,
                     "encode_speedup_vs_jnp_r5": 0.97,
                     "decode_speedup_vs_jnp_r5": 0.99},
    })
    cache_path = None
    if timing:
        # persist this run's measured speedups as THE substitution policy for
        # this chip (codecs/probe_cache.py), then re-annotate each block with
        # the post-record policy: what the NEXT sweep on this chip will
        # substitute, derived from measurement, never a stale constant
        from edgellm_tpu.codecs.pallas_kernels import default_substituted
        from edgellm_tpu.codecs.probe_cache import base_name, record

        cache_path = record(codecs)
        if cache_path:
            for c in codecs:
                if "excluded" not in c:  # deleted twins stay excluded
                    c["default_substituted"] = default_substituted(
                        base_name(c["codec"]))
    return {
        "backend": jax.default_backend(),
        "interpret": not on_tpu,
        "shape": [batch, seq, dim],
        "parity": "int leaves bit-identical; float leaves and decode <= 2 ulp",
        "timing": None if not timing else (
            "roundtrip per codec" + (" + encode/decode split" if detail else
                                     " (EDGELLM_PROBE_ALL=1 adds the split)")),
        "probe_cache": cache_path,
        "codecs": codecs,
    }


def main():
    print(json.dumps(probe_all(), indent=2))


if __name__ == "__main__":
    main()
