"""Build the WikiText-2 evaluation corpus exactly the way the reference does.

The reference constructs its corpus as ``"\\n\\n".join(test["text"])`` over the
``wikitext-2-raw-v1`` test split and tokenizes the joined string in one call
(``/root/reference/Experiments/Qwen2-0.5B/main.py:122-124``,
``Experiments/Pythia-70M/last_row_exp.py:49-55``) — 299,078 Qwen2 tokens
(``Notebooks/qwen2-0.5B_experiment.ipynb`` cell 5). The joining/tokenization
details define the PPL metric, so this tool pins them:

    python -m edgellm_tpu.tools.prepare_wikitext \\
        --input <source> --tokenizer <local HF tokenizer dir> --output corpus.npy

``--input`` accepts, in order of fidelity:
- an HF datasets directory saved with ``save_to_disk`` (test split or a
  DatasetDict containing one) — the reference's own data path, fully offline;
- a ``.jsonl`` file with one ``{"text": ...}`` object per line (the raw rows);
- a ``.txt`` file assumed to be the ALREADY-JOINED corpus (written verbatim).

The output ``.npy`` (int32 token ids) feeds ``edgellm_tpu.run --corpus``. A
``<output>.meta.json`` records the tokenizer path, document count, and token
count so a sweep's corpus provenance is auditable.
"""
from __future__ import annotations

import argparse
import json
import sys

import numpy as np

JOINER = "\n\n"  # Qwen2-0.5B/main.py:124


def load_texts(path: str):
    """-> (list of document strings, source_kind)."""
    if path.endswith(".jsonl"):
        with open(path) as f:
            return [json.loads(line)["text"] for line in f if line.strip()], "jsonl"
    if path.endswith(".txt"):
        with open(path) as f:
            return [f.read()], "joined-txt"
    # HF datasets directory (offline, save_to_disk layout)
    from datasets import load_from_disk

    ds = load_from_disk(path)
    if hasattr(ds, "keys") and "test" in ds:
        ds = ds["test"]
    return list(ds["text"]), "datasets-dir"


def build_corpus(texts, tokenizer_path: str, already_joined: bool = False):
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(tokenizer_path)
    joined = texts[0] if already_joined else JOINER.join(texts)
    ids = tok(joined, return_tensors="np").input_ids.reshape(-1)
    return np.asarray(ids, np.int32), joined


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--input", required=True,
                    help="datasets dir (save_to_disk), .jsonl rows, or joined .txt")
    ap.add_argument("--tokenizer", required=True, help="local HF tokenizer path")
    ap.add_argument("--output", default="corpus.npy")
    args = ap.parse_args(argv)

    texts, kind = load_texts(args.input)
    ids, joined = build_corpus(texts, args.tokenizer, already_joined=(kind == "joined-txt"))
    np.save(args.output, ids)
    meta = {
        "tokenizer": args.tokenizer,
        "source": args.input,
        "source_kind": kind,
        "n_documents": len(texts),
        "n_chars_joined": len(joined),
        "n_tokens": int(ids.size),
        "joiner": JOINER,
    }
    with open(args.output + ".meta.json", "w") as f:
        json.dump(meta, f, indent=1)
    print(json.dumps(meta))
    return 0


if __name__ == "__main__":
    sys.exit(main())
