"""North-star checker: compare a sweep result against the reference anchors.

``python -m edgellm_tpu.tools.check_reproduction out_sweep/avg_ppl_results.json``
prints one row per golden cell (got / want / delta / verdict) and exits 0 only
when every STABLE anchor matches within ±0.1 PPL — the BASELINE.md north star
— so the REPRODUCING.md §3 validation is a single command the day real
checkpoints and the WikiText-2 test tokens appear.

Expected values are the reference's own numbers, derived from the NLL dumps in
``/root/reference/Notebooks/qwen2-0.5B_experiment.ipynb`` cell 12 (1,000
chunks — run the sweep with ``--max-chunks 1000``; see BASELINE.md for the
derivation). Collapse cells (quantization destroyed the model; the reference
records 2.1e3-9.8e6) are checked to a factor of 2 — their exact values are
noise amplification, but the collapse itself must reproduce.
"""
from __future__ import annotations

import json
import sys

#: (method, split layer, ratio, expected PPL, kind); kind "abs" = ±0.1 PPL,
#: "collapse" = within 2x (the cell's defining property is the blow-up).
#: ratio-0.0 cells are the fp baseline: method-independent by construction.
GOLDEN = [
    ("last_row", 3, 0.0, 13.31, "abs"),
    ("last_row", 3, 0.25, 13.40, "abs"),
    ("last_row", 3, 0.5, 13.71, "abs"),
    ("last_row", 3, 0.75, 14.80, "abs"),
    ("last_row", 11, 0.25, 13.41, "abs"),
    ("last_row", 11, 0.5, 13.73, "abs"),
    ("last_row", 11, 0.75, 14.58, "abs"),
    ("last_row", 22, 0.25, 16.33, "abs"),
    ("last_row", 22, 0.5, 24.63, "abs"),
    ("last_row", 22, 0.75, 48.18, "abs"),
    ("regular_importance", 3, 0.25, 14.06, "abs"),
    ("regular_importance", 3, 0.5, 15.01, "abs"),
    ("regular_importance", 3, 0.75, 16.82, "abs"),
    ("regular_importance", 18, 0.25, 24.52, "abs"),
    ("regular_importance", 18, 0.5, 36.76, "abs"),
    ("regular_importance", 18, 0.75, 50.60, "abs"),
    ("regular_importance", 23, 0.25, 2141.0, "collapse"),
    ("last_row", 18, 1.0, 9.8e6, "collapse"),
    ("last_row", 3, 1.0, 8.7e6, "collapse"),
    ("last_row", 11, 1.0, 304e3, "collapse"),
]

ABS_TOL = 0.1  # the BASELINE.md north star
COLLAPSE_FACTOR = 2.0


def check(result: dict, golden=None) -> tuple:
    """-> (rows, n_failed). ``result`` is a SweepResult.to_json() dict; golden
    cells whose (method, layer, ratio) the sweep didn't run are skipped."""
    golden = GOLDEN if golden is None else golden
    axes, ppl = result["axes"], result["ppl"]
    # channel sweeps have no ratio axis, initial sweeps no method axis; their
    # results share the avg_ppl_results.json filename, so fall through to the
    # "no golden cells" guidance instead of a KeyError
    methods = axes.get("methods") or []
    layers = [int(l) for l in axes.get("layers_of_interest", [])
              if not isinstance(l, str)]  # initial sweeps mix in magic strings
    ratios = [float(r) for r in axes.get("ratios", [])]
    rows, failed = [], 0
    for method, layer, ratio, want, kind in golden:
        if method not in methods or layer not in layers or ratio not in ratios:
            continue
        got = float(ppl[methods.index(method)][layers.index(layer)]
                    [ratios.index(ratio)])
        if kind == "abs":
            ok = abs(got - want) <= ABS_TOL
        else:
            ok = want / COLLAPSE_FACTOR <= got <= want * COLLAPSE_FACTOR
        failed += not ok
        rows.append({"method": method, "layer": layer, "ratio": ratio,
                     "got": got, "want": want, "kind": kind, "ok": bool(ok)})
    return rows, failed


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print(__doc__)
        return 2
    with open(argv[0]) as f:
        result = json.load(f)
    rows, failed = check(result)
    if not rows:
        print("no golden cells match this sweep's axes — run "
              "configs/qwen_baseline_table.json (layers [22, 18, 3, 23, 11], "
              "ratios [0, 0.25, 0.5, 0.75, 1])")
        return 2
    for r in rows:
        mark = "ok  " if r["ok"] else "FAIL"
        tol = f"±{ABS_TOL}" if r["kind"] == "abs" else f"x{COLLAPSE_FACTOR}"
        print(f"{mark} {r['method']:<20} layer {r['layer']:>2} "
              f"r={r['ratio']:<4} got {r['got']:<12.4g} "
              f"want {r['want']:<10.4g} ({tol})")
    print(f"{len(rows) - failed}/{len(rows)} anchors reproduced"
          + ("" if not failed else f"; {failed} FAILED"))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
