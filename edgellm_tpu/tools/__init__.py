"""Operational tooling around the framework core.

- ``prepare_wikitext``: reference-exact corpus tokenization (join + tokenize).
- ``pallas_probe``: on-silicon codec parity + throughput (the bench's
  ``"pallas"`` block) and the differential-scan timing harness.
- ``wb_preflight``: AOT memory-analysis window-batch preflight (never OOM the
  device allocator).
- ``check_reproduction``: machine-check a sweep against the reference's
  golden PPL anchors (the REPRODUCING.md north star).
"""
