"""Offline data/corpus preparation utilities."""
