"""Silicon probe for the attention kernels: Pallas (whole-S / blocked) vs
XLA's fused ``jax.nn.dot_product_attention`` at the model shapes the sweeps
actually run.

Mirrors the codec probe's phase-robust estimator (``pallas_probe``): each
variant is timed with the differential scan, measurements are taken in
interleaved (pallas, xla) pairs, and the reported speedup is the median of
per-pair ratios — immune to the axon tunnel's slow phase drift, which once
read the same codec at 1.4x and 0.75x in back-to-back sequential runs.

Reference workload being covered: both Pythia experiments evaluate at
window = 2048 (``Experiments/Pythia-70M/initial_exp.py:86``,
``last_row_exp.py:72-74``) — the shape that motivated the blocked kernel.
"""
from __future__ import annotations

import json
from statistics import median

import numpy as np

from .pallas_probe import _ScanTimer

#: (name, batch, heads, kv_heads, seq, head_dim) — the sweep shapes:
#: pythia window-2048 (reference's own evaluation window), the flagship ring
#: config's full-sequence shape, llama-1b at the standard window, and the
#: two whole-S shapes already validated in round 4 (regression guards).
SHAPES = [
    ("pythia-70m_s2048", 8, 8, 8, 2048, 64),
    ("qwen2-0.5b_s2048", 8, 14, 2, 2048, 64),
    ("llama-3.2-1b_s512", 32, 32, 8, 512, 64),
    ("qwen2-0.5b_s512", 64, 14, 2, 512, 64),
    ("qwen2-1.5b_s512", 32, 12, 2, 512, 128),
]


def probe_shape(name: str, b: int, h: int, kv: int, s: int, hd: int,
                *, pool: int = 2, reps: int = 3, stats: bool = False,
                seed: int = 0) -> dict:
    """Time kernel vs XLA attention at one shape -> result dict."""
    import jax
    import jax.numpy as jnp

    from ..models import flash_attention as fa

    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(pool, b, s, h, hd)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(pool, b, s, kv, hd)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(pool, b, s, kv, hd)), jnp.bfloat16)
    tree = (q, k, v)

    plan = fa._shape_plan(s, h, kv, hd)
    if plan is None:
        return {"shape": name, "plan": None}

    if stats:
        def pallas_body(x):
            out, st = fa.causal_attention_stats(*x, interpret=False, plan=plan)
            return (out, *st)
    else:
        def pallas_body(x):
            return fa.causal_attention(*x, interpret=False, plan=plan)

    def xla_body(x):
        return jax.nn.dot_product_attention(*x, is_causal=True)

    import math

    tp = _ScanTimer(pallas_body, tree, pool)
    tx = _ScanTimer(xla_body, tree, pool)
    # drop pairs with an unresolved (NaN) differential, exactly like the
    # codec probe's paired_medians — a median over NaNs is undefined and a
    # NaN field would make the bench sidecar spec-invalid JSON
    pairs = [(p, x) for p, x in
             ((tp.differential(), tx.differential()) for _ in range(reps))
             if math.isfinite(p) and math.isfinite(x)]
    result = {"shape": name, "dims": [b, h, kv, s, hd], "plan": list(plan),
              "stats": stats}
    if not pairs:  # every rep stayed inside the jitter band: no rate fields
        return result
    p_s = median(p for p, _ in pairs)
    x_s = median(x for _, x in pairs)
    ratio = median(x / p for p, x in pairs)
    # full-square accounting (the kernels compute and mask the causal upper
    # triangle — measured faster than any skip; see flash_attention.py)
    flops = 4.0 * b * h * s * s * hd
    result.update({
        "pallas_us": round(p_s * 1e6, 1), "xla_us": round(x_s * 1e6, 1),
        "pallas_tflops": round(flops / p_s / 1e12, 1),
        "xla_tflops": round(flops / x_s / 1e12, 1),
        "speedup_vs_xla": round(ratio, 2),
    })
    return result


def probe_all(*, stats: bool = False, shapes=None) -> list[dict]:
    out = []
    for args in (shapes or SHAPES):
        out.append(probe_shape(*args, stats=stats))
        print(json.dumps(out[-1]), flush=True)
    return out


def main():
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--stats", action="store_true",
                    help="time the stats-capture variants instead")
    ap.add_argument("--shape", default=None,
                    help="probe only the named shape")
    a = ap.parse_args()
    shapes = [t for t in SHAPES if a.shape is None or t[0] == a.shape]
    probe_all(stats=a.stats, shapes=shapes)


if __name__ == "__main__":
    main()
