"""Measure the accuracy cost of ``ring_selective_int4(mode="local")`` vs the
exact ``mode="global"`` at the flagship ring config's shape.

``mode="local"`` is the wire-optimal variant users actually deploy (static
per-shard payloads equal to the dense codec's bytes); its selected token SET
is the per-shard restriction of a rank-balanced selection rather than the
dense global argsort, so its NLL is close to but not bit-equal with the
global mode (``codecs/ring_codecs.py``). This tool puts a NUMBER on "close":
it runs both modes through the full ``SplitRingRuntime`` at the
``configs/split5b_qwen_ring_selective.json`` shape (qwen2-0.5b, cut 11,
S=2048, n_seq=4) on a spoofed stage x seq CPU mesh with synthesized weights
and reports per-ratio |dNLL|.

Measured 2026-07-31 (synthetic bf16 weights, 2 windows, seed 0):
|dNLL| <= 8.4e-4 at ratio 0.25 and <= 1.6e-3 at ratio 0.5 — two orders of
magnitude below the reference's own reported PPL deltas between adjacent
ratios (BASELINE.md). The bound asserted in ``tests/test_ring_codecs.py``
(0.02) is >10x the worst measured value.
"""
from __future__ import annotations

import json


def measure(model: str = "qwen2-0.5b", seq: int = 2048, n_seq: int = 4,
            cut: int = 11, ratios=(0.25, 0.5), windows: int = 2,
            seed: int = 0) -> list[dict]:
    from ..utils.spoof import spoof_cpu_devices

    spoof_cpu_devices(2 * n_seq)

    import jax
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ..models import PRESETS, init_params
    from ..models.transformer import nll_from_logits
    from ..codecs.ring_codecs import ring_selective_int4
    from ..parallel.ring import SplitRingRuntime, importance_sp

    cfg = PRESETS[model]
    params = init_params(cfg, jax.random.key(seed), dtype=jnp.bfloat16)
    mesh = Mesh(np.asarray(jax.devices()[:2 * n_seq]).reshape(2, n_seq),
                ("stage", "seq"))
    # one runtime per (ratio, mode), HOISTED out of the window loop: each
    # SplitRingRuntime owns its own jitted closure, so rebuilding per window
    # would re-trace and re-compile the full 24-layer S=2048 graph
    runtimes = {
        (ratio, mode): SplitRingRuntime(
            cfg, (cut,),
            (ring_selective_int4(ratio, "bf16", n_seq=n_seq, mode=mode),),
            mesh)
        for ratio in ratios for mode in ("global", "local")}
    placed = {key: rt.place_params(params) for key, rt in runtimes.items()}
    rng = np.random.default_rng(seed)
    out = []
    for w in range(windows):
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, seq)))
        imp = importance_sp(cfg, params, ids, mesh, "last_row")[cut, 0]
        for ratio in ratios:
            nll = {}
            for mode in ("global", "local"):
                rt = runtimes[(ratio, mode)]
                logits = rt.forward(placed[(ratio, mode)], ids,
                                    hop_importance=[imp])
                nll[mode] = float(nll_from_logits(logits, ids))
            rec = {"window": w, "ratio": ratio, "nll_global": nll["global"],
                   "nll_local": nll["local"],
                   "dnll": abs(nll["local"] - nll["global"])}
            print(json.dumps(rec), flush=True)
            out.append(rec)
    return out


if __name__ == "__main__":
    rows = measure()
    worst = max(r["dnll"] for r in rows)
    print(json.dumps({"worst_dnll": worst}))
