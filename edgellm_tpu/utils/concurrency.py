"""Concurrency contracts for the host serve plane.

Two tiny primitives that threadlint (``edgellm_tpu/lint/threadlint.py``,
rules EG101-EG104) keys off:

- ``@guarded_by("_lock", fields=[...])`` declares which attributes of a
  class may only be written while ``self._lock`` is held.  The decorator
  is metadata-only (zero runtime cost); the static analyzer enforces it
  package-wide, and classes that merely own a ``threading.Lock`` are
  auto-discovered even without the decorator.
- ``acquire_in_order(*locks)`` acquires several locks in a single global
  deterministic order (ascending ``id()``), which makes symmetric
  multi-instance critical sections (A.merge_from(B) racing
  B.merge_from(A)) deadlock-free.  threadlint treats a ``with
  acquire_in_order(...)`` block as one atomic, correctly-ordered
  acquisition and never raises EG102 for it.

Stdlib-only: the obs/ and serve/ modules import this and must stay
importable without jax.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, Sequence, Tuple

__all__ = ["guarded_by", "acquire_in_order"]


def guarded_by(lock_attr: str, *, fields: Sequence[str]) -> Callable[[type], type]:
    """Class decorator declaring a lock-discipline contract.

    ``@guarded_by("_lock", fields=["count", "_values"])`` means: every
    write to ``self.count`` / ``self._values`` outside ``__init__`` must
    happen inside a ``with self._lock`` (or ``acquire_in_order``) block.
    Enforced statically by graphlint rule EG101; at runtime this only
    attaches ``__guarded_by__`` metadata for introspection.
    """
    contract: Dict[str, Any] = {"lock": lock_attr, "fields": tuple(fields)}

    def _decorate(cls: type) -> type:
        setattr(cls, "__guarded_by__", contract)
        return cls

    return _decorate


@contextmanager
def acquire_in_order(*locks: Any) -> Iterator[None]:
    """Acquire ``locks`` in ascending ``id()`` order, release in reverse.

    Duplicate lock objects are acquired once (safe for the self-merge
    ``h.merge_from(h)`` spelling even with non-reentrant locks).  Because
    every thread sorts by the same global key, two threads taking the
    same pair of locks can never deadlock on each other — the fix for
    the EG102 class of bugs (see ``Histogram.merge_from``).
    """
    unique: Dict[int, Any] = {}
    for lock in locks:
        unique.setdefault(id(lock), lock)
    ordered: Tuple[Any, ...] = tuple(unique[key] for key in sorted(unique))
    taken: List[Any] = []
    try:
        for lock in ordered:
            lock.acquire()
            taken.append(lock)
        yield
    finally:
        for lock in reversed(taken):
            lock.release()
