"""Spoof an n-device CPU platform for sharding code on any host.

One definition of the recipe the multi-chip dry-run, the ring-mode
measurement tool, and the test suite all rely on: force
``--xla_force_host_platform_device_count`` (replacing any prior value) and
redirect jax to CPU. Safe to call even when jax was pre-imported on another
platform (sitecustomize): backends are lazy, so the redirect works as long
as no backend has initialized yet.
"""
from __future__ import annotations

import os
import re


def spoof_cpu_devices(n_devices: int) -> None:
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
