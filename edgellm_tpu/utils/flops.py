"""Analytic FLOP accounting for the sweep workloads (MFU / tokens-per-second).

The reference reports only wall-clock progress bars (``qwen2-0.5B_experiment
.ipynb`` cell 12, ~16 s/chunk); here the bench derives model FLOPs from the
architecture so throughput can be stated as MFU against the chip's bf16 peak.
Counts follow the standard convention: a multiply-add is 2 FLOPs; matmuls only
(norms/softmax/elementwise are bandwidth, not FLOP, bound on TPU).
"""
from __future__ import annotations

from ..models.configs import ModelConfig


def layer_flops_per_token(cfg: ModelConfig, seq_len: int) -> float:
    """FLOPs one decoder block spends per token at sequence length ``seq_len``.

    Weight matmuls: q/k/v/o projections + the MLP (SwiGLU = 3 mats, GELU = 2).
    Attention: QK^T and PV are each 2*S*hd per head per query token on average
    S/2 visible keys under causal masking — counted at the full S upper bound
    the dense-softmax path actually executes (no causal-skip in XLA's einsum).
    """
    d, hd = cfg.hidden_size, cfg.head_dim
    h, kv = cfg.num_heads, cfg.num_kv_heads
    proj = 2 * (d * h * hd + 2 * d * kv * hd + h * hd * d)
    mlp_mats = 3 if cfg.family != "gpt_neox" else 2
    mlp = 2 * mlp_mats * cfg.hidden_size * cfg.intermediate_size
    attn = 2 * 2 * seq_len * h * hd  # QK^T + PV, dense causal
    return float(proj + mlp + attn)


def unembed_flops_per_position(cfg: ModelConfig) -> float:
    """Final-norm + LM-head matmul FLOPs for one scored position."""
    return float(2 * cfg.hidden_size * cfg.vocab_size)


def token_sweep_flops_per_chunk(
    cfg: ModelConfig,
    seq_len: int,
    tail: int,
    n_methods: int,
    layers_of_interest,
    n_ratios: int,
    n_zero_ratios: int = 0,
) -> float:
    """Model FLOPs the restructured token sweep performs for ONE evaluation
    window — the work actually executed, the honest numerator for MFU. The
    reference performs strictly more (a full forward incl. full unembed per
    combination, ``Qwen2-0.5B/main.py:170-178``).

    Mirrors ``run_token_sweep``'s round-4 executables exactly:

    - ``n_zero_ratios > 0`` (a ``DEDUP_ZERO_CODECS`` codec): the stats forward
      runs ALL layers and its final hidden is tail-scored ONCE — that single
      extra unembed IS the method- and layer-independent fp baseline; no
      baseline suffix forward exists anymore;
    - ``n_zero_ratios == 0``: no baseline is needed, so the stats forward
      stops at the deepest layer of interest;
    - per (method, layer, nonzero ratio): a layer suffix from the boundary
      plus a ``tail``-position unembed.
    """
    per_layer = layer_flops_per_token(cfg, seq_len)
    tail = min(tail, seq_len - 1)
    unembed = unembed_flops_per_position(cfg) * tail
    if n_zero_ratios > 0:
        stats_fwd = cfg.num_layers * per_layer * seq_len + unembed
    else:
        stats_fwd = (max(int(l) for l in layers_of_interest) + 1) \
            * per_layer * seq_len
    suffix = 0.0
    n_suffixes = n_methods * (n_ratios - n_zero_ratios)
    for layer in layers_of_interest:
        suffix_layers = cfg.num_layers - int(layer) - 1
        suffix += n_suffixes * (suffix_layers * per_layer * seq_len + unembed)
    return stats_fwd + suffix
