"""Shared utilities: profiling/tracing helpers."""
from .profiling import trace, timed, throughput

__all__ = ["trace", "timed", "throughput"]
