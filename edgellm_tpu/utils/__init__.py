"""Shared utilities: profiling/tracing helpers, the host-side clock protocol."""
from .clock import MONOTONIC, Clock, FakeClock, sequence_clock
from .profiling import trace, timed, throughput

__all__ = ["trace", "timed", "throughput",
           "Clock", "MONOTONIC", "FakeClock", "sequence_clock"]
