"""Tracing and timing helpers (SURVEY.md section 5: the reference's only
observability is tqdm progress bars; here: real XLA traces + wall-clock helpers).

``trace("/tmp/trace")`` wraps ``jax.profiler.trace`` — view the result with
TensorBoard or Perfetto to see per-op device time, including the ``ppermute``
boundary transfers and Pallas codec kernels. ``timed``/``throughput`` give
honest wall-clock numbers by blocking on device completion.
"""
from __future__ import annotations

import contextlib
import time

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA profiler trace for the enclosed block."""
    with jax.profiler.trace(log_dir):
        yield


def _block(x):
    return jax.tree_util.tree_map(
        lambda a: a.block_until_ready() if hasattr(a, "block_until_ready") else a, x)


def timed(fn, *args, warmup: int = 1, iters: int = 10, **kwargs):
    """(mean seconds per call, last result); compiles/warms up first."""
    result = None
    for _ in range(max(warmup, 0)):
        result = _block(fn(*args, **kwargs))
    t0 = time.monotonic()
    for _ in range(iters):
        result = _block(fn(*args, **kwargs))
    return (time.monotonic() - t0) / iters, result


def throughput(fn, *args, tokens: int, **kwargs) -> dict:
    """Tokens/second for a step processing ``tokens`` tokens."""
    sec, _ = timed(fn, *args, **kwargs)
    return {"s_per_step": sec, "tokens_per_s": tokens / sec}
