"""Tracing and timing helpers (SURVEY.md section 5: the reference's only
observability is tqdm progress bars; here: real XLA traces + wall-clock helpers).

``trace("/tmp/trace")`` wraps ``jax.profiler.trace`` — view the result with
TensorBoard or Perfetto to see per-op device time, including the ``ppermute``
boundary transfers and Pallas codec kernels. ``timed``/``throughput`` give
honest wall-clock numbers by blocking on device completion.
"""
from __future__ import annotations

import contextlib
import time

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Deprecated shim over :func:`edgellm_tpu.obs.tracing.trace_capture`
    (same contract: capture an XLA profiler trace for the enclosed block,
    degrade to a warning when the profiler cannot start). New code should
    use ``obs.tracing.trace_capture`` directly — it composes with the host
    span tracer and the ``--trace-out`` Chrome trace export."""
    import warnings

    from ..obs.tracing import trace_capture

    warnings.warn("utils.profiling.trace is deprecated; use "
                  "edgellm_tpu.obs.tracing.trace_capture",
                  DeprecationWarning, stacklevel=3)
    with trace_capture(log_dir):
        yield


def _block(x):
    return jax.tree_util.tree_map(
        lambda a: a.block_until_ready() if hasattr(a, "block_until_ready") else a, x)


def timed(fn, *args, warmup: int = 1, iters: int = 10, **kwargs):
    """(mean seconds per call, last result); compiles/warms up first."""
    result = None
    for _ in range(max(warmup, 0)):
        result = _block(fn(*args, **kwargs))
    t0 = time.monotonic()
    for _ in range(iters):
        result = _block(fn(*args, **kwargs))
    return (time.monotonic() - t0) / iters, result


def throughput(fn, *args, tokens: int, **kwargs) -> dict:
    """Tokens/second for a step processing ``tokens`` tokens."""
    sec, _ = timed(fn, *args, **kwargs)
    return {"s_per_step": sec, "tokens_per_s": tokens / sec}


def measure_peak_tflops(sizes=(4096, 6144), pool: int = 4,
                        attempts: int = 3, cap: float = None):
    """The chip's ACHIEVABLE bf16 matmul peak (TF/s): best sustained rate of a
    few large square matmuls, measured with the differential-scan harness that
    cancels the axon tunnel's fixed per-call cost. This is the honest MFU
    denominator to report next to the spec-sheet peak — prior measurement on
    the tunneled v5e put it near 150 TF/s vs the 197 spec.

    Returns None if no attempt lands in a physically sane band (the tunnel's
    call noise can swallow a short differential; callers must not divide by a
    garbage peak)."""
    import numpy as np
    import jax.numpy as jnp

    from ..tools.pallas_probe import _timed_scan

    rng = np.random.default_rng(0)
    best = None
    for n in sizes:
        a = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32)
                        ).astype(jnp.bfloat16)
        bs = jnp.asarray(rng.standard_normal((pool, n, n)).astype(np.float32)
                         ).astype(jnp.bfloat16)
        # MEDIAN of the sane attempts: a single differential can land +-15%
        # on the tunnel (round-4 observed 184-240 TF/s for the same chip),
        # and the MFU-vs-measured ratio is only as honest as this denominator.
        # The sanity band is PHYSICAL (no accelerator does 2000 bf16 TF/s),
        # deliberately NOT the ``cap`` env knob: banding on the knob would
        # reject every honest sample on a chip faster than the configured
        # spec and leave the denominator knob-bound — the median already
        # rejects a single noise outlier inside the physical band.
        vals = []
        for _ in range(attempts):
            t = _timed_scan(
                lambda b_mat: jnp.dot(a, b_mat, preferred_element_type=jnp.float32),
                bs, pool, lengths=(32, 256))
            tflops = 2.0 * n ** 3 / t / 1e12
            if 10.0 < tflops < 2000.0:
                vals.append(tflops)
        if vals:
            import statistics

            best = max(best or 0.0, statistics.median(vals))
    # the returned value is the measurement itself — neither clamped to nor
    # banded by the ``cap`` env knob (kept for API compatibility; a knob
    # that disagrees with the hardware must not shape the MFU denominator).
    return best
