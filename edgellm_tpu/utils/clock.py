"""One clock protocol for every host-side time consumer.

Before this module, each host-side controller grew its own injectable time
source: ``Watchdog``/``RecoveryConfig`` took a ``Callable[[], float]``,
``LinkHealth`` another, the eval harness a ``_clock`` kwarg, and every test
file re-invented its own fake (an attribute-mutated callable here, an
``iter(...).__next__`` there). The contract was always the same —
*monotonic seconds as a zero-arg callable* — so it lives here once:

- :class:`Clock` — the protocol (``() -> float``). ``time.monotonic``
  satisfies it; so does any test double.
- :data:`MONOTONIC` — the production default, aliased so call sites read as
  intent (``clock: Clock = MONOTONIC``) instead of an import of ``time``.
- :class:`FakeClock` — the shared test double: starts at 0.0 (or
  ``start``), returns the same instant until ``advance``/``set_time`` move
  it. Deterministic controllers (watchdog deadlines, LinkHealth dwell,
  breaker reset timeouts, brownout hysteresis) are all driven by it in
  tests and by :func:`time.monotonic` in production, with no code diff.
- :func:`sequence_clock` — a clock that replays an explicit list of
  instants, one per read, for tests that assert *how many times* the clock
  is consulted (the watchdog reads twice per passing check).

Nothing here imports anything from the package — every layer may depend on
it without cycles.
"""
from __future__ import annotations

import time
from typing import Iterable, Protocol, runtime_checkable

__all__ = ["Clock", "MONOTONIC", "FakeClock", "sequence_clock"]


@runtime_checkable
class Clock(Protocol):
    """Zero-arg callable returning monotonic seconds."""

    def __call__(self) -> float: ...


#: the production clock: monotonic, immune to wall-clock steps/NTP slew
MONOTONIC: Clock = time.monotonic


class FakeClock:
    """A clock that only moves when the test says so.

    Reads are free and repeatable; :meth:`advance` moves time forward by a
    delta, :meth:`set_time` jumps to an absolute instant (both refuse to go
    backwards — the protocol promises monotonicity, and a controller that
    silently tolerated regressing time would hide real bugs).
    """

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    @property
    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError(f"cannot advance a monotonic clock by {dt}")
        self._now += float(dt)
        return self._now

    def set_time(self, t: float) -> float:
        if t < self._now:
            raise ValueError(
                f"cannot move a monotonic clock backwards "
                f"({self._now} -> {t}); use a fresh FakeClock")
        self._now = float(t)
        return self._now


def sequence_clock(instants: Iterable[float]) -> Clock:
    """A clock that replays ``instants`` in order, one per read.

    For tests that pin the exact read schedule (e.g. the watchdog reads the
    clock once for the elapsed check and once to re-arm). Running out of
    instants raises ``StopIteration`` — a test consuming more reads than it
    scripted is a test bug, surfaced loudly."""
    it = iter(instants)
    return lambda: float(next(it))
