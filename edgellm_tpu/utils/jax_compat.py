"""Version shims for the jax APIs the parallel layer leans on.

The runtimes are written against the current jax surface (top-level
``jax.shard_map`` with ``check_vma`` and varying-mode ``lax.pcast``), but the
deployment images pin older releases where ``shard_map`` still lives in
``jax.experimental.shard_map`` with the ``check_rep`` spelling and no vma
typing at all. Every shard_map user in the package (and the tests that build
their own shard_maps) imports from here so the whole repo tracks exactly one
compatibility decision.
"""
from __future__ import annotations

import jax

try:  # jax >= 0.6: top-level export, vma typing, check_vma kwarg
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the replication/vma check flag translated to
    whatever the installed jax calls it (``check_vma`` vs ``check_rep``)."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_CHECK_KW: check_vma})


def axis_size(axis_name) -> int:
    """``lax.axis_size`` where it exists; on older jax the size of a mapped
    axis is recoverable as ``psum(1)`` over it (constant-folded, not a
    collective — the literal is replicated so the sum is the axis size)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast_varying(x, axes):
    """``lax.pcast(x, axes, to="varying")`` where vma typing exists; identity
    on jax versions whose shard_map has no vma types to promote (the cast is
    purely a type-system operation — no data movement either way)."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axes, to="varying")
