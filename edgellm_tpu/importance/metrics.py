"""Attention-statistic token-importance metrics, vectorized over layers.

The reference computes these from full (B, H, S, S) eager attention maps produced by
a *second* model instance (``/root/reference/Experiments/Qwen2-0.5B/main.py:21-98``,
``Experiments/Pythia-70M/last_row_exp.py:9-45``, ``initial_exp.py:27-72``). Every
metric only ever consumes two reductions of the map — the column-wise mean (average
attention *received* per key position) and the last query row — so here they operate
on the (L, B, H, S) reduced statistics captured in the main forward pass
(:class:`edgellm_tpu.models.transformer.AttnStats`), eliminating both the second
model and the O(S^2) HBM traffic.

Shape convention: ``col_mean``/``last_row`` are (L, B, H, S); per-layer importance
outputs are (L, B, S); single aggregated outputs are (B, S).
"""
from __future__ import annotations

import jax.numpy as jnp

#: methods accepted by ``importance_per_layer`` — the reference's four
#: (``Qwen2-0.5B/main.py:46-92``).
ATTENTION_METHODS = (
    "regular_importance",
    "weighted_importance",
    "last_row",
    "aggregate_till",
)


def regular_importance(col_mean: jnp.ndarray) -> jnp.ndarray:
    """Head-mean of the column-wise attention mean, per layer.

    Matches ``mean(heads) -> mean(queries)`` of ``main.py:46-56`` (the two means
    commute; the query mean is already folded into ``col_mean``).
    """
    return jnp.mean(col_mean, axis=2)


def weighted_importance(col_mean: jnp.ndarray, head_weights: jnp.ndarray) -> jnp.ndarray:
    """Per-head column means combined with LRP head weights (``main.py:57-78``).

    ``head_weights``: (L, H), typically normalized to sum 1 per layer (the
    reference's 24x14 LRP output). The reference takes a weighted *sum* over heads
    (no extra normalization), then the column mean — reproduced exactly.
    """
    return jnp.einsum("lbhs,lh->lbs", col_mean, head_weights)


def last_row_importance(last_row: jnp.ndarray) -> jnp.ndarray:
    """Head-mean of the final query row (``main.py:80-86``)."""
    return jnp.mean(last_row, axis=2)


def aggregate_till(col_mean: jnp.ndarray) -> jnp.ndarray:
    """Running mean of regular importance over layers 0..l (``main.py:87-92``)."""
    reg = regular_importance(col_mean)  # (L, B, S)
    counts = jnp.arange(1, reg.shape[0] + 1, dtype=reg.dtype)[:, None, None]
    return jnp.cumsum(reg, axis=0) / counts


def importance_per_layer(stats, method: str,
                         head_weights: jnp.ndarray | None = None) -> jnp.ndarray:
    """Dispatch one of the four reference methods -> (L, B, S) importance."""
    if method == "regular_importance":
        return regular_importance(stats.col_mean)
    if method == "weighted_importance":
        if head_weights is None:
            raise ValueError("weighted_importance requires head_weights (L, H)")
        return weighted_importance(stats.col_mean, head_weights)
    if method == "last_row":
        return last_row_importance(stats.last_row)
    if method == "aggregate_till":
        return aggregate_till(stats.col_mean)
    raise ValueError(f"unknown method {method!r}; options: {ATTENTION_METHODS}")


def aggregate_upto(col_mean: jnp.ndarray, k: int) -> jnp.ndarray:
    """Mean of regular importance over layers 0..k inclusive (``initial_exp.py:31-40``,
    the ``'aggregate upto 2'`` ordering with k=2)."""
    return jnp.mean(regular_importance(col_mean)[: k + 1], axis=0)


def maximum_aggregation(col_mean: jnp.ndarray, k: int = None) -> jnp.ndarray:
    """Elementwise max of per-layer regular importance (``initial_exp.py:41-51``;
    the reference maxes over layers 0..2, i.e. k=2)."""
    reg = regular_importance(col_mean)
    upto = reg if k is None else reg[: k + 1]
    return jnp.max(upto, axis=0)


def ordering_from_importance(importance: jnp.ndarray) -> jnp.ndarray:
    """Ascending stable argsort — least-important positions first
    (``initial_exp.py:39,50,70``)."""
    return jnp.argsort(importance, axis=-1)
