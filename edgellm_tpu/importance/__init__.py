"""Token- and head-importance scoring.

``metrics`` — attention-statistic metrics (column-mean, last-row, aggregates) that
consume the reduced :class:`~edgellm_tpu.models.transformer.AttnStats` captured by
the model forward, replacing the reference's second eager-attention model instance.
``relevance`` — LRP-style attention-head relevance (the reference's ``lxt`` path) as
explicit JAX vjp rules.
"""
from .metrics import (
    ATTENTION_METHODS,
    regular_importance,
    weighted_importance,
    last_row_importance,
    aggregate_till,
    importance_per_layer,
    aggregate_upto,
    maximum_aggregation,
    ordering_from_importance,
)

__all__ = [
    "ATTENTION_METHODS",
    "regular_importance",
    "weighted_importance",
    "last_row_importance",
    "aggregate_till",
    "importance_per_layer",
    "aggregate_upto",
    "maximum_aggregation",
    "ordering_from_importance",
]
