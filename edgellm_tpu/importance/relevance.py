"""LRP attention-head relevance — the reference's ``lxt`` path, in functional JAX.

The reference monkey-patches the torch Qwen2 module classes with ``lxt.efficient``
LRP rules, hooks every layer's softmaxed attention probabilities with
``retain_grad``, seeds the backward pass with the max last-position logit
(``max_logits.backward(max_logits)``), and scores each head by the total
attention-times-gradient mass ``sum(A * dA)``
(``/root/reference/Experiments/Relevance/main.py:21-128``).

JAX has no modules to patch; the same semantics are explicit here:

- **LRP rules as custom gradients**: normalization layers propagate relevance as
  if the normalizer were a constant (``stop_gradient`` on the rsqrt factor —
  lxt's identity rule for RMSNorm/LayerNorm), and the SwiGLU elementwise product
  splits relevance equally between its factors (uniform rule, a ``custom_vjp``).
  These are what ``lxt.efficient.monkey_patch`` rewires in ``Qwen2RMSNorm`` /
  ``Qwen2MLP`` (``Notebooks/attention_head_weights_via_relevance.ipynb`` cell 4).
- **retain_grad equivalent**: attention probabilities are materialized with an
  additive zero "offset" input per layer; one ``jax.vjp`` against the offsets
  yields exactly ``dSeed/dA`` alongside ``A`` from the same pass.
- **Accumulation/normalization**: per (layer, head) relevance summed over chunks,
  then normalized per layer to sum 1 (signed sums, zero-sum guarded with the
  reference's 1e-9 divisor — ``Relevance/main.py:111-118``).

The gradient-checkpointing the reference needs for memory
(``Relevance/main.py:63``) is ``jax.checkpoint`` on the per-layer scan body.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..models.configs import ModelConfig
from ..models.transformer import apply_rotary, embed, precompute_rope


@jax.custom_vjp
def uniform_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Elementwise product with the LRP uniform rule: relevance splits 50/50
    between the factors (gradient*input of each factor gets half the output's)."""
    return a * b


def _uniform_mul_fwd(a, b):
    return a * b, (a, b)


def _uniform_mul_bwd(res, g):
    a, b = res
    return 0.5 * g * b, 0.5 * g * a


uniform_mul.defvjp(_uniform_mul_fwd, _uniform_mul_bwd)


def _rmsnorm_lrp(x, scale, eps):
    xf = x.astype(jnp.float32)
    denom = jax.lax.stop_gradient(jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps))
    return (xf * denom) * scale


def _layernorm_lrp(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    denom = jax.lax.stop_gradient(jax.lax.rsqrt(jnp.var(xf, -1, keepdims=True) + eps))
    return (xf - mu) * denom * scale + bias


def _lrp_attention(cfg: ModelConfig, lp: dict, x, cos, sin, probs_offset):
    """Eager attention returning the (differentiable) probability tensor.

    ``probs_offset`` (B, H, S, S) is added to the post-softmax probabilities; the
    caller passes zeros and differentiates against it — the JAX equivalent of
    ``retain_grad`` on the probs (``Relevance/main.py:36-38``).
    """
    b, s, d = x.shape
    h, kv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    q = (x @ lp["wq"]).reshape(b, s, h, hd)
    k = (x @ lp["wk"]).reshape(b, s, kv, hd)
    v = (x @ lp["wv"]).reshape(b, s, kv, hd)
    if "bq" in lp:
        q = q + lp["bq"].reshape(h, hd)
        k = k + lp["bk"].reshape(kv, hd)
        v = v + lp["bv"].reshape(kv, hd)
    q = apply_rotary(q, cos, sin, cfg.rotary_dim)
    k = apply_rotary(k, cos, sin, cfg.rotary_dim)
    rep = h // kv
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bshd,bthd->bhst", q, k,
                        preferred_element_type=jnp.float32) / jnp.sqrt(
                            jnp.asarray(hd, jnp.float32))
    causal = jnp.tril(jnp.ones((s, s), dtype=bool))
    scores = jnp.where(causal[None, None], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1) + probs_offset
    out = jnp.einsum("bhst,bthd->bshd", probs.astype(x.dtype), v,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    out = out.reshape(b, s, h * hd) @ lp["wo"]
    if "bo" in lp:
        out = out + lp["bo"]
    return out, probs


def _lrp_mlp(cfg: ModelConfig, lp: dict, x):
    if cfg.family == "gpt_neox":
        # the reference's lxt patch list covers Qwen2 (no GELU rule needed for its
        # experiment); GELU keeps its standard gradient here
        hidden = jax.nn.gelu(x @ lp["w_in"] + lp["b_in"], approximate=False)
        return hidden @ lp["w_out"] + lp["b_out"]
    return uniform_mul(jax.nn.silu(x @ lp["w_gate"]), x @ lp["w_up"]) @ lp["w_down"]


def _lrp_block(cfg: ModelConfig, lp: dict, hidden, cos, sin, probs_offset):
    if cfg.family == "gpt_neox":
        attn_in = _layernorm_lrp(hidden, lp["ln1_scale"], lp["ln1_bias"], cfg.norm_eps)
        attn_out, probs = _lrp_attention(cfg, lp, attn_in, cos, sin, probs_offset)
        mlp_in = _layernorm_lrp(hidden, lp["ln2_scale"], lp["ln2_bias"], cfg.norm_eps)
        return hidden + attn_out + _lrp_mlp(cfg, lp, mlp_in), probs
    attn_in = _rmsnorm_lrp(hidden, lp["ln1_scale"], cfg.norm_eps)
    attn_out, probs = _lrp_attention(cfg, lp, attn_in, cos, sin, probs_offset)
    hidden = hidden + attn_out
    mlp_in = _rmsnorm_lrp(hidden, lp["ln2_scale"], cfg.norm_eps)
    return hidden + _lrp_mlp(cfg, lp, mlp_in), probs


def lrp_forward(cfg: ModelConfig, params: dict, input_ids, probs_offsets):
    """ids + per-layer probability offsets -> (logits, stacked probs).

    One ``lax.scan`` over the stacked layers, rematerialized per layer
    (``jax.checkpoint``) so the backward pass recomputes activations instead of
    storing them — the reference's ``gradient_checkpointing_enable``.

    The residual stream is pinned to fp32 regardless of the param dtype: the
    LRP norm rules already emit fp32 (their rsqrt is stop-gradiented in fp32),
    so a bf16 param pytree would otherwise flip the scan carry's dtype
    mid-layer; the reference's relevance run is fp32 torch throughout.
    """
    hidden = embed(params, input_ids).astype(jnp.float32)
    cos, sin = precompute_rope(cfg, input_ids.shape[1])

    @jax.checkpoint
    def body(h, xs):
        lp, off = xs
        h, probs = _lrp_block(cfg, lp, h, cos, sin, off)
        return h, probs

    hidden, probs = jax.lax.scan(body, hidden, (params["layers"], probs_offsets))
    if cfg.family == "gpt_neox":
        post = _layernorm_lrp(hidden, params["final_norm_scale"],
                              params["final_norm_bias"], cfg.norm_eps)
    else:
        post = _rmsnorm_lrp(hidden, params["final_norm_scale"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    logits = jnp.einsum("bsd,dv->bsv", post, head, preferred_element_type=jnp.float32)
    return logits, probs


@functools.lru_cache(maxsize=None)
def _chunk_relevance(cfg: ModelConfig):
    """Jitted: ids -> per-(layer, head) relevance for one chunk."""

    @jax.jit
    def fn(params, ids):
        L, b, s = cfg.num_layers, ids.shape[0], ids.shape[1]
        offsets = jnp.zeros((L, b, cfg.num_heads, s, s), jnp.float32)

        def f(off):
            logits, probs = lrp_forward(cfg, params, ids, off)
            # seed: per-row max logit at the last position; backward(max_logits)
            # uses the value vector itself as the cotangent
            # (Relevance/main.py:87-88) -- kept per-row so batch>1 matches
            return jnp.max(logits[:, -1, :], axis=-1), probs

        (seed, probs), vjp_fn = jax.vjp(f, offsets)
        (grad_off,) = vjp_fn((seed, jnp.zeros_like(probs)))
        return jnp.sum(probs * grad_off, axis=(1, 3, 4))  # (L, H)

    return fn


def run_relevance_extraction(
    cfg: ModelConfig,
    params,
    token_ids: np.ndarray,
    *,
    max_length: int,
    stride: int,
    max_chunks: Optional[int] = None,
    progress=None,
    window_batch: int = 1,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 1000,
    metrics_path: Optional[str] = None,
    stats: Optional[dict] = None,
) -> np.ndarray:
    """Sliding-window accumulation of head relevance -> (L, H) weights,
    normalized per layer to sum 1 (``Relevance/main.py:74-118``). The output is
    the ``head_weights`` input of ``weighted_importance``.

    Same durability and throughput treatment as the sweep drivers: up to
    ``window_batch`` full-length windows share one vjp executable (relevance is
    a plain sum over windows, so batching is exact — the seed is per-row and
    ``_chunk_relevance`` already sums the batch axis); host accumulation is
    pipelined one group behind device submission; an axes-validated checkpoint
    gives exact resume, and chunk throughput (the reference anchor is 2.1 it/s,
    ``BASELINE.md``) lands in ``stats`` (pass a dict) and ``metrics_path``.
    """
    from ..eval.harness import (ResumableDriver, _emit, _iter_window_groups,
                                _run_pipelined)

    fn = _chunk_relevance(cfg)
    axes = {"experiment": "relevance",
            "model": {"family": cfg.family, "num_layers": cfg.num_layers,
                      "hidden_size": cfg.hidden_size, "num_heads": cfg.num_heads,
                      "vocab_size": cfg.vocab_size},
            "max_length": int(max_length), "stride": int(stride)}
    rd = ResumableDriver(checkpoint_path, axes, checkpoint_every)
    total = (np.asarray(rd.state["total"]) if rd.state is not None
             else np.zeros((cfg.num_layers, cfg.num_heads)))

    def submit_group(group):
        ids = jnp.asarray(np.concatenate([c.input_ids for c in group]))
        return group, fn(params, ids)

    def drain_group(rec):
        group, dev = rec
        total[...] += np.asarray(dev, np.float64)
        if progress:
            progress(group[-1].index)
        if rd.advance(group):
            rd.save({"total": total.tolist()})
            _emit(metrics_path, {"chunk": group[-1].index, "chunks": rd.chunks,
                                 "it_per_s": rd.chunks / max(rd.wall(), 1e-9)})

    _run_pipelined(
        _iter_window_groups(token_ids, max_length, stride,
                            window_batch=window_batch,
                            start_chunk=rd.start_chunk,
                            max_count=rd.remaining(max_chunks)),
        submit_group, drain_group)
    wall = rd.wall()  # cumulative across resumes
    rd.save({"total": total.tolist()})
    if stats is not None:
        stats.update(chunks=rd.chunks, wall_s=wall,
                     it_per_s=rd.chunks / max(wall, 1e-9))
    _emit(metrics_path, {"final": True, "chunks": rd.chunks, "wall_s": wall,
                         "it_per_s": rd.chunks / max(wall, 1e-9)})
    layer_sum = total.sum(axis=1, keepdims=True)
    denom = np.where(layer_sum != 0, layer_sum, 1e-9)
    return total / denom
