// Host-side boundary-codec pack/unpack — the C++ twin of
// edgellm_tpu/codecs/packing.py (contiguous-half nibble layout, contiguous-
// quarter ternary layout).
//
// Role in the framework: (1) an implementation-independent oracle for the wire
// format (the Python tests cross-check the JAX/Pallas packers against this
// library bit-for-bit); (2) the host-side codec for boundary payloads that
// leave the accelerator fabric (DCN / file spills), where packing on-CPU avoids
// a device round-trip. The reference has no native code at all (SURVEY.md
// section 2); this is framework infrastructure, not a port.
//
// Plain-C ABI so Python binds via ctypes (no pybind11 in this environment).
#include <cstdint>
#include <cstring>
#include <cmath>
#include <algorithm>

extern "C" {

// fp32 (n_tokens, dim) -> per-token symmetric int4: packed (n_tokens, dim/2)
// nibbles + per-token fp32 scales. Layout: low nibble = element i, high nibble
// = element i + dim/2.
void int4_per_token_encode(const float* x, int64_t n_tokens, int64_t dim,
                           uint8_t* packed, float* scales) {
  const int64_t half = dim / 2;
  for (int64_t t = 0; t < n_tokens; ++t) {
    const float* row = x + t * dim;
    float max_abs = 0.0f;
    for (int64_t i = 0; i < dim; ++i) max_abs = std::max(max_abs, std::fabs(row[i]));
    const float safe = max_abs > 0.0f ? max_abs : 1.0f;
    scales[t] = safe;
    uint8_t* out = packed + t * half;
    for (int64_t i = 0; i < half; ++i) {
      const float lo_s = std::min(std::max(row[i] / safe * 7.0f, -8.0f), 7.0f);
      const float hi_s = std::min(std::max(row[i + half] / safe * 7.0f, -8.0f), 7.0f);
      const int lo = static_cast<int>(std::nearbyintf(lo_s)) + 8;  // [0, 15]
      const int hi = static_cast<int>(std::nearbyintf(hi_s)) + 8;
      out[i] = static_cast<uint8_t>((lo & 0xF) | ((hi & 0xF) << 4));
    }
  }
}

// Inverse: packed nibbles + scales -> fp32 (n_tokens, dim).
void int4_per_token_decode(const uint8_t* packed, const float* scales,
                           int64_t n_tokens, int64_t dim, float* out) {
  const int64_t half = dim / 2;
  for (int64_t t = 0; t < n_tokens; ++t) {
    const uint8_t* row = packed + t * half;
    float* o = out + t * dim;
    const float s = scales[t];
    for (int64_t i = 0; i < half; ++i) {
      o[i] = static_cast<float>((row[i] & 0xF) - 8) / 7.0f * s;
      o[i + half] = static_cast<float>(((row[i] >> 4) & 0xF) - 8) / 7.0f * s;
    }
  }
}

// int8 codes in {-1,0,1} (n, dim) -> 2-bit crumbs (n, dim/4), contiguous
// quarters, same layout as packing.pack_ternary.
void ternary_pack(const int8_t* codes, int64_t n, int64_t dim, uint8_t* packed) {
  const int64_t q = dim / 4;
  for (int64_t t = 0; t < n; ++t) {
    const int8_t* row = codes + t * dim;
    uint8_t* out = packed + t * q;
    for (int64_t i = 0; i < q; ++i) {
      out[i] = static_cast<uint8_t>(
          ((row[i] + 1) & 0x3) | (((row[i + q] + 1) & 0x3) << 2) |
          (((row[i + 2 * q] + 1) & 0x3) << 4) | (((row[i + 3 * q] + 1) & 0x3) << 6));
    }
  }
}

void ternary_unpack(const uint8_t* packed, int64_t n, int64_t dim, int8_t* codes) {
  const int64_t q = dim / 4;
  for (int64_t t = 0; t < n; ++t) {
    const uint8_t* row = packed + t * q;
    int8_t* out = codes + t * dim;
    for (int64_t i = 0; i < q; ++i) {
      out[i] = static_cast<int8_t>((row[i] & 0x3) - 1);
      out[i + q] = static_cast<int8_t>(((row[i] >> 2) & 0x3) - 1);
      out[i + 2 * q] = static_cast<int8_t>(((row[i] >> 4) & 0x3) - 1);
      out[i + 3 * q] = static_cast<int8_t>(((row[i] >> 6) & 0x3) - 1);
    }
  }
}

// Measured payload bytes for the int4_per_token codec (packed + fp32 scales).
int64_t int4_per_token_payload_bytes(int64_t n_tokens, int64_t dim) {
  return n_tokens * (dim / 2) + n_tokens * static_cast<int64_t>(sizeof(float));
}

// Shared: per-channel max-abs scales over all tokens (zero channels -> 1.0,
// matching packing._int8_per_channel / _int4_per_channel).
static void channel_absmax_scales(const float* x, int64_t n_tokens, int64_t dim,
                                  float* scales) {
  for (int64_t c = 0; c < dim; ++c) scales[c] = 0.0f;
  for (int64_t t = 0; t < n_tokens; ++t) {
    const float* row = x + t * dim;
    for (int64_t c = 0; c < dim; ++c)
      scales[c] = std::max(scales[c], std::fabs(row[c]));
  }
  for (int64_t c = 0; c < dim; ++c)
    if (!(scales[c] > 0.0f)) scales[c] = 1.0f;
}

// fp32 (n_tokens, dim) -> per-channel symmetric int8 codes + dim fp32 scales
// (the reference's channel_8 loop, qwen_layer_wise.py:125-134, vectorized).
void int8_per_channel_encode(const float* x, int64_t n_tokens, int64_t dim,
                             int8_t* q, float* scales) {
  channel_absmax_scales(x, n_tokens, dim, scales);
  for (int64_t t = 0; t < n_tokens; ++t) {
    const float* row = x + t * dim;
    int8_t* out = q + t * dim;
    for (int64_t c = 0; c < dim; ++c)
      out[c] = static_cast<int8_t>(std::nearbyintf(row[c] / scales[c] * 127.0f));
  }
}

void int8_per_channel_decode(const int8_t* q, const float* scales,
                             int64_t n_tokens, int64_t dim, float* out) {
  for (int64_t t = 0; t < n_tokens; ++t) {
    const int8_t* row = q + t * dim;
    float* o = out + t * dim;
    for (int64_t c = 0; c < dim; ++c)
      o[c] = static_cast<float>(row[c]) * scales[c] / 127.0f;
  }
}

// fp32 (n_tokens, dim) -> per-channel symmetric int4 nibbles (contiguous-half
// layout) + dim fp32 scales (channel_4, qwen_layer_wise.py:128-134).
void int4_per_channel_encode(const float* x, int64_t n_tokens, int64_t dim,
                             uint8_t* packed, float* scales) {
  channel_absmax_scales(x, n_tokens, dim, scales);
  const int64_t half = dim / 2;
  for (int64_t t = 0; t < n_tokens; ++t) {
    const float* row = x + t * dim;
    uint8_t* out = packed + t * half;
    for (int64_t i = 0; i < half; ++i) {
      const int lo = static_cast<int>(
          std::nearbyintf(row[i] / scales[i] * 7.0f)) + 8;
      const int hi = static_cast<int>(
          std::nearbyintf(row[i + half] / scales[i + half] * 7.0f)) + 8;
      out[i] = static_cast<uint8_t>((lo & 0xF) | ((hi & 0xF) << 4));
    }
  }
}

void int4_per_channel_decode(const uint8_t* packed, const float* scales,
                             int64_t n_tokens, int64_t dim, float* out) {
  const int64_t half = dim / 2;
  for (int64_t t = 0; t < n_tokens; ++t) {
    const uint8_t* row = packed + t * half;
    float* o = out + t * dim;
    for (int64_t i = 0; i < half; ++i) {
      o[i] = static_cast<float>((row[i] & 0xF) - 8) * scales[i] / 7.0f;
      o[i + half] =
          static_cast<float>(((row[i] >> 4) & 0xF) - 8) * scales[i + half] / 7.0f;
    }
  }
}

// selective_int4 wire-format decode (shared-ordering path): reassemble one
// batch of windows from the COMPACTED buffers. The side channel ships ONLY
// the k low-token indices (int16); high rows arrive position-ascending, so
// their placement is derived here as the sorted complement of the low-index
// set — the other half of the contract packing.selective_int4 encodes.
//   low_packed: (batch, k, dim/2) int4 nibbles, contiguous-half layout
//   scale:      one global fp32 scale over the selected slice
//   high_bf16:  (batch, s-k, dim) bfloat16 as raw uint16
//   low_idx:    (k,) int16 token positions of the low rows
//   out:        (batch, s, dim) fp32
void selective_int4_decode(const uint8_t* low_packed, float scale,
                           const uint16_t* high_bf16, const int16_t* low_idx,
                           int64_t batch, int64_t s, int64_t k, int64_t dim,
                           float* out) {
  const int64_t half = dim / 2;
  bool* taken = new bool[s]();
  for (int64_t i = 0; i < k; ++i) taken[low_idx[i]] = true;
  for (int64_t b = 0; b < batch; ++b) {
    float* ob = out + b * s * dim;
    // low rows: int4 dequantize into their shipped positions
    for (int64_t i = 0; i < k; ++i) {
      const uint8_t* row = low_packed + (b * k + i) * half;
      float* o = ob + static_cast<int64_t>(low_idx[i]) * dim;
      for (int64_t j = 0; j < half; ++j) {
        o[j] = static_cast<float>((row[j] & 0xF) - 8) / 7.0f * scale;
        o[j + half] = static_cast<float>(((row[j] >> 4) & 0xF) - 8) / 7.0f * scale;
      }
    }
    // high rows: walk positions ascending, fill every non-low slot from the
    // next high row (bf16 -> fp32 is exact: the top 16 bits of the float)
    int64_t h = 0;
    for (int64_t pos = 0; pos < s; ++pos) {
      if (taken[pos]) continue;
      const uint16_t* row = high_bf16 + (b * (s - k) + h) * dim;
      float* o = ob + pos * dim;
      for (int64_t j = 0; j < dim; ++j) {
        const uint32_t bits = static_cast<uint32_t>(row[j]) << 16;
        float v;
        std::memcpy(&v, &bits, sizeof(v));
        o[j] = v;
      }
      ++h;
    }
  }
  delete[] taken;
}

}  // extern "C"
