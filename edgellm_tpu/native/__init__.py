"""Native (C++) host-side codec library, bound via ctypes.

Compiled on first use with the system toolchain (``g++ -O2 -shared -fPIC``) and
cached next to the source; everything degrades gracefully to the JAX/numpy
implementations when a compiler is unavailable (``is_available()``).
"""
from .lib import (
    is_available,
    int4_per_token_encode,
    int4_per_token_decode,
    ternary_pack,
    ternary_unpack,
    int4_payload_bytes,
    int8_per_channel_encode,
    int8_per_channel_decode,
    int4_per_channel_encode,
    int4_per_channel_decode,
    selective_int4_decode,
)

__all__ = [
    "is_available",
    "int4_per_token_encode",
    "int4_per_token_decode",
    "ternary_pack",
    "ternary_unpack",
    "int4_payload_bytes",
    "int8_per_channel_encode",
    "int8_per_channel_decode",
    "int4_per_channel_encode",
    "int4_per_channel_decode",
    "selective_int4_decode",
]
