"""ctypes binding for the C++ packing library, with lazy on-demand compilation.

No pybind11 in this environment (see repo constraints), so the library exposes a
plain-C ABI and this module handles compilation (cached ``.so`` keyed by source
mtime) and numpy array marshalling.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "packing.cc")
_SO = os.path.join(_DIR, "_packing.so")
_lock = threading.Lock()
_lib = None
_failed = False


def _compile() -> bool:
    # per-process temp name: concurrent builds each publish their own complete
    # file via atomic rename instead of interleaving writes on a shared path
    fd, tmp = tempfile.mkstemp(dir=_DIR, suffix=".so.tmp")
    os.close(fd)
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    os.replace(tmp, _SO)
    return True


def _bind(lib):
    i64, f32p = ctypes.c_int64, ctypes.POINTER(ctypes.c_float)
    u8p, i8p = ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int8)
    lib.int4_per_token_encode.argtypes = [f32p, i64, i64, u8p, f32p]
    lib.int4_per_token_decode.argtypes = [u8p, f32p, i64, i64, f32p]
    lib.ternary_pack.argtypes = [i8p, i64, i64, u8p]
    lib.ternary_unpack.argtypes = [u8p, i64, i64, i8p]
    lib.int4_per_token_payload_bytes.argtypes = [i64, i64]
    lib.int4_per_token_payload_bytes.restype = i64
    lib.int8_per_channel_encode.argtypes = [f32p, i64, i64, i8p, f32p]
    lib.int8_per_channel_decode.argtypes = [i8p, f32p, i64, i64, f32p]
    lib.int4_per_channel_encode.argtypes = [f32p, i64, i64, u8p, f32p]
    lib.int4_per_channel_decode.argtypes = [u8p, f32p, i64, i64, f32p]
    u16p, i16p = ctypes.POINTER(ctypes.c_uint16), ctypes.POINTER(ctypes.c_int16)
    lib.selective_int4_decode.argtypes = [u8p, ctypes.c_float, u16p, i16p,
                                          i64, i64, i64, i64, f32p]
    return lib


def _load():
    global _lib, _failed
    with _lock:
        if _lib is not None or _failed:
            return _lib
        stale = (not os.path.exists(_SO)
                 or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if stale and not _compile():
            _failed = True
            return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except (OSError, AttributeError):
            # a cached .so that predates the current symbol set (mtime-
            # preserving copies defeat the staleness check) — rebuild once
            if not _compile():
                _failed = True
                return None
            try:
                _lib = _bind(ctypes.CDLL(_SO))
            except (OSError, AttributeError):
                _failed = True
                return None
        return _lib


def is_available() -> bool:
    """True when the native library compiled (or was cached) successfully."""
    return _load() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _require():
    lib = _load()
    if lib is None:
        raise RuntimeError("native packing library unavailable (no g++?)")
    return lib


def int4_per_token_encode(x: np.ndarray):
    """fp32 (N, D) -> (packed (N, D/2) uint8, scales (N,) fp32), on the host."""
    lib = _require()
    x = np.ascontiguousarray(x, np.float32)
    n, d = x.shape
    if d % 2:
        raise ValueError(f"int4 packing needs an even feature dim, got {d}")
    packed = np.empty((n, d // 2), np.uint8)
    scales = np.empty(n, np.float32)
    lib.int4_per_token_encode(_ptr(x, ctypes.c_float), n, d,
                              _ptr(packed, ctypes.c_uint8), _ptr(scales, ctypes.c_float))
    return packed, scales


def int4_per_token_decode(packed: np.ndarray, scales: np.ndarray) -> np.ndarray:
    lib = _require()
    packed = np.ascontiguousarray(packed, np.uint8)
    scales = np.ascontiguousarray(scales, np.float32)
    n, half = packed.shape
    out = np.empty((n, half * 2), np.float32)
    lib.int4_per_token_decode(_ptr(packed, ctypes.c_uint8), _ptr(scales, ctypes.c_float),
                              n, half * 2, _ptr(out, ctypes.c_float))
    return out


def ternary_pack(codes: np.ndarray) -> np.ndarray:
    lib = _require()
    codes = np.ascontiguousarray(codes, np.int8)
    n, d = codes.shape
    if d % 4:
        raise ValueError(f"ternary packing needs a feature dim divisible by 4, got {d}")
    packed = np.empty((n, d // 4), np.uint8)
    lib.ternary_pack(_ptr(codes, ctypes.c_int8), n, d, _ptr(packed, ctypes.c_uint8))
    return packed


def ternary_unpack(packed: np.ndarray) -> np.ndarray:
    lib = _require()
    packed = np.ascontiguousarray(packed, np.uint8)
    n, q = packed.shape
    codes = np.empty((n, q * 4), np.int8)
    lib.ternary_unpack(_ptr(packed, ctypes.c_uint8), n, q * 4, _ptr(codes, ctypes.c_int8))
    return codes


def int4_payload_bytes(n_tokens: int, dim: int) -> int:
    lib = _require()
    return int(lib.int4_per_token_payload_bytes(n_tokens, dim))


def int8_per_channel_encode(x: np.ndarray):
    """fp32 (N, D) -> (codes (N, D) int8, channel scales (D,) fp32)."""
    lib = _require()
    x = np.ascontiguousarray(x, np.float32)
    n, d = x.shape
    q = np.empty((n, d), np.int8)
    scales = np.empty(d, np.float32)
    lib.int8_per_channel_encode(_ptr(x, ctypes.c_float), n, d,
                                _ptr(q, ctypes.c_int8), _ptr(scales, ctypes.c_float))
    return q, scales


def int8_per_channel_decode(q: np.ndarray, scales: np.ndarray) -> np.ndarray:
    lib = _require()
    q = np.ascontiguousarray(q, np.int8)
    scales = np.ascontiguousarray(scales, np.float32)
    n, d = q.shape
    if scales.size != d:
        raise ValueError(f"per-channel scales must have length {d} (the feature "
                         f"dim), got {scales.size}")
    out = np.empty((n, d), np.float32)
    lib.int8_per_channel_decode(_ptr(q, ctypes.c_int8), _ptr(scales, ctypes.c_float),
                                n, d, _ptr(out, ctypes.c_float))
    return out


def int4_per_channel_encode(x: np.ndarray):
    """fp32 (N, D) -> (packed (N, D/2) uint8, channel scales (D,) fp32)."""
    lib = _require()
    x = np.ascontiguousarray(x, np.float32)
    n, d = x.shape
    if d % 2:
        raise ValueError(f"int4 packing needs an even feature dim, got {d}")
    packed = np.empty((n, d // 2), np.uint8)
    scales = np.empty(d, np.float32)
    lib.int4_per_channel_encode(_ptr(x, ctypes.c_float), n, d,
                                _ptr(packed, ctypes.c_uint8),
                                _ptr(scales, ctypes.c_float))
    return packed, scales


def selective_int4_decode(low_packed: np.ndarray, scale: float,
                          high_bf16: np.ndarray,
                          low_idx: np.ndarray) -> np.ndarray:
    """Reassemble a selective_int4 payload (shared-ordering wire format) on the
    host: low nibbles (B, k, D/2) + global scale + position-ascending bf16 high
    rows (B, S-k, D) + the int16 low-index side channel (k,) -> (B, S, D) fp32.
    High placement is DERIVED as the sorted complement of the low set — the
    independent C++ re-statement of the decode contract. Bit-identical to the
    CPU jnp decode; a TPU decode may differ by 1 ulp on low rows (XLA fuses
    the (c/7)*scale dequant differently on device)."""
    lib = _require()
    low_packed = np.ascontiguousarray(low_packed, np.uint8)
    high_bf16 = np.ascontiguousarray(high_bf16)
    if high_bf16.dtype != np.uint16:
        raise ValueError("high rows must be raw-bf16 uint16 (use "
                         "np.asarray(x).view(np.uint16) on a bfloat16 array)")
    low_idx = np.asarray(low_idx)
    if low_idx.ndim != 1:
        raise ValueError(
            f"per-row payloads (order shape {low_idx.shape}) are the "
            f"data-parallel wire format; this host oracle decodes the "
            f"shared-ordering path only (1-D order)")
    low_idx = np.ascontiguousarray(low_idx, np.int16)
    b, k, half = low_packed.shape
    bh, s_minus_k, d = high_bf16.shape
    if bh != b:
        raise ValueError(f"low batch {b} != high batch {bh}")
    if k and half * 2 != d:
        raise ValueError(f"low dim {half * 2} != high dim {d}")
    if low_idx.size != k:
        raise ValueError(f"order carries {low_idx.size} indices, low rows {k}")
    s = k + s_minus_k
    # wire indices come off-fabric (DCN / file spills): validate before the
    # C++ tight loop scatters through them
    if k and (low_idx.min() < 0 or low_idx.max() >= s
              or np.unique(low_idx).size != k):
        raise ValueError(f"corrupt low-index side channel: {k} indices must be "
                         f"unique and within [0, {s})")
    out = np.empty((b, s, d), np.float32)
    lib.selective_int4_decode(
        _ptr(low_packed, ctypes.c_uint8), ctypes.c_float(float(scale)),
        _ptr(high_bf16, ctypes.c_uint16), _ptr(low_idx, ctypes.c_int16),
        b, s, k, d, _ptr(out, ctypes.c_float))
    return out


def int4_per_channel_decode(packed: np.ndarray, scales: np.ndarray) -> np.ndarray:
    lib = _require()
    packed = np.ascontiguousarray(packed, np.uint8)
    scales = np.ascontiguousarray(scales, np.float32)
    n, half = packed.shape
    if scales.size != half * 2:
        raise ValueError(f"per-channel scales must have length {half * 2} (the "
                         f"feature dim), got {scales.size}")
    out = np.empty((n, half * 2), np.float32)
    lib.int4_per_channel_decode(_ptr(packed, ctypes.c_uint8),
                                _ptr(scales, ctypes.c_float),
                                n, half * 2, _ptr(out, ctypes.c_float))
    return out
