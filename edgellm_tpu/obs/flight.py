"""Failure flight recorder: a bounded ring of recent spans + counter
deltas, auto-dumped as a CRC-framed post-mortem artifact when a typed
failure fires.

The serve stack already *survives* its typed failures (``DecodeTimeout``,
``StageLostError``, ``OutOfPages``, checkpoint corruption) — what it loses
is the evidence: by the time a human looks, the registry has moved on and
the spans that led up to the failure are buried in a full-run trace. The
recorder keeps the last N closed spans (fed by the tracer's sink hook) and
the last N counter deltas in memory, and on failure writes one artifact
containing: the failure, the span ring, the counter-delta ring, a full
registry snapshot, the active-request table, and whatever window the
context provider contributes (the serve front installs one that reports
link health, breaker and brownout state).

Artifact framing reuses the ``DecodeCheckpoint`` discipline
(``serve/recovery.py``): ``magic(8) | u32 version | u64 payload_len |
u32 crc32(payload)`` then a UTF-8 JSON payload, written ``.part`` →
``os.replace`` so a crash mid-dump never leaves a half artifact behind.

Exactly-one semantics: a failure instance is dumped where it is *raised*
(watchdog, pool allocator) and often also observed where it is *caught*
(the serve front's retry ladder); :meth:`FlightRecorder.dump_for` marks the
exception object itself so the same failure never produces two artifacts.

Determinism: the recorder takes an injectable ``clock`` (the FakeClock the
soak harness already uses); with a fake clock and a seeded run the artifact
payload is byte-stable modulo span durations.
"""
from __future__ import annotations

import collections
import json
import os
import struct
import threading
import zlib
from typing import Any, Callable, Deque, Dict, List, Optional

from . import metrics as _metrics
from . import tracing as _tracing
from ..utils.concurrency import guarded_by

__all__ = [
    "FlightArtifactError", "FlightRecorder", "configure_flight",
    "flight_dump_for", "get_flight_recorder", "load_flight",
]

_MAGIC = b"EDGEFLTR"
_VERSION = 1
#: magic(8) | u32 version | u64 payload_len | u32 crc32(payload)
_HEADER = struct.Struct("<8sIQI")

_DUMPED_MARK = "_edgellm_flight_dumped"


class FlightArtifactError(RuntimeError):
    """A flight artifact failed its frame checks (magic/version/CRC)."""


@guarded_by("_lock", fields=["_spans", "_counters", "_active",
                             "_dump_paths", "_seq"])
class FlightRecorder:
    """Bounded in-memory ring + one-shot post-mortem dumps."""

    def __init__(self, out_dir: str, *, capacity: int = 256,
                 clock: Optional[Callable[[], float]] = None) -> None:
        if capacity <= 0:
            raise ValueError(f"flight capacity must be positive, "
                             f"got {capacity}")
        self.out_dir = out_dir
        self.capacity = capacity
        self._clock = clock
        self._lock = threading.Lock()
        self._spans: Deque[Dict[str, Any]] = collections.deque(
            maxlen=capacity)
        self._counters: Deque[Dict[str, Any]] = collections.deque(
            maxlen=capacity)
        self._active: Dict[str, Dict[str, Any]] = {}
        self._context_fn: Optional[Callable[[], Dict[str, Any]]] = None
        self._dump_paths: List[str] = []
        self._seq = 0

    # -- ring feeds ---------------------------------------------------------

    def record_span(self, span: "_tracing.Span") -> None:
        """Tracer sink: one closed span into the ring."""
        ev = span.to_event()
        with self._lock:
            self._spans.append(ev)

    def note_counters(self, kind: str, delta: Dict[str, Any]) -> None:
        """One counter delta (e.g. a decode call's per-hop fault counters)."""
        flat = {k: [int(x) for x in v] if hasattr(v, "__iter__") else int(v)
                for k, v in delta.items()}
        with self._lock:
            self._counters.append({"kind": kind, "delta": flat,
                                   "t": self._now()})

    def note_request(self, rid: str, **meta: Any) -> None:
        with self._lock:
            self._active[rid] = dict(meta)

    def end_request(self, rid: str) -> None:
        with self._lock:
            self._active.pop(rid, None)

    def set_context_provider(
            self, fn: Optional[Callable[[], Dict[str, Any]]]) -> None:
        """Install the serve front's live-state contributor (link health,
        breaker/brownout summary) — merged into every dump."""
        self._context_fn = fn

    def _now(self) -> Optional[float]:
        return self._clock() if self._clock is not None else None

    # -- dumping ------------------------------------------------------------

    def dump_for(self, exc: BaseException, **extra: Any) -> Optional[str]:
        """Dump once for this failure *instance*; the raise site and every
        catch site can all call this and exactly one artifact results."""
        with self._lock:
            if getattr(exc, _DUMPED_MARK, False):
                return None
            try:
                setattr(exc, _DUMPED_MARK, True)
            except AttributeError:  # __slots__ exception: fall back to id
                pass
        failure = {"type": type(exc).__name__, "message": str(exc)}
        for attr in ("stage", "at_step"):
            v = getattr(exc, attr, None)
            if isinstance(v, (int, str)):
                failure[attr] = v
        return self.dump(type(exc).__name__, failure=failure, **extra)

    def dump(self, reason: str, *, failure: Optional[Dict[str, Any]] = None,
             **extra: Any) -> str:
        """Write one CRC-framed post-mortem artifact; returns its path."""
        ctx: Dict[str, Any] = {}
        if self._context_fn is not None:
            try:
                ctx = dict(self._context_fn())
            except Exception:  # pragma: no cover - provider must not kill us
                ctx = {"context_provider_error": True}
        reg = _metrics.get_registry()
        with self._lock:
            self._seq += 1
            seq = self._seq
            payload_obj: Dict[str, Any] = {
                "reason": reason,
                "seq": seq,
                "t": self._now(),
                "failure": failure,
                "spans": list(self._spans),
                "counters": list(self._counters),
                "active_requests": {k: dict(v)
                                    for k, v in self._active.items()},
                "context": ctx,
                "registry": (json.loads(reg.to_json())
                             if reg.enabled else {}),
            }
            payload_obj.update(extra)
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)
        path = os.path.join(self.out_dir, f"flight-{seq:04d}-{safe}.bin")
        os.makedirs(self.out_dir, exist_ok=True)
        payload = json.dumps(payload_obj, sort_keys=True,
                             default=repr).encode("utf-8")
        header = _HEADER.pack(_MAGIC, _VERSION, len(payload),
                              zlib.crc32(payload) & 0xFFFFFFFF)
        tmp = path + ".part"
        with open(tmp, "wb") as f:
            f.write(header)
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        with self._lock:
            self._dump_paths.append(path)
        if reg.enabled:
            reg.counter("edgellm_flight_dumps_total",
                        "flight-recorder post-mortem artifacts written"
                        ).inc(reason=reason)
        return path

    def dumps(self) -> List[str]:
        """Paths of every artifact this recorder has written, in order."""
        with self._lock:
            return list(self._dump_paths)

    def snapshot(self) -> Dict[str, Any]:
        """The live ring as JSON-able state (the ``/snapshot.json`` and
        trace-report consumers)."""
        with self._lock:
            return {"spans": list(self._spans),
                    "counters": list(self._counters),
                    "active_requests": {k: dict(v)
                                        for k, v in self._active.items()},
                    "dumps": list(self._dump_paths)}


def load_flight(path: str) -> Dict[str, Any]:
    """Read one artifact back, verifying magic, version, and CRC."""
    with open(path, "rb") as f:
        head = f.read(_HEADER.size)
        if len(head) != _HEADER.size:
            raise FlightArtifactError(f"{path}: truncated header")
        magic, version, n, crc = _HEADER.unpack(head)
        if magic != _MAGIC:
            raise FlightArtifactError(f"{path}: bad magic {magic!r}")
        if version != _VERSION:
            raise FlightArtifactError(f"{path}: unsupported version "
                                      f"{version}")
        payload = f.read(n)
    if len(payload) != n:
        raise FlightArtifactError(f"{path}: truncated payload "
                                  f"({len(payload)} of {n} bytes)")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise FlightArtifactError(f"{path}: CRC mismatch")
    obj = json.loads(payload.decode("utf-8"))
    if not isinstance(obj, dict):
        raise FlightArtifactError(f"{path}: payload is not an object")
    return obj


_RECORDER: Optional[FlightRecorder] = None


def configure_flight(recorder: Optional[FlightRecorder]) -> None:
    """Install (or remove, with None) the process-global recorder and hook
    it into the global tracer's span sink."""
    global _RECORDER
    _RECORDER = recorder
    _tracing.get_tracer().set_sink(
        recorder.record_span if recorder is not None else None)


def get_flight_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def flight_dump_for(exc: BaseException, **extra: Any) -> Optional[str]:
    """Module-level convenience the failure sites call unconditionally:
    no-op when no recorder is configured."""
    rec = _RECORDER
    if rec is None:
        return None
    try:
        return rec.dump_for(exc, **extra)
    except Exception:  # pragma: no cover - dumping must never mask the
        return None    # original failure
