"""Typed metrics: Counter/Gauge/Histogram, a named registry, exporters,
and adapters absorbing the stack's scattered legacy counter sources.

Metric model: a metric has a name, a help string, and one value per label
set (a frozen ``{label: value}`` mapping; the empty label set is a plain
scalar). Histograms use log-spaced fixed bucket edges with positional
interpolation inside the landing bucket, so p50/p95/p99 are exact up to one
bucket's relative width (pick the bucket density for the accuracy you need;
the defaults resolve latency quantiles to ~10%).

The process-global registry (:func:`get_registry`) starts **disabled**:
every adapter self-gates on ``registry.enabled``, so with observability off
(the default) recording is a single attribute check and nothing is stored.
``edgellm_tpu.obs.enable()`` (or run.py's ``--metrics-out`` /
params.json ``"observability"``) arms it.

Exporters: :meth:`MetricsRegistry.to_prometheus` emits the text exposition
format (``# HELP``/``# TYPE`` + samples, histograms as cumulative
``_bucket{le=...}`` series); :meth:`MetricsRegistry.snapshot` is the
JSON-able form every bench artifact embeds.

Metric name catalog (REPRODUCING §10): ``edgellm_link_<counter>_total``
(per-hop fault-ladder counters, label ``hop``), ``edgellm_link_health_*``
(burn rate / windowed rates / tier), ``edgellm_recovery_<counter>_total``,
``edgellm_decode_jit_cache_misses_total``, ``edgellm_wire_bytes_total``
(labels ``hop``, ``kind``), ``edgellm_decode_ttft_seconds`` /
``edgellm_decode_token_latency_seconds`` (histograms),
``edgellm_spec_{drafted,accepted,rejected,bursts}_total`` /
``edgellm_spec_acceptance_rate`` / ``edgellm_spec_hops_per_token``
(speculative decode), ``edgellm_pipeline_microbatches`` /
``edgellm_pipeline_bubble_fraction[_measured]`` /
``edgellm_pipeline_stage_occupancy`` (µ-batch pipelined decode, label
``stage``), ``edgellm_fused_hop_active`` /
``edgellm_fused_hop_decision`` / ``edgellm_fused_probe_win`` (fused-hop
probe decisions, labels ``hop``, ``codec``, ``mode``, ``reason``).
"""
from __future__ import annotations

import bisect
import json
import math
import threading
from typing import Any, Dict, Iterable, List, Mapping, Optional, Protocol, \
    Sequence, Tuple, runtime_checkable

from ..utils.concurrency import acquire_in_order, guarded_by

__all__ = [
    "Counter", "CounterSource", "Gauge", "Histogram", "MetricsRegistry",
    "format_table", "get_registry", "record_decode_stats",
    "record_link_counters", "record_link_health", "record_pipeline_stats",
    "record_prefix_stats", "record_probe_decisions",
    "record_recovery_counters", "record_spec_stats", "record_wire_bytes",
]

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Mapping[str, Any]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(v: str) -> str:
    """Prometheus exposition escaping for quoted label values: backslash,
    double-quote, and newline (in that order — escaping the escapes first)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(text: str) -> str:
    """``# HELP`` line escaping: backslash and newline only (quotes are
    legal in help text)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _label_str(key: LabelKey) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label_value(v)}"'
                          for k, v in key) + "}"


@guarded_by("_lock", fields=["_values"])
class _Metric:
    """Shared name/help/values plumbing; subclasses define the semantics."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._values: Dict[LabelKey, float] = {}
        self._lock = threading.Lock()

    def value(self, **labels: Any) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def items(self) -> List[Tuple[LabelKey, float]]:
        with self._lock:
            return sorted(self._values.items())

    def snapshot(self) -> Dict[str, Any]:
        return {"kind": self.kind, "help": self.help,
                "values": {_label_str(k) or "": v for k, v in self.items()}}


class Counter(_Metric):
    """Monotonically increasing count. ``inc`` with a negative amount is a
    programming error and raises — a counter that can go down is a gauge."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc {amount})")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Gauge(_Metric):
    """A value that goes both ways (rates, tiers, sizes)."""

    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


@guarded_by("_lock", fields=["_counts", "count", "sum", "_min", "_max"])
class Histogram:
    """Log-spaced fixed-bucket histogram with interpolated quantiles.

    ``lo``/``hi`` bound the log-spaced range with ``n_buckets`` geometric
    buckets between them; values below ``lo`` land in an underflow bucket
    ``[0, lo)``, values at/above ``hi`` in an overflow bucket clamped by the
    tracked max. ``quantile(q)`` finds the landing bucket by cumulative rank
    (numpy's ``linear`` positional convention) and interpolates
    geometrically inside it — log-spaced buckets make relative (not
    absolute) error uniform across the range, which is the right shape for
    latency distributions spanning decades.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "", *, lo: float = 1e-5,
                 hi: float = 1e3, n_buckets: int = 200) -> None:
        if not (0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
        if n_buckets < 1:
            raise ValueError("n_buckets must be >= 1")
        self.name = name
        self.help = help
        # bucket b spans [edges[b], edges[b+1]); bucket 0 is [0, lo)
        ratio = (hi / lo) ** (1.0 / n_buckets)
        self.edges: List[float] = [0.0] + [lo * ratio ** i
                                           for i in range(n_buckets)] + [hi]
        self._counts = [0] * (len(self.edges))  # last slot = overflow [hi, inf)
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            b = bisect.bisect_right(self.edges, v) - 1 if v >= 0 else 0
            self._counts[min(b, len(self._counts) - 1)] += 1
            self.count += 1
            self.sum += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)

    def quantile(self, q: float) -> float:
        """Interpolated q-quantile (0 <= q <= 1); NaN when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            if self.count == 0:
                return math.nan
            rank = q * (self.count - 1)  # numpy 'linear' position
            cum = 0
            for b, c in enumerate(self._counts):
                if c == 0:
                    continue
                if rank < cum + c:  # rank lands in this bucket
                    lob = self.edges[b]
                    hib = (self.edges[b + 1] if b + 1 < len(self.edges)
                           else max(self._max, self.edges[-1]))
                    # clamp by the observed extremes: a single-value bucket
                    # must not report wider than what was actually seen
                    lob = max(lob, self._min) if b == 0 or lob == 0.0 else lob
                    hib = min(hib, self._max) if self._max > lob else hib
                    frac = (rank - cum + 0.5) / c  # midpoint-rank position
                    if lob <= 0.0:
                        return lob + (hib - lob) * frac  # linear near zero
                    return lob * (hib / lob) ** frac  # geometric in-bucket
                cum += c
            return self._max

    def percentiles(self) -> Dict[str, float]:
        """The SLO trio plus count/mean — the block bench artifacts embed."""
        mean = self.sum / self.count if self.count else math.nan
        return {"count": self.count, "mean": mean,
                "min": self._min if self.count else math.nan,
                "max": self._max if self.count else math.nan,
                "p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    def merge_from(self, other: "Histogram") -> None:
        """Fold another histogram with identical bucket edges into this one
        (used to publish a call-private observer into the registry)."""
        if other.edges != self.edges:
            raise ValueError(f"cannot merge {other.name}: bucket edges differ")
        # id()-ordered acquisition: A.merge_from(B) racing B.merge_from(A)
        # takes the pair in the same global order on both threads, so the
        # source-order ABBA deadlock (threadlint EG102) cannot happen
        with acquire_in_order(self._lock, other._lock):
            for b, c in enumerate(other._counts):
                self._counts[b] += c
            self.count += other.count
            self.sum += other.sum
            self._min = min(self._min, other._min)
            self._max = max(self._max, other._max)

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """[(upper edge, cumulative count)] in Prometheus ``le`` form."""
        out, cum = [], 0
        with self._lock:
            for b, c in enumerate(self._counts):
                cum += c
                le = (self.edges[b + 1] if b + 1 < len(self.edges)
                      else math.inf)
                out.append((le, cum))
        return out

    def snapshot(self) -> Dict[str, Any]:
        p = self.percentiles()
        return {"kind": self.kind, "help": self.help,
                **{k: (None if isinstance(v, float) and math.isnan(v) else v)
                   for k, v in p.items()},
                "sum": self.sum}


@runtime_checkable
class CounterSource(Protocol):
    """The typed contract the serve loops used to probe with
    ``hasattr(rt, "link_counters")``: any runtime that can report per-hop
    fault counters and per-step decode wire bytes. ``SplitRuntime``,
    ``SplitRingRuntime`` and ``LocalRuntime`` all satisfy it structurally
    (``LocalRuntime`` reports ``None``/``[]`` — nothing crosses a wire)."""

    def link_counters(self, reset: bool = False) -> Optional[dict]:
        """Accumulated per-hop counters ``{name: (n_hops,) ints}``, or None
        when the link machinery is not in the graph."""
        ...

    def decode_hop_bytes(self, batch: int) -> list:
        """Per-hop wire bytes one decode step moves at this batch."""
        ...


@guarded_by("_lock", fields=["_metrics"])
class MetricsRegistry:
    """Process-wide named metric store. ``enabled`` gates every adapter (and
    should gate ad-hoc recording too); metric creation is get-or-create so
    call sites never race on registration."""

    def __init__(self, enabled: bool = False) -> None:
        self.enabled = enabled
        self._metrics: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get(self, cls: type, name: str, help: str, **kwargs: Any) -> Any:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(f"metric {name!r} already registered as "
                                 f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "", *, lo: float = 1e-5,
                  hi: float = 1e3, n_buckets: int = 200) -> Histogram:
        return self._get(Histogram, name, help, lo=lo, hi=hi,
                         n_buckets=n_buckets)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def get(self, name: str) -> Any:
        return self._metrics.get(name)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    def _items(self) -> List[Tuple[str, Any]]:
        """Consistent name->metric view; per-metric state is read under
        each metric's own lock *after* the registry lock is released (no
        nested acquisition, no torn scrape on a concurrent clear())."""
        with self._lock:
            return sorted(self._metrics.items())

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able ``{name: {kind, help, values|percentiles}}``."""
        return {name: m.snapshot() for name, m in self._items()}

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def to_prometheus(self) -> str:
        """Prometheus text exposition format, one block per metric family.

        Hardened per the format spec: label values escape backslash /
        double-quote / newline, ``# HELP`` text escapes backslash / newline,
        and ``# HELP``/``# TYPE`` are emitted exactly once per family even
        if a family ever gains multiple sample series (histogram ``_bucket``
        / ``_sum`` / ``_count`` already share one family header)."""
        lines: List[str] = []
        emitted_headers: set = set()
        for name, m in self._items():
            if name not in emitted_headers:
                emitted_headers.add(name)
                if m.help:
                    lines.append(f"# HELP {name} {_escape_help(m.help)}")
                lines.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, Histogram):
                for le, cum in m.bucket_counts():
                    le_s = "+Inf" if math.isinf(le) else repr(le)
                    lines.append(f'{name}_bucket{{le="{le_s}"}} {cum}')
                lines.append(f"{name}_sum {m.sum!r}")
                lines.append(f"{name}_count {m.count}")
            else:
                for key, v in m.items():
                    lines.append(f"{name}{_label_str(key)} {v!r}")
        return "\n".join(lines) + "\n"


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry every adapter and exporter shares."""
    return _REGISTRY


# ---------------------------------------------------------------------------
# adapters: the scattered legacy sources, absorbed into one registry
# ---------------------------------------------------------------------------


def record_link_counters(delta: Optional[Mapping[str, Sequence[int]]],
                         registry: Optional[MetricsRegistry] = None) -> None:
    """Absorb a FaultyLink-style per-hop counter dict (``COUNTER_KEYS`` plus
    the self-healing extras) as ``edgellm_link_<key>_total{hop=i}``."""
    reg = registry if registry is not None else _REGISTRY
    if not reg.enabled or not delta:
        return
    if registry is None:  # the flight ring shadows the global adapter path
        from . import flight as _flight

        rec = _flight.get_flight_recorder()
        if rec is not None:
            rec.note_counters("link", dict(delta))
    for key, per_hop in delta.items():
        c = reg.counter(f"edgellm_link_{key}_total",
                        f"per-hop link-ladder counter {key!r}")
        if isinstance(per_hop, (str, bytes)) or not hasattr(per_hop,
                                                            "__iter__"):
            vals = [per_hop]  # scalar total: a single-hop figure
        else:
            vals = list(per_hop)  # list/tuple or numpy (n_hops,) array
        for hop, v in enumerate(vals):
            if int(v):
                c.inc(int(v), hop=hop)


def record_recovery_counters(counters: Optional[Any],
                             registry: Optional[MetricsRegistry] = None
                             ) -> None:
    """Absorb a :class:`~edgellm_tpu.serve.recovery.RecoveryCounters` (or its
    ``as_dict()`` form) as ``edgellm_recovery_<field>_total``."""
    reg = registry if registry is not None else _REGISTRY
    if not reg.enabled or counters is None:
        return
    d = counters.as_dict() if hasattr(counters, "as_dict") else dict(counters)
    for key, v in d.items():
        if int(v):
            reg.counter(f"edgellm_recovery_{key}_total",
                        f"recovery orchestration counter {key!r}").inc(int(v))


def record_link_health(summary: Optional[Mapping[str, Any]],
                       registry: Optional[MetricsRegistry] = None) -> None:
    """Absorb a :meth:`~edgellm_tpu.codecs.fec.LinkHealth.summary` dict as
    ``edgellm_link_health_*`` gauges (rates, burn, tier, window fill)."""
    reg = registry if registry is not None else _REGISTRY
    if not reg.enabled or not summary:
        return
    for key, v in summary.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        reg.gauge(f"edgellm_link_health_{key}",
                  f"windowed link-SLO field {key!r}").set(float(v))


def record_decode_stats(stats: Optional[Mapping[str, Any]],
                        registry: Optional[MetricsRegistry] = None) -> None:
    """Absorb a ``generate``/``generate_split`` stats dict: jit-miss count,
    decoded tokens, decode/prefill walls."""
    reg = registry if registry is not None else _REGISTRY
    if not reg.enabled or not stats:
        return
    misses = stats.get("decode_step_cache_misses")
    if misses:
        reg.counter("edgellm_decode_jit_cache_misses_total",
                    "per-step executables compiled (0 on a warm shape)"
                    ).inc(int(misses))
    steps = stats.get("decode_steps")
    if steps:
        reg.counter("edgellm_decode_steps_total",
                    "decode-loop steps executed").inc(int(steps))
    prefill_s = stats.get("prefill_s")
    if prefill_s is not None:
        reg.gauge("edgellm_decode_prefill_s",
                  "last call's prefill wall clock").set(float(prefill_s))
    decode_s = stats.get("decode_s")
    if decode_s is not None:
        reg.gauge("edgellm_decode_decode_s",
                  "last call's decode-loop wall clock").set(float(decode_s))


def record_prefix_stats(report: Optional[Mapping[str, Any]],
                        registry: Optional[MetricsRegistry] = None) -> None:
    """Absorb a :meth:`~edgellm_tpu.models.paged_kv.PagedKVCache.
    prefix_report` dict as ``edgellm_prefix_*`` series: hit/miss/saved-token/
    COW-fork counters (incremented with the report's running totals — call
    once per drain, not per step) plus hit-rate and shared/index page-count
    gauges — the numbers that say whether the radix index is earning its
    pinned pages."""
    reg = registry if registry is not None else _REGISTRY
    if not reg.enabled or not report or not report.get("enabled"):
        return
    hits = report.get("hits")
    if hits:
        reg.counter("edgellm_prefix_hits_total",
                    "admits that mapped shared prefix pages").inc(int(hits))
    misses = report.get("misses")
    if misses:
        reg.counter("edgellm_prefix_misses_total",
                    "admits with no usable indexed prefix").inc(int(misses))
    saved = report.get("saved_tokens")
    if saved:
        reg.counter("edgellm_prefix_saved_tokens_total",
                    "prefill token positions skipped via shared pages"
                    ).inc(int(saved))
    forks = report.get("cow_forks")
    if forks:
        reg.counter("edgellm_prefix_cow_forks_total",
                    "copy-on-write page forks").inc(int(forks))
    rate = report.get("hit_rate")
    if rate is not None:
        reg.gauge("edgellm_prefix_hit_rate",
                  "prefix-index hits / lookups").set(float(rate))
    reg.gauge("edgellm_prefix_shared_pages",
              "pages currently referenced more than once").set(
        float(report.get("shared_pages", 0)))
    reg.gauge("edgellm_prefix_index_pages",
              "pages currently pinned by the radix index").set(
        float(report.get("index_pages", 0)))


def record_wire_bytes(per_hop_bytes: Optional[Iterable[float]],
                      kind: str = "forward", steps: int = 1,
                      registry: Optional[MetricsRegistry] = None) -> None:
    """Absorb the split/ring runtimes' per-hop wire-byte accounting
    (``hop_bytes``/``decode_hop_bytes``) as
    ``edgellm_wire_bytes_total{hop, kind}`` — ``steps`` multiplies a
    per-step figure into a per-call total."""
    reg = registry if registry is not None else _REGISTRY
    if not reg.enabled or per_hop_bytes is None:
        return
    c = reg.counter("edgellm_wire_bytes_total",
                    "bytes moved across boundary hops")
    for hop, b in enumerate(per_hop_bytes):
        total = float(b) * int(steps)
        if total:
            c.inc(total, hop=hop, kind=kind)


def record_pipeline_stats(summary: Optional[Mapping[str, Any]],
                          registry: Optional[MetricsRegistry] = None) -> None:
    """Absorb a :meth:`~edgellm_tpu.parallel.split.SplitRuntime.
    pipeline_summary` dict as ``edgellm_pipeline_*`` gauges: µ-batch count,
    per-stage occupancy (label ``stage``), and the analytic schedule bubble
    fraction — plus ``edgellm_pipeline_bubble_fraction_measured`` when the
    caller attaches a timed value (BENCH_PIPE does), so bubble regressions
    surface in scraped metrics, not just bench artifacts."""
    reg = registry if registry is not None else _REGISTRY
    if not reg.enabled or not summary:
        return
    reg.gauge("edgellm_pipeline_microbatches",
              "µ-batches per pipelined step (1 = sequential schedule)").set(
        float(summary.get("num_microbatches", 1)))
    reg.gauge("edgellm_pipeline_bubble_fraction",
              "analytic pipeline bubble fraction (n-1)/(M+n-1)").set(
        float(summary.get("bubble_fraction_schedule", 0.0)))
    if summary.get("bubble_fraction_measured") is not None:
        reg.gauge("edgellm_pipeline_bubble_fraction_measured",
                  "measured steady-state bubble fraction (1 - t_seq/(n*t_pipe))"
                  ).set(float(summary["bubble_fraction_measured"]))
    occ = reg.gauge("edgellm_pipeline_stage_occupancy",
                    "fraction of unroll steps each stage computes")
    for stage, frac in enumerate(summary.get("stage_occupancy", ())):
        occ.set(float(frac), stage=stage)


def record_spec_stats(stats: Optional[Mapping[str, Any]],
                      registry: Optional[MetricsRegistry] = None) -> None:
    """Absorb a speculative-decode stats dict (``generate_speculative``'s
    ``stats["speculative"]``): drafted/accepted/rejected/burst counters plus
    acceptance-rate and hops-per-token gauges — the two numbers that say
    whether speculation is paying for its drafts."""
    reg = registry if registry is not None else _REGISTRY
    if not reg.enabled or not stats:
        return
    for key in ("drafted", "accepted", "rejected", "bursts"):
        v = stats.get(key)
        if v:
            reg.counter(f"edgellm_spec_{key}_total",
                        f"speculative-decode counter {key!r}").inc(int(v))
    ar = stats.get("acceptance_rate")
    if ar is not None:
        reg.gauge("edgellm_spec_acceptance_rate",
                  "accepted drafts / drafted tokens, last run").set(float(ar))
    hpt = stats.get("hops_per_token")
    if hpt is not None:
        reg.gauge("edgellm_spec_hops_per_token",
                  "boundary hop rounds per emitted token, last run "
                  "(< 1.0 means speculation amortized the link)"
                  ).set(float(hpt))


def record_probe_decisions(rows: Optional[Sequence[Mapping[str, Any]]],
                           registry: Optional[MetricsRegistry] = None
                           ) -> None:
    """Absorb ``SplitRuntime.wire_summary`` rows' fused-hop plan decisions,
    plus the probe cache's measured-win verdict per codec, so
    ``--metrics-out`` says WHY a hop did or didn't fuse instead of that
    living only in the BENCH_WIRE detail sidecar: ``edgellm_fused_hop_active
    {hop, codec}`` is 1/0, ``edgellm_fused_hop_decision{hop, codec, mode,
    reason}`` is an info-style gauge carrying the plan's reason string, and
    ``edgellm_fused_probe_win{codec}`` is 1 for a measured win, -1 for a
    measured loss, 0 for no probe data."""
    reg = registry if registry is not None else _REGISTRY
    if not reg.enabled or not rows:
        return
    from ..codecs import probe_cache

    active = reg.gauge("edgellm_fused_hop_active",
                       "1 when this hop crosses as one fused sealed buffer, "
                       "0 on the unfused encode/ppermute/decode ladder")
    decision = reg.gauge("edgellm_fused_hop_decision",
                         "info-style record (value always 1) of each hop's "
                         "fuse/no-fuse decision and its reason")
    win = reg.gauge("edgellm_fused_probe_win",
                    "probe-cache verdict per codec: 1 measured win, "
                    "-1 measured loss, 0 no data")
    for row in rows:
        hop = row.get("hop", 0)
        codec = row.get("codec", "?")
        fused = row.get("fused")
        active.set(1.0 if fused else 0.0, hop=hop, codec=codec)
        if fused:
            decision.set(1.0, hop=hop, codec=codec,
                         mode=fused.get("mode", "?"),
                         reason=fused.get("reason", "?"))
        else:
            decision.set(1.0, hop=hop, codec=codec, mode="off",
                         reason="no fused plan (gate ladder refused)")
        w = probe_cache.measured_win(f"fused_hop:{codec}")
        win.set(0.0 if w is None else (1.0 if w else -1.0), codec=codec)


def record_cluster_stats(report: Optional[Mapping[str, Any]],
                         registry: Optional[MetricsRegistry] = None) -> None:
    """Absorb a :meth:`~edgellm_tpu.serve.cluster.ClusterFront.report` dict
    as ``edgellm_cluster_*`` series: fleet-size/pressure/parked gauges plus
    kill/respawn/readmission/recompute counters (incremented with the
    report's running totals — call once per report, not per drain tick)."""
    reg = registry if registry is not None else _REGISTRY
    if not reg.enabled or not report:
        return
    replicas = report.get("replicas", {})
    live = sum(1 for r in replicas.values() if r.get("state") == "live")
    reg.gauge("edgellm_cluster_replicas",
              "replicas in the fleet (any state)").set(float(len(replicas)))
    reg.gauge("edgellm_cluster_live_replicas",
              "replicas currently serving").set(float(live))
    reg.gauge("edgellm_cluster_parked",
              "accepted requests waiting for a routable replica").set(
        float(report.get("parked", 0)))
    pressure = report.get("pressure")
    if pressure is not None:
        reg.gauge("edgellm_cluster_pressure",
                  "mean live-replica load fraction").set(float(pressure))
    kills = report.get("kills")
    if kills:
        reg.counter("edgellm_cluster_kills_total",
                    "replicas removed by fault or chaos").inc(len(kills))
    respawns = sum(r.get("respawns", 0) for r in replicas.values())
    if respawns:
        reg.counter("edgellm_cluster_respawns_total",
                    "replica respawns from a clean plan").inc(int(respawns))
    totals = report.get("totals", {})
    if totals.get("readmitted"):
        reg.counter("edgellm_cluster_readmitted_total",
                    "accepted requests re-placed after a replica loss").inc(
            int(totals["readmitted"]))
    if totals.get("recompute_tokens"):
        reg.counter("edgellm_cluster_recompute_tokens_total",
                    "tokens regenerated after scratch re-admissions").inc(
            int(totals["recompute_tokens"]))
    events = report.get("autoscale_events")
    if events:
        c = reg.counter("edgellm_cluster_autoscale_events_total",
                        "autoscaler scale decisions")
        for ev in events:
            c.inc(direction=ev.get("direction", "?"))


def format_table(registry: Optional[MetricsRegistry] = None,
                 title: str = "metrics") -> str:
    """One aligned name/value table over the whole registry — the unified
    ``--fault-report`` output (replaces three hand-formatted tables)."""
    reg = registry if registry is not None else _REGISTRY
    rows: List[Tuple[str, str]] = []
    for name in reg.names():
        m = reg.get(name)
        if isinstance(m, Histogram):
            p = m.percentiles()
            for k in ("count", "p50", "p95", "p99"):
                v = p[k]
                if isinstance(v, float) and math.isnan(v):
                    continue
                rows.append((f"{name}.{k}", f"{v:.6g}"))
        else:
            for key, v in m.items():
                rows.append((f"{name}{_label_str(key)}", f"{v:.6g}"))
    if not rows:
        return f"{title}: (empty)"
    w = max(len(r[0]) for r in rows)
    body = "\n".join(f"  {n.ljust(w)}  {v.rjust(12)}" for n, v in rows)
    return f"{title}:\n{body}"
