"""The registered metric/span name tables — the single vocabulary every
``registry.counter/gauge/histogram(...)`` and ``span(...)`` call site must
draw from.

Why a table: a typo'd metric name (``"edgellm_hop_bytez"``) is not an error
anywhere — the registry happily creates the series, dashboards scrape
nothing, and the mistake is only found weeks later by a human staring at an
empty panel. graphlint rule EG007 (``lint/ast_rules.py``) closes that hole
statically: every *literal* name passed to a metric factory or a span
constructor must appear here, and every f-string name must match one of the
registered ``*`` templates (the holes are the runtime-varying segment, e.g.
the fault-counter key in ``edgellm_link_*_total``). Dynamic names (a
variable first argument) are out of scope — the lint stands down rather
than guess.

This module is imported by the lint layer, so it must stay stdlib-only and
import nothing from the rest of the package.
"""
from __future__ import annotations

from fnmatch import fnmatchcase
from typing import FrozenSet, Tuple

__all__ = [
    "METRIC_NAMES", "METRIC_TEMPLATES", "SPAN_NAMES", "SPAN_TEMPLATES",
    "metric_registered", "span_registered",
]

#: every literal metric family name in the package (registry factories and
#: direct Counter/Gauge/Histogram constructions)
METRIC_NAMES: FrozenSet[str] = frozenset({
    # serve front (pre-date the edgellm_ prefix; renaming would break the
    # serve-report consumers, so they are registered as-is)
    "serve_requests_total",
    "serve_ttft_s",
    "serve_latency_s",
    "serve_retries_charged_total",
    "serve_brownout_level",
    "serve_queue_depth",
    # decode loop
    "edgellm_decode_jit_cache_misses_total",
    "edgellm_decode_steps_total",
    "edgellm_decode_prefill_s",
    "edgellm_decode_decode_s",
    "edgellm_decode_ttft_seconds",
    "edgellm_decode_token_latency_seconds",
    # boundary wire
    "edgellm_wire_bytes_total",
    # pipelined decode
    "edgellm_pipeline_microbatches",
    "edgellm_pipeline_bubble_fraction",
    "edgellm_pipeline_bubble_fraction_measured",
    "edgellm_pipeline_stage_occupancy",
    # speculative decode
    "edgellm_spec_acceptance_rate",
    "edgellm_spec_hops_per_token",
    # prefix-sharing paged KV cache
    "edgellm_prefix_hits_total",
    "edgellm_prefix_misses_total",
    "edgellm_prefix_saved_tokens_total",
    "edgellm_prefix_cow_forks_total",
    "edgellm_prefix_hit_rate",
    "edgellm_prefix_shared_pages",
    "edgellm_prefix_index_pages",
    # fused-hop probe provenance
    "edgellm_fused_hop_active",
    "edgellm_fused_hop_decision",
    "edgellm_fused_probe_win",
    # tracing plane
    "edgellm_flight_dumps_total",
    "edgellm_obs_scrapes_total",
    # cluster router (serve/cluster.py)
    "edgellm_cluster_replicas",
    "edgellm_cluster_live_replicas",
    "edgellm_cluster_pressure",
    "edgellm_cluster_parked",
    "edgellm_cluster_placements_total",
    "edgellm_cluster_kills_total",
    "edgellm_cluster_respawns_total",
    "edgellm_cluster_readmitted_total",
    "edgellm_cluster_recompute_tokens_total",
    "edgellm_cluster_autoscale_events_total",
    # disaggregated prefill/decode (serve/disagg.py)
    "edgellm_disagg_migrations_total",
    "edgellm_disagg_pages_migrated_total",
    "edgellm_disagg_wire_bytes_total",
    "edgellm_disagg_recompute_tokens_total",
    "edgellm_disagg_readmitted_total",
    "edgellm_disagg_prefill_workers",
    "edgellm_disagg_queue_depth",
    "edgellm_disagg_degraded",
    # gray-failure plane (serve/overload.py StragglerDetector +
    # serve/cluster.py hedging + deadline propagation)
    "edgellm_gray_stragglers",
    "edgellm_gray_hedge_delay_s",
    "edgellm_gray_hedges_total",
    "edgellm_gray_hedge_wins_total",
    "edgellm_gray_deadline_expired_total",
    "edgellm_gray_demotions_total",
})

#: templates for adapter families whose middle segment is a runtime key
#: (fault-counter names, recovery counters, link-health gauges); an f-string
#: call site lints against these with its holes as ``*``
METRIC_TEMPLATES: Tuple[str, ...] = (
    "edgellm_link_*_total",
    "edgellm_recovery_*_total",
    "edgellm_link_health_*",
    "edgellm_spec_*_total",
)

#: every literal span name
SPAN_NAMES: FrozenSet[str] = frozenset({
    # serve/decode.py
    "generate.prefill",
    "generate.decode_loop",
    "generate_split.prefill",
    "generate_split.decode_loop",
    "decode.checkpoint_write",
    "decode.checkpoint_resume",
    "decode.failover",
    # serve/speculative.py
    "generate_spec.prefill",
    "generate_spec.resume_draft_prefill",
    "generate_spec.burst_loop",
    # serve/recovery.py
    "recovery.checkpoint_save",
    "recovery.checkpoint_load",
    # serve/frontend.py + serve/batching.py (request-scoped tracing plane)
    "serve.submit",
    "serve.execute",
    "batch.submit",
    "batch.admit",
    "batch.step",
    # per-cut boundary-hop attribution (decode, speculative, eval)
    "split.hop",
    # eval/split_eval.py
    "eval.checkpoint_write",
    "eval.failover",
    "eval.submit_group",
    "eval.drain_group",
    "eval.time_hops",
    "eval.time_decode_hops",
    # lint graph-layer probe
    "lint.obs-identity-probe",
    # serve/cluster.py replica lifecycle (rare paths only — the router's
    # per-request hot path stays span-free for the 10⁶-request soak)
    "cluster.kill",
    "cluster.respawn",
    "cluster.autoscale",
    # serve/disagg.py migration lifecycle (per-page hop attribution rides
    # on disagg.migrate_page's sid/wid/page attrs)
    "disagg.prefill",
    "disagg.migrate",
    "disagg.migrate_page",
    "disagg.adopt",
    "disagg.degrade",
    "disagg.kill",
    "disagg.readmit",
    # gray-failure plane: hedges and straggler verdict flips are rare by
    # construction (bounded by max_hedge_fraction / dwell hysteresis), so
    # spanning them keeps the per-request hot path span-free
    "cluster.hedge",
    "gray.demote",
})

#: span-name templates (none yet — span names are all static today); kept so
#: EG007 treats spans and metrics uniformly
SPAN_TEMPLATES: Tuple[str, ...] = ()


def _registered(pattern: str, names: FrozenSet[str],
                templates: Tuple[str, ...]) -> bool:
    if "*" in pattern:
        # an f-string call site: its hole pattern must be a registered
        # template verbatim — matching a template *partially* would let
        # ``f"edgellm_link_{x}z_total"`` slip through
        return pattern in templates
    return pattern in names or any(fnmatchcase(pattern, t)
                                   for t in templates)


def metric_registered(name_or_pattern: str) -> bool:
    """True when a literal metric name (or the ``*``-holed pattern of an
    f-string call site) is in the registered vocabulary."""
    return _registered(name_or_pattern, METRIC_NAMES, METRIC_TEMPLATES)


def span_registered(name_or_pattern: str) -> bool:
    """Span-name twin of :func:`metric_registered`."""
    return _registered(name_or_pattern, SPAN_NAMES, SPAN_TEMPLATES)
