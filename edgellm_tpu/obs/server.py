"""Live telemetry endpoint: a zero-dependency stdlib ``http.server``
background thread exposing the obs plane while a run is in flight.

Endpoints:

- ``/metrics``       — the registry's Prometheus text exposition
  (``text/plain; version=0.0.4``), scrapeable by a stock Prometheus.
- ``/healthz``       — JSON liveness summary; the serve front installs a
  provider reporting breaker states, brownout level, queue depth, and the
  link-health window. Without a provider it reports ``{"status": "ok"}``.
- ``/snapshot.json`` — the registry's full JSON snapshot plus (when a
  flight recorder is configured) the live ring state.
- ``/trace``         — the tracer's Chrome trace of everything recorded so
  far; save the body and load it at https://ui.perfetto.dev.

Design: ``ThreadingHTTPServer`` on a daemon thread, bound to localhost by
default; ``port=0`` lets the OS pick (tests and parallel CI jobs). Request
logging is silenced — the serve loop's stdout is the product. Every
response is built from a point-in-time snapshot under the collectors' own
locks, so scraping mid-soak never torn-reads the registry.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from . import flight as _flight
from . import metrics as _metrics
from . import tracing as _tracing

__all__ = ["ObsServer", "get_global", "start_global", "stop_global"]

_PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class ObsServer:
    """One background HTTP server over the (default: global) obs state."""

    def __init__(self, port: int = 0, *, host: str = "127.0.0.1",
                 registry: Optional["_metrics.MetricsRegistry"] = None,
                 tracer: Optional["_tracing.Tracer"] = None,
                 health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 flight: Optional["_flight.FlightRecorder"] = None) -> None:
        self._registry = registry
        self._tracer = tracer
        self.health_fn = health_fn
        self._flight = flight
        self._host = host
        self._requested_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # resolve lazily so a server built before obs.enable() still serves the
    # armed global collectors
    def _reg(self) -> "_metrics.MetricsRegistry":
        return self._registry or _metrics.get_registry()

    def _trc(self) -> "_tracing.Tracer":
        return self._tracer or _tracing.get_tracer()

    def _fl(self) -> Optional["_flight.FlightRecorder"]:
        return self._flight or _flight.get_flight_recorder()

    @property
    def port(self) -> Optional[int]:
        """The bound port (resolves ``port=0``), or None before start()."""
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self) -> Optional[str]:
        return (f"http://{self._host}:{self.port}"
                if self._httpd else None)

    # -- endpoint bodies ----------------------------------------------------

    def _count_scrape(self, endpoint: str) -> None:
        reg = self._reg()
        if reg.enabled:
            reg.counter("edgellm_obs_scrapes_total",
                        "live-endpoint scrapes served").inc(endpoint=endpoint)

    def render(self, path: str) -> Optional[tuple]:
        """(status, content_type, body bytes) for one GET, None -> 404."""
        if path == "/metrics":
            self._count_scrape("metrics")
            return 200, _PROM_CONTENT_TYPE, \
                self._reg().to_prometheus().encode("utf-8")
        if path == "/healthz":
            self._count_scrape("healthz")
            health: Dict[str, Any] = {"status": "ok"}
            if self.health_fn is not None:
                try:
                    health = dict(self.health_fn())
                except Exception as e:  # provider broke: report, stay up
                    health = {"status": "error", "error": repr(e)}
            return 200, "application/json", \
                json.dumps(health, sort_keys=True,
                           default=repr).encode("utf-8")
        if path == "/snapshot.json":
            self._count_scrape("snapshot")
            snap: Dict[str, Any] = {
                "metrics": json.loads(self._reg().to_json())}
            fl = self._fl()
            if fl is not None:
                snap["flight"] = fl.snapshot()
            return 200, "application/json", \
                json.dumps(snap, sort_keys=True,
                           default=repr).encode("utf-8")
        if path == "/trace":
            self._count_scrape("trace")
            return 200, "application/json", \
                json.dumps(self._trc().to_chrome_trace()).encode("utf-8")
        return None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the bound port."""
        if self._httpd is not None:
            return self._httpd.server_address[1]
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                try:
                    out = server.render(self.path.split("?", 1)[0])
                except Exception as e:  # never let a scrape kill the thread
                    self.send_response(500)
                    body = repr(e).encode("utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if out is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                status, ctype, body = out
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args: Any) -> None:
                pass  # the serve loop owns stdout

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="edgellm-obs-server", daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._httpd = None
        self._thread = None


_SERVER: Optional[ObsServer] = None


def start_global(port: int, **kwargs: Any) -> ObsServer:
    """Start (or return) the process-global server — the ``--obs-port`` /
    params ``"observability": {"obs_port": ...}`` path."""
    global _SERVER
    if _SERVER is None:
        _SERVER = ObsServer(port, **kwargs)
        _SERVER.start()
    return _SERVER


def get_global() -> Optional[ObsServer]:
    """The running process-global server, or None — lets late-constructed
    components (the serve front) attach their health provider to it."""
    return _SERVER


def stop_global() -> None:
    global _SERVER
    if _SERVER is not None:
        _SERVER.stop()
        _SERVER = None
