"""Request-scoped trace context, propagated host-side through the serve
stack.

A :class:`TraceContext` names where in the serving hierarchy an observation
happened: which request (``rid``), which batcher stream/slot (``sid`` /
``slot``), which pipeline µ-batch (``microbatch``), which speculative burst
(``spec_burst``). The context rides a :mod:`contextvars` variable, so it

- follows the host thread that opened it (``ServeFront.submit`` →
  ``_execute`` → ``generate_split`` → hop accounting) with zero plumbing
  through the call signatures, and
- is isolated per thread — a multi-threaded front never cross-labels
  requests.

Every span the :mod:`~edgellm_tpu.obs.tracing` tracer opens while a context
is bound inherits the context's non-``None`` fields as span args (explicit
span kwargs win on collision). The whole mechanism is host-side Python —
nothing here is visible to jit tracing, so the disabled-obs graph-identity
fingerprints are untouched by construction.
"""
from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import itertools
from typing import Any, Dict, Iterator, Optional

__all__ = ["TraceContext", "bind", "current", "current_labels", "next_rid"]


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """The request-scoped labels. All fields optional: deeper layers refine
    the binding (the batcher knows the slot, the spec loop the burst)."""

    rid: Optional[str] = None         #: serve-front request id
    sid: Optional[int] = None         #: batcher stream id
    slot: Optional[int] = None        #: batcher slot index
    microbatch: Optional[int] = None  #: pipeline µ-batch index
    spec_burst: Optional[int] = None  #: speculative burst index

    def labels(self) -> Dict[str, Any]:
        """The non-``None`` fields, as span-arg / metric-label material."""
        return {f.name: v for f in dataclasses.fields(self)
                if (v := getattr(self, f.name)) is not None}


_CURRENT: contextvars.ContextVar[Optional[TraceContext]] = \
    contextvars.ContextVar("edgellm_trace_context", default=None)

_RID_COUNTER = itertools.count()


def current() -> Optional[TraceContext]:
    """The bound context of this thread/task, or None outside any bind."""
    return _CURRENT.get()


def current_labels() -> Dict[str, Any]:
    """``current().labels()`` or ``{}`` — the tracer's merge source."""
    ctx = _CURRENT.get()
    return ctx.labels() if ctx is not None else {}


@contextlib.contextmanager
def bind(**fields: Any) -> Iterator[TraceContext]:
    """Bind (or refine) the current context for the ``with`` body.

    Fields given here override the enclosing binding's; unset fields are
    inherited, so ``bind(rid=...)`` at the front composes with a later
    ``bind(spec_burst=...)`` deep in the spec loop::

        with context.bind(rid=rid):
            ...
            with context.bind(spec_burst=b):   # rid still attached
                ...
    """
    base = _CURRENT.get()
    ctx = (dataclasses.replace(base, **fields) if base is not None
           else TraceContext(**fields))
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


def next_rid(prefix: str = "r") -> str:
    """A process-unique request id (``r0``, ``r1``, ...) for callers that
    arrive without one — eval chunks, ad-hoc generate calls."""
    return f"{prefix}{next(_RID_COUNTER)}"
