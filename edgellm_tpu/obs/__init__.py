"""Unified telemetry: metrics registry, span tracer, per-token latency SLOs.

One place every number lives. Before this package, observability was
scattered ad hoc: per-hop fault counters in ``codecs/faults.py``, recovery
bookkeeping in ``serve/recovery.py``, windowed link-health rates in
``codecs/fec.py``, a jit-miss counter in ``serve/decode.py`` — each with its
own dict shape, its own reporting path, and no latency distributions at all.
The three pillars here:

- :mod:`~edgellm_tpu.obs.metrics` — typed ``Counter``/``Gauge``/``Histogram``
  (log-spaced buckets, interpolated p50/p95/p99), a process-global named
  registry, Prometheus text-format + JSON exporters, and adapters that absorb
  every legacy counter source. The :class:`~edgellm_tpu.obs.metrics
  .CounterSource` protocol replaces the ``hasattr(rt, "link_counters")``
  duck-typing in the serve loops.
- :mod:`~edgellm_tpu.obs.tracing` — thread-safe host-side spans on a
  monotonic clock, exported as Chrome trace-event JSON (load in Perfetto),
  bridged to ``jax.profiler.TraceAnnotation`` so host spans line up with the
  device timeline; :func:`~edgellm_tpu.obs.tracing.trace_capture` subsumes
  the old ``utils.profiling.trace`` stub.
- :mod:`~edgellm_tpu.obs.latency` — TTFT + per-token latency histograms for
  the decode loops, measured at *sample boundaries* (one host sync per
  sampled token, never per-op) so observation does not serialize dispatch.

Everything is host-side: with observability disabled (the default) the serve
and split stacks trace the byte-identical pre-feature jaxprs — enforced as a
graphlint identity contract — and enabled instrumentation stays within a 3%
decode-overhead budget (regression-tested).
"""
from __future__ import annotations

import dataclasses

from . import latency, metrics, tracing
from .latency import LatencyObserver
from .metrics import (Counter, CounterSource, Gauge, Histogram,
                      MetricsRegistry, get_registry)
from .tracing import Tracer, get_tracer, span, trace_capture


@dataclasses.dataclass(frozen=True)
class ObservabilityConfig:
    """Which pillars to arm when observability is requested (the params.json
    ``"observability"`` object and the ``--metrics-out``/``--trace-out``
    flags both resolve to one of these). All three default on — requesting
    observability without naming pillars arms the whole subsystem."""

    metrics: bool = True
    tracing: bool = True
    latency: bool = True

    def __post_init__(self) -> None:
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if not isinstance(v, bool):
                raise ValueError(f"observability.{f.name} must be a boolean, "
                                 f"got {v!r}")


def enable(config: ObservabilityConfig | None = None) -> None:
    """Arm the global registry/tracer per ``config`` (default: everything)."""
    cfg = config if config is not None else ObservabilityConfig()
    metrics.get_registry().enabled = cfg.metrics
    tracing.configure(enabled=cfg.tracing)


def disable() -> None:
    """Back to the default: metrics and tracing both off (the zero-overhead,
    graph-identical state the lint contract checks)."""
    metrics.get_registry().enabled = False
    tracing.configure(enabled=False)


def enabled() -> bool:
    return metrics.get_registry().enabled or tracing.tracing_enabled()


__all__ = [
    "Counter", "CounterSource", "Gauge", "Histogram", "LatencyObserver",
    "MetricsRegistry", "ObservabilityConfig", "Tracer", "disable", "enable",
    "enabled", "get_registry", "get_tracer", "latency", "metrics", "span",
    "trace_capture", "tracing",
]
