"""Unified telemetry: metrics registry, span tracer, per-token latency SLOs.

One place every number lives. Before this package, observability was
scattered ad hoc: per-hop fault counters in ``codecs/faults.py``, recovery
bookkeeping in ``serve/recovery.py``, windowed link-health rates in
``codecs/fec.py``, a jit-miss counter in ``serve/decode.py`` — each with its
own dict shape, its own reporting path, and no latency distributions at all.
The three pillars here:

- :mod:`~edgellm_tpu.obs.metrics` — typed ``Counter``/``Gauge``/``Histogram``
  (log-spaced buckets, interpolated p50/p95/p99), a process-global named
  registry, Prometheus text-format + JSON exporters, and adapters that absorb
  every legacy counter source. The :class:`~edgellm_tpu.obs.metrics
  .CounterSource` protocol replaces the ``hasattr(rt, "link_counters")``
  duck-typing in the serve loops.
- :mod:`~edgellm_tpu.obs.tracing` — thread-safe host-side spans on a
  monotonic clock, exported as Chrome trace-event JSON (load in Perfetto),
  bridged to ``jax.profiler.TraceAnnotation`` so host spans line up with the
  device timeline; :func:`~edgellm_tpu.obs.tracing.trace_capture` subsumes
  the old ``utils.profiling.trace`` stub.
- :mod:`~edgellm_tpu.obs.latency` — TTFT + per-token latency histograms for
  the decode loops, measured at *sample boundaries* (one host sync per
  sampled token, never per-op) so observation does not serialize dispatch.

Everything is host-side: with observability disabled (the default) the serve
and split stacks trace the byte-identical pre-feature jaxprs — enforced as a
graphlint identity contract — and enabled instrumentation stays within a 3%
decode-overhead budget (regression-tested).
"""
from __future__ import annotations

import dataclasses

from . import context, flight, latency, metrics, names, server, tracing
from .context import TraceContext
from .flight import (FlightRecorder, configure_flight, flight_dump_for,
                     get_flight_recorder, load_flight)
from .latency import LatencyObserver
from .metrics import (Counter, CounterSource, Gauge, Histogram,
                      MetricsRegistry, get_registry)
from .server import ObsServer
from .tracing import Tracer, get_tracer, span, trace_capture


@dataclasses.dataclass(frozen=True)
class ObservabilityConfig:
    """Which pillars to arm when observability is requested (the params.json
    ``"observability"`` object and the ``--metrics-out``/``--trace-out``
    flags both resolve to one of these). The three classic pillars default
    on — requesting observability without naming pillars arms the whole
    subsystem; the tracing-plane extras (flight recorder, live endpoint)
    stay opt-in."""

    metrics: bool = True
    tracing: bool = True
    latency: bool = True
    #: False = off; True = record into ``flight_recorder/`` under the cwd;
    #: a string names the artifact directory
    flight_recorder: bool | str = False
    #: None = no live endpoint; 0 = bind an OS-assigned port; else the port
    obs_port: int | None = None

    def __post_init__(self) -> None:
        for f in dataclasses.fields(self):
            if f.name in ("flight_recorder", "obs_port"):
                continue
            v = getattr(self, f.name)
            if not isinstance(v, bool):
                raise ValueError(f"observability.{f.name} must be a boolean, "
                                 f"got {v!r}")
        fr = self.flight_recorder
        if not isinstance(fr, (bool, str)):
            raise ValueError(f"observability.flight_recorder must be a "
                             f"boolean or a directory path, got {fr!r}")
        p = self.obs_port
        if p is not None and (isinstance(p, bool) or not isinstance(p, int)
                              or not 0 <= p <= 65535):
            raise ValueError(f"observability.obs_port must be null or an "
                             f"integer in [0, 65535], got {p!r}")


def enable(config: ObservabilityConfig | None = None) -> None:
    """Arm the global registry/tracer per ``config`` (default: everything);
    opt-in extras also arm the flight recorder and the live endpoint."""
    cfg = config if config is not None else ObservabilityConfig()
    metrics.get_registry().enabled = cfg.metrics
    tracing.configure(enabled=cfg.tracing)
    if cfg.flight_recorder and flight.get_flight_recorder() is None:
        out_dir = (cfg.flight_recorder
                   if isinstance(cfg.flight_recorder, str)
                   else "flight_recorder")
        flight.configure_flight(FlightRecorder(out_dir))
    if cfg.obs_port is not None:
        server.start_global(cfg.obs_port)


def disable() -> None:
    """Back to the default: metrics and tracing both off (the zero-overhead,
    graph-identical state the lint contract checks), flight recorder
    detached, live endpoint stopped."""
    metrics.get_registry().enabled = False
    tracing.configure(enabled=False)
    flight.configure_flight(None)
    server.stop_global()


def enabled() -> bool:
    return metrics.get_registry().enabled or tracing.tracing_enabled()


__all__ = [
    "Counter", "CounterSource", "FlightRecorder", "Gauge", "Histogram",
    "LatencyObserver", "MetricsRegistry", "ObservabilityConfig", "ObsServer",
    "TraceContext", "Tracer", "configure_flight", "context", "disable",
    "enable", "enabled", "flight", "flight_dump_for", "get_flight_recorder",
    "get_registry", "get_tracer", "latency", "load_flight", "metrics",
    "names", "server", "span", "trace_capture", "tracing",
]
