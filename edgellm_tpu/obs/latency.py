"""Per-token latency SLOs for the decode loops: TTFT and inter-token
latency histograms, measured without serializing the dispatch stream.

The measurement discipline matters more than the histogram: JAX dispatch is
async, so a naive ``time.monotonic()`` around each step measures *enqueue*
latency (microseconds) not *token* latency. A ``block_until_ready`` on
every intermediate would be worse — it serializes the stream the decode
loop deliberately keeps deep. The correct boundary is the **sampled
token**: the (B,) int32 array each step must materialize anyway before it
feeds the next step's embedding lookup. :meth:`LatencyObserver.token`
blocks on exactly that array — one host sync per token, at a point the
data dependency already forces — so observed latency is true per-token
wall clock and overhead stays inside the 3% budget the regression test
enforces (EG005's host-sync lint explicitly allows ``block_until_ready``
for this reason; ``.item()`` in the loop would be flagged).

``generate``/``generate_split`` accept ``observe=LatencyObserver(...)``;
with ``observe=None`` (default) the loops are untouched.
"""
from __future__ import annotations

import math
import time
from typing import Any, Dict, Optional

from . import metrics as _metrics

__all__ = ["LatencyObserver"]


def _block(x: Any) -> None:
    """Block until the sampled token is on host-reachable memory. Guarded:
    numpy arrays (already host) and test doubles pass through."""
    try:
        import jax
        jax.block_until_ready(x)
    except ImportError:  # pragma: no cover - bare-stdlib fallback
        pass


class LatencyObserver:
    """Accumulates TTFT and per-token latency for one or more generate calls.

    Protocol (driven by the decode loops):

    - :meth:`start` at the top of a call, before prefill dispatch;
    - :meth:`first_token` with the prefill-sampled token — blocks on it,
      records time-to-first-token;
    - :meth:`token` with each decode step's sampled token — blocks on it,
      records the inter-token gap;
    - :meth:`summary` for the ``{ttft_s, p50/p95/p99, ...}`` dict the
      caller folds into ``stats``; :meth:`publish` mirrors both histograms
      into the global registry (self-gated on ``registry.enabled``).

    Histograms span 10µs–100s with ~3%-wide log buckets, so p99 is exact
    to well under the bucket width at any realistic token rate.
    """

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None) -> None:
        self._registry = registry
        self._ttft = _metrics.Histogram(
            "edgellm_decode_ttft_seconds",
            "prefill start to first sampled token",
            lo=1e-5, hi=1e2, n_buckets=480)
        self._tok = _metrics.Histogram(
            "edgellm_decode_token_latency_seconds",
            "gap between consecutive sampled tokens",
            lo=1e-5, hi=1e2, n_buckets=480)
        self._t_start: Optional[float] = None
        self._t_last: Optional[float] = None

    def start(self) -> None:
        self._t_start = time.monotonic()
        self._t_last = None

    def first_token(self, sampled: Any) -> None:
        _block(sampled)
        now = time.monotonic()
        if self._t_start is not None:
            self._ttft.observe(now - self._t_start)
        self._t_last = now

    def token(self, sampled: Any) -> None:
        _block(sampled)
        now = time.monotonic()
        if self._t_last is not None:
            self._tok.observe(now - self._t_last)
        self._t_last = now

    @property
    def ttft(self) -> _metrics.Histogram:
        return self._ttft

    @property
    def token_latency(self) -> _metrics.Histogram:
        return self._tok

    def summary(self) -> Dict[str, float]:
        """The SLO block ``generate`` folds into its stats dict."""
        out: Dict[str, float] = {}
        tp = self._ttft.percentiles()
        if self._ttft.count:
            out["ttft_s"] = tp["mean"]
            out["ttft_p50_s"] = tp["p50"]
        kp = self._tok.percentiles()
        if self._tok.count:
            out["token_latency_p50_s"] = kp["p50"]
            out["token_latency_p95_s"] = kp["p95"]
            out["token_latency_p99_s"] = kp["p99"]
            out["token_latency_mean_s"] = kp["mean"]
            if kp["mean"] and not math.isnan(kp["mean"]):
                out["tokens_per_s_observed"] = 1.0 / kp["mean"]
        return out

    def publish(self) -> None:
        """Mirror the private histograms into the (global or injected)
        registry so exporters and ``--metrics-out`` see them. Self-gated:
        a disabled registry records nothing."""
        reg = (self._registry if self._registry is not None
               else _metrics.get_registry())
        if not reg.enabled:
            return
        for h in (self._ttft, self._tok):
            dst = reg.histogram(h.name, h.help, lo=h.edges[1],
                                hi=h.edges[-1],
                                n_buckets=len(h.edges) - 2)
            if dst is not h:
                dst.merge_from(h)
