"""Host-side span tracing: nested spans on a monotonic clock, exported as
Chrome trace-event JSON (load the file at https://ui.perfetto.dev), bridged
into ``jax.profiler`` so host spans line up with the device timeline.

Design points:

- **Thread-safe, nesting-aware.** Each thread keeps its own open-span stack
  (``threading.local``); finished spans append to one locked list. Chrome's
  viewer infers nesting from ``ts``/``dur`` on the same ``tid``, which the
  per-thread stack discipline guarantees.
- **Disabled is near-free.** :func:`span` hands back a shared
  ``nullcontext`` when tracing is off — no allocation, no clock read, no
  lock. The serve loops call it unconditionally.
- **Device bridge.** When tracing is on and jax is importable, each span
  also enters ``jax.profiler.TraceAnnotation``, so a
  ``jax.profiler.trace`` capture (see :func:`trace_capture`) shows host
  spans on the TensorBoard/Perfetto device timeline. The bridge degrades
  silently when jax or its profiler is unavailable — tracing must work in
  a bare-stdlib process.
- **trace_capture** wraps ``jax.profiler.trace`` (the XLA-level profiler
  dump) and subsumes the old ``utils.profiling.trace`` stub, which now
  delegates here.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import warnings
from typing import Any, Callable, Dict, Iterator, List, Optional

from . import context as _context
from ..utils.concurrency import guarded_by

__all__ = [
    "Span", "Tracer", "configure", "get_tracer", "span", "trace_capture",
    "tracing_enabled",
]


class Span:
    """One finished (or open) span: name, µs timestamps, attributes."""

    __slots__ = ("name", "ts_us", "dur_us", "tid", "args")

    def __init__(self, name: str, ts_us: float, tid: int,
                 args: Optional[Dict[str, Any]] = None) -> None:
        self.name = name
        self.ts_us = ts_us
        self.dur_us: float = 0.0
        self.tid = tid
        self.args: Dict[str, Any] = dict(args) if args else {}

    def to_event(self) -> Dict[str, Any]:
        """Chrome trace-event 'X' (complete) event."""
        ev: Dict[str, Any] = {"name": self.name, "ph": "X",
                              "ts": self.ts_us, "dur": self.dur_us,
                              "pid": os.getpid(), "tid": self.tid}
        if self.args:
            ev["args"] = {k: (v if isinstance(v, (int, float, str, bool))
                              else repr(v)) for k, v in self.args.items()}
        return ev


def _jax_annotation(name: str) -> contextlib.AbstractContextManager:
    try:  # bridge is best-effort: bare-stdlib processes still trace
        import jax.profiler as _prof
        return _prof.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


@guarded_by("_lock", fields=["_spans"])
class Tracer:
    """Collects spans process-wide; one instance behind :func:`get_tracer`."""

    def __init__(self, enabled: bool = False) -> None:
        self.enabled = enabled
        self._spans: List[Span] = []
        self._lock = threading.Lock()
        self._stack = threading.local()
        self._t0 = time.monotonic()
        #: closed-span hook (the flight recorder's ring feed); exceptions
        #: are swallowed — observation must never take down serving
        self._sink: Optional[Callable[[Span], None]] = None

    def _now_us(self) -> float:
        return (time.monotonic() - self._t0) * 1e6

    def set_sink(self, sink: Optional[Callable[[Span], None]]) -> None:
        self._sink = sink

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Optional[Span]]:
        if not self.enabled:
            yield None
            return
        stack = getattr(self._stack, "open", None)
        if stack is None:
            stack = self._stack.open = []
        labels = _context.current_labels()
        if labels:  # ambient request labels; explicit span kwargs win
            labels.update(attrs)
            attrs = labels
        s = Span(name, self._now_us(), threading.get_ident(), attrs)
        stack.append(s)
        try:
            with _jax_annotation(name):
                yield s
        finally:
            s.dur_us = self._now_us() - s.ts_us
            stack.pop()
            with self._lock:
                self._spans.append(s)
            sink = self._sink
            if sink is not None:
                try:
                    sink(s)
                except Exception:  # pragma: no cover - defensive
                    pass

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The Perfetto-loadable trace object."""
        with self._lock:
            events = [s.to_event() for s in self._spans]
        events.sort(key=lambda e: (e["tid"], e["ts"]))
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export(self, path: str) -> None:
        """Write the Chrome trace-event JSON atomically (.part → rename)."""
        tmp = path + ".part"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f, indent=1)
        os.replace(tmp, path)


_TRACER = Tracer()
_NULL = contextlib.nullcontext()  # shared: span() when disabled allocates nothing


def get_tracer() -> Tracer:
    return _TRACER


def configure(*, enabled: bool) -> None:
    _TRACER.enabled = enabled


def tracing_enabled() -> bool:
    return _TRACER.enabled


def span(name: str, **attrs: Any) -> contextlib.AbstractContextManager:
    """Module-level span on the global tracer; the form call sites use:

        with obs.span("decode.checkpoint", step=k):
            ...
    """
    if not _TRACER.enabled:
        return _NULL
    return _TRACER.span(name, **attrs)


@contextlib.contextmanager
def trace_capture(log_dir: Optional[str]) -> Iterator[None]:
    """Optionally capture a ``jax.profiler.trace`` XLA profile to ``log_dir``
    (None → no-op). Degrades to a warning when the profiler cannot start
    (double capture, missing backend support) instead of killing the run —
    same contract the old ``utils.profiling.trace`` stub had, which now
    shims onto this."""
    if not log_dir:
        yield
        return
    cm: Optional[contextlib.AbstractContextManager] = None
    try:
        import jax.profiler as _prof
        cm = _prof.trace(log_dir)
        cm.__enter__()
    except Exception as e:  # pragma: no cover - import/env/double-capture
        warnings.warn(f"jax profiler trace unavailable ({e}); "
                      "continuing without XLA capture", stacklevel=2)
        cm = None
    try:
        yield
    finally:
        if cm is not None:
            try:
                cm.__exit__(None, None, None)
            except RuntimeError as e:  # pragma: no cover - profiler teardown
                warnings.warn(f"jax profiler trace failed to stop ({e})",
                              stacklevel=2)
