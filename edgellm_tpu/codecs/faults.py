"""Boundary-wire fault layer: seeded fault injection, payload integrity, and
bounded-retry / degradation policies for the split pipeline's ``ppermute`` hops.

The split runtimes model every cut as a lossless collective; the reference's
edge-network premise says otherwise. This module makes the wire *faulty on
purpose* — reproducibly — and makes the receiver notice:

- :class:`FaultConfig`: a seeded, jit-compatible injector spec. Bit flips hit
  the packed payload bytes through a ``bitcast_convert_type`` byte view (any
  leaf dtype), scale corruption multiplies float leaves, whole-hop drops zero
  the entire sealed payload, and a per-hop byte budget statically squeezes
  hops whose packed payload no longer fits. Everything is driven by
  ``fold_in`` chains off one seed, so two runs with the same seed corrupt the
  same bytes on the same hops.
- :func:`seal_payload` / :func:`verify_payload`: a canary word plus a weighted
  byte checksum folded into every payload pytree before the ``ppermute`` and
  checked after it. The per-byte weights are odd (``(2i+1) * Knuth``), and an
  odd weight is invertible mod 2**32 — so any single corrupted byte always
  changes the sum; a dropped payload zeroes the canary. Corruption is
  *detected and counted*, never silently decoded into the next stage.
- :class:`FaultyLink`: the hop protocol under faults — encode, seal, inject,
  ``ppermute``, verify, with ``LinkPolicy.max_retries`` statically-unrolled
  re-sends (every attempt re-rolls its injection key, so a retry can genuinely
  recover), and on exhausted retries either a zero-state substitution with a
  counted degradation flag or a counted pass-through of the corrupted decode.
- :class:`TierController`: the host-side hysteresis half of graceful
  degradation — consecutive corrupted chunks step the hop codecs down a
  precision ladder (int8 -> int4 -> ternary), consecutive clean chunks step
  back up. Codec tiers change payload *shapes*, so switching happens between
  jitted calls, never inside one.

With ``FaultConfig.enabled`` false the runtimes build the exact pre-fault
graph — the zero-rate path is bit-identical to a fault-free build, and tests
assert it.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..lint import graph_contract
# the wire primitives (canary + checksum seal, byte accounting) moved to
# wire_format.py so the fused hops share the exact byte layout; re-exported
# here verbatim — every existing import path and traced graph is unchanged
from .wire_format import (CANARY, _CRC_MULT, _leaf_crc,  # noqa: F401
                          payload_checksum, seal_payload, tree_nbytes,
                          verify_payload)

#: per-hop counter names accumulated by :class:`FaultyLink` (all (n_hops,)
#: int32, receiver-side, psum-replicated by the pipeline protocol):
#: hops = transfers attempted, detected = corrupted arrivals caught by the
#: integrity check, retried = re-sends actually needed, recovered = hops that
#: failed at least once but eventually verified, substituted = hops that
#: exhausted retries and fell back per the policy, budget_dropped = hops whose
#: packed payload statically exceeded the byte budget. A self-healing link
#: (:mod:`~edgellm_tpu.codecs.fec`) appends "repaired" (corrupted arrivals
#: healed in band by XOR parity) and "hedge_wins" (hops a non-primary
#: staggered route delivered first) via :attr:`FaultyLink.counter_keys`.
COUNTER_KEYS = ("hops", "detected", "retried", "recovered", "substituted",
                "budget_dropped")


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Seeded wire-fault rates. All rates are per *attempt*; ``bitflip_rate``
    is per payload byte, ``scale_corrupt_rate`` per float element,
    ``drop_rate`` per hop. ``byte_budget`` (bytes) statically squeezes any hop
    whose packed payload exceeds it. ``enabled`` False builds the exact
    fault-free graph."""

    bitflip_rate: float = 0.0
    scale_corrupt_rate: float = 0.0
    drop_rate: float = 0.0
    byte_budget: Optional[int] = None
    seed: int = 0

    def __post_init__(self):
        for f in ("bitflip_rate", "scale_corrupt_rate", "drop_rate"):
            v = getattr(self, f)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"{f} must be a number, got {v!r}")
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{f} must be in [0, 1], got {v}")
        if self.byte_budget is not None and (
                isinstance(self.byte_budget, bool)
                or not isinstance(self.byte_budget, int)
                or self.byte_budget <= 0):
            raise ValueError(f"byte_budget must be a positive integer, "
                             f"got {self.byte_budget!r}")
        if isinstance(self.seed, bool) or not isinstance(self.seed, int):
            raise ValueError(f"seed must be an integer, got {self.seed!r}")

    @property
    def enabled(self) -> bool:
        return (self.bitflip_rate > 0 or self.scale_corrupt_rate > 0
                or self.drop_rate > 0 or self.byte_budget is not None)


@dataclasses.dataclass(frozen=True)
class LinkPolicy:
    """What the receiver does about a hop that fails integrity.

    ``max_retries`` re-sends are statically unrolled inside the jitted hop
    (each with a fresh injection key). When all attempts fail:
    ``on_fail="substitute"`` forwards a zero hidden state and counts the hop
    as degraded; ``on_fail="passthrough"`` decodes the corrupted payload
    anyway (the "silently poisoned" baseline, but counted). ``tiers`` names
    the codec degradation ladder the host-side :class:`TierController` walks
    (int8 -> int4 -> ternary by default when adaptive mode is requested);
    ``degrade_after`` / ``recover_after`` are its hysteresis thresholds in
    consecutive chunks."""

    max_retries: int = 0
    on_fail: str = "substitute"
    tiers: tuple = ()
    degrade_after: int = 2
    recover_after: int = 8

    def __post_init__(self):
        if self.on_fail not in ("substitute", "passthrough"):
            raise ValueError(f"on_fail must be 'substitute' or 'passthrough', "
                             f"got {self.on_fail!r}")
        for f, lo in (("max_retries", 0), ("degrade_after", 1),
                      ("recover_after", 1)):
            v = getattr(self, f)
            if isinstance(v, bool) or not isinstance(v, int) or v < lo:
                raise ValueError(f"{f} must be an integer >= {lo}, got {v!r}")


def inject_faults(sealed: dict, key: jax.Array,
                  cfg: FaultConfig) -> dict:
    """Corrupt a sealed payload tree per ``cfg``, deterministically from
    ``key``. Bit flips and drops hit every leaf (sidecar included — a flipped
    checksum is a detected corruption too); scale corruption hits float
    leaves. Zero-rate configs return the tree untouched (same graph)."""
    leaves, treedef = jax.tree_util.tree_flatten(sealed)
    drop = (jax.random.uniform(jax.random.fold_in(key, 0xD0)) < cfg.drop_rate
            if cfg.drop_rate > 0 else None)
    out = []
    for j, x in enumerate(leaves):
        kj = jax.random.fold_in(key, j)
        if cfg.bitflip_rate > 0 and x.size:
            b = jax.lax.bitcast_convert_type(x, jnp.uint8)
            k_hit, k_bit = jax.random.split(kj)
            hit = jax.random.bernoulli(k_hit, cfg.bitflip_rate, b.shape)
            bit = jax.random.randint(k_bit, b.shape, 0, 8).astype(jnp.uint8)
            b = b ^ jnp.where(hit, jnp.left_shift(jnp.uint8(1), bit),
                              jnp.uint8(0))
            x = jax.lax.bitcast_convert_type(b, x.dtype)
        if (cfg.scale_corrupt_rate > 0 and x.size
                and jnp.issubdtype(x.dtype, jnp.floating)):
            k_sc = jax.random.fold_in(kj, 0x5C)
            hit = jax.random.bernoulli(k_sc, cfg.scale_corrupt_rate, x.shape)
            # affine blowup: moves every value, zeros included
            x = jnp.where(hit, x * x.dtype.type(-997.0) + x.dtype.type(1.0), x)
        if drop is not None:
            x = jnp.where(drop, jnp.zeros_like(x), x)
        out.append(x)
    return jax.tree_util.tree_unflatten(treedef, out)


def _bump(counters: dict, key: str, hop: int, cond) -> dict:
    new = dict(counters)
    new[key] = counters[key].at[hop].add(jnp.asarray(cond).astype(jnp.int32))
    return new


@dataclasses.dataclass(frozen=True)
class FaultyLink:
    """The hop protocol under faults — a static closure the pipeline unroll
    calls in place of the bare encode/ppermute/decode when faults are on.

    ``fec`` (a :class:`~edgellm_tpu.codecs.fec.FECConfig`) and ``hedge``
    (a :class:`~edgellm_tpu.codecs.fec.HedgeConfig`) arm the self-healing
    ladder — in-band XOR-parity repair and staggered redundant routes; with
    both absent or disabled, :meth:`hop` is the exact PR 2 protocol and the
    traced graph is bit-identical to a pre-FEC build."""

    faults: FaultConfig
    policy: LinkPolicy
    fec: Optional[Any] = None
    hedge: Optional[Any] = None

    @property
    def healing(self) -> bool:
        return ((self.fec is not None and self.fec.enabled)
                or (self.hedge is not None and self.hedge.enabled))

    @property
    def counter_keys(self) -> tuple:
        keys = COUNTER_KEYS
        if self.fec is not None and self.fec.enabled:
            keys = keys + ("repaired",)
        if self.hedge is not None and self.hedge.enabled:
            keys = keys + ("hedge_wins",)
        return keys

    def init_counters(self, n_hops: int) -> dict:
        return {k: jnp.zeros((n_hops,), jnp.int32) for k in self.counter_keys}

    @graph_contract(
        "faults.hop",
        # per cut: every statically-unrolled attempt re-sends every sealed
        # leaf (payload + canary + crc); the psum count is the structural
        # output replication plus one per replicated counter. The lint driver
        # traces a faulted split forward and supplies the measured ctx.
        collectives=lambda ctx: {"ppermute": ctx["hop_eqns"],
                                 "psum": ctx["n_psum"]},
        wire_dtypes=lambda ctx: ctx["wire_dtypes"],
        wire_bytes=lambda ctx: ctx["wire_bytes"])
    def hop(self, codec: Any, hidden: jnp.ndarray, s: int, axis_name: str,
            idx: jnp.ndarray, key: jax.Array, counters: dict,
            hop_imp: Optional[jnp.ndarray] = None) -> tuple:
        """One faulty boundary crossing stage s -> s+1 (inside shard_map).

        Encode once; then up to 1+max_retries sealed transmissions, each with
        its own injection key. Every device runs every attempt (static
        unroll); the receiver's verify gates which attempt's decode is kept,
        and counters accumulate receiver-side only so the later psum counts
        each hop exactly once. Returns (new hidden, counters)."""
        if self.healing:
            from .fec import healing_hop

            return healing_hop(self, codec, hidden, s, axis_name, idx, key,
                               counters, hop_imp)
        if codec.needs_importance:
            payload = codec.encode(hidden, hop_imp)
        else:
            payload = codec.encode(hidden)
        over_budget = (self.faults.byte_budget is not None
                       and tree_nbytes(payload) > self.faults.byte_budget)
        sealed = seal_payload(payload)
        k_hop = jax.random.fold_in(key, s)
        recv = idx == s + 1
        ok = jnp.asarray(False)
        first_fail = jnp.asarray(False)
        decoded = jnp.zeros_like(hidden)
        last_dec = jnp.zeros_like(hidden)
        counters = _bump(counters, "hops", s, recv)
        if over_budget:
            counters = _bump(counters, "budget_dropped", s, recv)
        for a in range(1 + max(self.policy.max_retries, 0)):
            needed = jnp.logical_not(ok)  # this attempt actually transmits
            corrupted = inject_faults(sealed, jax.random.fold_in(k_hop, a),
                                      self.faults)
            moved = jax.tree_util.tree_map(
                lambda x: jax.lax.ppermute(x, axis_name, [(s, s + 1)]),
                corrupted)
            ok_a = verify_payload(moved)
            if over_budget:  # squeezed link: the payload never fits
                ok_a = jnp.logical_and(ok_a, False)
            dec_a = codec.decode(moved["p"])
            decoded = jnp.where(jnp.logical_and(needed, ok_a), dec_a, decoded)
            last_dec = jnp.where(needed, dec_a, last_dec)
            counters = _bump(counters, "detected", s,
                             recv & needed & ~ok_a)
            if a > 0:
                counters = _bump(counters, "retried", s, recv & needed)
            if a == 0:
                first_fail = jnp.logical_not(ok_a)
            ok = jnp.logical_or(ok, ok_a)
        counters = _bump(counters, "recovered", s, recv & ok & first_fail)
        if self.policy.on_fail == "substitute":
            counters = _bump(counters, "substituted", s, recv & ~ok)
            final = jnp.where(ok, decoded, jnp.zeros_like(hidden))
        else:  # passthrough: accept the corrupted decode, but count it
            counters = _bump(counters, "substituted", s, recv & ~ok)
            final = jnp.where(ok, decoded, last_dec)
        return jnp.where(recv, final, hidden), counters


class TierController:
    """Host-side hysteresis over a codec degradation ladder.

    ``observe(corrupted)`` once per evaluation chunk: ``degrade_after``
    consecutive corrupted chunks step to the next (lower-precision) tier,
    ``recover_after`` consecutive clean chunks step back up. Both streaks
    reset on a switch, so the controller can't oscillate every chunk."""

    def __init__(self, n_tiers: int, degrade_after: int = 2,
                 recover_after: int = 8):
        if n_tiers < 1:
            raise ValueError("need at least one tier")
        self.n_tiers = n_tiers
        self.degrade_after = degrade_after
        self.recover_after = recover_after
        self.tier = 0
        self.switches = 0
        self._bad = 0
        self._good = 0

    def observe(self, corrupted: bool) -> int:
        if corrupted:
            self._bad += 1
            self._good = 0
            if self._bad >= self.degrade_after and self.tier < self.n_tiers - 1:
                self.tier += 1
                self.switches += 1
                self._bad = 0
        else:
            self._good += 1
            self._bad = 0
            if self._good >= self.recover_after and self.tier > 0:
                self.tier -= 1
                self.switches += 1
                self._good = 0
        return self.tier


def sum_counters(counter_list: Optional[Sequence[dict]]) -> Optional[dict]:
    """Host-side total of per-call counter dicts -> {key: (n_hops,) int64
    ndarray}. None/empty in, None out."""
    if not counter_list:
        return None
    tot = {k: np.zeros_like(np.asarray(counter_list[0][k]), dtype=np.int64)
           for k in counter_list[0]}
    for c in counter_list:
        for k, v in c.items():
            tot[k] = tot[k] + np.asarray(v, dtype=np.int64)
    return tot


def flatten_counters(counters: Optional[dict]) -> dict:
    """Collapse a per-hop counter dict ({key: (n_hops,) ints}) to per-key
    scalar totals: {key: int}. The shape reports and the obs metric adapters
    want; None/empty in, {} out."""
    if not counters:
        return {}
    return {k: int(np.asarray(v, dtype=np.int64).sum())
            for k, v in counters.items()}
