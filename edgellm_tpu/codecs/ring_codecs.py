"""Sequence-sharded (ring) variants of the token-selective boundary codec.

The reference's headline scheme — quantize the ``ratio`` least-important tokens
of the boundary activation to symmetric int4 with one global scale
(``/root/reference/Experiments/Qwen2-0.5B/qwen_layer_wise.py:54-73``) — selects
tokens by a GLOBAL argsort of the importance vector. Under the stage x seq
runtime no device holds the full sequence, so the selection and the scale must
be agreed across sequence shards. Two variants, both running INSIDE
``shard_map`` on the ring axis:

- ``mode="global"`` — exact reference semantics. The (B, S) importance vector
  (a scalar per token — tiny next to the (B, S, D) activation) is
  ``all_gather``-ed over the ring axis so every shard computes the SAME stable
  argsort as the dense codec; the int4 scale is the ``pmax`` of the per-shard
  maxima over selected tokens (exactly the global max). Decoded values are
  bit-identical to the dense ``selective_int4`` codec given the same
  importance. The wire price of exactness: the number of selected tokens per
  shard is data-dependent, so the low buffer is capacity-padded to
  ``min(S_loc, k)`` and the high tokens ship IN PLACE (a full ``S_loc``-token
  buffer) — per-token bytes are ``high + c_low/S_loc * (D/2 + 2)``, i.e.
  MORE than an all-``high`` hop. Use it when reference parity matters more
  than wire bytes (it is the parity oracle for the local mode).

- ``mode="local"`` — the wire-optimal scalable variant. Each shard selects its
  own ``int(ratio * S_loc)`` least-important LOCAL tokens (same compression
  ratio, shard-local ordering) while the int4 scale is still agreed globally
  via ``pmax`` so all shards quantize on one grid. Static per-shard payload
  sizes equal the dense codec's per-token bytes exactly; the selected SET may
  differ from the dense global argsort (it is the per-shard restriction of a
  rank-balanced selection), so PPL is close to but not bit-equal with the
  dense path. MEASURED accuracy cost at the flagship ring shape
  (``tools/ring_mode_gap.py``: qwen2-0.5b, cut 11, S=2048, n_seq=4,
  ``configs/split5b_qwen_ring_selective.json``): |dNLL vs mode="global"|
  <= 8.4e-4 at ratio 0.25 and <= 1.6e-3 at ratio 0.5 — two orders of
  magnitude below the reference's own PPL deltas between adjacent ratios.
  ``tests/test_ring_codecs.py`` asserts a 0.02 bound; ``dryrun_multichip``
  records the local-vs-global |dNLL| in every round's MULTICHIP artifact.

Both accept shared ``(S_loc,)`` or per-row ``(B, S_loc)`` LOCAL importance
shards, mirroring the dense codec's wire format rules.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from .packing import (WireCodec, _jnp_quant_pack, _jnp_unpack_dequant,
                      selective_int4, _saturating, SATURATE_MAG)


@dataclasses.dataclass(frozen=True)
class RingWireCodec(WireCodec):
    """A wire codec whose encode/decode run inside ``shard_map`` on
    ``ring_axis`` and move one LOCAL sequence shard per device. Collectives
    inside ``encode`` make ``jax.eval_shape``-based byte accounting impossible
    outside the mesh, so payload bytes are computed analytically (verified
    against the in-mesh buffers in ``tests/test_ring_codecs.py``)."""

    ring_axis: str = "seq"
    n_seq: int = 1
    #: (full_hidden_shape, per_row) -> total payload bytes across all shards
    payload_bytes_fn: object = None

    def payload_bytes(self, hidden_shape, dtype=jnp.float32,
                      per_row: bool = True) -> int:
        """``per_row`` picks the wire format being accounted: per-row (B, S)
        importance carries a (B,) scale and (B, c_low) int16 indices per
        shard; shared (S,) importance carries a (1,) scale and (c_low,)
        indices. ``SplitRingRuntime`` forces per-row whenever batch > 1, so
        the default matches what actually crosses the hop."""
        return int(self.payload_bytes_fn(hidden_shape, per_row))


_HIGH_DTYPES = {"fp32": jnp.float32, "bf16": jnp.bfloat16, "fp16": jnp.float16}


def _global_scale(low, k_known_nonempty, axis_name, per_row):
    """max|selected| on this shard -> pmax over the ring = the global max,
    with the dense codec's zero/empty guard applied AFTER the reduction."""
    if per_row:
        local = jnp.max(jnp.abs(low), axis=(1, 2)) if k_known_nonempty \
            else jnp.zeros((low.shape[0],), jnp.float32)
    else:
        local = jnp.max(jnp.abs(low)) if k_known_nonempty else jnp.asarray(0.0)
    mx = jax.lax.pmax(local, axis_name)
    return jnp.where(mx > 0, mx, 1.0)


def ring_selective_int4(ratio: float, high: str = "bf16", *, n_seq: int,
                        axis_name: str = "seq",
                        mode: str = "global") -> RingWireCodec:
    """Build the ring-sharded token-selective codec (see module docstring)."""
    if not 0.0 <= ratio <= 1.0:
        raise ValueError(f"ratio must be in [0, 1], got {ratio}")
    if mode not in ("global", "local"):
        raise ValueError(f"mode must be 'global' or 'local', got {mode!r}")
    if n_seq < 1:
        raise ValueError(f"n_seq must be >= 1, got {n_seq}")
    high_dtype = _HIGH_DTYPES[high]
    high_bytes = jnp.dtype(high_dtype).itemsize

    # ---------- mode="global": exact dense selection ----------

    def encode_global(h_loc, imp_loc):
        b, s_loc, d = h_loc.shape
        s = s_loc * n_seq
        if s > 32767:
            raise ValueError(f"selective_int4 int16 side channel needs "
                             f"S <= 32767, got {s}")
        k = int(ratio * s)  # static, same float64 truncation as dense
        c_low = min(s_loc, k)
        idx = jax.lax.axis_index(axis_name)
        per_row = jnp.ndim(imp_loc) == 2
        # the small collective: gather the per-token importance scalars and
        # run the SAME stable argsort the dense codec runs -> identical set
        imp_full = jax.lax.all_gather(imp_loc, axis_name, axis=-1, tiled=True)
        order = jnp.argsort(imp_full, axis=-1)  # (S,) or (B, S), ascending
        low_global = order[..., :k]  # global positions of the selected tokens
        # membership mask for THIS shard's positions [idx*s_loc, (idx+1)*s_loc)
        full_mask = jnp.zeros(imp_full.shape, bool)
        if per_row:
            rows = jnp.arange(b)[:, None]
            full_mask = full_mask.at[rows, low_global].set(k > 0)
            mask_loc = jax.lax.dynamic_slice_in_dim(
                full_mask, idx * s_loc, s_loc, axis=1)  # (B, S_loc)
            # compacted local low positions; empty slots point past the shard
            low_idx = jax.vmap(
                lambda m: jnp.nonzero(m, size=c_low, fill_value=s_loc)[0])(
                    mask_loc)  # (B, c_low)
            take = jnp.minimum(low_idx, s_loc - 1)
            low = jnp.where((low_idx < s_loc)[..., None],
                            h_loc[rows, take], 0.0)  # (B, c_low, D)
            safe = _global_scale(low, k > 0, axis_name, True)  # (B,)
            packed = (_jnp_quant_pack(low, safe[:, None, None]) if c_low
                      else jnp.zeros((b, 0, d // 2), jnp.uint8))
            return {"low": packed, "scale": safe,
                    "high": h_loc.astype(high_dtype),  # in place; low slots
                    "idx": low_idx.astype(jnp.int16)}  # overwritten on decode
        full_mask = full_mask.at[low_global].set(k > 0)
        mask_loc = jax.lax.dynamic_slice_in_dim(full_mask, idx * s_loc, s_loc, 0)
        low_idx = jnp.nonzero(mask_loc, size=c_low, fill_value=s_loc)[0]
        take = jnp.minimum(low_idx, s_loc - 1)
        low = jnp.where((low_idx < s_loc)[None, :, None],
                        jnp.take(h_loc, take, axis=1), 0.0)  # (B, c_low, D)
        safe = _global_scale(low, k > 0, axis_name, False)
        packed = (_jnp_quant_pack(low, safe) if c_low
                  else jnp.zeros((b, 0, d // 2), jnp.uint8))
        return {"low": packed, "scale": safe[None],
                "high": h_loc.astype(high_dtype),
                "idx": low_idx.astype(jnp.int16)}

    def decode_global(p):
        out = p["high"].astype(jnp.float32)  # (B, S_loc, D)
        b, s_loc, d = out.shape
        c_low = p["low"].shape[1]
        if not c_low:
            return out
        if p["scale"].ndim == 1 and p["scale"].shape[0] == b and p["idx"].ndim == 2:
            low = _jnp_unpack_dequant(p["low"], p["scale"][:, None, None])
            rows = jnp.arange(b)[:, None]
            # empty capacity slots carry index s_loc -> dropped by the scatter
            return out.at[rows, p["idx"].astype(jnp.int32)].set(
                low, mode="drop")
        low = _jnp_unpack_dequant(p["low"], p["scale"][0])
        return out.at[:, p["idx"].astype(jnp.int32)].set(low, mode="drop")

    # ---------- mode="local": shard-local selection, global scale ----------
    # the dense codec applied to each shard (its encode sees the LOCAL
    # sequence, so k becomes int(ratio * S_loc) automatically), with only the
    # scale reduction swapped for the ring-agreed pmax — one wire-format
    # definition, no drift

    def ring_scale(low, nonempty, per_row):
        return _global_scale(low, nonempty, axis_name, per_row)

    local_base = selective_int4(ratio, high, scale_fn=ring_scale)

    def payload_bytes_fn(hidden_shape, per_row=True):
        """Total bytes across all n_seq shard payloads for one full (B, S, D)
        boundary activation (what actually crosses the stage hop). The scale
        and index side channels follow the wire format: per-row importance
        ships a (B,) scale + (B, c_low) int16 indices, shared importance a
        (1,) scale + (c_low,) indices (ADVICE r4 — the old accounting
        assumed per-row for both)."""
        b, s, d = hidden_shape
        s_loc = s // n_seq
        rows = b if per_row else 1
        if mode == "global":
            k = int(ratio * s)
            c_low = min(s_loc, k)
            per_shard = (b * c_low * (d // 2)       # packed int4 capacity
                         + b * s_loc * d * high_bytes  # in-place high buffer
                         + rows * c_low * 2         # int16 local indices
                         + rows * 4)                # fp32 scale
        else:
            k_loc = int(ratio * s_loc)
            per_shard = (b * k_loc * (d // 2)
                         + b * (s_loc - k_loc) * d * high_bytes
                         + rows * k_loc * 2
                         + rows * 4)
        return n_seq * per_shard

    enc = encode_global if mode == "global" else local_base.encode
    dec = decode_global if mode == "global" else local_base.decode
    # same pathological-input saturation as the dense codec (mode="local"
    # inherits it via local_base; wrapping twice is an identity)
    return _saturating(RingWireCodec(
        name=f"ring_selective_int4_r{ratio}_{high}_{mode}",
        encode=enc, decode=dec,
        batch_invariant=False, needs_importance=True,
        ring_axis=axis_name, n_seq=n_seq, payload_bytes_fn=payload_bytes_fn),
        min(SATURATE_MAG, float(jnp.finfo(high_dtype).max)))
