"""Self-healing boundary links: in-band FEC repair, hedged hops, and a
host-side link-health SLO controller over the faulty split wire.

PR 2's fault layer *detects* corruption (canary + weighted-byte checksum) but
every detected hop costs a full re-transmission, a codec tier, or a zeroed
substitute. This module spends a declared fraction of the wire on parity so
single-event corruption is repaired IN BAND, with zero extra hops:

- :class:`FECConfig` + :func:`fec_encode` / :func:`fec_decode`: the sealed
  payload's byte stream is interleaved round-robin into
  ``group_size * n_groups`` data chunks; chunk ``c`` joins parity group
  ``c % n_groups``, so a contiguous burst up to ``n_groups`` chunks wide
  lands in distinct groups. Every group carries one XOR parity chunk, and
  every chunk (parity included) carries a canary-folded weighted-byte
  checksum word — the per-byte weights are odd (PR 2's ``(2i+1) * Knuth``
  construction), so any single corrupted byte in a chunk always trips its
  word, and the canary fold keeps a zeroed (dropped) chunk from agreeing
  with its zeroed word. A mismatching chunk is *located* by its word and
  *repaired* by a masked ``where``-select of ``parity ^ xor(group)`` — pure
  jit-compatible integer ops. Two bad chunks in one group exceed XOR parity;
  the outer PR 2 seal then fails and the hop falls back to retry.
- :func:`healing_hop`: the extended hop ladder — detect -> repair -> retry
  -> hedge -> (host-side) degrade -> substitute. With
  :class:`HedgeConfig` the payload rides ``routes`` staggered ``ppermute``
  transmissions per attempt, each with an independent injection key, and the
  receiver keeps the first verified copy — trading wire for latency on
  drop-dominated links where parity can't help (a drop zeroes every chunk).
- :class:`LinkHealth`: the SLO half — a host-side sibling of
  :class:`~edgellm_tpu.codecs.faults.TierController` that keeps windowed
  corruption / repair / retry / hedge-win rates from the per-call counter
  deltas, compares the *unrepaired* corruption rate against an error budget
  (its burn rate), degrades the codec tier while the budget burns, and
  re-promotes once it recovers — with a full-window re-measure plus a
  clock-based dwell between switches, so the tier can't flap.

With ``FECConfig.enabled`` false and no hedging, :class:`FaultyLink` never
calls into this module — the build is the exact PR 2/3 graph, bit-identical,
and a graphlint fingerprint contract asserts it.
"""
from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..lint import graph_contract
from ..utils.clock import MONOTONIC, Clock
from ..utils.concurrency import guarded_by
from .faults import (_CRC_MULT, _bump, inject_faults, seal_payload,
                     tree_nbytes, verify_payload)
# the byte-stream flatten/unflatten moved to wire_format.py (the fused hops
# cross the same flat layout); aliased to the historical private names
from .wire_format import flatten_bytes as _flatten_bytes
from .wire_format import unflatten_bytes as _unflatten_bytes

#: folded into every chunk checksum word so an all-zero (dropped) chunk and
#: its zeroed word can never agree
_CHUNK_CANARY = 0x5EA1C0DE


@dataclasses.dataclass(frozen=True)
class FECConfig:
    """Parity layout for the sealed boundary payload.

    ``group_size`` data chunks share one XOR parity chunk (the overhead knob:
    parity costs ~``1/group_size`` of the payload, plus 4 bytes of checksum
    word per chunk); ``n_groups`` parity groups interleave the byte stream,
    so a contiguous corruption burst up to ``n_groups`` chunks wide stays
    single-chunk-per-group — still repairable. ``enabled`` False builds the
    exact pre-FEC graph."""

    enabled: bool = True
    group_size: int = 4
    n_groups: int = 4

    def __post_init__(self):
        if not isinstance(self.enabled, bool):
            raise ValueError(f"enabled must be a boolean, got {self.enabled!r}")
        for f in ("group_size", "n_groups"):
            v = getattr(self, f)
            if isinstance(v, bool) or not isinstance(v, int) or v < 1:
                raise ValueError(f"{f} must be an integer >= 1, got {v!r}")

    @property
    def n_data_chunks(self) -> int:
        return self.group_size * self.n_groups

    def chunk_len(self, sealed_nbytes: int) -> int:
        return max(1, -(-int(sealed_nbytes) // self.n_data_chunks))

    def wire_nbytes(self, sealed_nbytes: int) -> int:
        """Static byte size of the FEC wire tree for a sealed payload of
        ``sealed_nbytes`` bytes: padded data + parity chunks + one uint32
        checksum word per chunk."""
        n_chunks = self.n_data_chunks + self.n_groups
        return n_chunks * self.chunk_len(sealed_nbytes) + 4 * n_chunks

    def overhead(self, sealed_nbytes: int) -> float:
        """Fractional wire overhead vs sending the sealed payload bare."""
        return self.wire_nbytes(sealed_nbytes) / max(sealed_nbytes, 1) - 1.0


@dataclasses.dataclass(frozen=True)
class HedgeConfig:
    """Hedged-hop mode: send ``routes`` staggered copies per attempt and keep
    the first verified one. Wire cost scales with ``routes``; latency (counted
    retries) falls on drop-dominated links."""

    enabled: bool = True
    routes: int = 2

    def __post_init__(self):
        if not isinstance(self.enabled, bool):
            raise ValueError(f"enabled must be a boolean, got {self.enabled!r}")
        if (isinstance(self.routes, bool) or not isinstance(self.routes, int)
                or self.routes < 2):
            raise ValueError(f"routes must be an integer >= 2, "
                             f"got {self.routes!r}")


def _chunk_words(chunks: jnp.ndarray) -> jnp.ndarray:
    """Per-chunk canary-folded weighted byte sums: (C, L) uint8 -> (C,)
    uint32. Weights are odd per position (invertible mod 2**32 — any single
    corrupted byte in a chunk always moves its word) and salted per chunk so
    chunks can't trade bytes; the XOR fold keeps an all-zero chunk from
    matching an all-zero word."""
    n_chunks, chunk_len = chunks.shape
    i = jnp.arange(chunk_len, dtype=jnp.uint32)[None, :]
    salt = (jnp.arange(n_chunks, dtype=jnp.uint32)
            * jnp.uint32(0x9E3779B1))[:, None]
    w = (jnp.uint32(2) * (i + salt) + jnp.uint32(1)) * jnp.uint32(_CRC_MULT)
    s = jnp.sum(chunks.astype(jnp.uint32) * w, axis=1, dtype=jnp.uint32)
    return s ^ jnp.uint32(_CHUNK_CANARY)


def fec_encode(sealed: Any, cfg: FECConfig) -> dict:
    """Sealed payload tree -> FEC wire tree ``{"chunks", "words"}``.

    ``chunks`` stacks the ``group_size * n_groups`` interleaved data chunks
    and the ``n_groups`` XOR parity chunks as one (C, L) uint8 array;
    ``words`` carries each chunk's locate-checksum. Byte i of the sealed
    stream lands in data chunk ``i % n_data_chunks`` (round-robin), and data
    chunk ``c`` belongs to parity group ``c % n_groups``."""
    stream = _flatten_bytes(sealed)
    d = cfg.n_data_chunks
    chunk_len = cfg.chunk_len(stream.size)
    pad = d * chunk_len - stream.size
    if pad:
        stream = jnp.pad(stream, (0, pad))
    data = stream.reshape(chunk_len, d).T  # (d, L): chunk c = byte i % d
    grouped = data.reshape(cfg.group_size, cfg.n_groups, chunk_len)
    parity = grouped[0]
    for s in range(1, cfg.group_size):
        parity = parity ^ grouped[s]
    chunks = jnp.concatenate([data, parity], axis=0)
    return {"chunks": chunks, "words": _chunk_words(chunks)}


def fec_decode(wire: dict, cfg: FECConfig, like: Any) -> tuple:
    """Arrived FEC wire tree -> (sealed tree, any_chunk_bad, repaired).

    Recomputes every chunk word; a mismatch locates the chunk. A group with
    exactly one bad data chunk and a good parity chunk is repaired by the
    masked XOR select ``parity ^ xor(all data in group) ^ bad_chunk`` (for a
    falsely-accused chunk — its word corrupted, its bytes fine — that select
    is the identity, so the repair is safely a no-op). Groups with two or
    more bad data chunks, or a dropped hop (every chunk bad), are beyond XOR
    parity and left for the retry ladder; the caller's outer
    :func:`~edgellm_tpu.codecs.faults.verify_payload` stays the authority on
    the reconstruction."""
    chunks, words = wire["chunks"], wire["words"]
    d = cfg.n_data_chunks
    chunk_len = chunks.shape[1]
    bad = _chunk_words(chunks) != words  # (d + n_groups,)
    bad_data = bad[:d].reshape(cfg.group_size, cfg.n_groups)
    bad_parity = bad[d:]
    n_bad = jnp.sum(bad_data.astype(jnp.int32), axis=0)  # per group
    repairable = jnp.logical_and(n_bad == 1, jnp.logical_not(bad_parity))
    grouped = chunks[:d].reshape(cfg.group_size, cfg.n_groups, chunk_len)
    gx = chunks[d:]  # parity ^ xor(data) == 0 when the group is intact
    for s in range(cfg.group_size):
        gx = gx ^ grouped[s]
    candidate = gx[None] ^ grouped  # the missing chunk, per slot
    fix = jnp.logical_and(bad_data, repairable[None])[:, :, None]
    grouped = jnp.where(fix, candidate, grouped)
    n = tree_nbytes(like)
    stream = grouped.reshape(d, chunk_len).T.reshape(-1)[:n]
    return _unflatten_bytes(stream, like), jnp.any(bad), jnp.any(fix)


@graph_contract(
    "fec.hop",
    # per cut: every transmission (attempts x hedge routes) re-sends the
    # 2-leaf FEC wire tree (chunk matrix + word vector); psums are the
    # structural output replication plus one per replicated counter. The
    # lint driver traces a FEC-enabled split forward and supplies the ctx.
    collectives=lambda ctx: {"ppermute": ctx["hop_eqns"],
                             "psum": ctx["n_psum"]},
    wire_dtypes=lambda ctx: ctx["wire_dtypes"],
    wire_bytes=lambda ctx: ctx["wire_bytes"])
def healing_hop(link: Any, codec: Any, hidden: jnp.ndarray, s: int,
                axis_name: str, idx: jnp.ndarray, key: jax.Array,
                counters: dict,
                hop_imp: Optional[jnp.ndarray] = None) -> tuple:
    """One self-healing boundary crossing stage s -> s+1 (inside shard_map).

    The full ladder per hop: seal, (FEC-encode,) then for every statically
    unrolled attempt send ``routes`` staggered copies — each with a fresh
    injection key — and on arrival locate + XOR-repair bad chunks before the
    outer integrity verdict gates which copy's decode is kept. ``detected``
    counts corrupted arrivals (repaired ones included), ``repaired`` the
    arrivals healed in band, ``hedge_wins`` the hops a non-primary route
    delivered first, ``retried`` the attempts (not routes) that actually
    re-transmitted. :class:`~edgellm_tpu.codecs.faults.FaultyLink.hop`
    dispatches here only when FEC or hedging is enabled — the disabled build
    never traces this function."""
    fec = link.fec if (link.fec is not None and link.fec.enabled) else None
    routes = (link.hedge.routes
              if link.hedge is not None and link.hedge.enabled else 1)
    if codec.needs_importance:
        payload = codec.encode(hidden, hop_imp)
    else:
        payload = codec.encode(hidden)
    over_budget = (link.faults.byte_budget is not None
                   and tree_nbytes(payload) > link.faults.byte_budget)
    sealed = seal_payload(payload)
    wire = fec_encode(sealed, fec) if fec is not None else sealed
    k_hop = jax.random.fold_in(key, s)
    recv = idx == s + 1
    ok = jnp.asarray(False)
    first_fail = jnp.asarray(False)
    decoded = jnp.zeros_like(hidden)
    last_dec = jnp.zeros_like(hidden)
    counters = _bump(counters, "hops", s, recv)
    if over_budget:
        counters = _bump(counters, "budget_dropped", s, recv)
    t = 0  # transmission index = fresh fault draw
    for a in range(1 + max(link.policy.max_retries, 0)):
        attempt_needed = None
        for r in range(routes):
            take = jnp.logical_not(ok)  # no earlier copy verified yet
            if r == 0:
                attempt_needed = take
            corrupted = inject_faults(wire, jax.random.fold_in(k_hop, t),
                                      link.faults)
            moved = jax.tree_util.tree_map(
                lambda x: jax.lax.ppermute(x, axis_name, [(s, s + 1)]),
                corrupted)
            if fec is not None:
                arrived, arrived_bad, did_repair = fec_decode(moved, fec,
                                                              sealed)
            else:
                arrived = moved
            ok_a = verify_payload(arrived)
            if over_budget:  # squeezed link: the payload never fits
                ok_a = jnp.logical_and(ok_a, False)
            dec_a = codec.decode(arrived["p"])
            decoded = jnp.where(jnp.logical_and(take, ok_a), dec_a, decoded)
            last_dec = jnp.where(take, dec_a, last_dec)
            if fec is not None:
                # chunk words can collide on multi-byte damage; the outer
                # seal is the authority, so a failed verdict counts detected
                arrived_bad = jnp.logical_or(arrived_bad, ~ok_a)
                counters = _bump(counters, "detected", s,
                                 recv & take & arrived_bad)
                counters = _bump(counters, "repaired", s,
                                 recv & take & did_repair & ok_a)
            else:
                counters = _bump(counters, "detected", s, recv & take & ~ok_a)
            if routes > 1 and r > 0:
                counters = _bump(counters, "hedge_wins", s,
                                 recv & take & ok_a)
            if t == 0:
                first_fail = jnp.logical_not(ok_a)
            ok = jnp.logical_or(ok, ok_a)
            t += 1
        if a > 0:
            counters = _bump(counters, "retried", s, recv & attempt_needed)
    counters = _bump(counters, "recovered", s, recv & ok & first_fail)
    counters = _bump(counters, "substituted", s, recv & ~ok)
    if link.policy.on_fail == "substitute":
        final = jnp.where(ok, decoded, jnp.zeros_like(hidden))
    else:  # passthrough: accept the last corrupted decode, but count it
        final = jnp.where(ok, decoded, last_dec)
    return jnp.where(recv, final, hidden), counters


# ---------------------------------------------------------------------------
# host-side SLO control
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LinkHealthConfig:
    """SLO budget for :class:`LinkHealth`. ``error_budget`` is the tolerated
    fraction of hops left corrupted after in-band repair; the burn rate is
    the windowed unrepaired-corruption rate divided by that budget.
    ``degrade_burn`` / ``promote_burn`` are the switch thresholds (with
    ``promote_burn`` strictly below ``degrade_burn`` — rate hysteresis), and
    ``min_dwell_s`` is the wall-clock floor between tier switches (time
    hysteresis; the clock is injectable for tests)."""

    window: int = 16
    error_budget: float = 0.02
    degrade_burn: float = 1.0
    promote_burn: float = 0.25
    min_dwell_s: float = 0.0

    def __post_init__(self):
        if (isinstance(self.window, bool) or not isinstance(self.window, int)
                or self.window < 1):
            raise ValueError(f"window must be an integer >= 1, "
                             f"got {self.window!r}")
        for f, lo, hi in (("error_budget", 0.0, 1.0),
                          ("degrade_burn", 0.0, float("inf")),
                          ("promote_burn", 0.0, float("inf")),
                          ("min_dwell_s", 0.0, float("inf"))):
            v = getattr(self, f)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"{f} must be a number, got {v!r}")
            if not lo <= v <= hi or (f in ("error_budget", "degrade_burn")
                                     and v <= 0):
                raise ValueError(f"{f} out of range: {v!r}")
        if self.promote_burn >= self.degrade_burn:
            raise ValueError(
                f"promote_burn ({self.promote_burn}) must be below "
                f"degrade_burn ({self.degrade_burn}) — no hysteresis band")


#: the counter names LinkHealth folds into its window (missing keys read 0,
#: so pre-FEC counter dicts observe cleanly)
_HEALTH_KEYS = ("hops", "detected", "repaired", "retried", "substituted",
                "hedge_wins")


@guarded_by("_lock", fields=["tier", "switches", "observations", "_window",
                             "_last_switch"])
class LinkHealth:
    """Host-side link SLO tracker and tier driver.

    ``observe(delta)`` once per call/chunk with that call's counter deltas
    (any :data:`~edgellm_tpu.codecs.faults.COUNTER_KEYS`-style dict of
    per-hop arrays or scalars). Over a full sliding window it keeps the
    corruption / repair / retry / hedge-win rates, and burns the error
    budget with the *unrepaired* corruption rate: ``burn >= degrade_burn``
    steps the codec tier down, ``burn <= promote_burn`` steps it back up.
    Every switch clears the window (the new tier gets a full re-measure) and
    arms the ``min_dwell_s`` clock, so a noisy link cannot flap the tier.

    Thread-safe: the decode thread observes while the obs scrape thread
    reads :meth:`summary` and the rate properties, so window/tier state
    mutates under ``_lock``. The registry publish happens *outside* the
    lock (it re-enters :meth:`summary`, and holding a lock across the
    metrics adapters would be a threadlint EG102/EG103 hazard)."""

    def __init__(self, n_tiers: int = 1,
                 config: Optional[LinkHealthConfig] = None,
                 clock: Clock = MONOTONIC):
        if n_tiers < 1:
            raise ValueError("need at least one tier")
        self.cfg = config if config is not None else LinkHealthConfig()
        self.n_tiers = n_tiers
        self.clock = clock
        self._lock = threading.Lock()
        self.tier = 0
        self.switches = 0
        self.observations = 0
        self._window: deque = deque(maxlen=self.cfg.window)
        self._last_switch: Optional[float] = None

    def observe(self, counters: Optional[dict]) -> int:
        tot = {k: 0 for k in _HEALTH_KEYS}
        if counters:
            for k in _HEALTH_KEYS:
                if k in counters:
                    tot[k] = int(np.asarray(counters[k]).sum())
        with self._lock:
            self._window.append(tot)
            self.observations += 1
            if len(self._window) == self.cfg.window:
                burn = self._burn_rate_locked()
                now = self.clock()
                dwell_ok = (self._last_switch is None
                            or now - self._last_switch >= self.cfg.min_dwell_s)
                if (burn >= self.cfg.degrade_burn and dwell_ok
                        and self.tier < self.n_tiers - 1):
                    self.tier += 1
                    self.switches += 1
                    self._last_switch = now
                    self._window.clear()
                elif (burn <= self.cfg.promote_burn and dwell_ok
                      and self.tier > 0):
                    self.tier -= 1
                    self.switches += 1
                    self._last_switch = now
                    self._window.clear()
            tier = self.tier
        self._publish()
        return tier

    def _publish(self) -> None:
        """Mirror the windowed SLO fields into the global obs registry.
        Lazy import + enabled gate: with observability off (the default)
        this is one attribute check per observation."""
        from ..obs.metrics import get_registry, record_link_health

        if get_registry().enabled:
            record_link_health(self.summary())

    def _sum_locked(self, key: str) -> int:
        return sum(o[key] for o in self._window)

    @property
    def corruption_rate(self) -> float:
        with self._lock:
            return self._sum_locked("detected") / max(
                self._sum_locked("hops"), 1)

    @property
    def repair_rate(self) -> float:
        """Fraction of detected corruption healed in band."""
        with self._lock:
            return self._sum_locked("repaired") / max(
                self._sum_locked("detected"), 1)

    @property
    def retry_rate(self) -> float:
        with self._lock:
            return self._sum_locked("retried") / max(
                self._sum_locked("hops"), 1)

    @property
    def hedge_win_rate(self) -> float:
        with self._lock:
            return self._sum_locked("hedge_wins") / max(
                self._sum_locked("hops"), 1)

    def _burn_rate_locked(self) -> float:
        unrepaired = (self._sum_locked("detected")
                      - self._sum_locked("repaired"))
        return ((unrepaired / max(self._sum_locked("hops"), 1))
                / self.cfg.error_budget)

    @property
    def burn_rate(self) -> float:
        """Windowed unrepaired-corruption rate over the error budget; >= 1
        means the link is out of SLO at the current tier."""
        with self._lock:
            return self._burn_rate_locked()

    def summary(self) -> dict:
        with self._lock:
            return {
                "tier": self.tier,
                "switches": self.switches,
                "observations": self.observations,
                "window": len(self._window),
                "error_budget": self.cfg.error_budget,
                "burn_rate": self._burn_rate_locked(),
                "corruption_rate": self._sum_locked("detected") / max(
                    self._sum_locked("hops"), 1),
                "repair_rate": self._sum_locked("repaired") / max(
                    self._sum_locked("detected"), 1),
                "retry_rate": self._sum_locked("retried") / max(
                    self._sum_locked("hops"), 1),
                "hedge_win_rate": self._sum_locked("hedge_wins") / max(
                    self._sum_locked("hops"), 1),
            }
