"""The boundary wire format: what bytes actually cross a cut.

Before the fused-hop work, "what crosses the wire" was a property of the hop
*implementation*: :mod:`~edgellm_tpu.codecs.faults` owned the canary/checksum
seal, :mod:`~edgellm_tpu.codecs.fec` owned the byte-stream flattening, and a
fused transport would have had to re-invent both. This module hoists the wire
layout into one place so every hop implementation — the separate
encode/``ppermute``/decode ladder, the faulty link, FEC parity framing, and
the fused single-buffer/remote-DMA hops — moves the *same bytes* in the *same
order*:

- :func:`seal_payload` / :func:`verify_payload` / :func:`payload_checksum`:
  the 8-byte integrity sidecar (canary word + weighted-byte checksum) sealed
  next to every payload pytree. The per-byte weights are odd
  (``(2i+1) * Knuth``), and an odd weight is invertible mod 2**32 — so any
  single corrupted byte always changes the sum; a dropped payload zeroes the
  canary. (Moved verbatim from ``codecs.faults``, which re-exports them; the
  traced graphs are unchanged.)
- :func:`flatten_bytes` / :func:`unflatten_bytes`: every leaf's bytes
  bitcast to uint8 and concatenated in tree-flatten order, and the inverse
  against a template tree (static slices — shapes/dtypes are trace-time
  constants). Promoted from ``codecs.fec``'s private helpers; FEC chunking
  and the fused flat-buffer hop now share one byte order by construction.
- :class:`WireFormat`: the layout of one hop's flat wire buffer for a given
  (codec, activation shape): ``[canary u32][crc u32][payload leaves in
  tree-flatten order]``, with static byte accounting (``wire_nbytes ==
  payload bytes + 8``) that the graphlint wire-byte contracts check against
  the traced ``ppermute`` traffic.

Because the seal word, checksum, and byte order live here, fault injection
(:func:`~edgellm_tpu.codecs.faults.inject_faults` corrupting the flat
buffer), FEC repair (chunking the same stream), hedging, and the fused
remote-copy kernel all interoperate: a fused hop's wire buffer round-trips
through ``WireFormat.from_wire`` into the exact sealed tree the unfused
ladder would have built.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np
import jax
import jax.numpy as jnp

#: canary word sealed next to every payload; a dropped hop arrives all-zero
#: and fails this check even when the zeroed payload's checksum is trivially 0
CANARY = 0x5EA1C0DE

#: Knuth's multiplicative-hash constant; ``(2i+1) * _CRC_MULT`` gives every
#: byte position a distinct ODD weight mod 2**32 (odd => invertible => any
#: single-byte change always moves the checksum)
_CRC_MULT = 2654435761


def tree_nbytes(tree: Any) -> int:
    """Static byte size of a payload pytree (shapes/dtypes are trace-time
    constants, so the byte-budget comparison is a python bool under jit)."""
    return int(sum(int(np.prod(a.shape)) * a.dtype.itemsize
                   for a in jax.tree_util.tree_leaves(tree)))


def _leaf_crc(leaf, salt: int):
    """Weighted byte sum of one leaf in uint32. Weights are odd (see
    _CRC_MULT), so flipping any single byte always changes the sum."""
    b = jax.lax.bitcast_convert_type(leaf, jnp.uint8).reshape(-1)
    if b.size == 0:
        return jnp.uint32(0)
    i = jnp.arange(b.size, dtype=jnp.uint32) + jnp.uint32(salt & 0xFFFFFFFF)
    w = (jnp.uint32(2) * i + jnp.uint32(1)) * jnp.uint32(_CRC_MULT)
    return jnp.sum(b.astype(jnp.uint32) * w, dtype=jnp.uint32)


def payload_checksum(payload: Any) -> jnp.ndarray:
    """uint32 checksum over every byte of every leaf; the per-leaf salt keys
    the positional weights so leaves can't trade bytes."""
    crc = jnp.uint32(0)
    for j, leaf in enumerate(jax.tree_util.tree_leaves(payload)):
        crc = crc + _leaf_crc(leaf, j * 0x9E3779B1)
    return crc


def seal_payload(payload: Any) -> dict:
    """Wrap a codec payload with its integrity sidecar (8 bytes: canary +
    checksum) — the tree that actually crosses the wire under faults."""
    return {"canary": jnp.full((1,), CANARY, jnp.uint32),
            "crc": payload_checksum(payload)[None],
            "p": payload}


def verify_payload(sealed: dict) -> jnp.ndarray:
    """Scalar bool: the arrived payload is intact (canary alive AND checksum
    matches a fresh computation over the arrived bytes)."""
    return jnp.logical_and(sealed["canary"][0] == jnp.uint32(CANARY),
                           payload_checksum(sealed["p"]) == sealed["crc"][0])


def flatten_bytes(tree: Any) -> jnp.ndarray:
    """Every leaf's bytes, concatenated in tree-flatten order -> (N,) uint8."""
    parts = []
    for leaf in jax.tree_util.tree_leaves(tree):
        parts.append(jax.lax.bitcast_convert_type(leaf, jnp.uint8).reshape(-1))
    return jnp.concatenate(parts) if parts else jnp.zeros((0,), jnp.uint8)


def unflatten_bytes(stream: jnp.ndarray, like: Any) -> Any:
    """Inverse of :func:`flatten_bytes` against a template tree (shapes and
    dtypes are trace-time constants, so every slice is static)."""
    leaves, treedef = jax.tree_util.tree_flatten(like)
    out, off = [], 0
    for leaf in leaves:
        itemsize = leaf.dtype.itemsize
        n = leaf.size * itemsize
        b = stream[off:off + n]
        off += n
        if itemsize == 1:
            x = jax.lax.bitcast_convert_type(b, leaf.dtype)
        else:
            x = jax.lax.bitcast_convert_type(b.reshape(-1, itemsize),
                                             leaf.dtype)
        out.append(x.reshape(leaf.shape))
    return jax.tree_util.tree_unflatten(treedef, out)


@dataclasses.dataclass(frozen=True)
class WireFormat:
    """The flat-buffer wire layout of one hop for a fixed (codec, activation
    shape): ``[canary u32][crc u32][payload leaves in tree-flatten order]``.

    ``sealed_spec`` is the abstract sealed tree (``ShapeDtypeStruct`` leaves)
    the buffer round-trips through; every byte count is a static trace-time
    constant, which is what lets the graphlint wire-byte contracts check the
    fused hop's single-buffer ``ppermute`` traffic against
    ``hop_bytes + 8`` per cut without executing anything."""

    codec_name: str
    sealed_spec: Any

    @classmethod
    def for_codec(cls, codec, hidden_shape, dtype=jnp.float32) -> "WireFormat":
        """The wire format of ``codec`` hopping one (B, S, D) activation."""
        payload = jax.eval_shape(codec.encode,
                                 jax.ShapeDtypeStruct(hidden_shape, dtype))
        sealed = jax.eval_shape(seal_payload, payload)
        return cls(codec_name=codec.name, sealed_spec=sealed)

    @property
    def payload_nbytes(self) -> int:
        """Codec payload bytes — matches ``WireCodec.payload_bytes``."""
        return tree_nbytes(self.sealed_spec["p"])

    @property
    def wire_nbytes(self) -> int:
        """Total flat-buffer bytes: payload + the 8-byte integrity sidecar."""
        return tree_nbytes(self.sealed_spec)

    def to_wire(self, sealed: dict) -> jnp.ndarray:
        """Sealed tree -> the (wire_nbytes,) uint8 buffer that crosses the
        cut. Pure bitcasts — bit-exact round-trip with :meth:`from_wire`."""
        return flatten_bytes(sealed)

    def from_wire(self, buf: jnp.ndarray) -> dict:
        """Arrived flat buffer -> sealed tree (static slices against the
        spec); feed it to :func:`verify_payload` and the codec's decode."""
        return unflatten_bytes(buf, self.sealed_spec)
