"""Boundary activation codecs.

``simulate`` holds the pure quantize->dequantize ("fake quant") codecs matching the
reference's simulated boundary compression; ``packing`` produces real packed byte
buffers (the thing that actually crosses the device boundary in the split runtime)
plus exact byte accounting.
"""
from .simulate import (
    token_select_mask,
    top_rho_mask,
    int4_token_select,
    simulate_symmetric,
    per_token_affine_int8,
    channel_wise_quant,
    CHANNEL_METHODS,
)

__all__ = [
    "token_select_mask",
    "top_rho_mask",
    "int4_token_select",
    "simulate_symmetric",
    "per_token_affine_int8",
    "channel_wise_quant",
    "CHANNEL_METHODS",
]
