"""Pallas TPU kernels for the boundary codec hot path.

The reference's clearest kernel-shaped code is its per-channel Python loop over
896 channels (``qwen_layer_wise.py:125-152``, SURVEY.md section 3.5); here the
codec ops are single fused TPU kernels: quantize + nibble/crumb-pack in one VMEM
pass (fp32 in -> packed uint8 + scales out, one HBM round-trip instead of
quantize/clip/round/pack each materializing an intermediate), and the matching
unpack + dequantize.

Layout notes (see ``pallas_guide.md``):
- blocks tile the token axis; the feature axis stays whole (a lane multiple for
  real models: 896, 512) so per-token reductions are single-block row reductions;
- packing pairs element i with element i + D/2 (contiguous halves — full-lane
  slices, no strided lane access); identical to ``packing.pack_int4``;
- interpret mode runs the same kernels on CPU (used by the test suite; the
  wrappers auto-select based on the backend).

Kernel inventory (each bit-identical to its jnp twin in ``packing`` — tested):
- ``int4_per_token``: per-row max-abs scale + quantize + pack, fully fused;
- ``int8_per_token``: per-row affine (min/max -> scale, zero-point) + quantize;
- channel-scale ternary quantize+pack (``ternary_mean`` / ``ternary_max``;
  the (B,S) channel-scale reduction stays in XLA);
- channel-scale int8 quantize and int4 quantize+pack (``int8_per_channel`` /
  ``int4_per_channel`` — the reference's 896-channel Python loop as one pass).

``selective_int4`` deliberately has NO kernel twin — a measured round-5
deletion, not a gap: the codec is gather-bound and XLA fuses the quantize
into the gather chain, so the twin could only lose (``SELECTIVE_EXCLUSION``
carries the numbers; the probe records it every bench run).

``pallas_wire_codec`` / ``pallas_int8_per_token`` / ``pallas_ternary`` wrap
these in the :class:`~edgellm_tpu.codecs.packing.WireCodec` interface;
``pallas_variant`` maps any jnp wire codec to its Pallas twin (the split
runtime substitutes automatically on TPU where the probe cache says the twin
wins on this chip).
"""
from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .packing import WireCodec


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _encode_kernel(x_ref, packed_ref, scale_ref):
    """One token-tile: per-row max-abs scale -> int4 codes -> packed nibbles."""
    x = x_ref[:]  # (T, D) fp32
    half = x.shape[-1] // 2
    max_val = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    safe = jnp.where(max_val > 0, max_val, 1.0)
    codes = jnp.round(jnp.clip(x / safe * 7.0, -8.0, 7.0)).astype(jnp.int32) + 8
    lo, hi = codes[:, :half], codes[:, half:]
    packed_ref[:] = (lo | (hi << 4)).astype(jnp.uint8)
    scale_ref[:] = safe


def _decode_kernel(packed_ref, scale_ref, out_ref):
    """Unpack nibbles -> dequantize. ONE body for every int4 scale granularity:
    ``scale_ref[:]`` broadcasts a per-row (T, 1), global (1, 1), or per-channel
    (1, D) scale block identically, so the unpack logic exists exactly once.
    Arithmetic order matches the per-token jnp twin bit-for-bit; the per-channel
    twin multiplies scale before the /7 (<=1 ulp apart, within the decode
    tolerance the twin tests pin)."""
    packed = packed_ref[:].astype(jnp.int32)  # (T, D/2)
    lo = (packed & 0xF) - 8
    hi = ((packed >> 4) & 0xF) - 8
    codes = jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)
    out_ref[:] = codes / 7.0 * scale_ref[:]


def _tile(n_tokens: int) -> int:
    """Token-tile size: sublane-friendly, bounded by the token count."""
    for t in (256, 128, 64, 32, 16, 8):
        if n_tokens % t == 0:
            return t
    return n_tokens


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_encode_pallas(x: jnp.ndarray, interpret: bool | None = None):
    """(N, D) fp32 -> (packed (N, D/2) uint8, scale (N, 1) fp32), fused."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    grid = (n // t,)
    return pl.pallas_call(
        _encode_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((t, d), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((t, d // 2), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d // 2), jnp.uint8),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x.astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_decode_pallas(packed: jnp.ndarray, scale: jnp.ndarray,
                       interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`int4_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, dh = packed.shape
    t = _tile(n)
    return pl.pallas_call(
        _decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, dh), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((t, dh * 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, dh * 2), jnp.float32),
        interpret=interpret,
    )(packed, scale)


def _int8_affine_encode_kernel(x_ref, q_ref, scale_ref, mn_ref):
    """Per-row affine int8: scale = (max-min)/255, zero-point from min."""
    x = x_ref[:]  # (T, D) fp32
    mn = jnp.min(x, axis=-1, keepdims=True)
    mx = jnp.max(x, axis=-1, keepdims=True)
    scale = (mx - mn) * jnp.float32(1.0 / 255.0)  # matches packing.py bit-for-bit
    safe = jnp.where(scale > 0, scale, 1.0)
    zp = jnp.round(-128.0 - mn / safe)
    q_ref[:] = jnp.clip(jnp.round(x / safe) + zp, -128, 127).astype(jnp.int8)
    scale_ref[:] = scale
    mn_ref[:] = mn


def _int8_affine_decode_kernel(q_ref, scale_ref, mn_ref, out_ref):
    scale, mn = scale_ref[:], mn_ref[:]
    safe = jnp.where(scale > 0, scale, 1.0)
    zp = jnp.round(-128.0 - mn / safe)
    deq = (q_ref[:].astype(jnp.float32) - zp) * safe
    out_ref[:] = jnp.where(scale > 0, deq, mn)


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_affine_encode_pallas(x: jnp.ndarray, interpret: bool | None = None):
    """(N, D) fp32 -> (q (N, D) int8, scale (N, 1) fp32, mn (N, 1) fp32)."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _int8_affine_encode_kernel,
        grid=(n // t,),
        in_specs=[pl.BlockSpec((t, d), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), jnp.int8),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x.astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_affine_decode_pallas(q: jnp.ndarray, scale: jnp.ndarray, mn: jnp.ndarray,
                              interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`int8_affine_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = q.shape
    t = _tile(n)
    return pl.pallas_call(
        _int8_affine_decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((t, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.float32),
        interpret=interpret,
    )(q, scale, mn)


# The scalar-scale int4 quantize core that once backed a selective_int4
# kernel twin was DELETED in round 5, on measurement (VERDICT r4 weak #1 /
# next #2): the codec is gather-bound, and XLA fuses the quantize into its
# gather consumers, so a pallas_call boundary can only break that fusion —
# the twin probed 0.97x (r4) and, split, encode 0.97x / decode 0.99x (r5) on
# the v5e. The in-kernel alternatives lose structurally: a VMEM row gather
# is sublane-granular (1-row copies waste 7/8 of the VPU), a one-hot-matmul
# gather multiplies traffic by k (3.8 GFLOP at the probe shape vs a ~19 MB
# bandwidth floor), and a scalar-prefetch DMA gather needs a B*S-step grid.
# An invperm-gather decode restructure was also measured (58-60 us vs the
# scatter path's 51-58) and rejected. The jnp codec IS the TPU-native
# implementation; the probe records this exclusion (tools/pallas_probe.py).


def _chan_int8_encode_kernel(x_ref, scale_ref, q_ref):
    """Per-channel symmetric int8 quantize with provided channel scales (1, D)."""
    q_ref[:] = jnp.round(x_ref[:] / scale_ref[:] * 127.0).astype(jnp.int8)


def _chan_int8_decode_kernel(q_ref, scale_ref, out_ref):
    # divide (not reciprocal-multiply): matches the jnp twin bit-for-bit
    out_ref[:] = q_ref[:].astype(jnp.float32) * scale_ref[:] / 127.0


@functools.partial(jax.jit, static_argnames=("interpret",))
def chan_int8_encode_pallas(x: jnp.ndarray, scale: jnp.ndarray,
                            interpret: bool | None = None) -> jnp.ndarray:
    """(N, D) fp32 + channel scales (1, D) -> int8 codes (N, D)."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _chan_int8_encode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.int8),
        interpret=interpret,
    )(x.astype(jnp.float32), scale.reshape(1, -1).astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def chan_int8_decode_pallas(q: jnp.ndarray, scale: jnp.ndarray,
                            interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`chan_int8_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = q.shape
    t = _tile(n)
    return pl.pallas_call(
        _chan_int8_decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.float32),
        interpret=interpret,
    )(q, scale.reshape(1, -1).astype(jnp.float32))


def _chan_int4_encode_kernel(x_ref, scale_ref, packed_ref):
    """Per-channel symmetric int4 quantize + nibble pack, channel scales (1, D).

    No clip: |x| <= channel max by construction, so codes land in [-7, 7]
    (mirrors the jnp twin ``packing._int4_per_channel`` bit-for-bit)."""
    x = x_ref[:]
    half = x.shape[-1] // 2
    codes = jnp.round(x / scale_ref[:] * 7.0).astype(jnp.int32) + 8
    packed_ref[:] = (codes[:, :half] | (codes[:, half:] << 4)).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def chan_int4_encode_pallas(x: jnp.ndarray, scale: jnp.ndarray,
                            interpret: bool | None = None) -> jnp.ndarray:
    """(N, D) fp32 + channel scales (1, D) -> packed (N, D/2) uint8."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _chan_int4_encode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d // 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d // 2), jnp.uint8),
        interpret=interpret,
    )(x.astype(jnp.float32), scale.reshape(1, -1).astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def chan_int4_decode_pallas(packed: jnp.ndarray, scale: jnp.ndarray,
                            interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`chan_int4_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, dh = packed.shape
    t = _tile(n)
    return pl.pallas_call(
        _decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, dh), lambda i: (i, 0)),
            pl.BlockSpec((1, dh * 2), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, dh * 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, dh * 2), jnp.float32),
        interpret=interpret,
    )(packed, scale.reshape(1, -1).astype(jnp.float32))


def _ternary_encode_kernel(x_ref, scale_ref, packed_ref):
    """Ternary quantize + 2-bit pack with provided per-channel scales (1, D)."""
    x = x_ref[:]
    quarter = x.shape[-1] // 4
    codes = (jnp.clip(jnp.round(x / scale_ref[:]), -1, 1).astype(jnp.int32) + 1)
    packed_ref[:] = (codes[:, :quarter]
                     | (codes[:, quarter:2 * quarter] << 2)
                     | (codes[:, 2 * quarter:3 * quarter] << 4)
                     | (codes[:, 3 * quarter:] << 6)).astype(jnp.uint8)


def _ternary_decode_kernel(packed_ref, scale_ref, out_ref):
    packed = packed_ref[:].astype(jnp.int32)
    parts = [((packed >> (2 * i)) & 0x3) - 1 for i in range(4)]
    codes = jnp.concatenate(parts, axis=-1).astype(jnp.float32)
    out_ref[:] = codes * scale_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def ternary_encode_pallas(x: jnp.ndarray, scale: jnp.ndarray,
                          interpret: bool | None = None) -> jnp.ndarray:
    """(N, D) fp32 + channel scales (1, D) -> packed (N, D/4) uint8."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _ternary_encode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d // 4), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d // 4), jnp.uint8),
        interpret=interpret,
    )(x.astype(jnp.float32), scale.reshape(1, -1).astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def ternary_decode_pallas(packed: jnp.ndarray, scale: jnp.ndarray,
                          interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`ternary_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, dq = packed.shape
    t = _tile(n)
    return pl.pallas_call(
        _ternary_decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, dq), lambda i: (i, 0)),
            pl.BlockSpec((1, dq * 4), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, dq * 4), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, dq * 4), jnp.float32),
        interpret=interpret,
    )(packed, scale.reshape(1, -1).astype(jnp.float32))


# ---------- WireCodec wrappers ----------


def pallas_wire_codec() -> WireCodec:
    """``int4_per_token`` wire codec backed by the fused Pallas kernels.

    Bit-identical payloads and reconstruction vs the jnp ``int4_per_token``
    codec (tested), usable as a split-runtime hop codec.
    """

    def encode(h):
        b, s, d = h.shape
        packed, scale = int4_encode_pallas(h.reshape(b * s, d))
        return {"packed": packed.reshape(b, s, d // 2),
                "scale": scale.reshape(b, s, 1)}

    def decode(p):
        b, s, dh = p["packed"].shape
        out = int4_decode_pallas(p["packed"].reshape(b * s, dh),
                                 p["scale"].reshape(b * s, 1))
        return out.reshape(b, s, dh * 2)

    return WireCodec("int4_per_token_pallas", encode, decode)


def pallas_int8_per_token() -> WireCodec:
    """``int8_per_token`` wire codec backed by the fused affine kernels."""

    def encode(h):
        b, s, d = h.shape
        q, scale, mn = int8_affine_encode_pallas(h.reshape(b * s, d))
        return {"q": q.reshape(b, s, d), "scale": scale.reshape(b, s, 1),
                "mn": mn.reshape(b, s, 1)}

    def decode(p):
        b, s, d = p["q"].shape
        out = int8_affine_decode_pallas(p["q"].reshape(b * s, d),
                                        p["scale"].reshape(b * s, 1),
                                        p["mn"].reshape(b * s, 1))
        return out.reshape(b, s, d)

    return WireCodec("int8_per_token_pallas", encode, decode)


def pallas_ternary(kind: str) -> WireCodec:
    """``ternary_mean`` / ``ternary_max`` with the quantize+pack fused; the
    (batch, seq) channel-scale reduction stays in XLA (a single fused reduce)."""

    def encode(h):
        b, s, d = h.shape
        if kind == "mean":
            scale = jnp.mean(h, axis=(0, 1), keepdims=True) + 1e-8
        else:
            cmax = jnp.max(jnp.abs(h), axis=(0, 1), keepdims=True)
            scale = jnp.where(cmax > 0, cmax, 1.0)
        packed = ternary_encode_pallas(h.reshape(b * s, d), scale.reshape(1, d))
        return {"packed": packed.reshape(b, s, d // 4), "scale": scale}

    def decode(p):
        b, s, dq = p["packed"].shape
        out = ternary_decode_pallas(p["packed"].reshape(b * s, dq),
                                    p["scale"].reshape(1, dq * 4))
        return out.reshape(b, s, dq * 4)

    return WireCodec(f"ternary_{kind}_pallas", encode, decode,
                     batch_invariant=False)


def pallas_per_channel(bits: int) -> WireCodec:
    """``int8_per_channel`` / ``int4_per_channel`` with the quantize(+pack)
    fused; the (batch, seq) channel abs-max reduction stays in XLA (one fused
    reduce). This is the reference's 896-iteration channel loop
    (``qwen_layer_wise.py:125-152``) as a single kernel pass.

    The int4 kernel earns its keep by fusing the nibble pack. The int8 kernel
    is a plain elementwise op XLA fuses equally well on its own — it exists so
    every quantizing hop codec has a kernel twin (uniform Pallas hop pipeline,
    BASELINE.json north star), not for a fusion win."""

    def encode(h):
        b, s, d = h.shape
        cmax = jnp.max(jnp.abs(h), axis=(0, 1), keepdims=True)
        safe = jnp.where(cmax > 0, cmax, 1.0)
        flat = h.reshape(b * s, d)
        if bits == 8:
            return {"q": chan_int8_encode_pallas(flat, safe.reshape(1, d))
                    .reshape(b, s, d), "scale": safe}
        return {"packed": chan_int4_encode_pallas(flat, safe.reshape(1, d))
                .reshape(b, s, d // 2), "scale": safe}

    def decode(p):
        if bits == 8:
            b, s, d = p["q"].shape
            out = chan_int8_decode_pallas(p["q"].reshape(b * s, d),
                                          p["scale"].reshape(1, d))
            return out.reshape(b, s, d)
        b, s, dh = p["packed"].shape
        out = chan_int4_decode_pallas(p["packed"].reshape(b * s, dh),
                                      p["scale"].reshape(1, dh * 2))
        return out.reshape(b, s, dh * 2)

    return WireCodec(f"int{bits}_per_channel_pallas", encode, decode,
                     batch_invariant=False)


#: Why there is NO ``pallas_selective_int4`` (deleted round 5; the full
#: measurement story sits where its quantize cores used to live, above
#: :func:`int4_decode_pallas`'s channel siblings): the selective codec is
#: gather-bound and its jnp implementation is the TPU-native one. The probe
#: embeds this string so the exclusion stays a recorded decision, not an
#: absence (``tools/pallas_probe.py``).
SELECTIVE_EXCLUSION = (
    "selective_int4 has no kernel twin BY MEASUREMENT (v5e, rounds 4-5): the "
    "codec is gather-bound; XLA fuses the int4 quantize into its gather "
    "consumers, so a pallas_call boundary only breaks that fusion (twin "
    "probed 0.97x roundtrip; split: encode 0.97x, decode 0.99x). In-kernel "
    "gathers lose structurally on TPU: VMEM row copies are sublane-granular, "
    "a one-hot-matmul gather multiplies traffic by k, a scalar-prefetch DMA "
    "gather needs a B*S-step grid. The jnp codec IS the TPU-native path.")


_PALLAS_FACTORIES = {
    "int4_per_token": pallas_wire_codec,
    "int8_per_token": pallas_int8_per_token,
    "int8_per_channel": lambda: pallas_per_channel(8),
    "int4_per_channel": lambda: pallas_per_channel(4),
    "ternary_mean": lambda: pallas_ternary("mean"),
    "ternary_max": lambda: pallas_ternary("max"),
}

#: NO-DATA FALLBACK for the substitution policy: base codecs whose fused
#: kernel beat the jnp/XLA path on the round-4/5 probe of the tunneled v5e
#: (differential-scan roundtrip, interleaved pairs, median-decided — single
#: runs swing +-30%). Round-4 decision data (5 reps each): int4_per_token
#: 1.33x (fuses the scale reduce + quantize + nibble pack), int4_per_channel
#: ~1.4x, ternary ~1.4x; EXCLUDED: int8_per_token 0.80x, int8_per_channel
#: ~0.92x — passes XLA already fuses into one bandwidth-bound sweep, where a
#: kernel only adds launch/layout overhead. The LIVE policy is the probe
#: cache (``codecs/probe_cache.py``): every bench's probe records each
#: codec's measured speedup keyed by chip fingerprint, and substitution
#: consults that first — this constant only decides when the current chip
#: has never been probed. Substitution must be EARNED — a default path
#: slower than doing nothing is worse than no kernel.
PALLAS_DEFAULT_WINS = frozenset({
    "int4_per_token", "int4_per_channel", "ternary_mean", "ternary_max"})


def default_substituted(base: str) -> bool:
    """The substitution policy for one base codec name: this chip's probe
    cache when it has data, the frozen fallback set when it does not."""
    from . import probe_cache

    win = probe_cache.measured_win(base)
    if win is None:
        return base in PALLAS_DEFAULT_WINS
    return win


def pallas_variant(codec: WireCodec, *, measured_wins_only: bool = False
                   ) -> Optional[WireCodec]:
    """The Pallas-backed twin of a jnp wire codec, or None when no fused kernel
    exists (identity casts — nothing to fuse). With ``measured_wins_only`` the
    twin is returned only when it is a probed on-silicon win for THIS chip
    (:func:`default_substituted`) — the TPU default-substitution policy;
    explicit ``*_pallas`` pins are always honored."""
    if codec.name.endswith("_pallas"):
        return codec
    if codec.name in _PALLAS_FACTORIES:
        if measured_wins_only and not default_substituted(codec.name):
            return None
        # the twins share the jnp codecs' pathological-input saturation, so
        # kernel/jnp payload parity holds on sanitized inputs too
        from .packing import _saturating

        return _saturating(_PALLAS_FACTORIES[codec.name]())
    # selective_int4: no kernel twin exists — a measured deletion, not a gap
    # (SELECTIVE_EXCLUSION); the jnp codec is returned-as-is by the runtimes'
    # `pallas_variant(c) or c` fallback on every path, including forced
    # EDGELLM_PALLAS=1 substitution
    return None


# ---------------------------------------------------------------------------
# Fused boundary hops: quantize -> seal -> transport in one shot
# ---------------------------------------------------------------------------
# A separate hop is five XLA ops (encode -> seal -> ppermute -> verify ->
# decode) and BENCH_r03/r04 show the packed payload paying an extra HBM
# round-trip before the collective (int8_per_token roundtrip 0.80x,
# int8_per_channel 0.91-0.94x vs the jnp twins). The fused family moves the
# quantize INTO the transport (EQuARX-style):
#
# - "wire" mode: encode + seal, then bitcast the whole sealed tree into ONE
#   flat uint8 buffer (codecs.wire_format.WireFormat) and cross the cut with
#   a single ppermute instead of one per payload leaf; the receiver slices
#   the buffer back, verifies, and decodes. Pure XLA + the existing Pallas
#   encode/decode kernels -- runs everywhere (CPU tests it in interpret
#   mode), and collapses per-leaf collective launches into one.
# - "remote" mode: one Pallas kernel per hop that quantizes each token tile
#   in VMEM and pltpu.make_async_remote_copy's it straight to the neighbor,
#   double-buffered so tile i's DMA overlaps tile i+1's quantize and tile
#   i-1's dequantize; the in-kernel checksum reproduces
#   wire_format._leaf_crc bit-for-bit, so the sealed bytes on the
#   interconnect are the SAME bytes the unfused ladder would have sent.
#   TPU-only (remote DMA has no interpret mode) and scoped to
#   REMOTE_CAPABLE codecs.
#
# Both modes decode the exact payload bytes the fallback would have decoded,
# so zero-fault fused hops are token-identical through generate_split; the
# plan gate (fused_hop_plan) refuses unless the win is forced or PROBED on
# this chip, and a refused gate leaves the pre-fusion graph byte-identical
# (the graphlint fused-disabled fingerprint contracts pin this).

#: base codecs a fused hop can carry: everything with a Pallas twin. The
#: exclusion of selective_int4 is measured, not incidental -- see
#: SELECTIVE_EXCLUSION (gather-bound, and its importance sidecar makes the
#: payload data-dependent, which the static wire layout can't carry).
FUSED_CAPABLE = frozenset(_PALLAS_FACTORIES)

#: base codecs with a single-kernel remote-DMA hop. int8_per_token first:
#: it is the default split hop codec AND the worst r03/r04 regression
#: (0.80x), i.e. the codec where only fusing the transport can win.
REMOTE_CAPABLE = frozenset({"int8_per_token"})


@dataclasses.dataclass(frozen=True)
class FusedHopPlan:
    """One hop's fused-transport decision (mirrors ``decode_plan``: a plan
    object you can log, not a bare bool). ``base`` is the probe-cache key
    (codec name sans ``_pallas``); ``reason`` records why the gate said yes
    so bench sidecars can carry the provenance."""

    mode: str    # "wire" | "remote"
    base: str
    reason: str


def _fused_base(codec) -> Optional[str]:
    name = getattr(codec, "name", None)
    if name is None:
        return None
    return name[:-len("_pallas")] if name.endswith("_pallas") else name


def fused_hop_plan(codec, *, link_active: bool = False,
                   backend: Optional[str] = None) -> Optional[FusedHopPlan]:
    """The gating ladder for one hop codec -> a plan, or None (= keep the
    separate encode/ppermute/decode ladder, byte-identical pre-fusion graph).

    1. ``EDGELLM_FUSED_HOP=0`` -- hard off (the fused-disabled identity
       contract traces this build against the default CPU build).
    2. An active FaultyLink owns the hop (retries, FEC framing, hedging,
       tiering) -- the fused kernel would bypass injection, so refuse.
    3. The base codec must be FUSED_CAPABLE and carry no importance sidecar.
    4. ``EDGELLM_FUSED_HOP=wire|remote`` forces a mode (remote only on TPU
       for a REMOTE_CAPABLE base -- it cannot even trace elsewhere);
       ``=1`` forces the best available mode.
    5. Default: the win must be EARNED -- TPU backend AND this chip's probe
       cache says ``fused_hop:<base>`` beat the separate ladder
       (``measured_win is True``; None means never probed -> refuse, same
       policy as kernel-twin substitution: a default path slower than doing
       nothing is worse than no fusion).
    """
    env = os.environ.get("EDGELLM_FUSED_HOP", "").strip().lower()
    if env == "0" or codec is None or link_active:
        return None
    base = _fused_base(codec)
    if base not in FUSED_CAPABLE or getattr(codec, "needs_importance", False):
        return None
    if backend is None:
        backend = jax.default_backend()
    remote_ok = backend == "tpu" and base in REMOTE_CAPABLE
    if env in ("wire", "remote"):
        if env == "remote" and not remote_ok:
            return None
        return FusedHopPlan(env, base, f"forced: EDGELLM_FUSED_HOP={env}")
    if env == "1":
        return FusedHopPlan("remote" if remote_ok else "wire",
                            base, "forced: EDGELLM_FUSED_HOP=1")
    if backend != "tpu":
        return None
    from . import probe_cache

    if probe_cache.measured_win(f"fused_hop:{base}") is not True:
        return None
    return FusedHopPlan("remote" if remote_ok else "wire", base,
                        "probe-cache measured win on this chip")


def fused_wire_hop(codec, hidden: jnp.ndarray, source: int, axis_name: str,
                   idx: jnp.ndarray) -> jnp.ndarray:
    """Fused "wire" hop ``source -> source+1``: encode, seal, flatten the
    sealed tree to ONE uint8 buffer, cross the cut with a single ppermute,
    then slice/verify/decode on the receiver. Same bytes, same seal, same
    checksum as the separate ladder (codecs.wire_format owns the layout) --
    just one collective launch per hop instead of one per payload leaf.

    The verify stays live in the graph: a corrupt arrival substitutes the
    receiver's own ``hidden`` (exactly what a zero-budget FaultyLink would
    do), so DCE can't silently drop the integrity check."""
    from .wire_format import WireFormat, seal_payload, verify_payload

    wf = WireFormat.for_codec(codec, hidden.shape, hidden.dtype)
    buf = wf.to_wire(seal_payload(codec.encode(hidden)))
    moved = jax.lax.ppermute(buf, axis_name, [(source, source + 1)])
    arrived = wf.from_wire(moved)
    ok = verify_payload(arrived)
    decoded = codec.decode(arrived["p"]).astype(hidden.dtype)
    return jnp.where(idx == source + 1,
                     jnp.where(ok, decoded, hidden), hidden)


# -- remote mode: the single-kernel quantize->DMA hop (int8 per-token) ------

_GOLD = 0x9E3779B1  # per-leaf checksum salt stride (wire_format)
_SALT_MN, _SALT_Q, _SALT_SCALE = 0, _GOLD, (2 * _GOLD) & 0xFFFFFFFF


def _crc_f32_rows(vals, row0, salt: int):
    """In-kernel wire_format._leaf_crc for a (T, 1) f32 column whose rows sit
    at global offset ``row0``: little-endian byte k of row r weighs
    ``(2*(4r+k+salt)+1) * _CRC_MULT`` -- exact uint32 arithmetic."""
    from .wire_format import _CRC_MULT

    t = vals.shape[0]
    u = pltpu.bitcast(vals, jnp.uint32)
    rows = jax.lax.broadcasted_iota(jnp.uint32, (t, 1), 0) + row0
    crc = jnp.uint32(0)
    for k in range(4):
        pos = jnp.uint32(4) * rows + jnp.uint32(k) + jnp.uint32(salt)
        w = (jnp.uint32(2) * pos + jnp.uint32(1)) * jnp.uint32(_CRC_MULT)
        crc = crc + jnp.sum(((u >> jnp.uint32(8 * k)) & jnp.uint32(0xFF)) * w,
                            dtype=jnp.uint32)
    return crc


def _crc_i8_tile(q, row0, salt: int):
    """In-kernel wire_format._leaf_crc for a (T, D) int8 tile at global row
    offset ``row0`` (one byte per element, row-major positions)."""
    from .wire_format import _CRC_MULT

    t, d = q.shape
    rows = jax.lax.broadcasted_iota(jnp.uint32, (t, d), 0) + row0
    cols = jax.lax.broadcasted_iota(jnp.uint32, (t, d), 1)
    pos = rows * jnp.uint32(d) + cols + jnp.uint32(salt)
    w = (jnp.uint32(2) * pos + jnp.uint32(1)) * jnp.uint32(_CRC_MULT)
    b = (q.astype(jnp.int32) & 0xFF).astype(jnp.uint32)
    return jnp.sum(b * w, dtype=jnp.uint32)


def _remote_hop_kernel(n_dev: int, n_tiles: int, axis_name: str,
                       x_ref, out_ref, ok_ref,
                       send_q, send_mn, send_scale, head_send,
                       recv_q, recv_mn, recv_scale, head_recv,
                       send_crc, recv_crc, send_sems, recv_sems, head_sems):
    """Grid step i of (n_tiles + 1): quantize token tile i into send slot
    i%2 and start its remote copies (overlapping the previous tile's DMA),
    then wait + dequantize tile i-1 from the recv slots; the final step
    ships the 8-byte head (canary + checksum) and verifies.

    Every device sends to its right neighbor (uniform SPMD ring -- the
    symmetric program is deadlock-free: step 0 has no waits, and step i's
    waits depend only on the left neighbor's step i sends). The receiver
    gate (``idx == source+1``) lives OUTSIDE the kernel, so off-path
    devices' arrivals are computed and ignored, trading one redundant
    neighbor transfer for a kernel with no data-dependent control flow."""
    from .wire_format import CANARY

    i = pl.program_id(0)
    t = send_q.shape[1]
    my = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my + 1, n_dev)
    left = jax.lax.rem(my + n_dev - 1, n_dev)
    slot = jax.lax.rem(i, 2)
    prev_slot = jax.lax.rem(i + 1, 2)

    def leaf_copy(leaf, src, dst, s):
        return pltpu.make_async_remote_copy(
            src_ref=src.at[s], dst_ref=dst.at[s],
            send_sem=send_sems.at[leaf, s], recv_sem=recv_sems.at[leaf, s],
            device_id=(right,), device_id_type=pltpu.DeviceIdType.LOGICAL)

    @pl.when(i == 0)
    def _prologue():
        # neighborhood barrier: nobody DMAs until both neighbors entered
        # the kernel (their recv buffers exist); then zero the accumulators
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=(left,),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, inc=1, device_id=(right,),
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)
        send_crc[0] = jnp.uint32(0)
        recv_crc[0] = jnp.uint32(0)

    @pl.when(jnp.logical_and(i >= 2, i < n_tiles))
    def _reclaim_slot():
        # tile i reuses tile i-2's send slot: drain those DMAs first
        for leaf in range(3):
            leaf_copy(leaf, (send_q, send_mn, send_scale)[leaf],
                      (recv_q, recv_mn, recv_scale)[leaf], slot).wait_send()

    @pl.when(i < n_tiles)
    def _quantize_and_send():
        # per-row affine int8 -- bit-for-bit _int8_affine_encode_kernel
        x = x_ref[:]
        mn = jnp.min(x, axis=-1, keepdims=True)
        mx = jnp.max(x, axis=-1, keepdims=True)
        scale = (mx - mn) * jnp.float32(1.0 / 255.0)
        safe = jnp.where(scale > 0, scale, 1.0)
        zp = jnp.round(-128.0 - mn / safe)
        q = jnp.clip(jnp.round(x / safe) + zp, -128, 127).astype(jnp.int8)
        send_q[slot] = q
        send_mn[slot] = mn
        send_scale[slot] = scale
        row0 = (i * t).astype(jnp.uint32)
        send_crc[0] = (send_crc[0]
                       + _crc_f32_rows(mn, row0, _SALT_MN)
                       + _crc_i8_tile(q, row0, _SALT_Q)
                       + _crc_f32_rows(scale, row0, _SALT_SCALE))
        for leaf, (src, dst) in enumerate(((send_q, recv_q),
                                           (send_mn, recv_mn),
                                           (send_scale, recv_scale))):
            leaf_copy(leaf, src, dst, slot).start()

    @pl.when(i >= 1)
    def _receive_and_decode():
        # tile i-1 has landed (or we block until the left neighbor sends it)
        for leaf in range(3):
            leaf_copy(leaf, (send_q, send_mn, send_scale)[leaf],
                      (recv_q, recv_mn, recv_scale)[leaf],
                      prev_slot).wait_recv()
        q = recv_q[prev_slot]
        mn = recv_mn[prev_slot]
        scale = recv_scale[prev_slot]
        row0 = ((i - 1) * t).astype(jnp.uint32)
        recv_crc[0] = (recv_crc[0]
                       + _crc_f32_rows(mn, row0, _SALT_MN)
                       + _crc_i8_tile(q, row0, _SALT_Q)
                       + _crc_f32_rows(scale, row0, _SALT_SCALE))
        # bit-for-bit _int8_affine_decode_kernel
        safe = jnp.where(scale > 0, scale, 1.0)
        zp = jnp.round(-128.0 - mn / safe)
        deq = (q.astype(jnp.float32) - zp) * safe
        out_ref[:] = jnp.where(scale > 0, deq, mn)

    @pl.when(i == n_tiles)
    def _finalize():
        # drain every send still in flight (kernel must not exit with live
        # DMAs): tiles n_tiles-1 and (when it exists) n_tiles-2
        for s in ((0, 1) if n_tiles >= 2 else (0,)):
            for leaf in range(3):
                leaf_copy(leaf, (send_q, send_mn, send_scale)[leaf],
                          (recv_q, recv_mn, recv_scale)[leaf], s).wait_send()
        # ship the 8-byte integrity head: [canary, crc] in the first two
        # lanes of a padded u32 vector (vector stores only -- no scalar
        # writes into VMEM)
        lane = jax.lax.broadcasted_iota(jnp.uint32, head_send.shape, 1)
        head_send[:] = jnp.where(
            lane == 0, jnp.uint32(CANARY),
            jnp.where(lane == 1, send_crc[0], jnp.uint32(0)))
        head = pltpu.make_async_remote_copy(
            src_ref=head_send, dst_ref=head_recv,
            send_sem=head_sems.at[0], recv_sem=head_sems.at[1],
            device_id=(right,), device_id_type=pltpu.DeviceIdType.LOGICAL)
        head.start()
        head.wait_recv()
        got = jnp.where(lane < 2, head_recv[:], jnp.uint32(0))
        want = jnp.where(
            lane == 0, jnp.uint32(CANARY),
            jnp.where(lane == 1, recv_crc[0], jnp.uint32(0)))
        ok_ref[0] = jnp.all(got == want).astype(jnp.int32)
        head.wait_send()


def fused_remote_hop(codec, hidden: jnp.ndarray, source: int, axis_name: str,
                     idx: jnp.ndarray, *, n_dev: int) -> jnp.ndarray:
    """Fused "remote" hop: ONE Pallas kernel quantizes the activation tile
    by tile and remote-DMAs the sealed int8 payload straight to the right
    neighbor (uniform ring), double-buffered so each tile's send overlaps
    the next tile's quantize and the previous tile's dequantize. The bytes
    on the interconnect are exactly the wire-format sealed tree the unfused
    ladder would ppermute (same leaves, same checksum math), so the fused
    hop stays token-identical under zero faults. TPU-only; the plan gate
    (``fused_hop_plan``) guarantees this is never traced elsewhere."""
    from .packing import sanitize_hidden

    b, s_len, d = hidden.shape
    x = sanitize_hidden(hidden).astype(jnp.float32).reshape(b * s_len, d)
    n = b * s_len
    t = _tile(n)
    n_tiles = n // t

    grid = (n_tiles + 1,)
    kernel = functools.partial(_remote_hop_kernel, n_dev, n_tiles, axis_name)
    decoded, ok = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((t, d),
                               lambda i: (jnp.minimum(i, n_tiles - 1), 0))],
        out_specs=[
            pl.BlockSpec((t, d), lambda i: (jnp.maximum(i - 1, 0), 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, t, d), jnp.int8),      # send_q
            pltpu.VMEM((2, t, 1), jnp.float32),   # send_mn
            pltpu.VMEM((2, t, 1), jnp.float32),   # send_scale
            pltpu.VMEM((1, 128), jnp.uint32),     # head_send
            pltpu.VMEM((2, t, d), jnp.int8),      # recv_q
            pltpu.VMEM((2, t, 1), jnp.float32),   # recv_mn
            pltpu.VMEM((2, t, 1), jnp.float32),   # recv_scale
            pltpu.VMEM((1, 128), jnp.uint32),     # head_recv
            pltpu.SMEM((1,), jnp.uint32),         # send_crc
            pltpu.SMEM((1,), jnp.uint32),         # recv_crc
            pltpu.SemaphoreType.DMA((3, 2)),      # send_sems (leaf, slot)
            pltpu.SemaphoreType.DMA((3, 2)),      # recv_sems
            pltpu.SemaphoreType.DMA((2,)),        # head send/recv
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",), collective_id=0),
    )(x)
    decoded = decoded.reshape(b, s_len, d).astype(hidden.dtype)
    return jnp.where(idx == source + 1,
                     jnp.where(ok[0] != 0, decoded, hidden), hidden)


def fused_hop(plan: FusedHopPlan, codec, hidden: jnp.ndarray, source: int,
              axis_name: str, idx: jnp.ndarray, *, n_dev: int) -> jnp.ndarray:
    """Dispatch one planned fused hop (``fused_hop_plan`` decided the mode)."""
    if plan.mode == "remote":
        return fused_remote_hop(codec, hidden, source, axis_name, idx,
                                n_dev=n_dev)
    return fused_wire_hop(codec, hidden, source, axis_name, idx)
