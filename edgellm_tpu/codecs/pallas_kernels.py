"""Pallas TPU kernels for the boundary codec hot path.

The reference's clearest kernel-shaped code is its per-channel Python loop over
896 channels (``qwen_layer_wise.py:125-152``, SURVEY.md section 3.5); here the
codec ops are single fused TPU kernels: quantize + nibble/crumb-pack in one VMEM
pass (fp32 in -> packed uint8 + scales out, one HBM round-trip instead of
quantize/clip/round/pack each materializing an intermediate), and the matching
unpack + dequantize.

Layout notes (see ``pallas_guide.md``):
- blocks tile the token axis; the feature axis stays whole (a lane multiple for
  real models: 896, 512) so per-token reductions are single-block row reductions;
- packing pairs element i with element i + D/2 (contiguous halves — full-lane
  slices, no strided lane access); identical to ``packing.pack_int4``;
- interpret mode runs the same kernels on CPU (used by the test suite; the
  wrappers auto-select based on the backend).

Kernel inventory (each bit-identical to its jnp twin in ``packing`` — tested):
- ``int4_per_token``: per-row max-abs scale + quantize + pack, fully fused;
- ``int8_per_token``: per-row affine (min/max -> scale, zero-point) + quantize;
- channel-scale ternary quantize+pack (``ternary_mean`` / ``ternary_max``;
  the (B,S) channel-scale reduction stays in XLA);
- channel-scale int8 quantize and int4 quantize+pack (``int8_per_channel`` /
  ``int4_per_channel`` — the reference's 896-channel Python loop as one pass).

``selective_int4`` deliberately has NO kernel twin — a measured round-5
deletion, not a gap: the codec is gather-bound and XLA fuses the quantize
into the gather chain, so the twin could only lose (``SELECTIVE_EXCLUSION``
carries the numbers; the probe records it every bench run).

``pallas_wire_codec`` / ``pallas_int8_per_token`` / ``pallas_ternary`` wrap
these in the :class:`~edgellm_tpu.codecs.packing.WireCodec` interface;
``pallas_variant`` maps any jnp wire codec to its Pallas twin (the split
runtime substitutes automatically on TPU where the probe cache says the twin
wins on this chip).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .packing import WireCodec


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _encode_kernel(x_ref, packed_ref, scale_ref):
    """One token-tile: per-row max-abs scale -> int4 codes -> packed nibbles."""
    x = x_ref[:]  # (T, D) fp32
    half = x.shape[-1] // 2
    max_val = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    safe = jnp.where(max_val > 0, max_val, 1.0)
    codes = jnp.round(jnp.clip(x / safe * 7.0, -8.0, 7.0)).astype(jnp.int32) + 8
    lo, hi = codes[:, :half], codes[:, half:]
    packed_ref[:] = (lo | (hi << 4)).astype(jnp.uint8)
    scale_ref[:] = safe


def _decode_kernel(packed_ref, scale_ref, out_ref):
    """Unpack nibbles -> dequantize. ONE body for every int4 scale granularity:
    ``scale_ref[:]`` broadcasts a per-row (T, 1), global (1, 1), or per-channel
    (1, D) scale block identically, so the unpack logic exists exactly once.
    Arithmetic order matches the per-token jnp twin bit-for-bit; the per-channel
    twin multiplies scale before the /7 (<=1 ulp apart, within the decode
    tolerance the twin tests pin)."""
    packed = packed_ref[:].astype(jnp.int32)  # (T, D/2)
    lo = (packed & 0xF) - 8
    hi = ((packed >> 4) & 0xF) - 8
    codes = jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)
    out_ref[:] = codes / 7.0 * scale_ref[:]


def _tile(n_tokens: int) -> int:
    """Token-tile size: sublane-friendly, bounded by the token count."""
    for t in (256, 128, 64, 32, 16, 8):
        if n_tokens % t == 0:
            return t
    return n_tokens


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_encode_pallas(x: jnp.ndarray, interpret: bool | None = None):
    """(N, D) fp32 -> (packed (N, D/2) uint8, scale (N, 1) fp32), fused."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    grid = (n // t,)
    return pl.pallas_call(
        _encode_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((t, d), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((t, d // 2), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d // 2), jnp.uint8),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x.astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_decode_pallas(packed: jnp.ndarray, scale: jnp.ndarray,
                       interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`int4_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, dh = packed.shape
    t = _tile(n)
    return pl.pallas_call(
        _decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, dh), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((t, dh * 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, dh * 2), jnp.float32),
        interpret=interpret,
    )(packed, scale)


def _int8_affine_encode_kernel(x_ref, q_ref, scale_ref, mn_ref):
    """Per-row affine int8: scale = (max-min)/255, zero-point from min."""
    x = x_ref[:]  # (T, D) fp32
    mn = jnp.min(x, axis=-1, keepdims=True)
    mx = jnp.max(x, axis=-1, keepdims=True)
    scale = (mx - mn) * jnp.float32(1.0 / 255.0)  # matches packing.py bit-for-bit
    safe = jnp.where(scale > 0, scale, 1.0)
    zp = jnp.round(-128.0 - mn / safe)
    q_ref[:] = jnp.clip(jnp.round(x / safe) + zp, -128, 127).astype(jnp.int8)
    scale_ref[:] = scale
    mn_ref[:] = mn


def _int8_affine_decode_kernel(q_ref, scale_ref, mn_ref, out_ref):
    scale, mn = scale_ref[:], mn_ref[:]
    safe = jnp.where(scale > 0, scale, 1.0)
    zp = jnp.round(-128.0 - mn / safe)
    deq = (q_ref[:].astype(jnp.float32) - zp) * safe
    out_ref[:] = jnp.where(scale > 0, deq, mn)


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_affine_encode_pallas(x: jnp.ndarray, interpret: bool | None = None):
    """(N, D) fp32 -> (q (N, D) int8, scale (N, 1) fp32, mn (N, 1) fp32)."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _int8_affine_encode_kernel,
        grid=(n // t,),
        in_specs=[pl.BlockSpec((t, d), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), jnp.int8),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x.astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_affine_decode_pallas(q: jnp.ndarray, scale: jnp.ndarray, mn: jnp.ndarray,
                              interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`int8_affine_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = q.shape
    t = _tile(n)
    return pl.pallas_call(
        _int8_affine_decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((t, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.float32),
        interpret=interpret,
    )(q, scale, mn)


# The scalar-scale int4 quantize core that once backed a selective_int4
# kernel twin was DELETED in round 5, on measurement (VERDICT r4 weak #1 /
# next #2): the codec is gather-bound, and XLA fuses the quantize into its
# gather consumers, so a pallas_call boundary can only break that fusion —
# the twin probed 0.97x (r4) and, split, encode 0.97x / decode 0.99x (r5) on
# the v5e. The in-kernel alternatives lose structurally: a VMEM row gather
# is sublane-granular (1-row copies waste 7/8 of the VPU), a one-hot-matmul
# gather multiplies traffic by k (3.8 GFLOP at the probe shape vs a ~19 MB
# bandwidth floor), and a scalar-prefetch DMA gather needs a B*S-step grid.
# An invperm-gather decode restructure was also measured (58-60 us vs the
# scatter path's 51-58) and rejected. The jnp codec IS the TPU-native
# implementation; the probe records this exclusion (tools/pallas_probe.py).


def _chan_int8_encode_kernel(x_ref, scale_ref, q_ref):
    """Per-channel symmetric int8 quantize with provided channel scales (1, D)."""
    q_ref[:] = jnp.round(x_ref[:] / scale_ref[:] * 127.0).astype(jnp.int8)


def _chan_int8_decode_kernel(q_ref, scale_ref, out_ref):
    # divide (not reciprocal-multiply): matches the jnp twin bit-for-bit
    out_ref[:] = q_ref[:].astype(jnp.float32) * scale_ref[:] / 127.0


@functools.partial(jax.jit, static_argnames=("interpret",))
def chan_int8_encode_pallas(x: jnp.ndarray, scale: jnp.ndarray,
                            interpret: bool | None = None) -> jnp.ndarray:
    """(N, D) fp32 + channel scales (1, D) -> int8 codes (N, D)."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _chan_int8_encode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.int8),
        interpret=interpret,
    )(x.astype(jnp.float32), scale.reshape(1, -1).astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def chan_int8_decode_pallas(q: jnp.ndarray, scale: jnp.ndarray,
                            interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`chan_int8_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = q.shape
    t = _tile(n)
    return pl.pallas_call(
        _chan_int8_decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.float32),
        interpret=interpret,
    )(q, scale.reshape(1, -1).astype(jnp.float32))


def _chan_int4_encode_kernel(x_ref, scale_ref, packed_ref):
    """Per-channel symmetric int4 quantize + nibble pack, channel scales (1, D).

    No clip: |x| <= channel max by construction, so codes land in [-7, 7]
    (mirrors the jnp twin ``packing._int4_per_channel`` bit-for-bit)."""
    x = x_ref[:]
    half = x.shape[-1] // 2
    codes = jnp.round(x / scale_ref[:] * 7.0).astype(jnp.int32) + 8
    packed_ref[:] = (codes[:, :half] | (codes[:, half:] << 4)).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def chan_int4_encode_pallas(x: jnp.ndarray, scale: jnp.ndarray,
                            interpret: bool | None = None) -> jnp.ndarray:
    """(N, D) fp32 + channel scales (1, D) -> packed (N, D/2) uint8."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _chan_int4_encode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d // 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d // 2), jnp.uint8),
        interpret=interpret,
    )(x.astype(jnp.float32), scale.reshape(1, -1).astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def chan_int4_decode_pallas(packed: jnp.ndarray, scale: jnp.ndarray,
                            interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`chan_int4_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, dh = packed.shape
    t = _tile(n)
    return pl.pallas_call(
        _decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, dh), lambda i: (i, 0)),
            pl.BlockSpec((1, dh * 2), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, dh * 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, dh * 2), jnp.float32),
        interpret=interpret,
    )(packed, scale.reshape(1, -1).astype(jnp.float32))


def _ternary_encode_kernel(x_ref, scale_ref, packed_ref):
    """Ternary quantize + 2-bit pack with provided per-channel scales (1, D)."""
    x = x_ref[:]
    quarter = x.shape[-1] // 4
    codes = (jnp.clip(jnp.round(x / scale_ref[:]), -1, 1).astype(jnp.int32) + 1)
    packed_ref[:] = (codes[:, :quarter]
                     | (codes[:, quarter:2 * quarter] << 2)
                     | (codes[:, 2 * quarter:3 * quarter] << 4)
                     | (codes[:, 3 * quarter:] << 6)).astype(jnp.uint8)


def _ternary_decode_kernel(packed_ref, scale_ref, out_ref):
    packed = packed_ref[:].astype(jnp.int32)
    parts = [((packed >> (2 * i)) & 0x3) - 1 for i in range(4)]
    codes = jnp.concatenate(parts, axis=-1).astype(jnp.float32)
    out_ref[:] = codes * scale_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def ternary_encode_pallas(x: jnp.ndarray, scale: jnp.ndarray,
                          interpret: bool | None = None) -> jnp.ndarray:
    """(N, D) fp32 + channel scales (1, D) -> packed (N, D/4) uint8."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _ternary_encode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d // 4), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d // 4), jnp.uint8),
        interpret=interpret,
    )(x.astype(jnp.float32), scale.reshape(1, -1).astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def ternary_decode_pallas(packed: jnp.ndarray, scale: jnp.ndarray,
                          interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`ternary_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, dq = packed.shape
    t = _tile(n)
    return pl.pallas_call(
        _ternary_decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, dq), lambda i: (i, 0)),
            pl.BlockSpec((1, dq * 4), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, dq * 4), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, dq * 4), jnp.float32),
        interpret=interpret,
    )(packed, scale.reshape(1, -1).astype(jnp.float32))


# ---------- WireCodec wrappers ----------


def pallas_wire_codec() -> WireCodec:
    """``int4_per_token`` wire codec backed by the fused Pallas kernels.

    Bit-identical payloads and reconstruction vs the jnp ``int4_per_token``
    codec (tested), usable as a split-runtime hop codec.
    """

    def encode(h):
        b, s, d = h.shape
        packed, scale = int4_encode_pallas(h.reshape(b * s, d))
        return {"packed": packed.reshape(b, s, d // 2),
                "scale": scale.reshape(b, s, 1)}

    def decode(p):
        b, s, dh = p["packed"].shape
        out = int4_decode_pallas(p["packed"].reshape(b * s, dh),
                                 p["scale"].reshape(b * s, 1))
        return out.reshape(b, s, dh * 2)

    return WireCodec("int4_per_token_pallas", encode, decode)


def pallas_int8_per_token() -> WireCodec:
    """``int8_per_token`` wire codec backed by the fused affine kernels."""

    def encode(h):
        b, s, d = h.shape
        q, scale, mn = int8_affine_encode_pallas(h.reshape(b * s, d))
        return {"q": q.reshape(b, s, d), "scale": scale.reshape(b, s, 1),
                "mn": mn.reshape(b, s, 1)}

    def decode(p):
        b, s, d = p["q"].shape
        out = int8_affine_decode_pallas(p["q"].reshape(b * s, d),
                                        p["scale"].reshape(b * s, 1),
                                        p["mn"].reshape(b * s, 1))
        return out.reshape(b, s, d)

    return WireCodec("int8_per_token_pallas", encode, decode)


def pallas_ternary(kind: str) -> WireCodec:
    """``ternary_mean`` / ``ternary_max`` with the quantize+pack fused; the
    (batch, seq) channel-scale reduction stays in XLA (a single fused reduce)."""

    def encode(h):
        b, s, d = h.shape
        if kind == "mean":
            scale = jnp.mean(h, axis=(0, 1), keepdims=True) + 1e-8
        else:
            cmax = jnp.max(jnp.abs(h), axis=(0, 1), keepdims=True)
            scale = jnp.where(cmax > 0, cmax, 1.0)
        packed = ternary_encode_pallas(h.reshape(b * s, d), scale.reshape(1, d))
        return {"packed": packed.reshape(b, s, d // 4), "scale": scale}

    def decode(p):
        b, s, dq = p["packed"].shape
        out = ternary_decode_pallas(p["packed"].reshape(b * s, dq),
                                    p["scale"].reshape(1, dq * 4))
        return out.reshape(b, s, dq * 4)

    return WireCodec(f"ternary_{kind}_pallas", encode, decode,
                     batch_invariant=False)


def pallas_per_channel(bits: int) -> WireCodec:
    """``int8_per_channel`` / ``int4_per_channel`` with the quantize(+pack)
    fused; the (batch, seq) channel abs-max reduction stays in XLA (one fused
    reduce). This is the reference's 896-iteration channel loop
    (``qwen_layer_wise.py:125-152``) as a single kernel pass.

    The int4 kernel earns its keep by fusing the nibble pack. The int8 kernel
    is a plain elementwise op XLA fuses equally well on its own — it exists so
    every quantizing hop codec has a kernel twin (uniform Pallas hop pipeline,
    BASELINE.json north star), not for a fusion win."""

    def encode(h):
        b, s, d = h.shape
        cmax = jnp.max(jnp.abs(h), axis=(0, 1), keepdims=True)
        safe = jnp.where(cmax > 0, cmax, 1.0)
        flat = h.reshape(b * s, d)
        if bits == 8:
            return {"q": chan_int8_encode_pallas(flat, safe.reshape(1, d))
                    .reshape(b, s, d), "scale": safe}
        return {"packed": chan_int4_encode_pallas(flat, safe.reshape(1, d))
                .reshape(b, s, d // 2), "scale": safe}

    def decode(p):
        if bits == 8:
            b, s, d = p["q"].shape
            out = chan_int8_decode_pallas(p["q"].reshape(b * s, d),
                                          p["scale"].reshape(1, d))
            return out.reshape(b, s, d)
        b, s, dh = p["packed"].shape
        out = chan_int4_decode_pallas(p["packed"].reshape(b * s, dh),
                                      p["scale"].reshape(1, dh * 2))
        return out.reshape(b, s, dh * 2)

    return WireCodec(f"int{bits}_per_channel_pallas", encode, decode,
                     batch_invariant=False)


#: Why there is NO ``pallas_selective_int4`` (deleted round 5; the full
#: measurement story sits where its quantize cores used to live, above
#: :func:`int4_decode_pallas`'s channel siblings): the selective codec is
#: gather-bound and its jnp implementation is the TPU-native one. The probe
#: embeds this string so the exclusion stays a recorded decision, not an
#: absence (``tools/pallas_probe.py``).
SELECTIVE_EXCLUSION = (
    "selective_int4 has no kernel twin BY MEASUREMENT (v5e, rounds 4-5): the "
    "codec is gather-bound; XLA fuses the int4 quantize into its gather "
    "consumers, so a pallas_call boundary only breaks that fusion (twin "
    "probed 0.97x roundtrip; split: encode 0.97x, decode 0.99x). In-kernel "
    "gathers lose structurally on TPU: VMEM row copies are sublane-granular, "
    "a one-hot-matmul gather multiplies traffic by k, a scalar-prefetch DMA "
    "gather needs a B*S-step grid. The jnp codec IS the TPU-native path.")


_PALLAS_FACTORIES = {
    "int4_per_token": pallas_wire_codec,
    "int8_per_token": pallas_int8_per_token,
    "int8_per_channel": lambda: pallas_per_channel(8),
    "int4_per_channel": lambda: pallas_per_channel(4),
    "ternary_mean": lambda: pallas_ternary("mean"),
    "ternary_max": lambda: pallas_ternary("max"),
}

#: NO-DATA FALLBACK for the substitution policy: base codecs whose fused
#: kernel beat the jnp/XLA path on the round-4/5 probe of the tunneled v5e
#: (differential-scan roundtrip, interleaved pairs, median-decided — single
#: runs swing +-30%). Round-4 decision data (5 reps each): int4_per_token
#: 1.33x (fuses the scale reduce + quantize + nibble pack), int4_per_channel
#: ~1.4x, ternary ~1.4x; EXCLUDED: int8_per_token 0.80x, int8_per_channel
#: ~0.92x — passes XLA already fuses into one bandwidth-bound sweep, where a
#: kernel only adds launch/layout overhead. The LIVE policy is the probe
#: cache (``codecs/probe_cache.py``): every bench's probe records each
#: codec's measured speedup keyed by chip fingerprint, and substitution
#: consults that first — this constant only decides when the current chip
#: has never been probed. Substitution must be EARNED — a default path
#: slower than doing nothing is worse than no kernel.
PALLAS_DEFAULT_WINS = frozenset({
    "int4_per_token", "int4_per_channel", "ternary_mean", "ternary_max"})


def default_substituted(base: str) -> bool:
    """The substitution policy for one base codec name: this chip's probe
    cache when it has data, the frozen fallback set when it does not."""
    from . import probe_cache

    win = probe_cache.measured_win(base)
    if win is None:
        return base in PALLAS_DEFAULT_WINS
    return win


def pallas_variant(codec: WireCodec, *, measured_wins_only: bool = False
                   ) -> Optional[WireCodec]:
    """The Pallas-backed twin of a jnp wire codec, or None when no fused kernel
    exists (identity casts — nothing to fuse). With ``measured_wins_only`` the
    twin is returned only when it is a probed on-silicon win for THIS chip
    (:func:`default_substituted`) — the TPU default-substitution policy;
    explicit ``*_pallas`` pins are always honored."""
    if codec.name.endswith("_pallas"):
        return codec
    if codec.name in _PALLAS_FACTORIES:
        if measured_wins_only and not default_substituted(codec.name):
            return None
        # the twins share the jnp codecs' pathological-input saturation, so
        # kernel/jnp payload parity holds on sanitized inputs too
        from .packing import _saturating

        return _saturating(_PALLAS_FACTORIES[codec.name]())
    # selective_int4: no kernel twin exists — a measured deletion, not a gap
    # (SELECTIVE_EXCLUSION); the jnp codec is returned-as-is by the runtimes'
    # `pallas_variant(c) or c` fallback on every path, including forced
    # EDGELLM_PALLAS=1 substitution
    return None
