"""Pallas TPU kernels for the boundary codec hot path.

The reference's clearest kernel-shaped code is its per-channel Python loop over
896 channels (``qwen_layer_wise.py:125-152``, SURVEY.md section 3.5); here the
codec ops are single fused TPU kernels: quantize + nibble/crumb-pack in one VMEM
pass (fp32 in -> packed uint8 + scales out, one HBM round-trip instead of
quantize/clip/round/pack each materializing an intermediate), and the matching
unpack + dequantize.

Layout notes (see ``pallas_guide.md``):
- blocks tile the token axis; the feature axis stays whole (a lane multiple for
  real models: 896, 512) so per-token reductions are single-block row reductions;
- packing pairs element i with element i + D/2 (contiguous halves — full-lane
  slices, no strided lane access); identical to ``packing.pack_int4``;
- interpret mode runs the same kernels on CPU (used by the test suite; the
  wrappers auto-select based on the backend).

Kernel inventory (each bit-identical to its jnp twin in ``packing`` — tested):
- ``int4_per_token``: per-row max-abs scale + quantize + pack, fully fused;
- ``int8_per_token``: per-row affine (min/max -> scale, zero-point) + quantize;
- scalar-scale int4 quantize+pack — the compute core of ``selective_int4``
  (the gather/scatter of selected tokens stays in XLA, which lowers it to
  efficient dynamic-slice sequences; the FLOP+pack part is the kernel);
- channel-scale ternary quantize+pack (``ternary_mean`` / ``ternary_max``;
  the (B,S) channel-scale reduction stays in XLA);
- channel-scale int8 quantize and int4 quantize+pack (``int8_per_channel`` /
  ``int4_per_channel`` — the reference's 896-channel Python loop as one pass).

``pallas_wire_codec`` / ``pallas_int8_per_token`` / ``pallas_selective_int4`` /
``pallas_ternary`` wrap these in the
:class:`~edgellm_tpu.codecs.packing.WireCodec` interface; ``pallas_variant``
maps any jnp wire codec to its Pallas twin (the split runtime substitutes
automatically on TPU).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .packing import WireCodec, selective_int4


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _encode_kernel(x_ref, packed_ref, scale_ref):
    """One token-tile: per-row max-abs scale -> int4 codes -> packed nibbles."""
    x = x_ref[:]  # (T, D) fp32
    half = x.shape[-1] // 2
    max_val = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    safe = jnp.where(max_val > 0, max_val, 1.0)
    codes = jnp.round(jnp.clip(x / safe * 7.0, -8.0, 7.0)).astype(jnp.int32) + 8
    lo, hi = codes[:, :half], codes[:, half:]
    packed_ref[:] = (lo | (hi << 4)).astype(jnp.uint8)
    scale_ref[:] = safe


def _decode_kernel(packed_ref, scale_ref, out_ref):
    """Unpack nibbles -> dequantize. ONE body for every int4 scale granularity:
    ``scale_ref[:]`` broadcasts a per-row (T, 1), global (1, 1), or per-channel
    (1, D) scale block identically, so the unpack logic exists exactly once.
    Arithmetic order matches the per-token jnp twin bit-for-bit; the per-channel
    twin multiplies scale before the /7 (<=1 ulp apart, within the decode
    tolerance the twin tests pin)."""
    packed = packed_ref[:].astype(jnp.int32)  # (T, D/2)
    lo = (packed & 0xF) - 8
    hi = ((packed >> 4) & 0xF) - 8
    codes = jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)
    out_ref[:] = codes / 7.0 * scale_ref[:]


def _tile(n_tokens: int) -> int:
    """Token-tile size: sublane-friendly, bounded by the token count."""
    for t in (256, 128, 64, 32, 16, 8):
        if n_tokens % t == 0:
            return t
    return n_tokens


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_encode_pallas(x: jnp.ndarray, interpret: bool | None = None):
    """(N, D) fp32 -> (packed (N, D/2) uint8, scale (N, 1) fp32), fused."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    grid = (n // t,)
    return pl.pallas_call(
        _encode_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((t, d), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((t, d // 2), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d // 2), jnp.uint8),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x.astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_decode_pallas(packed: jnp.ndarray, scale: jnp.ndarray,
                       interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`int4_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, dh = packed.shape
    t = _tile(n)
    return pl.pallas_call(
        _decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, dh), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((t, dh * 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, dh * 2), jnp.float32),
        interpret=interpret,
    )(packed, scale)


def _int8_affine_encode_kernel(x_ref, q_ref, scale_ref, mn_ref):
    """Per-row affine int8: scale = (max-min)/255, zero-point from min."""
    x = x_ref[:]  # (T, D) fp32
    mn = jnp.min(x, axis=-1, keepdims=True)
    mx = jnp.max(x, axis=-1, keepdims=True)
    scale = (mx - mn) * jnp.float32(1.0 / 255.0)  # matches packing.py bit-for-bit
    safe = jnp.where(scale > 0, scale, 1.0)
    zp = jnp.round(-128.0 - mn / safe)
    q_ref[:] = jnp.clip(jnp.round(x / safe) + zp, -128, 127).astype(jnp.int8)
    scale_ref[:] = scale
    mn_ref[:] = mn


def _int8_affine_decode_kernel(q_ref, scale_ref, mn_ref, out_ref):
    scale, mn = scale_ref[:], mn_ref[:]
    safe = jnp.where(scale > 0, scale, 1.0)
    zp = jnp.round(-128.0 - mn / safe)
    deq = (q_ref[:].astype(jnp.float32) - zp) * safe
    out_ref[:] = jnp.where(scale > 0, deq, mn)


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_affine_encode_pallas(x: jnp.ndarray, interpret: bool | None = None):
    """(N, D) fp32 -> (q (N, D) int8, scale (N, 1) fp32, mn (N, 1) fp32)."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _int8_affine_encode_kernel,
        grid=(n // t,),
        in_specs=[pl.BlockSpec((t, d), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), jnp.int8),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x.astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_affine_decode_pallas(q: jnp.ndarray, scale: jnp.ndarray, mn: jnp.ndarray,
                              interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`int8_affine_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = q.shape
    t = _tile(n)
    return pl.pallas_call(
        _int8_affine_decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((t, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.float32),
        interpret=interpret,
    )(q, scale, mn)


def _int4_scaled_encode_kernel(x_ref, scale_ref, packed_ref):
    """int4 quantize + pack with a provided scale block — broadcasts a global
    (1, 1) or per-row (T, 1) scale identically (one body for both)."""
    x = x_ref[:]
    half = x.shape[-1] // 2
    safe = scale_ref[:]
    codes = jnp.round(jnp.clip(x / safe * 7.0, -8.0, 7.0)).astype(jnp.int32) + 8
    packed_ref[:] = (codes[:, :half] | (codes[:, half:] << 4)).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_scaled_encode_pallas(x: jnp.ndarray, scale: jnp.ndarray,
                              interpret: bool | None = None) -> jnp.ndarray:
    """(N, D) fp32 + global scale (1, 1) -> packed (N, D/2) uint8."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _int4_scaled_encode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d // 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d // 2), jnp.uint8),
        interpret=interpret,
    )(x.astype(jnp.float32), scale.reshape(1, 1).astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_rowscaled_encode_pallas(x: jnp.ndarray, scale: jnp.ndarray,
                                 interpret: bool | None = None) -> jnp.ndarray:
    """(N, D) fp32 + per-row scales (N, 1) -> packed (N, D/2) uint8 (same
    kernel body as the global-scale variant; the scale block is per-row)."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _int4_scaled_encode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((t, d // 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d // 2), jnp.uint8),
        interpret=interpret,
    )(x.astype(jnp.float32), scale.reshape(-1, 1).astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_scaled_decode_pallas(packed: jnp.ndarray, scale: jnp.ndarray,
                              interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`int4_scaled_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, dh = packed.shape
    t = _tile(n)
    return pl.pallas_call(
        _decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, dh), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, dh * 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, dh * 2), jnp.float32),
        interpret=interpret,
    )(packed, scale.reshape(1, 1).astype(jnp.float32))


def _chan_int8_encode_kernel(x_ref, scale_ref, q_ref):
    """Per-channel symmetric int8 quantize with provided channel scales (1, D)."""
    q_ref[:] = jnp.round(x_ref[:] / scale_ref[:] * 127.0).astype(jnp.int8)


def _chan_int8_decode_kernel(q_ref, scale_ref, out_ref):
    # divide (not reciprocal-multiply): matches the jnp twin bit-for-bit
    out_ref[:] = q_ref[:].astype(jnp.float32) * scale_ref[:] / 127.0


@functools.partial(jax.jit, static_argnames=("interpret",))
def chan_int8_encode_pallas(x: jnp.ndarray, scale: jnp.ndarray,
                            interpret: bool | None = None) -> jnp.ndarray:
    """(N, D) fp32 + channel scales (1, D) -> int8 codes (N, D)."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _chan_int8_encode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.int8),
        interpret=interpret,
    )(x.astype(jnp.float32), scale.reshape(1, -1).astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def chan_int8_decode_pallas(q: jnp.ndarray, scale: jnp.ndarray,
                            interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`chan_int8_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = q.shape
    t = _tile(n)
    return pl.pallas_call(
        _chan_int8_decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.float32),
        interpret=interpret,
    )(q, scale.reshape(1, -1).astype(jnp.float32))


def _chan_int4_encode_kernel(x_ref, scale_ref, packed_ref):
    """Per-channel symmetric int4 quantize + nibble pack, channel scales (1, D).

    No clip: |x| <= channel max by construction, so codes land in [-7, 7]
    (mirrors the jnp twin ``packing._int4_per_channel`` bit-for-bit)."""
    x = x_ref[:]
    half = x.shape[-1] // 2
    codes = jnp.round(x / scale_ref[:] * 7.0).astype(jnp.int32) + 8
    packed_ref[:] = (codes[:, :half] | (codes[:, half:] << 4)).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def chan_int4_encode_pallas(x: jnp.ndarray, scale: jnp.ndarray,
                            interpret: bool | None = None) -> jnp.ndarray:
    """(N, D) fp32 + channel scales (1, D) -> packed (N, D/2) uint8."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _chan_int4_encode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d // 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d // 2), jnp.uint8),
        interpret=interpret,
    )(x.astype(jnp.float32), scale.reshape(1, -1).astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def chan_int4_decode_pallas(packed: jnp.ndarray, scale: jnp.ndarray,
                            interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`chan_int4_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, dh = packed.shape
    t = _tile(n)
    return pl.pallas_call(
        _decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, dh), lambda i: (i, 0)),
            pl.BlockSpec((1, dh * 2), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, dh * 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, dh * 2), jnp.float32),
        interpret=interpret,
    )(packed, scale.reshape(1, -1).astype(jnp.float32))


def _ternary_encode_kernel(x_ref, scale_ref, packed_ref):
    """Ternary quantize + 2-bit pack with provided per-channel scales (1, D)."""
    x = x_ref[:]
    quarter = x.shape[-1] // 4
    codes = (jnp.clip(jnp.round(x / scale_ref[:]), -1, 1).astype(jnp.int32) + 1)
    packed_ref[:] = (codes[:, :quarter]
                     | (codes[:, quarter:2 * quarter] << 2)
                     | (codes[:, 2 * quarter:3 * quarter] << 4)
                     | (codes[:, 3 * quarter:] << 6)).astype(jnp.uint8)


def _ternary_decode_kernel(packed_ref, scale_ref, out_ref):
    packed = packed_ref[:].astype(jnp.int32)
    parts = [((packed >> (2 * i)) & 0x3) - 1 for i in range(4)]
    codes = jnp.concatenate(parts, axis=-1).astype(jnp.float32)
    out_ref[:] = codes * scale_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def ternary_encode_pallas(x: jnp.ndarray, scale: jnp.ndarray,
                          interpret: bool | None = None) -> jnp.ndarray:
    """(N, D) fp32 + channel scales (1, D) -> packed (N, D/4) uint8."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    return pl.pallas_call(
        _ternary_encode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, d // 4), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d // 4), jnp.uint8),
        interpret=interpret,
    )(x.astype(jnp.float32), scale.reshape(1, -1).astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def ternary_decode_pallas(packed: jnp.ndarray, scale: jnp.ndarray,
                          interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`ternary_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, dq = packed.shape
    t = _tile(n)
    return pl.pallas_call(
        _ternary_decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, dq), lambda i: (i, 0)),
            pl.BlockSpec((1, dq * 4), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, dq * 4), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, dq * 4), jnp.float32),
        interpret=interpret,
    )(packed, scale.reshape(1, -1).astype(jnp.float32))


# ---------- WireCodec wrappers ----------


def pallas_wire_codec() -> WireCodec:
    """``int4_per_token`` wire codec backed by the fused Pallas kernels.

    Bit-identical payloads and reconstruction vs the jnp ``int4_per_token``
    codec (tested), usable as a split-runtime hop codec.
    """

    def encode(h):
        b, s, d = h.shape
        packed, scale = int4_encode_pallas(h.reshape(b * s, d))
        return {"packed": packed.reshape(b, s, d // 2),
                "scale": scale.reshape(b, s, 1)}

    def decode(p):
        b, s, dh = p["packed"].shape
        out = int4_decode_pallas(p["packed"].reshape(b * s, dh),
                                 p["scale"].reshape(b * s, 1))
        return out.reshape(b, s, dh * 2)

    return WireCodec("int4_per_token_pallas", encode, decode)


def pallas_int8_per_token() -> WireCodec:
    """``int8_per_token`` wire codec backed by the fused affine kernels."""

    def encode(h):
        b, s, d = h.shape
        q, scale, mn = int8_affine_encode_pallas(h.reshape(b * s, d))
        return {"q": q.reshape(b, s, d), "scale": scale.reshape(b, s, 1),
                "mn": mn.reshape(b, s, 1)}

    def decode(p):
        b, s, d = p["q"].shape
        out = int8_affine_decode_pallas(p["q"].reshape(b * s, d),
                                        p["scale"].reshape(b * s, 1),
                                        p["mn"].reshape(b * s, 1))
        return out.reshape(b, s, d)

    return WireCodec("int8_per_token_pallas", encode, decode)


def pallas_ternary(kind: str) -> WireCodec:
    """``ternary_mean`` / ``ternary_max`` with the quantize+pack fused; the
    (batch, seq) channel-scale reduction stays in XLA (a single fused reduce)."""

    def encode(h):
        b, s, d = h.shape
        if kind == "mean":
            scale = jnp.mean(h, axis=(0, 1), keepdims=True) + 1e-8
        else:
            cmax = jnp.max(jnp.abs(h), axis=(0, 1), keepdims=True)
            scale = jnp.where(cmax > 0, cmax, 1.0)
        packed = ternary_encode_pallas(h.reshape(b * s, d), scale.reshape(1, d))
        return {"packed": packed.reshape(b, s, d // 4), "scale": scale}

    def decode(p):
        b, s, dq = p["packed"].shape
        out = ternary_decode_pallas(p["packed"].reshape(b * s, dq),
                                    p["scale"].reshape(1, dq * 4))
        return out.reshape(b, s, dq * 4)

    return WireCodec(f"ternary_{kind}_pallas", encode, decode,
                     batch_invariant=False)


def pallas_per_channel(bits: int) -> WireCodec:
    """``int8_per_channel`` / ``int4_per_channel`` with the quantize(+pack)
    fused; the (batch, seq) channel abs-max reduction stays in XLA (one fused
    reduce). This is the reference's 896-iteration channel loop
    (``qwen_layer_wise.py:125-152``) as a single kernel pass.

    The int4 kernel earns its keep by fusing the nibble pack. The int8 kernel
    is a plain elementwise op XLA fuses equally well on its own — it exists so
    every quantizing hop codec has a kernel twin (uniform Pallas hop pipeline,
    BASELINE.json north star), not for a fusion win."""

    def encode(h):
        b, s, d = h.shape
        cmax = jnp.max(jnp.abs(h), axis=(0, 1), keepdims=True)
        safe = jnp.where(cmax > 0, cmax, 1.0)
        flat = h.reshape(b * s, d)
        if bits == 8:
            return {"q": chan_int8_encode_pallas(flat, safe.reshape(1, d))
                    .reshape(b, s, d), "scale": safe}
        return {"packed": chan_int4_encode_pallas(flat, safe.reshape(1, d))
                .reshape(b, s, d // 2), "scale": safe}

    def decode(p):
        if bits == 8:
            b, s, d = p["q"].shape
            out = chan_int8_decode_pallas(p["q"].reshape(b * s, d),
                                          p["scale"].reshape(1, d))
            return out.reshape(b, s, d)
        b, s, dh = p["packed"].shape
        out = chan_int4_decode_pallas(p["packed"].reshape(b * s, dh),
                                      p["scale"].reshape(1, dh * 2))
        return out.reshape(b, s, dh * 2)

    return WireCodec(f"int{bits}_per_channel_pallas", encode, decode,
                     batch_invariant=False)


def pallas_selective_int4(ratio: float, high: str = "bf16") -> WireCodec:
    """Token-selective mixed-precision codec with the int4 low-path quantize+pack
    (and unpack+dequantize) as fused kernels.

    One definition of the wire format: this delegates to
    ``packing.selective_int4`` with the compute core swapped for the kernels —
    the gather of the k least-important tokens and the global max-abs reduction
    stay in XLA (gathers are XLA's strength; a Pallas row-gather would serialize
    on dynamic sublane indices), the quantize+pack of the gathered (B, k, D)
    slice is the kernel.
    """

    def quant_pack(low, safe):
        b, k, d = low.shape
        safe = jnp.asarray(safe)
        if safe.size > 1:  # per-row (B, 1, 1) scales -> one scale per flat row
            rows = jnp.broadcast_to(safe.reshape(b, 1), (b, k)).reshape(b * k, 1)
            return int4_rowscaled_encode_pallas(low.reshape(b * k, d), rows) \
                .reshape(b, k, d // 2)
        return int4_scaled_encode_pallas(low.reshape(b * k, d), safe) \
            .reshape(b, k, d // 2)

    def unpack_dequant(packed, safe):
        b, k, dh = packed.shape
        safe = jnp.asarray(safe)
        if safe.size > 1:  # per-row scales: the shared decode kernel broadcasts
            rows = jnp.broadcast_to(safe.reshape(b, 1), (b, k)).reshape(b * k, 1)
            return int4_decode_pallas(packed.reshape(b * k, dh), rows) \
                .reshape(b, k, dh * 2)
        return int4_scaled_decode_pallas(packed.reshape(b * k, dh), safe) \
            .reshape(b, k, dh * 2)

    return selective_int4(ratio, high, quant_pack=quant_pack,
                          unpack_dequant=unpack_dequant, name_suffix="_pallas")


_PALLAS_FACTORIES = {
    "int4_per_token": pallas_wire_codec,
    "int8_per_token": pallas_int8_per_token,
    "int8_per_channel": lambda: pallas_per_channel(8),
    "int4_per_channel": lambda: pallas_per_channel(4),
    "ternary_mean": lambda: pallas_ternary("mean"),
    "ternary_max": lambda: pallas_ternary("max"),
}

#: Base codecs whose fused kernel MEASURABLY beats the jnp/XLA path on silicon
#: (differential-scan roundtrip probe, repeated and decided on the median —
#: single probe runs on the tunneled chip swing +-30% for the fastest bodies).
#: Round-4 decision data (5 reps each): int4_per_token 1.33x (fuses the scale
#: reduce + quantize + nibble pack), int4_per_channel ~1.4x, ternary ~1.4x;
#: EXCLUDED: int8_per_token 0.80x, int8_per_channel ~0.92x, selective core
#: ~0.97x — those are passes XLA already fuses into one bandwidth-bound sweep,
#: so the kernel only adds launch/layout overhead. Substitution must be
#: EARNED — a default path slower than doing nothing is worse than no kernel.
PALLAS_DEFAULT_WINS = frozenset({
    "int4_per_token", "int4_per_channel", "ternary_mean", "ternary_max"})


def pallas_variant(codec: WireCodec, *, measured_wins_only: bool = False
                   ) -> Optional[WireCodec]:
    """The Pallas-backed twin of a jnp wire codec, or None when no fused kernel
    exists (identity casts — nothing to fuse). With ``measured_wins_only`` the
    twin is returned only when it is a probed on-silicon win
    (``PALLAS_DEFAULT_WINS``) — the TPU default-substitution policy; explicit
    ``*_pallas`` pins are always honored."""
    if codec.name.endswith("_pallas"):
        return codec
    if codec.name in _PALLAS_FACTORIES:
        if measured_wins_only and codec.name not in PALLAS_DEFAULT_WINS:
            return None
        return _PALLAS_FACTORIES[codec.name]()
    if codec.name.startswith("selective_int4_r"):
        if measured_wins_only:  # quantize core probed at 0.97x — not a win
            return None
        ratio_high = codec.name[len("selective_int4_r"):]
        ratio_str, high = ratio_high.rsplit("_", 1)
        return pallas_selective_int4(float(ratio_str), high)
    return None
