"""Pallas TPU kernels for the boundary codec hot path.

The reference's clearest kernel-shaped code is its per-channel Python loop over
896 channels (``qwen_layer_wise.py:125-152``, SURVEY.md section 3.5); here the
codec ops are single fused TPU kernels: quantize + nibble-pack in one VMEM pass
(fp32 in -> packed uint8 + scales out, one HBM round-trip instead of
quantize/clip/round/pack each materializing an intermediate), and the matching
unpack + dequantize.

Layout notes (see ``pallas_guide.md``):
- blocks tile the token axis; the feature axis stays whole (a lane multiple for
  real models: 896, 512) so per-token reductions are single-block row reductions;
- packing pairs element i with element i + D/2 (contiguous halves — full-lane
  slices, no strided lane access); identical to ``packing.pack_int4``;
- interpret mode runs the same kernels on CPU (used by the test suite; the
  wrappers auto-select based on the backend).

These kernels implement the ``int4_per_token`` wire codec; ``pallas_wire_codec``
wraps them in the :class:`~edgellm_tpu.codecs.packing.WireCodec` interface so the
split runtime can use them as hop codecs on TPU unchanged.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .packing import WireCodec


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _encode_kernel(x_ref, packed_ref, scale_ref):
    """One token-tile: per-row max-abs scale -> int4 codes -> packed nibbles."""
    x = x_ref[:]  # (T, D) fp32
    half = x.shape[-1] // 2
    max_val = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    safe = jnp.where(max_val > 0, max_val, 1.0)
    codes = jnp.round(jnp.clip(x / safe * 7.0, -8.0, 7.0)).astype(jnp.int32) + 8
    lo, hi = codes[:, :half], codes[:, half:]
    packed_ref[:] = (lo | (hi << 4)).astype(jnp.uint8)
    scale_ref[:] = safe


def _decode_kernel(packed_ref, scale_ref, out_ref):
    """Inverse: unpack nibbles -> dequantize with the per-row scale."""
    packed = packed_ref[:].astype(jnp.int32)  # (T, D/2)
    lo = (packed & 0xF) - 8
    hi = ((packed >> 4) & 0xF) - 8
    codes = jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)
    out_ref[:] = codes / 7.0 * scale_ref[:]


def _tile(n_tokens: int) -> int:
    """Token-tile size: sublane-friendly, bounded by the token count."""
    for t in (256, 128, 64, 32, 16, 8):
        if n_tokens % t == 0:
            return t
    return n_tokens


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_encode_pallas(x: jnp.ndarray, interpret: bool | None = None):
    """(N, D) fp32 -> (packed (N, D/2) uint8, scale (N, 1) fp32), fused."""
    if interpret is None:
        interpret = _use_interpret()
    n, d = x.shape
    t = _tile(n)
    grid = (n // t,)
    return pl.pallas_call(
        _encode_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((t, d), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((t, d // 2), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d // 2), jnp.uint8),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x.astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_decode_pallas(packed: jnp.ndarray, scale: jnp.ndarray,
                       interpret: bool | None = None) -> jnp.ndarray:
    """Inverse of :func:`int4_encode_pallas` -> (N, D) fp32."""
    if interpret is None:
        interpret = _use_interpret()
    n, dh = packed.shape
    t = _tile(n)
    return pl.pallas_call(
        _decode_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, dh), lambda i: (i, 0)),
            pl.BlockSpec((t, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((t, dh * 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, dh * 2), jnp.float32),
        interpret=interpret,
    )(packed, scale)


def pallas_wire_codec() -> WireCodec:
    """``int4_per_token`` wire codec backed by the fused Pallas kernels.

    Bit-identical payloads and reconstruction vs the jnp ``int4_per_token``
    codec (tested), usable as a split-runtime hop codec.
    """

    def encode(h):
        b, s, d = h.shape
        packed, scale = int4_encode_pallas(h.reshape(b * s, d))
        return {"packed": packed.reshape(b, s, d // 2),
                "scale": scale.reshape(b, s, 1)}

    def decode(p):
        b, s, dh = p["packed"].shape
        out = int4_decode_pallas(p["packed"].reshape(b * s, dh),
                                 p["scale"].reshape(b * s, 1))
        return out.reshape(b, s, dh * 2)

    return WireCodec("int4_per_token_pallas", encode, decode)
