"""Packed wire codecs: the bytes that actually cross the device boundary.

The reference never packs anything — its quantization is an in-place fp
quantize->dequantize and its compression claims are analytic bit counts
(SURVEY.md section 5, ``BASELINE.md``). Here every codec has a real packed
representation: ``encode`` produces integer payload buffers (int4 nibbles packed
two-per-byte, ternary codes four-per-byte) plus fp scales, ``decode`` inverts the
packing, and ``payload_bytes`` is measured from the buffers that cross
``lax.ppermute`` in the split runtime — not asserted.

Numerical contract: for every codec, ``decode(encode(x))`` equals the matching
*simulate* codec's quantize->dequantize output exactly (tested), so a split run
with a wire codec reproduces the reference's simulated-quantization perplexities
while moving real compressed bytes.
"""
from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


def pack_int4(codes: jnp.ndarray) -> jnp.ndarray:
    """Pack int4 codes in [-8, 7] (last axis even) into uint8, two per byte.

    Wire layout: element i pairs with element i + D/2 (low nibble = first half,
    high nibble = second half). Contiguous-half pairing keeps the packing a pair
    of full-lane slices on TPU (the interleaved 0::2/1::2 layout would be a
    strided lane access) — the Pallas kernels share this convention.
    """
    half = codes.shape[-1] // 2
    u = (codes.astype(jnp.int32) + 8).astype(jnp.uint8)  # [0, 15]
    return u[..., :half] | (u[..., half:] << 4)


def unpack_int4(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_int4` -> int8 codes in [-8, 7]."""
    lo = (packed & 0xF).astype(jnp.int8) - 8
    hi = (packed >> 4).astype(jnp.int8) - 8
    return jnp.concatenate([lo, hi], axis=-1)


def pack_ternary(codes: jnp.ndarray) -> jnp.ndarray:
    """Pack ternary codes in {-1, 0, 1} (last axis % 4 == 0) into uint8, four per
    byte. Same contiguous-quarter pairing as :func:`pack_int4`."""
    quarter = codes.shape[-1] // 4
    u = (codes.astype(jnp.int32) + 1).astype(jnp.uint8)  # [0, 2], 2 bits each
    parts = [u[..., i * quarter:(i + 1) * quarter] for i in range(4)]
    return parts[0] | (parts[1] << 2) | (parts[2] << 4) | (parts[3] << 6)


def unpack_ternary(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_ternary` -> int8 codes in {-1, 0, 1}."""
    parts = [((packed >> (2 * i)) & 0x3).astype(jnp.int8) - 1 for i in range(4)]
    return jnp.concatenate(parts, axis=-1)


def _nbytes(tree) -> int:
    return int(sum(np.prod(a.shape) * a.dtype.itemsize
                   for a in jax.tree_util.tree_leaves(tree)))


#: saturation bound for pathological encoder inputs; well inside fp32 range so
#: downstream scale arithmetic (division, reciprocal-multiply) stays finite
SATURATE_MAG = 1e30


def sanitize_hidden(h: jnp.ndarray, max_mag: float = SATURATE_MAG) -> jnp.ndarray:
    """Deterministic saturation of pathological activations before encoding:
    NaN -> 0, +-Inf and magnitudes beyond ``max_mag`` clamp to ``+-max_mag``.
    A bit-exact identity for ordinary finite inputs (clip and a false-predicate
    where both return x unchanged), so codec parity with the simulate path is
    untouched — but no wire codec ever turns a poisoned activation into silent
    garbage bytes: every payload decodes to something finite."""
    h = jnp.clip(h, -max_mag, max_mag)  # NaN propagates through clip...
    return jnp.where(jnp.isnan(h), jnp.zeros_like(h), h)  # ...and lands here


def _saturating(codec: "WireCodec", max_mag: float = SATURATE_MAG) -> "WireCodec":
    """Wrap a codec's encode with :func:`sanitize_hidden` (identity for finite
    inputs). Every registry codec and every Pallas twin passes through this."""
    enc = codec.encode
    if codec.needs_importance:
        def wrapped(h, importance):
            return enc(sanitize_hidden(h, max_mag), importance)
    else:
        def wrapped(h):
            return enc(sanitize_hidden(h, max_mag))
    return dataclasses.replace(codec, encode=wrapped)


@dataclasses.dataclass(frozen=True)
class WireCodec:
    """One boundary codec: ``encode(hidden) -> payload`` (pytree of arrays that
    cross the wire), ``decode(payload) -> hidden``. ``payload_bytes`` measures the
    encoded size of one (B, S, D) activation.

    ``batch_invariant``: True when encode/decode treat batch rows independently
    (per-token codecs, identity casts). Codecs whose scales reduce over the batch
    or sequence axes (global / per-channel) are NOT safe under data-parallel
    sharding of the batch axis — each shard would compute a different scale than
    a single-device run; the split runtime rejects that combination."""

    name: str
    encode: Callable
    decode: Callable
    batch_invariant: bool = True
    #: True when ``encode`` takes (hidden, importance) — the split runtime must
    #: supply a per-hop importance vector (token-selective mixed precision)
    needs_importance: bool = False

    def payload_bytes(self, hidden_shape, dtype=jnp.float32) -> int:
        spec = jax.ShapeDtypeStruct(hidden_shape, dtype)
        if self.needs_importance:
            # batch > 1 implies per-row importance (per-row ordering/scale wire
            # format — the low-index side channel is B x k, not k)
            b, s = hidden_shape[0], hidden_shape[1]
            imp = jax.ShapeDtypeStruct((s,) if b == 1 else (b, s), jnp.float32)
            return _nbytes(jax.eval_shape(self.encode, spec, imp))
        return _nbytes(jax.eval_shape(self.encode, spec))


def _identity_codec(name: str, dtype) -> WireCodec:
    # saturate to the WIRE dtype's own range (fp16 overflows far below
    # SATURATE_MAG), so a huge input crosses as the dtype max, never as Inf
    max_mag = min(SATURATE_MAG, float(jnp.finfo(dtype).max))
    return _saturating(WireCodec(
        name=name,
        encode=lambda h: {"x": h.astype(dtype)},
        decode=lambda p: p["x"].astype(jnp.float32),
    ), max_mag)


def _int8_per_token() -> WireCodec:
    """Per-token affine int8: D bytes + 2 fp32 scalars (scale, min) per token
    (the intent of ``pythia_model.py:57-68``). The zero-point is recomputed from
    (scale, min) on the decode side; constant tokens (scale == 0) reconstruct to
    exactly ``min`` — matching the simulate codec's pass-through."""

    def encode(h):
        mn = jnp.min(h, axis=-1, keepdims=True)
        mx = jnp.max(h, axis=-1, keepdims=True)
        # multiply by the fp32 reciprocal rather than divide: a constant divide
        # is strength-reduced differently under jit vs eager (1-ulp drift), and
        # the Pallas twin must produce bit-identical scales
        scale = (mx - mn) * jnp.float32(1.0 / 255.0)
        safe = jnp.where(scale > 0, scale, 1.0)
        zp = jnp.round(-128.0 - mn / safe)
        q = jnp.clip(jnp.round(h / safe) + zp, -128, 127).astype(jnp.int8)
        return {"q": q, "scale": scale, "mn": mn}

    def decode(p):
        safe = jnp.where(p["scale"] > 0, p["scale"], 1.0)
        zp = jnp.round(-128.0 - p["mn"] / safe)
        deq = (p["q"].astype(jnp.float32) - zp) * safe
        return jnp.where(p["scale"] > 0, deq, p["mn"])

    return WireCodec("int8_per_token", encode, decode)


def _int4_global() -> WireCodec:
    """Symmetric int4 with one global max-abs scale — the packed twin of the
    reference's headline simulated codec (``qwen_layer_wise.py:58-70``)."""

    def encode(h):
        max_val = jnp.max(jnp.abs(h))
        safe = jnp.where(max_val > 0, max_val, 1.0)
        codes = jnp.round(jnp.clip(h / safe * 7.0, -8.0, 7.0)).astype(jnp.int8)
        return {"packed": pack_int4(codes), "scale": safe[None]}

    def decode(p):
        return unpack_int4(p["packed"]).astype(jnp.float32) / 7.0 * p["scale"][0]

    return WireCodec("int4_global", encode, decode, batch_invariant=False)


def _int4_per_token() -> WireCodec:
    """Symmetric int4, one max-abs scale per token (D/2 bytes + 4 per token)."""

    def encode(h):
        max_val = jnp.max(jnp.abs(h), axis=-1, keepdims=True)
        safe = jnp.where(max_val > 0, max_val, 1.0)
        codes = jnp.round(jnp.clip(h / safe * 7.0, -8.0, 7.0)).astype(jnp.int8)
        return {"packed": pack_int4(codes), "scale": safe}

    def decode(p):
        return unpack_int4(p["packed"]).astype(jnp.float32) / 7.0 * p["scale"]

    return WireCodec("int4_per_token", encode, decode)


def _ternary(kind: str) -> WireCodec:
    """Per-channel ternary (packed twin of ``channel_1_mean`` / ``channel_1_max``,
    ``qwen_layer_wise.py:135-150``): D/4 bytes per token + D fp32 channel scales."""

    def encode(h):
        if kind == "mean":
            scale = jnp.mean(h, axis=(0, 1), keepdims=True) + 1e-8
            codes = jnp.clip(jnp.round(h / scale), -1, 1).astype(jnp.int8)
        else:
            cmax = jnp.max(jnp.abs(h), axis=(0, 1), keepdims=True)
            scale = jnp.where(cmax > 0, cmax, 1.0)
            codes = jnp.clip(jnp.round(h / scale), -1, 1).astype(jnp.int8)
        return {"packed": pack_ternary(codes), "scale": scale}

    def decode(p):
        return unpack_ternary(p["packed"]).astype(jnp.float32) * p["scale"]

    return WireCodec(f"ternary_{kind}", encode, decode, batch_invariant=False)


def _ternary_per_token() -> WireCodec:
    """Per-token symmetric ternary: D/4 packed crumbs + one fp32 max-abs scale
    per token. The degradation ladder's floor tier (``codecs.faults``): unlike
    the per-channel ternary codecs its scale reduces only over the feature
    axis, so it is batch-invariant — legal under data parallelism and the
    stage x seq runtime, and usable for single-token decode hops."""

    def encode(h):
        mx = jnp.max(jnp.abs(h), axis=-1, keepdims=True)
        scale = jnp.where(mx > 0, mx, 1.0)
        codes = jnp.clip(jnp.round(h / scale), -1, 1).astype(jnp.int8)
        return {"packed": pack_ternary(codes), "scale": scale}

    def decode(p):
        return unpack_ternary(p["packed"]).astype(jnp.float32) * p["scale"]

    return WireCodec("ternary_per_token", encode, decode)


def _int8_per_channel() -> WireCodec:
    """Per-channel symmetric int8 (packed twin of ``channel_8``)."""

    def encode(h):
        # an all-zero channel encodes to zero codes and decodes to exactly zero,
        # so no zero-channel sidecar is needed
        cmax = jnp.max(jnp.abs(h), axis=(0, 1), keepdims=True)
        safe = jnp.where(cmax > 0, cmax, 1.0)
        codes = jnp.round(h / safe * 127.0).astype(jnp.int8)
        return {"q": codes, "scale": safe}

    def decode(p):
        return p["q"].astype(jnp.float32) * p["scale"] / 127.0

    return WireCodec("int8_per_channel", encode, decode, batch_invariant=False)


def _int4_per_channel() -> WireCodec:
    """Per-channel symmetric int4 (packed twin of ``channel_4``)."""

    def encode(h):
        cmax = jnp.max(jnp.abs(h), axis=(0, 1), keepdims=True)
        safe = jnp.where(cmax > 0, cmax, 1.0)
        codes = jnp.round(h / safe * 7.0).astype(jnp.int8)
        return {"packed": pack_int4(codes), "scale": safe}

    def decode(p):
        return unpack_int4(p["packed"]).astype(jnp.float32) * p["scale"] / 7.0

    return WireCodec("int4_per_channel", encode, decode, batch_invariant=False)


def _jnp_quant_pack(low: jnp.ndarray, safe: jnp.ndarray) -> jnp.ndarray:
    """(B, k, D) fp32 + global scale -> packed (B, k, D/2) int4 nibbles."""
    codes = jnp.round(jnp.clip(low / safe * 7.0, -8.0, 7.0)).astype(jnp.int8)
    return pack_int4(codes)


def _jnp_unpack_dequant(packed: jnp.ndarray, safe: jnp.ndarray) -> jnp.ndarray:
    return unpack_int4(packed).astype(jnp.float32) / 7.0 * safe


def _local_selective_scale(low, nonempty: bool, per_row: bool):
    """Default int4 scale for the selective codec: max|low| with the zero /
    empty-k guard. ``nonempty`` is the static ``k > 0``."""
    if per_row:
        mx = (jnp.max(jnp.abs(low), axis=(1, 2)) if nonempty
              else jnp.zeros((low.shape[0],), jnp.float32))
    else:
        mx = jnp.max(jnp.abs(low)) if nonempty else jnp.asarray(0.0)
    return jnp.where(mx > 0, mx, 1.0)


def selective_int4(ratio: float, high: str = "bf16", *,
                   quant_pack=None, unpack_dequant=None, scale_fn=None,
                   name_suffix: str = "") -> WireCodec:
    """Token-selective mixed-precision boundary codec (BASELINE.json configs[2]).

    The reference's headline scheme: the ``ratio`` least-important tokens cross
    as symmetric int4 with one global scale over the selected slice
    (``qwen_layer_wise.py:54-70``), the remaining tokens cross at ``high``
    precision (fp16/bf16 is the reference's notional transfer baseline, fp32 is
    bit-exact vs the in-place simulation). The wire carries two COMPACTED
    buffers — ``k = floor(ratio*S)`` is static, so the low/high split has static
    shapes — plus the side channel needed to reassemble on the far side: ONLY
    the ``k`` low-token indices, as int16 (S <= 32767). The high tokens are
    shipped in position-ascending order, so their placement is derived on the
    decode side as the sorted complement of the low-index set — no full
    permutation crosses the wire (2k bytes vs the naive 4S; the reference's
    analytic byte counts ignore the side channel entirely, the measured
    ``payload_bytes`` here does not).

    ``encode(hidden, importance)``; the split runtime threads the importance
    vector to importance-carrying hops. ``importance`` may be a shared (S,)
    vector (the reference's batch-1 shape — wire format unchanged) or per-row
    (B, S): each evaluation window then carries its OWN ordering and scale,
    exactly as the reference selects per window at batch 1
    (``Qwen2-0.5B/main.py:161-165``), which is what makes this codec usable
    under data-parallel window batching.

    ``quant_pack(low, scale)`` / ``unpack_dequant(packed, scale)`` override the
    int4 compute core (the Pallas wrapper passes its fused kernels; the wire
    format and all selection/reassembly logic stay in this one definition).
    ``scale`` arrives as a scalar (shared path) or (B, 1, 1) (per-row path).
    ``scale_fn(low, nonempty, per_row)`` overrides the scale reduction (the
    ring-sharded local mode passes a ``pmax``-agreed global scale).
    """
    if not 0.0 <= ratio <= 1.0:
        raise ValueError(f"ratio must be in [0, 1], got {ratio}")
    high_dtype = {"fp32": jnp.float32, "bf16": jnp.bfloat16, "fp16": jnp.float16}[high]
    quant_pack = quant_pack or _jnp_quant_pack
    unpack_dequant = unpack_dequant or _jnp_unpack_dequant
    scale_fn = scale_fn or _local_selective_scale

    def encode(h, importance):
        b, s, d = h.shape
        if s > 32767:
            raise ValueError(f"selective_int4 int16 index side channel needs "
                             f"S <= 32767, got {s}")
        k = int(ratio * s)
        importance = jnp.asarray(importance)
        if importance.ndim == 2:  # per-row ordering + scale
            order = jnp.argsort(importance, axis=-1)  # (B, S), ascending
            rows = jnp.arange(b)[:, None]
            low = h[rows, order[:, :k]]  # (B, k, D)
            safe = scale_fn(low, k > 0, True)  # (B,)
            # high tokens ship position-ascending: their placement is implied
            # by the low-index set, so only the k low indices cross the wire
            high_pos = jnp.sort(order[:, k:], axis=-1)
            return {
                "low": (quant_pack(low, safe[:, None, None]) if k
                        else jnp.zeros((b, 0, d // 2), jnp.uint8)),
                "scale": safe,
                "high": h[rows, high_pos].astype(high_dtype),
                "order": order[:, :k].astype(jnp.int16),
            }
        order = jnp.argsort(importance)  # ascending, stable — least important first
        low_idx = order[:k]
        high_pos = jnp.sort(order[k:])  # position-ascending (see per-row note)
        low = jnp.take(h, low_idx, axis=1)  # (B, k, D)
        safe = scale_fn(low, k > 0, False)
        return {
            "low": quant_pack(low, safe) if k else jnp.zeros((b, 0, d // 2), jnp.uint8),
            "scale": safe[None],
            "high": jnp.take(h, high_pos, axis=1).astype(high_dtype),
            "order": low_idx.astype(jnp.int16),
        }

    def decode(p):
        b = p["high"].shape[0]
        k = p["low"].shape[1]
        d = p["low"].shape[2] * 2 if k else p["high"].shape[2]
        s = k + p["high"].shape[1]
        out = jnp.zeros((b, s, d), jnp.float32)
        if p["order"].ndim == 2:  # per-row
            low_idx = p["order"].astype(jnp.int32)  # (B, k)
            rows = jnp.arange(b)[:, None]
            mask = jnp.ones((b, s), bool).at[rows, low_idx].set(False)
            high_pos = jax.vmap(lambda m: jnp.nonzero(m, size=s - k)[0])(mask)
            low = unpack_dequant(p["low"], p["scale"][:, None, None]) \
                if k else jnp.zeros((b, 0, d), jnp.float32)
            out = out.at[rows, low_idx].set(low)
            return out.at[rows, high_pos].set(p["high"].astype(jnp.float32))
        low_idx = p["order"].astype(jnp.int32)  # (k,)
        mask = jnp.ones((s,), bool).at[low_idx].set(False)
        high_pos = jnp.nonzero(mask, size=s - k)[0]  # sorted complement
        low = unpack_dequant(p["low"], p["scale"][0]) \
            if k else jnp.zeros((b, 0, d), jnp.float32)
        out = out.at[:, low_idx, :].set(low)
        return out.at[:, high_pos, :].set(p["high"].astype(jnp.float32))

    # high tokens cross at `high` precision: saturate to THAT dtype's range
    return _saturating(
        WireCodec(f"selective_int4_r{ratio}_{high}{name_suffix}", encode, decode,
                  batch_invariant=False, needs_importance=True),
        min(SATURATE_MAG, float(jnp.finfo(high_dtype).max)))


def _pallas(base_name: str) -> Callable[[], WireCodec]:
    """Lazy factory for a Pallas-backed codec (pallas_kernels imports this
    module, so the import must happen at call time)."""

    def factory() -> WireCodec:
        from .pallas_kernels import pallas_variant

        return pallas_variant(get_wire_codec(base_name))

    return factory


def get_wire_codec(name: str) -> WireCodec:
    """Codec registry. Names map to the reference's boundary compression schemes
    (fp16 is its notional uncompressed transfer baseline, BASELINE.md). The
    ``*_pallas`` names select the fused TPU kernel implementation explicitly;
    on TPU the split runtime substitutes them for the jnp twins automatically."""
    # identity codecs, selective_int4, and the Pallas twins sanitize inside
    # their own factories (dtype-specific bounds / shared twin path); the
    # quantizing jnp codecs are wrapped here
    factories = {
        "fp32": lambda: _identity_codec("fp32", jnp.float32),
        "bf16": lambda: _identity_codec("bf16", jnp.bfloat16),
        "fp16": lambda: _identity_codec("fp16", jnp.float16),
        "int8_per_token": lambda: _saturating(_int8_per_token()),
        "int8_per_channel": lambda: _saturating(_int8_per_channel()),
        "int4_global": lambda: _saturating(_int4_global()),
        "int4_per_token": lambda: _saturating(_int4_per_token()),
        "int4_per_channel": lambda: _saturating(_int4_per_channel()),
        "ternary_mean": lambda: _saturating(_ternary("mean")),
        "ternary_max": lambda: _saturating(_ternary("max")),
        "ternary_per_token": lambda: _saturating(_ternary_per_token()),
        "int4_per_token_pallas": _pallas("int4_per_token"),
        "int8_per_token_pallas": _pallas("int8_per_token"),
        "int8_per_channel_pallas": _pallas("int8_per_channel"),
        "int4_per_channel_pallas": _pallas("int4_per_channel"),
        "ternary_mean_pallas": _pallas("ternary_mean"),
        "ternary_max_pallas": _pallas("ternary_max"),
    }
    if name not in factories:
        raise ValueError(f"unknown wire codec {name!r}; options: {sorted(factories)}")
    return factories[name]()


WIRE_CODECS = ("fp32", "bf16", "fp16", "int8_per_token", "int8_per_channel",
               "int4_global", "int4_per_token", "int4_per_channel",
               "ternary_mean", "ternary_max", "ternary_per_token",
               "int4_per_token_pallas", "int8_per_token_pallas",
               "int8_per_channel_pallas", "int4_per_channel_pallas",
               "ternary_mean_pallas", "ternary_max_pallas")
