"""Simulated (quantize->dequantize in fp) boundary codecs, jit-safe.

These reproduce the reference's boundary compression semantics exactly, but as pure
vectorized functions with static shapes instead of in-place fancy-indexed edits:

- token-selective symmetric int4 over the ``ratio`` least-important tokens, with one
  *global* max-abs scale over the whole selected slice
  (``/root/reference/Experiments/Qwen2-0.5B/qwen_layer_wise.py:54-73``,
  ``Experiments/Pythia-70M/pythia_model.py:167-191``);
- per-token affine int8 (``pythia_model.py:57-68`` — implemented with correct
  scale/zero-point math; the committed reference passes ``scale = max-min`` and a
  tensor zero-point into ``torch.quantize_per_tensor`` and crashes, see SURVEY.md
  section 2.1);
- per-channel symmetric 8/4-bit and ternary mean/max codecs
  (``qwen_layer_wise.py:106-152``), vectorized over the channel axis instead of a
  Python loop over 896 channels;
- top-rho importance-mass token selection (``pythia_model.py:95-109``) as a
  cumulative-sum over the sorted distribution instead of a greedy Python loop.

Dynamic token selection under jit: ``hidden[:, idx, :] = q(...)`` becomes a boolean
mask + ``jnp.where`` (static shapes; the quantized values are computed everywhere and
selected where the mask is set — the masked lanes are dead code XLA fuses away).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

CHANNEL_METHODS = ("channel_8", "channel_4", "channel_1_mean", "channel_1_max")


def token_select_mask(importance: jnp.ndarray, ratio, seq_len: int,
                      k=None) -> jnp.ndarray:
    """Boolean mask (S,) marking the ``int(ratio * seq_len)`` least-important tokens.

    Matches ``argsort(importance, descending=False)[:int(ratio*S)]``
    (``qwen_layer_wise.py:57``): ascending stable argsort, take the first k.
    jit-safe version: rank every position by importance (stable, so ties break by
    position exactly like torch's stable sort) and mark ranks < k.

    ``k``: the token count, when the caller has already computed it. Pass
    ``int(ratio * seq_len)`` evaluated in Python float64 whenever ``ratio`` is
    known host-side — the reference truncates the float64 product
    (``qwen_layer_wise.py:57``), and for near-integer products (e.g. 0.3 * 10)
    float64 truncation and the float32 traced fallback below disagree by one
    token. The wire codec (``packing.selective_int4``) computes k the float64
    way, so host-side k keeps simulate-vs-wire parity bit-exact.
    """
    order = jnp.argsort(importance)  # ascending, stable
    rank = jnp.argsort(order)  # rank[i] = position of token i in ascending order
    if k is None:
        if isinstance(ratio, (int, float)):
            k = int(float(ratio) * seq_len)
        else:
            k = jnp.floor(ratio * seq_len).astype(jnp.int32)  # traced fallback
    return rank < jnp.asarray(k, jnp.int32)


def top_rho_mask(distribution: jnp.ndarray, threshold) -> jnp.ndarray:
    """Mask of tokens to QUANTIZE under the "upto ratio" (top-rho) scheme.

    The reference greedily walks the importance distribution in descending order,
    keeping tokens until the kept mass reaches ``threshold`` (= 1 - 0.1*ratio), and
    quantizes every token after that point (``pythia_model.py:95-109``). A token is
    kept iff the exclusive prefix-sum of the descending-sorted distribution at its
    position is still below the threshold; everything else is quantized.
    """
    order = jnp.argsort(-distribution)  # descending, stable (ties by position)
    sorted_vals = distribution[order]
    excl_cumsum = jnp.cumsum(sorted_vals) - sorted_vals
    quantize_sorted = excl_cumsum >= threshold
    # scatter back to original token positions
    mask = jnp.zeros_like(quantize_sorted).at[order].set(quantize_sorted)
    return mask


def _masked_symmetric(hidden: jnp.ndarray, mask: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Symmetric fake-quant of masked token positions with one global scale.

    ``mask``: (S,) over the token axis of ``hidden`` (B, S, D). The scale is the max
    |value| over the *selected slice only* — all batch rows, all channels — exactly
    the reference's ``max(|hidden[:, least_important, :]|)`` (``qwen_layer_wise.py:60``).
    """
    qmax = 2 ** (bits - 1) - 1
    qmin = -(2 ** (bits - 1))
    m = mask[None, :, None]
    max_val = jnp.max(jnp.where(m, jnp.abs(hidden), 0.0))
    max_val = jnp.where(max_val > 0, max_val, 1.0)  # mask empty / all-zero: no-op below
    scaled = jnp.clip(hidden / max_val * qmax, qmin, qmax)
    deq = jnp.round(scaled) / qmax * max_val
    return jnp.where(m, deq, hidden)


def int4_token_select(hidden: jnp.ndarray, importance: jnp.ndarray, ratio,
                      k=None) -> jnp.ndarray:
    """The reference's headline codec: symmetric int4 on the least-important tokens.

    ``k`` (optional): host-computed ``int(ratio * S)`` — see
    :func:`token_select_mask` for why float64 truncation matters."""
    mask = token_select_mask(importance, ratio, hidden.shape[1], k=k)
    return _masked_symmetric(hidden, mask, bits=4)


def simulate_symmetric(hidden: jnp.ndarray, mask: jnp.ndarray, bits: int = 4) -> jnp.ndarray:
    """Generic masked symmetric fake-quant (int2..int8) with global max-abs scale."""
    return _masked_symmetric(hidden, mask, bits)


def per_token_affine_int8(hidden: jnp.ndarray, mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Per-token affine int8: each token's D-vector gets its own (scale, zero_point).

    This is the *documented intent* of ``Pythia70Model.simulate_quantization``
    (``pythia_model.py:57-68``) with correct affine math: scale = (max-min)/255,
    zero_point chosen so min maps to -128; q = clamp(round(x/scale)+zp, -128, 127).
    The committed reference version crashes (SURVEY.md section 2.1).
    """
    mn = jnp.min(hidden, axis=-1, keepdims=True)
    mx = jnp.max(hidden, axis=-1, keepdims=True)
    # reciprocal multiply, matching the wire codec bit-for-bit (packing.py)
    scale = (mx - mn) * jnp.float32(1.0 / 255.0)
    safe_scale = jnp.where(scale > 0, scale, 1.0)
    zp = jnp.round(-128.0 - mn / safe_scale)
    q = jnp.clip(jnp.round(hidden / safe_scale) + zp, -128, 127)
    # constant tokens (mx == mn) are exactly representable: pass through unchanged
    deq = jnp.where(scale > 0, (q - zp) * safe_scale, hidden)
    if mask is None:
        return deq
    return jnp.where(mask[None, :, None], deq, hidden)


def channel_wise_quant(hidden: jnp.ndarray, method: str) -> jnp.ndarray:
    """Per-channel boundary codecs (``qwen_layer_wise.py:106-152``), vectorized.

    The reference loops Python-level over all D channels; here the channel axis is
    just the reduction layout — one fused XLA op. Scales are computed per channel
    over the (batch, seq) slice, exactly like the reference's
    ``hidden_states[:, :, c]`` reductions:

    - ``channel_8`` / ``channel_4``: symmetric max-abs, round to +/-127 / +/-7 (no
      clamp needed: |x| <= max by construction);
    - ``channel_1_mean``: BitNet-style: scale = *signed* mean + 1e-8, round then
      clamp to {-1, 0, 1} (``qwen_layer_wise.py:135-142`` — the signed mean is
      faithfully kept, it is the reference's behavior);
    - ``channel_1_max``: same with max-abs scale.
    """
    if method not in CHANNEL_METHODS:
        raise ValueError(f"unknown channel method {method!r}; options: {CHANNEL_METHODS}")
    if method in ("channel_8", "channel_4"):
        max_levels = 127.0 if method == "channel_8" else 7.0
        cmax = jnp.max(jnp.abs(hidden), axis=(0, 1), keepdims=True)
        safe = jnp.where(cmax > 0, cmax, 1.0)
        q = jnp.round(hidden / safe * max_levels)
        return jnp.where(cmax > 0, q * safe / max_levels, hidden)
    if method == "channel_1_mean":
        scale = jnp.mean(hidden, axis=(0, 1), keepdims=True) + 1e-8
        q = jnp.clip(jnp.round(hidden / scale), -1, 1)
        return q * scale
    # channel_1_max
    cmax = jnp.max(jnp.abs(hidden), axis=(0, 1), keepdims=True)
    safe = jnp.where(cmax > 0, cmax, 1.0)
    q = jnp.clip(jnp.round(hidden / safe), -1, 1)
    return jnp.where(cmax > 0, q * safe, hidden)
