"""Persisted, chip-keyed substitution policy derived from the codec probe.

Round 4 froze the default-substitution set (``PALLAS_DEFAULT_WINS``) from one
chip's probe data — and the probe itself showed how treacherous a frozen
constant is: ``int8_per_token`` read 2.12x in round 3 and 0.79x in round 4
once the interleaved-pair estimator removed phase drift. A different TPU
generation (or a fixed tunnel) would silently inherit a stale policy.

This module closes that loop: every bench run's probe
(``tools/pallas_probe.probe_all``) records the measured
``roundtrip_speedup_vs_jnp`` per codec into a small JSON cache keyed by a
backend/chip fingerprint; ``pallas_variant(..., measured_wins_only=True)``
consults the cache for the CURRENT chip first and only falls back to the
frozen constant when no measurement exists for it. A fresh chip therefore
re-derives its winners on its first bench, and a codec that stops winning
stops being substituted on the next.

Cache location: ``EDGELLM_PROBE_CACHE`` or
``~/.cache/edgellm_tpu/pallas_wins.json``. Writes are atomic (tmp+rename);
corrupt or unreadable caches degrade to the no-data fallback, never an error.
"""
from __future__ import annotations

import json
import math
import os
import tempfile
from typing import Optional


def _cache_path() -> str:
    return os.environ.get(
        "EDGELLM_PROBE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "edgellm_tpu",
                     "pallas_wins.json"))


def fingerprint() -> str:
    """Backend + device kind of the chip the current process would run on —
    the cache key that keeps one machine's measurements from steering
    another's policy (e.g. ``tpu:TPU v5 lite``)."""
    import jax

    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        kind = "unknown"
    return f"{jax.default_backend()}:{kind}"


def base_name(codec_name: str) -> str:
    """Probe result names -> policy keys: the selective family probes as
    ``selective_int4_r<ratio>_<high>`` but is one substitution decision."""
    if codec_name.startswith("selective_int4"):
        return "selective_int4"
    return codec_name


def load_speedups(fp: Optional[str] = None) -> Optional[dict]:
    """``{base codec name: measured roundtrip speedup}`` for this chip, or
    None when the cache holds no data for it (callers fall back to the
    frozen ``PALLAS_DEFAULT_WINS``)."""
    try:
        with open(_cache_path()) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    entry = data.get(fp or fingerprint())
    if not isinstance(entry, dict):
        return None
    speedups = entry.get("speedups")
    if not isinstance(speedups, dict):
        return None
    out = {k: float(v) for k, v in speedups.items()
           if isinstance(v, (int, float)) and math.isfinite(v)}
    return out or None


def record(results, fp: Optional[str] = None) -> Optional[str]:
    """Merge one probe run's codec blocks (``probe_all()["codecs"]``) into
    the cache under this chip's fingerprint; returns the cache path written,
    or None when the results carry no finite speedups (e.g. parity-only
    probes on CPU). Unwritable locations are a no-op, not an error — the
    policy then simply stays on the fallback constant."""
    speedups = {}
    for r in results:
        # prefer the probe's unrounded ratio: WIN_MARGIN is a hysteresis
        # threshold and must never see a 1.045 reading pre-rounded to 1.05
        # (the rounded field stays for display and as back-compat fallback)
        s = r.get("roundtrip_speedup_vs_jnp_raw",
                  r.get("roundtrip_speedup_vs_jnp"))
        if isinstance(s, (int, float)) and math.isfinite(s):
            speedups[base_name(r["codec"])] = float(s)
    if not speedups:
        return None
    path = _cache_path()
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        try:
            with open(path) as f:
                data = json.load(f)
            if not isinstance(data, dict):
                data = {}
        except (OSError, ValueError):
            data = {}
        key = fp or fingerprint()
        entry = data.get(key) if isinstance(data.get(key), dict) else {}
        merged = dict(entry.get("speedups") or {})
        merged.update(speedups)
        data[key] = {"speedups": merged}
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                                   suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


#: substitution requires the measured speedup to clear this margin, not just
#: 1.0: the interleaved-pair median still swings a few percent run to run
#: (the module docstring's r3/r4 flip), and a codec oscillating around
#: break-even must NOT flap into the default path on one 1.02x reading —
#: "earned" means measurably faster, at worst costing a true ~1.04x
#: marginal win (which the next probe can still promote)
WIN_MARGIN = 1.05


def measured_win(codec_name: str, fp: Optional[str] = None) -> Optional[bool]:
    """True/False when this chip has a measurement for the codec (win =
    speedup >= WIN_MARGIN), None when there is no data (caller falls back)."""
    speedups = load_speedups(fp)
    if speedups is None:
        return None
    s = speedups.get(base_name(codec_name))
    if s is None:
        return None
    return s >= WIN_MARGIN
