"""Sweep drivers: (method x split-layer x ratio) perplexity sweeps, restructured for TPU.

The reference recomputes a **full** model forward for every combination — with its
committed Qwen params that is 1 eager + 20 quantized forwards per 32-token stride,
~16 s/chunk on the Colab GPU (``qwen2-0.5B_experiment.ipynb`` cell 12;
``Qwen2-0.5B/main.py:170-178``). Here each chunk runs ONE forward that captures
attention statistics *and* caches the boundary activation at every split layer of
interest; each (method, layer, ratio) combination then costs only a quantize + the
layer suffix [l+1, L), with the ratio axis vmapped into a single batched suffix run.
Identical math (the suffix resumes from the exact pre-quantization hidden state the
reference recomputes), a fraction of the FLOPs.

Accumulation semantics are preserved per experiment:
- token-weighted: ``total += nll * num_loss_tokens; PPL = exp(total / n_tokens)``
  (``Qwen2-0.5B/main.py:166-207``, ``last_row_exp.py:100-143``, ``channel_wise.py:42-49``)
- unweighted mean-of-chunk-means for the Pythia "initial" experiment
  (``initial_exp.py:123-133``)

Checkpoint/resume: the reference pickles partial sums every 1000 chunks but cannot
resume (``main.py:184-192``); here the JSON checkpoint stores the next chunk index
and restart is exact.
"""
from __future__ import annotations

import dataclasses
import functools
import json
import os
import time
from typing import Callable, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..models import run_layers, unembed, nll_from_logits
from ..models.transformer import run_layers_from_ids, nll_tail
from ..models.configs import ModelConfig
from ..codecs import (
    int4_token_select,
    token_select_mask,
    top_rho_mask,
    per_token_affine_int8,
    channel_wise_quant,
)
from ..importance import importance_per_layer, aggregate_upto, maximum_aggregation, regular_importance
from .windowing import sliding_windows

TOKEN_CODECS = ("int4_token_select", "affine_int8_rank", "affine_int8_top_rho")


def is_oom_error(e: BaseException) -> bool:
    """True for XLA device-memory exhaustion (any backend's phrasing).

    Only runtime-launch errors qualify: the message heuristic alone would let
    any exception that merely *mentions* "out of memory" (a wrapped host OOM,
    a quoted log line) trigger a halve-and-retry and mask the real failure.
    ``XlaRuntimeError`` isn't a stable public import path across jaxlib
    versions, so match the class name up the MRO instead of the type.
    """
    if isinstance(e, MemoryError):  # host allocator exhaustion (often bare)
        return True
    names = {c.__name__ for c in type(e).__mro__}
    if not {"XlaRuntimeError", "JaxRuntimeError"} & names:
        return False
    msg = str(e)
    return ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
            or "out of memory" in msg)


def run_with_oom_backoff(run: Callable[[int], object], window_batch: int,
                         min_window_batch: int = 1, on_backoff=None):
    """Call ``run(window_batch)``, halving the batch on RESOURCE_EXHAUSTED
    instead of dying -> (result, effective_window_batch).

    ``run`` must be restartable (the sweep drivers are: each call builds fresh
    accumulators, and with a ``checkpoint_path`` a retried call resumes exactly
    from the last checkpoint, so work done before the OOM is kept)."""
    import gc

    wb = window_batch
    while True:
        msg = None
        try:
            return run(wb), wb
        except Exception as e:  # XlaRuntimeError isn't a stable public type
            if not is_oom_error(e) or wb <= min_window_batch:
                raise
            msg = str(e)
            wb = max(wb // 2, min_window_batch)
        # cleanup OUTSIDE the except block: while the handler is active the
        # interpreter's exception state still references the traceback frames
        # (which pin the failed launch's device buffers), so a collect inside
        # it could not free them
        gc.collect()
        jax.clear_caches()
        if on_backoff:
            on_backoff(wb, msg)


def _apply_token_codec(codec: str, hidden, importance, ratio, k):
    """Quantize ``hidden`` (B, S, D) at the boundary under one token codec.

    ``ratio`` is always a *fraction* here; "initial"-style integer ratios are
    normalized by the driver (the reference multiplies by 0.1 at use sites:
    ``pythia_model.py:95,142``). ``k`` is the host-computed ``int(ratio * S)``
    token count for the rank-based codecs (float64 truncation, matching the
    reference and the wire codecs — see ``token_select_mask``).
    """
    seq_len = hidden.shape[1]
    if codec == "int4_token_select":
        return int4_token_select(hidden, importance, ratio, k=k)
    if codec == "affine_int8_rank":
        mask = token_select_mask(importance, ratio, seq_len, k=k)
        return per_token_affine_int8(hidden, mask)
    if codec == "affine_int8_top_rho":
        mask = top_rho_mask(importance, 1.0 - ratio)
        return per_token_affine_int8(hidden, mask)
    raise ValueError(f"unknown token codec {codec!r}; options: {TOKEN_CODECS}")


@functools.lru_cache(maxsize=None)
def _stats_forward(cfg: ModelConfig, hidden_layers: tuple = None,
                   want_final: bool = False,
                   stats_upto: Optional[int] = None):
    """Jitted prefix pass: ids -> (attention stats, boundary hiddens[, final
    hidden]).

    Specialized to what the sweep consumes (round 4 — the original pass
    captured stats and stacked hiddens for every layer, most never read):

    - attention stats cover layers [0, stats_upto] (default: the deepest
      hidden layer) — no importance method reads past its cut, and
      ``aggregate_till``'s running means are prefix-local, so truncation is
      exact;
    - boundary hiddens are collected ONLY at ``hidden_layers`` (the full
      (L, W, S, D) stack was 1.4 GB of HBM writes per 64-window flagship
      group), returned stacked in sorted-layer order — index via
      ``sorted(set(hidden_layers)).index(layer)``;
    - with ``want_final``, the layers past ``stats_upto`` run WITHOUT stats
      capture and the FINAL hidden is returned; the caller tail-scores it
      with :func:`_base_tail` into the method-independent ratio-0 fp
      baseline, replacing the old separate baseline executable (a second
      full suffix forward per group). The tail length lives in that thin
      scorer, NOT here — so the full-depth stats executable compiles once
      per layer set while only the small unembed tail recompiles per
      distinct scoring-tail length (ADVICE r4). With ``want_final=False``
      those layers never run at all.

    ``hidden_layers=None`` keeps the original full-depth behavior (all
    layers' stats + hiddens; no final hidden).
    """
    from ..models.transformer import embed

    if hidden_layers is None:
        @jax.jit
        def full(params, ids):
            _, aux = run_layers_from_ids(cfg, params, ids, capture_stats=True)
            return aux["stats"], aux["hiddens"], None

        return full

    from ..models.transformer import AttnStats

    layers = tuple(sorted({int(l) for l in hidden_layers}))
    upto = max(stats_upto if stats_upto is not None else 0, layers[-1])

    @jax.jit
    def fn(params, ids):
        h = embed(params, ids)
        cols, lasts, hiddens = [], [], []
        prev = 0
        for cut in layers:
            h, aux = run_layers(cfg, params, h, start=prev, stop=cut + 1,
                                capture_stats=True)
            cols.append(aux["stats"].col_mean)
            lasts.append(aux["stats"].last_row)
            hiddens.append(h)
            prev = cut + 1
        if prev <= upto:
            h, aux = run_layers(cfg, params, h, start=prev, stop=upto + 1,
                                capture_stats=True)
            cols.append(aux["stats"].col_mean)
            lasts.append(aux["stats"].last_row)
            prev = upto + 1
        stats = AttnStats(
            col_mean=jnp.concatenate(cols) if len(cols) > 1 else cols[0],
            last_row=jnp.concatenate(lasts) if len(lasts) > 1 else lasts[0])
        final = None
        if want_final:
            final, _ = run_layers(cfg, params, h, start=prev)
        return stats, jnp.stack(hiddens), final

    return fn


@functools.lru_cache(maxsize=None)
def _base_tail(cfg: ModelConfig, tail: int):
    """Thin per-tail scorer over the stats forward's returned final hidden:
    only this unembed tail recompiles per distinct scoring-tail length, the
    full-depth stats executable stays tail-independent (ADVICE r4)."""
    @jax.jit
    def fn(params, final, targets):
        return nll_tail(cfg, params, final, targets, tail, per_example=True)

    return fn


@functools.lru_cache(maxsize=None)
def _plain_forward(cfg: ModelConfig, hidden_layers: tuple = None):
    """Jitted prefix pass without attention stats (channel sweep); with
    ``hidden_layers`` set, collects only those boundary hiddens (stacked in
    sorted-layer order) and stops at the deepest one."""
    from ..models.transformer import embed

    if hidden_layers is None:
        @jax.jit
        def full(params, ids):
            _, aux = run_layers_from_ids(cfg, params, ids, capture_stats=False)
            return aux["hiddens"]

        return full

    layers = tuple(sorted({int(l) for l in hidden_layers}))

    @jax.jit
    def fn(params, ids):
        h = embed(params, ids)
        hiddens = []
        prev = 0
        for cut in layers:
            h, _ = run_layers(cfg, params, h, start=prev, stop=cut + 1)
            hiddens.append(h)
            prev = cut + 1
        return jnp.stack(hiddens)

    return fn


@functools.lru_cache(maxsize=None)
def _importance_stack(cfg: ModelConfig, methods: tuple):
    """Jitted: attention stats -> (M, L, B, S) importance for all methods at once.

    One device call per chunk group instead of per-method eager jnp dispatches —
    on a remote-executed backend every unjitted op is a round trip, which
    dominated the sweep's non-compute time.
    """

    @jax.jit
    def fn(stats, head_weights):
        return jnp.stack([importance_per_layer(stats, m, head_weights)
                          for m in methods])

    return fn


# Codecs for which ratio == 0 provably quantizes nothing, so the fp-baseline
# column is method-independent and can be computed once per split layer instead
# of once per (method, layer) — the reference recomputes identical forwards
# (``Qwen2-0.5B/main.py:170-178``); the values are unchanged.
DEDUP_ZERO_CODECS = ("int4_token_select", "affine_int8_rank")


@functools.lru_cache(maxsize=None)
def _suffix_sweep(cfg: ModelConfig, layer: int, codec: str, tail: int):
    """Jitted: boundary hiddens at ``layer`` -> (ratio, window) NLL matrix.

    The codec step keeps the reference's batched-over-ratios intent
    (``pythia_model.py:36-54``, one batch row per ratio) as a vmap over
    (ratio, window) — per-window codec scales are preserved (the reference
    quantizes each window independently at batch 1) — but the suffix forward
    and scoring tail then run UNVMAPPED on the flattened (R*W, S, D) batch.
    Numerically identical (layers and tail are ratio-independent; each row
    still scores alone), and measured faster on the v5e (round 5): the
    nested-vmap version carried 5-D [R, W, 1, S, D] activations whose
    non-default layouts forced a ~117 MB physical-no-op copy on each side of
    every attention custom-call and a per-vocab-block logits retile copy in
    the streamed unembed (~0.48 ms per block — as much as the block's matmul
    itself); the flat batch keeps every tensor in default layout. The
    full-vocab unembed runs only on the ``tail`` scoring positions
    (``nll_tail``) — exact, because everything earlier is masked to -100 by
    the windowing recipe.

    boundary_hidden (W, S, D), targets (W, S), importance (W, S), ratios (R,)
    -> (R, W).
    """

    @jax.jit
    def fn(params, boundary_hidden, targets, importance, ratios, ks):
        w, s, d = boundary_hidden.shape
        r = ratios.shape[0]

        def per_ratio(ratio, k):
            def per_window(h_w, imp_w):
                return _apply_token_codec(codec, h_w[None], imp_w, ratio, k)[0]

            return jax.vmap(per_window)(boundary_hidden, importance)

        h = jax.vmap(per_ratio)(ratios, ks).reshape(r * w, s, d)
        out, _ = run_layers(cfg, params, h, start=layer + 1)
        tgt = jnp.broadcast_to(targets[None], (r, w, s)).reshape(r * w, s)
        nll = nll_tail(cfg, params, out, tgt, tail, per_example=True)
        return nll.reshape(r, w)

    return fn


@functools.lru_cache(maxsize=None)
def _suffix_channel(cfg: ModelConfig, layer: int, method: str, tail: int):
    """Jitted: boundary hiddens -> per-window NLL under one per-channel codec.

    Windows are vmapped with the codec INSIDE the per-window function, so each
    window keeps its own channel scales — identical to the reference's batch-1
    sweep (``channel_wise.py:35-49``), W windows per executable."""

    @jax.jit
    def fn(params, boundary_hidden, targets):  # (W, S, D), (W, S) -> (W,)
        h = jax.vmap(lambda h_w: channel_wise_quant(h_w[None], method)[0])(
            boundary_hidden)
        # flat-batch suffix + tail (same 5-D-layout-copy reasoning as
        # _suffix_sweep; identical values — rows score independently)
        out, _ = run_layers(cfg, params, h, start=layer + 1)
        return nll_tail(cfg, params, out, targets, tail, per_example=True)

    return fn


@dataclasses.dataclass
class SweepResult:
    """Accumulated sweep state. ``total_nll`` indexed [method][layer][ratio] (token
    sweeps), [method][layer] (channel sweep), or [layer][ratio] (initial)."""

    axes: dict
    total_nll: np.ndarray
    n_tokens: float
    chunks: int
    weighting: str  # "token_weighted" | "mean_of_means"
    wall_s: float = 0.0

    def ppl(self) -> np.ndarray:
        denom = self.n_tokens if self.weighting == "token_weighted" else max(self.chunks, 1)
        return np.exp(self.total_nll / max(denom, 1e-9))

    def to_json(self) -> dict:
        return {
            "axes": self.axes,
            "total_nll": self.total_nll.tolist(),
            "n_tokens": self.n_tokens,
            "chunks": self.chunks,
            "weighting": self.weighting,
            "wall_s": self.wall_s,
            "ppl": self.ppl().tolist(),
        }

    def table(self) -> str:
        """Human-readable PPL table, the shape of the reference notebook's
        results cell (``qwen2-0.5B_experiment.ipynb`` cell 12: one row per
        (method, split layer), one column per ratio)."""
        ppl = self.ppl()
        lines = []
        if "ratios" in self.axes:
            ratios = self.axes["ratios"]
            layers = self.axes["layers_of_interest"]
            methods = self.axes.get("methods")
            header = ["method", "layer"] if methods else ["layer"]
            cols = header + [f"r={r}" for r in ratios]
            rows = []
            if methods:
                for m, method in enumerate(methods):
                    for l, layer in enumerate(layers):
                        rows.append([method, str(layer)]
                                    + [f"{v:.4g}" for v in ppl[m, l]])
            else:
                for l, layer in enumerate(layers):
                    rows.append([str(layer)] + [f"{v:.4g}" for v in ppl[l]])
        else:  # channel sweep: methods x layers
            cols = ["method"] + [f"layer {l}" for l in self.axes["layers_of_interest"]]
            rows = [[m] + [f"{v:.4g}" for v in ppl[i]]
                    for i, m in enumerate(self.axes["methods"])]
        widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
                  for i, c in enumerate(cols)]
        fmt = lambda vals: "  ".join(v.ljust(w) for v, w in zip(vals, widths))
        lines.append(fmt(cols))
        lines.append(fmt(["-" * w for w in widths]))
        lines.extend(fmt(r) for r in rows)
        lines.append(f"[{self.chunks} chunks, {self.n_tokens:.0f} scored tokens, "
                     f"{self.wall_s:.1f}s, weighting={self.weighting}]")
        return "\n".join(lines)


def _scoring_tail(chunk) -> int:
    """Scoring-tail length of one window: trg_len = num_loss_tokens + 1 (the
    windowing shift correction), clamped to the unembeddable positions."""
    return min(chunk.num_loss_tokens + 1, chunk.input_ids.shape[1] - 1)


def _group_arrays(group):
    """One window group -> (ids (W, S), targets (W, S), counts (W,), tail).
    The group's max tail bounds every member's scoring span, so a single
    static tail keeps one executable per group shape while staying exact."""
    ids = jnp.asarray(np.concatenate([c.input_ids for c in group]))
    targets = jnp.asarray(np.concatenate([c.target_ids for c in group]))
    counts = np.array([c.num_loss_tokens for c in group], np.float64)
    tail = max(c.num_loss_tokens + 1 for c in group)
    return ids, targets, counts, tail


def _iter_window_groups(token_ids, max_length: int, stride: int, *,
                        window_batch: int, start_chunk: int = 0,
                        max_count: Optional[int] = None, tail_of=None):
    """Yield groups of evaluation windows for one batched executable each.

    Only full-length windows are grouped (the short corpus-tail window runs
    singly); ``tail_of`` further splits groups whose scoring-tail lengths
    differ — chunk 0 scores the whole window and batching it with stride-tail
    chunks would force the group's unembed to the full window for every member,
    a W-fold blowup of the logits buffer. ``start_chunk`` skips resumed chunks;
    ``max_count`` caps the total yielded. Shared by all sweep drivers.
    """
    buffer: list = []
    yielded = 0
    for chunk in sliding_windows(token_ids, max_length, stride):
        if chunk.index < start_chunk:
            continue
        if max_count is not None and yielded + len(buffer) >= max_count:
            break
        if chunk.input_ids.shape[1] == max_length and window_batch > 1:
            if buffer and tail_of is not None and tail_of(chunk) != tail_of(buffer[0]):
                yield buffer
                yielded += len(buffer)
                buffer = []
            buffer.append(chunk)
            if len(buffer) == window_batch:
                yield buffer
                yielded += len(buffer)
                buffer = []
        else:
            if buffer:
                yield buffer
                yielded += len(buffer)
                buffer = []
            yield [chunk]
            yielded += 1
    if buffer:
        yield buffer


def _run_pipelined(groups, submit, drain):
    """Drive submit/drain one group apart: ``submit(group)`` enqueues device
    work without host syncs and returns a record; ``drain(record)`` does the
    host-side accumulation. Keeping exactly one record in flight lets each
    group's conversions and checkpointing overlap the next group's device
    compute. Used by every sweep driver."""
    inflight = None
    for group in groups:
        rec = submit(group)
        if inflight is not None:
            drain(inflight)
        inflight = rec
    if inflight is not None:
        drain(inflight)


def _load_checkpoint(path: Optional[str], axes: dict) -> Optional[dict]:
    """Load a resume checkpoint only if it was written by the SAME sweep
    configuration — a stale checkpoint from a different axes layout must not be
    silently resumed (its shape may still match)."""
    if path and os.path.exists(path):
        with open(path) as f:
            state = json.load(f)
        if state.get("axes") == json.loads(json.dumps(axes)):
            return state
        raise ValueError(
            f"checkpoint {path} was written by a different sweep configuration "
            f"({state.get('axes')} != {axes}); delete it or use a fresh output dir")
    return None


def _save_checkpoint_state(path: Optional[str], state: dict):
    """Atomic JSON checkpoint write (tmp + rename), shared by every resumable
    driver (sweeps, split eval, relevance). Multi-host runs write from process
    0 only (all processes hold identical accumulators under SPMD); resume
    expects the checkpoint on storage every process can read."""
    if not path or jax.process_index() != 0:
        return
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
    os.replace(tmp, path)


def fetch_global(x) -> np.ndarray:
    """Host-fetch a device array that may be sharded across PROCESSES (the
    data axis of a multi-host split mesh): single-process arrays go straight
    to numpy; process-spanning arrays are allgathered first (np.asarray on a
    non-addressable jax.Array raises)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        x = multihost_utils.process_allgather(x, tiled=True)
    return np.asarray(x)


class ResumableDriver:
    """The shared resumable-driver scaffold: axes-validated checkpoint load,
    atomic save, cumulative wall-clock across resumes, and the
    ``checkpoint_every`` trigger. Every resumable driver sits on it: split
    eval and relevance directly, the three sweep drivers via
    :func:`_run_accumulator_sweep`.

    ``state`` holds the loaded checkpoint dict (None on a fresh start) for
    driver-specific fields; ``save(extra)`` persists them alongside the
    common ones.
    """

    def __init__(self, checkpoint_path: Optional[str], axes: dict,
                 checkpoint_every: int):
        self.path, self.axes, self.every = checkpoint_path, axes, checkpoint_every
        self.state = _load_checkpoint(checkpoint_path, axes)
        loaded = self.state or {}
        self.prior_wall = loaded.get("wall_s", 0.0)
        self.start_chunk = loaded.get("next_chunk", 0)
        self.chunks = loaded.get("chunks", 0)
        self.next_chunk = self.start_chunk
        self._last_ckpt = self.chunks
        self._t0 = time.monotonic()

    def wall(self) -> float:
        """Cumulative seconds across every resumed run (honest rates)."""
        return self.prior_wall + time.monotonic() - self._t0

    def save(self, extra: dict):
        _save_checkpoint_state(self.path, {
            "next_chunk": self.next_chunk, "axes": self.axes,
            "chunks": self.chunks, "wall_s": self.wall(), **extra})

    def advance(self, group, count: Optional[int] = None) -> bool:
        """Account one drained window group -> True when a checkpoint is due.
        ``count`` overrides the chunk increment (e.g. to exclude batch-pad
        repeat windows, which are not resume chunks)."""
        self.chunks += len(group) if count is None else count
        self.next_chunk = group[-1].index + 1
        if self.chunks - self._last_ckpt >= self.every:
            self._last_ckpt = self.chunks
            return True
        return False

    def remaining(self, max_chunks: Optional[int]) -> Optional[int]:
        return None if max_chunks is None else max_chunks - self.chunks


def _emit(metrics_path: Optional[str], record: dict):
    if not metrics_path or jax.process_index() != 0:
        return
    with open(metrics_path, "a") as f:
        f.write(json.dumps(record) + "\n")


def _run_accumulator_sweep(result: SweepResult, token_ids: np.ndarray, *,
                           max_length: int, stride: int, window_batch: int,
                           submit: Callable, accumulate: Callable,
                           checkpoint_path: Optional[str],
                           checkpoint_every: int,
                           metrics_path: Optional[str],
                           max_chunks: Optional[int],
                           progress: Optional[Callable[[int], None]] = None,
                           emit_tokens: bool = False) -> SweepResult:
    """One implementation of the sweep-driver loop, shared by the three
    array-accumulator drivers (token / initial / channel) on top of
    :class:`ResumableDriver` — exact resume, atomic checkpoints, cumulative
    wall clock, pipelined submit/drain (reference checkpoint intent:
    ``Qwen2-0.5B/main.py:184-192``, previously hand-rolled per driver).

    ``submit(ids, targets, tail) -> pending`` enqueues one window group's
    device work with no host sync; ``accumulate(pending, counts)`` folds the
    drained results into ``result.total_nll``. ``emit_tokens`` adds the
    running token count to metrics records (the token sweep's historical
    schema).
    """
    drv = ResumableDriver(checkpoint_path, result.axes, checkpoint_every)
    if drv.state is not None:
        result.total_nll = np.asarray(drv.state["total_nll"])
        result.n_tokens = drv.state["n_tokens"]
        result.chunks = drv.chunks

    def save():
        drv.save({"total_nll": result.total_nll.tolist(),
                  "n_tokens": result.n_tokens})

    def submit_group(group):
        ids, targets, counts, tail = _group_arrays(group)
        return group, counts, submit(ids, targets, tail)

    def drain_group(rec):
        group, counts, pending = rec
        accumulate(pending, counts)
        result.n_tokens += counts.sum()
        due = drv.advance(group)
        result.chunks = drv.chunks
        if progress:
            progress(group[-1].index)
        if due:
            save()
            record = {"chunk": group[-1].index}
            if emit_tokens:
                record["n_tokens"] = result.n_tokens
            _emit(metrics_path, {**record, "ppl": result.ppl().tolist()})

    _run_pipelined(
        _iter_window_groups(token_ids, max_length, stride,
                            window_batch=window_batch,
                            start_chunk=drv.start_chunk,
                            max_count=drv.remaining(max_chunks),
                            tail_of=_scoring_tail),
        submit_group, drain_group)
    result.wall_s = drv.wall()
    save()
    final = {"final": True, "chunks": result.chunks}
    if emit_tokens:
        final["n_tokens"] = result.n_tokens
    _emit(metrics_path, {**final, "ppl": result.ppl().tolist(),
                         "wall_s": result.wall_s})
    return result


def run_token_sweep(
    cfg: ModelConfig,
    params,
    token_ids: np.ndarray,
    *,
    methods: Sequence[str],
    layers_of_interest: Sequence[int],
    ratios: Sequence[float],
    max_length: int,
    stride: int,
    head_weights: Optional[np.ndarray] = None,
    codec: str = "int4_token_select",
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 1000,
    metrics_path: Optional[str] = None,
    max_chunks: Optional[int] = None,
    progress: Optional[Callable[[int], None]] = None,
    window_batch: int = 1,
) -> SweepResult:
    """The main (method x split-layer x ratio) token-selective sweep.

    Reproduces ``Qwen2-0.5B/main.py:136-207`` and ``last_row_exp.py:72-143``:
    token-weighted NLL, int4 token-selective codec at the split layer, importance
    from the four attention methods. ``ratios`` are fractions (0..1).

    ``window_batch``: process up to W full-length evaluation windows per forward
    (short tail windows run singly). Identical accumulation — each window keeps
    its own codec scales and token weighting — but one batched executable per
    step instead of W small ones, which is what keeps the MXU busy at the
    reference's 512-token window size.
    """
    bad = [l for l in layers_of_interest if not 0 <= int(l) < cfg.num_layers]
    if bad:
        raise ValueError(f"layers_of_interest {bad} out of range for a "
                         f"{cfg.num_layers}-layer model")
    shape = (len(methods), len(layers_of_interest), len(ratios))
    result = SweepResult(
        axes={"methods": list(methods), "layers_of_interest": list(layers_of_interest),
              "ratios": list(ratios)},
        total_nll=np.zeros(shape), n_tokens=0.0, chunks=0, weighting="token_weighted")

    # truncate head weights to the captured stats depth (weighted importance
    # only consumes rows <= the deepest cut)
    n_stats = max(int(l) for l in layers_of_interest) + 1
    hw = None if head_weights is None else jnp.asarray(head_weights)[:n_stats]
    # ratio == 0 is the fp baseline: method-independent for the rank codecs,
    # so it is computed ONCE per group as the tail NLL of the stats forward's
    # own full-depth continuation (no separate baseline executable)
    zero_idx = [i for i, r in enumerate(ratios) if float(r) == 0.0] \
        if codec in DEDUP_ZERO_CODECS else []
    nz_idx = [i for i in range(len(ratios)) if i not in zero_idx]
    nz_ratios = jnp.asarray(np.asarray([ratios[i] for i in nz_idx], np.float32))
    imp_fn = _importance_stack(cfg, tuple(methods))
    layer_key = tuple(int(l) for l in layers_of_interest)
    pos_of = {l: i for i, l in enumerate(sorted(set(layer_key)))}

    def submit(ids, targets, tail):
        """Enqueue all of one group's device work; NO host sync — returns the
        device result handles for a later drain."""
        # k per ratio, truncated in Python float64 exactly like the reference's
        # int(ratio * s) (qwen_layer_wise.py:57) and the wire codecs
        ks = jnp.asarray([int(float(ratios[i]) * ids.shape[1]) for i in nz_idx],
                         jnp.int32)
        stats_fn = _stats_forward(cfg, layer_key, want_final=bool(zero_idx))
        stats, hiddens, final = stats_fn(params, ids)
        base = _base_tail(cfg, tail)(params, final, targets) if zero_idx else None
        # drop the (W, S, D) final-hidden buffer BEFORE the suffix loop: the
        # tail scorer has consumed it, and keeping it alive would add ~59 MB
        # (flagship shape) the preflight's suffix-phase model doesn't budget
        del final
        imp_all = imp_fn(stats, hw)  # (M, L', W, S), one device call
        pending = []  # (m_indices, l, ratio_indices, device_nlls)
        for l, layer in enumerate(layers_of_interest):
            h_l = hiddens[pos_of[int(layer)]]
            if zero_idx:
                # layer-independent: no codec at ratio 0, any cut is a no-op
                pending.append((range(len(methods)), l, zero_idx, base[None]))
            if nz_idx:
                for m in range(len(methods)):
                    nlls = _suffix_sweep(cfg, int(layer), codec, tail)(
                        params, h_l, targets, imp_all[m, layer], nz_ratios, ks)  # (R', W)
                    pending.append(([m], l, nz_idx, nlls))
        return pending

    def accumulate(pending, counts):
        for ms, l, r_idx, nlls in pending:
            contrib = np.asarray(nlls, np.float64) @ counts  # (R',)
            for m in ms:
                result.total_nll[m, l, r_idx] += contrib

    return _run_accumulator_sweep(
        result, token_ids, max_length=max_length, stride=stride,
        window_batch=window_batch, submit=submit, accumulate=accumulate,
        checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
        metrics_path=metrics_path, max_chunks=max_chunks, progress=progress,
        emit_tokens=True)


def run_initial_sweep(
    cfg: ModelConfig,
    params,
    token_ids: np.ndarray,
    *,
    layers_of_interest: Sequence,
    ratios: Sequence[float],
    max_length: int,
    stride: int,
    quant_layer: int = 2,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 1000,
    metrics_path: Optional[str] = None,
    max_chunks: Optional[int] = None,
    window_batch: int = 1,
) -> SweepResult:
    """The Pythia "initial" experiment (``initial_exp.py:74-137``).

    ``layers_of_interest`` may mix layer ints with the magic strings
    ``'aggregate upto 2'``, ``'maximum aggregation'``, ``'upto ratio'`` — each
    selects how the token ordering/distribution is built (``initial_exp.py:27-72``);
    quantization always happens at ``quant_layer`` (=2 in the reference dispatch,
    ``initial_exp.py:117-122``) with the per-token affine int8 codec. ``ratios``
    follow the reference's 0..10 integer convention (fraction = 0.1 * ratio,
    ``pythia_model.py:95,142``). Accumulation is the unweighted mean of per-chunk
    NLL means (``initial_exp.py:123-133``).
    """
    magic = {"aggregate upto 2", "maximum aggregation", "upto ratio"}
    bad = [l for l in layers_of_interest
           if l not in magic and not 0 <= int(l) < cfg.num_layers]
    if bad or not 0 <= quant_layer < cfg.num_layers:
        raise ValueError(f"layer specs {bad or [quant_layer]} out of range for a "
                         f"{cfg.num_layers}-layer model")
    shape = (len(layers_of_interest), len(ratios))
    result = SweepResult(
        axes={"layers_of_interest": [str(l) for l in layers_of_interest],
              "ratios": list(ratios)},
        total_nll=np.zeros(shape), n_tokens=0.0, chunks=0, weighting="mean_of_means")

    fracs = jnp.asarray([0.1 * r for r in ratios], jnp.float32)
    # stats must cover every referenced layer: int specs, the fixed layer-2
    # aggregations, and "upto ratio"'s quant-layer distribution
    n_stats = max([quant_layer, 2] + [int(l) for l in layers_of_interest
                                      if l not in magic]) + 1
    stats_fn = _stats_forward(cfg, (quant_layer,), stats_upto=n_stats - 1)

    def submit(ids, targets, tail):
        ks = jnp.asarray([int(0.1 * r * ids.shape[1]) for r in ratios], jnp.int32)
        stats, hiddens, _ = stats_fn(params, ids)
        reg = regular_importance(stats.col_mean)  # (L', W, S)
        pending = []
        for l, spec in enumerate(layers_of_interest):
            if spec == "aggregate upto 2":
                imp, codec = aggregate_upto(stats.col_mean, 2), "affine_int8_rank"
            elif spec == "maximum aggregation":
                imp, codec = maximum_aggregation(stats.col_mean, 2), "affine_int8_rank"
            elif spec == "upto ratio":
                imp, codec = reg[quant_layer], "affine_int8_top_rho"
            else:
                imp, codec = reg[int(spec)], "affine_int8_rank"
            pending.append((l, _suffix_sweep(cfg, quant_layer, codec, tail)(
                params, hiddens[0], targets, imp, fracs, ks)))  # (R, W)
        return pending

    def accumulate(pending, counts):
        for l, nlls in pending:
            # unweighted mean-of-chunk-means: each window contributes equally
            result.total_nll[l] += np.asarray(nlls, np.float64).sum(axis=1)

    return _run_accumulator_sweep(
        result, token_ids, max_length=max_length, stride=stride,
        window_batch=window_batch, submit=submit, accumulate=accumulate,
        checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
        metrics_path=metrics_path, max_chunks=max_chunks)


def run_channel_sweep(
    cfg: ModelConfig,
    params,
    token_ids: np.ndarray,
    *,
    methods: Sequence[str],
    layers_of_interest: Sequence[int],
    max_length: int,
    stride: int,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 1000,
    metrics_path: Optional[str] = None,
    max_chunks: Optional[int] = None,
    window_batch: int = 1,
) -> SweepResult:
    """Per-channel codec sweep (``channel_wise.py:10-78``): methods x layers,
    token-weighted NLL, no importance scoring. ``window_batch`` groups
    evaluation windows into one executable (per-window channel scales kept)."""
    bad = [l for l in layers_of_interest if not 0 <= int(l) < cfg.num_layers]
    if bad:
        raise ValueError(f"layers_of_interest {bad} out of range for a "
                         f"{cfg.num_layers}-layer model")
    shape = (len(methods), len(layers_of_interest))
    result = SweepResult(
        axes={"methods": list(methods), "layers_of_interest": list(layers_of_interest)},
        total_nll=np.zeros(shape), n_tokens=0.0, chunks=0, weighting="token_weighted")

    fwd = _plain_forward(cfg, tuple(int(l) for l in layers_of_interest))
    pos_of = {l: i for i, l in enumerate(sorted({int(l) for l in layers_of_interest}))}

    def submit(ids, targets, tail):
        hiddens = fwd(params, ids)  # (n_interest, W, S, D)
        return [(m, l, _suffix_channel(cfg, int(layer), method, tail)(
                    params, hiddens[pos_of[int(layer)]], targets))  # (W,)
                for m, method in enumerate(methods)
                for l, layer in enumerate(layers_of_interest)]

    def accumulate(pending, counts):
        for m, l, nlls in pending:
            result.total_nll[m, l] += np.asarray(nlls, np.float64) @ counts

    return _run_accumulator_sweep(
        result, token_ids, max_length=max_length, stride=stride,
        window_batch=window_batch, submit=submit, accumulate=accumulate,
        checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
        metrics_path=metrics_path, max_chunks=max_chunks)
