"""Sliding-window chunking with the exact HF perplexity-recipe semantics.

This is the loop header shared by every reference harness
(``/root/reference/Experiments/Qwen2-0.5B/main.py:151-156``,
``Experiments/Pythia-70M/initial_exp.py:98-103``, ``last_row_exp.py:85-90``):

    for begin_loc in range(0, seq_len, stride):
        end_loc = min(begin_loc + max_length, seq_len)
        trg_len = end_loc - prev_end_loc          # tokens not yet scored
        targets = inputs.clone(); targets[:, :-trg_len] = -100
        ...
        prev_end_loc = end_loc
        if end_loc == seq_len: break

The window/stride/masking details define the PPL metric; they are reproduced here
bit-for-bit (including ``num_loss_tokens = valid - batch_size``, the shift
correction of ``main.py:166-168``). Chunks keep their natural length — the tail
chunk is shorter; XLA compiles one executable per distinct length (two in
practice), which is cheaper than the masking bookkeeping padded stats would need.
"""
from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np


@dataclasses.dataclass(frozen=True)
class Chunk:
    """One evaluation window.

    input_ids / target_ids: (1, T) arrays; target positions already scored by a
    previous window are masked to -100. ``num_loss_tokens`` is the reference's
    token-weighting factor (valid targets minus batch size, accounting for the
    internal 1-shift).
    """

    index: int
    begin: int
    end: int
    input_ids: np.ndarray
    target_ids: np.ndarray
    num_loss_tokens: int


def sliding_windows(token_ids: np.ndarray, max_length: int, stride: int) -> Iterator[Chunk]:
    """Yield evaluation chunks over a 1-D token-id array."""
    token_ids = np.asarray(token_ids).reshape(-1)
    seq_len = token_ids.shape[0]
    if seq_len < 2:
        return
    prev_end_loc = 0
    for index, begin_loc in enumerate(range(0, seq_len, stride)):
        end_loc = min(begin_loc + max_length, seq_len)
        trg_len = end_loc - prev_end_loc
        input_ids = token_ids[begin_loc:end_loc][None, :]
        target_ids = input_ids.copy().astype(np.int64)
        if trg_len < target_ids.shape[1]:
            target_ids[:, :-trg_len] = -100
        num_valid = int((target_ids != -100).sum())
        yield Chunk(
            index=index,
            begin=begin_loc,
            end=end_loc,
            input_ids=input_ids,
            target_ids=target_ids,
            num_loss_tokens=num_valid - target_ids.shape[0],
        )
        prev_end_loc = end_loc
        if end_loc == seq_len:
            break
