"""WikiText sliding-window perplexity harness and sweep drivers.

Reproduces the reference's evaluation semantics exactly (they define the metric):
corpus joined with ``"\\n\\n"``, fixed window advanced by ``stride``, overlap masked
to ``-100``, token-weighted NLL accumulation, ``PPL = exp(total_nll / n_tokens)``
(``/root/reference/Experiments/Qwen2-0.5B/main.py:151-207``) — while restructuring
the compute for TPU: one stats forward per chunk with boundary activations cached at
every split layer, and the (ratio) axis vmapped so each method x layer combination
costs one *suffix* run instead of a full forward.
"""
from .windowing import Chunk, sliding_windows
from .harness import (
    SweepResult,
    run_token_sweep,
    run_initial_sweep,
    run_channel_sweep,
)
from .split_eval import run_split_eval, run_fault_sweep, parse_hop_codec

__all__ = [
    "Chunk",
    "sliding_windows",
    "SweepResult",
    "run_token_sweep",
    "run_initial_sweep",
    "run_channel_sweep",
    "run_split_eval",
    "run_fault_sweep",
    "parse_hop_codec",
]
