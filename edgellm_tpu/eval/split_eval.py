"""Sliding-window perplexity over the REAL split runtime.

Where ``harness.py`` reproduces the reference's *simulated* boundary (in-place
quant-dequant), this driver runs the same metric with the model actually cut
across mesh devices: every chunk's forward crosses each cut as a packed payload
over ``lax.ppermute``. This is the end-to-end path for the BASELINE.json
configs — two-stage Pythia with no quantization (configs[0]), uniform 8-bit
Qwen2 (configs[1]), importance-guided mixed 4/8-bit (configs[2]), and the
3-device multi-hop Qwen2-1.5B chain (configs[4]).

Byte accounting comes from the split runtime's measured payload sizes; the
result records bytes/token per hop alongside the PPL.

Durability matches the simulate sweep drivers (and the reference's
partial-sum checkpointing, ``Qwen2-0.5B/main.py:184-192``): an axes-validated
JSON checkpoint written every ``checkpoint_every`` chunks enables EXACT resume
— identical final PPL and measured byte totals — plus an append-only
``metrics.jsonl`` stream.
"""
from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..models.configs import ModelConfig
from ..models.transformer import nll_from_logits, run_layers_from_ids
from ..importance import importance_per_layer
from ..parallel import SplitConfig, SplitRuntime, make_stage_mesh
from ..codecs.packing import WireCodec, get_wire_codec, selective_int4
from ..codecs.faults import FaultConfig, LinkPolicy, TierController, sum_counters
from ..codecs.fec import FECConfig, HedgeConfig, LinkHealth, LinkHealthConfig
from ..obs.metrics import (record_link_counters, record_link_health,
                           record_probe_decisions, record_recovery_counters,
                           record_wire_bytes)
from ..obs.tracing import span as obs_span
from ..obs.tracing import tracing_enabled
from ..utils.clock import MONOTONIC
from ..serve.decode import _emit_hop_spans
from ..serve.recovery import (DecodeTimeout, RecoveryCounters, StageFailure,
                              StageLostError, Watchdog)
from .harness import (ResumableDriver, _emit, _iter_window_groups,
                      _run_pipelined, fetch_global)


def parse_hop_codec(spec: str, n_seq: int = 1) -> object:
    """Codec spec -> registry name or WireCodec.

    Plain names pass through (``"int4_per_token"``, ``"int8_per_token_pallas"``);
    token-selective specs use ``"selective_int4:<ratio>[:<high>][:<mode>]"``
    (e.g. ``"selective_int4:0.25:bf16"``) or ``"selective_int4_pallas:..."``
    to pin the fused-kernel implementation explicitly.

    With ``n_seq > 1`` (the stage x seq runtime) selective specs resolve to the
    ring-sharded variant (``codecs.ring_codecs.ring_selective_int4``):
    ``mode`` picks ``"global"`` (exact dense selection via an importance
    all_gather — the default) or ``"local"`` (wire-optimal shard-local
    selection, globally agreed scale).
    """
    if not spec.startswith("selective_int4"):
        return spec
    parts = spec.split(":")
    ratio = float(parts[1]) if len(parts) > 1 else 0.25
    high = parts[2] if len(parts) > 2 else "bf16"
    mode = parts[3] if len(parts) > 3 else "global"
    if n_seq > 1:
        if parts[0].endswith("_pallas"):
            # no fused ring variant exists; silently substituting the jnp ring
            # codec would discard the user's explicit kernel pin
            raise ValueError(
                f"{parts[0]!r} has no ring (n_seq > 1) implementation; use "
                f"'selective_int4:...' and let the backend choose")
        from ..codecs.ring_codecs import ring_selective_int4

        return ring_selective_int4(ratio, high, n_seq=n_seq, mode=mode)
    if len(parts) > 3:
        raise ValueError(f"selective mode {mode!r} only applies to the "
                         f"stage x seq runtime (n_seq > 1)")
    if parts[0].endswith("_pallas"):
        from ..codecs.pallas_kernels import SELECTIVE_EXCLUSION

        # the kernel twin was DELETED round 5 on measurement; honoring the
        # pin silently with the jnp codec would misreport what ran
        raise ValueError(f"'selective_int4_pallas' no longer exists: "
                         f"{SELECTIVE_EXCLUSION}")
    return selective_int4(ratio, high)


@functools.lru_cache(maxsize=None)
def _importance_fn(cfg: ModelConfig, method: str):
    @jax.jit
    def fn(params, ids, head_weights):
        _, aux = run_layers_from_ids(cfg, params, ids, capture_stats=True)
        return importance_per_layer(aux["stats"], method, head_weights)  # (L, B, S)

    return fn


def run_split_eval(
    cfg: ModelConfig,
    params,
    token_ids: np.ndarray,
    *,
    cuts: Sequence[int],
    hop_codecs: Sequence,
    max_length: int,
    stride: int,
    importance_method: Optional[str] = None,
    head_weights: Optional[np.ndarray] = None,
    mesh=None,
    max_chunks: Optional[int] = None,
    progress=None,
    time_hops: bool = True,
    window_batch: int = 1,
    n_seq: int = 1,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 1000,
    metrics_path: Optional[str] = None,
    faults: Optional[object] = None,
    link_policy: Optional[object] = None,
    fec: Optional[object] = None,
    hedge: Optional[object] = None,
    link_health: Optional[object] = None,
    deadline_s: Optional[float] = None,
    stage_failure: Optional[object] = None,
    recovery: Optional[dict] = None,
    pipeline: Optional[object] = None,
    _clock=MONOTONIC,
) -> dict:
    """Token-weighted sliding-window PPL with the model split at ``cuts``.

    ``n_seq > 1`` selects the composed stage x seq runtime
    (:class:`~edgellm_tpu.parallel.ring.SplitRingRuntime`): within every
    pipeline stage the sequence is ring-sharded over a "seq" mesh axis and each
    boundary hop moves the local per-token-compressed shard — the long-context
    path, where no device ever holds the full sequence at a cut. Requires
    per-token (batch-invariant) hop codecs; windows whose length is not a
    multiple of ``n_seq`` are right-padded with masked (-100) positions, which
    is exact under causal attention.

    ``hop_codecs`` entries may be names, codec-spec strings, or WireCodec
    instances. Token-selective hops take their importance from
    ``importance_method`` (computed at the hop's cut layer by a stats pass —
    the same scores the simulate harness uses).

    ``window_batch``: run up to W full-length evaluation windows through the
    pipeline as one batch (identical accumulation — per-row NLL weighting, and
    token-selective hops carry per-row importance so every window keeps its own
    ordering and scale). With the mesh's "data" axis populated the batch is
    additionally sharded across it; a final partial group is padded up to the
    axis size with repeated windows whose loss weight is zero (the padding does
    cross the wire and is counted in the pushed-token/byte totals).

    ``faults`` (a :class:`~edgellm_tpu.codecs.faults.FaultConfig` or kwargs
    dict) turns the boundary wire faulty: every hop is sealed with the
    integrity check, corrupted per the seeded rates, and handled per
    ``link_policy`` (:class:`LinkPolicy` or dict). The chunk index is the fault
    step, so a fixed seed corrupts the same hops of the same chunks on every
    run. When ``link_policy.tiers`` names a codec ladder, a host-side
    :class:`TierController` walks it: chunks whose hops report corruption step
    the codecs down a tier (``degrade_after`` consecutive), clean chunks step
    back up (``recover_after``) — the controller observes at drain time, so
    under the two-deep submit pipeline a switch takes effect one group late.
    Per-hop counters, the tier trail, and degraded-chunk totals land in the
    result. Robustness state is per-run: a resumed run restarts counters and
    the tier ladder at tier 0 (the checkpointed PPL partial sums stay exact).

    Self-healing (PR 5): ``fec`` (:class:`~edgellm_tpu.codecs.fec.FECConfig`
    or kwargs dict) adds interleaved XOR parity to every sealed hop so a
    single corrupted chunk per parity group is repaired in band — zero extra
    hops; ``hedge`` (:class:`~edgellm_tpu.codecs.fec.HedgeConfig` or dict)
    sends each attempt over staggered redundant routes and keeps the first
    verified copy (for drop-dominated links, where parity can't help).
    ``link_health`` (:class:`~edgellm_tpu.codecs.fec.LinkHealthConfig` or
    dict) replaces the streak-based TierController with the SLO tracker:
    windowed corruption/repair/retry/hedge-win rates from the per-chunk
    counter deltas, burn-rate-driven degradation AND re-promotion over
    ``link_policy.tiers``, with a full-window re-measure plus ``min_dwell_s``
    of clock hysteresis between switches. All three require an enabled
    ``faults`` config (the link machinery otherwise never enters the graph);
    disabled configs build the exact PR 2/3 graph.

    Survivability (PR 3): ``deadline_s`` arms a host-side monotonic
    :class:`~edgellm_tpu.serve.recovery.Watchdog` that is petted after every
    drained chunk — a stalled eval writes a best-effort resume checkpoint and
    raises a typed :class:`DecodeTimeout` instead of hanging (``_clock`` is
    injectable so tests fire it deterministically). ``stage_failure`` (a
    :class:`StageFailure` or ``{"stage", "at_step"}`` dict; ``at_step`` is a
    chunk index here) marks that stage dark; the harness then re-plans the
    split boundary onto the surviving stages (evenly-spaced cuts, the first
    hop's codec on every new hop), re-places the weights, rebuilds the tier
    ladder for the new hop count, and continues the SAME accumulation —
    partial sums, chunk counters, and the metrics stream carry across the
    failover. ``recovery`` tunes the failover (``{"replan": bool,
    "max_failovers": int}``); post-failover byte totals are accounted per
    plan generation in ``result["recovery"]``. Stage failure needs the plain
    split runtime (``n_seq == 1``) — the stage x seq ring has no failover.
    With all three left at their defaults the harness builds the exact
    pre-recovery graph: the knobs are host-side orchestration only.
    """
    if isinstance(faults, dict):
        faults = FaultConfig(**faults)
    if isinstance(link_policy, dict):
        link_policy = dataclasses.replace(
            LinkPolicy(**link_policy),
            tiers=tuple(link_policy.get("tiers", ())))
    fault_on = faults is not None and faults.enabled
    policy = link_policy if link_policy is not None else LinkPolicy()
    if isinstance(fec, dict):
        fec = FECConfig(**fec)
    if isinstance(hedge, dict):
        hedge = HedgeConfig(**hedge)
    if isinstance(link_health, dict):
        link_health = LinkHealthConfig(**link_health)
    healing_requested = ((fec is not None and fec.enabled)
                         or (hedge is not None and hedge.enabled)
                         or link_health is not None)
    if healing_requested and not fault_on:
        raise ValueError(
            "fec/hedge/link_health require an enabled faults config — the "
            "link machinery only exists in the graph when a fault can fire")
    if isinstance(stage_failure, dict):
        stage_failure = StageFailure(**stage_failure)
    if stage_failure is not None and n_seq > 1:
        raise ValueError(
            "stage_failure needs the plain split runtime: the stage x seq "
            "ring has no failover re-planning (n_seq must be 1)")
    recovery_on = (deadline_s is not None or stage_failure is not None
                   or bool(recovery))
    recovery = dict(recovery or {})
    unknown = set(recovery) - {"replan", "max_failovers"}
    if unknown:
        raise ValueError(f"unknown recovery key(s): {sorted(unknown)}")
    rec_replan = bool(recovery.get("replan", True))
    rec_max_failovers = int(recovery.get("max_failovers", 1))
    if rec_max_failovers < 1:
        raise ValueError("recovery.max_failovers must be >= 1")
    rcounters = RecoveryCounters()
    wd = Watchdog(deadline_s, clock=_clock) if deadline_s is not None else None
    codecs = [parse_hop_codec(c, n_seq) if isinstance(c, str) else c
              for c in hop_codecs]
    split = SplitConfig(cuts=tuple(cuts), hop_codecs=tuple(codecs))
    if n_seq > 1:
        from ..parallel.ring import make_sp_stage_mesh

        if mesh is None:
            mesh = make_sp_stage_mesh(split.n_stages, n_seq)
    elif mesh is None:
        mesh = make_stage_mesh(split.n_stages)

    if (pipeline is not None and getattr(pipeline, "enabled", False)
            and n_seq > 1):
        raise ValueError(
            "micro-batch pipelining composes with the plain split runtime "
            "only; the stage x seq ring runtime already overlaps its hops "
            "with the ring rotation — drop pipeline or set n_seq=1")

    def _make_runtime(tier_codecs):
        if n_seq > 1:
            from ..parallel.ring import SplitRingRuntime

            return SplitRingRuntime(cfg, split.cuts, list(tier_codecs), mesh,
                                    faults=faults, policy=link_policy,
                                    fec=fec, hedge=hedge)
        return SplitRuntime(
            cfg, SplitConfig(cuts=split.cuts, hop_codecs=tuple(tier_codecs)),
            mesh, faults=faults, policy=link_policy, fec=fec, hedge=hedge,
            pipeline=pipeline)

    # tier 0 is the configured codec set; lower tiers swap EVERY hop to one
    # uniform fallback codec (payload shapes change, hence separate runtimes
    # — parameter placement is codec-independent, so ``placed`` is shared)
    ladder = [list(codecs)]
    controller = None
    health = None
    if fault_on and policy.tiers:
        for name in policy.tiers:
            c = get_wire_codec(name)  # fail fast on a bad ladder entry
            if (pipeline is not None and getattr(pipeline, "enabled", False)
                    and not c.batch_invariant and not c.needs_importance):
                raise ValueError(
                    f"degradation-ladder tier '{name}' couples batch rows; "
                    "its wire scales would change under the µ-batch split — "
                    "use batch-invariant fallback tiers or drop pipeline")
            ladder.append([name] * len(codecs))
    if link_health is not None:
        # the SLO tracker supersedes the streak controller: burn-rate-driven
        # degradation AND re-promotion, clock-hysteresis via the injectable
        # eval clock (so tests can fake it)
        health = LinkHealth(len(ladder), link_health, clock=_clock)
    elif fault_on and policy.tiers:
        controller = TierController(len(ladder), policy.degrade_after,
                                    policy.recover_after)
    runtimes = {0: _make_runtime(ladder[0])}
    rt = runtimes[0]
    placed = rt.place_params(params)
    needs_imp = [c.needs_importance for c in rt.codecs]
    if any(needs_imp) and importance_method is None:
        raise ValueError("token-selective hop codecs require importance_method")
    # only pay the stats forward when some hop actually consumes importance;
    # under the stage x seq runtime the stats come from the ring rotation
    # itself (importance_sp) — no device ever holds the full sequence
    if any(needs_imp) and importance_method is not None:
        if n_seq > 1:
            from ..parallel.ring import importance_sp

            def imp_fn(params_, ids_, hw_):
                return importance_sp(cfg, params_, ids_, mesh,
                                     importance_method, head_weights=hw_)
        else:
            imp_fn = _importance_fn(cfg, importance_method)
    else:
        imp_fn = None
    hw = None if head_weights is None else jnp.asarray(head_weights)
    n_data = dict(mesh.shape).get("data", 1)
    if window_batch % n_data:
        raise ValueError(f"window_batch {window_batch} must be a multiple of the "
                         f"mesh data axis size {n_data}")
    if getattr(rt, "pipelined", False):
        # fail before the first chunk, not inside the first traced forward
        rt.pipeline.validate_batch(window_batch, "window_batch")
    # a partial tail group pads up to the data axis AND the µ-batch grid
    # (n_data == 1 whenever pipelined: the runtime enforces a stage-only mesh)
    group_pad = n_data * (rt.pipeline.num_microbatches
                          if getattr(rt, "pipelined", False) else 1)

    # resume axes: the USER-LEVEL split spec (requested codec specs, not the
    # runtime's possibly Pallas-substituted names, so a checkpoint written on a
    # CPU host resumes on TPU and vice versa)
    axes = {
        "model": {"family": cfg.family, "num_layers": cfg.num_layers,
                  "hidden_size": cfg.hidden_size, "num_heads": cfg.num_heads,
                  "vocab_size": cfg.vocab_size},
        "cuts": [int(c) for c in cuts],
        "hop_codecs": [c if isinstance(c, str) else c.name for c in hop_codecs],
        "max_length": int(max_length), "stride": int(stride),
        "importance_method": importance_method,
        "window_batch": int(window_batch), "n_seq": int(n_seq),
        "mesh": {k: int(v) for k, v in dict(mesh.shape).items()},
    }
    if pipeline is not None and getattr(pipeline, "enabled", False):
        # the µ-batch count changes per-chunk wire traffic and fault-counter
        # shapes — a plan axis, so resume refuses a mismatched schedule.
        # Only written when pipelining is ON (axes compare by strict dict
        # equality, so an unconditional key would orphan pre-pipeline
        # checkpoints)
        axes["num_microbatches"] = int(pipeline.num_microbatches)
    if fault_on:
        # a checkpoint written under one fault regime must not silently resume
        # under another (JSON round-trips lists, so tuples are listified here)
        axes["faults"] = dataclasses.asdict(faults)
        axes["link_policy"] = {**dataclasses.asdict(policy),
                               "tiers": list(policy.tiers)}
        if fec is not None:
            axes["fec"] = dataclasses.asdict(fec)
        if hedge is not None:
            axes["hedge"] = dataclasses.asdict(hedge)
        if link_health is not None:
            axes["link_health"] = dataclasses.asdict(link_health)
    if stage_failure is not None:
        axes["stage_failure"] = dataclasses.asdict(stage_failure)
    rd = ResumableDriver(checkpoint_path, axes, checkpoint_every)
    total_nll, n_tokens = 0.0, 0.0
    fwd_tokens = 0  # every token pushed through the pipeline (incl. overlap/pad)
    real_fwd_tokens = 0  # same, minus batch-pad windows and seq-pad positions
    hop_bytes_total = [0] * len(rt.codecs)  # measured per chunk, tail included
    if rd.state is not None:
        total_nll, n_tokens = rd.state["total_nll"], rd.state["n_tokens"]
        fwd_tokens = rd.state["fwd_tokens"]
        real_fwd_tokens = rd.state["real_fwd_tokens"]
        hop_bytes_total = list(rd.state["hop_bytes_total"])

    def save_checkpoint():
        with obs_span("eval.checkpoint_write"):
            rd.save({"total_nll": total_nll, "n_tokens": n_tokens,
                     "fwd_tokens": fwd_tokens,
                     "real_fwd_tokens": real_fwd_tokens,
                     "hop_bytes_total": hop_bytes_total})

    bytes_cache: dict = {}
    degraded_chunks = 0  # chunks that ran below tier 0
    tier_log: list = []  # (chunk_index, tier) at every controller switch
    gen = 0  # plan generation: bumped on every failover re-plan
    # gen 0 shares the checkpointed hop_bytes_total list; post-failover plans
    # have a different hop count, so their bytes accumulate per generation
    gen_bytes = {0: hop_bytes_total}
    sf_pending = stage_failure is not None

    def _eval_failover(lost: int):
        """Re-plan the boundary onto the survivors and swap every per-tier
        runtime; the accumulated partial sums carry over untouched (the PPL
        metric does not depend on where the boundary sits)."""
        nonlocal mesh, split, placed, gen, ladder
        if not rec_replan or rcounters.failovers >= rec_max_failovers:
            raise  # the active StageLostError stays fatal
        rcounters.failovers += 1
        from jax.sharding import Mesh

        with obs_span("eval.failover", lost_stage=lost):
            survivors = np.delete(np.asarray(mesh.devices), lost, axis=0)
            mesh = Mesh(survivors, ("stage", "data", "model"))
            split = split.replan(cfg.num_layers, survivors.shape[0])
            rcounters.replans += 1
            ladder = [list(split.hop_codecs)]
            if controller is not None or health is not None:
                for name in policy.tiers:
                    ladder.append([name] * len(split.hop_codecs))
            runtimes.clear()
            runtimes[0] = _make_runtime(ladder[0])
            placed = runtimes[0].place_params(params)
            gen += 1
            gen_bytes[gen] = [0] * len(split.hop_codecs)

    def submit_group(group):
        nonlocal sf_pending
        n_real = len(group)
        s_unpadded = group[0].input_ids.shape[1]
        counts = [c.num_loss_tokens for c in group]
        # pad a partial group up to the data-axis size (and, when pipelined,
        # the µ-batch grid) with repeated windows; their loss weight is zero
        while len(group) % group_pad:
            group = group + [group[-1]]
            counts = counts + [0]
        ids = np.concatenate([c.input_ids for c in group])
        targets = np.concatenate([c.target_ids for c in group])
        if n_seq > 1 and ids.shape[1] % n_seq:
            # right-pad to a seq-shardable length; padded positions are masked
            # (-100) and, under causal attention, invisible to scored ones
            pad = n_seq - ids.shape[1] % n_seq
            ids = np.pad(ids, ((0, 0), (0, pad)))
            targets = np.pad(targets, ((0, 0), (0, pad)), constant_values=-100)
        ids, targets = jnp.asarray(ids), jnp.asarray(targets)
        if sf_pending and group[0].index >= stage_failure.at_step:
            sf_pending = False
            for r in runtimes.values():
                r.mark_stage_lost(stage_failure.stage)
        if health is not None:
            tier = health.tier
        else:
            tier = controller.tier if controller is not None else 0
        # the chunk index drives the fault stream: same seed => same chunks
        # corrupted, run after run (ignored when the link is off)
        fstep = group[0].index

        def _forward():
            if tier not in runtimes:  # built on first demand, cached thereafter
                runtimes[tier] = _make_runtime(ladder[tier])
            art = runtimes[tier]
            needs_t = [c.needs_importance for c in art.codecs]
            if imp_fn is not None and any(needs_t):
                imp = imp_fn(params, ids, hw)  # (L, W, S)
                hop_imp = [(imp[cut] if len(group) > 1 else imp[cut, 0])
                           if need else None
                           for cut, need in zip(split.cuts, needs_t)]
                logits = art.forward(placed, ids, hop_importance=hop_imp,
                                     fault_step=fstep)
            else:
                logits = art.forward(placed, ids, fault_step=fstep)
            return art, logits

        try:
            with obs_span("eval.submit_group", chunk=group[0].index,
                          tier=tier):
                art, logits = _forward()
        except StageLostError as e:
            _eval_failover(e.stage)
            art, logits = _forward()  # same chunk, re-planned boundary
        # this chunk's (still on-device) counters, for the tier controller
        chunk_counters = art._counter_accum[-1] if fault_on else None
        nlls = nll_from_logits(logits, targets, per_example=True)
        return (group, n_real, s_unpadded, counts, ids.shape, nlls, tier,
                chunk_counters, art, gen)

    def drain_group(rec):
        with obs_span("eval.drain_group", chunk=rec[0][-1].index):
            _drain_impl(rec)

    def _drain_impl(rec):
        nonlocal total_nll, n_tokens, fwd_tokens, real_fwd_tokens
        nonlocal degraded_chunks
        (group, n_real, s_unpadded, counts, (w, s_chunk), nlls, tier,
         chunk_counters, art, g) = rec
        # the per-example NLLs ride the mesh's data axis, which is the one
        # axis allowed to span processes in a multi-host run
        total_nll += float(fetch_global(nlls).astype(np.float64)
                           @ np.asarray(counts, np.float64))
        n_tokens += sum(counts)
        fwd_tokens += w * s_chunk
        real_fwd_tokens += n_real * s_unpadded
        key = (g, tier, w, s_chunk)
        if key not in bytes_cache:  # payloads are shape-determined
            bytes_cache[key] = art.hop_bytes(w, s_chunk)
        for i, b in enumerate(bytes_cache[key]):
            gen_bytes[g][i] += b
        if tier:
            degraded_chunks += 1
        if health is not None:
            prev = health.tier
            if health.observe(chunk_counters) != prev:
                tier_log.append((group[-1].index, health.tier))
        elif controller is not None:
            corrupted = any(
                int(np.asarray(chunk_counters[k]).sum())
                for k in ("detected", "budget_dropped"))
            prev = controller.tier
            if controller.observe(corrupted) != prev:
                tier_log.append((group[-1].index, controller.tier))
        if progress:
            progress(group[-1].index)
        if rd.advance(group, count=n_real):
            save_checkpoint()
            rec_out = {
                "chunk": group[-1].index, "chunks": rd.chunks,
                "n_tokens": n_tokens,
                "ppl": float(np.exp(total_nll / max(n_tokens, 1e-9))),
                "hop_bytes_total": hop_bytes_total}
            if fault_on:
                rec_out["tier"] = tier
            if health is not None:
                rec_out["burn_rate"] = health.burn_rate
            _emit(metrics_path, rec_out)
        if wd is not None:
            # pet-the-dog once per drained chunk; a stall past the deadline
            # writes a best-effort resume checkpoint and raises typed
            try:
                wd.check(save_checkpoint, what="eval chunk")
            except DecodeTimeout:
                rcounters.watchdog_fires += 1
                raise

    _run_pipelined(
        _iter_window_groups(token_ids, max_length, stride,
                            window_batch=window_batch,
                            start_chunk=rd.start_chunk,
                            max_count=rd.remaining(max_chunks)),
        submit_group, drain_group)
    wall = rd.wall()  # cumulative across resumes
    save_checkpoint()

    seq = min(max_length, len(np.asarray(token_ids).reshape(-1)))
    result = {
        "ppl": float(np.exp(total_nll / max(n_tokens, 1e-9))),
        "total_nll": total_nll,
        "n_tokens": n_tokens,
        "chunks": rd.chunks,
        "wall_s": wall,
        "tokens_per_s": fwd_tokens / max(wall, 1e-9),
        "scored_tokens_per_s": n_tokens / max(wall, 1e-9),
        "cuts": list(split.cuts),
        "hop_codecs": [c.name for c in rt.codecs],
        # analytic per-token rate at the steady window size, plus the ACTUAL
        # byte totals accumulated chunk by chunk (short tail windows and
        # selective codecs' length-dependent splits included)
        "bytes_per_token_per_hop": rt.bytes_per_token(seq),
        "measured_hop_bytes_total": hop_bytes_total,
        "measured_bytes_per_fwd_token_per_hop": [
            b / max(fwd_tokens, 1) for b in hop_bytes_total],
        # fwd_tokens counts every pipeline-pushed token (batch-pad windows and
        # seq-pad positions included — they DO cross the wire); these separate
        # wire traffic from useful throughput for small corpora / big batches
        "real_fwd_tokens": real_fwd_tokens,
        "pad_fraction": 1.0 - real_fwd_tokens / max(fwd_tokens, 1),
        "real_tokens_per_s": real_fwd_tokens / max(wall, 1e-9),
        "mesh": dict(mesh.shape),
    }
    if fault_on:
        agg = None  # per-hop counters summed over every tier's runtime
        for r in runtimes.values():
            c = r.link_counters()
            if c is None:
                continue
            if agg is None:
                agg = {k: v.copy() for k, v in c.items()}
            else:
                for k in agg:
                    agg[k] += c[k]
        result["faults"] = dataclasses.asdict(faults)
        result["link_policy"] = {**dataclasses.asdict(policy),
                                 "tiers": list(policy.tiers)}
        result["link_counters"] = {k: [int(x) for x in v]
                                   for k, v in (agg or {}).items()}
        result["tier_ladder"] = [[c if isinstance(c, str) else c.name
                                  for c in t] for t in ladder]
        result["tier_switches"] = [list(t) for t in tier_log]
        result["final_tier"] = (health.tier if health is not None
                                else controller.tier
                                if controller is not None else 0)
        result["degraded_chunks"] = degraded_chunks
        if fec is not None:
            result["fec"] = dataclasses.asdict(fec)
        if hedge is not None:
            result["hedge"] = dataclasses.asdict(hedge)
        if health is not None:
            result["link_health"] = health.summary()
    if recovery_on:
        rec_block = {
            "deadline_s": deadline_s,
            "stage_failure": (dataclasses.asdict(stage_failure)
                              if stage_failure is not None else None),
            "counters": rcounters.as_dict(),
            "plan_generations": gen + 1,
        }
        if rcounters.failovers:
            rec_block["replanned_cuts"] = list(split.cuts)
            rec_block["failover_hop_codecs"] = [c.name
                                               for c in runtimes[0].codecs]
            rec_block["failover_hop_bytes_total"] = {
                str(g): list(b) for g, b in gen_bytes.items() if g > 0}
            rec_block["failover_mesh"] = dict(mesh.shape)
        result["recovery"] = rec_block
    if getattr(rt, "pipelined", False):
        result["pipeline"] = rt.pipeline_summary()
    if time_hops and rd.chunks:
        t_seq = seq if n_seq <= 1 else seq + (-seq) % n_seq
        # after a failover, time the boundary that actually finished the run
        timed_rt = runtimes[0] if rcounters.failovers else rt
        with obs_span("eval.time_hops", seq=t_seq):
            result["per_hop_ms"] = timed_rt.time_hops(1, t_seq)
        # the ring runtime is a whole-window forward — no per-token decode
        # surface, so nothing to time at the (B, 1, D) shape
        if hasattr(timed_rt, "time_decode_hops"):
            with obs_span("eval.time_decode_hops"):
                result["per_decode_hop_ms"] = timed_rt.time_decode_hops(1)
        # the flat lists above are positional; label each entry with the
        # boundary it measures so multi-hop configs (split4 multihop) can
        # attribute WHICH cut is slow without cross-referencing the config
        timed_cuts = list(timed_rt.split.cuts)
        result["per_hop_timing"] = [
            {"hop": s, "cut_layer": int(timed_cuts[s]),
             "codec": timed_rt.codecs[s].name,
             "forward_ms": result["per_hop_ms"][s],
             **({"decode_ms": result["per_decode_hop_ms"][s]}
                if "per_decode_hop_ms" in result else {})}
            for s in range(len(timed_cuts))]
    # mirror this sweep's totals into the global registry (no-ops when
    # observability is off): wire bytes, fault/health/recovery counters,
    # and the per-hop fused-probe decisions (why a hop did/didn't fuse)
    record_wire_bytes(hop_bytes_total, kind="eval_forward")
    final_rt = runtimes[0] if recovery_on and rcounters.failovers else rt
    if hasattr(final_rt, "wire_summary"):
        record_probe_decisions(final_rt.wire_summary(1, seq))
    if fault_on:
        record_link_counters(result["link_counters"])
        if health is not None:
            record_link_health(result["link_health"])
    if recovery_on:
        record_recovery_counters(rcounters)
    if tracing_enabled() and hasattr(final_rt, "hop_attribution"):
        # one attribution span per boundary cut for the whole sweep: cut
        # layer, codec, total wire bytes moved, and the worst ladder outcome
        _emit_hop_spans(final_rt, result.get("link_counters"),
                        list(hop_bytes_total),
                        link_tier=getattr(health, "tier", None),
                        chunks=int(rd.chunks))
    final_rec = {"final": True, "chunks": rd.chunks, "n_tokens": n_tokens,
                 "ppl": result["ppl"], "wall_s": wall,
                 "hop_bytes_total": hop_bytes_total,
                 "pad_fraction": result["pad_fraction"]}
    if fault_on:
        final_rec["link_counters"] = result["link_counters"]
        final_rec["degraded_chunks"] = degraded_chunks
        if health is not None:
            final_rec["burn_rate"] = health.burn_rate
    if recovery_on:
        final_rec["failovers"] = rcounters.failovers
    _emit(metrics_path, final_rec)
    return result


def run_fault_sweep(
    cfg: ModelConfig,
    params,
    token_ids: np.ndarray,
    *,
    rates: Sequence[float],
    knob: str = "drop_rate",
    seed: int = 0,
    byte_budget: Optional[int] = None,
    link_policy: Optional[object] = None,
    **eval_kwargs,
) -> list:
    """PPL / throughput / counter curve as a function of fault rate.

    Runs :func:`run_split_eval` once per entry of ``rates``, setting ``knob``
    (``"drop_rate"``, ``"bitflip_rate"``, or ``"scale_corrupt_rate"``) on a
    fresh :class:`FaultConfig` each time. Rate 0 with no ``byte_budget`` runs
    the plain fault-free graph — the sweep's exact baseline point. Each result
    dict gains ``fault_knob`` / ``fault_rate``; remaining kwargs pass through
    (cuts, hop_codecs, max_length, stride, ...). Healing kwargs
    (``fec``/``hedge``/``link_health``) are withheld from fault-free points —
    the clean graph has no link to heal, so the baseline stays exact.
    """
    if knob not in ("drop_rate", "bitflip_rate", "scale_corrupt_rate"):
        raise ValueError(f"unknown fault knob {knob!r}")
    out = []
    for r in rates:
        fc = FaultConfig(**{knob: float(r)}, byte_budget=byte_budget,
                         seed=seed)
        kw = eval_kwargs
        if not fc.enabled:
            kw = {k: v for k, v in eval_kwargs.items()
                  if k not in ("fec", "hedge", "link_health")}
        res = run_split_eval(cfg, params, token_ids,
                             faults=fc if fc.enabled else None,
                             link_policy=link_policy, **kw)
        res["fault_knob"] = knob
        res["fault_rate"] = float(r)
        out.append(res)
    return out


def run_kv_tier_eval(
    cfg: ModelConfig,
    params,
    token_ids: np.ndarray,
    *,
    kv_codec: str = "fp",
    max_length: int,
    stride: int,
    page_size: int = 16,
    window_batch: int = 4,
    max_chunks: Optional[int] = None,
    compute_dtype=None,
    metrics_path: Optional[str] = None,
    progress=None,
) -> dict:
    """Token-weighted sliding-window PPL with the KV cache held AT REST in
    one ``kv_codec`` tier (models.paged_kv.KV_PAGE_CODECS).

    The boundary sweep measures what wire compression costs; this measures
    what PAGE compression costs, with the same window/stride/masking recipe
    and the same token weighting, so the two curves are directly comparable.
    Every window is teacher-force decoded through a paged pool one position
    at a time — the exact serving data path (quantize-on-append, in-kernel
    dequant attention), not a whole-window forward, so the PPL delta vs the
    ``"fp"`` tier is the delta a served stream actually experiences. One
    executable per (window_batch, window_length) group shape; full-length
    groups all share one, the short corpus tail gets its own.
    """
    from ..models.paged_kv import resolve_kv_codec as _resolve_tier
    from ..models.paged_kv import (kv_page_bytes, paged_decode_step,
                                   paged_decode_step_quant)

    codec = _resolve_tier(kv_codec)
    quant = codec.quantized
    L, KV, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
    fn_cache: dict = {}

    def _make_fn(w, t, pps, num_pages):
        def fn(p, pt, ids, targets):
            if quant:
                hdc = codec.code_lanes(hd)
                pools = (jnp.zeros((L, num_pages, page_size, KV, hdc),
                                   codec.code_dtype),
                         jnp.zeros((L, num_pages, page_size, KV, hdc),
                                   codec.code_dtype),
                         jnp.zeros((L, num_pages, page_size, KV),
                                   jnp.float32),
                         jnp.zeros((L, num_pages, page_size, KV),
                                   jnp.float32))
            else:
                pools = (jnp.zeros((L, num_pages, page_size, KV, hd),
                                   jnp.float32),
                         jnp.zeros((L, num_pages, page_size, KV, hd),
                                   jnp.float32))

            def body(pools_c, xs):
                tok, tgt, step = xs
                lengths = jnp.full((w,), step, jnp.int32)
                if quant:
                    logits, *pools2 = paged_decode_step_quant(
                        cfg, p, *pools_c, pt, lengths, tok,
                        kv_codec=codec.name, compute_dtype=compute_dtype)
                else:
                    logits, *pools2 = paged_decode_step(
                        cfg, p, *pools_c, pt, lengths, tok,
                        compute_dtype=compute_dtype)
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
                valid = tgt != -100
                safe = jnp.where(valid, tgt, 0)
                nll = -jnp.take_along_axis(logp, safe[:, None], 1)[:, 0]
                return tuple(pools2), (jnp.where(valid, nll, 0.0), valid)

            # feed positions 0..t-2; the step-s logits score target s+1 —
            # the same shift nll_from_logits applies to whole-window logits
            xs = (ids[:, :-1].T, targets[:, 1:].T, jnp.arange(t - 1))
            _, (nlls, valids) = jax.lax.scan(body, pools, xs)
            return nlls.sum(0), valids.sum(0).astype(jnp.float32)
        return jax.jit(fn)

    total_nll, n_tokens, chunks = 0.0, 0.0, 0
    t0 = time.perf_counter()
    for group in _iter_window_groups(token_ids, max_length, stride,
                                     window_batch=window_batch,
                                     max_count=max_chunks):
        ids = np.concatenate([c.input_ids for c in group])       # (W, T)
        targets = np.concatenate([c.target_ids for c in group])
        counts = np.array([c.num_loss_tokens for c in group], np.float64)
        w, t = ids.shape
        pps = -(-t // page_size)
        num_pages = 1 + w * pps                  # page 0 stays the trash page
        key = (w, t)
        if key not in fn_cache:
            fn_cache[key] = _make_fn(w, t, pps, num_pages)
        pt = jnp.asarray(np.arange(1, num_pages, dtype=np.int32)
                         .reshape(w, pps))
        nll_sum, n_valid = fn_cache[key](params, pt, jnp.asarray(ids),
                                         jnp.asarray(targets))
        per_window = (np.asarray(nll_sum, np.float64)
                      / np.maximum(np.asarray(n_valid, np.float64), 1.0))
        total_nll += float(per_window @ counts)
        n_tokens += float(counts.sum())
        chunks += len(group)
        if progress:
            progress(group[-1].index)
    wall = time.perf_counter() - t0
    result = {
        "kv_codec": codec.name,
        "ppl": float(np.exp(total_nll / max(n_tokens, 1e-9))),
        "total_nll": total_nll,
        "n_tokens": n_tokens,
        "chunks": chunks,
        "wall_s": wall,
        "page_size": page_size,
        "window_batch": window_batch,
        # bytes one page costs at this tier (all layers, K+V, codes+scales) —
        # the capacity story: fp_bytes / tier_bytes pages fit per fp page
        "kv_page_bytes": kv_page_bytes(cfg, page_size, kv_codec=codec.name),
        "kv_page_bytes_fp": kv_page_bytes(cfg, page_size),
    }
    _emit(metrics_path, {"final": True, **{k: result[k] for k in
                         ("kv_codec", "ppl", "n_tokens", "chunks", "wall_s",
                          "kv_page_bytes")}})
    return result


def run_kv_tier_sweep(
    cfg: ModelConfig,
    params,
    token_ids: np.ndarray,
    *,
    tiers: Sequence[str] = ("fp", "int8_per_channel", "int4_per_channel"),
    **eval_kwargs,
) -> list:
    """PPL / page-bytes curve as a function of KV-at-rest tier.

    Runs :func:`run_kv_tier_eval` once per entry of ``tiers`` — the KV twin
    of :func:`run_fault_sweep`'s rate sweep, with the ``"fp"`` entry as the
    exact baseline point (plain fp pages, the pre-quantization data path).
    Each result gains ``ppl_delta_vs_fp`` when the sweep includes ``"fp"``.
    """
    out = [run_kv_tier_eval(cfg, params, token_ids, kv_codec=t, **eval_kwargs)
           for t in tiers]
    base = next((r["ppl"] for r in out if r["kv_codec"] == "fp"), None)
    if base is not None:
        for r in out:
            r["ppl_delta_vs_fp"] = (r["ppl"] - base) / base
    return out
