"""Cross-layer importance-distribution analysis (the reference's
``Notebooks/distributions_distance_across_layers.ipynb``)."""
from .distances import (
    kl_divergence,
    jensen_shannon_divergence,
    layer_importance_distributions,
    pairwise_layer_distances,
    bucket_lengths,
    save_heatmap,
)

__all__ = [
    "kl_divergence",
    "jensen_shannon_divergence",
    "layer_importance_distributions",
    "pairwise_layer_distances",
    "bucket_lengths",
    "save_heatmap",
]
