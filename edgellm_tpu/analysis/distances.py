"""Jensen-Shannon similarity of per-layer token-importance distributions.

Reproduces the analysis that exists only in the reference's
``distributions_distance_across_layers.ipynb`` (cells 10-18): for each corpus
sample, compute every layer's regular-importance distribution (head-mean
column-mean of the attention map — a probability distribution over positions),
then average pairwise Jensen-Shannon divergences between layers over samples.
The resulting upper-triangular LxL matrix (e.g. Pythia layers 0<->1 = 0.0516,
0<->4 = 0.3946 — BASELINE.md) quantifies how transferable an importance ordering
computed at one layer is to another split point.

Formulas follow the notebook exactly: base-2 KL with the ``p != 0`` guard
(cell 12) and JS as the symmetrized average against the mixture (cell 13 — the
notebook's "distance" is the divergence, not its square root; kept as-is).
"""
from __future__ import annotations

import functools
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..models.configs import ModelConfig
from ..models.transformer import run_layers_from_ids
from ..importance import regular_importance


def kl_divergence(p: np.ndarray, q: np.ndarray) -> float:
    """Base-2 KL divergence with zero-p guard (notebook cell 12)."""
    p, q = np.asarray(p, np.float64), np.asarray(q, np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = np.where(p != 0, p * np.log2(p / q), 0.0)
    return float(np.sum(terms))


def jensen_shannon_divergence(p: np.ndarray, q: np.ndarray) -> float:
    """JS divergence against the 50/50 mixture (notebook cell 13)."""
    m = 0.5 * (np.asarray(p, np.float64) + np.asarray(q, np.float64))
    return 0.5 * (kl_divergence(p, m) + kl_divergence(q, m))


@functools.lru_cache(maxsize=None)
def _per_layer_importance(cfg: ModelConfig):
    @jax.jit
    def fn(params, ids):
        _, aux = run_layers_from_ids(cfg, params, ids, capture_stats=True)
        return regular_importance(aux["stats"].col_mean)[:, 0]  # (L, S)

    return fn


def layer_importance_distributions(cfg: ModelConfig, params,
                                   samples: Sequence[np.ndarray]) -> list:
    """Per-sample regular-importance distributions: list over L layers of lists
    over samples of (S_i,) arrays (the notebook's ``all_distributions``).

    Samples run at their native lengths, like the notebook's per-line forwards —
    each DISTINCT length compiles the stats forward once. For large ragged
    corpora, pre-bucket or clip samples to a few fixed lengths to bound
    compilation time.
    """
    fn = _per_layer_importance(cfg)
    out = [[] for _ in range(cfg.num_layers)]
    for ids in samples:
        ids = np.asarray(ids).reshape(1, -1)
        imp = np.asarray(fn(params, jnp.asarray(ids)))
        for layer in range(cfg.num_layers):
            out[layer].append(imp[layer])
    return out


def pairwise_layer_distances(distributions: list) -> np.ndarray:
    """Sample-averaged JS divergence between every layer pair -> (L, L) matrix,
    upper triangle filled, rest NaN (notebook cell 16)."""
    L = len(distributions)
    results = np.full((L, L), np.nan)
    for i in range(L):
        for j in range(i + 1, L):
            acc = 0.0
            for p, q in zip(distributions[i], distributions[j]):
                acc += jensen_shannon_divergence(p, q)
            results[i, j] = acc / len(distributions[i])
    return results
