"""Jensen-Shannon similarity of per-layer token-importance distributions.

Reproduces the analysis that exists only in the reference's
``distributions_distance_across_layers.ipynb`` (cells 10-18): for each corpus
sample, compute every layer's regular-importance distribution (head-mean
column-mean of the attention map — a probability distribution over positions),
then average pairwise Jensen-Shannon divergences between layers over samples.
The resulting upper-triangular LxL matrix (e.g. Pythia layers 0<->1 = 0.0516,
0<->4 = 0.3946 — BASELINE.md) quantifies how transferable an importance ordering
computed at one layer is to another split point.

Formulas follow the notebook exactly: base-2 KL with the ``p != 0`` guard
(cell 12) and JS as the symmetrized average against the mixture (cell 13 — the
notebook's "distance" is the divergence, not its square root; kept as-is).
"""
from __future__ import annotations

import functools
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..models.configs import ModelConfig
from ..models.transformer import run_layers_from_ids
from ..importance import regular_importance


def kl_divergence(p: np.ndarray, q: np.ndarray) -> float:
    """Base-2 KL divergence with zero-p guard (notebook cell 12)."""
    p, q = np.asarray(p, np.float64), np.asarray(q, np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = np.where(p != 0, p * np.log2(p / q), 0.0)
    return float(np.sum(terms))


def jensen_shannon_divergence(p: np.ndarray, q: np.ndarray) -> float:
    """JS divergence against the 50/50 mixture (notebook cell 13)."""
    m = 0.5 * (np.asarray(p, np.float64) + np.asarray(q, np.float64))
    return 0.5 * (kl_divergence(p, m) + kl_divergence(q, m))


@functools.lru_cache(maxsize=None)
def _per_layer_importance(cfg: ModelConfig):
    @jax.jit
    def fn(params, ids):
        _, aux = run_layers_from_ids(cfg, params, ids, capture_stats=True)
        return regular_importance(aux["stats"].col_mean)[:, 0]  # (L, S)

    return fn


def bucket_lengths(lengths: Sequence[int], max_buckets: int) -> list:
    """Pick <= max_buckets clip lengths (ascending) covering a ragged corpus.

    Quantile-spaced over the distinct lengths so short and long samples each
    get a nearby bucket; every sample is clipped DOWN to the largest bucket
    <= its length (samples shorter than the smallest bucket keep their native
    length — at most max_buckets extra compiles in pathological corpora).
    """
    distinct = sorted(set(int(l) for l in lengths))
    if len(distinct) <= max_buckets:
        return distinct
    qs = np.linspace(0, len(distinct) - 1, max_buckets).round().astype(int)
    return [distinct[i] for i in sorted(set(qs))]


def layer_importance_distributions(cfg: ModelConfig, params,
                                   samples: Sequence[np.ndarray],
                                   max_compiles: int | None = None) -> list:
    """Per-sample regular-importance distributions: list over L layers of lists
    over samples of (S_i,) arrays (the notebook's ``all_distributions``).

    Samples run at their native lengths by default, like the notebook's
    per-line forwards — each DISTINCT length compiles the stats forward once.
    ``max_compiles`` bounds that for large ragged corpora: samples are clipped
    down to <= max_compiles bucket lengths (``bucket_lengths``). Clipping keeps
    the analysis exact *for the analyzed prefix* — every layer of a sample sees
    the same tokens, which is all the layer-pair JS comparison needs — unlike
    padding, which would let pad positions perturb the attention statistics.
    """
    fn = _per_layer_importance(cfg)
    samples = [np.asarray(s).reshape(-1) for s in samples]
    if max_compiles is not None:
        buckets = bucket_lengths([s.shape[0] for s in samples], max_compiles)
        clipped = []
        for s in samples:
            fits = [b for b in buckets if b <= s.shape[0]]
            clipped.append(s[: fits[-1]] if fits else s)
        samples = clipped
    out = [[] for _ in range(cfg.num_layers)]
    for ids in samples:
        imp = np.asarray(fn(params, jnp.asarray(ids[None, :])))
        for layer in range(cfg.num_layers):
            out[layer].append(imp[layer])
    return out


def pairwise_layer_distances(distributions: list) -> np.ndarray:
    """Sample-averaged JS divergence between every layer pair -> (L, L) matrix,
    upper triangle filled, rest NaN (notebook cell 16)."""
    L = len(distributions)
    if L and not distributions[0]:
        raise ValueError("no usable samples: every corpus sample was filtered "
                         "out before the layer-importance pass")
    results = np.full((L, L), np.nan)
    for i in range(L):
        for j in range(i + 1, L):
            acc = 0.0
            for p, q in zip(distributions[i], distributions[j]):
                acc += jensen_shannon_divergence(p, q)
            results[i, j] = acc / len(distributions[i])
    return results


def save_heatmap(matrix: np.ndarray, path: str, title: str = "JS divergence "
                 "between layer importance distributions") -> None:
    """The notebook's cell-18 seaborn heatmap as a matplotlib artifact."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(1 + 0.5 * matrix.shape[0],) * 2)
    im = ax.imshow(matrix, cmap="viridis")
    fig.colorbar(im, ax=ax)
    for i in range(matrix.shape[0]):
        for j in range(matrix.shape[1]):
            if np.isfinite(matrix[i, j]):
                ax.text(j, i, f"{matrix[i, j]:.2f}", ha="center", va="center",
                        color="white", fontsize=7)
    ax.set_xlabel("layer")
    ax.set_ylabel("layer")
    ax.set_title(title, fontsize=9)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
