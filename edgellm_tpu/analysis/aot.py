"""Shared AOT compile-and-measure driver.

Both the window-batch preflight (``tools/wb_preflight.py``) and the
config-lattice verifier (``lint/lattice.py``) need the same primitive:
lower a jitted entry point, compile it WITHOUT allocating device memory,
and read XLA's ``memory_analysis()`` — argument, output and temp bytes —
plus whether the compiler itself proved the program over-HBM. This module
is that primitive, extracted so the two callers cannot drift.

Nothing here runs model math: ``.lower()`` traces, ``.compile()`` builds
the executable, and ``memory_analysis()`` is a static read. On the
tunneled TPU backend this matters doubly — a real RESOURCE_EXHAUSTED
poisons the process allocator, so "compile first, run only what fits" is
the only robust order (see the wb_preflight module docstring).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional


def is_over_hbm(e: BaseException) -> bool:
    """True when a compile failed because the program provably exceeds HBM
    ('Program hbm requirement ...G' dump) — extends the runtime-OOM
    vocabulary of :func:`edgellm_tpu.eval.harness.is_oom_error` to compile
    time."""
    from ..eval.harness import is_oom_error

    msg = str(e)
    return ("hbm requirement" in msg or "allocations in hbm" in msg
            or is_oom_error(e))


@dataclasses.dataclass(frozen=True)
class AOTCost:
    """Static memory footprint of one compiled executable, in bytes."""

    argument_bytes: int
    output_bytes: int
    temp_bytes: int

    @property
    def total(self) -> int:
        """argument + output + temp — the peak one call keeps live."""
        return self.argument_bytes + self.output_bytes + self.temp_bytes

    def as_dict(self) -> dict:
        return {"argument_bytes": self.argument_bytes,
                "output_bytes": self.output_bytes,
                "temp_bytes": self.temp_bytes,
                "total_bytes": self.total}


def lowered_cost(lowered: Any) -> Optional[AOTCost]:
    """Compile a ``.lower()`` result and read its memory analysis.

    Returns ``None`` when the backend compiler rejects the program as
    provably over-HBM — a doesn't-fit verdict reached with zero device
    allocation. Any other compile failure propagates: a program that fails
    to compile for a non-memory reason is a bug, not a budget miss."""
    try:
        compiled = lowered.compile()
    except Exception as e:
        if is_over_hbm(e):
            return None
        raise
    ma = compiled.memory_analysis()
    return AOTCost(argument_bytes=int(ma.argument_size_in_bytes),
                   output_bytes=int(ma.output_size_in_bytes),
                   temp_bytes=int(ma.temp_size_in_bytes))


def aot_cost(jitted_fn: Callable, *args: Any, **kwargs: Any) -> Optional[AOTCost]:
    """Lower + compile ``jitted_fn(*args)`` and return its
    :class:`AOTCost` (``None`` when provably over-HBM)."""
    return lowered_cost(jitted_fn.lower(*args, **kwargs))


def call_total_bytes(lowered: Any) -> Optional[int]:
    """argument+output+temp bytes of one lowered call, or ``None`` when the
    compiler rejects it as over-HBM — the wb_preflight convention."""
    cost = lowered_cost(lowered)
    return None if cost is None else cost.total
