"""TPU-native framework for split-LLM inference across distributed edge devices.

A ground-up JAX/XLA/Pallas re-design with the capabilities of
``sv-goat/LLM-Inference-in-Distributed-Edge-Networks`` (mounted read-only at
``/root/reference``): layer-split causal LMs over a ``jax.sharding.Mesh`` (each
"edge device" = one TPU chip), boundary activation codecs as packed Pallas
kernels crossing ``lax.ppermute``, attention/relevance token-importance scoring
fused into the forward pass, and a sliding-window WikiText perplexity harness.

Subpackages (see each subpackage's docstring):
- ``models``   — functional GPT-NeoX (Pythia) and Qwen2 cores, HF weight conversion
- ``codecs``   — boundary activation quantizers (simulate + packed)
"""

__version__ = "0.1.0"
