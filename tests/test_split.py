"""Split-runtime tests on the spoofed 8-device CPU mesh (conftest.py).

The key claims, each tested:
1. an fp32-wire split forward equals the unsplit forward (the transfer itself is
   lossless — reference's ratio-0 / ``layer_by_layer_impl`` parity check, made
   multi-device);
2. a quantized-wire split forward equals the single-device forward with the
   matching *simulate* codec applied via ``boundary_fn`` at the cut layer — i.e.
   real packed bytes over ppermute reproduce the reference's in-place simulation
   exactly;
3. multi-hop (3-stage) chains with per-hop codecs work the same way;
4. byte accounting comes from the actual payload buffers.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.models import tiny_config, init_params, forward
from edgellm_tpu.codecs import channel_wise_quant, per_token_affine_int8, int4_token_select
from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh

CFG = tiny_config("qwen2", num_layers=6, hidden_size=32, num_heads=4, vocab_size=128)
NEOX = tiny_config("gpt_neox", num_layers=4, hidden_size=32, num_heads=4, vocab_size=128)


@pytest.fixture(scope="module")
def setup():
    params = init_params(CFG, jax.random.key(1))
    ids = jnp.asarray(np.random.default_rng(5).integers(0, CFG.vocab_size, (1, 24)))
    base, _ = forward(CFG, params, ids)
    return params, ids, base


def test_mesh_construction():
    mesh = make_stage_mesh(2, n_data=2, n_model=2)
    assert dict(mesh.shape) == {"stage": 2, "data": 2, "model": 2}
    with pytest.raises(ValueError):
        make_stage_mesh(16)


def test_split_config_validation():
    with pytest.raises(ValueError):
        SplitConfig(cuts=(3,), hop_codecs=())
    with pytest.raises(ValueError):
        SplitConfig(cuts=(3, 2), hop_codecs=("fp32", "fp32"))
    sc = SplitConfig(cuts=(1, 3), hop_codecs=("fp32", "fp32"))
    assert sc.stage_bounds(6) == [(0, 2), (2, 4), (4, 6)]
    with pytest.raises(ValueError):
        SplitConfig(cuts=(5,), hop_codecs=("fp32",)).stage_bounds(6)


def test_fp32_split_matches_unsplit(setup):
    params, ids, base = setup
    rt = SplitRuntime(CFG, SplitConfig(cuts=(2,), hop_codecs=("fp32",)), make_stage_mesh(2))
    out = rt.forward(rt.place_params(params), ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=1e-5, rtol=1e-5)


def test_uneven_stage_split_matches_unsplit(setup):
    """cut after layer 0 -> stages of 1 and 5 layers (padding/masking path)."""
    params, ids, base = setup
    rt = SplitRuntime(CFG, SplitConfig(cuts=(0,), hop_codecs=("fp32",)), make_stage_mesh(2))
    out = rt.forward(rt.place_params(params), ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("wire,sim", [
    ("int4_global", lambda h: int4_token_select(h, jnp.arange(h.shape[1], 0.0, -1.0), 1.0)),
    ("int8_per_token", per_token_affine_int8),
    ("int4_per_channel", lambda h: channel_wise_quant(h, "channel_4")),
    ("ternary_max", lambda h: channel_wise_quant(h, "channel_1_max")),
])
def test_quantized_split_equals_simulated_boundary(setup, wire, sim):
    """Packed bytes over ppermute == the reference's in-place simulation."""
    params, ids, _ = setup
    cut = 2
    rt = SplitRuntime(CFG, SplitConfig(cuts=(cut,), hop_codecs=(wire,)), make_stage_mesh(2))
    split_logits = rt.forward(rt.place_params(params), ids)

    def bfn(idx, h):
        return jnp.where(idx == cut, sim(h), h)

    ref_logits, _ = forward(CFG, params, ids, boundary_fn=bfn)
    np.testing.assert_allclose(np.asarray(split_logits), np.asarray(ref_logits),
                               atol=2e-5, rtol=2e-5)


def test_three_hop_chain(setup):
    params, ids, base = setup
    rt = SplitRuntime(
        CFG, SplitConfig(cuts=(1, 3), hop_codecs=("fp32", "fp32")), make_stage_mesh(3))
    out = rt.forward(rt.place_params(params), ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=1e-5, rtol=1e-5)

    rt_q = SplitRuntime(
        CFG, SplitConfig(cuts=(1, 3), hop_codecs=("int4_global", "int8_per_token")),
        make_stage_mesh(3))
    out_q = rt_q.forward(rt_q.place_params(params), ids)

    def bfn(idx, h):
        h = jnp.where(idx == 1, int4_token_select(h, jnp.arange(h.shape[1], 0.0, -1.0), 1.0), h)
        return jnp.where(idx == 3, per_token_affine_int8(h), h)

    ref_logits, _ = forward(CFG, params, ids, boundary_fn=bfn)
    np.testing.assert_allclose(np.asarray(out_q), np.asarray(ref_logits),
                               atol=2e-5, rtol=2e-5)


def test_gpt_neox_family_split(setup):
    params = init_params(NEOX, jax.random.key(2))
    ids = jnp.asarray(np.random.default_rng(6).integers(0, NEOX.vocab_size, (1, 16)))
    base, _ = forward(NEOX, params, ids)
    rt = SplitRuntime(NEOX, SplitConfig(cuts=(1,), hop_codecs=("fp32",)), make_stage_mesh(2))
    out = rt.forward(rt.place_params(params), ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=1e-5, rtol=1e-5)


def test_hop_bytes_measured(setup):
    rt = SplitRuntime(
        CFG, SplitConfig(cuts=(1, 3), hop_codecs=("int4_per_token", "fp16")),
        make_stage_mesh(3))
    b4, b16 = rt.bytes_per_token(32)
    D = CFG.hidden_size
    assert b4 == D / 2 + 4  # packed nibbles + fp32 scale per token
    assert b16 == D * 2


def test_tensor_parallel_matches_unsplit(setup):
    """stage=2 x model=2: heads/FFN column-row split with in-block psum ==
    the single-device forward (real TP, not a GSPMD hint)."""
    params, ids, base = setup
    rt = SplitRuntime(CFG, SplitConfig(cuts=(2,), hop_codecs=("fp32",)),
                      make_stage_mesh(2, n_model=2))
    # weights actually land split: wq's last axis is halved per shard
    placed = rt.place_params(params)
    shard_shape = placed["layers"]["wq"].sharding.shard_shape(
        placed["layers"]["wq"].shape)
    assert shard_shape[-1] == CFG.num_heads * CFG.head_dim // 2
    out = rt.forward(placed, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=1e-5, rtol=1e-5)


def test_tensor_parallel_with_quantized_hop(setup):
    """TP composes with a packed quantized boundary hop."""
    params, ids, _ = setup
    cut = 2
    rt = SplitRuntime(CFG, SplitConfig(cuts=(cut,), hop_codecs=("int8_per_token",)),
                      make_stage_mesh(2, n_model=2))
    out = rt.forward(rt.place_params(params), ids)

    def bfn(idx, h):
        return jnp.where(idx == cut, per_token_affine_int8(h), h)

    ref_logits, _ = forward(CFG, params, ids, boundary_fn=bfn)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               atol=2e-5, rtol=2e-5)


def test_tensor_parallel_gpt_neox(setup):
    """TP with the biased / parallel-residual family (b_in split, b_out post-psum)."""
    params = init_params(NEOX, jax.random.key(2))
    ids = jnp.asarray(np.random.default_rng(6).integers(0, NEOX.vocab_size, (1, 16)))
    base, _ = forward(NEOX, params, ids)
    rt = SplitRuntime(NEOX, SplitConfig(cuts=(1,), hop_codecs=("fp32",)),
                      make_stage_mesh(2, n_model=2))
    out = rt.forward(rt.place_params(params), ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=1e-5, rtol=1e-5)


def test_tensor_parallel_divisibility_validated():
    cfg = tiny_config("qwen2", num_layers=4, hidden_size=36, num_heads=3,
                      num_kv_heads=3, vocab_size=128)
    with pytest.raises(ValueError, match="tensor parallelism"):
        SplitRuntime(cfg, SplitConfig(cuts=(1,), hop_codecs=("fp32",)),
                     make_stage_mesh(2, n_model=2))


def test_zero_cut_single_stage_runs(setup):
    """Degenerate baseline: no cuts, one stage — still matches unsplit."""
    params, ids, base = setup
    rt = SplitRuntime(CFG, SplitConfig(cuts=(), hop_codecs=()), make_stage_mesh(1))
    out = rt.forward(rt.place_params(params), ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=1e-5, rtol=1e-5)


def test_mesh_stage_count_mismatch_raises(setup):
    with pytest.raises(ValueError):
        SplitRuntime(CFG, SplitConfig(cuts=(2,), hop_codecs=("fp32",)), make_stage_mesh(3))
