"""Prefix-sharing paged KV cache: radix index, refcounts, copy-on-write.

The load-bearing claim: prefix sharing is HOST-SIDE bookkeeping — it
changes which page-table rows point at which pages, never the traced
graph — so a prefix-enabled batcher's tokens are identical to the
non-shared path on a mixed trace while matched prompt prefixes cost zero
prefill compute. The graphlint contracts pin the jaxpr half
(``batching.prefix-disabled-identity``); these tests pin the executed
half plus every allocator invariant sharing touches: refcounted frees,
COW forks, defrag under sharing churn, LRU index eviction under page
pressure, and checkpoint/restore with shared pages in flight.
"""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.models import init_params, tiny_config
from edgellm_tpu.models.paged_kv import PagedKVCache, PrefixCacheConfig
from edgellm_tpu.serve.batching import BatchingConfig, ContinuousBatcher
from edgellm_tpu.serve.decode import generate

CFG = tiny_config("qwen2", num_layers=4, hidden_size=32, num_heads=4,
                  vocab_size=128)
# same geometry as tests/test_batching.py so the compiled ragged step is
# shared across the suite; prefix-enabled twins differ only in host state
BCFG = BatchingConfig(page_size=8, num_pages=17, max_slots=4,
                      pages_per_slot=4)
PCFG = PrefixCacheConfig(enabled=True, min_shared_block=1)

# pool-level tests use a 2-layer model: the allocator math is layer-count
# independent and the materialized pages stay tiny
CFG2 = tiny_config("qwen2", num_layers=2, hidden_size=32, num_heads=4,
                   vocab_size=128)
PROMPT = list(range(100, 110))     # 10 tokens = 2 full blocks + partial 2


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(1))


def _prompt(n, seed=0):
    return np.random.default_rng(seed).integers(
        1, CFG.vocab_size, size=n).astype(np.int32)


def _solo(params, prompt, max_new, temp=0.0, seed=0):
    out = generate(CFG, params, jnp.asarray(prompt)[None], max_new,
                   capacity=BCFG.span, temperature=temp,
                   rng_key=jax.random.key(seed))
    return np.asarray(out)[0]


def _seq(n, seed):
    r = np.random.default_rng(seed)
    shape = (CFG2.num_layers, n, CFG2.num_kv_heads, CFG2.head_dim)
    return (jnp.asarray(r.standard_normal(shape), jnp.float32),
            jnp.asarray(r.standard_normal(shape), jnp.float32))


def _pool(prefix=PCFG, **kw):
    return PagedKVCache(CFG2, num_pages=13, page_size=4, max_slots=3,
                        pages_per_slot=4, prefix_cache=prefix, **kw)


def _donor_pool(prefix=PCFG):
    """A pool whose slot 0 adopted PROMPT and published it to the index."""
    cache = _pool(prefix)
    s0 = cache.alloc_slot()
    k0, v0 = _seq(10, 0)
    cache.adopt(s0, k0, v0, 10)
    assert cache.register_prefix(s0, PROMPT) == 3
    cache.check_invariants()
    return cache, s0


# ---------------------------------------------------------------------------
# config + inert paths
# ---------------------------------------------------------------------------


def test_prefix_config_validation():
    with pytest.raises(ValueError, match="min_shared_block"):
        PrefixCacheConfig(min_shared_block=0)
    with pytest.raises(ValueError, match="max_index_pages"):
        PrefixCacheConfig(max_index_pages=-1)


def test_prefix_api_inert_without_index():
    # no prefix_cache at all, and enabled=False, behave identically: the
    # sharing API returns zeros and allocator state never changes
    for prefix in (None, PrefixCacheConfig(enabled=False)):
        pool = PagedKVCache(CFG2, num_pages=13, page_size=4, max_slots=3,
                            pages_per_slot=4, materialize=False,
                            prefix_cache=prefix)
        assert pool.prefix is None
        s = pool.alloc_slot()
        pool.ensure(s, 10)
        assert pool.register_prefix(s, PROMPT) == 0
        assert pool.probe_prefix(PROMPT) == {"tokens": 0, "pages": 0,
                                             "forks": 0}
        s1 = pool.alloc_slot()
        assert pool.share_prefix(s1, PROMPT) == 0
        assert pool.release_prefix() == 0
        pool.check_invariants()


# ---------------------------------------------------------------------------
# probe / share / COW
# ---------------------------------------------------------------------------


def test_probe_share_cow_and_unique_tokens():
    cache, s0 = _donor_pool()
    g0 = cache.gather_slot(s0)
    probe = cache.probe_prefix(PROMPT + [111, 112])
    assert probe == {"tokens": 10, "pages": 3, "forks": 1}
    s1 = cache.alloc_slot()
    # the batcher caps the claim at S-1 so one suffix token remains
    assert cache.share_prefix(s1, PROMPT + [111, 112], max_tokens=11) == 10
    cache.check_invariants()
    assert cache.prefix_counters["hits"] == 1
    assert cache.prefix_counters["saved_tokens"] == 10
    # suffix rows land in the shared partial page: it must COW-fork, and
    # the fork's device copy must carry the donor's matched rows
    k1, v1 = _seq(2, 1)
    cache.adopt_rows(s1, k1, v1, 10, 12)
    cache.check_invariants()
    assert cache.prefix_counters["cow_forks"] == 1
    g1 = cache.gather_slot(s1)
    np.testing.assert_array_equal(g1["k"][:, :10], g0["k"][:, :10])
    np.testing.assert_array_equal(g1["v"][:, :10], g0["v"][:, :10])
    np.testing.assert_array_equal(np.asarray(g1["k"][:, 10:12]),
                                  np.asarray(k1))
    # divergent tail registers under the matched chain without re-pinning
    cache.register_prefix(s1, PROMPT + [111, 112])
    cache.check_invariants()
    # unique coverage: 2 shared full pages (8) + donor partial (2) + the
    # sharer's forked partial covering rows 8..12 (4) = 14, not 10 + 12
    assert cache.unique_live_tokens == 14
    assert cache.live_tokens == 22
    assert cache.shared_pages >= 2


def test_share_cap_lands_mid_partial_node():
    cache, _ = _donor_pool()
    assert cache.probe_prefix(PROMPT, max_tokens=9) == {
        "tokens": 9, "pages": 3, "forks": 1}
    s1 = cache.alloc_slot()
    # cap 9 = 2 full blocks + ONE token of the 2-token partial node
    assert cache.share_prefix(s1, PROMPT, max_tokens=9) == 9
    assert int(cache.lengths[s1]) == 9
    cache.check_invariants()
    k, v = _seq(1, 2)
    cache.adopt_rows(s1, k, v, 9, 10)
    cache.check_invariants()
    assert cache.prefix_counters["cow_forks"] == 1


def test_min_shared_block_gates_sharing():
    cache, _ = _donor_pool(
        PrefixCacheConfig(enabled=True, min_shared_block=12))
    assert cache.probe_prefix(PROMPT) == {"tokens": 0, "pages": 0,
                                          "forks": 0}
    s1 = cache.alloc_slot()
    assert cache.share_prefix(s1, PROMPT) == 0
    assert cache.prefix_counters["misses"] == 1
    # the miss must leave the slot untouched
    assert int(cache.lengths[s1]) == 0 and not cache._slot_pages[s1]
    cache.check_invariants()


def test_share_requires_fresh_slot():
    cache, s0 = _donor_pool()
    with pytest.raises(ValueError, match="fresh"):
        cache.share_prefix(s0, PROMPT)


# ---------------------------------------------------------------------------
# index cap + LRU eviction + pressure reclaim
# ---------------------------------------------------------------------------


def test_index_cap_evicts_lru_leaves():
    cache = _pool(PrefixCacheConfig(enabled=True, min_shared_block=1,
                                    max_index_pages=2))
    s0 = cache.alloc_slot()
    k0, v0 = _seq(10, 0)
    cache.adopt(s0, k0, v0, 10)
    # the cap stops registration at 2 nodes: the partial tail never pins
    # (its only evictable victim is the chain being registered)
    assert cache.register_prefix(s0, PROMPT) == 2
    assert cache.prefix.num_nodes == 2
    assert cache.probe_prefix(PROMPT)["tokens"] == 8
    cache.check_invariants()
    # a disjoint prompt evicts the donor chain leaf-first (LRU order)
    other = list(range(30, 38))
    s1 = cache.alloc_slot()
    k1, v1 = _seq(8, 1)
    cache.adopt(s1, k1, v1, 8)
    assert cache.register_prefix(s1, other) == 2
    cache.check_invariants()
    assert cache.prefix.num_nodes == 2
    assert cache.prefix_counters["index_evictions"] == 2
    assert cache.probe_prefix(PROMPT)["tokens"] == 0
    assert cache.probe_prefix(other)["tokens"] == 8


def test_pressure_reclaims_lru_index_pages_first():
    cache, s0 = _donor_pool()
    other = [int(t) for t in range(30, 40)]
    s1 = cache.alloc_slot()
    k1, v1 = _seq(10, 1)
    cache.adopt(s1, k1, v1, 10)
    assert cache.register_prefix(s1, other) == 3
    cache.free_slot(s0)
    cache.free_slot(s1)
    cache.check_invariants()
    # both chains now live only in the index (refcount 1 each); touch the
    # PROMPT chain so it is the recently-used one
    s = cache.alloc_slot()
    assert cache.share_prefix(s, PROMPT) == 10
    cache.free_slot(s)
    assert cache.index_pages == 6
    assert cache.reclaimable_index_pages == 6
    # demand 8 pages with 6 free: the allocator must reclaim exactly two
    # index-only pages, LRU-first — the untouched chain loses its tail
    sa = cache.alloc_slot()
    cache.ensure(sa, 16)
    sb = cache.alloc_slot()
    cache.ensure(sb, 16)
    cache.check_invariants()
    assert cache.prefix_counters["reclaimed_pages"] == 2
    assert cache.probe_prefix(PROMPT)["tokens"] == 10
    assert cache.probe_prefix(other)["tokens"] == 4
    # release everything: every page must come home
    cache.free_slot(sa)
    cache.free_slot(sb)
    cache.release_prefix()
    cache.check_invariants()
    assert cache.num_free_pages == 12


def test_release_prefix_path_drops_exclusive_suffix():
    cache, s0 = _donor_pool()
    cache.free_slot(s0)
    cache.check_invariants()
    assert cache.index_pages == 3
    assert cache.release_prefix(PROMPT) == 3
    cache.check_invariants()
    assert cache.probe_prefix(PROMPT)["tokens"] == 0
    assert cache.num_free_pages == 12


# ---------------------------------------------------------------------------
# defrag x sharing churn
# ---------------------------------------------------------------------------


def test_defrag_relocates_shared_pages_once_for_all_owners():
    cache, s0 = _donor_pool()
    g0 = cache.gather_slot(s0)
    s1 = cache.alloc_slot()
    cache.share_prefix(s1, PROMPT + [111, 112], max_tokens=11)
    k1, v1 = _seq(2, 1)
    cache.adopt_rows(s1, k1, v1, 10, 12)
    cache.register_prefix(s1, PROMPT + [111, 112])
    s2 = cache.alloc_slot()
    cache.share_prefix(s2, PROMPT, max_tokens=9)
    k2, v2 = _seq(1, 2)
    cache.adopt_rows(s2, k2, v2, 9, 10)
    cache.check_invariants()
    g1 = cache.gather_slot(s1)
    g2 = cache.gather_slot(s2)
    # a page referenced by three slots moves once; every owner's view is
    # byte-identical afterwards
    cache.defrag()
    cache.check_invariants()
    for slot, g in ((s0, g0), (s1, g1), (s2, g2)):
        got = cache.gather_slot(slot)
        np.testing.assert_array_equal(got["k"], g["k"])
        np.testing.assert_array_equal(got["v"], g["v"])
    # free the DONOR mid-churn: shared pages survive for the other owners,
    # and defragging across the freed hole keeps them byte-identical
    cache.free_slot(s0)
    cache.check_invariants()
    cache.defrag()
    cache.check_invariants()
    np.testing.assert_array_equal(cache.gather_slot(s1)["k"], g1["k"])
    np.testing.assert_array_equal(cache.gather_slot(s2)["k"], g2["k"])


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------


def _shared_state_dict():
    cache, s0 = _donor_pool()
    s1 = cache.alloc_slot()
    cache.share_prefix(s1, PROMPT + [111, 112], max_tokens=11)
    k1, v1 = _seq(2, 1)
    cache.adopt_rows(s1, k1, v1, 10, 12)
    cache.check_invariants()
    return cache, s0, s1, cache.state_dict()


def test_state_dict_roundtrips_refcounts_and_index():
    cache, s0, s1, sd = _shared_state_dict()
    cache2 = _pool()
    cache2.load_state_dict(sd)
    cache2.check_invariants()
    assert cache2.prefix.num_nodes == cache.prefix.num_nodes
    assert (cache2._refcount == cache._refcount).all()
    for slot in (s0, s1):
        np.testing.assert_array_equal(cache2.gather_slot(slot)["k"],
                                      cache.gather_slot(slot)["k"])
    # the restored index is live, not a husk: a new admit shares from it
    s2 = cache2.alloc_slot()
    assert cache2.share_prefix(s2, PROMPT) == 10
    cache2.check_invariants()


def test_sharing_checkpoint_restores_into_prefix_disabled_pool():
    cache, s0, s1, sd = _shared_state_dict()
    plain = _pool(prefix=None)
    plain.load_state_dict(sd)
    # the index is gone, so its holds must drop without double-freeing or
    # leaking — check_invariants cross-checks refcount == slot references
    plain.check_invariants()
    assert plain.prefix is None
    assert plain.index_pages == 0
    for slot in (s0, s1):
        np.testing.assert_array_equal(plain.gather_slot(slot)["k"],
                                      cache.gather_slot(slot)["k"])


# ---------------------------------------------------------------------------
# batched decode: token identity + reporting
# ---------------------------------------------------------------------------


def _mixed_trace(rng_seed=3):
    rng = np.random.default_rng(rng_seed)
    shared = rng.integers(1, CFG.vocab_size, size=8)
    prompts = [
        np.concatenate([shared, rng.integers(1, CFG.vocab_size, size=5)]),
        np.concatenate([shared, rng.integers(1, CFG.vocab_size, size=3)]),
        rng.integers(1, CFG.vocab_size, size=9),          # disjoint
        np.concatenate([shared, rng.integers(1, CFG.vocab_size, size=7)]),
    ]
    return [p.astype(np.int32) for p in prompts], [0.0, 0.7, 0.0, 1.1]


def test_batched_mixed_trace_token_identity(params):
    prompts, temps = _mixed_trace()

    def run(prefix_cache):
        bc = BatchingConfig(page_size=8, num_pages=17, max_slots=4,
                            pages_per_slot=4, prefix_cache=prefix_cache)
        b = ContinuousBatcher(CFG, params, bc)
        sids = [b.submit(p, 6, temperature=t, rng_seed=i)
                for i, (p, t) in enumerate(zip(prompts, temps))]
        res = b.run()
        b.pool.check_invariants()
        return {s: res[s].tolist() for s in sids}, b

    base, off_bat = run(None)
    got, on_bat = run(PCFG)
    assert got == base
    # the parity proved something: the shared prefix actually hit
    rep = on_bat.report()["prefix"]
    assert rep["hits"] >= 2 and rep["saved_tokens"] > 0
    assert rep["cow_forks"] >= 1
    # enabled=False must be indistinguishable from no config at all
    off, _ = run(PrefixCacheConfig(enabled=False))
    assert off == base
    # and both pin to solo generate through the greedy stream
    np.testing.assert_array_equal(np.asarray(base[0], np.int32),
                                  _solo(params, prompts[0], 6, 0.0, 0))
    # occupancy counts a shared page ONCE: sharing can only lower it
    assert (on_bat.report()["occupancy_mean"]
            <= off_bat.report()["occupancy_mean"] + 1e-9)


def test_checkpoint_restore_with_shared_pages(params, tmp_path):
    bc = BatchingConfig(page_size=8, num_pages=17, max_slots=4,
                        pages_per_slot=4, prefix_cache=PCFG)
    bat = ContinuousBatcher(CFG, params, bc)
    shared = _prompt(9, 21)
    pa = np.concatenate([shared, _prompt(3, 22)])
    pb = np.concatenate([shared, _prompt(2, 23)])
    sa = bat.submit(pa, 8, temperature=0.6, rng_seed=7)
    sb = bat.submit(pb, 8, temperature=0.0, rng_seed=8)
    for _ in range(3):
        bat.step()
    assert bat.pool.shared_pages >= 1
    path = bat.checkpoint_stream(sb, str(tmp_path / "b.ckpt"))
    # kill the stream mid-decode: its shared pages must survive for the
    # other holder — no double-free, no leak
    bat.discard(sb)
    bat.pool.check_invariants()
    res = bat.run()
    bat.pool.check_invariants()
    np.testing.assert_array_equal(res[sa], _solo(params, pa, 8, 0.6, 7))
    # restore into a FRESH prefix-enabled batcher: the payload is the
    # contiguous prefix, adopted privately, finishing bit-identically
    other = ContinuousBatcher(CFG, params, bc)
    rid = other.restore_stream(path)
    out = other.run()
    other.pool.check_invariants()
    np.testing.assert_array_equal(out[rid], _solo(params, pb, 8, 0.0, 8))
    # and into a prefix-DISABLED pool: no index state rides the checkpoint
    plain = ContinuousBatcher(CFG, params, BCFG)
    rid2 = plain.restore_stream(path)
    np.testing.assert_array_equal(plain.run()[rid2],
                                  _solo(params, pb, 8, 0.0, 8))


def test_split_mixed_trace_token_identity(params):
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    from edgellm_tpu.parallel import (SplitConfig, SplitRuntime,
                                      make_stage_mesh)

    mesh = make_stage_mesh(2)
    rt = SplitRuntime(CFG, SplitConfig(cuts=(2,),
                                       hop_codecs=("int8_per_token",)), mesh)
    placed = rt.place_params(params)
    prompts, temps = _mixed_trace(5)
    prompts, temps = prompts[:3], temps[:3]

    def run(prefix_cache):
        bc = BatchingConfig(page_size=8, num_pages=17, max_slots=4,
                            pages_per_slot=4, prefix_cache=prefix_cache)
        b = ContinuousBatcher(CFG, params, bc, split_runtime=rt,
                              placed_params=placed)
        sids = [b.submit(p, 5, temperature=t, rng_seed=i)
                for i, (p, t) in enumerate(zip(prompts, temps))]
        res = b.run()
        b.pool.check_invariants()
        return {s: res[s].tolist() for s in sids}, b

    base, _ = run(None)
    got, gb = run(PCFG)
    assert got == base
    assert gb.report()["prefix"]["hits"] >= 1


def test_front_report_carries_prefix_scoreboard(params):
    from edgellm_tpu.serve import Request, ServeFront

    bat = ContinuousBatcher(
        CFG, params, BatchingConfig(page_size=8, num_pages=17, max_slots=4,
                                    pages_per_slot=4, prefix_cache=PCFG))
    front = ServeFront(CFG, params, batcher=bat)
    shared = _prompt(9, 50)
    reqs = [(np.concatenate([shared, _prompt(3, 51)]), 4, 0.0, 1),
            (np.concatenate([shared, _prompt(2, 52)]), 4, 0.6, 2)]
    for p, m, t, s in reqs:
        front.submit(Request(prompt_ids=p, max_new_tokens=m, temperature=t,
                             rng_seed=s))
    recs = front.drain_batched()
    assert len(recs) == 2
    for (p, m, t, s), rec in zip(reqs, sorted(recs,
                                              key=lambda r: r.request_id)):
        assert rec.outcome == "completed"
        np.testing.assert_array_equal(rec.tokens[0],
                                      _solo(params, p, m, t, s))
    # the drain stamps the headline numbers into each record's plan and
    # the front-level report exposes the live scoreboard
    assert any(r.plan.get("prefix", {}).get("saved_tokens", 0) > 0
               for r in recs)
    rep = front.report()
    assert rep["prefix"]["hits"] >= 1
    assert 0.0 < rep["prefix"]["hit_rate"] <= 1.0
    # a front without a prefix-enabled batcher reports no such section
    assert "prefix" not in ServeFront(CFG, params).report()


# ---------------------------------------------------------------------------
# run.py params validation
# ---------------------------------------------------------------------------


def _prefix_params():
    root = os.path.join(os.path.dirname(__file__), "..")
    with open(os.path.join(root, "configs",
                           "split13_qwen_prefix.json")) as f:
        return json.load(f)


def test_params_validation_accepts_prefix_config():
    from edgellm_tpu.run import _validate_params_json

    _validate_params_json(_prefix_params())  # must not raise


@pytest.mark.parametrize("patch, msg", [
    ({"batching": None}, "rides the continuous batcher"),
    ({"prefix_cache": [1]}, "object of PrefixCacheConfig"),
    ({"prefix_cache": {"enabled": True, "page_size": 8}}, "unknown field"),
    ({"prefix_cache": {"enabled": 1}}, "must be a boolean"),
    ({"prefix_cache": {"min_shared_block": -1}}, "non-negative"),
    ({"prefix_cache": {"min_shared_block": True}}, "non-negative"),
    ({"prefix_cache": {"min_shared_block": 0}}, "min_shared_block"),
])
def test_params_validation_rejects_prefix_footguns(patch, msg):
    from edgellm_tpu.run import _validate_params_json

    p = _prefix_params()
    p.update(patch)
    if p.get("batching") is None:
        p.pop("batching", None)
    with pytest.raises(SystemExit, match=msg):
        _validate_params_json(p)


def test_params_validation_prefix_needs_serve():
    from edgellm_tpu.run import _validate_params_json

    with pytest.raises(SystemExit, match="experiment 'serve'"):
        _validate_params_json({"experiment": "relevance", "max_length": 64,
                               "stride": 32,
                               "prefix_cache": {"enabled": True}})


def test_soak_shared_prefix_len_validation():
    from edgellm_tpu.serve.soak import SoakConfig

    with pytest.raises(ValueError, match="shared_prefix_len"):
        SoakConfig(prompt_len=8, shared_prefix_len=9)
    assert SoakConfig(prompt_len=8, shared_prefix_len=8).shared_prefix_len \
        == 8
