"""Ring-attention sequence-parallelism tests on the spoofed CPU mesh: the
sharded-sequence forward must match the dense single-device forward for both
families, any ring size, and sequence lengths that stress the blockwise causal
mask."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.models import tiny_config, init_params, forward
from edgellm_tpu.parallel.ring import make_seq_mesh, forward_sp, ring_attention
from edgellm_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

QWEN = tiny_config("qwen2", num_layers=4, hidden_size=32, num_heads=4, vocab_size=128)
NEOX = tiny_config("gpt_neox", num_layers=3, hidden_size=32, num_heads=4, vocab_size=128)


def _dense_reference(q, k, v):
    """Naive causal attention, fp32."""
    b, s, h, hd = q.shape
    scores = np.einsum("bshd,bthd->bhst", q, k) / np.sqrt(hd)
    mask = np.tril(np.ones((s, s), bool))
    scores = np.where(mask[None, None], scores, -np.inf)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhst,bthd->bshd", p, v)


@pytest.mark.parametrize("n_ring", [2, 4, 8])
def test_ring_attention_matches_dense(rng, n_ring):
    b, s, h, hd = 2, 32, 3, 8
    q = rng.normal(size=(b, s, h, hd)).astype(np.float32)
    k = rng.normal(size=(b, s, h, hd)).astype(np.float32)
    v = rng.normal(size=(b, s, h, hd)).astype(np.float32)
    mesh = make_seq_mesh(n_ring)
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq"),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
    )
    got = np.asarray(ring(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(got, _dense_reference(q, k, v), atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("cfg", [QWEN, NEOX], ids=["qwen2", "gpt_neox"])
def test_forward_sp_matches_dense_forward(cfg):
    params = init_params(cfg, jax.random.key(2))
    ids = jnp.asarray(np.random.default_rng(8).integers(0, cfg.vocab_size, (2, 32)))
    base, _ = forward(cfg, params, ids)
    mesh = make_seq_mesh(4)
    got = forward_sp(cfg, params, ids, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base), atol=2e-4, rtol=2e-4)


def test_forward_sp_rejects_indivisible_seq():
    params = init_params(QWEN, jax.random.key(2))
    ids = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(ValueError, match="divisible"):
        forward_sp(QWEN, params, ids, make_seq_mesh(4))


def test_ring_nll_long_sequence():
    """Longer-than-window sequence across 8 devices stays finite and causal:
    perturbing a late token must not change earlier logits."""
    cfg = QWEN
    params = init_params(cfg, jax.random.key(4))
    rng = np.random.default_rng(12)
    ids = rng.integers(0, cfg.vocab_size, (1, 64))
    mesh = make_seq_mesh(8)
    out = np.asarray(forward_sp(cfg, params, jnp.asarray(ids), mesh))
    assert np.isfinite(out).all()
    ids2 = ids.copy()
    ids2[0, -1] = (ids2[0, -1] + 1) % cfg.vocab_size
    out2 = np.asarray(forward_sp(cfg, params, jnp.asarray(ids2), mesh))
    np.testing.assert_allclose(out[0, :-1], out2[0, :-1], atol=1e-5)
    assert not np.allclose(out[0, -1], out2[0, -1])


def test_stage_seq_composition_fp32_matches_dense():
    """stage=2 x seq=4 on the 8-device mesh: pipeline-split layers + ring-
    sharded sequence == the dense single-device forward (the composability
    claim in ring.py, backed by execution)."""
    from edgellm_tpu.parallel import SplitRingRuntime, make_sp_stage_mesh

    cfg = QWEN
    params = init_params(cfg, jax.random.key(3))
    ids = jnp.asarray(np.random.default_rng(9).integers(0, cfg.vocab_size, (1, 32)))
    base, _ = forward(cfg, params, ids)
    rt = SplitRingRuntime(cfg, cuts=(1,), hop_codecs=("fp32",),
                          mesh=make_sp_stage_mesh(2, 4))
    out = rt.forward(rt.place_params(params), ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               atol=2e-4, rtol=2e-4)


def test_stage_seq_composition_quantized_hop():
    """A per-token packed hop composes with ring sharding: encoding each
    sequence shard locally == the single-device simulated boundary."""
    from edgellm_tpu.codecs import per_token_affine_int8
    from edgellm_tpu.parallel import SplitRingRuntime, make_sp_stage_mesh

    cfg = QWEN
    cut = 1
    params = init_params(cfg, jax.random.key(3))
    ids = jnp.asarray(np.random.default_rng(9).integers(0, cfg.vocab_size, (1, 32)))
    rt = SplitRingRuntime(cfg, cuts=(cut,), hop_codecs=("int8_per_token",),
                          mesh=make_sp_stage_mesh(2, 4))
    out = rt.forward(rt.place_params(params), ids)

    def bfn(idx, h):
        return jnp.where(idx == cut, per_token_affine_int8(h), h)

    ref_logits, _ = forward(cfg, params, ids, boundary_fn=bfn)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               atol=2e-4, rtol=2e-4)


def test_stage_seq_rejects_non_per_token_codecs():
    from edgellm_tpu.parallel import SplitRingRuntime, make_sp_stage_mesh

    with pytest.raises(ValueError, match="per-token"):
        SplitRingRuntime(QWEN, cuts=(1,), hop_codecs=("int4_global",),
                         mesh=make_sp_stage_mesh(2, 4))


def test_long_context_ring_matches_dense_forward():
    """The long-context claim at scale: a 2048-token sequence ring-sharded over
    8 devices (256 tokens per shard) matches the dense single-device forward.
    The ring path never materializes the full S x S score matrix on one device."""
    cfg = QWEN
    params = init_params(cfg, jax.random.key(4))
    ids = jnp.asarray(np.random.default_rng(12).integers(
        0, cfg.vocab_size, (1, 2048)))
    dense, _ = forward(cfg, params, ids)
    mesh = make_seq_mesh(8)
    sharded = forward_sp(cfg, params, ids, mesh, "seq")
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(dense),
                               atol=3e-4, rtol=3e-4)
