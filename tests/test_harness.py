"""Harness tests on a micro-corpus.

Parity chain: the model forward is logits-exact vs HF (test_model_parity), the
codecs are oracle-exact vs the reference algorithms (test_codecs), so here we close
the loop by checking that the harness's cached-boundary suffix path produces the
SAME NLL as running the full forward with the codec applied via ``boundary_fn`` —
i.e. the sweep restructuring changes the FLOPs, not the math — plus the windowing
semantics (literal loop oracle) and exact checkpoint/resume.
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.models import tiny_config, init_params, forward, nll_from_logits
from edgellm_tpu.codecs import int4_token_select, channel_wise_quant
from edgellm_tpu.importance import importance_per_layer
from edgellm_tpu.eval import (
    sliding_windows,
    run_token_sweep,
    run_initial_sweep,
    run_channel_sweep,
)

CFG = tiny_config("qwen2", num_layers=5, hidden_size=32, num_heads=4, vocab_size=128)


@pytest.fixture(scope="module")
def setup():
    params = init_params(CFG, jax.random.key(7))
    corpus = np.random.default_rng(3).integers(0, CFG.vocab_size, 150)
    return params, corpus


def test_sliding_windows_matches_reference_loop():
    """Oracle: the literal header loop of Qwen2-0.5B/main.py:151-156."""
    ids = np.arange(100)
    max_length, stride = 40, 16
    want = []
    prev_end = 0
    for begin in range(0, 100, stride):
        end = min(begin + max_length, 100)
        trg_len = end - prev_end
        tgt = ids[begin:end].copy().astype(np.int64)
        if trg_len < len(tgt):
            tgt[:-trg_len] = -100
        want.append((begin, end, tgt))
        prev_end = end
        if end == 100:
            break
    got = list(sliding_windows(ids, max_length, stride))
    assert len(got) == len(want)
    for chunk, (begin, end, tgt) in zip(got, want):
        assert (chunk.begin, chunk.end) == (begin, end)
        np.testing.assert_array_equal(chunk.target_ids[0], tgt)
        assert chunk.num_loss_tokens == int((tgt != -100).sum()) - 1


def test_token_sweep_equals_full_boundary_forward(setup, tmp_path):
    """Suffix-resume math == full forward with boundary_fn, for every combo."""
    params, corpus = setup
    methods = ["regular_importance", "last_row"]
    layers, ratios = [1, 3], [0.0, 0.5, 1.0]
    res = run_token_sweep(
        CFG, params, corpus, methods=methods, layers_of_interest=layers,
        ratios=ratios, max_length=48, stride=24)

    # independent accumulation with full forwards
    want = np.zeros((2, 2, 3))
    n_tokens = 0
    for chunk in sliding_windows(corpus, 48, 24):
        ids, targets = jnp.asarray(chunk.input_ids), jnp.asarray(chunk.target_ids)
        _, aux = forward(CFG, params, ids, capture_stats=True)
        for m, method in enumerate(methods):
            imp = importance_per_layer(aux["stats"], method)
            for l, layer in enumerate(layers):
                for r, ratio in enumerate(ratios):
                    def bfn(idx, h, _imp=imp[layer, 0], _ratio=ratio, _layer=layer):
                        return jnp.where(idx == _layer,
                                         int4_token_select(h, _imp, _ratio), h)
                    logits, _ = forward(CFG, params, ids, boundary_fn=bfn)
                    want[m, l, r] += float(nll_from_logits(logits, targets)) * chunk.num_loss_tokens
        n_tokens += chunk.num_loss_tokens

    assert res.n_tokens == n_tokens
    np.testing.assert_allclose(res.total_nll, want, rtol=1e-5, atol=1e-5)
    assert np.isfinite(res.ppl()).all()


def test_ratio_zero_matches_unquantized_baseline(setup):
    params, corpus = setup
    res = run_token_sweep(
        CFG, params, corpus, methods=["regular_importance"], layers_of_interest=[2],
        ratios=[0.0], max_length=48, stride=24)
    base = 0.0
    for chunk in sliding_windows(corpus, 48, 24):
        logits, _ = forward(CFG, params, jnp.asarray(chunk.input_ids))
        base += float(nll_from_logits(logits, jnp.asarray(chunk.target_ids))) * chunk.num_loss_tokens
    np.testing.assert_allclose(res.total_nll[0, 0, 0], base, rtol=1e-5)


def test_checkpoint_resume_is_exact(setup, tmp_path):
    params, corpus = setup
    kw = dict(methods=["regular_importance"], layers_of_interest=[1],
              ratios=[0.0, 0.5], max_length=48, stride=24)
    full = run_token_sweep(CFG, params, corpus, **kw)
    ckpt = str(tmp_path / "ckpt.json")
    part = run_token_sweep(CFG, params, corpus, checkpoint_path=ckpt,
                           checkpoint_every=1, max_chunks=2, **kw)
    assert part.chunks == 2
    resumed = run_token_sweep(CFG, params, corpus, checkpoint_path=ckpt,
                              checkpoint_every=1, **kw)
    assert resumed.chunks == full.chunks
    np.testing.assert_allclose(resumed.total_nll, full.total_nll, rtol=1e-6)
    np.testing.assert_allclose(resumed.ppl(), full.ppl(), rtol=1e-6)


@pytest.mark.parametrize("driver", ["initial", "channel"])
def test_repeated_kill_resume_all_drivers(setup, tmp_path, driver):
    """The unified scaffold gives initial/channel sweeps the same exact resume
    as the token sweep: kill after every chunk, resume until done, totals match
    the uninterrupted run bit-for-bit."""
    params, corpus = setup
    if driver == "initial":
        def run(**extra):
            return run_initial_sweep(
                CFG, params, corpus, layers_of_interest=[1, "upto ratio"],
                ratios=[0, 5], max_length=48, stride=24, quant_layer=1, **extra)
    else:
        def run(**extra):
            return run_channel_sweep(
                CFG, params, corpus, methods=["channel_8", "channel_1_mean"],
                layers_of_interest=[2], max_length=48, stride=24, **extra)

    full = run()
    ckpt = str(tmp_path / "ckpt.json")
    out = run(checkpoint_path=ckpt, checkpoint_every=1, max_chunks=1)
    for _ in range(full.chunks * 2):  # one chunk per "crash"
        if out.chunks >= full.chunks:
            break
        out = run(checkpoint_path=ckpt, checkpoint_every=1,
                  max_chunks=out.chunks + 1)
    resumed = run(checkpoint_path=ckpt, checkpoint_every=1)
    assert resumed.chunks == full.chunks
    np.testing.assert_allclose(resumed.total_nll, full.total_nll, rtol=1e-6)
    # the cumulative wall clock survives resumes (monotone, not reset)
    assert resumed.wall_s >= out.wall_s


def test_channel_sweep_equals_full_boundary_forward(setup):
    params, corpus = setup
    methods, layers = ["channel_4", "channel_1_max"], [2]
    res = run_channel_sweep(CFG, params, corpus, methods=methods,
                            layers_of_interest=layers, max_length=48, stride=24)
    want = np.zeros((2, 1))
    for chunk in sliding_windows(corpus, 48, 24):
        ids, targets = jnp.asarray(chunk.input_ids), jnp.asarray(chunk.target_ids)
        for m, method in enumerate(methods):
            def bfn(idx, h, _m=method):
                return jnp.where(idx == 2, channel_wise_quant(h, _m), h)
            logits, _ = forward(CFG, params, ids, boundary_fn=bfn)
            want[m, 0] += float(nll_from_logits(logits, targets)) * chunk.num_loss_tokens
    np.testing.assert_allclose(res.total_nll, want, rtol=1e-5, atol=1e-5)


def test_initial_sweep_runs_all_ordering_variants(setup):
    params, corpus = setup
    res = run_initial_sweep(
        CFG, params, corpus,
        layers_of_interest=[1, "aggregate upto 2", "maximum aggregation", "upto ratio"],
        ratios=[0, 5, 10], max_length=48, stride=24, quant_layer=2)
    assert res.total_nll.shape == (4, 3)
    assert np.isfinite(res.ppl()).all()
    # ratio 0 column: no quantization -> identical NLL across ordering variants
    col0 = res.total_nll[:, 0]
    np.testing.assert_allclose(col0, col0[0], rtol=1e-5)
    # full-ratio quantization actually perturbs the NLL (int8 is near-lossless,
    # so only assert a nonzero perturbation, not a direction)
    assert not np.isclose(res.total_nll[0, 2], res.total_nll[0, 0], rtol=0, atol=1e-7)


def test_window_batching_is_exact(setup):
    """window_batch > 1 changes the executable, not the math: identical totals,
    including the short tail window that runs singly."""
    params, corpus = setup
    kw = dict(methods=["regular_importance", "last_row"], layers_of_interest=[1, 3],
              ratios=[0.0, 0.5, 1.0], max_length=48, stride=24)
    single = run_token_sweep(CFG, params, corpus, **kw)
    batched = run_token_sweep(CFG, params, corpus, window_batch=3, **kw)
    assert batched.chunks == single.chunks
    assert batched.n_tokens == single.n_tokens
    np.testing.assert_allclose(batched.total_nll, single.total_nll, rtol=1e-5, atol=1e-5)


def test_metrics_jsonl_written(setup, tmp_path):
    params, corpus = setup
    mpath = str(tmp_path / "metrics.jsonl")
    run_token_sweep(CFG, params, corpus, methods=["last_row"], layers_of_interest=[1],
                    ratios=[0.5], max_length=48, stride=24,
                    metrics_path=mpath, checkpoint_every=1)
    lines = [json.loads(l) for l in open(mpath)]
    assert any(rec.get("final") for rec in lines)
    assert all("ppl" in rec for rec in lines)


def test_channel_window_batching_is_exact(setup):
    """Batched channel sweep: per-window channel scales preserved -> totals
    identical to the chunk-by-chunk run."""
    params, corpus = setup
    kw = dict(methods=["channel_8", "channel_1_mean"], layers_of_interest=[2],
              max_length=48, stride=24)
    single = run_channel_sweep(CFG, params, corpus, **kw)
    batched = run_channel_sweep(CFG, params, corpus, window_batch=3, **kw)
    assert batched.chunks == single.chunks
    np.testing.assert_allclose(batched.total_nll, single.total_nll, rtol=1e-5, atol=1e-5)


def test_initial_window_batching_is_exact(setup):
    """Batched initial sweep: per-window orderings/top-rho masses preserved."""
    params, corpus = setup
    kw = dict(layers_of_interest=[1, "aggregate upto 2", "upto ratio"],
              ratios=[0, 5, 10], max_length=48, stride=24, quant_layer=1)
    single = run_initial_sweep(CFG, params, corpus, **kw)
    batched = run_initial_sweep(CFG, params, corpus, window_batch=3, **kw)
    assert batched.chunks == single.chunks
    np.testing.assert_allclose(batched.total_nll, single.total_nll, rtol=1e-5, atol=1e-5)


def test_run_with_oom_backoff():
    """RESOURCE_EXHAUSTED from the XLA runtime halves the window batch until it
    fits; other errors — including non-runtime exceptions whose message merely
    mentions memory — propagate untouched."""
    import jax
    from edgellm_tpu.eval.harness import run_with_oom_backoff

    oom = jax.errors.JaxRuntimeError  # name-matched up the MRO by is_oom_error
    calls = []

    def run(wb):
        calls.append(wb)
        if wb > 2:
            raise oom("RESOURCE_EXHAUSTED: Out of memory allocating ...")
        return "ok"

    result, wb = run_with_oom_backoff(run, 8)
    assert result == "ok" and wb == 2 and calls == [8, 4, 2]

    def always_oom(wb):
        raise oom("RESOURCE_EXHAUSTED")

    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        run_with_oom_backoff(always_oom, 4)  # min batch reached -> re-raise

    def other(wb):
        raise ValueError("boom")

    with pytest.raises(ValueError, match="boom"):
        run_with_oom_backoff(other, 8)

    def fake_oom(wb):
        # an arbitrary exception that merely *mentions* OOM must not back off
        raise RuntimeError("subprocess log said: out of memory")

    with pytest.raises(RuntimeError, match="subprocess log"):
        run_with_oom_backoff(fake_oom, 8)


class TestResumableDriver:
    """The shared resumable scaffold, unit-tested directly (the drivers cover
    it end to end; these pin the contract new drivers build on)."""

    class FakeChunk:
        def __init__(self, index):
            self.index = index

    def test_fresh_start(self, tmp_path):
        from edgellm_tpu.eval.harness import ResumableDriver

        rd = ResumableDriver(str(tmp_path / "c.json"), {"a": 1}, 2)
        assert rd.state is None and rd.chunks == 0 and rd.start_chunk == 0
        assert rd.remaining(None) is None and rd.remaining(5) == 5

    def test_advance_trigger_and_roundtrip(self, tmp_path):
        from edgellm_tpu.eval.harness import ResumableDriver

        path = str(tmp_path / "c.json")
        rd = ResumableDriver(path, {"a": 1}, checkpoint_every=3)
        group = [self.FakeChunk(0), self.FakeChunk(1)]
        assert rd.advance(group) is False  # 2 < 3
        assert rd.advance([self.FakeChunk(2)]) is True  # 3 >= 3
        rd.save({"extra": 7})
        assert rd.advance([self.FakeChunk(3)]) is False  # trigger reset

        rd2 = ResumableDriver(path, {"a": 1}, 3)
        assert rd2.state["extra"] == 7
        assert rd2.chunks == 3 and rd2.start_chunk == 3
        assert rd2.remaining(10) == 7
        # wall accumulates across resumes: the reloaded prior_wall carries the
        # first run's elapsed time (strictly positive), and wall() adds to it
        assert rd2.prior_wall > 0
        assert rd2.wall() >= rd2.prior_wall

    def test_count_override_excludes_pad_windows(self, tmp_path):
        from edgellm_tpu.eval.harness import ResumableDriver

        rd = ResumableDriver(None, {}, 2)  # no checkpoint path: save is a no-op
        padded_group = [self.FakeChunk(0), self.FakeChunk(1), self.FakeChunk(1)]
        rd.advance(padded_group, count=2)
        assert rd.chunks == 2 and rd.next_chunk == 2
        rd.save({})  # must not touch the filesystem

    def test_axes_mismatch_rejected(self, tmp_path):
        from edgellm_tpu.eval.harness import ResumableDriver

        path = str(tmp_path / "c.json")
        rd = ResumableDriver(path, {"a": 1}, 1)
        rd.advance([self.FakeChunk(0)])
        rd.save({})
        with pytest.raises(ValueError, match="different sweep configuration"):
            ResumableDriver(path, {"a": 2}, 1)
