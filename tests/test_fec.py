"""Self-healing links: in-band FEC repair, hedged hops, and the LinkHealth
SLO controller.

The load-bearing claims, each asserted here:
- ANY single corrupted byte of the FEC wire tree — every byte position of the
  chunk matrix and of the checksum words — is repaired in band: one decode,
  zero retransmissions, reconstruction bit-identical (non-finite and huge
  payload values included);
- two bad chunks in one parity group exceed XOR parity and fall through to
  the PR 2 retry ladder (the outer seal stays the authority);
- a clean link with FEC + hedging armed is bit-exact with the plain runtime,
  and a faulted build with both *disabled* traces the exact PR 2 graph
  (fingerprint identity — the no-cost-when-off contract);
- hedged routes win on drop-dominated links (hedge_wins counted);
- LinkHealth degrades on budget burn and RE-PROMOTES when the budget
  recovers, with full-window re-measure + clock dwell hysteresis (fake clock).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from edgellm_tpu.codecs.faults import FaultConfig, LinkPolicy, verify_payload
from edgellm_tpu.codecs import fec as fec_mod
from edgellm_tpu.codecs.fec import (FECConfig, HedgeConfig, LinkHealth,
                                    LinkHealthConfig, fec_decode, fec_encode)
from edgellm_tpu.codecs.faults import seal_payload
from edgellm_tpu.models import init_params, tiny_config
from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh
from edgellm_tpu.utils.clock import FakeClock

CFG = tiny_config("qwen2", num_layers=6, hidden_size=32, num_heads=4,
                  vocab_size=128)
SPLIT = SplitConfig(cuts=(2,), hop_codecs=("int8_per_token",))
FEC = FECConfig(group_size=2, n_groups=2)  # small geometry: exhaustive sweeps


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(1))


@pytest.fixture(scope="module")
def ids():
    rng = np.random.default_rng(5)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (1, 24)))


@pytest.fixture(scope="module")
def mesh():
    return make_stage_mesh(2)


def _counters(rt):
    return {k: v.tolist() for k, v in rt.link_counters().items()}


def _payload():
    return {"packed": jnp.arange(-12, 11, dtype=jnp.int8).reshape(23),
            "scale": jnp.asarray([1.5, -2.25, 3e-9], jnp.float32)}


def _tree_equal(a, b):
    """Bit-exact tree equality (byte compare — NaN == NaN by bit pattern)."""
    return all(np.asarray(x).tobytes() == np.asarray(y).tobytes()
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def _flip(wire, leaf, pos, bit=1):
    """Flip one bit of one byte of a wire-tree leaf."""
    arr = np.asarray(wire[leaf])
    raw = bytearray(arr.tobytes())
    raw[pos] ^= 1 << bit
    new = np.frombuffer(bytes(raw), arr.dtype).reshape(arr.shape)
    return dict(wire, **{leaf: jnp.asarray(new)})


# ---------- config validation ----------


def test_config_validation():
    assert FECConfig().enabled and FECConfig().n_data_chunks == 16
    with pytest.raises(ValueError):
        FECConfig(group_size=0)
    with pytest.raises(ValueError):
        FECConfig(n_groups=-1)
    with pytest.raises(ValueError):
        FECConfig(enabled="yes")
    with pytest.raises(ValueError):
        HedgeConfig(routes=1)
    with pytest.raises(ValueError):
        LinkHealthConfig(window=0)
    with pytest.raises(ValueError):
        LinkHealthConfig(error_budget=0.0)
    with pytest.raises(ValueError):  # no hysteresis band
        LinkHealthConfig(promote_burn=1.0, degrade_burn=1.0)


def test_wire_accounting_matches_encode():
    from edgellm_tpu.codecs.faults import tree_nbytes

    sealed = seal_payload(_payload())
    n = tree_nbytes(sealed)
    for cfg in (FEC, FECConfig(group_size=4, n_groups=4),
                FECConfig(group_size=1, n_groups=3)):
        wire = fec_encode(sealed, cfg)
        assert tree_nbytes(wire) == cfg.wire_nbytes(n)
        assert cfg.overhead(n) == cfg.wire_nbytes(n) / n - 1.0


# ---------- FEC codec: exhaustive repair ----------


def test_clean_roundtrip_bit_exact():
    sealed = seal_payload(_payload())
    out, bad, fixed = fec_decode(fec_encode(sealed, FEC), FEC, sealed)
    assert _tree_equal(out, sealed)
    assert not bool(bad) and not bool(fixed)
    assert bool(verify_payload(out))


def test_every_single_corrupted_byte_is_repaired_without_retry():
    """The acceptance sweep: one flipped bit at EVERY byte position of the
    wire tree (data chunks, parity chunks, checksum words) must come back
    verified and bit-identical from ONE decode — in-band repair, zero
    retransmissions involved."""
    sealed = seal_payload(_payload())
    wire = fec_encode(sealed, FEC)
    for leaf in ("chunks", "words"):
        nbytes = np.asarray(wire[leaf]).nbytes
        for pos in range(nbytes):
            for bit in (0, 7):
                out, bad, _ = fec_decode(_flip(wire, leaf, pos, bit), FEC,
                                         sealed)
                assert bool(bad), f"{leaf} byte {pos} bit {bit} undetected"
                assert _tree_equal(out, sealed), \
                    f"{leaf} byte {pos} bit {bit} not repaired"
                assert bool(verify_payload(out))


def test_nonfinite_and_huge_values_repair_bit_exact():
    """Repair is pure byte algebra: NaN/Inf/huge payloads reconstruct to the
    exact original bit patterns (a value-space repair would laundering NaNs)."""
    weird = {"x": jnp.asarray([np.nan, np.inf, -np.inf, 3.4e38, -0.0, 1e-45],
                              jnp.float32),
             "y": jnp.asarray([np.float16("nan"), np.float16(65504)],
                              jnp.float16)}
    sealed = seal_payload(weird)
    wire = fec_encode(sealed, FEC)
    for pos in range(np.asarray(wire["chunks"]).nbytes):
        out, _, _ = fec_decode(_flip(wire, "chunks", pos), FEC, sealed)
        assert _tree_equal(out, sealed), f"byte {pos} not bit-exact"
        assert bool(verify_payload(out))


def test_two_bad_chunks_same_group_falls_through():
    """XOR parity repairs one chunk per group; two in the same group must be
    left corrupted so the outer seal fails and the retry ladder takes over."""
    sealed = seal_payload(_payload())
    wire = fec_encode(sealed, FEC)
    L = np.asarray(wire["chunks"]).shape[1]
    # data chunks 0 and n_groups share group 0 (c % n_groups)
    corrupt = _flip(_flip(wire, "chunks", 0), "chunks", FEC.n_groups * L)
    out, bad, _ = fec_decode(corrupt, FEC, sealed)
    assert bool(bad)
    assert not bool(verify_payload(out))  # retry ladder's cue


def test_two_bad_chunks_different_groups_both_repaired():
    sealed = seal_payload(_payload())
    wire = fec_encode(sealed, FEC)
    L = np.asarray(wire["chunks"]).shape[1]
    # chunks 0 and 1 are adjacent -> distinct groups (burst tolerance)
    out, bad, fixed = fec_decode(_flip(_flip(wire, "chunks", 0),
                                       "chunks", L + 1), FEC, sealed)
    assert bool(bad) and bool(fixed)
    assert _tree_equal(out, sealed)


def test_dropped_wire_is_unrepairable():
    sealed = seal_payload(_payload())
    wire = jax.tree.map(jnp.zeros_like, fec_encode(sealed, FEC))
    out, bad, _ = fec_decode(wire, FEC, sealed)
    assert bool(bad)
    assert not bool(verify_payload(out))


# ---------- the healing hop on the real split runtime ----------


def test_clean_link_fec_and_hedge_bit_exact(params, ids, mesh):
    """The whole FEC + hedge machinery on a clean (but active) link changes
    NOTHING: logits bit-identical to the plain runtime, zero repair work."""
    base = SplitRuntime(CFG, SPLIT, mesh)
    out0 = np.asarray(base.forward(base.place_params(params), ids))
    rt = SplitRuntime(CFG, SPLIT, mesh,
                      faults=FaultConfig(byte_budget=10**9),
                      policy=LinkPolicy(max_retries=1),
                      fec=FECConfig(group_size=2, n_groups=2),
                      hedge=HedgeConfig(routes=2))
    out1 = rt.forward(rt.place_params(params), ids, fault_step=3)
    np.testing.assert_array_equal(out0, np.asarray(out1))
    c = _counters(rt)
    assert c["hops"] == [1] and c["detected"] == [0]
    assert c["repaired"] == [0] and c["hedge_wins"] == [0]
    assert c["retried"] == [0] and c["substituted"] == [0]


def test_counter_keys_follow_config(mesh):
    from edgellm_tpu.codecs.faults import COUNTER_KEYS, FaultyLink

    plain = FaultyLink(FaultConfig(byte_budget=1), LinkPolicy())
    assert plain.counter_keys == COUNTER_KEYS and not plain.healing
    fec_link = FaultyLink(FaultConfig(byte_budget=1), LinkPolicy(),
                          fec=FECConfig())
    assert "repaired" in fec_link.counter_keys
    assert "hedge_wins" not in fec_link.counter_keys
    both = FaultyLink(FaultConfig(byte_budget=1), LinkPolicy(),
                      fec=FECConfig(), hedge=HedgeConfig())
    assert {"repaired", "hedge_wins"} <= set(both.counter_keys)
    off = FaultyLink(FaultConfig(byte_budget=1), LinkPolicy(),
                     fec=FECConfig(enabled=False),
                     hedge=HedgeConfig(enabled=False))
    assert off.counter_keys == COUNTER_KEYS and not off.healing


def test_single_flip_repaired_in_band_with_zero_retries(params, ids, mesh,
                                                        monkeypatch):
    """Hop-level proof of the headline property: exactly one corrupted wire
    byte on the first transmission is repaired with NO retransmission — the
    retried counter stays zero and the logits stay bit-exact."""
    base = SplitRuntime(CFG, SPLIT, mesh)
    out0 = np.asarray(base.forward(base.place_params(params), ids))

    calls = []  # transmissions are statically unrolled: trace-time state works
    real_inject = fec_mod.inject_faults

    def inject_one_flip(wire, key, cfg):
        calls.append(1)
        if len(calls) == 1 and isinstance(wire, dict) and "chunks" in wire:
            flipped = wire["chunks"].at[0, 0].set(wire["chunks"][0, 0] ^ 1)
            return dict(wire, chunks=flipped)
        return real_inject(wire, key, cfg)

    monkeypatch.setattr(fec_mod, "inject_faults", inject_one_flip)
    rt = SplitRuntime(CFG, SPLIT, mesh,
                      faults=FaultConfig(byte_budget=10**9),
                      policy=LinkPolicy(max_retries=2),
                      fec=FECConfig(group_size=2, n_groups=2))
    out1 = rt.forward(rt.place_params(params), ids, fault_step=0)
    np.testing.assert_array_equal(out0, np.asarray(out1))
    c = _counters(rt)
    assert c["detected"] == [1] and c["repaired"] == [1]
    assert c["retried"] == [0] and c["recovered"] == [0]
    assert c["substituted"] == [0]


def test_double_flip_same_group_falls_to_retry(params, ids, mesh, monkeypatch):
    """Two bad chunks in one parity group on the first transmission defeat
    XOR parity: the hop must fall through to a retry and recover there."""
    base = SplitRuntime(CFG, SPLIT, mesh)
    out0 = np.asarray(base.forward(base.place_params(params), ids))

    calls = []
    real_inject = fec_mod.inject_faults
    geometry = FECConfig(group_size=2, n_groups=2)

    def inject_two_flips(wire, key, cfg):
        calls.append(1)
        if len(calls) == 1 and isinstance(wire, dict) and "chunks" in wire:
            # chunks 0 and n_groups are both in group 0
            flipped = wire["chunks"].at[0, 0].set(wire["chunks"][0, 0] ^ 1)
            g = geometry.n_groups
            flipped = flipped.at[g, 0].set(flipped[g, 0] ^ 1)
            return dict(wire, chunks=flipped)
        return real_inject(wire, key, cfg)

    monkeypatch.setattr(fec_mod, "inject_faults", inject_two_flips)
    rt = SplitRuntime(CFG, SPLIT, mesh,
                      faults=FaultConfig(byte_budget=10**9),
                      policy=LinkPolicy(max_retries=2), fec=geometry)
    out1 = rt.forward(rt.place_params(params), ids, fault_step=0)
    np.testing.assert_array_equal(out0, np.asarray(out1))  # retry recovered
    c = _counters(rt)
    assert c["detected"] == [1] and c["repaired"] == [0]
    assert c["retried"] == [1] and c["recovered"] == [1]


def test_hedge_wins_on_drop_dominated_link(params, ids, mesh):
    """Parity can't fix a drop (every chunk zeroed); a second staggered route
    can. Over seeded drops the hedged link must log wins, and seeded runs
    must reproduce exactly."""
    rt = SplitRuntime(CFG, SPLIT, mesh,
                      faults=FaultConfig(drop_rate=0.4, seed=1),
                      policy=LinkPolicy(max_retries=2),
                      hedge=HedgeConfig(routes=2))
    placed = rt.place_params(params)
    for step in range(8):
        out = rt.forward(placed, ids, fault_step=step)
    assert np.isfinite(np.asarray(out)).all()
    c = _counters(rt)
    assert c["hops"] == [8] and c["hedge_wins"][0] > 0
    assert c["detected"][0] >= c["hedge_wins"][0]

    rt2 = SplitRuntime(CFG, SPLIT, mesh,
                       faults=FaultConfig(drop_rate=0.4, seed=1),
                       policy=LinkPolicy(max_retries=2),
                       hedge=HedgeConfig(routes=2))
    placed2 = rt2.place_params(params)
    for step in range(8):
        rt2.forward(placed2, ids, fault_step=step)
    assert _counters(rt2) == c


def test_fec_repairs_bitflips_on_live_link(params, ids, mesh):
    """Seeded low-rate bitflips over many steps: the FEC link repairs some
    hops in band, and every detected hop is accounted exactly once as
    repaired-or-clean / recovered / substituted."""
    rt = SplitRuntime(CFG, SPLIT, mesh,
                      faults=FaultConfig(bitflip_rate=0.0005, seed=2),
                      policy=LinkPolicy(max_retries=3),
                      fec=FECConfig(group_size=4, n_groups=4))
    placed = rt.place_params(params)
    for step in range(16):
        out = rt.forward(placed, ids, fault_step=step)
    assert np.isfinite(np.asarray(out)).all()
    c = _counters(rt)
    assert c["hops"] == [16]
    assert c["detected"][0] > 0 and c["repaired"][0] > 0
    assert c["repaired"][0] <= c["detected"][0]
    # hops that needed MORE than in-band repair either recovered via retry or
    # were substituted; none may be silently dropped
    assert c["retried"][0] >= c["recovered"][0]


def test_disabled_fec_fingerprint_identical_to_pre_feature_graph(params, ids,
                                                                 mesh):
    """The no-cost-when-off contract: a faulted build with FEC and hedging
    disabled hashes to the EXACT same jaxpr as a build that never heard of
    fec.py (same check graphlint enforces in CI)."""
    from edgellm_tpu.lint.contracts import graph_fingerprint

    faults = FaultConfig(bitflip_rate=0.01, seed=0)
    policy = LinkPolicy(max_retries=1)
    rt_pre = SplitRuntime(CFG, SPLIT, mesh, faults=faults, policy=policy)
    rt_off = SplitRuntime(CFG, SPLIT, mesh, faults=faults, policy=policy,
                          fec=FECConfig(enabled=False),
                          hedge=HedgeConfig(enabled=False))
    placed = rt_pre.place_params(params)
    imps = jnp.zeros((1, ids.shape[1]), jnp.float32)
    step = jnp.asarray(0, jnp.int32)
    fp_pre = graph_fingerprint(rt_pre._forward, placed, ids, imps, step)
    fp_off = graph_fingerprint(rt_off._forward, placed, ids, imps, step)
    assert fp_pre == fp_off
    # and an ENABLED build must differ (the identity test has teeth)
    rt_on = SplitRuntime(CFG, SPLIT, mesh, faults=faults, policy=policy,
                         fec=FECConfig(group_size=2, n_groups=2))
    assert graph_fingerprint(rt_on._forward, placed, ids, imps, step) != fp_pre


# ---------- LinkHealth SLO controller ----------


def _obs(hops=4, detected=0, repaired=0, retried=0):
    return {"hops": [hops], "detected": [detected], "repaired": [repaired],
            "retried": [retried]}


def test_link_health_degrades_on_burn_and_repromotes():
    clk = FakeClock()
    lh = LinkHealth(3, LinkHealthConfig(window=4, error_budget=0.1,
                                        degrade_burn=1.0, promote_burn=0.25),
                    clock=clk)
    # burn = unrepaired corruption rate / budget: 2/4 hops corrupted = 5x
    for _ in range(3):
        assert lh.observe(_obs(detected=2)) == 0  # window not full yet
    assert lh.observe(_obs(detected=2)) == 1      # full window, burn 5 >= 1
    assert len(lh._window) == 0                   # full re-measure at tier 1
    # tier 1 still burning -> degrade to the floor
    for _ in range(3):
        assert lh.observe(_obs(detected=2)) == 1
    assert lh.observe(_obs(detected=2)) == 2
    assert lh.observe(_obs(detected=2)) == 2      # floor holds
    # budget recovers -> re-promote one tier per full clean window
    for _ in range(4):
        lh.observe(_obs())
    assert lh.tier == 1
    for _ in range(4):
        lh.observe(_obs())
    assert lh.tier == 0 and lh.switches == 4


def test_link_health_repair_discounts_burn():
    """In-band repaired corruption does NOT burn the budget — only the
    unrepaired remainder does."""
    lh = LinkHealth(2, LinkHealthConfig(window=4, error_budget=0.1))
    for _ in range(8):
        lh.observe(_obs(detected=2, repaired=2))
    assert lh.tier == 0 and lh.burn_rate == 0.0
    assert lh.repair_rate == 1.0 and lh.corruption_rate == 0.5


def test_link_health_dwell_hysteresis_under_fake_clock():
    """min_dwell_s is a wall-clock floor between switches: a clean window
    inside the dwell may NOT re-promote; after the dwell it must."""
    clk = FakeClock()
    lh = LinkHealth(2, LinkHealthConfig(window=2, error_budget=0.1,
                                        min_dwell_s=10.0), clock=clk)
    lh.observe(_obs(detected=2))
    assert lh.observe(_obs(detected=2)) == 1      # degrade at t=0
    for _ in range(6):                            # clean, but inside dwell
        assert lh.observe(_obs()) == 1
    clk.set_time(9.9)
    assert lh.observe(_obs()) == 1                # still inside
    clk.set_time(10.0)
    assert lh.observe(_obs()) == 0                # dwell elapsed -> promote
    # and the switch re-arms the dwell: an immediately-burning window cannot
    # flap back down before t=20
    lh.observe(_obs(detected=4))
    assert lh.observe(_obs(detected=4)) == 0
    clk.set_time(20.0)
    lh.observe(_obs(detected=4))
    assert lh.observe(_obs(detected=4)) == 1


def test_link_health_summary_shape():
    lh = LinkHealth(2, LinkHealthConfig(window=2))
    lh.observe(_obs(detected=1, repaired=1, retried=1))
    s = lh.summary()
    assert {"tier", "switches", "observations", "window", "error_budget",
            "burn_rate", "corruption_rate", "repair_rate", "retry_rate",
            "hedge_win_rate"} <= set(s)
    assert s["observations"] == 1 and s["tier"] == 0


# ---------- eval + CLI integration ----------


def test_split_eval_healing_requires_enabled_faults(params):
    from edgellm_tpu.eval.split_eval import run_split_eval

    toks = np.random.default_rng(0).integers(0, CFG.vocab_size, (256,))
    kw = dict(cuts=(2,), hop_codecs=["int8_per_token"], max_length=64,
              stride=32, time_hops=False)
    with pytest.raises(ValueError, match="enabled faults"):
        run_split_eval(CFG, params, toks, fec={"group_size": 2}, **kw)
    with pytest.raises(ValueError, match="enabled faults"):
        run_split_eval(CFG, params, toks, hedge={"routes": 2}, **kw)
    with pytest.raises(ValueError, match="enabled faults"):
        run_split_eval(CFG, params, toks, link_health={"window": 2}, **kw)


def test_split_eval_full_healing_ladder(params):
    """The chaos-config shape end to end: faults + retries + FEC + hedge +
    LinkHealth over the tier ladder, with the health blocks in the result."""
    from edgellm_tpu.eval.split_eval import run_split_eval

    toks = np.random.default_rng(0).integers(0, CFG.vocab_size, (1024,))
    res = run_split_eval(
        CFG, params, toks, cuts=(2,), hop_codecs=["int8_per_token"],
        max_length=64, stride=32, time_hops=False,
        faults={"bitflip_rate": 0.002, "drop_rate": 0.1, "seed": 0},
        link_policy={"max_retries": 2,
                     "tiers": ["int4_per_token", "ternary_per_token"]},
        fec={"group_size": 2, "n_groups": 2}, hedge={"routes": 2},
        link_health={"window": 2, "error_budget": 0.05})
    assert np.isfinite(res["ppl"])
    c = res["link_counters"]
    assert c["detected"][0] > 0
    assert "repaired" in c and "hedge_wins" in c
    assert res["fec"]["group_size"] == 2 and res["hedge"]["routes"] == 2
    assert res["link_health"]["observations"] == res["chunks"]
    assert res["final_tier"] == res["link_health"]["tier"]


def test_run_fault_sweep_passes_healing_only_to_faulted_points(params):
    from edgellm_tpu.eval.split_eval import run_fault_sweep, run_split_eval

    toks = np.random.default_rng(0).integers(0, CFG.vocab_size, (512,))
    kw = dict(cuts=(2,), hop_codecs=["int8_per_token"], max_length=64,
              stride=32, time_hops=False)
    base = run_split_eval(CFG, params, toks, **kw)
    sweep = run_fault_sweep(CFG, params, toks, rates=[0.0, 0.3],
                            knob="drop_rate", link_policy={"max_retries": 2},
                            hedge={"routes": 2}, **kw)
    # rate 0: healing kwargs withheld, the exact fault-free baseline
    assert sweep[0]["ppl"] == base["ppl"]
    assert "link_counters" not in sweep[0]
    assert sweep[1]["link_counters"]["hedge_wins"][0] >= 0
    assert sweep[1]["hedge"]["routes"] == 2


def test_params_json_validates_healing_keys(tmp_path):
    """run.py must die fast, naming the bad key, before any model loads."""
    import json

    from edgellm_tpu.run import main

    def run_with(body):
        p = tmp_path / "params.json"
        p.write_text(json.dumps(body))
        return main(["--params", str(p), "--model", "qwen2-0.5b"])

    split = {"experiment": "split", "cuts": [2],
             "hop_codecs": ["int8_per_token"], "max_length": 64, "stride": 32,
             "faults": {"drop_rate": 0.1}}
    with pytest.raises(SystemExit, match="fec"):
        run_with({**split, "fec": {"group_sizes": 4}})  # typo'd field
    with pytest.raises(SystemExit, match="hedge"):
        run_with({**split, "hedge": {"routes": 1}})  # constructor rejects
    with pytest.raises(SystemExit, match="link_health"):
        run_with({**split, "link_health": ["not", "a", "dict"]})
    with pytest.raises(SystemExit, match="faults"):
        run_with({**split, "faults": {}, "fec": {"group_size": 4}})
    with pytest.raises(SystemExit, match="split"):  # split-only keys
        run_with({"ratios": [0], "layers_of_interest": [1], "max_length": 64,
                  "stride": 32, "methods": ["last_row"],
                  "fec": {"group_size": 4}})


def test_fault_report_prints_counters_and_health(capsys):
    from edgellm_tpu.run import _print_fault_report

    _print_fault_report({
        "link_counters": {"hops": [4, 4], "detected": [2, 1],
                          "repaired": [1, 1], "retried": [1, 0],
                          "hedge_wins": [0, 1], "substituted": [1, 0]},
        "tier_switches": [[3, 1], [9, 0]],
        "link_health": {"tier": 0, "burn_rate": 0.5, "corruption_rate": 0.375,
                        "repair_rate": 0.667, "retry_rate": 0.125,
                        "hedge_win_rate": 0.125, "error_budget": 0.05,
                        "observations": 12, "switches": 2, "window": 2},
    })
    out = capsys.readouterr().out
    # one unified obs-registry table: per-hop counters, totals, health gauges
    assert "edgellm_link_detected_total" in out
    assert "edgellm_link_repaired_total" in out
    assert "edgellm_link_hedge_wins_total" in out
    assert 'hop="0"' in out and 'hop="1"' in out and 'hop="total"' in out
    assert "edgellm_link_health_burn_rate" in out
    _print_fault_report({})
    assert "no link counters" in capsys.readouterr().out
