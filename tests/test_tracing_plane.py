"""Request-scoped tracing plane coverage: TraceContext propagation (nesting,
thread isolation, span-arg merge), the failure flight recorder (bounded ring,
CRC-framed artifacts, exactly-one dump per failure instance, FakeClock
determinism), the live telemetry endpoint (all four routes plus 404 over real
HTTP), EG007 name-vocabulary lint, Prometheus exposition hardening (label /
HELP escaping round-tripped through a strict parser, one ``# HELP``/``# TYPE``
per family), ServeFront submit-side thread safety, per-cut boundary-hop
attribution spans out of ``generate_split``, and the run.py wiring for the
new ``obs_port`` / ``flight_recorder`` params fields and ``--trace-report``.
"""
import json
import re
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from edgellm_tpu import obs
from edgellm_tpu.obs import context as obs_context
from edgellm_tpu.obs.flight import (FlightArtifactError, FlightRecorder,
                                    configure_flight, flight_dump_for,
                                    load_flight)
from edgellm_tpu.obs.metrics import MetricsRegistry
from edgellm_tpu.obs.server import ObsServer
from edgellm_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_obs():
    """Never leak armed process-global obs state across tests."""
    yield
    obs.disable()
    obs.get_registry().clear()
    obs.get_tracer().clear()


# ---------------------------------------------------------------------------
# TraceContext propagation
# ---------------------------------------------------------------------------


def test_bind_nesting_inherits_and_restores():
    assert obs_context.current() is None
    with obs_context.bind(rid="r1") as outer:
        assert outer.labels() == {"rid": "r1"}
        with obs_context.bind(spec_burst=3, slot=0) as inner:
            # refinement inherits the enclosing rid
            assert inner.labels() == {"rid": "r1", "slot": 0,
                                      "spec_burst": 3}
            with obs_context.bind(rid="r2"):
                assert obs_context.current().rid == "r2"
            assert obs_context.current().rid == "r1"
        assert obs_context.current_labels() == {"rid": "r1"}
    assert obs_context.current() is None
    assert obs_context.current_labels() == {}


def test_context_merges_into_spans_and_explicit_kwargs_win():
    obs.enable(obs.ObservabilityConfig())
    with obs_context.bind(rid="r9", slot=3):
        with obs.span("serve.submit", slot=7, priority=1):
            pass
    with obs.span("serve.execute"):  # outside any bind: no context args
        pass
    events = {e["name"]: e for e in
              obs.get_tracer().to_chrome_trace()["traceEvents"]}
    assert events["serve.submit"]["args"] == {"rid": "r9", "slot": 7,
                                              "priority": 1}
    assert "rid" not in events["serve.execute"].get("args", {})


def test_context_is_isolated_per_thread():
    obs.enable(obs.ObservabilityConfig())
    seen = {}

    def worker(rid):
        with obs_context.bind(rid=rid):
            with obs.span("serve.execute"):
                seen[rid] = obs_context.current().rid

    ts = [threading.Thread(target=worker, args=(f"r{i}",)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert seen == {f"r{i}": f"r{i}" for i in range(4)}
    rids = sorted(e["args"]["rid"] for e in
                  obs.get_tracer().to_chrome_trace()["traceEvents"]
                  if e["name"] == "serve.execute")
    assert rids == [f"r{i}" for i in range(4)]


def test_next_rid_unique():
    a, b = obs_context.next_rid(), obs_context.next_rid()
    assert a != b and a.startswith("r") and b.startswith("r")


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_ring_bounded_and_artifact_round_trip(tmp_path):
    rec = FlightRecorder(str(tmp_path), capacity=4)
    configure_flight(rec)
    try:
        obs.enable(obs.ObservabilityConfig())
        for i in range(9):  # tracer sink feeds the ring; ring keeps last 4
            with obs.span("serve.execute", i=i):
                pass
        rec.note_request("r1", priority=1, prompt=8)
        rec.note_request("r2", priority=0, prompt=4)
        rec.end_request("r2")
        rec.note_counters("link", {"retried": [2], "repaired": 1})
        path = rec.dump("manual", failure=None, note="hello")
        art = load_flight(path)
    finally:
        configure_flight(None)
    assert art["reason"] == "manual" and art["note"] == "hello"
    assert [e["args"]["i"] for e in art["spans"]] == [5, 6, 7, 8]
    assert art["active_requests"] == {"r1": {"priority": 1, "prompt": 8}}
    assert art["counters"] == [
        {"kind": "link", "delta": {"retried": [2], "repaired": 1}, "t": None}]
    assert art["seq"] == 1
    # the dump itself rode the enabled registry
    assert obs.get_registry().counter(
        "edgellm_flight_dumps_total").value(reason="manual") == 1.0


def test_flight_dump_exactly_once_per_failure_instance(tmp_path):
    from edgellm_tpu.serve.recovery import DecodeTimeout

    rec = FlightRecorder(str(tmp_path))
    configure_flight(rec)
    try:
        exc = DecodeTimeout("boom")
        first = flight_dump_for(exc, where="raise_site")
        # every catch site may also call dump_for; the instance latch absorbs
        assert flight_dump_for(exc, where="catch_site") is None
        assert flight_dump_for(exc) is None
        other = flight_dump_for(DecodeTimeout("boom 2"))
        assert rec.dumps() == [first, other]
    finally:
        configure_flight(None)


def test_flight_dump_is_noop_without_recorder():
    assert flight_dump_for(RuntimeError("nobody listening")) is None


def test_flight_artifact_corruption_detected(tmp_path):
    rec = FlightRecorder(str(tmp_path))
    path = rec.dump("corruption_probe")
    data = bytearray(open(path, "rb").read())
    load_flight(path)  # sanity: pristine artifact reads back

    flipped = tmp_path / "flipped.bin"
    data2 = bytearray(data)
    data2[-1] ^= 0xFF  # payload bit-flip -> CRC mismatch
    flipped.write_bytes(bytes(data2))
    with pytest.raises(FlightArtifactError, match="CRC"):
        load_flight(str(flipped))

    truncated = tmp_path / "truncated.bin"
    truncated.write_bytes(bytes(data[:len(data) - 5]))
    with pytest.raises(FlightArtifactError, match="truncated"):
        load_flight(str(truncated))

    badmagic = tmp_path / "badmagic.bin"
    data3 = bytearray(data)
    data3[0:4] = b"NOPE"
    badmagic.write_bytes(bytes(data3))
    with pytest.raises(FlightArtifactError, match="magic"):
        load_flight(str(badmagic))


def _timeout_scenario(out_dir):
    """One injected watchdog timeout on a FakeClock; returns the artifact."""
    from edgellm_tpu.serve.recovery import DecodeTimeout, Watchdog

    clock = FakeClock()
    rec = FlightRecorder(str(out_dir), clock=clock)
    configure_flight(rec)
    try:
        wd = Watchdog(1.0, clock=clock)
        wd.arm()
        clock.advance(2.5)
        with pytest.raises(DecodeTimeout) as ei:
            wd.check(what="test chunk")
        # the raise site dumped; the catch site's dump_for is a no-op
        assert flight_dump_for(ei.value) is None
        (path,) = rec.dumps()
        return load_flight(path)
    finally:
        configure_flight(None)


def test_watchdog_timeout_dumps_once_and_deterministically(tmp_path):
    """The acceptance criterion: one injected DecodeTimeout -> exactly one
    artifact, and with a FakeClock the payload is bit-stable across runs."""
    a = _timeout_scenario(tmp_path / "a")
    b = _timeout_scenario(tmp_path / "b")
    assert a["failure"]["type"] == "DecodeTimeout"
    assert a["what"] == "test chunk"
    assert a["deadline_s"] == 1.0 and a["elapsed_s"] == 2.5
    assert a["t"] == 2.5  # recorder rode the same fake clock
    assert a == b


# ---------------------------------------------------------------------------
# live telemetry endpoint
# ---------------------------------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


def test_obs_server_endpoints_and_404(tmp_path):
    obs.enable(obs.ObservabilityConfig())
    rec = FlightRecorder(str(tmp_path))
    configure_flight(rec)  # sink installed before the span closes
    obs.get_registry().counter("serve_requests_total",
                               "terminal serve outcomes").inc(
                                   outcome="completed")
    with obs.span("serve.submit"):
        pass
    srv = ObsServer(0, health_fn=lambda: {"status": "ok", "queue_depth": 0})
    try:
        port = srv.start()
        assert srv.port == port and srv.url.endswith(str(port))
        base = f"http://127.0.0.1:{port}"

        status, ctype, body = _get(base + "/metrics")
        assert status == 200 and ctype.startswith("text/plain")
        assert "serve_requests_total" in body.decode()

        status, ctype, body = _get(base + "/healthz")
        assert status == 200 and ctype == "application/json"
        assert json.loads(body) == {"status": "ok", "queue_depth": 0}

        status, _, body = _get(base + "/snapshot.json")
        snap = json.loads(body)
        assert "serve_requests_total" in snap["metrics"]
        assert [e["name"] for e in snap["flight"]["spans"]] == \
            ["serve.submit"]

        status, _, body = _get(base + "/trace")
        trace = json.loads(body)
        assert {e["name"] for e in trace["traceEvents"]} == {"serve.submit"}

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/nope")
        assert ei.value.code == 404

        # the scrapes themselves were metered
        assert obs.get_registry().counter(
            "edgellm_obs_scrapes_total").value(endpoint="metrics") == 1.0
    finally:
        srv.stop()
        configure_flight(None)
    assert srv.port is None  # stop() released the socket


def test_healthz_survives_broken_provider():
    def broken():
        raise RuntimeError("provider exploded")

    srv = ObsServer(0, health_fn=broken)
    try:
        port = srv.start()
        status, _, body = _get(f"http://127.0.0.1:{port}/healthz")
        health = json.loads(body)
        assert status == 200 and health["status"] == "error"
        assert "provider exploded" in health["error"]
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# EG007: the metric/span name vocabulary
# ---------------------------------------------------------------------------


def _eg007(src):
    from edgellm_tpu.lint.ast_rules import lint_source

    return [f for f in lint_source(src, "t.py") if f.rule == "EG007"]


def test_eg007_flags_unregistered_literal_names():
    src = (
        "from edgellm_tpu.obs.tracing import span as obs_span\n"
        "from edgellm_tpu.obs.metrics import Counter, get_registry\n\n"
        "def f(reg):\n"
        "    reg.counter('edgellm_bogus_total').inc()\n"
        "    Counter('also_bogus')\n"
        "    with obs_span('serve.submitz'):\n"
        "        pass\n")
    findings = _eg007(src)
    assert len(findings) == 3
    assert all("registered vocabulary" in f.message for f in findings)


def test_eg007_accepts_registered_names_templates_and_dynamic():
    src = (
        "from edgellm_tpu.obs.tracing import span as obs_span\n\n"
        "def f(reg, k, name):\n"
        "    reg.counter('edgellm_wire_bytes_total').inc()\n"
        "    reg.counter(f'edgellm_link_{k}_total').inc()\n"
        "    reg.histogram('serve_ttft_s')\n"
        "    with obs_span('split.hop'):\n"
        "        pass\n"
        "    with obs_span(name):\n"  # dynamic: out of scope
        "        pass\n")
    assert _eg007(src) == []


def test_eg007_fstring_must_match_template_exactly():
    src = ("def f(reg, k):\n"
           "    reg.counter(f'edgellm_link_{k}z_total').inc()\n")
    (finding,) = _eg007(src)
    assert "edgellm_link_*z_total" in finding.message


def test_eg007_suppression_comment():
    src = ("def f(reg):\n"
           "    reg.counter('oneoff_debug')  # graphlint: disable=EG007\n")
    assert _eg007(src) == []


def test_shipped_package_uses_only_registered_names():
    """Every literal call site in the package draws from obs/names.py —
    the vocabulary table cannot drift from the code."""
    import os

    import edgellm_tpu
    from edgellm_tpu.lint.ast_rules import iter_package_files, lint_paths

    pkg_root = os.path.dirname(os.path.abspath(edgellm_tpu.__file__))
    findings = [f for f in lint_paths(iter_package_files(pkg_root))
                if f.rule == "EG007"]
    assert findings == [], [f.format() for f in findings]


# ---------------------------------------------------------------------------
# Prometheus exposition hardening
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:\\.|[^"\\])*)"')


def _unescape(v):
    out, i = [], 0
    while i < len(v):
        if v[i] == "\\" and i + 1 < len(v):
            out.append({"\\": "\\", '"': '"', "n": "\n"}[v[i + 1]])
            i += 2
        else:
            out.append(v[i])
            i += 1
    return "".join(out)


def _strict_parse(text):
    """A strict text-exposition parser: every line must be a valid HELP /
    TYPE / sample line; returns (samples, helps, types)."""
    samples, helps, types = [], {}, {}
    assert text.endswith("\n")
    for line in text.splitlines():
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            assert name not in helps, f"duplicate HELP for {name}"
            helps[name] = _unescape(help_text)
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            assert name not in types, f"duplicate TYPE for {name}"
            assert kind in ("counter", "gauge", "histogram", "untyped")
            types[name] = kind
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"unparseable sample line: {line!r}"
            labels = {}
            if m.group(2):
                consumed = _LABEL_RE.sub("", m.group(2)).replace(",", "")
                assert consumed == "", f"bad label syntax: {m.group(2)!r}"
                labels = {k: _unescape(v)
                          for k, v in _LABEL_RE.findall(m.group(2))}
            samples.append((m.group(1), labels, float(m.group(3))))
    return samples, helps, types


def test_prometheus_escaping_round_trips_through_strict_parser():
    reg = MetricsRegistry(enabled=True)
    nasty = 'a"b\\c\nd'
    help_text = 'help with \\ backslash\nand "quotes"'
    reg.counter("serve_requests_total", help_text).inc(2, outcome=nasty)
    text = reg.to_prometheus()
    samples, helps, types = _strict_parse(text)
    assert samples == [("serve_requests_total", {"outcome": nasty}, 2.0)]
    assert helps["serve_requests_total"] == help_text
    assert types["serve_requests_total"] == "counter"
    # the raw exposition never contains an unescaped newline mid-line
    assert nasty not in text


def test_prometheus_help_and_type_once_per_family():
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("edgellm_wire_bytes_total", "bytes moved")
    for hop in range(3):
        c.inc(10, hop=hop, kind="decode")
    reg.histogram("serve_ttft_s", "submit -> first token").observe(0.01)
    text = reg.to_prometheus()
    for fam in ("edgellm_wire_bytes_total", "serve_ttft_s"):
        assert text.count(f"# HELP {fam} ") == 1
        assert text.count(f"# TYPE {fam} ") == 1
    samples, _, types = _strict_parse(text)
    assert types["serve_ttft_s"] == "histogram"
    buckets = [s for s in samples if s[0] == "serve_ttft_s_bucket"]
    assert buckets and buckets[-1][1]["le"] == "+Inf"
    assert len([s for s in samples
                if s[0] == "edgellm_wire_bytes_total"]) == 3


# ---------------------------------------------------------------------------
# ServeFront submit-side thread safety
# ---------------------------------------------------------------------------


def _tiny_front():
    import jax
    from edgellm_tpu.models import init_params, tiny_config
    from edgellm_tpu.serve.frontend import ServeFront

    cfg = tiny_config("qwen2", num_layers=2, hidden_size=32, num_heads=4,
                      vocab_size=128)
    params = init_params(cfg, jax.random.key(1))
    return cfg, params, ServeFront(cfg, params, clock=FakeClock())


def test_serve_front_concurrent_submit_is_thread_safe():
    """8 threads x 6 submits: every request id minted exactly once, every
    submission queued exactly once, and every serve.submit span carries its
    own request's rid — no torn heap, no duplicate ids, no cross-labels."""
    from edgellm_tpu.serve.frontend import Request

    obs.enable(obs.ObservabilityConfig())
    cfg, params, front = _tiny_front()
    n_threads, per_thread = 8, 6
    rids, errors = [], []
    lock = threading.Lock()
    start = threading.Barrier(n_threads)

    def worker():
        try:
            start.wait(timeout=10)
            for _ in range(per_thread):
                rid = front.submit(Request(
                    prompt_ids=np.ones((4,), np.int32),
                    max_new_tokens=2))
                with lock:
                    rids.append(rid)
        except Exception as e:  # pragma: no cover - the assert reports it
            with lock:
                errors.append(e)

    ts = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    total = n_threads * per_thread
    assert errors == []
    assert sorted(rids) == list(range(1, total + 1))
    assert len(front._queue) == total  # all admitted (no deadline, depth ok)
    spans = [e for e in obs.get_tracer().to_chrome_trace()["traceEvents"]
             if e["name"] == "serve.submit"]
    assert sorted(e["args"]["rid"] for e in spans) == \
        sorted(f"r{i}" for i in range(1, total + 1))
    # drain stays single-threaded by contract; the queue built under
    # contention must still execute cleanly end to end
    records = front.drain(max_requests=4)
    assert [r.outcome for r in records] == ["completed"] * 4


def test_registry_concurrent_inc_is_exact():
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("edgellm_decode_steps_total")
    n_threads, per_thread = 8, 500

    def worker():
        for _ in range(per_thread):
            c.inc()

    ts = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value() == n_threads * per_thread


# ---------------------------------------------------------------------------
# boundary-hop attribution
# ---------------------------------------------------------------------------


def _tiny_split_rt():
    import jax
    from edgellm_tpu.models import init_params, tiny_config
    from edgellm_tpu.parallel.split import (SplitConfig, SplitRuntime,
                                            make_stage_mesh)

    cfg = tiny_config("qwen2", num_layers=6, hidden_size=32, num_heads=4,
                      vocab_size=128)
    params = init_params(cfg, jax.random.key(1))
    rt = SplitRuntime(cfg, SplitConfig(cuts=(1,),
                                       hop_codecs=("int8_per_token",)),
                      make_stage_mesh(2))
    return cfg, params, rt


def test_hop_attribution_rows_and_ladder_severity():
    _, _, rt = _tiny_split_rt()
    (row,) = rt.hop_attribution(None, [120.0])
    assert row == {"hop": 0, "cut": 1, "codec": "int8_per_token",
                   "wire_bytes": 120.0, "outcome": "clean"}
    # worst-wins severity order
    assert rt.hop_attribution({"substituted": [1], "retried": [9]},
                              None)[0]["outcome"] == "substituted"
    assert rt.hop_attribution({"hedge_wins": [1], "repaired": [2]},
                              None)[0]["outcome"] == "hedged"
    assert rt.hop_attribution({"retried": [1]}, None,
                              link_tier=2)[0]["outcome"] == "retried"
    assert rt.hop_attribution({"repaired": [3]},
                              None)[0]["outcome"] == "repaired"
    assert rt.hop_attribution(None, None,
                              link_tier=1)[0]["outcome"] == "degraded"


def test_generate_split_emits_request_labelled_hop_spans():
    """The tentpole acceptance shape: a traced split decode emits one
    ``split.hop`` span per cut carrying {cut layer, codec, wire bytes,
    ladder outcome, µ-batch count} plus the ambient request id."""
    import jax.numpy as jnp
    from edgellm_tpu.serve.decode import generate_split

    cfg, params, rt = _tiny_split_rt()
    obs.enable(obs.ObservabilityConfig())
    ids = jnp.ones((1, 4), jnp.int32)
    with obs_context.bind(rid="r77"):
        generate_split(rt, rt.place_params(params), ids, 4, capacity=16)
    (hop,) = [e for e in obs.get_tracer().to_chrome_trace()["traceEvents"]
              if e["name"] == "split.hop"]
    args = hop["args"]
    assert args["rid"] == "r77"
    assert args["hop"] == 0 and args["cut"] == 1
    assert args["codec"] == "int8_per_token"
    assert args["wire_bytes"] > 0
    assert args["outcome"] == "clean"
    assert args["microbatches"] == 1


# ---------------------------------------------------------------------------
# run.py wiring: new params fields, --obs-port, --trace-report
# ---------------------------------------------------------------------------


def test_run_params_tracing_plane_field_validation(tmp_path):
    from edgellm_tpu.run import main

    def run_with(ob):
        p = tmp_path / "params.json"
        p.write_text(json.dumps({"observability": ob}))
        main(["--params", str(p), "--model", "tiny-qwen2"])

    with pytest.raises(SystemExit,
                       match=r"obs_port must be null or an integer"):
        run_with({"obs_port": 70000})
    with pytest.raises(SystemExit,
                       match=r"obs_port must be null or an integer"):
        run_with({"obs_port": True})
    with pytest.raises(SystemExit,
                       match=r"flight_recorder must be a boolean or a "
                             r"directory path"):
        run_with({"flight_recorder": 3})


def test_run_serve_trace_report_and_obs_port_e2e(tmp_path, capsys):
    """--trace-report + --obs-port 0 on the serve soak: the endpoint line is
    printed, and the report groups spans per request with hop attribution
    riding the split hops."""
    from edgellm_tpu.run import main

    p = tmp_path / "params.json"
    p.write_text(json.dumps({
        "experiment": "serve", "cuts": [1],
        "hop_codecs": ["int8_per_token"],
        "serving": {"soak": {"n_requests": 2, "prompt_len": 8,
                             "max_new_tokens": 4}}}))
    try:
        assert main(["--params", str(p), "--model", "tiny-qwen2",
                     "--output-dir", str(tmp_path / "out"),
                     "--obs-port", "0", "--trace-report"]) in (0, None)
    finally:
        obs.disable()
    out = capsys.readouterr().out
    assert "obs endpoint -> http://127.0.0.1:" in out
    assert "trace report: 2 request(s)" in out
    assert "  r1:" in out and "  r2:" in out
    assert "serve.execute" in out
    assert "cut=1 codec=int8_per_token" in out
    assert "outcome=clean" in out
