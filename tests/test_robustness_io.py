"""Satellite robustness: params.json schema validation (fail fast, name the
bad key), safetensors integrity verification (corrupt files rejected before
any tensor is materialized), and bounded-retry fetches (exponential backoff,
atomic dest write, actionable terminal errors).
"""
import json
import struct
import urllib.error

import numpy as np
import pytest

from edgellm_tpu.models.hf_loader import fetch_with_retry
from edgellm_tpu.models.safetensors_io import (read_safetensors,
                                               verify_safetensors_integrity)
from edgellm_tpu.run import _validate_params_json
from tests.test_safetensors_io import write_safetensors

# ---------- params.json schema validation ----------

SPLIT_OK = {"experiment": "split", "max_length": 64, "stride": 32,
            "cuts": [2], "hop_codecs": ["int8_per_token"]}


def test_all_shipped_configs_validate():
    import glob
    import os
    cfg_dir = os.path.join(os.path.dirname(__file__), "..", "configs")
    paths = sorted(glob.glob(os.path.join(cfg_dir, "*.json")))
    assert paths
    for p in paths:
        with open(p) as f:
            _validate_params_json(json.load(f))  # must not raise


def test_valid_split_params_pass():
    _validate_params_json(dict(SPLIT_OK))
    _validate_params_json(dict(SPLIT_OK, faults={"drop_rate": 0.1},
                               link_policy={"max_retries": 1,
                                            "tiers": ["int4_per_token"]}))


@pytest.mark.parametrize("mutate,needle", [
    (lambda p: p.update(hop_codex=["int8_per_token"]), "hop_codex"),
    (lambda p: p.update(experiment="tachyon"), "tachyon"),
    (lambda p: p.pop("cuts"), "cuts"),
    (lambda p: p.update(hop_codecs=["int8_per_token", "fp32"]), "hop_codecs"),
    (lambda p: p.update(hop_codecs=["warp_drive"]), "warp_drive"),
    (lambda p: p.update(faults={"drop_rat": 0.1}), "drop_rat"),
    (lambda p: p.update(faults={"drop_rate": 2.0}), "drop_rate"),
    (lambda p: p.update(link_policy={"tiers": ["unobtainium"]}),
     "unobtainium"),
    (lambda p: p.update(link_policy={"max_retries": "two"}), "max_retries"),
    (lambda p: p.update(max_length=-5), "max_length"),
    (lambda p: p.update(cuts="2"), "cuts"),
])
def test_bad_split_params_die_naming_the_problem(mutate, needle):
    p = {k: (list(v) if isinstance(v, list) else v)
         for k, v in SPLIT_OK.items()}
    mutate(p)
    with pytest.raises(SystemExit, match=needle):
        _validate_params_json(p)


def test_faults_outside_split_experiment_die():
    with pytest.raises(SystemExit, match="split"):
        _validate_params_json({"experiment": "last_row", "max_length": 64,
                               "stride": 32, "faults": {"drop_rate": 0.1}})


# ---------- safetensors integrity ----------


@pytest.fixture
def good_st(tmp_path):
    path = str(tmp_path / "m.safetensors")
    write_safetensors(path, {
        "a": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b": np.arange(5, dtype=np.int32)})
    return path


def test_verify_good_file(good_st):
    info = verify_safetensors_integrity(good_st)
    assert info["tensors"] == 2
    assert info["data_bytes"] == 12 * 4 + 5 * 4


def test_truncated_data_rejected(good_st, tmp_path):
    raw = open(good_st, "rb").read()
    bad = str(tmp_path / "trunc.safetensors")
    open(bad, "wb").write(raw[:-7])
    with pytest.raises(ValueError, match="trunc.safetensors"):
        verify_safetensors_integrity(bad)
    with pytest.raises(ValueError):
        read_safetensors(bad)  # the reader verifies before loading


def test_lying_header_len_rejected(good_st, tmp_path):
    raw = bytearray(open(good_st, "rb").read())
    raw[:8] = struct.pack("<Q", len(raw) * 4)
    bad = str(tmp_path / "lying.safetensors")
    open(bad, "wb").write(bytes(raw))
    with pytest.raises(ValueError, match="header"):
        verify_safetensors_integrity(bad)


def test_shape_span_mismatch_rejected(tmp_path):
    hdr = {"a": {"dtype": "F32", "shape": [3, 4], "data_offsets": [0, 40]}}
    blob = json.dumps(hdr).encode()
    bad = str(tmp_path / "span.safetensors")
    with open(bad, "wb") as f:
        f.write(struct.pack("<Q", len(blob)) + blob + b"\0" * 40)
    with pytest.raises(ValueError, match="'a'"):
        verify_safetensors_integrity(bad)


def test_overlapping_tensors_rejected(tmp_path):
    hdr = {"a": {"dtype": "F32", "shape": [4], "data_offsets": [0, 16]},
           "b": {"dtype": "F32", "shape": [4], "data_offsets": [8, 24]}}
    blob = json.dumps(hdr).encode()
    bad = str(tmp_path / "overlap.safetensors")
    with open(bad, "wb") as f:
        f.write(struct.pack("<Q", len(blob)) + blob + b"\0" * 24)
    with pytest.raises(ValueError, match="overlap"):
        verify_safetensors_integrity(bad)


def test_garbage_json_header_rejected(tmp_path):
    bad = str(tmp_path / "garbage.safetensors")
    with open(bad, "wb") as f:
        f.write(struct.pack("<Q", 4) + b"{!!}")
    with pytest.raises(ValueError, match="garbage.safetensors"):
        verify_safetensors_integrity(bad)


# ---------- bounded-retry fetch ----------


def test_fetch_file_url_roundtrip(tmp_path):
    src = tmp_path / "src.bin"
    src.write_bytes(b"payload-bytes" * 100)
    dest = str(tmp_path / "dest.bin")
    fetch_with_retry("file://" + str(src), dest)
    assert open(dest, "rb").read() == src.read_bytes()


def test_fetch_retries_with_backoff_then_fails(tmp_path):
    sleeps = []
    dest = str(tmp_path / "never.bin")
    with pytest.raises(RuntimeError, match="4 attempts"):
        fetch_with_retry("file://" + str(tmp_path / "missing.bin"), dest,
                         max_retries=3, backoff=0.5, _sleep=sleeps.append)
    assert sleeps == [0.5, 1.0, 2.0]  # exponential, no sleep after last try
    import os
    assert not os.path.exists(dest)  # no partial file left behind
    assert not os.path.exists(dest + ".part")


def test_fetch_client_error_fails_immediately(tmp_path, monkeypatch):
    def boom(url, timeout):
        raise urllib.error.HTTPError(url, 404, "not found", None, None)

    # fetch_with_retry imports urllib lazily, so patch the stdlib module
    monkeypatch.setattr("urllib.request.urlopen", boom)
    sleeps = []
    with pytest.raises(RuntimeError, match="404"):
        fetch_with_retry("https://example.invalid/x", str(tmp_path / "x"),
                         _sleep=sleeps.append)
    assert sleeps == []  # a 4xx is permanent: no retries
