"""KV-cached incremental decode: parity, capacity, compile-once, split hops.

The decode subsystem's correctness anchor is teacher-forced parity: feeding the
same token sequence through prefill + repeated ``decode_step`` must reproduce
the full-sequence ``forward`` logits at every position, for both attention
layouts — GPT-NeoX (parallel residual, partial rotary, MHA) and Qwen2 (GQA,
where the cache stores ``num_kv_heads`` and decode attention re-broadcasts per
query group). The ISSUE acceptance pins this at preset scale (pythia-70m and
qwen2-0.5b, atol 1e-4 fp32) on top of the fast tiny-config checks.

Also covered here: the serve loop's greedy output vs an iterated full-forward
oracle, cache-capacity overflow behavior, the compiled-once-per-(batch,
capacity) contract via the jit cache-miss counter, and the split-decode mode
whose per-step boundary hop quantizes a single token's hidden state through a
real wire codec over ppermute (checked against the in-place ``simulate`` codec
at the cut, the same pairing ``test_split.py`` uses for full-sequence hops).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.models import (
    PRESETS, tiny_config, init_params, forward, nll_from_logits,
    KVCache, init_cache, prefill, decode_step,
)
from edgellm_tpu.models.flash_attention import decode_attention
from edgellm_tpu.codecs import per_token_affine_int8
from edgellm_tpu.codecs.packing import selective_int4
from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh
from edgellm_tpu.serve import generate

TINY = {
    "gpt_neox": tiny_config("gpt_neox", num_layers=3, hidden_size=32,
                            num_heads=4, vocab_size=128),
    "qwen2": tiny_config("qwen2", num_layers=3, hidden_size=32, num_heads=4,
                         vocab_size=128),
}


def _ids(cfg, batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, size=(batch, seq)))


def _teacher_forced_decode(cfg, params, ids, prompt_len, capacity):
    """prefill on ids[:, :prompt_len], then decode_step over the rest; returns
    (prefill logits, [per-step logits]) with the final cache."""
    step = jax.jit(decode_step, static_argnames=("cfg",))
    pre_logits, cache = prefill(cfg, params, ids[:, :prompt_len], capacity)
    steps = []
    for t in range(prompt_len, ids.shape[1]):
        logits, cache = step(cfg, params, cache, ids[:, t])
        steps.append(logits)
    return pre_logits, steps, cache


@pytest.mark.parametrize("family", ["gpt_neox", "qwen2"])
def test_tiny_decode_matches_forward(family):
    cfg = TINY[family]
    params = init_params(cfg, jax.random.key(2))
    ids = _ids(cfg, 2, 16, seed=3)
    full, _ = forward(cfg, params, ids)

    pre_logits, steps, cache = _teacher_forced_decode(cfg, params, ids, 7, 16)
    np.testing.assert_allclose(np.asarray(pre_logits), np.asarray(full[:, :7]),
                               atol=1e-4, rtol=1e-4)
    for i, logits in enumerate(steps):
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, 7 + i]),
                                   atol=1e-4, rtol=1e-4)
    assert int(cache.length) == 16


def test_gqa_cache_stores_kv_heads():
    """GQA caches the grouped heads, not the broadcast query heads."""
    cfg = TINY["qwen2"]
    assert cfg.num_kv_heads < cfg.num_heads
    cache = init_cache(cfg, batch=2, capacity=8)
    assert cache.k.shape == (cfg.num_layers, 2, 8, cfg.num_kv_heads,
                             cfg.head_dim)
    params = init_params(cfg, jax.random.key(0))
    _, filled = prefill(cfg, params, _ids(cfg, 2, 5), capacity=8)
    assert filled.k.shape == cache.k.shape
    assert int(filled.length) == 5
    # unfilled tail stays zero (prefill pads, decode writes one slot at a time)
    assert np.all(np.asarray(filled.k[:, :, 5:]) == 0.0)


def test_decode_attention_matches_dense_oracle():
    """q_len=1 GQA attention against a length-masked cache == explicit
    softmax over the valid prefix with keys repeated per query group."""
    rng = np.random.default_rng(7)
    b, cap, h, kv, hd, length = 2, 10, 4, 2, 8, 6
    q = jnp.asarray(rng.standard_normal((b, 1, h, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, cap, kv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, cap, kv, hd)), jnp.float32)
    out = decode_attention(q, k, v, jnp.asarray(length, jnp.int32))

    kr = np.repeat(np.asarray(k)[:, :length], h // kv, axis=2)  # (b, len, h, hd)
    vr = np.repeat(np.asarray(v)[:, :length], h // kv, axis=2)
    scores = np.einsum("bqhd,bchd->bhc", np.asarray(q), kr) / np.sqrt(hd)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ref = np.einsum("bhc,bchd->bhd", probs, vr)[:, None]
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("preset", ["pythia-70m", "qwen2-0.5b"])
def test_preset_decode_matches_forward(preset):
    """ISSUE acceptance: decode_step logits == full forward logits at the same
    positions, atol 1e-4 fp32, at real preset scale (partial rotary for
    pythia-70m, 14q/2kv GQA for qwen2-0.5b). Shapes kept tiny (B=1, S=12) —
    the presets' width/depth is the point, not the window."""
    cfg = PRESETS[preset]
    params = init_params(cfg, jax.random.key(0))
    ids = _ids(cfg, 1, 12, seed=1)
    full, _ = forward(cfg, params, ids)

    _, steps, _ = _teacher_forced_decode(cfg, params, ids, 6, 12)
    for i, logits in enumerate(steps):
        np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, 6 + i]),
                                   atol=1e-4, rtol=1e-4)


def test_generate_greedy_matches_full_forward_oracle():
    """generate(temperature=0) == re-running the full forward after each
    emitted token and taking argmax — the O(S)-per-token loop the cache
    replaces."""
    cfg = TINY["qwen2"]
    params = init_params(cfg, jax.random.key(4))
    prompt = _ids(cfg, 2, 6, seed=9)
    n_new = 5
    out = generate(cfg, params, prompt, n_new)
    assert out.shape == (2, n_new) and out.dtype == jnp.int32

    seq = np.asarray(prompt)
    for t in range(n_new):
        logits, _ = forward(cfg, params, jnp.asarray(seq))
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        np.testing.assert_array_equal(np.asarray(out[:, t]), nxt)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)


def test_generate_temperature_sampling():
    cfg = TINY["gpt_neox"]
    params = init_params(cfg, jax.random.key(5))
    prompt = _ids(cfg, 3, 4, seed=11)
    out = generate(cfg, params, prompt, 6, temperature=0.8,
                   rng_key=jax.random.key(42))
    assert out.shape == (3, 6) and out.dtype == jnp.int32
    arr = np.asarray(out)
    assert np.all((arr >= 0) & (arr < cfg.vocab_size))
    # fixed key -> reproducible draws
    out2 = generate(cfg, params, prompt, 6, temperature=0.8,
                    rng_key=jax.random.key(42))
    np.testing.assert_array_equal(arr, np.asarray(out2))


def test_capacity_overflow_raises():
    cfg = TINY["gpt_neox"]
    params = init_params(cfg, jax.random.key(6))
    prompt = _ids(cfg, 1, 8, seed=13)
    with pytest.raises(ValueError, match="capacity overflow"):
        generate(cfg, params, prompt, 4, capacity=10)
    with pytest.raises(ValueError, match="capacity"):
        prefill(cfg, params, prompt, capacity=4)
    with pytest.raises(ValueError):
        generate(cfg, params, prompt, 0)
    with pytest.raises(ValueError):
        generate(cfg, params, prompt, 2, temperature=-0.1)


def test_decode_step_compiles_once_per_shape():
    """ISSUE acceptance: one per-step executable per (batch, capacity) —
    emitting more tokens or rerunning the same shape must not retrace."""
    cfg = TINY["qwen2"]
    params = init_params(cfg, jax.random.key(8))
    prompt = _ids(cfg, 5, 3, seed=17)  # batch 5: unique shape for this test
    stats = {}
    generate(cfg, params, prompt, 8, stats=stats)
    assert stats["decode_step_cache_misses"] == 1
    assert stats["decode_steps"] == 7
    stats2 = {}
    generate(cfg, params, prompt, 8, stats=stats2)  # warm: same (batch, capacity)
    assert stats2["decode_step_cache_misses"] == 0
    # more tokens at the same capacity still reuse the one executable
    stats3 = {}
    generate(cfg, params, prompt[:, :2], 9, stats=stats3)
    assert stats3["decode_step_cache_misses"] == 0


# ---------------------------------------------------------------------------
# split decode on the spoofed CPU mesh
# ---------------------------------------------------------------------------

SPLIT_CFG = tiny_config("qwen2", num_layers=6, hidden_size=32, num_heads=4,
                        vocab_size=128)


@pytest.fixture(scope="module")
def split_setup():
    params = init_params(SPLIT_CFG, jax.random.key(1))
    ids = _ids(SPLIT_CFG, 2, 14, seed=21)
    return params, ids


def _run_split_decode(rt, params, ids, prompt_len, capacity):
    placed = rt.place_params(params)
    pre_logits, cache = rt.prefill_decode(placed, ids[:, :prompt_len], capacity)
    steps = []
    for t in range(prompt_len, ids.shape[1]):
        logits, cache = rt.decode_step(placed, cache, ids[:, t])
        steps.append(logits)
    return pre_logits, steps


def test_split_decode_quantized_hop_preserves_nll(split_setup):
    """Per-token decode hops through a real int8 wire codec over ppermute ==
    the single-device decode with the matching simulate codec applied at the
    cut — so the split changes neither the logits nor the sequence NLL."""
    params, ids = split_setup
    cut, prompt_len, capacity = 2, 7, 14
    rt = SplitRuntime(SPLIT_CFG,
                      SplitConfig(cuts=(cut,), hop_codecs=("int8_per_token",)),
                      make_stage_mesh(2))
    split_pre, split_steps = _run_split_decode(rt, params, ids, prompt_len,
                                               capacity)

    def bfn(idx, h):
        return jnp.where(idx == cut, per_token_affine_int8(h), h)

    step = jax.jit(decode_step, static_argnames=("cfg", "boundary_fn"))
    ref_pre, cache = prefill(SPLIT_CFG, params, ids[:, :prompt_len], capacity,
                             boundary_fn=bfn)
    np.testing.assert_allclose(np.asarray(split_pre), np.asarray(ref_pre),
                               atol=2e-5, rtol=2e-5)
    ref_steps = []
    for t in range(prompt_len, ids.shape[1]):
        logits, cache = step(SPLIT_CFG, params, cache, ids[:, t],
                             boundary_fn=bfn)
        ref_steps.append(logits)
    for got, want in zip(split_steps, ref_steps):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    # stitched teacher-forced logits -> NLL unchanged by the split transport
    split_all = jnp.concatenate(
        [split_pre] + [s[:, None] for s in split_steps], axis=1)
    ref_all = jnp.concatenate(
        [ref_pre] + [s[:, None] for s in ref_steps], axis=1)
    nll_split = float(nll_from_logits(split_all, ids))
    nll_ref = float(nll_from_logits(ref_all, ids))
    assert abs(nll_split - nll_ref) < 1e-5


def test_split_decode_fp32_hop_matches_unsplit(split_setup):
    """fp32 wire: the split transport itself is lossless at decode time."""
    params, ids = split_setup
    rt = SplitRuntime(SPLIT_CFG, SplitConfig(cuts=(2,), hop_codecs=("fp32",)),
                      make_stage_mesh(2))
    split_pre, split_steps = _run_split_decode(rt, params, ids, 7, 14)
    _, ref_steps, _ = _teacher_forced_decode(SPLIT_CFG, params, ids, 7, 14)
    for got, want in zip(split_steps, ref_steps):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)


def test_split_decode_hop_bytes(split_setup):
    """Decode hops move one token's hidden state: int8 per-token payload =
    B * (D int8 bytes + 2 fp32 scale/zero) per step."""
    params, _ = split_setup
    rt = SplitRuntime(SPLIT_CFG,
                      SplitConfig(cuts=(2,), hop_codecs=("int8_per_token",)),
                      make_stage_mesh(2))
    (per_step,) = rt.decode_hop_bytes(batch=2)
    assert per_step == 2 * (SPLIT_CFG.hidden_size + 8)


def test_split_decode_rejects_unsupported(split_setup):
    params, ids = split_setup
    # token-selective codecs have no importance source for a 1-token step
    rt = SplitRuntime(SPLIT_CFG,
                      SplitConfig(cuts=(2,), hop_codecs=(selective_int4(0.5),)),
                      make_stage_mesh(2))
    with pytest.raises(ValueError, match="importance"):
        rt.prefill_decode(rt.place_params(params), ids[:, :4], 8)
    # decode is stage-only: data/model axes unsupported
    rt2 = SplitRuntime(SPLIT_CFG, SplitConfig(cuts=(2,), hop_codecs=("fp32",)),
                       make_stage_mesh(2, n_data=2))
    with pytest.raises(ValueError, match="stage-only"):
        rt2.prefill_decode(rt2.place_params(params), ids[:, :4], 8)
