"""bench.py end-to-end on CPU with a tiny preset: the driver-recorded artifact
must never die on a plain Python error (a NameError in the FLOPs block once
slipped past unit tests because only the TPU path ran it)."""
import json
import sys

import pytest


def test_bench_main_emits_one_json_line(monkeypatch, capsys):
    sys.modules.pop("bench", None)
    import bench

    monkeypatch.setenv("BENCH_MODEL", "tiny-qwen2")
    monkeypatch.setenv("BENCH_CHUNKS", "2")
    monkeypatch.setenv("BENCH_WINDOW_BATCH", "2")
    monkeypatch.setenv("BENCH_PALLAS", "0")
    monkeypatch.setenv("BENCH_RELEVANCE", "0")
    monkeypatch.setenv("BENCH_MEASURE_PEAK", "0")
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    line = json.loads(out[-1])
    assert line["unit"] == "s/chunk" and line["value"] > 0
    assert line["vs_baseline"] is None  # anchor is qwen2-0.5b only
    assert line["window_batch"] == 2
    assert "tiny-qwen2" in line["metric"]
