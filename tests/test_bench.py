"""bench.py end-to-end on CPU with a tiny preset: the driver-recorded artifact
must never die on a plain Python error (a NameError in the FLOPs block once
slipped past unit tests because only the TPU path ran it)."""
import json
import sys

import pytest


def test_bench_main_headline_is_final_compact_line(monkeypatch, capsys, tmp_path):
    sys.modules.pop("bench", None)
    import bench

    monkeypatch.setenv("BENCH_DETAIL_PATH", str(tmp_path / "detail.json"))
    monkeypatch.setenv("BENCH_MODEL", "tiny-qwen2")
    monkeypatch.setenv("BENCH_CHUNKS", "2")
    monkeypatch.setenv("BENCH_WINDOW_BATCH", "2")
    monkeypatch.setenv("BENCH_PALLAS", "0")
    monkeypatch.setenv("BENCH_RELEVANCE", "0")
    monkeypatch.setenv("BENCH_MEASURE_PEAK", "0")
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    line = json.loads(out[-1])
    assert line["unit"] == "s/chunk" and line["value"] > 0
    assert line["vs_baseline"] is None  # anchor is qwen2-0.5b only
    assert line["window_batch"] == 2
    assert "tiny-qwen2" in line["metric"]
    # the FINAL line is the compact headline (the driver's tail capture
    # truncates giant lines); verbose blocks ride the preceding detail line
    # and the sidecar. A closed key set keeps future verbose additions out.
    assert len(out[-1]) < 1024
    assert set(line) <= {
        "metric", "value", "unit", "vs_baseline", "tokens_per_s",
        "window_batch", "model_tflops_per_s", "mfu", "measured_peak_tflops",
        "mfu_vs_measured", "relevance_it_per_s", "relevance_vs_baseline"}
    detail = json.loads(out[-2])["detail"]
    assert detail["requested_window_batch"] == 2
    assert json.load(open(tmp_path / "detail.json")) == detail


def test_bench_decode_headline(monkeypatch, capsys, tmp_path):
    """BENCH_DECODE=1 flips the bench to the KV-cached decode workload with
    the same stdout contract: compact headline as the FINAL line, verbose
    decode block (incl. split hop bytes/token) on the detail line/sidecar."""
    sys.modules.pop("bench", None)
    import bench

    monkeypatch.setenv("BENCH_DETAIL_PATH", str(tmp_path / "detail.json"))
    monkeypatch.setenv("BENCH_DECODE", "1")
    monkeypatch.setenv("BENCH_MODEL", "tiny-qwen2")
    monkeypatch.setenv("BENCH_DECODE_PROMPT", "8")
    monkeypatch.setenv("BENCH_DECODE_TOKENS", "8")
    monkeypatch.setenv("BENCH_DECODE_BATCH", "2")
    monkeypatch.setenv("BENCH_DECODE_SPLIT", "1")
    monkeypatch.setenv("BENCH_DTYPE", "float32")
    monkeypatch.setenv("BENCH_REPEATS", "1")
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    line = json.loads(out[-1])
    assert line["unit"] == "decode tokens/s" and line["value"] > 0
    assert line["vs_baseline"] is None
    assert line["batch"] == 2
    assert "decode" in line["metric"]
    assert line["decode_step_cache_misses"] == 1  # compiled once, ever
    assert len(out[-1]) < 1024
    assert set(line) <= {"metric", "value", "unit", "vs_baseline",
                         "tokens_per_s", "prefill_s", "batch",
                         "decode_step_cache_misses", "ttft_s",
                         "token_latency_p50_s", "token_latency_p95_s",
                         "token_latency_p99_s"}
    # the SLO acceptance surface: TTFT + per-token p50/p95/p99 in the artifact
    assert line["ttft_s"] > 0
    assert 0 < line["token_latency_p50_s"] <= line["token_latency_p95_s"]
    assert line["token_latency_p95_s"] <= line["token_latency_p99_s"]
    detail = json.loads(out[-2])["detail"]
    dec = detail["decode"]
    assert dec["prompt"] == 8 and dec["batch"] == 2
    assert dec["split_hop_bytes_per_token"] > 0
    assert dec["obs_overhead_frac"] >= 0  # instrumented-vs-clean delta
    assert dec["slo"]["token_latency_p50_s"] > 0
    # conftest spoofs 8 CPU devices, so the split section must have run
    assert dec["split"]["tokens_per_s"] > 0
    assert dec["split"]["hop_bytes_per_token"] == [
        b / 2 for b in dec["split"]["measured_hop_bytes_per_step"]]
    # the meta provenance block is stamped centrally on every artifact
    meta = detail["meta"]
    assert meta["schema_version"] == bench.BENCH_SCHEMA_VERSION
    assert meta["jax_version"] and meta["backend"] == "cpu"
    assert json.load(open(tmp_path / "detail.json")) == detail


def test_bench_fec_headline(monkeypatch, capsys, tmp_path):
    """BENCH_FEC=1: the self-healing-link sweep with the same stdout
    contract — headline carries the repaired-vs-retried split and the
    declared parity wire overhead."""
    sys.modules.pop("bench", None)
    import bench

    monkeypatch.setenv("BENCH_DETAIL_PATH", str(tmp_path / "detail.json"))
    monkeypatch.setenv("BENCH_FEC", "1")
    monkeypatch.setenv("BENCH_MODEL", "tiny-qwen2")
    monkeypatch.setenv("BENCH_DTYPE", "float32")
    monkeypatch.setenv("BENCH_FEC_RATES", "0,0.0002")
    monkeypatch.setenv("BENCH_FAULT_CHUNKS", "2")
    monkeypatch.setenv("BENCH_MAX_LENGTH", "64")
    monkeypatch.setenv("BENCH_STRIDE", "32")
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    line = json.loads(out[-1])
    assert line["unit"] == "ppl" and line["value"] > 0
    assert line["vs_baseline"] is None
    assert "FEC" in line["metric"]
    assert line["wire_overhead"] > 0
    assert len(out[-1]) < 1024
    assert set(line) <= {
        "metric", "value", "unit", "vs_baseline", "ppl_clean", "ppl_ratio",
        "wire_overhead", "detected", "repaired", "retried", "hedge_wins",
        "substituted", "decode_tokens_per_s_clean",
        "decode_tokens_per_s_faulty"}
    detail = json.loads(out[-2])["detail"]
    fec = detail["fec"]
    assert fec["sweep"][0]["rate"] == 0  # exact fault-free baseline point
    assert fec["sweep"][0]["link_counters"] is None
    assert "repaired" in fec["sweep"][-1]["link_counters"]
    # the decode leg ran (8 spoofed devices) with all three link builds
    assert {"clean", "faulty_retry_only", "faulty_fec"} <= set(fec["decode"])
    assert json.load(open(tmp_path / "detail.json")) == detail


def test_bench_obs_headline(monkeypatch, capsys, tmp_path):
    """BENCH_OBS=1: the observability smoke arms the full obs stack, runs an
    instrumented decode, and writes the two promised artifacts — a metrics
    snapshot and a Perfetto-loadable Chrome trace — while the detail sidecar
    carries the registry snapshot via _emit's enabled-registry hook."""
    sys.modules.pop("bench", None)
    import bench
    from edgellm_tpu import obs

    metrics_path = tmp_path / "metrics.json"
    trace_path = tmp_path / "trace.json"
    monkeypatch.setenv("BENCH_DETAIL_PATH", str(tmp_path / "detail.json"))
    monkeypatch.setenv("BENCH_OBS", "1")
    monkeypatch.setenv("BENCH_MODEL", "tiny-qwen2")
    monkeypatch.setenv("BENCH_DTYPE", "float32")
    monkeypatch.setenv("BENCH_OBS_PROMPT", "8")
    monkeypatch.setenv("BENCH_OBS_TOKENS", "8")
    monkeypatch.setenv("BENCH_OBS_BATCH", "2")
    monkeypatch.setenv("BENCH_OBS_METRICS_PATH", str(metrics_path))
    monkeypatch.setenv("BENCH_OBS_TRACE_PATH", str(trace_path))
    try:
        bench.main()
    finally:
        obs.disable()  # never leak an armed registry into other tests
    assert not obs.enabled()  # obs_main's own finally already disarmed it
    out = capsys.readouterr().out.strip().splitlines()
    line = json.loads(out[-1])
    assert line["unit"] == "decode tokens/s (obs on)" and line["value"] > 0
    assert line["n_metrics"] > 0 and line["n_spans"] > 0
    assert line["ttft_s"] > 0 and line["token_latency_p99_s"] > 0
    assert len(out[-1]) < 1024
    detail = json.loads(out[-2])["detail"]
    # _emit folded the enabled registry's snapshot into the sidecar
    assert "edgellm_decode_steps_total" in detail["metrics"]
    assert "edgellm_decode_ttft_seconds" in detail["metrics"]
    assert detail["obs"]["split"]["decode_tokens_per_s"] > 0
    # the on-disk artifacts: JSON snapshot + valid Chrome trace-event JSON
    snap = json.load(open(metrics_path))
    assert "edgellm_decode_token_latency_seconds" in snap
    trace = json.load(open(trace_path))
    assert trace["traceEvents"], "trace must contain spans"
    ev = trace["traceEvents"][0]
    assert ev["ph"] == "X" and {"name", "ts", "dur", "pid", "tid"} <= set(ev)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "generate.decode_loop" in names


def test_bench_backend_outage_emits_status_artifact(monkeypatch, capsys,
                                                    tmp_path):
    """An accelerator outage must not kill the bench rc=1 with no artifact:
    every section preflights the backend and, on failure, emits a partial
    artifact with an explicit per-section status — and returns success."""
    sys.modules.pop("bench", None)
    import bench
    import jax

    def _dead_backend():
        raise RuntimeError(
            "Unable to initialize backend 'axon': UNAVAILABLE: connection "
            "refused (you may need to restart the tunnel)")

    monkeypatch.setenv("BENCH_DETAIL_PATH", str(tmp_path / "detail.json"))
    monkeypatch.setenv("BENCH_FEC", "1")
    monkeypatch.setattr(jax, "devices", _dead_backend)
    assert bench.main() == 0
    out = capsys.readouterr().out.strip().splitlines()
    line = json.loads(out[-1])
    assert line["status"] == "backend_unavailable"
    assert line["section"] == "fec" and line["value"] is None
    detail = json.loads(out[-2])["detail"]
    assert detail["status"] == "backend_unavailable"
    assert "axon" in detail["error"]
    assert json.load(open(tmp_path / "detail.json")) == detail

    # a NON-outage error must still propagate loudly — the status path is
    # for environmental outages only, never a mask for real bugs
    def _real_bug():
        raise RuntimeError("shape mismatch in decode step")

    monkeypatch.setattr(jax, "devices", _real_bug)
    with pytest.raises(RuntimeError, match="shape mismatch"):
        bench.main()
