"""bench.py end-to-end on CPU with a tiny preset: the driver-recorded artifact
must never die on a plain Python error (a NameError in the FLOPs block once
slipped past unit tests because only the TPU path ran it)."""
import json
import sys

import pytest


def test_bench_main_headline_is_final_compact_line(monkeypatch, capsys, tmp_path):
    sys.modules.pop("bench", None)
    import bench

    monkeypatch.setenv("BENCH_DETAIL_PATH", str(tmp_path / "detail.json"))
    monkeypatch.setenv("BENCH_MODEL", "tiny-qwen2")
    monkeypatch.setenv("BENCH_CHUNKS", "2")
    monkeypatch.setenv("BENCH_WINDOW_BATCH", "2")
    monkeypatch.setenv("BENCH_PALLAS", "0")
    monkeypatch.setenv("BENCH_RELEVANCE", "0")
    monkeypatch.setenv("BENCH_MEASURE_PEAK", "0")
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    line = json.loads(out[-1])
    assert line["unit"] == "s/chunk" and line["value"] > 0
    assert line["vs_baseline"] is None  # anchor is qwen2-0.5b only
    assert line["window_batch"] == 2
    assert "tiny-qwen2" in line["metric"]
    # the FINAL line is the compact headline (the driver's tail capture
    # truncates giant lines); verbose blocks ride the preceding detail line
    # and the sidecar. A closed key set keeps future verbose additions out.
    assert len(out[-1]) < 1024
    assert set(line) <= {
        "metric", "value", "unit", "vs_baseline", "tokens_per_s",
        "window_batch", "model_tflops_per_s", "mfu", "measured_peak_tflops",
        "mfu_vs_measured", "relevance_it_per_s", "relevance_vs_baseline"}
    detail = json.loads(out[-2])["detail"]
    assert detail["requested_window_batch"] == 2
    assert json.load(open(tmp_path / "detail.json")) == detail


def test_bench_decode_headline(monkeypatch, capsys, tmp_path):
    """BENCH_DECODE=1 flips the bench to the KV-cached decode workload with
    the same stdout contract: compact headline as the FINAL line, verbose
    decode block (incl. split hop bytes/token) on the detail line/sidecar."""
    sys.modules.pop("bench", None)
    import bench

    monkeypatch.setenv("BENCH_DETAIL_PATH", str(tmp_path / "detail.json"))
    monkeypatch.setenv("BENCH_DECODE", "1")
    monkeypatch.setenv("BENCH_MODEL", "tiny-qwen2")
    monkeypatch.setenv("BENCH_DECODE_PROMPT", "8")
    monkeypatch.setenv("BENCH_DECODE_TOKENS", "8")
    monkeypatch.setenv("BENCH_DECODE_BATCH", "2")
    monkeypatch.setenv("BENCH_DECODE_SPLIT", "1")
    monkeypatch.setenv("BENCH_DTYPE", "float32")
    monkeypatch.setenv("BENCH_REPEATS", "1")
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    line = json.loads(out[-1])
    assert line["unit"] == "decode tokens/s" and line["value"] > 0
    assert line["vs_baseline"] is None
    assert line["batch"] == 2
    assert "decode" in line["metric"]
    assert line["decode_step_cache_misses"] == 1  # compiled once, ever
    assert len(out[-1]) < 1024
    assert set(line) <= {"metric", "value", "unit", "vs_baseline",
                         "tokens_per_s", "prefill_s", "batch",
                         "decode_step_cache_misses"}
    detail = json.loads(out[-2])["detail"]
    dec = detail["decode"]
    assert dec["prompt"] == 8 and dec["batch"] == 2
    assert dec["split_hop_bytes_per_token"] > 0
    # conftest spoofs 8 CPU devices, so the split section must have run
    assert dec["split"]["tokens_per_s"] > 0
    assert dec["split"]["hop_bytes_per_token"] == [
        b / 2 for b in dec["split"]["measured_hop_bytes_per_step"]]
    assert json.load(open(tmp_path / "detail.json")) == detail
