"""Disaggregated prefill/decode: token identity by construction, the
migration ladder (detect -> repair -> retry -> degrade), the failure
matrix (prefill kill mid-migration, decode kill, dead link), cross-tier
adoption refusals, and the wire-byte contract.

The load-bearing claim: for every COMPLETED request, disagg serving emits
BIT-IDENTICAL tokens to colocated serving — greedy and sampled, fp and
quantized tiers, under corruption and under mid-workload worker kills —
because the handoff is a verified byte move of the staged pool rows
(never a requantize) injected before any decode step runs.
"""
import dataclasses
import os

import numpy as np
import pytest

import jax

from edgellm_tpu.codecs.faults import FaultConfig
from edgellm_tpu.codecs.fec import FECConfig, HedgeConfig
from edgellm_tpu.codecs.wire_format import seal_payload, tree_nbytes
from edgellm_tpu.models import init_params, tiny_config
from edgellm_tpu.models.paged_kv import KVTierMismatchError, PagedKVCache
from edgellm_tpu.obs.flight import FlightRecorder, configure_flight
from edgellm_tpu.serve.batching import BatchingConfig, ContinuousBatcher
from edgellm_tpu.serve.disagg import (DisaggConfig, DisaggServer,
                                      MigrationError, MigrationLink,
                                      PrefillWorkerLost,
                                      migration_wire_nbytes)
from edgellm_tpu.serve.recovery import (CheckpointError,
                                        CheckpointTierMismatchError)

CFG = tiny_config("qwen2", num_layers=2, hidden_size=32, num_heads=4,
                  vocab_size=128)
BCFG = BatchingConfig(page_size=8, num_pages=17, max_slots=4,
                      pages_per_slot=4)
QCFG = dataclasses.replace(BCFG, kv_codec="int8_per_channel")


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(1))


def _prompt(n, seed=0):
    return np.random.default_rng(seed).integers(
        1, CFG.vocab_size, size=n).astype(np.int32)


#: a mixed workload: multi-page prompts, greedy + sampled, a 1-token
#: degenerate, different seeds
REQS = [(_prompt(5, 1), 6, 0.0, 0),
        (_prompt(11, 2), 8, 0.7, 3),
        (_prompt(9, 4), 5, 1.1, 9),
        (_prompt(3, 3), 1, 0.0, 7)]


def _colocated(params, bcfg, reqs=REQS):
    ref = ContinuousBatcher(CFG, params, bcfg)
    sids = [ref.submit(p, m, temperature=t, rng_seed=s)
            for p, m, t, s in reqs]
    res = ref.run()
    return [res[s] for s in sids]


def _assert_identical(server, expected, reqs=REQS):
    sids = [server.submit(p, m, temperature=t, rng_seed=s)
            for p, m, t, s in reqs]
    res = server.run()
    for want, s in zip(expected, sids):
        assert np.array_equal(want, res[s]), (want, res[s])


# ---------------------------------------------------------------------------
# token identity by construction: disagg == colocated, fp + quantized
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("bcfg", [BCFG, QCFG], ids=["fp", "int8"])
def test_disagg_token_identity(params, bcfg):
    expected = _colocated(params, bcfg)
    srv = DisaggServer(CFG, params, bcfg, DisaggConfig())
    _assert_identical(srv, expected)
    rep = srv.report()["disagg"]
    assert rep["migrations"] == 3          # the 1-token request never ships
    assert rep["migrated_pages"] >= 4
    assert not rep["degraded"]
    assert rep["link"]["failed"] == 0
    assert rep["recompute_tokens"] == 0


def test_disagg_identity_with_fec_and_hedge(params):
    expected = _colocated(params, QCFG)
    srv = DisaggServer(CFG, params, QCFG, DisaggConfig(
        fec=FECConfig(enabled=True), hedge=HedgeConfig(enabled=True)))
    _assert_identical(srv, expected)
    assert srv.report()["disagg"]["link"]["failed"] == 0


# ---------------------------------------------------------------------------
# the ladder: FEC heals a single corrupt chunk in band, zero retries
# ---------------------------------------------------------------------------


def test_fec_heals_single_corrupt_chunk_without_retry(params):
    expected = _colocated(params, QCFG)
    srv = DisaggServer(CFG, params, QCFG,
                       DisaggConfig(fec=FECConfig(enabled=True)))
    srv.link.corrupt_chunk_once = 0
    _assert_identical(srv, expected)
    c = srv.link.counters
    assert c["detected"] == 1
    assert c["repaired"] == 1
    assert c["retried"] == 0            # healed in band, no re-send
    assert c["failed"] == 0


def test_corruption_beyond_repair_is_never_adopted(params):
    """A hot link without FEC: every transfer arrives corrupt, the ladder
    exhausts, and the request falls back to a COLOCATED prefill — tokens
    stay identical, the corrupt bytes never reach the decode pool."""
    expected = _colocated(params, QCFG)
    srv = DisaggServer(CFG, params, QCFG, DisaggConfig(
        max_retries=1, degrade_after=2,
        faults=FaultConfig(bitflip_rate=0.5, seed=9)))
    _assert_identical(srv, expected)
    rep = srv.report()["disagg"]
    assert rep["link"]["failed"] >= 1
    assert rep["link"]["detected"] >= 2     # every attempt detected
    assert rep["migrations"] == 0           # nothing corrupt was adopted
    assert rep["colocated_fallbacks"] >= 1
    assert rep["degraded"] and rep["degrade_reason"] == "migration_failures"


def test_link_send_raises_after_exhaustion():
    link = MigrationLink(faults=FaultConfig(bitflip_rate=0.5, seed=3),
                         max_retries=1)
    with pytest.raises(MigrationError, match="never adopted"):
        link.send({"k": np.ones((2, 4, 2), np.float32)}, sid=0, page=0)
    assert link.counters["failed"] == 1
    assert link.counters["transmissions"] == 2
    assert link.counters["pages"] == 0


def test_dead_link_refuses_immediately():
    link = MigrationLink()
    link.fail()
    with pytest.raises(MigrationError, match="link is down"):
        link.send({"k": np.ones((1, 2, 2), np.float32)}, sid=0, page=0)


# ---------------------------------------------------------------------------
# wire-byte contract
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fec", [None, FECConfig(enabled=True)],
                         ids=["sealed", "fec"])
def test_migration_wire_bytes_match_declared(fec):
    import jax.numpy as jnp
    payload = {"k": np.ones((2, 8, 2, 4), np.float32),
               "v": np.ones((2, 8, 2, 4), np.float32)}
    link = MigrationLink(fec=fec)
    link.send(payload, sid=0, page=0)
    declared = migration_wire_nbytes(tree_nbytes(
        jax.tree_util.tree_map(jnp.asarray, payload)), fec)
    assert link.counters["wire_bytes"] == declared
    sealed = seal_payload(jax.tree_util.tree_map(jnp.asarray, payload))
    assert tree_nbytes(sealed) == tree_nbytes(
        jax.tree_util.tree_map(jnp.asarray, payload)) + 8


def test_disagg_accounts_wire_bytes_per_request(params):
    srv = DisaggServer(CFG, params, BCFG, DisaggConfig())
    sid = srv.submit(_prompt(11, 2), 4, temperature=0.0, rng_seed=0)
    srv.run()
    srv.pop_result(sid)
    rep = srv.report()["disagg"]
    assert rep["wire_bytes"] == rep["link"]["wire_bytes"] > 0
    # 11 rows over page_size=8 -> 2 page transfers
    assert rep["migrated_pages"] == 2 == rep["link"]["pages"]


# ---------------------------------------------------------------------------
# failure matrix: prefill worker dies mid-migration
# ---------------------------------------------------------------------------


def test_prefill_kill_mid_migration_redrives_from_checkpoint(params):
    """The worker dies BETWEEN page transfers; the server-held prefill
    checkpoint re-drives the remaining pages — zero recompute, identical
    tokens."""
    expected = _colocated(params, BCFG)
    srv = DisaggServer(CFG, params, BCFG,
                       DisaggConfig(num_prefill_workers=2))
    armed = {"done": False}

    def hook(wid, sid, page):
        # fire after page 0 of a MULTI-page migration, so the kill lands
        # with the handoff genuinely in flight
        if not armed["done"] and page == 0 and REQS[sid][0].size > 8:
            armed["done"] = True
            srv.kill_prefill_worker(wid)

    srv.page_hook = hook
    _assert_identical(srv, expected)
    rep = srv.report()["disagg"]
    assert armed["done"]
    assert rep["live_prefill_workers"] == 1
    assert rep["redriven_pages"] > 0
    assert rep["recompute_tokens"] == 0     # nothing accepted was lost
    assert not rep["degraded"]


def test_prefill_kill_without_checkpoint_reprefills(params):
    """prefill_checkpoint=False: the dead worker's staged rows are gone, so
    the prompt re-prefills on the surviving worker — counted recompute,
    still identical tokens."""
    expected = _colocated(params, BCFG)
    srv = DisaggServer(CFG, params, BCFG, DisaggConfig(
        num_prefill_workers=2, prefill_checkpoint=False))
    armed = {"done": False}

    def hook(wid, sid, page):
        if not armed["done"] and page == 0 and REQS[sid][0].size > 8:
            armed["done"] = True
            srv.kill_prefill_worker(wid)

    srv.page_hook = hook
    _assert_identical(srv, expected)
    rep = srv.report()["disagg"]
    assert rep["recompute_tokens"] > 0
    assert rep["redriven_pages"] == 0
    assert not rep["degraded"]


def test_all_prefill_workers_dead_degrades_to_colocated(params):
    expected = _colocated(params, BCFG)
    srv = DisaggServer(CFG, params, BCFG,
                       DisaggConfig(num_prefill_workers=2))
    srv.kill_prefill_worker(0)
    srv.kill_prefill_worker(1)
    _assert_identical(srv, expected)
    rep = srv.report()["disagg"]
    assert rep["degraded"]
    assert rep["degrade_reason"] == "prefill_workers_lost"
    assert rep["live_prefill_workers"] == 0


# ---------------------------------------------------------------------------
# failure matrix: decode worker dies
# ---------------------------------------------------------------------------


def test_decode_kill_readmits_via_checkpoint(params, tmp_path):
    expected = _colocated(params, BCFG)
    bcfg = dataclasses.replace(BCFG, checkpoint_dir=str(tmp_path))
    srv = DisaggServer(CFG, params, bcfg, DisaggConfig())
    sids = [srv.submit(p, m, temperature=t, rng_seed=s)
            for p, m, t, s in REQS]
    for _ in range(3):
        srv.step()
    srv.kill_decode_worker()
    res = srv.run()
    for want, s in zip(expected, sids):
        assert np.array_equal(want, res[s])
    assert srv.report()["disagg"]["readmitted"] >= 1


def test_decode_kill_replays_handoff_without_checkpoint_dir(params):
    expected = _colocated(params, BCFG)
    srv = DisaggServer(CFG, params, BCFG, DisaggConfig())
    sids = [srv.submit(p, m, temperature=t, rng_seed=s)
            for p, m, t, s in REQS]
    for _ in range(3):
        srv.step()
    srv.kill_decode_worker()
    res = srv.run()
    for want, s in zip(expected, sids):
        assert np.array_equal(want, res[s])
    rep = srv.report()["disagg"]
    assert rep["readmitted"] >= 1


# ---------------------------------------------------------------------------
# failure matrix: dead link -> typed graceful degrade
# ---------------------------------------------------------------------------


def test_link_death_degrades_with_typed_reason(params):
    expected = _colocated(params, BCFG)
    srv = DisaggServer(CFG, params, BCFG, DisaggConfig())
    srv.fail_link()
    _assert_identical(srv, expected)
    rep = srv.report()["disagg"]
    assert rep["degraded"]
    assert rep["degrade_reason"] == "migration_link_dead"
    assert rep["migrations"] == 0


def test_link_death_mid_workload_loses_nothing(params):
    """The link dies AFTER some requests migrated: completed handoffs still
    adopt and finish; later prompts fall back colocated. Identity holds for
    every request."""
    expected = _colocated(params, BCFG)
    srv = DisaggServer(CFG, params, BCFG, DisaggConfig())
    first = REQS[:2]
    rest = REQS[2:]
    sids = [srv.submit(p, m, temperature=t, rng_seed=s)
            for p, m, t, s in first]
    srv.step()   # migrate the first wave
    srv.fail_link()
    sids += [srv.submit(p, m, temperature=t, rng_seed=s)
             for p, m, t, s in rest]
    res = srv.run()
    for want, s in zip(expected, sids):
        assert np.array_equal(want, res[s])
    assert srv.degraded


# ---------------------------------------------------------------------------
# bounded handoff queue: decode pulls, prefill back-pressures
# ---------------------------------------------------------------------------


def test_handoff_queue_is_bounded(params):
    srv = DisaggServer(CFG, params, BCFG, DisaggConfig(queue_bound=1))
    reqs = [(_prompt(5, i), 3, 0.0, i) for i in range(6)]
    sids = [srv.submit(p, m, temperature=t, rng_seed=s)
            for p, m, t, s in reqs]
    max_depth = 0
    for _ in range(200):
        srv.step()
        max_depth = max(max_depth, len(srv.queue))
        if not srv._unfinished():
            break
    assert max_depth <= 1
    assert all(s in srv.results for s in sids)


# ---------------------------------------------------------------------------
# exactly one flight-recorder dump per migration-fatal failure
# ---------------------------------------------------------------------------


def test_exactly_one_flight_dump_on_migration_fatal(params, tmp_path):
    rec = FlightRecorder(str(tmp_path))
    configure_flight(rec)
    try:
        srv = DisaggServer(CFG, params, QCFG, DisaggConfig(
            max_retries=0, degrade_after=10,
            faults=FaultConfig(bitflip_rate=0.5, seed=5)))
        sid = srv.submit(_prompt(5, 1), 3, temperature=0.0, rng_seed=0)
        srv.run()
        srv.pop_result(sid)
        dumps = rec.dumps()
        assert len(dumps) == 1          # one fatal failure, one post-mortem
        assert os.path.exists(dumps[0])
    finally:
        configure_flight(None)


# ---------------------------------------------------------------------------
# cross-tier adoption refusals: every path, typed
# ---------------------------------------------------------------------------


def _pool(kv_codec):
    return PagedKVCache(CFG, num_pages=9, page_size=4, max_slots=2,
                        pages_per_slot=2, kv_codec=kv_codec)


def test_adopt_packed_refuses_on_fp_pool_typed():
    pool = _pool("fp")
    z = np.zeros((2, 4, 2, 2), np.int8)
    s = np.zeros((2, 4, 2), np.float32)
    with pytest.raises(KVTierMismatchError) as ei:
        pool.adopt_packed(0, z, z, s, s, 4)
    assert ei.value.offered == "quantized"
    assert ei.value.pool == "fp"
    assert ei.value.where == "adopt_packed"


def test_load_state_dict_refuses_cross_tier_typed():
    pool = _pool("int8_per_channel")
    state = pool.state_dict()
    other = _pool("fp")
    with pytest.raises(KVTierMismatchError) as ei:
        other.load_state_dict(state)
    assert ei.value.offered == "int8_per_channel"
    assert ei.value.pool == "fp"
    assert ei.value.where == "load_state_dict"


def test_gather_rows_packed_refuses_on_fp_pool():
    pool = _pool("fp")
    with pytest.raises(ValueError, match="quantized tiers"):
        pool.gather_slot_rows_packed(0, 0, 1)


def test_restore_stream_refuses_cross_tier_typed(params, tmp_path):
    bat = ContinuousBatcher(CFG, params, QCFG)
    sid = bat.submit(_prompt(5, 1), 6, temperature=0.0, rng_seed=0)
    bat.step()
    path = bat.checkpoint_stream(sid, str(tmp_path / "s.ckpt"))
    fbat = ContinuousBatcher(CFG, params, BCFG)
    with pytest.raises(CheckpointTierMismatchError) as ei:
        fbat.restore_stream(path)
    # one typed error serves both audiences
    assert isinstance(ei.value, KVTierMismatchError)
    assert isinstance(ei.value, CheckpointError)
    assert ei.value.offered == "int8_per_channel"
    assert ei.value.pool == "fp"


def test_split_packed_adopt_refusals_are_typed():
    # the tier gate fires before any mesh work, so an uninitialized
    # runtime exercises the refusal without needing >= 2 devices
    from edgellm_tpu.parallel.split import SplitRuntime
    rt = SplitRuntime.__new__(SplitRuntime)
    fake_pool = {"k": np.zeros((2, 3, 4, 2, 2), np.float32),
                 "v": np.zeros((2, 3, 4, 2, 2), np.float32)}
    with pytest.raises(KVTierMismatchError) as ei:
        rt.gather_paged_packed(fake_pool, np.zeros(2, np.int32))
    assert ei.value.where == "gather_paged_packed"
    z = np.zeros((2, 3, 4, 2, 2), np.int8)
    s = np.zeros((2, 3, 4, 2), np.float32)
    with pytest.raises(KVTierMismatchError) as ei2:
        rt.adopt_paged_rows_packed(fake_pool, z, z, s, s,
                                   np.zeros(2, np.int32))
    assert ei2.value.where == "adopt_paged_rows_packed"
    assert ei2.value.pool == "fp"


# ---------------------------------------------------------------------------
# migration holds: a held slot survives frees and defrag
# ---------------------------------------------------------------------------


def test_held_slot_refuses_free_and_defers_defrag():
    pool = _pool("fp")
    slot = pool.alloc_slot()
    pool.ensure(slot, 4)
    pool.hold_slot(slot)
    assert pool.held_slots == [slot]
    with pytest.raises(ValueError, match="held for an in-flight migration"):
        pool.free_slot(slot)
    assert pool.defrag() == 0
    assert pool.deferred_defrags == 1
    pool.release_slot_hold(slot)
    pool.free_slot(slot)            # now fine
    with pytest.raises(ValueError, match="hold"):
        pool.release_slot_hold(slot)
    pool.check_invariants()


def test_release_handoff_frees_staging_state(params):
    bat = ContinuousBatcher(CFG, params, BCFG)
    sid = bat.submit(_prompt(5, 1), 4, temperature=0.0, rng_seed=0)
    st = bat.prefill_hold(sid)
    assert st is not None and st.status == "running"
    assert bat.pool.held_slots == [st.slot]
    bat.release_handoff(sid)
    assert bat.pool.held_slots == []
    assert sid not in bat._streams
    bat.pool.check_invariants()


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kw", [
    {"num_prefill_workers": 0},
    {"prefill_batch": 0},
    {"queue_bound": 0},
    {"max_retries": -1},
    {"degrade_after": 0},
    {"enabled": "yes"},
    {"fec": "on"},
    {"hedge": 2},
    {"faults": {"bitflip_rate": 0.1}},
    {"link_seed": 1.5},
])
def test_disagg_config_validation(kw):
    with pytest.raises(ValueError):
        DisaggConfig(**kw)


def test_disagg_server_validates_submissions(params):
    srv = DisaggServer(CFG, params, BCFG, DisaggConfig())
    with pytest.raises(ValueError, match="empty prompt"):
        srv.submit(np.array([], np.int32), 4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        srv.submit(_prompt(4), 0)
    with pytest.raises(ValueError, match="temperature"):
        srv.submit(_prompt(4), 4, temperature=-1.0)
    with pytest.raises(ValueError, match="cache positions"):
        srv.submit(_prompt(4), BCFG.span + 1)


def test_disagg_disabled_config_routes_colocated(params):
    expected = _colocated(params, BCFG)
    srv = DisaggServer(CFG, params, BCFG, DisaggConfig(enabled=False))
    _assert_identical(srv, expected)
    assert srv.report()["disagg"]["migrations"] == 0


# ---------------------------------------------------------------------------
# chaos soak: every worker class killed mid-workload, corruption burst,
# zero accepted loss, full identity
# ---------------------------------------------------------------------------


def test_disagg_chaos_soak_all_legs(params):
    from edgellm_tpu.serve.soak import DisaggSoakConfig, run_disagg_soak
    srv = DisaggServer(CFG, params, BCFG, DisaggConfig(
        num_prefill_workers=3, queue_bound=4, degrade_after=50,
        fec=FECConfig(enabled=True)))
    soak = DisaggSoakConfig(
        n_requests=12, seed=7, vocab_size=CFG.vocab_size,
        min_prompt_len=3, max_prompt_len=14, max_new_tokens=5,
        kills=((0.2, "prefill"), (0.8, "decode")),
        burst_start_frac=0.4, burst_end_frac=0.6,
        burst_bitflip_rate=0.01)
    art = run_disagg_soak(
        srv, soak,
        reference_factory=lambda: ContinuousBatcher(CFG, params, BCFG))
    assert art["accepted_lost"] == 0            # nothing accepted was lost
    assert art["completed"] == 12
    assert art["token_identity"]["ok"]
    assert art["token_identity"]["checked"] == 12
    assert any(k["target"].startswith("prefill") and k["mid_migration"]
               for k in art["kills"])
    assert any(k["target"] == "decode" for k in art["kills"])


def test_disagg_soak_link_kill_degrades_cleanly(params):
    from edgellm_tpu.serve.soak import DisaggSoakConfig, run_disagg_soak
    srv = DisaggServer(CFG, params, BCFG, DisaggConfig())
    soak = DisaggSoakConfig(n_requests=8, seed=3,
                            vocab_size=CFG.vocab_size,
                            kills=((0.5, "link"),))
    art = run_disagg_soak(
        srv, soak,
        reference_factory=lambda: ContinuousBatcher(CFG, params, BCFG))
    assert art["accepted_lost"] == 0
    assert art["token_identity"]["ok"]
    assert art["disagg"]["degraded"]
    assert art["disagg"]["degrade_reason"] == "migration_link_dead"


def test_disagg_soak_config_validation():
    from edgellm_tpu.serve.soak import DisaggSoakConfig
    with pytest.raises(ValueError, match="kill target"):
        DisaggSoakConfig(kills=((0.5, "gpu"),))
    with pytest.raises(ValueError, match="burst_end_frac"):
        DisaggSoakConfig(burst_start_frac=0.8, burst_end_frac=0.2)
    with pytest.raises(ValueError, match="prompt_len"):
        DisaggSoakConfig(min_prompt_len=9, max_prompt_len=3)


# ---------------------------------------------------------------------------
# run.py params validation: the shipped config and the refusals
# ---------------------------------------------------------------------------


def _disagg_params():
    root = os.path.join(os.path.dirname(__file__), "..")
    with open(os.path.join(root, "configs", "split16_qwen_disagg.json")) as f:
        import json

        return json.load(f)


def test_params_validation_accepts_disagg_config():
    from edgellm_tpu.run import _validate_params_json

    _validate_params_json(_disagg_params())  # must not raise


def test_params_validation_disagg_is_serve_only():
    from edgellm_tpu.run import _validate_params_json

    p = {"experiment": "split", "max_length": 512, "stride": 32,
         "cuts": [1], "hop_codecs": ["int8_per_token"],
         "disagg": {"num_prefill_workers": 2}}
    with pytest.raises(SystemExit, match="only applies to experiment "
                                         "'serve'"):
        _validate_params_json(p)


def test_params_validation_disagg_requires_batching():
    from edgellm_tpu.run import _validate_params_json

    p = _disagg_params()
    del p["batching"]
    with pytest.raises(SystemExit, match="add a 'batching' block"):
        _validate_params_json(p)


@pytest.mark.parametrize("patch, msg", [
    ({"speculative": {"k": 4}}, "speculative"),
    ({"disagg": [2]}, "object of DisaggConfig fields"),
    ({"disagg": {"num_prefill_workerz": 2}}, "disagg: unknown field"),
    ({"disagg": {"fec": {"chunkz": 4}}}, "disagg.fec: unknown field"),
    ({"disagg": {"hedge": 3}}, "disagg.hedge must be an object"),
    ({"disagg": {"num_prefill_workers": 0}}, "num_prefill_workers"),
    ({"disagg": {"queue_bound": 0}}, "queue_bound"),
    ({"disagg": {"max_retries": -1}}, "max_retries"),
])
def test_params_validation_rejects_disagg_footguns(patch, msg):
    from edgellm_tpu.run import _validate_params_json

    p = _disagg_params()
    p.update(patch)
    with pytest.raises(SystemExit, match=msg):
        _validate_params_json(p)


def test_disagg_config_builder_nests_the_ladder_configs():
    from edgellm_tpu.run import _disagg_config

    dcfg = _disagg_config({"num_prefill_workers": 3,
                           "fec": {"enabled": True},
                           "hedge": {"enabled": True, "routes": 2},
                           "faults": {"bitflip_rate": 0.01}})
    assert dcfg.num_prefill_workers == 3
    assert isinstance(dcfg.fec, FECConfig) and dcfg.fec.enabled
    assert isinstance(dcfg.hedge, HedgeConfig) and dcfg.hedge.routes == 2
    assert isinstance(dcfg.faults, FaultConfig)


# ---------------------------------------------------------------------------
# front + router surfacing: disagg state rides the serve report and demotes
# degraded replicas in placement
# ---------------------------------------------------------------------------


def test_serve_front_drains_a_disagg_batcher(params):
    from edgellm_tpu.serve import Request, ServeFront
    from edgellm_tpu.utils.clock import FakeClock

    srv = DisaggServer(CFG, params, BCFG, DisaggConfig())
    front = ServeFront(CFG, params, batcher=srv, clock=FakeClock())
    for i, (prompt, mnt, temp, seed) in enumerate(REQS[:2]):
        front.submit(Request(prompt_ids=prompt, max_new_tokens=mnt,
                             temperature=temp, rng_seed=seed))
    recs = front.drain_batched()
    assert len(recs) == 2
    assert all(r.outcome == "completed" for r in recs)
    assert recs[0].plan["mode"] == "disagg"
    assert recs[0].plan["disagg"]["degraded"] is False
    rep = front.report()
    assert rep["disagg"] == {"degraded": False, "degrade_reason": None}
    assert front.disagg_state() == {"degraded": False,
                                    "degrade_reason": None}
    # degrade surfaces through the same probe (what the router reads)
    srv.fail_link()
    assert front.disagg_state() == {
        "degraded": True, "degrade_reason": "migration_link_dead"}


def test_serve_front_disagg_state_is_none_for_colocated(params):
    from edgellm_tpu.serve import ServeFront
    from edgellm_tpu.utils.clock import FakeClock

    front = ServeFront(CFG, params, batcher=ContinuousBatcher(
        CFG, params, BCFG), clock=FakeClock())
    assert front.disagg_state() is None
    assert "disagg" not in front.report()


def test_cluster_demotes_degraded_disagg_replicas():
    from edgellm_tpu.serve import Request
    from edgellm_tpu.serve.cluster import (ClusterConfig, ClusterFront,
                                           SimReplicaConfig, SimReplicaFront)
    from edgellm_tpu.utils.clock import FakeClock

    class DisaggSimFront(SimReplicaFront):
        degraded = False

        def disagg_state(self):
            return {"degraded": self.degraded,
                    "degrade_reason": ("migration_link_dead"
                                       if self.degraded else None)}

    clock = FakeClock()
    fronts = {}

    def factory(rid, gen):
        f = DisaggSimFront(SimReplicaConfig(), clock=clock, replica_id=rid)
        fronts[rid] = f
        return f

    cluster = ClusterFront(factory, ClusterConfig(num_replicas=2),
                           clock=clock)
    # equal load: the (disagg_penalty, queue_depth, id) key demotes the
    # degraded replica 0 even though the plain tiebreak would pick it
    fronts[0].degraded = True
    prompt = np.random.default_rng(5).integers(
        1, 50_000, size=16).astype(np.int32)
    crid = cluster.submit(Request(prompt_ids=prompt, max_new_tokens=4))
    assert cluster._placements[crid].replica_id == 1
    # the replica summary carries the typed reason for the fleet report
    summaries = {r.id: r.summary() for r in cluster.replicas.values()}
    assert summaries[0]["disagg"]["degrade_reason"] == "migration_link_dead"
    assert summaries[1]["disagg"]["degraded"] is False
    # healthy again: the deterministic tiebreak returns to lowest id
    fronts[0].degraded = False
    crid2 = cluster.submit(Request(prompt_ids=prompt[::-1].copy(),
                                   max_new_tokens=4))
    assert cluster._placements[crid2].replica_id == 0
