"""Importance-metric tests: vectorized metrics vs naive oracles that follow the
reference's torch loops over full (B, H, S, S) attention maps
(``Qwen2-0.5B/main.py:21-98``, ``Pythia-70M/initial_exp.py:27-72``), plus a check
that the stats captured by the model forward feed the metrics identically to full
maps computed by HF.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from edgellm_tpu.models.transformer import AttnStats
from edgellm_tpu.importance import (
    importance_per_layer,
    aggregate_upto,
    maximum_aggregation,
    ordering_from_importance,
)

L, B, H, S = 4, 1, 3, 10


@pytest.fixture
def attn_maps(rng):
    """Random stochastic attention maps (L, B, H, S, S), rows sum to 1."""
    maps = rng.random((L, B, H, S, S)).astype(np.float32)
    return maps / maps.sum(-1, keepdims=True)


@pytest.fixture
def stats(attn_maps):
    return AttnStats(
        col_mean=jnp.asarray(attn_maps.mean(axis=3)),
        last_row=jnp.asarray(attn_maps[:, :, :, -1, :]),
    )


def _oracle(method, maps, head_weights=None):
    """Literal translation of get_importance_order (Qwen2-0.5B/main.py:43-98)."""
    res = []
    aggregate = 0.0
    for layer in range(maps.shape[0]):
        if method == "regular_importance":
            avg_heads = maps[layer].mean(axis=1)  # (B, S, S)
            res.append(avg_heads.mean(axis=1).squeeze(0))
        elif method == "weighted_importance":
            weighted = np.zeros_like(maps[layer][:, 0])
            for h in range(maps.shape[2]):
                weighted += maps[layer][:, h] * head_weights[layer][h]
            res.append(weighted.mean(axis=1).squeeze(0))
        elif method == "last_row":
            res.append(maps[layer][:, :, -1, :].mean(axis=1).squeeze(0))
        elif method == "aggregate_till":
            cur = maps[layer].mean(axis=1).squeeze(0).mean(axis=0)
            aggregate = aggregate + cur
            res.append(aggregate / (layer + 1))
    return np.stack(res)


@pytest.mark.parametrize("method", ["regular_importance", "last_row", "aggregate_till"])
def test_methods_match_oracle(attn_maps, stats, method):
    got = np.asarray(importance_per_layer(stats, method))[:, 0]
    np.testing.assert_allclose(got, _oracle(method, attn_maps), atol=1e-6)


def test_weighted_importance_matches_oracle(attn_maps, stats, rng):
    w = rng.random((L, H)).astype(np.float32)
    w /= w.sum(1, keepdims=True)
    got = np.asarray(importance_per_layer(stats, "weighted_importance", jnp.asarray(w)))[:, 0]
    np.testing.assert_allclose(got, _oracle("weighted_importance", attn_maps, w), atol=1e-6)


def test_aggregate_upto_matches_initial_exp(attn_maps, stats):
    """'aggregate upto 2' = mean of col-means of layers 0..2 (initial_exp.py:31-40)."""
    want = 0.0
    for i in range(3):
        want = want + attn_maps[i].mean(axis=1).mean(axis=1).squeeze(0)
    want = want / 3
    got = np.asarray(aggregate_upto(stats.col_mean, 2))[0]
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_maximum_aggregation_matches_initial_exp(attn_maps, stats):
    """elementwise max of col-means of layers 0..2 (initial_exp.py:41-51)."""
    want = np.zeros(S, np.float32)
    for i in range(3):
        want = np.maximum(want, attn_maps[i].mean(axis=1).mean(axis=1).squeeze(0))
    got = np.asarray(maximum_aggregation(stats.col_mean, 2))[0]
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_ordering_is_ascending_stable(stats):
    imp = jnp.asarray([0.3, 0.1, 0.1, 0.5])
    np.testing.assert_array_equal(np.asarray(ordering_from_importance(imp)), [1, 2, 0, 3])


def test_unknown_method_raises(stats):
    with pytest.raises(ValueError):
        importance_per_layer(stats, "nope")
    with pytest.raises(ValueError):
        importance_per_layer(stats, "weighted_importance")  # missing head_weights
