"""Importance-metric tests: vectorized metrics vs naive oracles that follow the
reference's torch loops over full (B, H, S, S) attention maps
(``Qwen2-0.5B/main.py:21-98``, ``Pythia-70M/initial_exp.py:27-72``), plus a check
that the stats captured by the model forward feed the metrics identically to full
maps computed by HF.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from edgellm_tpu.models.transformer import AttnStats
from edgellm_tpu.importance import (
    importance_per_layer,
    aggregate_upto,
    maximum_aggregation,
    ordering_from_importance,
)

L, B, H, S = 4, 1, 3, 10


@pytest.fixture
def attn_maps(rng):
    """Random stochastic attention maps (L, B, H, S, S), rows sum to 1."""
    maps = rng.random((L, B, H, S, S)).astype(np.float32)
    return maps / maps.sum(-1, keepdims=True)


@pytest.fixture
def stats(attn_maps):
    return AttnStats(
        col_mean=jnp.asarray(attn_maps.mean(axis=3)),
        last_row=jnp.asarray(attn_maps[:, :, :, -1, :]),
    )


def _oracle(method, maps, head_weights=None):
    """Literal translation of get_importance_order (Qwen2-0.5B/main.py:43-98)."""
    res = []
    aggregate = 0.0
    for layer in range(maps.shape[0]):
        if method == "regular_importance":
            avg_heads = maps[layer].mean(axis=1)  # (B, S, S)
            res.append(avg_heads.mean(axis=1).squeeze(0))
        elif method == "weighted_importance":
            weighted = np.zeros_like(maps[layer][:, 0])
            for h in range(maps.shape[2]):
                weighted += maps[layer][:, h] * head_weights[layer][h]
            res.append(weighted.mean(axis=1).squeeze(0))
        elif method == "last_row":
            res.append(maps[layer][:, :, -1, :].mean(axis=1).squeeze(0))
        elif method == "aggregate_till":
            cur = maps[layer].mean(axis=1).squeeze(0).mean(axis=0)
            aggregate = aggregate + cur
            res.append(aggregate / (layer + 1))
    return np.stack(res)


@pytest.mark.parametrize("method", ["regular_importance", "last_row", "aggregate_till"])
def test_methods_match_oracle(attn_maps, stats, method):
    got = np.asarray(importance_per_layer(stats, method))[:, 0]
    np.testing.assert_allclose(got, _oracle(method, attn_maps), atol=1e-6)


def test_weighted_importance_matches_oracle(attn_maps, stats, rng):
    w = rng.random((L, H)).astype(np.float32)
    w /= w.sum(1, keepdims=True)
    got = np.asarray(importance_per_layer(stats, "weighted_importance", jnp.asarray(w)))[:, 0]
    np.testing.assert_allclose(got, _oracle("weighted_importance", attn_maps, w), atol=1e-6)


def test_aggregate_upto_matches_initial_exp(attn_maps, stats):
    """'aggregate upto 2' = mean of col-means of layers 0..2 (initial_exp.py:31-40)."""
    want = 0.0
    for i in range(3):
        want = want + attn_maps[i].mean(axis=1).mean(axis=1).squeeze(0)
    want = want / 3
    got = np.asarray(aggregate_upto(stats.col_mean, 2))[0]
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_maximum_aggregation_matches_initial_exp(attn_maps, stats):
    """elementwise max of col-means of layers 0..2 (initial_exp.py:41-51)."""
    want = np.zeros(S, np.float32)
    for i in range(3):
        want = np.maximum(want, attn_maps[i].mean(axis=1).mean(axis=1).squeeze(0))
    got = np.asarray(maximum_aggregation(stats.col_mean, 2))[0]
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_ordering_is_ascending_stable(stats):
    imp = jnp.asarray([0.3, 0.1, 0.1, 0.5])
    np.testing.assert_array_equal(np.asarray(ordering_from_importance(imp)), [1, 2, 0, 3])


def test_unknown_method_raises(stats):
    with pytest.raises(ValueError):
        importance_per_layer(stats, "nope")
    with pytest.raises(ValueError):
        importance_per_layer(stats, "weighted_importance")  # missing head_weights


class TestBlockedStatsCapture:
    """The streaming (query-blocked) stats path vs the full-probs oracle
    (stats_block=0 IS the old formulation): identical hidden outputs and
    importance statistics without the (B, H, S, S) tensor."""

    @pytest.fixture(scope="class")
    def model(self):
        import jax
        from edgellm_tpu.models import tiny_config, init_params

        cfg = tiny_config("qwen2", num_layers=3, hidden_size=32, num_heads=4,
                          vocab_size=64)
        return cfg, init_params(cfg, jax.random.key(3))

    @pytest.mark.parametrize("seq,blk", [(64, None), (64, 16), (20, None)])
    def test_matches_full_probs_oracle(self, model, rng, seq, blk):
        from edgellm_tpu.models import forward

        cfg, params = model
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, seq)))
        logits_full, aux_full = forward(cfg, params, ids, capture_stats=True,
                                        stats_block=0)
        logits_blk, aux_blk = forward(cfg, params, ids, capture_stats=True,
                                      stats_block=blk)
        np.testing.assert_allclose(np.asarray(logits_blk),
                                   np.asarray(logits_full), atol=1e-5, rtol=1e-5)
        for got, want in zip(aux_blk["stats"], aux_full["stats"]):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=1e-6, rtol=1e-5)
        for method in ("regular_importance", "last_row", "aggregate_till"):
            np.testing.assert_allclose(
                np.asarray(importance_per_layer(aux_blk["stats"], method)),
                np.asarray(importance_per_layer(aux_full["stats"], method)),
                atol=1e-6, rtol=1e-5)

    def test_bad_block_size_raises(self, model, rng):
        from edgellm_tpu.models import forward

        cfg, params = model
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 64)))
        with pytest.raises(ValueError, match="must divide"):
            forward(cfg, params, ids, capture_stats=True, stats_block=24)

    def test_auto_block_sizes(self):
        from edgellm_tpu.models.transformer import _stats_block_size

        assert _stats_block_size(512, None) == 128
        assert _stats_block_size(64, None) == 32  # largest divisor < S
        assert _stats_block_size(20, None) == 20  # no friendly divisor: 1 block
        assert _stats_block_size(512, 0) == 512  # explicit oracle path
        assert _stats_block_size(512, 64) == 64
