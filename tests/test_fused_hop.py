"""Fused quantize->DMA boundary hops: the wire-mode fused hop must be
BIT-identical to the separate encode/ppermute/decode ladder, the gate must
refuse everywhere fusion could regress or lie, and the disabled build must
trace the byte-identical pre-fusion graph.

The load-bearing claims, each asserted here:
- a fused "wire" hop (encode -> seal -> ONE flat uint8 ppermute -> verify ->
  decode) delivers the receiver the exact bytes-and-bits the unfused ladder
  would — for every FUSED_CAPABLE base codec;
- the gating ladder refuses: CPU default (no measured win), remote off-TPU,
  an active FaultyLink, importance-carrying codecs, and EDGELLM_FUSED_HOP=0;
- a forced-wire SplitRuntime is bitwise-identical to the default build at
  forward, decode prefill/step, paged decode step, and whole-generation
  (generate_split) granularity;
- fault injection and FEC repair operate on the SAME flat wire stream the
  fused hop ships (codecs.wire_format owns the layout): a corrupted fused
  buffer fails verification, and FEC parity repairs it back to bit-exact;
- the remote-DMA kernel traces (abstract eval) under shard_map even on CPU,
  so its graph structure is CI-checkable without a TPU.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from edgellm_tpu.codecs.packing import get_wire_codec
from edgellm_tpu.codecs.pallas_kernels import (FUSED_CAPABLE, REMOTE_CAPABLE,
                                               FusedHopPlan, fused_hop_plan,
                                               fused_remote_hop,
                                               fused_wire_hop)
from edgellm_tpu.codecs.wire_format import (WireFormat, flatten_bytes,
                                            seal_payload, unflatten_bytes,
                                            verify_payload)
from edgellm_tpu.models import init_params, tiny_config
from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh
from edgellm_tpu.utils.jax_compat import shard_map

CFG = tiny_config("qwen2", num_layers=4, hidden_size=32, num_heads=4,
                  vocab_size=128)
SPLIT = SplitConfig(cuts=(2,), hop_codecs=("int8_per_token",))


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(3))


@pytest.fixture(scope="module")
def ids():
    rng = np.random.default_rng(11)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (1, 8)))


@pytest.fixture(scope="module")
def mesh():
    return make_stage_mesh(2)


@pytest.fixture(scope="module")
def runtimes(mesh):
    """(default build, forced-wire build, forced-off build) — the env gate
    resolves at construction time, so set it around each __init__."""
    saved = os.environ.get("EDGELLM_FUSED_HOP")
    try:
        os.environ.pop("EDGELLM_FUSED_HOP", None)
        rt = SplitRuntime(CFG, SPLIT, mesh)
        os.environ["EDGELLM_FUSED_HOP"] = "wire"
        rt_wire = SplitRuntime(CFG, SPLIT, mesh)
        os.environ["EDGELLM_FUSED_HOP"] = "0"
        rt_off = SplitRuntime(CFG, SPLIT, mesh)
    finally:
        if saved is None:
            os.environ.pop("EDGELLM_FUSED_HOP", None)
        else:
            os.environ["EDGELLM_FUSED_HOP"] = saved
    return rt, rt_wire, rt_off


# ---------- the wire hop itself: bit-parity vs the separate ladder ----------


def _hop_pair(codec, hidden, fused: bool):
    """Run one 0->1 hop on a 2-stage mesh; returns the (2, ...) per-stage
    results (row 0 = sender, untouched; row 1 = receiver)."""
    mesh = make_stage_mesh(2)

    def body(h):
        idx = jax.lax.axis_index("stage")
        mine = h[0]
        if fused:
            out = fused_wire_hop(codec, mine, 0, "stage", idx)
        else:
            sealed = seal_payload(codec.encode(mine))
            moved = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, "stage", [(0, 1)]), sealed)
            ok = verify_payload(moved)
            dec = codec.decode(moved["p"]).astype(mine.dtype)
            out = jnp.where(idx == 1, jnp.where(ok, dec, mine), mine)
        return out[None]

    fn = shard_map(body, mesh=mesh, in_specs=P("stage"), out_specs=P("stage"))
    stacked = jnp.broadcast_to(hidden[None], (2,) + hidden.shape)
    return np.asarray(jax.jit(fn)(stacked))


@pytest.mark.parametrize("base", sorted(FUSED_CAPABLE))
def test_wire_hop_bit_identical_to_separate_ladder(base):
    codec = get_wire_codec(base)
    rng = np.random.default_rng(7)
    hidden = jnp.asarray(rng.standard_normal((1, 4, 32)), jnp.float32)
    fused = _hop_pair(codec, hidden, fused=True)
    plain = _hop_pair(codec, hidden, fused=False)
    # sender row untouched, receiver row decoded — and both BIT-equal
    np.testing.assert_array_equal(fused[0], np.asarray(hidden))
    np.testing.assert_array_equal(fused, plain)
    assert not np.array_equal(fused[1], np.asarray(hidden)), \
        "receiver row identical to raw hidden: quantization never happened"


def test_wire_format_roundtrip_is_the_sealed_tree():
    codec = get_wire_codec("int8_per_token")
    hidden = jnp.asarray(np.random.default_rng(0).standard_normal((1, 4, 32)),
                         jnp.float32)
    sealed = seal_payload(codec.encode(hidden))
    wf = WireFormat.for_codec(codec, hidden.shape, hidden.dtype)
    back = wf.from_wire(wf.to_wire(sealed))
    for a, b in zip(jax.tree_util.tree_leaves(sealed),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert wf.wire_nbytes == wf.payload_nbytes + 8


# ---------- the gating ladder ----------


def test_gate_default_refuses_on_cpu(monkeypatch):
    monkeypatch.delenv("EDGELLM_FUSED_HOP", raising=False)
    assert fused_hop_plan(get_wire_codec("int8_per_token")) is None


def test_gate_forced_wire(monkeypatch):
    monkeypatch.setenv("EDGELLM_FUSED_HOP", "wire")
    plan = fused_hop_plan(get_wire_codec("int8_per_token"))
    assert plan == FusedHopPlan("wire", "int8_per_token",
                                "forced: EDGELLM_FUSED_HOP=wire")


def test_gate_remote_needs_tpu(monkeypatch):
    monkeypatch.setenv("EDGELLM_FUSED_HOP", "remote")
    assert fused_hop_plan(get_wire_codec("int8_per_token")) is None
    plan = fused_hop_plan(get_wire_codec("int8_per_token"), backend="tpu")
    assert plan is not None and plan.mode == "remote"


def test_gate_best_mode_picks_remote_only_where_capable(monkeypatch):
    monkeypatch.setenv("EDGELLM_FUSED_HOP", "1")
    assert fused_hop_plan(get_wire_codec("int8_per_token")).mode == "wire"
    assert fused_hop_plan(get_wire_codec("int8_per_token"),
                          backend="tpu").mode == "remote"
    assert "ternary_mean" not in REMOTE_CAPABLE
    assert fused_hop_plan(get_wire_codec("ternary_mean"),
                          backend="tpu").mode == "wire"


def test_gate_refusals(monkeypatch):
    monkeypatch.setenv("EDGELLM_FUSED_HOP", "wire")
    codec = get_wire_codec("int8_per_token")
    assert fused_hop_plan(None) is None
    # an active FaultyLink owns the hop (injection/retries/FEC would be
    # bypassed by fusion)
    assert fused_hop_plan(codec, link_active=True) is None
    # importance sidecars don't fit the fused payload
    from edgellm_tpu.codecs.packing import selective_int4

    sel = selective_int4(0.5)
    assert sel.needs_importance and fused_hop_plan(sel) is None
    monkeypatch.setenv("EDGELLM_FUSED_HOP", "0")
    assert fused_hop_plan(codec) is None


def test_gate_default_requires_probe_cache_win(monkeypatch):
    from edgellm_tpu.codecs import probe_cache

    monkeypatch.delenv("EDGELLM_FUSED_HOP", raising=False)
    codec = get_wire_codec("int8_per_token")
    monkeypatch.setattr(probe_cache, "measured_win", lambda name: None)
    assert fused_hop_plan(codec, backend="tpu") is None
    monkeypatch.setattr(probe_cache, "measured_win", lambda name: False)
    assert fused_hop_plan(codec, backend="tpu") is None
    monkeypatch.setattr(probe_cache, "measured_win", lambda name: True)
    plan = fused_hop_plan(codec, backend="tpu")
    assert plan is not None and "measured win" in plan.reason


# ---------- runtime threading: forced-wire == default, bit for bit ----------


def test_runtime_plans_and_provenance(runtimes):
    rt, rt_wire, rt_off = runtimes
    assert all(p is None for p in rt.fused_plans)  # CPU: no measured win
    assert all(p is not None and p.mode == "wire"
               for p in rt_wire.fused_plans)
    assert all(p is None for p in rt_off.fused_plans)
    rows = rt_wire.wire_summary(1, 8)
    assert all(r["fused"] == {"mode": "wire",
                              "reason": "forced: EDGELLM_FUSED_HOP=wire"}
               for r in rows)
    assert all(r["fused"] is None for r in rt.wire_summary(1, 8))


def test_forward_bitwise_parity(runtimes, params, ids):
    rt, rt_wire, _ = runtimes
    out = rt.forward(rt.place_params(params), ids)
    out_f = rt_wire.forward(rt_wire.place_params(params), ids)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out_f))


def test_decode_step_bitwise_parity(runtimes, params, ids):
    rt, rt_wire, _ = runtimes
    placed = rt.place_params(params)
    cap = 16
    logits0, cache0 = rt.prefill_decode(placed, ids, cap)
    logits1, cache1 = rt_wire.prefill_decode(placed, ids, cap)
    np.testing.assert_array_equal(np.asarray(logits0), np.asarray(logits1))
    tok = jnp.argmax(logits0[:, -1], axis=-1).astype(jnp.int32)
    step0, cache0 = rt.decode_step(placed, cache0, tok)
    step1, cache1 = rt_wire.decode_step(placed, cache1, tok)
    np.testing.assert_array_equal(np.asarray(step0), np.asarray(step1))
    np.testing.assert_array_equal(np.asarray(cache0["k"]),
                                  np.asarray(cache1["k"]))


def test_paged_decode_step_bitwise_parity(runtimes, params, ids):
    rt, rt_wire, _ = runtimes
    placed = rt.place_params(params)
    npages, psize = 5, 8
    out = []
    for r in (rt, rt_wire):
        pool = r.init_paged_pool(npages, psize)
        table = jnp.zeros((2, 2), jnp.int32).at[0].set(jnp.asarray([1, 2]))
        lengths = jnp.asarray([ids.shape[1], 0], jnp.int32)
        toks = jnp.asarray([int(ids[0, -1]), 0], jnp.int32)
        out.append(r.decode_step_paged(placed, pool, table, lengths, toks))
    logits0, logits1 = np.asarray(out[0][0]), np.asarray(out[1][0])
    np.testing.assert_array_equal(logits0, logits1)


def test_generate_split_token_identical(runtimes, params, ids):
    from edgellm_tpu.serve import generate_split

    rt, rt_wire, _ = runtimes
    out = generate_split(rt, rt.place_params(params), ids, 6)
    out_f = generate_split(rt_wire, rt_wire.place_params(params), ids, 6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out_f))


def test_fused_disabled_graph_identity(runtimes, params, ids):
    from edgellm_tpu.lint.contracts import graph_fingerprint

    rt, rt_wire, rt_off = runtimes
    placed = rt.place_params(params)
    imps = jnp.zeros((len(rt.codecs), ids.shape[1]), jnp.float32)
    fp_default = graph_fingerprint(rt._forward, placed, ids, imps)
    fp_off = graph_fingerprint(rt_off._forward, placed, ids, imps)
    fp_wire = graph_fingerprint(rt_wire._forward, placed, ids, imps)
    assert fp_off == fp_default  # =0 build IS the pre-fusion graph
    assert fp_wire != fp_default  # the fused build genuinely differs


def test_faulty_link_build_never_fuses(mesh):
    from edgellm_tpu.codecs.faults import FaultConfig, LinkPolicy

    saved = os.environ.get("EDGELLM_FUSED_HOP")
    try:
        os.environ["EDGELLM_FUSED_HOP"] = "wire"
        rt_fault = SplitRuntime(CFG, SPLIT, mesh,
                                faults=FaultConfig(bitflip_rate=0.01, seed=0),
                                policy=LinkPolicy(max_retries=1))
    finally:
        if saved is None:
            os.environ.pop("EDGELLM_FUSED_HOP", None)
        else:
            os.environ["EDGELLM_FUSED_HOP"] = saved
    assert all(p is None for p in rt_fault.fused_plans)


# ---------- faults + FEC through the fused wire stream ----------


def _sealed_payload():
    codec = get_wire_codec("int8_per_token")
    hidden = jnp.asarray(np.random.default_rng(1).standard_normal((1, 4, 32)),
                         jnp.float32)
    return codec, hidden, seal_payload(codec.encode(hidden))


def test_corrupted_fused_buffer_fails_verification():
    codec, hidden, sealed = _sealed_payload()
    wf = WireFormat.for_codec(codec, hidden.shape, hidden.dtype)
    buf = np.asarray(wf.to_wire(sealed))
    assert bool(verify_payload(wf.from_wire(jnp.asarray(buf))))
    for pos in (0, 7, 8, buf.size // 2, buf.size - 1):  # seal AND payload
        bad = buf.copy()
        bad[pos] ^= 0x40
        assert not bool(verify_payload(wf.from_wire(jnp.asarray(bad)))), \
            f"flipped byte {pos} slipped through the fused wire format"


def test_fec_repairs_the_fused_wire_stream():
    from edgellm_tpu.codecs.fec import FECConfig, fec_decode, fec_encode

    _, _, sealed = _sealed_payload()
    cfg = FECConfig(group_size=4, n_groups=4)
    wire = fec_encode(sealed, cfg)
    chunks = np.asarray(wire["chunks"]).copy()
    chunks[2, 1] ^= 0xA5  # one corrupted data chunk: XOR parity territory
    got, any_bad, repaired = fec_decode(
        {"chunks": jnp.asarray(chunks), "words": wire["words"]}, cfg, sealed)
    assert bool(any_bad) and bool(repaired)
    assert bool(verify_payload(got))
    np.testing.assert_array_equal(np.asarray(flatten_bytes(got)),
                                  np.asarray(flatten_bytes(sealed)))


def test_flat_stream_is_shared_by_fec_and_fused_hop():
    # the FEC chunker and the fused hop must serialize the SAME byte order
    codec, hidden, sealed = _sealed_payload()
    wf = WireFormat.for_codec(codec, hidden.shape, hidden.dtype)
    np.testing.assert_array_equal(np.asarray(wf.to_wire(sealed)),
                                  np.asarray(flatten_bytes(sealed)))
    spec = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), sealed)
    back = unflatten_bytes(wf.to_wire(sealed), spec)
    assert bool(verify_payload(back))


# ---------- remote kernel: trace-only on CPU ----------


def test_remote_hop_traces_under_shard_map():
    """The remote-DMA kernel can't EXECUTE off-TPU, but its graph must
    still build (CI checks structure without a TPU)."""
    codec = get_wire_codec("int8_per_token")
    mesh = make_stage_mesh(2)

    def body(h):
        idx = jax.lax.axis_index("stage")
        return fused_remote_hop(codec, h[0], 0, "stage", idx, n_dev=2)[None]

    # check_vma=False matches the production shard_maps in parallel/split.py
    # (pallas_call has no replication rule)
    fn = shard_map(body, mesh=mesh, in_specs=P("stage"),
                   out_specs=P("stage"), check_vma=False)
    hidden = jnp.zeros((2, 1, 4, 32), jnp.float32)
    out = jax.eval_shape(fn, hidden)
    assert out.shape == hidden.shape and out.dtype == jnp.float32
