"""threadlint coverage: every EG1xx rule catches its seeded fixture, the
shipped package is lock-discipline clean, and the deterministic-schedule
harness (lint/schedules.py) proves the Histogram.merge_from ABBA deadlock
reachable under the old source-order acquisition and absent from the
bounded interleaving set under the shipped id()-ordered fix.

The static fixtures live in ``tests/graphlint_fixtures/bad_eg10x.py`` and
are PARSED, never imported (same convention as the EG00x seeds).
"""
import json
import os
import threading
import urllib.request

import pytest

from edgellm_tpu.lint.schedules import (Scheduler, explore, instrument,
                                        run_schedule)
from edgellm_tpu.lint.threadlint import (lint_file, lint_files, lint_package,
                                         lint_source)
from edgellm_tpu.obs.flight import FlightRecorder, load_flight
from edgellm_tpu.obs.metrics import Histogram, MetricsRegistry
from edgellm_tpu.utils.concurrency import acquire_in_order, guarded_by

FIXTURES = os.path.join(os.path.dirname(__file__), "graphlint_fixtures")


def _fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


# ---------------------------------------------------------------------------
# static layer: each EG1xx rule catches its seeded fixture
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fixture,rule,min_hits", [
    ("bad_eg101.py", "EG101", 3),  # declared + auto-discovered bare writes
    ("bad_eg102.py", "EG102", 2),  # cross-instance order + re-acquire
    ("bad_eg103.py", "EG103", 3),  # sleep / open / block_until_ready held
    ("bad_eg104.py", "EG104", 4),  # self-stored / foreign / lost / leaked
])
def test_thread_rule_catches_fixture(fixture, rule, min_hits):
    findings = lint_file(_fixture(fixture))
    hits = [f for f in findings if f.rule == rule]
    assert len(hits) >= min_hits, \
        f"{fixture}: expected >= {min_hits} {rule} findings, got {findings}"
    assert all(f.line > 0 for f in hits)
    assert all(f.layer == "thread" for f in findings)


def test_thread_rules_only_fire_their_own_fixture():
    """Each seeded fixture trips exactly its own rule — no cross-noise."""
    for fx, rule in [("bad_eg101.py", "EG101"), ("bad_eg103.py", "EG103"),
                     ("bad_eg104.py", "EG104")]:
        rules = {f.rule for f in lint_file(_fixture(fx))}
        assert rules == {rule}, (fx, rules)


def test_real_package_thread_clean():
    """Acceptance: the shipped package carries no EG1xx violations."""
    import edgellm_tpu
    from edgellm_tpu.lint.ast_rules import iter_package_files

    pkg_root = os.path.dirname(os.path.abspath(edgellm_tpu.__file__))
    findings = lint_files(iter_package_files(pkg_root))
    assert findings == [], [f.format() for f in findings]
    assert lint_package(pkg_root) == []


def test_suppression_comment_disables_thread_rule():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.x = 0\n"
        "    def locked(self):\n"
        "        with self._lock:\n"
        "            self.x += 1\n"
        "    def bare(self):\n"
        "        self.x = 1{sup}\n")
    assert {f.rule for f in lint_source(src.format(sup=""), "t.py")} \
        == {"EG101"}
    sup = "  # graphlint: disable=EG101"
    assert lint_source(src.format(sup=sup), "t.py") == []
    # an unrelated rule id does not suppress it
    wrong = "  # graphlint: disable=EG103"
    assert {f.rule for f in lint_source(src.format(sup=wrong), "t.py")} \
        == {"EG101"}


def test_clean_locked_class_passes():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.x = 0\n"
        "    def inc(self):\n"
        "        with self._lock:\n"
        "            self.x += 1\n"
        "    def get(self):\n"
        "        with self._lock:\n"
        "            return self.x\n")
    assert lint_source(src, "t.py") == []


def test_contextvar_clean_pattern_passes():
    """set + try/finally reset in the same frame (the obs/context.py bind()
    shape) is the blessed pattern and must not fire EG104."""
    src = (
        "import contextvars\n"
        "CV = contextvars.ContextVar('cv', default='')\n"
        "def scoped(v):\n"
        "    token = CV.set(v)\n"
        "    try:\n"
        "        return CV.get()\n"
        "    finally:\n"
        "        CV.reset(token)\n")
    assert lint_source(src, "t.py") == []


def test_eg102_fires_on_old_merge_from_shape():
    """The exact pre-fix metrics.py:218 shape — source-order acquisition of
    two same-class instance locks — must be flagged."""
    src = (
        "import threading\n"
        "class Histogram:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0\n"
        "    def merge_from(self, other):\n"
        "        with self._lock, other._lock:\n"
        "            self.count += other.count\n")
    findings = lint_source(src, "metrics_old.py")
    assert any(f.rule == "EG102" and f.line == 7 for f in findings), findings


def test_shipped_metrics_module_thread_clean():
    import edgellm_tpu.obs.metrics as m

    assert lint_file(os.path.abspath(m.__file__)) == []


def test_guarded_by_metadata():
    @guarded_by("_lock", fields=["a", "b"])
    class C:
        pass

    assert C.__guarded_by__ == {"lock": "_lock", "fields": ("a", "b")}
    # the shipped contracts are declared where threadlint expects them
    from edgellm_tpu.obs.metrics import MetricsRegistry as MR

    assert "_metrics" in MR.__guarded_by__["fields"]


def test_acquire_in_order_is_id_ordered_and_reentrant_safe():
    a, b = threading.Lock(), threading.Lock()
    with acquire_in_order(a, b):
        assert a.locked() and b.locked()
    assert not a.locked() and not b.locked()
    # duplicates are deduped, not double-acquired
    with acquire_in_order(a, a, b):
        assert a.locked() and b.locked()
    assert not a.locked() and not b.locked()


# ---------------------------------------------------------------------------
# dynamic layer: the schedule harness
# ---------------------------------------------------------------------------


def _two_histograms(sched):
    a = Histogram("a", lo=0.1, hi=10.0, n_buckets=4)
    b = Histogram("b", lo=0.1, hi=10.0, n_buckets=4)
    a.observe(1.0)
    b.observe(2.0)
    instrument(sched, a)
    instrument(sched, b)
    return a, b


def _unordered_merge(dst, src):
    """The pre-fix merge_from: source-order lock acquisition (the EG102
    seed). Kept here so the deadlock stays demonstrable after the fix."""
    with dst._lock:
        with src._lock:
            dst.count += src.count
            dst.sum += src.sum


def test_harness_finds_prefix_merge_deadlock():
    """Pre-fix cross-merge deadlocks within the 2-preemption bound, and the
    found schedule replays deterministically."""

    def scenario(sched):
        a, b = _two_histograms(sched)
        return [lambda: _unordered_merge(a, b),
                lambda: _unordered_merge(b, a)]

    outcomes = explore(scenario, max_preemptions=2)
    dead = [o for o in outcomes if o.deadlocked]
    assert dead, "bounded search failed to reach the known ABBA deadlock"
    first = dead[0]
    # both workers are stuck on the *other* instance's lock
    assert set(first.blocked) == {0, 1}
    assert all(name == "Histogram._lock" for name in first.blocked.values())
    # replay: the recorded decisions reproduce the deadlock exactly
    replay = run_schedule(scenario,
                          decisions=[idx for _, idx in first.choice_points])
    assert replay.deadlocked
    assert replay.schedule == first.schedule


def test_shipped_merge_from_is_deadlock_free():
    """Post-fix acceptance: id()-ordered acquisition leaves NO deadlocking
    schedule in the bounded interleaving set, and every schedule merges
    conservation-correct totals (5 observations counted across the pair)."""

    def scenario(sched):
        a, b = _two_histograms(sched)

        def verify():
            assert a.count + b.count == 5, (a.count, b.count)

        return ([lambda: a.merge_from(b), lambda: b.merge_from(a)], verify)

    outcomes = explore(scenario, max_preemptions=3)
    assert len(outcomes) > 1  # the bound actually explored interleavings
    assert not any(o.deadlocked for o in outcomes), \
        [o.blocked for o in outcomes if o.deadlocked]
    assert not any(o.errors for o in outcomes), \
        [o.errors for o in outcomes if o.errors]


def test_real_thread_cross_merge_regression():
    """Satellite regression: real threads hammering A.merge_from(B) against
    B.merge_from(A) must finish (pre-fix this wedges in milliseconds)."""
    a = Histogram("a", lo=0.1, hi=10.0, n_buckets=4)
    b = Histogram("b", lo=0.1, hi=10.0, n_buckets=4)
    a.observe(1.0)
    b.observe(2.0)
    start = threading.Barrier(2)

    def pound(dst, src):
        start.wait()
        for _ in range(300):
            dst.merge_from(src)

    t1 = threading.Thread(target=pound, args=(a, b), daemon=True)
    t2 = threading.Thread(target=pound, args=(b, a), daemon=True)
    t1.start()
    t2.start()
    t1.join(timeout=30)
    t2.join(timeout=30)
    assert not t1.is_alive() and not t2.is_alive(), \
        "cross-merge deadlocked: ordered acquisition regressed"


def test_harness_registry_inc_vs_snapshot():
    """Concurrent submit-path inc against a /snapshot.json-style scrape:
    no deadlock, no torn final state, over all bounded interleavings."""

    def scenario(sched):
        reg = MetricsRegistry(enabled=True)
        c = reg.counter("tl_sched_total", "seed")
        instrument(sched, reg)
        instrument(sched, c)
        seen = []

        def writer():
            c.inc()
            c.inc()

        def scraper():
            seen.append(reg.snapshot())

        def verify():
            snap = reg.snapshot()["tl_sched_total"]["values"]
            total = sum(snap.values()) if isinstance(snap, dict) else snap
            assert total == 2.0, snap
            for s in seen:  # mid-run scrapes saw 0, 1 or 2 — never garbage
                vals = s["tl_sched_total"]["values"]
                got = sum(vals.values()) if isinstance(vals, dict) else vals
                assert got in (0.0, 1.0, 2.0), s

        return ([writer, scraper], verify)

    outcomes = explore(scenario, max_preemptions=2)
    assert not any(o.deadlocked or o.errors for o in outcomes), \
        [(o.blocked, o.errors) for o in outcomes if not o.ok]


def test_harness_flight_append_vs_dump(tmp_path):
    """Flight-ring append racing a post-mortem dump: every interleaving
    completes and the artifact passes its CRC frame check."""

    def scenario(sched):
        rec = FlightRecorder(str(tmp_path), capacity=8)
        instrument(sched, rec)
        paths = []

        def appender():
            rec.note_counters("race", {"n": 1})

        def dumper():
            paths.append(rec.dump("sched_race"))

        def verify():
            assert paths and load_flight(paths[-1])["reason"] == "sched_race"

        return ([appender, dumper], verify)

    outcomes = explore(scenario, max_preemptions=2)
    assert not any(o.deadlocked or o.errors for o in outcomes), \
        [(o.blocked, o.errors) for o in outcomes if not o.ok]


def test_harness_self_deadlock_detected():
    """Re-acquiring a non-reentrant SchedLock is reported as a worker error
    (the EG102 re-acquire rule's dynamic twin), not a hang."""

    def scenario(sched):
        class Box:
            def __init__(self):
                self._lock = threading.Lock()

        box = instrument(sched, Box())

        def hog():
            with box._lock:
                with box._lock:
                    pass

        return [hog]

    out = run_schedule(scenario)
    assert not out.deadlocked
    assert len(out.errors) == 1
    assert "self-deadlock" in str(out.errors[0][1])


# ---------------------------------------------------------------------------
# satellite: live scrape under concurrent writes never tears
# ---------------------------------------------------------------------------


def test_concurrent_scrape_never_tears():
    """N scraper threads hammering /metrics + /snapshot.json against a hot
    writer: the exposition parses every time, the watched counter is
    monotone per scraper, and every snapshot is valid JSON."""
    from edgellm_tpu.obs.server import ObsServer

    reg = MetricsRegistry(enabled=True)
    counter = reg.counter("tl_scrape_total", "writer progress")
    hist = reg.histogram("tl_scrape_seconds", "writer latencies",
                         lo=1e-4, hi=10.0, n_buckets=16)
    counter.inc()  # seed so the first scrape always has a sample line
    hist.observe(1e-3)
    srv = ObsServer(port=0, registry=reg)
    srv.start()
    stop = threading.Event()
    failures = []

    def writer():
        i = 0
        while not stop.is_set():
            counter.inc()
            hist.observe(1e-3 * (1 + i % 7))
            i += 1

    def scrape(kind):
        with urllib.request.urlopen(f"{srv.url}{kind}", timeout=10) as r:
            return r.read().decode("utf-8")

    def scraper():
        last = -1.0
        try:
            for i in range(30):
                text = scrape("/metrics")
                value = None
                for line in text.splitlines():
                    if not line or line.startswith("#"):
                        continue
                    # every sample line must parse: "<series> <float>"
                    float(line.rsplit(None, 1)[1])
                    if line.startswith("tl_scrape_total"):
                        value = float(line.rsplit(None, 1)[1])
                assert value is not None, "counter missing from exposition"
                assert value >= last, f"counter went backwards: {value}<{last}"
                last = value
                snap = json.loads(scrape("/snapshot.json"))
                assert "tl_scrape_total" in json.dumps(snap["metrics"])
        except Exception as e:  # noqa: BLE001 - surfaced via failures
            failures.append(e)

    w = threading.Thread(target=writer, daemon=True)
    scrapers = [threading.Thread(target=scraper, daemon=True)
                for _ in range(4)]
    w.start()
    for t in scrapers:
        t.start()
    for t in scrapers:
        t.join(timeout=60)
    stop.set()
    w.join(timeout=10)
    srv.stop()
    assert not failures, failures
    assert all(not t.is_alive() for t in scrapers)
