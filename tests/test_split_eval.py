"""Split-eval driver tests: the mesh-split PPL must equal the single-device
simulated-boundary PPL (same metric, real transport), covering the BASELINE
config shapes on tiny models."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.models import tiny_config, init_params, forward, nll_from_logits
from edgellm_tpu.codecs import per_token_affine_int8
from edgellm_tpu.eval import run_split_eval, parse_hop_codec, sliding_windows
from edgellm_tpu.codecs.packing import WireCodec

CFG = tiny_config("qwen2", num_layers=6, hidden_size=32, num_heads=4, vocab_size=128)


@pytest.fixture(scope="module")
def setup():
    params = init_params(CFG, jax.random.key(5))
    corpus = np.random.default_rng(6).integers(0, CFG.vocab_size, 120)
    return params, corpus


def test_parse_hop_codec():
    assert parse_hop_codec("int4_per_token") == "int4_per_token"
    c = parse_hop_codec("selective_int4:0.5:fp32")
    assert isinstance(c, WireCodec) and c.needs_importance
    assert "0.5" in c.name and "fp32" in c.name


def test_fp32_split_eval_matches_unsplit_ppl(setup):
    params, corpus = setup
    res = run_split_eval(CFG, params, corpus, cuts=[2], hop_codecs=["fp32"],
                         max_length=48, stride=24)
    total, n = 0.0, 0
    for chunk in sliding_windows(corpus, 48, 24):
        logits, _ = forward(CFG, params, jnp.asarray(chunk.input_ids))
        total += float(nll_from_logits(logits, jnp.asarray(chunk.target_ids))) * chunk.num_loss_tokens
        n += chunk.num_loss_tokens
    assert res["n_tokens"] == n
    np.testing.assert_allclose(res["ppl"], np.exp(total / n), rtol=1e-5)
    assert res["bytes_per_token_per_hop"] == [CFG.hidden_size * 4]


def test_int8_split_eval_matches_simulated_boundary(setup):
    params, corpus = setup
    res = run_split_eval(CFG, params, corpus, cuts=[2], hop_codecs=["int8_per_token"],
                         max_length=48, stride=24)
    total, n = 0.0, 0
    for chunk in sliding_windows(corpus, 48, 24):
        def bfn(idx, h):
            return jnp.where(idx == 2, per_token_affine_int8(h), h)
        logits, _ = forward(CFG, params, jnp.asarray(chunk.input_ids), boundary_fn=bfn)
        total += float(nll_from_logits(logits, jnp.asarray(chunk.target_ids))) * chunk.num_loss_tokens
        n += chunk.num_loss_tokens
    np.testing.assert_allclose(res["ppl"], np.exp(total / n), rtol=1e-5)


def test_selective_hop_with_importance(setup):
    params, corpus = setup
    res = run_split_eval(
        CFG, params, corpus, cuts=[2],
        hop_codecs=["selective_int4:0.5:fp32"],
        importance_method="last_row",
        max_length=48, stride=24)
    assert np.isfinite(res["ppl"]) and res["chunks"] > 0
    with pytest.raises(ValueError, match="importance_method"):
        run_split_eval(CFG, params, corpus, cuts=[2],
                       hop_codecs=["selective_int4:0.5:fp32"],
                       max_length=48, stride=24)


def test_multihop_split_eval(setup):
    params, corpus = setup
    res = run_split_eval(
        CFG, params, corpus, cuts=[1, 3],
        hop_codecs=["int8_per_token", "int4_per_token"],
        max_length=48, stride=24)
    assert np.isfinite(res["ppl"])
    assert res["mesh"]["stage"] == 3
    assert len(res["bytes_per_token_per_hop"]) == 2


@pytest.mark.parametrize("n_data", [1, 2])
def test_window_batched_split_eval_matches_unbatched(setup, n_data):
    """window_batch > 1 (optionally data-sharded) must reproduce the
    chunk-by-chunk split eval exactly, including with a token-selective hop
    carrying per-row importance."""
    from edgellm_tpu.parallel import make_stage_mesh

    params, corpus = setup
    kw = dict(cuts=[2], hop_codecs=["selective_int4:0.5:fp32"],
              max_length=16, stride=8, importance_method="regular_importance",
              time_hops=False)
    want = run_split_eval(CFG, params, corpus,
                          mesh=make_stage_mesh(2), **kw)
    got = run_split_eval(CFG, params, corpus, window_batch=4,
                         mesh=make_stage_mesh(2, n_data=n_data), **kw)
    assert got["chunks"] == want["chunks"]
    assert got["n_tokens"] == want["n_tokens"]
    np.testing.assert_allclose(got["ppl"], want["ppl"], rtol=1e-6)


def test_window_batch_not_multiple_of_data_axis_raises(setup):
    from edgellm_tpu.parallel import make_stage_mesh

    params, corpus = setup
    with pytest.raises(ValueError, match="multiple"):
        run_split_eval(CFG, params, corpus, cuts=[2], hop_codecs=["fp32"],
                       max_length=16, stride=8, window_batch=3,
                       mesh=make_stage_mesh(2, n_data=2), time_hops=False)


def test_checkpoint_resume_exact(setup, tmp_path):
    """Kill/resume: a run interrupted mid-corpus and resumed from its checkpoint
    produces IDENTICAL final PPL, token counts, and measured byte totals."""
    params, corpus = setup
    kw = dict(cuts=[2], hop_codecs=["int8_per_token"], max_length=16, stride=8,
              window_batch=2, time_hops=False)
    want = run_split_eval(CFG, params, corpus, **kw)

    ckpt = str(tmp_path / "split_ckpt.json")
    metrics = str(tmp_path / "metrics.jsonl")
    partial = run_split_eval(CFG, params, corpus, max_chunks=4,
                             checkpoint_path=ckpt, checkpoint_every=2,
                             metrics_path=metrics, **kw)
    assert partial["chunks"] == 4
    got = run_split_eval(CFG, params, corpus, checkpoint_path=ckpt,
                         checkpoint_every=2, metrics_path=metrics, **kw)
    assert got["chunks"] == want["chunks"]
    assert got["n_tokens"] == want["n_tokens"]
    assert got["measured_hop_bytes_total"] == want["measured_hop_bytes_total"]
    assert got["real_fwd_tokens"] == want["real_fwd_tokens"]
    np.testing.assert_allclose(got["ppl"], want["ppl"], rtol=1e-12)
    import json as _json
    lines = [_json.loads(l) for l in open(metrics)]
    assert lines[-1]["final"] and lines[-1]["chunks"] == want["chunks"]


def test_repeated_kill_resume_exact(setup, tmp_path):
    """Three successive interruptions at different points, then completion:
    the final totals must equal the uninterrupted run's exactly (the
    reference's 9,347-chunk corpus makes multi-crash runs a realistic case)."""
    params, corpus = setup
    kw = dict(cuts=[2], hop_codecs=["int8_per_token"], max_length=16, stride=8,
              window_batch=2, time_hops=False)
    want = run_split_eval(CFG, params, corpus, **kw)

    ckpt = str(tmp_path / "ckpt.json")
    for stop_at in (2, 5, 9):
        partial = run_split_eval(CFG, params, corpus, max_chunks=stop_at,
                                 checkpoint_path=ckpt, checkpoint_every=1, **kw)
        assert partial["chunks"] == stop_at
    got = run_split_eval(CFG, params, corpus, checkpoint_path=ckpt,
                         checkpoint_every=1, **kw)
    assert got["chunks"] == want["chunks"]
    assert got["measured_hop_bytes_total"] == want["measured_hop_bytes_total"]
    np.testing.assert_allclose(got["ppl"], want["ppl"], rtol=1e-12)


def test_checkpoint_axes_mismatch_raises(setup, tmp_path):
    params, corpus = setup
    ckpt = str(tmp_path / "ckpt.json")
    run_split_eval(CFG, params, corpus, cuts=[2], hop_codecs=["int8_per_token"],
                   max_length=16, stride=8, max_chunks=2, checkpoint_path=ckpt,
                   checkpoint_every=1, time_hops=False)
    with pytest.raises(ValueError, match="different sweep configuration"):
        run_split_eval(CFG, params, corpus, cuts=[2], hop_codecs=["int4_per_token"],
                       max_length=16, stride=8, checkpoint_path=ckpt,
                       time_hops=False)


def test_pad_accounting_fields(setup):
    """pad_fraction separates wire traffic from useful throughput: padded
    windows (partial group under a data axis) and seq-pad positions are in
    fwd_tokens but not real_fwd_tokens."""
    from edgellm_tpu.parallel import make_stage_mesh

    params, corpus = setup
    # 13 windows at stride 8 -> last full group padded; n_seq=3 pads 16 -> 18
    res = run_split_eval(CFG, params, corpus, cuts=[2],
                         hop_codecs=["int8_per_token"], max_length=16, stride=8,
                         n_seq=3, window_batch=2, time_hops=False)
    assert 0.0 < res["pad_fraction"] < 1.0
    assert res["real_tokens_per_s"] > 0

    none = run_split_eval(CFG, params, corpus, cuts=[2],
                          hop_codecs=["int8_per_token"], max_length=16, stride=8,
                          time_hops=False)
    assert none["pad_fraction"] == 0.0


def test_ring_split_eval_matches_plain(setup):
    """n_seq > 1 (stage x seq ring runtime) reproduces the plain split eval,
    including a window length that needs right-padding to shard."""
    from edgellm_tpu.parallel import make_stage_mesh

    params, corpus = setup
    kw = dict(cuts=[2], hop_codecs=["int8_per_token"], max_length=18, stride=9,
              time_hops=False)
    want = run_split_eval(CFG, params, corpus[:100],
                          mesh=make_stage_mesh(2), **kw)
    got = run_split_eval(CFG, params, corpus[:100], n_seq=3, **kw)
    assert got["chunks"] == want["chunks"]
    assert got["n_tokens"] == want["n_tokens"]
    np.testing.assert_allclose(got["ppl"], want["ppl"], rtol=2e-5)
    assert got["mesh"] == {"stage": 2, "seq": 3}
