"""Unified telemetry (obs/) coverage: histogram quantiles vs numpy, span
nesting/threading and Chrome trace-event schema, Prometheus round-trip,
adapter parity with the legacy counter dicts, the CounterSource protocol,
and the zero-residue guarantees — obs disabled (the default) must trace
byte-identical jaxprs, and enabled instrumentation must not change the
sampled tokens. The ≤3% decode-overhead budget rides the slow marker (the
same number BENCH_DECODE=1 records as ``obs_overhead_frac``)."""
import json
import math
import threading

import numpy as np
import pytest

from edgellm_tpu import obs
from edgellm_tpu.obs import metrics as obs_metrics
from edgellm_tpu.obs.latency import LatencyObserver
from edgellm_tpu.obs.metrics import (Counter, CounterSource, Gauge, Histogram,
                                     MetricsRegistry, format_table,
                                     record_decode_stats, record_link_counters,
                                     record_link_health,
                                     record_recovery_counters,
                                     record_wire_bytes)


@pytest.fixture(autouse=True)
def _clean_obs():
    """Never leak an armed registry/tracer (process-global) across tests."""
    yield
    obs.disable()
    obs.get_registry().clear()
    obs.get_tracer().clear()


# ---------------------------------------------------------------------------
# metrics primitives
# ---------------------------------------------------------------------------


def test_counter_and_gauge_basics():
    c = Counter("c", "help")
    c.inc()
    c.inc(2.5, hop=0)
    assert c.value() == 1.0
    assert c.value(hop=0) == 2.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = Gauge("g")
    g.set(3.0)
    g.inc(-1.5)
    assert g.value() == 1.5  # gauges go both ways


def test_histogram_quantiles_match_numpy():
    """Interpolated p50/p95/p99 within one bucket's relative width of
    numpy's linear-interpolation percentiles on a latency-shaped sample."""
    rng = np.random.default_rng(0)
    xs = rng.lognormal(mean=-4.0, sigma=1.0, size=20_000)  # ~18ms median
    h = Histogram("h", lo=1e-5, hi=1e2, n_buckets=480)
    for x in xs:
        h.observe(float(x))
    bucket_width = (1e2 / 1e-5) ** (1.0 / 480) - 1.0  # ~3.4% relative
    for q in (0.50, 0.95, 0.99):
        got = h.quantile(q)
        want = float(np.percentile(xs, q * 100))
        assert abs(got - want) / want < 1.5 * bucket_width, (q, got, want)
    p = h.percentiles()
    assert p["count"] == 20_000
    assert p["min"] == xs.min() and p["max"] == xs.max()
    np.testing.assert_allclose(p["mean"], xs.mean(), rtol=1e-9)


def test_histogram_bounds_and_edge_cases():
    h = Histogram("h", lo=1e-3, hi=1.0, n_buckets=8)
    assert math.isnan(h.quantile(0.5))  # empty
    for v in (1e-6, 0.5, 100.0):  # underflow, in-range, overflow
        h.observe(v)
    # quantiles stay inside the observed extremes despite coarse buckets
    for q in (0.0, 0.5, 1.0):
        assert 1e-6 <= h.quantile(q) <= 100.0
    with pytest.raises(ValueError):
        h.quantile(1.5)
    with pytest.raises(ValueError):
        Histogram("bad", lo=1.0, hi=0.5)


def test_histogram_merge_from():
    a = Histogram("h", lo=1e-3, hi=1.0, n_buckets=32)
    b = Histogram("h", lo=1e-3, hi=1.0, n_buckets=32)
    for v in (0.01, 0.02):
        a.observe(v)
    for v in (0.2, 0.4, 0.8):
        b.observe(v)
    a.merge_from(b)
    assert a.count == 5
    np.testing.assert_allclose(a.sum, 0.01 + 0.02 + 0.2 + 0.4 + 0.8)
    assert a.percentiles()["max"] == 0.8
    with pytest.raises(ValueError):
        a.merge_from(Histogram("h", lo=1e-3, hi=1.0, n_buckets=16))


def test_registry_get_or_create_and_kind_mismatch():
    reg = MetricsRegistry(enabled=True)
    c1 = reg.counter("x_total")
    assert reg.counter("x_total") is c1  # get-or-create, never re-registered
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    assert reg.names() == ["x_total"]
    reg.clear()
    assert reg.names() == []


def test_prometheus_text_format_round_trip():
    """Every sample line of the exposition parses back to the registry's
    value; histogram bucket series are cumulative and consistent."""
    reg = MetricsRegistry(enabled=True)
    reg.counter("edgellm_x_total", "a counter").inc(3, hop=1)
    reg.gauge("edgellm_g", "a gauge").set(2.5)
    h = reg.histogram("edgellm_h", "a histogram", lo=1e-3, hi=1.0,
                      n_buckets=16)
    for v in (0.01, 0.1, 0.5):
        h.observe(v)
    text = reg.to_prometheus()
    assert "# HELP edgellm_x_total a counter" in text
    assert "# TYPE edgellm_h histogram" in text
    assert 'edgellm_x_total{hop="1"} 3.0' in text
    samples = {}
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        name_labels, val = line.rsplit(" ", 1)
        samples[name_labels] = float(val)
    assert samples['edgellm_x_total{hop="1"}'] == 3.0
    assert samples["edgellm_g"] == 2.5
    assert samples["edgellm_h_count"] == 3
    np.testing.assert_allclose(samples["edgellm_h_sum"], 0.61)
    buckets = [(k, v) for k, v in samples.items()
               if k.startswith("edgellm_h_bucket")]
    cums = [v for _, v in buckets]
    assert cums == sorted(cums)  # cumulative le-series never decreases
    assert any(k.endswith('le="+Inf"}') and v == 3 for k, v in buckets)
    # the JSON exporter round-trips through json.loads
    snap = json.loads(reg.to_json())
    assert snap["edgellm_h"]["kind"] == "histogram"
    assert snap["edgellm_x_total"]["values"]['{hop="1"}'] == 3.0


def test_format_table_renders_all_kinds():
    reg = MetricsRegistry(enabled=True)
    reg.counter("edgellm_x_total").inc(2, hop=0)
    reg.histogram("edgellm_lat", lo=1e-3, hi=1.0, n_buckets=8).observe(0.1)
    out = format_table(reg, title="t")
    assert out.startswith("t:")
    assert 'edgellm_x_total{hop="0"}' in out
    assert "edgellm_lat.p99" in out
    assert format_table(MetricsRegistry(), title="e") == "e: (empty)"


# ---------------------------------------------------------------------------
# adapters: registry values == the legacy dict shapes
# ---------------------------------------------------------------------------


def test_adapter_parity_link_counters():
    delta = {"detected": np.array([2, 0]), "repaired": [1, 3]}
    reg = MetricsRegistry(enabled=True)
    record_link_counters(delta, registry=reg)
    c = reg.get("edgellm_link_detected_total")
    assert c.value(hop=0) == 2 and c.value(hop=1) == 0  # zero hops skipped
    r = reg.get("edgellm_link_repaired_total")
    assert r.value(hop=0) == 1 and r.value(hop=1) == 3
    # the registry totals match the legacy dict exactly
    for key, per_hop in delta.items():
        got = sum(v for _, v in reg.get(f"edgellm_link_{key}_total").items())
        assert got == sum(int(x) for x in per_hop)
    # disabled registry records nothing at all
    off = MetricsRegistry(enabled=False)
    record_link_counters(delta, registry=off)
    assert off.names() == []


def test_adapter_parity_recovery_health_decode_wire():
    from edgellm_tpu.serve.recovery import RecoveryCounters

    reg = MetricsRegistry(enabled=True)
    rc = RecoveryCounters(failovers=1, checkpoints_written=4)
    record_recovery_counters(rc, registry=reg)
    assert reg.get("edgellm_recovery_failovers_total").value() == 1
    assert reg.get("edgellm_recovery_checkpoints_written_total").value() == 4
    assert reg.get("edgellm_recovery_replans_total") is None  # zeros skipped

    health = {"tier": 1, "burn_rate": 0.25, "corruption_rate": 0.01,
              "window": 128, "note": "not-a-number"}
    record_link_health(health, registry=reg)
    assert reg.get("edgellm_link_health_burn_rate").value() == 0.25
    assert reg.get("edgellm_link_health_tier").value() == 1
    assert reg.get("edgellm_link_health_note") is None  # non-numeric skipped

    record_decode_stats({"decode_step_cache_misses": 2, "decode_steps": 63,
                         "prefill_s": 0.5, "decode_s": 1.25}, registry=reg)
    assert reg.get("edgellm_decode_jit_cache_misses_total").value() == 2
    assert reg.get("edgellm_decode_steps_total").value() == 63
    assert reg.get("edgellm_decode_decode_s").value() == 1.25

    record_wire_bytes([100.0, 50.0], kind="decode", steps=10, registry=reg)
    w = reg.get("edgellm_wire_bytes_total")
    assert w.value(hop=0, kind="decode") == 1000.0
    assert w.value(hop=1, kind="decode") == 500.0


def test_counter_source_protocol_covers_all_runtimes():
    """The typed replacement for hasattr(rt, "link_counters"): every decode
    runtime satisfies the protocol structurally (no inheritance)."""
    from edgellm_tpu.parallel.ring import SplitRingRuntime
    from edgellm_tpu.parallel.split import SplitRuntime
    from edgellm_tpu.serve.recovery import LocalRuntime

    for cls in (SplitRuntime, SplitRingRuntime, LocalRuntime):
        assert isinstance(cls.__new__(cls), CounterSource), cls
    assert not isinstance(object(), CounterSource)


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------


def test_span_nesting_ordering_and_threads():
    obs.enable(obs.ObservabilityConfig(metrics=False, tracing=True,
                                       latency=False))
    tracer = obs.get_tracer()
    tracer.clear()

    def work(tag):
        with obs.span(f"outer.{tag}", tag=tag):
            with obs.span(f"inner.{tag}"):
                pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with obs.span("main.solo"):
        pass
    spans = {s.name: s for s in tracer.spans()}
    assert len(spans) == 9
    for i in range(4):
        outer, inner = spans[f"outer.{i}"], spans[f"inner.{i}"]
        assert outer.tid == inner.tid  # per-thread lanes
        assert outer.ts_us <= inner.ts_us  # child opens inside parent
        assert outer.dur_us >= inner.dur_us  # and closes inside it
        assert outer.args["tag"] == i
    assert spans["main.solo"].tid != spans["outer.0"].tid


def test_chrome_trace_schema_and_export(tmp_path):
    obs.enable(obs.ObservabilityConfig(metrics=False, tracing=True,
                                       latency=False))
    tracer = obs.get_tracer()
    tracer.clear()
    with obs.span("a", shape=(2, 3), n=7):  # non-primitive arg -> repr
        with obs.span("b"):
            pass
    path = tmp_path / "trace.json"
    tracer.export(str(path))
    trace = json.load(open(path))
    assert set(trace) == {"traceEvents", "displayTimeUnit"}
    assert trace["displayTimeUnit"] == "ms"
    for ev in trace["traceEvents"]:
        assert ev["ph"] == "X"
        assert {"name", "ts", "dur", "pid", "tid"} <= set(ev)
        assert isinstance(ev["ts"], (int, float)) and ev["dur"] >= 0
    ev_a = next(e for e in trace["traceEvents"] if e["name"] == "a")
    assert ev_a["args"] == {"shape": "(2, 3)", "n": 7}
    # events come out (tid, ts)-sorted — stable lanes in Perfetto
    keys = [(e["tid"], e["ts"]) for e in trace["traceEvents"]]
    assert keys == sorted(keys)


def test_span_disabled_is_free_and_records_nothing():
    assert not obs.enabled()
    tracer = obs.get_tracer()
    tracer.clear()
    cm1, cm2 = obs.span("x"), obs.span("y", k=1)
    assert cm1 is cm2  # the shared nullcontext: zero allocation per call
    with cm1 as s:
        assert s is None
    assert tracer.spans() == []


def test_trace_capture_shim_and_deprecation(tmp_path):
    """utils.profiling.trace delegates (with a DeprecationWarning) to
    obs.tracing.trace_capture, which degrades to a warning — never a crash —
    when the profiler can't start."""
    from edgellm_tpu.utils import profiling

    with pytest.deprecated_call():
        with profiling.trace(str(tmp_path / "xla")):
            pass
    # double-start degrades: the second capture warns instead of raising
    from edgellm_tpu.obs.tracing import trace_capture

    with trace_capture(str(tmp_path / "a")):
        with trace_capture(str(tmp_path / "b")):
            pass


# ---------------------------------------------------------------------------
# latency + decode integration: zero residue, identical tokens
# ---------------------------------------------------------------------------


def _tiny_setup():
    import jax
    from edgellm_tpu.models import init_params, tiny_config

    cfg = tiny_config("qwen2", num_layers=2, hidden_size=32, num_heads=4,
                      vocab_size=64)
    params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    ids = np.asarray(rng.integers(0, cfg.vocab_size, (2, 4)))
    return cfg, params, ids


def test_latency_observer_summary_and_publish():
    obs.enable(obs.ObservabilityConfig())
    lat = LatencyObserver()
    lat.start()
    lat.first_token(np.zeros(2))
    for _ in range(8):
        lat.token(np.zeros(2))
    s = lat.summary()
    assert {"ttft_s", "ttft_p50_s", "token_latency_p50_s",
            "token_latency_p95_s", "token_latency_p99_s",
            "token_latency_mean_s", "tokens_per_s_observed"} <= set(s)
    assert s["token_latency_p50_s"] <= s["token_latency_p99_s"]
    lat.publish()
    reg = obs.get_registry()
    assert reg.get("edgellm_decode_ttft_seconds").count == 1
    assert reg.get("edgellm_decode_token_latency_seconds").count == 8


def test_generate_tokens_identical_with_and_without_observe():
    import jax.numpy as jnp
    from edgellm_tpu.serve.decode import generate

    cfg, params, ids = _tiny_setup()
    ids = jnp.asarray(ids)
    plain = generate(cfg, params, ids, 6, capacity=12)
    obs.enable(obs.ObservabilityConfig())
    st: dict = {}
    observed = generate(cfg, params, ids, 6, capacity=12, stats=st,
                        observe=LatencyObserver())
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(observed))
    # the stats dict gains the SLO block and the registry absorbed it
    assert st["ttft_s"] > 0 and st["token_latency_p50_s"] > 0
    assert obs.get_registry().get("edgellm_decode_steps_total").value() == 5


def test_obs_enabled_traces_identical_jaxpr():
    """The graphlint identity contract at unit scale: arming the full obs
    stack (registry + tracer + an open span) must not change one byte of the
    decode-step jaxpr — all instrumentation is host-side."""
    import jax
    from edgellm_tpu.lint.contracts import graph_fingerprint
    from edgellm_tpu.models import transformer

    cfg, params, ids = _tiny_setup()
    cache = transformer.init_cache(cfg, 2, 8)
    tok = np.zeros((2,), np.int32)

    def step(p, c, t):
        return transformer.decode_step(cfg, p, c, t)

    args = (params, cache, jax.numpy.asarray(tok))
    fp_off = graph_fingerprint(step, *args)
    obs.enable(obs.ObservabilityConfig())
    with obs.span("probe"):
        fp_on = graph_fingerprint(step, *args)
    assert fp_on == fp_off


@pytest.mark.slow
def test_decode_observe_overhead_within_budget():
    """The 3% SLO: instrumented decode (block at sample boundaries only)
    must stay within 3% tok/s of uninstrumented — the same number
    BENCH_DECODE=1 records as ``obs_overhead_frac``. Best-of-N on both arms
    to shed scheduler noise."""
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import init_params, tiny_config
    from edgellm_tpu.serve.decode import generate

    # big enough that a per-step compute dwarfs the one host sync per sampled
    # token; at toy widths (32) the sync itself dominates and the 3% budget
    # is meaningless
    cfg = tiny_config("qwen2", num_layers=4, hidden_size=128, num_heads=4,
                      vocab_size=256)
    params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 16)))
    new_tokens, capacity, n = 64, 80, 5
    generate(cfg, params, ids, new_tokens, capacity=capacity)  # compile

    def best(observe_factory):
        rates = []
        for _ in range(n):
            st: dict = {}
            generate(cfg, params, ids, new_tokens, capacity=capacity,
                     stats=st, observe=observe_factory())
            rates.append(st["decode_tokens_per_s"])
        return max(rates)

    plain = best(lambda: None)
    instrumented = best(lambda: LatencyObserver())
    overhead = 1.0 - instrumented / plain
    assert overhead <= 0.03, f"obs decode overhead {overhead:.2%} > 3%"


# ---------------------------------------------------------------------------
# run.py wiring
# ---------------------------------------------------------------------------


def test_run_params_observability_validation(tmp_path):
    from edgellm_tpu.run import main

    def run_with(ob):
        p = tmp_path / "params.json"
        p.write_text(json.dumps({"observability": ob}))
        main(["--params", str(p), "--model", "tiny-qwen2"])

    with pytest.raises(SystemExit, match="observability.metrics must be"):
        run_with({"metrics": "yes"})
    with pytest.raises(SystemExit, match="unknown field"):
        run_with({"metricz": True})
    with pytest.raises(SystemExit, match="must be an object"):
        run_with(True)


def test_run_metrics_and_trace_out_split_e2e(tmp_path):
    """--metrics-out/--trace-out end to end on the split eval (smoke mode):
    the snapshot carries the wire-byte counters, the trace carries the eval
    section spans, and a .prom path switches to Prometheus text format."""
    from edgellm_tpu.run import main

    p = tmp_path / "params.json"
    p.write_text(json.dumps({
        "experiment": "split", "cuts": [1],
        "hop_codecs": ["int8_per_token"], "max_length": 32, "stride": 16}))
    mpath, tpath = tmp_path / "metrics.json", tmp_path / "trace.json"
    try:
        assert main(["--params", str(p), "--model", "tiny-qwen2",
                     "--output-dir", str(tmp_path / "out"),
                     "--max-chunks", "2", "--window-batch", "2",
                     "--synthetic-corpus-len", "256",
                     "--metrics-out", str(mpath),
                     "--trace-out", str(tpath)]) in (0, None)
    finally:
        obs.disable()
    snap = json.load(open(mpath))
    assert "edgellm_wire_bytes_total" in snap
    trace = json.load(open(tpath))
    names = {e["name"] for e in trace["traceEvents"]}
    assert "eval.submit_group" in names and "eval.drain_group" in names
