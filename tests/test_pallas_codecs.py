"""Pallas codec kernels (interpret mode on CPU): must be bit-identical to the
jnp wire codecs — same packed bytes, same reconstruction."""
import numpy as np
import pytest

import jax.numpy as jnp

from edgellm_tpu.codecs.packing import get_wire_codec, selective_int4
from edgellm_tpu.codecs.pallas_kernels import (
    SELECTIVE_EXCLUSION, int4_encode_pallas, int4_decode_pallas,
    pallas_wire_codec, pallas_int8_per_token, pallas_ternary, pallas_variant,
)


@pytest.fixture
def hidden(rng):
    return jnp.asarray(rng.normal(size=(2, 16, 64)).astype(np.float32))


def test_encode_matches_jnp_codec_bitwise(hidden):
    jnp_codec = get_wire_codec("int4_per_token")
    want = jnp_codec.encode(hidden)
    b, s, d = hidden.shape
    packed, scale = int4_encode_pallas(hidden.reshape(b * s, d))
    np.testing.assert_array_equal(np.asarray(packed).reshape(b, s, -1),
                                  np.asarray(want["packed"]))
    np.testing.assert_allclose(np.asarray(scale).reshape(b, s, 1),
                               np.asarray(want["scale"]), rtol=1e-7)


def test_roundtrip_matches_jnp_roundtrip(hidden):
    jnp_codec = get_wire_codec("int4_per_token")
    want = jnp_codec.decode(jnp_codec.encode(hidden))
    codec = pallas_wire_codec()
    got = codec.decode(codec.encode(hidden))
    # payload bytes are bit-identical (previous test); reconstruction may differ
    # by 1 ulp from XLA fusing (c/7)*s differently
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_ragged_token_counts(rng):
    """Token counts that don't hit the preferred tile sizes still work."""
    for n in (8, 24, 40, 72):
        x = jnp.asarray(rng.normal(size=(n, 32)).astype(np.float32))
        packed, scale = int4_encode_pallas(x)
        out = int4_decode_pallas(packed, scale)
        err = np.abs(np.asarray(out) - np.asarray(x)).max()
        assert err <= np.abs(np.asarray(x)).max() / 7.0 + 1e-6


def _assert_payload_equal(got: dict, want: dict):
    assert set(got) == set(want)
    for key in want:
        g, w = np.asarray(got[key]), np.asarray(want[key])
        assert g.dtype == w.dtype and g.shape == w.shape, key
        if np.issubdtype(w.dtype, np.integer) or w.dtype == np.uint8:
            np.testing.assert_array_equal(g, w, err_msg=key)
        else:
            np.testing.assert_allclose(g, w, rtol=1e-7, err_msg=key)


@pytest.mark.parametrize("name", ["int8_per_token", "int8_per_channel",
                                  "int4_per_channel", "ternary_mean",
                                  "ternary_max"])
def test_pallas_twins_bit_identical(hidden, name):
    jnp_codec = get_wire_codec(name)
    pallas_codec = pallas_variant(jnp_codec)
    assert pallas_codec is not None and pallas_codec.name == name + "_pallas"
    want = jnp_codec.encode(hidden)
    got = pallas_codec.encode(hidden)
    _assert_payload_equal(got, want)
    np.testing.assert_allclose(np.asarray(pallas_codec.decode(got)),
                               np.asarray(jnp_codec.decode(want)), atol=1e-6)


def test_selective_has_no_kernel_twin_by_measurement():
    """The selective codec's Pallas twin was DELETED in round 5 on silicon
    measurement (gather-bound; the kernel boundary broke XLA's gather->quant
    fusion, 0.96-0.97x across rounds). The exclusion is a recorded decision:
    pallas_variant returns None on every path and the runtimes fall back to
    the jnp codec, which IS the TPU-native implementation."""
    import edgellm_tpu.codecs.pallas_kernels as pk

    jnp_codec = selective_int4(0.5, "bf16")
    assert pallas_variant(jnp_codec) is None
    assert pallas_variant(jnp_codec, measured_wins_only=True) is None
    assert not hasattr(pk, "pallas_selective_int4")
    assert "gather-bound" in SELECTIVE_EXCLUSION


def test_registry_exposes_pallas_names():
    codec = get_wire_codec("int8_per_token_pallas")
    assert codec.name == "int8_per_token_pallas"


def test_split_runtime_substitutes_pallas_when_forced(rng, monkeypatch):
    """EDGELLM_PALLAS=1 swaps jnp hop codecs for their fused twins (the TPU
    default path, exercised here on CPU interpret mode)."""
    import jax
    from edgellm_tpu.models import tiny_config, init_params
    from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh

    monkeypatch.setenv("EDGELLM_PALLAS", "1")
    cfg = tiny_config("qwen2", num_layers=4, hidden_size=32, num_heads=4, vocab_size=128)
    params = init_params(cfg, jax.random.key(1))
    ids = jnp.asarray(rng.integers(0, 128, (1, 16)))
    rt = SplitRuntime(cfg, SplitConfig(cuts=(1,), hop_codecs=("int8_per_token",)),
                      make_stage_mesh(2))
    assert rt.codecs[0].name == "int8_per_token_pallas"
    monkeypatch.setenv("EDGELLM_PALLAS", "0")
    rt_j = SplitRuntime(cfg, SplitConfig(cuts=(1,), hop_codecs=("int8_per_token",)),
                        make_stage_mesh(2))
    assert rt_j.codecs[0].name == "int8_per_token"
    out_p = rt.forward(rt.place_params(params), ids)
    out_j = rt_j.forward(rt_j.place_params(params), ids)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_j),
                               atol=1e-6, rtol=1e-6)


def test_default_substitution_is_gated_on_measured_wins(monkeypatch, tmp_path):
    """The TPU default path substitutes only kernels measured as wins for
    this chip (probe cache, frozen set as no-data fallback); int8_per_channel
    (0.94x) stays jnp, the selective twin no longer exists at all, and
    EDGELLM_PALLAS=1 forces every REMAINING twin. Explicit *_pallas pins are
    always honored."""
    import jax
    from edgellm_tpu.codecs.packing import selective_int4
    from edgellm_tpu.parallel.split import apply_default_codec_backend

    monkeypatch.delenv("EDGELLM_PALLAS", raising=False)
    # point the policy at an empty cache: the frozen fallback set decides
    monkeypatch.setenv("EDGELLM_PROBE_CACHE", str(tmp_path / "none.json"))
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    out = apply_default_codec_backend(
        ["int4_per_token", "int8_per_token", selective_int4(0.5, "bf16"),
         "int8_per_channel_pallas"])
    assert [c.name for c in out] == [
        "int4_per_token_pallas",       # measured win (1.33x) -> substituted
        "int8_per_token",              # 0.80x -> stays jnp
        "selective_int4_r0.5_bf16",    # twin deleted on measurement
        "int8_per_channel_pallas",     # explicit pin honored
    ]

    monkeypatch.setenv("EDGELLM_PALLAS", "1")
    forced = apply_default_codec_backend(
        ["int8_per_channel", selective_int4(0.5, "bf16")])
    # even forced substitution cannot resurrect a deleted twin
    assert [c.name for c in forced] == [
        "int8_per_channel_pallas", "selective_int4_r0.5_bf16"]


def test_pallas_codec_in_split_runtime(rng):
    """Pallas hop codec through ppermute == jnp hop codec, end to end."""
    import jax
    from edgellm_tpu.models import tiny_config, init_params
    from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh

    cfg = tiny_config("qwen2", num_layers=4, hidden_size=32, num_heads=4, vocab_size=128)
    params = init_params(cfg, jax.random.key(1))
    ids = jnp.asarray(rng.integers(0, 128, (1, 16)))
    rt_p = SplitRuntime(cfg, SplitConfig(cuts=(1,), hop_codecs=(pallas_wire_codec(),)),
                        make_stage_mesh(2))
    rt_j = SplitRuntime(cfg, SplitConfig(cuts=(1,), hop_codecs=("int4_per_token",)),
                        make_stage_mesh(2))
    out_p = rt_p.forward(rt_p.place_params(params), ids)
    out_j = rt_j.forward(rt_j.place_params(params), ids)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_j),
                               atol=1e-6, rtol=1e-6)
