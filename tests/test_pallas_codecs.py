"""Pallas codec kernels (interpret mode on CPU): must be bit-identical to the
jnp int4_per_token wire codec — same packed bytes, same reconstruction."""
import numpy as np
import pytest

import jax.numpy as jnp

from edgellm_tpu.codecs.packing import get_wire_codec
from edgellm_tpu.codecs.pallas_kernels import (
    int4_encode_pallas, int4_decode_pallas, pallas_wire_codec,
)


@pytest.fixture
def hidden(rng):
    return jnp.asarray(rng.normal(size=(2, 16, 64)).astype(np.float32))


def test_encode_matches_jnp_codec_bitwise(hidden):
    jnp_codec = get_wire_codec("int4_per_token")
    want = jnp_codec.encode(hidden)
    b, s, d = hidden.shape
    packed, scale = int4_encode_pallas(hidden.reshape(b * s, d))
    np.testing.assert_array_equal(np.asarray(packed).reshape(b, s, -1),
                                  np.asarray(want["packed"]))
    np.testing.assert_allclose(np.asarray(scale).reshape(b, s, 1),
                               np.asarray(want["scale"]), rtol=1e-7)


def test_roundtrip_matches_jnp_roundtrip(hidden):
    jnp_codec = get_wire_codec("int4_per_token")
    want = jnp_codec.decode(jnp_codec.encode(hidden))
    codec = pallas_wire_codec()
    got = codec.decode(codec.encode(hidden))
    # payload bytes are bit-identical (previous test); reconstruction may differ
    # by 1 ulp from XLA fusing (c/7)*s differently
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_ragged_token_counts(rng):
    """Token counts that don't hit the preferred tile sizes still work."""
    for n in (8, 24, 40, 72):
        x = jnp.asarray(rng.normal(size=(n, 32)).astype(np.float32))
        packed, scale = int4_encode_pallas(x)
        out = int4_decode_pallas(packed, scale)
        err = np.abs(np.asarray(out) - np.asarray(x)).max()
        assert err <= np.abs(np.asarray(x)).max() / 7.0 + 1e-6


def test_pallas_codec_in_split_runtime(rng):
    """Pallas hop codec through ppermute == jnp hop codec, end to end."""
    import jax
    from edgellm_tpu.models import tiny_config, init_params
    from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh

    cfg = tiny_config("qwen2", num_layers=4, hidden_size=32, num_heads=4, vocab_size=128)
    params = init_params(cfg, jax.random.key(1))
    ids = jnp.asarray(rng.integers(0, 128, (1, 16)))
    rt_p = SplitRuntime(cfg, SplitConfig(cuts=(1,), hop_codecs=(pallas_wire_codec(),)),
                        make_stage_mesh(2))
    rt_j = SplitRuntime(cfg, SplitConfig(cuts=(1,), hop_codecs=("int4_per_token",)),
                        make_stage_mesh(2))
    out_p = rt_p.forward(rt_p.place_params(params), ids)
    out_j = rt_j.forward(rt_j.place_params(params), ids)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_j),
                               atol=1e-6, rtol=1e-6)
