"""Selective mixed-precision wire codec (BASELINE configs[2]) tests.

With high="fp32" the round-trip must EXACTLY equal the reference's in-place
token-selective int4 simulation (same global scale over the selected slice, same
stable-argsort selection); through the split runtime the packed payload crossing
ppermute must reproduce the boundary_fn simulate path.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.models import tiny_config, init_params, forward
from edgellm_tpu.codecs import int4_token_select
from edgellm_tpu.codecs.packing import selective_int4
from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh

CFG = tiny_config("qwen2", num_layers=6, hidden_size=32, num_heads=4, vocab_size=128)


@pytest.fixture
def data(rng):
    h = jnp.asarray(rng.normal(size=(2, 16, 32)).astype(np.float32))
    imp = jnp.asarray(rng.random(16).astype(np.float32))
    return h, imp


@pytest.mark.parametrize("ratio", [0.0, 0.25, 0.5, 1.0])
def test_fp32_high_matches_simulate_exactly(data, ratio):
    h, imp = data
    codec = selective_int4(ratio, high="fp32")
    got = codec.decode(codec.encode(h, imp))
    want = int4_token_select(h, imp, ratio)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_bf16_high_bounded_error(data):
    h, imp = data
    codec = selective_int4(0.5, high="bf16")
    out = codec.decode(codec.encode(h, imp))
    # bf16 has ~3 decimal digits; unselected tokens only lose mantissa bits
    assert float(jnp.max(jnp.abs(out - int4_token_select(h, imp, 0.5)))) < 0.05


def test_payload_bytes_scale_with_ratio():
    D, S = 896, 512
    full = selective_int4(1.0, high="bf16").payload_bytes((1, S, D))
    none = selective_int4(0.0, high="bf16").payload_bytes((1, S, D))
    half = selective_int4(0.5, high="bf16").payload_bytes((1, S, D))
    # side channel = k int16 low indices only (high placement is the sorted
    # complement, derived on decode) — 2k bytes, zero at ratio 0
    assert none == S * D * 2 + 4  # all bf16 + scale, NO side channel
    assert full == S * D // 2 + S * 2 + 4  # all packed int4 + full low-index set
    assert half == S * D // 4 + (S // 2) * D * 2 + (S // 2) * 2 + 4
    assert none > half > full


def test_order_side_channel_is_int16_low_only(rng):
    h = jnp.asarray(rng.normal(size=(1, 16, 32)).astype(np.float32))
    imp = jnp.asarray(rng.random(16).astype(np.float32))
    p = selective_int4(0.25, "bf16").encode(h, imp)
    assert p["order"].dtype == jnp.int16 and p["order"].shape == (4,)
    pr = selective_int4(0.25, "bf16").encode(
        jnp.tile(h, (3, 1, 1)), jnp.asarray(rng.random((3, 16)).astype(np.float32)))
    assert pr["order"].dtype == jnp.int16 and pr["order"].shape == (3, 4)


def test_seq_over_int16_limit_raises():
    codec = selective_int4(0.5, "bf16")
    with pytest.raises(ValueError, match="32767"):
        codec.encode(jnp.zeros((1, 32768, 2), jnp.float32),
                     jnp.zeros((32768,), jnp.float32))


def test_split_runtime_with_selective_hop(data):
    params = init_params(CFG, jax.random.key(1))
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, CFG.vocab_size, (1, 16)))
    imp = jnp.asarray(rng.random(16).astype(np.float32))
    cut, ratio = 2, 0.5

    rt = SplitRuntime(
        CFG, SplitConfig(cuts=(cut,), hop_codecs=(selective_int4(ratio, "fp32"),)),
        make_stage_mesh(2))
    out = rt.forward(rt.place_params(params), ids, hop_importance=[imp])

    def bfn(idx, h):
        return jnp.where(idx == cut, int4_token_select(h, imp, ratio), h)

    want, _ = forward(CFG, params, ids, boundary_fn=bfn)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_split_runtime_missing_importance_raises(data):
    params = init_params(CFG, jax.random.key(1))
    ids = jnp.zeros((1, 16), jnp.int32)
    rt = SplitRuntime(
        CFG, SplitConfig(cuts=(2,), hop_codecs=(selective_int4(0.5),)),
        make_stage_mesh(2))
    placed = rt.place_params(params)
    with pytest.raises(ValueError, match="importance"):
        rt.forward(placed, ids)


def test_invalid_ratio_raises():
    with pytest.raises(ValueError):
        selective_int4(1.5)


@pytest.mark.parametrize("ratio", [0.25, 0.5])
def test_per_row_importance_matches_independent_rows(rng, ratio):
    """(B, S) importance: every row gets its own ordering AND scale — identical
    to encoding each row separately with its own (S,) vector."""
    h = jnp.asarray(rng.normal(size=(3, 16, 32)).astype(np.float32))
    imp = jnp.asarray(rng.random((3, 16)).astype(np.float32))
    codec = selective_int4(ratio, high="fp32")
    batched = np.asarray(codec.decode(codec.encode(h, imp)))
    for b in range(3):
        single = np.asarray(codec.decode(codec.encode(h[b:b + 1], imp[b])))
        np.testing.assert_array_equal(batched[b:b + 1], single)


def test_per_row_payload_counts_batched_order():
    D, S, B = 64, 16, 4
    codec = selective_int4(0.5, high="bf16")
    one = codec.payload_bytes((1, S, D))
    four = codec.payload_bytes((B, S, D))
    # per-row wire format: order side channel and scales scale with B
    assert four == B * (one - 4) + B * 4


def test_selective_pallas_pin_is_a_clear_error():
    """The selective kernel twin was deleted on measurement (round 5); an
    explicit 'selective_int4_pallas' split-eval spec must fail loudly with
    the recorded reason, never silently run something else."""
    from edgellm_tpu.eval.split_eval import parse_hop_codec

    with pytest.raises(ValueError, match="gather-bound"):
        parse_hop_codec("selective_int4_pallas:0.5:bf16", n_seq=1)


def test_split_runtime_per_row_importance_data_parallel(rng):
    """Batched windows + selective hop: per-row (B, S) importance through the
    split runtime over ("stage", "data") equals the per-window batch-1 runs."""
    params = init_params(CFG, jax.random.key(1))
    ids = jnp.asarray(rng.integers(0, CFG.vocab_size, (2, 16)))
    imp = jnp.asarray(rng.random((2, 16)).astype(np.float32))
    cut, ratio = 2, 0.5
    codec = selective_int4(ratio, "fp32")

    rt = SplitRuntime(CFG, SplitConfig(cuts=(cut,), hop_codecs=(codec,)),
                      make_stage_mesh(2, n_data=2))
    out = np.asarray(rt.forward(rt.place_params(params), ids, hop_importance=[imp]))

    rt1 = SplitRuntime(CFG, SplitConfig(cuts=(cut,), hop_codecs=(codec,)),
                       make_stage_mesh(2))
    placed1 = rt1.place_params(params)
    for b in range(2):
        want = np.asarray(rt1.forward(placed1, ids[b:b + 1],
                                      hop_importance=[imp[b]]))
        np.testing.assert_allclose(out[b:b + 1], want, atol=2e-5, rtol=2e-5)


def test_split_runtime_batch_without_per_row_importance_raises(rng):
    params = init_params(CFG, jax.random.key(1))
    ids = jnp.asarray(rng.integers(0, CFG.vocab_size, (2, 16)))
    imp = jnp.asarray(rng.random(16).astype(np.float32))
    rt = SplitRuntime(CFG, SplitConfig(cuts=(2,), hop_codecs=(selective_int4(0.5),)),
                      make_stage_mesh(2))
    with pytest.raises(ValueError, match="per-row"):
        rt.forward(rt.place_params(params), ids, hop_importance=[imp])


def test_split_runtime_broadcast_row_importance_raises(rng):
    """A (1, S) importance at batch > 1 must be rejected, not silently shared."""
    params = init_params(CFG, jax.random.key(1))
    ids = jnp.asarray(rng.integers(0, CFG.vocab_size, (4, 16)))
    imp = jnp.asarray(rng.random((1, 16)).astype(np.float32))
    rt = SplitRuntime(CFG, SplitConfig(cuts=(2,), hop_codecs=(selective_int4(0.5),)),
                      make_stage_mesh(2))
    with pytest.raises(ValueError, match=r"\(4, S\)"):
        rt.forward(rt.place_params(params), ids, hop_importance=[imp])
