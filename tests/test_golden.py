"""Golden fixture pinning the tokenization-adjacent evaluation contract.

The PPL metric is DEFINED by the sliding-window schedule (begin/end/trg_len,
``Qwen2-0.5B/main.py:151-156``), the -100 masking, the ``num_loss_tokens =
valid - batch`` weighting, and the shifted-CE NLL. A silent change to any of
them invalidates every cross-round comparison and the ±0.1-PPL target, so this
test pins all of it against a checked-in fixture: a seeded corpus + seeded
tiny-model per-chunk NLLs recorded at float64.

Regenerate (after an INTENTIONAL metric change, never to quiet a failure):

    python tests/test_golden.py --regen
"""
import os
import sys

import numpy as np

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden", "windowing_nll.npz")

CASES = [
    # (family, corpus_len, max_length, stride) — covers the steady stride tail,
    # the full-window first chunk, and a short final tail chunk
    ("qwen2", 200, 64, 16),
    ("gpt_neox", 131, 48, 32),
]


def _compute_case(family, corpus_len, max_length, stride):
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import tiny_config, init_params, forward, nll_from_logits
    from edgellm_tpu.eval.windowing import sliding_windows

    cfg = tiny_config(family, num_layers=3, hidden_size=32, num_heads=4, vocab_size=128)
    params = init_params(cfg, jax.random.key(7))
    corpus = np.random.default_rng(11).integers(0, cfg.vocab_size, corpus_len)
    schedule, nlls = [], []
    for chunk in sliding_windows(corpus, max_length, stride):
        schedule.append([chunk.index, chunk.begin, chunk.end, chunk.num_loss_tokens])
        logits, _ = forward(cfg, params, jnp.asarray(chunk.input_ids))
        nlls.append(float(nll_from_logits(logits, jnp.asarray(chunk.target_ids))))
    return np.asarray(schedule, np.int64), np.asarray(nlls, np.float64)


def _case_key(case):
    return "_".join(str(c) for c in case)


def regenerate():
    os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
    payload = {}
    for case in CASES:
        schedule, nlls = _compute_case(*case)
        payload[f"schedule_{_case_key(case)}"] = schedule
        payload[f"nll_{_case_key(case)}"] = nlls
    np.savez(GOLDEN_PATH, **payload)
    print(f"wrote {GOLDEN_PATH}: "
          f"{ {k: v.shape for k, v in payload.items()} }")


def test_windowing_and_nll_match_golden():
    assert os.path.exists(GOLDEN_PATH), \
        "golden fixture missing — run: python tests/test_golden.py --regen"
    golden = np.load(GOLDEN_PATH)
    for case in CASES:
        schedule, nlls = _compute_case(*case)
        np.testing.assert_array_equal(
            schedule, golden[f"schedule_{_case_key(case)}"],
            err_msg=f"window schedule drifted for {case} — the PPL metric "
                    f"definition changed")
        # fp32 forward + fp32 CE: identical op sequence must reproduce exactly
        # on the same backend; allow only float noise across backends
        np.testing.assert_allclose(
            nlls, golden[f"nll_{_case_key(case)}"], rtol=2e-6, atol=2e-6,
            err_msg=f"per-chunk NLL drifted for {case}")


def test_golden_covers_edge_chunks():
    """The fixture really exercises first-window, steady, and tail chunks."""
    golden = np.load(GOLDEN_PATH)
    sched = golden[f"schedule_{_case_key(CASES[0])}"]
    _, corpus_len, max_length, stride = CASES[0]
    assert sched[0][3] == max_length - 1          # chunk 0 scores everything
    assert sched[1][3] == stride - 1              # steady: trg_len - batch
    assert sched[-1][2] == corpus_len             # tail reaches corpus end
    assert sched[-1][2] - sched[-1][1] < max_length  # and is genuinely short


if __name__ == "__main__":
    if "--regen" in sys.argv:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        regenerate()
    else:
        print(__doc__)
