"""The on-silicon Pallas probe's parity logic, exercised on CPU (interpret
mode). On the real chip ``bench.py`` runs the same probe with timing and embeds
it as the bench line's ``"pallas"`` block — this pins the comparison machinery
(ulp math, leaf checks, codec pairing) without a TPU."""
import numpy as np

from edgellm_tpu.tools.pallas_probe import PROBE_CODECS, _ulp_diff, probe_all


def test_ulp_diff():
    a = np.float32(1.0)
    assert _ulp_diff(np.asarray([a]), np.asarray([np.nextafter(a, 2.0)])) == 1
    assert _ulp_diff(np.asarray([a]), np.asarray([a])) == 0
    # sign crossing: -eps to +eps is two representable steps apart at most
    tiny = np.float32(1e-45)
    assert _ulp_diff(np.asarray([-tiny]), np.asarray([tiny])) == 2
    assert _ulp_diff(np.zeros((0,), np.float32), np.zeros((0,), np.float32)) == 0


def test_probe_all_parity_small():
    out = probe_all(timing=False, batch=2, seq=32, dim=64)
    assert out["interpret"] is True
    # every kernel-twinned codec, plus the recorded selective exclusion (the
    # measured round-5 deletion travels in every probe artifact)
    assert [c["codec"] for c in out["codecs"]] == \
        list(PROBE_CODECS) + ["selective_int4"]
    assert "gather-bound" in out["codecs"][-1]["excluded"]
    assert not out["codecs"][-1]["default_substituted"]
    for c in out["codecs"][:-1]:
        assert c["encode_max_ulp"] <= 2 and c["decode_max_ulp"] <= 2
        assert c["int_leaves_bit_identical"] >= 1
        # timing disabled off-chip
        assert "roundtrip_gbps" not in c and "encode_gbps" not in c
