"""End-to-end CLI runs from a real on-disk checkpoint (HF safetensors layout).

This is the "real weights + real corpus readiness" contract (VERDICT missing #2):
the moment actual Qwen2/Pythia artifacts appear, ``run.py --weights <dir>
--corpus <ids.npy>`` must execute the reference's experiments end to end. The
environment has no pretrained checkpoints, so these tests synthesize a
bit-exact HF-style model directory (config.json + model.safetensors) and drive
``edgellm_tpu.run.main`` through every dispatch branch the reference has
(token sweep ``Qwen2-0.5B/main.py:100-207``, channel sweep ``channel_wise.py``,
initial sweep ``initial_exp.py``, mesh-split eval), checking artifacts land and
that the loaded weights actually produced the numbers (vs. random init).
"""
import json

import numpy as np
import pytest

from edgellm_tpu.run import main
from test_safetensors_io import write_safetensors, _qwen_state_dict

TINY_HF_CONFIG = {
    "model_type": "qwen2",
    "vocab_size": 256,
    "hidden_size": 64,
    "num_hidden_layers": 6,
    "num_attention_heads": 4,
    "num_key_value_heads": 2,
    "intermediate_size": 256,
    "max_position_embeddings": 512,
    "rms_norm_eps": 1e-6,
    "rope_theta": 1000000.0,
    "tie_word_embeddings": True,
}


@pytest.fixture(scope="module")
def ckpt_dir(tmp_path_factory):
    """Synthesized HF-style checkpoint directory + token corpus."""
    from edgellm_tpu.models import tiny_config

    root = tmp_path_factory.mktemp("ckpt")
    cfg = tiny_config("qwen2", num_layers=6)
    rng = np.random.default_rng(7)
    sd = _qwen_state_dict(cfg, rng)
    model_dir = root / "model"
    model_dir.mkdir()
    (model_dir / "config.json").write_text(json.dumps(TINY_HF_CONFIG))
    write_safetensors(str(model_dir / "model.safetensors"), sd)
    corpus = rng.integers(0, cfg.vocab_size, 600).astype(np.int64)
    np.save(root / "corpus.npy", corpus)
    return {"model_dir": str(model_dir), "corpus": str(root / "corpus.npy"),
            "cfg": cfg, "sd": sd, "corpus_ids": corpus}


def _params(tmp_path, body):
    p = tmp_path / "params.json"
    p.write_text(json.dumps(body))
    return str(p)


def _run(argv):
    assert main(argv) in (0, None)


def test_token_sweep_from_checkpoint_dir(ckpt_dir, tmp_path):
    params = _params(tmp_path, {
        "ratios": [0, 0.5, 1], "layers_of_interest": [2],
        "max_length": 64, "stride": 32,
        "methods": ["regular_importance", "last_row"]})
    out = tmp_path / "out"
    _run(["--params", params, "--weights", ckpt_dir["model_dir"],
          "--corpus", ckpt_dir["corpus"], "--output-dir", str(out),
          "--window-batch", "4"])
    result = json.load(open(out / "avg_ppl_results.json"))
    ppl = np.asarray(result["ppl"])
    assert ppl.shape == (2, 1, 3) and np.isfinite(ppl).all()

    # the numbers must come from the checkpoint weights: the same sweep driven
    # directly through the library with the loaded pytree agrees exactly
    from edgellm_tpu.models.safetensors_io import load_checkpoint
    from edgellm_tpu.eval import run_token_sweep

    cfg, pt = load_checkpoint(ckpt_dir["model_dir"])
    direct = run_token_sweep(
        cfg, pt, ckpt_dir["corpus_ids"], methods=["regular_importance", "last_row"],
        layers_of_interest=[2], ratios=[0, 0.5, 1], max_length=64, stride=32,
        window_batch=4)
    np.testing.assert_allclose(ppl, direct.ppl(), rtol=1e-6)


def test_channel_sweep_from_checkpoint_dir(ckpt_dir, tmp_path):
    params = _params(tmp_path, {
        "layers_of_interest": [3], "max_length": 64, "stride": 32,
        "methods": ["channel_8", "channel_1_mean"], "ratios": []})
    out = tmp_path / "out"
    _run(["--params", params, "--weights", ckpt_dir["model_dir"],
          "--corpus", ckpt_dir["corpus"], "--output-dir", str(out),
          "--max-chunks", "4"])
    result = json.load(open(out / "avg_ppl_results.json"))
    assert np.isfinite(result["ppl"]).all()


def test_initial_sweep_from_checkpoint_dir(ckpt_dir, tmp_path):
    params = _params(tmp_path, {
        "experiment": "initial",
        "ratios": [0, 5], "layers_of_interest": [1, "upto ratio"],
        "max_length": 64, "stride": 32})
    out = tmp_path / "out"
    _run(["--params", params, "--weights", ckpt_dir["model_dir"],
          "--corpus", ckpt_dir["corpus"], "--output-dir", str(out),
          "--max-chunks", "4"])
    result = json.load(open(out / "avg_ppl_results.json"))
    assert np.isfinite(result["ppl"]).all()


def test_split_eval_from_checkpoint_dir(ckpt_dir, tmp_path):
    params = _params(tmp_path, {
        "experiment": "split", "cuts": [2],
        "hop_codecs": ["int8_per_token"], "max_length": 64, "stride": 32})
    out = tmp_path / "out"
    _run(["--params", params, "--weights", ckpt_dir["model_dir"],
          "--corpus", ckpt_dir["corpus"], "--output-dir", str(out),
          "--max-chunks", "4"])
    result = json.load(open(out / "split_eval_results.json"))
    assert np.isfinite(result["ppl"])
    assert result["bytes_per_token_per_hop"][0] > 0


def test_ring_long_context_split_cli(ckpt_dir, tmp_path):
    """The stage x seq long-context path end to end from the CLI (the shape of
    configs/split5_qwen_ring_long.json on the synthesized checkpoint): seq
    sharded within each stage, windows right-padded to a shardable length."""
    out = tmp_path / "out_ring"
    params = _params(tmp_path, {
        "experiment": "split", "cuts": [2], "hop_codecs": ["int4_per_token"],
        "max_length": 44, "stride": 22, "n_seq": 3})
    main(["--params", params, "--weights", ckpt_dir["model_dir"],
          "--corpus", ckpt_dir["corpus"], "--output-dir", str(out),
          "--max-chunks", "4"])
    result = json.load(open(out / "split_eval_results.json"))
    assert np.isfinite(result["ppl"])
    assert result["mesh"] == {"stage": 2, "seq": 3}
    assert result["pad_fraction"] > 0  # 44 % 3 != 0: the padding path ran
