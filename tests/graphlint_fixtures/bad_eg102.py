"""EG102 seed: inconsistent / hazardous multi-lock acquisition order."""
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = {}

    def merge_from(self, other):
        # line 13: source-order acquisition of two same-class instance
        # locks — A.merge_from(B) racing B.merge_from(A) is an ABBA deadlock
        with self._lock, other._lock:
            self.items.update(other.items)

    def double_take(self):
        with self._lock:
            with self._lock:  # line 18: re-acquire of a non-reentrant lock
                return dict(self.items)
