"""EG001 seed: Python control flow on traced values inside jitted code."""
import jax
import jax.numpy as jnp


@jax.jit
def branch_on_traced(x):
    if jnp.any(x > 0):  # line 8: traced branch
        return x + 1
    return x


@jax.jit
def loop_on_traced(x):
    while x.any():  # line 15: traced while
        x = x - 1
    return x


@jax.jit
def assert_on_traced(x):
    assert jnp.all(x > 0)  # line 22: trace-time assert
    return x
