"""EG103 seed: blocking work while holding a lock."""
import threading
import time


class Dumper:
    def __init__(self):
        self._lock = threading.Lock()
        self.rows = []

    def slow_append(self, row):
        with self._lock:
            time.sleep(0.1)  # line 13: sleep with the lock held
            self.rows.append(row)

    def dump(self, path):
        with self._lock:
            f = open(path, "w")  # line 18: file I/O with the lock held
            f.write(str(self.rows))
            f.close()

    def sync(self, array):
        with self._lock:
            array.block_until_ready()  # line 24: device sync under the lock
