# Deliberately-broken modules for tests/test_graphlint.py. They are parsed
# by the AST lint layer, NEVER imported — each bad_eg00x.py seeds exactly the
# footgun its rule exists to catch.
