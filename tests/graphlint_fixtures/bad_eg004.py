"""EG004 seed: jax.jit over config-like params without static_argnames."""
from functools import partial

import jax


def run(cfg, x):
    return x * cfg.scale


run_jit = jax.jit(run)  # line 11: cfg not static


@partial(jax.jit, static_argnames=("unrelated",))
def stepper(cfg, capacity, x, unrelated=None):  # line 15: cfg/capacity missing
    return x[:capacity] * cfg.scale
