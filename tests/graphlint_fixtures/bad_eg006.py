"""EG006 seed: trace-time mutation of captured containers."""
import jax


@jax.jit
def outer(x):
    acc = []
    seen = {}

    def inner(y):
        acc.append(y)  # line 11: captured list mutated under trace
        seen["y"] = y  # line 12: captured dict written under trace
        return y

    return inner(x)
