"""EG002 seed: host I/O reachable from a jitted function."""
import time

import jax


def helper(x):
    t0 = time.time()  # line 9: trace-time clock read
    print("tracing", t0)  # line 10: trace-time print
    return x


@jax.jit
def jitted(x):
    return helper(x) * 2
