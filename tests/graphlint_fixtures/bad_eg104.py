"""EG104 seed: contextvars tokens reset on a different frame than set."""
import contextvars

REQUEST_ID = contextvars.ContextVar("request_id", default="")


class Session:
    def begin(self, rid):
        self._token = REQUEST_ID.set(rid)  # line 9: token parked on self

    def end(self):
        REQUEST_ID.reset(self._token)


def fire_and_forget(rid):
    REQUEST_ID.set(rid)  # line 16: token discarded, can never be reset


def leaky(rid):
    token = REQUEST_ID.set(rid)  # line 20: set but never reset in frame
    return token
