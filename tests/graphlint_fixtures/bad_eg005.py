"""EG005 seed: per-token host syncs inside a decode/generate loop."""


def generate(model, steps):
    toks = []
    tok = 0
    for _ in range(steps):
        logits = model(tok)
        tok = int(logits.argmax())  # line 9: host coercion per token
        toks.append(logits.item())  # line 10: device sync per token
    return toks
