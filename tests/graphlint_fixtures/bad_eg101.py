"""EG101 seed: guarded fields written outside ``with self._lock``."""
import threading

from edgellm_tpu.utils.concurrency import guarded_by


@guarded_by("_lock", fields=["balance", "entries"])
class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self.balance = 0
        self.entries = []

    def deposit(self, amount):
        with self._lock:
            self.balance += amount

    def fast_deposit(self, amount):
        self.balance += amount  # line 19: declared field, no lock held

    def log(self, entry):
        self.entries.append(entry)  # line 22: mutator call, no lock held


class AutoCounter:
    """No decorator: the owned Lock + locked writes imply the contract."""

    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def inc(self):
        with self._lock:
            self.total += 1

    def reset(self):
        self.total = 0  # line 37: written under _lock elsewhere, bare here
