"""EG003 seed: numpy math applied to a traced array under jit."""
import jax
import numpy as np


@jax.jit
def numpy_on_tracer(x):
    return np.sqrt(x)  # line 8: host numpy on a tracer
