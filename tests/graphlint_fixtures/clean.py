"""Clean module: every rule's legitimate counterpart — must lint clean."""
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("cfg", "capacity"))
def run(cfg, capacity, x):
    # static python branch is fine (cfg is static, the bool is concrete)
    if capacity > 4:
        x = x[:, :capacity]
    return jnp.where(x > 0, x, 0.0) * cfg if cfg else x


def host_driver(steps):
    # host code may print, time, and use numpy freely
    t0 = time.time()
    sizes = np.asarray([1, 2, 3])
    print("driver", t0, int(np.prod(sizes)))
    out = []
    for s in range(steps):
        out.append(s)  # mutation in plain host code is fine
    return out


def generate(model, steps):
    toks = []
    tok = jnp.zeros((1,), jnp.int32)
    for _ in range(steps):
        tok = model(tok)
        toks.append(tok)  # stays on device; one sync after the loop
    return jnp.stack(toks)
