"""safetensors reader: byte-level parsing (incl. bf16 upcast), directory/shard
layouts, and end-to-end pytree construction without any torch import."""
import json
import struct

import numpy as np
import pytest

from edgellm_tpu.models import tiny_config
from edgellm_tpu.models.safetensors_io import (
    read_safetensors, load_checkpoint, config_from_dir, _bf16_to_f32,
)
from edgellm_tpu.models.hf_loader import params_from_state_dict

_ST_DTYPES = {np.float32: "F32", np.float16: "F16", np.int32: "I32"}


def write_safetensors(path, tensors, bf16_keys=()):
    """Minimal writer for the test (mirrors the on-disk format spec)."""
    header, blobs, offset = {}, [], 0
    for name, arr in tensors.items():
        if name in bf16_keys:
            # fp32 -> bf16 bit pattern (truncate mantissa)
            raw = (arr.astype(np.float32).view(np.uint32) >> 16).astype(np.uint16)
            blob, dtype = raw.tobytes(), "BF16"
        else:
            blob, dtype = arr.tobytes(), _ST_DTYPES[arr.dtype.type]
        header[name] = {"dtype": dtype, "shape": list(arr.shape),
                        "data_offsets": [offset, offset + len(blob)]}
        blobs.append(blob)
        offset += len(blob)
    hdr = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hdr)))
        f.write(hdr)
        for blob in blobs:
            f.write(blob)


def _qwen_state_dict(cfg, rng):
    D, F = cfg.hidden_size, cfg.intermediate_size
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    sd = {"model.embed_tokens.weight": rng.normal(size=(cfg.vocab_size, D)),
          "model.norm.weight": rng.normal(size=(D,))}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        sd.update({
            p + "self_attn.q_proj.weight": rng.normal(size=(H * hd, D)),
            p + "self_attn.k_proj.weight": rng.normal(size=(KV * hd, D)),
            p + "self_attn.v_proj.weight": rng.normal(size=(KV * hd, D)),
            p + "self_attn.q_proj.bias": rng.normal(size=(H * hd,)),
            p + "self_attn.k_proj.bias": rng.normal(size=(KV * hd,)),
            p + "self_attn.v_proj.bias": rng.normal(size=(KV * hd,)),
            p + "self_attn.o_proj.weight": rng.normal(size=(D, H * hd)),
            p + "input_layernorm.weight": rng.normal(size=(D,)),
            p + "post_attention_layernorm.weight": rng.normal(size=(D,)),
            p + "mlp.gate_proj.weight": rng.normal(size=(F, D)),
            p + "mlp.up_proj.weight": rng.normal(size=(F, D)),
            p + "mlp.down_proj.weight": rng.normal(size=(D, F)),
        })
    return {k: np.asarray(v, np.float32) for k, v in sd.items()}


def test_bf16_upcast_bit_patterns():
    # 1.0 = 0x3F80, -2.5 = 0xC020, 0 = 0x0000 in bf16
    raw = np.asarray([0x3F80, 0xC020, 0x0000], np.uint16)
    np.testing.assert_array_equal(_bf16_to_f32(raw), [1.0, -2.5, 0.0])


def test_read_roundtrip(tmp_path, rng):
    tensors = {"a": rng.normal(size=(3, 4)).astype(np.float32),
               "b": np.arange(6, dtype=np.int32).reshape(2, 3),
               "c": rng.normal(size=(2, 2)).astype(np.float16)}
    path = str(tmp_path / "t.safetensors")
    write_safetensors(path, tensors)
    got = read_safetensors(path)
    for k, v in tensors.items():
        np.testing.assert_array_equal(got[k], v)


def test_bf16_tensor_reads_as_fp32(tmp_path, rng):
    x = rng.normal(size=(4, 8)).astype(np.float32)
    path = str(tmp_path / "t.safetensors")
    write_safetensors(path, {"x": x}, bf16_keys={"x"})
    got = read_safetensors(path)["x"]
    assert got.dtype == np.float32
    # bf16 truncation: ~3 decimal digits
    np.testing.assert_allclose(got, x, rtol=1e-2)


def test_load_checkpoint_file_matches_state_dict_path(tmp_path, rng):
    cfg = tiny_config("qwen2", num_layers=2, hidden_size=16, num_heads=4,
                      num_kv_heads=2, vocab_size=64, intermediate_size=32)
    sd = _qwen_state_dict(cfg, rng)
    path = str(tmp_path / "model.safetensors")
    write_safetensors(path, sd)
    got_cfg, got = load_checkpoint(path, cfg)
    want = params_from_state_dict(cfg, sd)
    assert got_cfg == cfg
    for key in ("embed", "final_norm_scale"):
        np.testing.assert_array_equal(np.asarray(got[key]), np.asarray(want[key]))
    for key in want["layers"]:
        np.testing.assert_array_equal(np.asarray(got["layers"][key]),
                                      np.asarray(want["layers"][key]), err_msg=key)


def test_load_checkpoint_dir_with_shards_and_config(tmp_path, rng):
    cfg = tiny_config("qwen2", num_layers=2, hidden_size=16, num_heads=4,
                      num_kv_heads=2, vocab_size=64, intermediate_size=32)
    sd = _qwen_state_dict(cfg, rng)
    keys = sorted(sd)
    half = len(keys) // 2
    write_safetensors(str(tmp_path / "model-00001.safetensors"),
                      {k: sd[k] for k in keys[:half]})
    write_safetensors(str(tmp_path / "model-00002.safetensors"),
                      {k: sd[k] for k in keys[half:]})
    index = {"weight_map": {k: ("model-00001.safetensors" if i < half
                                else "model-00002.safetensors")
                            for i, k in enumerate(keys)}}
    (tmp_path / "model.safetensors.index.json").write_text(json.dumps(index))
    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "qwen2",
        "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "intermediate_size": cfg.intermediate_size,
        "max_position_embeddings": cfg.max_position_embeddings,
        "rms_norm_eps": cfg.norm_eps, "rope_theta": cfg.rope_theta,
        "tie_word_embeddings": True,
    }))
    got_cfg, got = load_checkpoint(str(tmp_path))
    assert got_cfg.family == "qwen2" and got_cfg.num_layers == 2
    want = params_from_state_dict(cfg, sd)
    np.testing.assert_array_equal(np.asarray(got["layers"]["wq"]),
                                  np.asarray(want["layers"]["wq"]))


def test_config_from_dir_rejects_unknown_family(tmp_path):
    (tmp_path / "config.json").write_text(json.dumps({"model_type": "mistral"}))
    with pytest.raises(ValueError, match="unsupported model_type"):
        config_from_dir(str(tmp_path))


def test_prepare_wikitext_joining(tmp_path):
    """Corpus construction pins the reference's "\\n\\n" join (main.py:122-124)."""
    from edgellm_tpu.tools.prepare_wikitext import load_texts, JOINER

    rows = [{"text": "alpha"}, {"text": ""}, {"text": "beta\n"}]
    p = tmp_path / "rows.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    texts, kind = load_texts(str(p))
    assert kind == "jsonl" and texts == ["alpha", "", "beta\n"]
    # empty rows are kept — wikitext is full of them and the reference joins
    # them too, producing the 4-newline runs the tokenizer sees
    assert JOINER.join(texts) == "alpha\n\n\n\nbeta\n"

    t = tmp_path / "joined.txt"
    t.write_text("already joined corpus")
    texts, kind = load_texts(str(t))
    assert kind == "joined-txt" and texts == ["already joined corpus"]
