"""Test configuration: force an 8-device virtual CPU platform before JAX initializes.

Multi-chip sharding paths (pipeline splits over a stage mesh, ppermute boundary
transfers) are exercised on a spoofed 8-device CPU mesh, per the reference test
strategy gap analysis (SURVEY.md section 4): the reference has no tests at all; we
test every layer of the stack on CPU so TPU runs are config changes, not code changes.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The environment pre-imports jax (sitecustomize) with JAX_PLATFORMS=axon (the real
# TPU tunnel); backends are lazy, so redirect to CPU before anything initializes.
import jax

jax.config.update("jax_platforms", "cpu")

# persistent compilation cache: the suite compiles hundreds of executables and
# reruns are dominated by recompilation; cache them across runs
_cache_dir = os.environ.get("EDGELLM_JAX_CACHE",
                            os.path.join(os.path.dirname(__file__), ".jax_cache"))
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
