"""The north-star checker: golden-table matching, tolerance semantics, and
the CLI contract (exit 0 only when every stable anchor is within ±0.1)."""
import json

import pytest

from edgellm_tpu.tools.check_reproduction import GOLDEN, check, main


def synth_result(perturb=0.0, collapse_factor=1.0):
    """A result dict whose cells equal the golden values (optionally off)."""
    methods = ["regular_importance", "last_row"]
    layers = [22, 18, 3, 23, 11]
    ratios = [0.0, 0.25, 0.5, 0.75, 1.0]
    ppl = [[[13.31 for _ in ratios] for _ in layers] for _ in methods]
    for method, layer, ratio, want, kind in GOLDEN:
        m, l, r = methods.index(method), layers.index(layer), ratios.index(ratio)
        ppl[m][l][r] = want * collapse_factor if kind == "collapse" \
            else want + perturb
    return {"axes": {"methods": methods, "layers_of_interest": layers,
                     "ratios": ratios}, "ppl": ppl}


def test_exact_result_passes():
    rows, failed = check(synth_result())
    assert failed == 0 and len(rows) == len(GOLDEN)


def test_within_tolerance_passes():
    _, failed = check(synth_result(perturb=0.09))
    assert failed == 0


def test_outside_tolerance_fails():
    rows, failed = check(synth_result(perturb=0.2))
    assert failed == sum(1 for *_abc, kind in GOLDEN if kind == "abs")
    assert any(not r["ok"] for r in rows)


def test_collapse_cells_check_factor_not_abs():
    _, failed = check(synth_result(collapse_factor=1.8))
    assert failed == 0  # within 2x: the collapse reproduced
    _, failed = check(synth_result(collapse_factor=3.0))
    assert failed == sum(1 for *_abc, kind in GOLDEN if kind == "collapse")


def test_partial_axes_skip_missing_cells():
    res = synth_result()
    res["axes"]["layers_of_interest"] = [3]  # keep the layer-3 column only
    res["ppl"] = [[method_ppl[2]] for method_ppl in res["ppl"]]
    rows, failed = check(res)
    assert failed == 0 and rows and all(r["layer"] == 3 for r in rows)


def test_cli_contract(tmp_path, capsys):
    path = tmp_path / "avg_ppl_results.json"
    path.write_text(json.dumps(synth_result()))
    assert main([str(path)]) == 0
    assert "anchors reproduced" in capsys.readouterr().out

    path.write_text(json.dumps(synth_result(perturb=0.5)))
    assert main([str(path)]) == 1
    assert "FAIL" in capsys.readouterr().out

    empty = synth_result()
    empty["axes"]["methods"] = ["aggregate_till"]
    path.write_text(json.dumps(empty))
    assert main([str(path)]) == 2  # no matching cells: tell the user which config


def test_non_token_sweep_results_get_guidance_not_traceback(tmp_path, capsys):
    """Channel/initial sweeps write the same filename with different axes."""
    path = tmp_path / "avg_ppl_results.json"
    path.write_text(json.dumps({  # channel sweep: no ratios axis
        "axes": {"methods": ["channel_8"], "layers_of_interest": [2]},
        "ppl": [[20.0]]}))
    assert main([str(path)]) == 2
    assert "no golden cells" in capsys.readouterr().out
    path.write_text(json.dumps({  # initial sweep: no methods, magic strings
        "axes": {"layers_of_interest": [1, "aggregate upto 2"],
                 "ratios": [0, 5]}, "ppl": [[1.0, 2.0], [3.0, 4.0]]}))
    assert main([str(path)]) == 2
