"""Faulty-link resilience: integrity detection, seeded injection, retry and
degradation policies, and the bit-exactness of the fault-disabled path.

The load-bearing claims, each asserted here:
- the canary + weighted-byte checksum detects EVERY single corrupted byte
  (odd weights are invertible mod 2**32) and every injected corruption the
  fault layer can produce — verification outcome == payload-unchanged, always;
- with a zero-fault active link the runtimes produce bit-identical logits to
  the plain build, and a disabled FaultConfig builds the plain graph itself;
- same seed => identical fault sequence => identical logits AND counters;
- retries genuinely recover, exhausted retries substitute (finite output),
  the byte budget statically squeezes oversized hops, and the host-side tier
  controller walks the codec ladder with hysteresis.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from edgellm_tpu.codecs.faults import (FaultConfig, LinkPolicy,
                                       TierController, inject_faults,
                                       payload_checksum, seal_payload,
                                       tree_nbytes, verify_payload)
from edgellm_tpu.models import init_params, tiny_config
from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh

CFG = tiny_config("qwen2", num_layers=6, hidden_size=32, num_heads=4,
                  vocab_size=128)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(1))


@pytest.fixture(scope="module")
def ids():
    rng = np.random.default_rng(5)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (1, 24)))


@pytest.fixture(scope="module")
def mesh():
    return make_stage_mesh(2)


SPLIT = SplitConfig(cuts=(2,), hop_codecs=("int8_per_token",))


def _counters(rt):
    return {k: v.tolist() for k, v in rt.link_counters().items()}


# ---------- integrity layer (no mesh) ----------


def _tiny_payload():
    return {"packed": jnp.arange(6, dtype=jnp.int8).reshape(2, 3),
            "scale": jnp.asarray([1.5, -2.25], jnp.float32)}


def test_checksum_detects_every_single_byte_flip():
    sealed = seal_payload(_tiny_payload())
    assert bool(verify_payload(sealed))
    for leaf_name in ("packed", "scale"):
        raw = bytearray(np.asarray(sealed["p"][leaf_name]).tobytes())
        template = np.asarray(sealed["p"][leaf_name])
        for pos in range(len(raw)):
            for bit in (0, 3, 7):
                mutated = bytearray(raw)
                mutated[pos] ^= 1 << bit
                leaf = np.frombuffer(bytes(mutated), template.dtype).reshape(
                    template.shape)
                corrupt = dict(sealed, p=dict(sealed["p"],
                                              **{leaf_name: jnp.asarray(leaf)}))
                assert not bool(verify_payload(corrupt)), \
                    f"byte {pos} bit {bit} of {leaf_name} slipped through"


def test_canary_dies_on_drop():
    sealed = jax.tree.map(jnp.zeros_like, seal_payload(_tiny_payload()))
    assert not bool(verify_payload(sealed))


def test_verification_outcome_equals_payload_unchanged():
    """100% detection: over many injection draws, the integrity check passes
    IFF the injector left every payload byte untouched."""
    cfg = FaultConfig(bitflip_rate=0.02, scale_corrupt_rate=0.05,
                      drop_rate=0.15)
    sealed = seal_payload(_tiny_payload())
    flat0 = [np.asarray(x) for x in jax.tree.leaves(sealed)]
    hits = 0
    for i in range(64):
        injected = inject_faults(sealed, jax.random.key(i), cfg)
        ok = bool(verify_payload(injected))
        # a corrupted sidecar (canary/crc byte) is a detected corruption too,
        # so "unchanged" is judged over the entire sealed tree
        unchanged = all(np.array_equal(a, np.asarray(b)) for a, b in
                        zip(flat0, jax.tree.leaves(injected)))
        assert ok == unchanged, f"draw {i}: verify={ok} unchanged={unchanged}"
        hits += not unchanged
    assert hits > 10  # the rates above must actually exercise detection


def test_injection_is_seed_deterministic():
    cfg = FaultConfig(bitflip_rate=0.05, drop_rate=0.2)
    sealed = seal_payload(_tiny_payload())
    a = inject_faults(sealed, jax.random.key(7), cfg)
    b = inject_faults(sealed, jax.random.key(7), cfg)
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_checksum_covers_leaf_order():
    """Identical bytes in different leaves hash differently (per-leaf salt)."""
    a = payload_checksum({"x": jnp.ones((4,), jnp.int8),
                          "y": jnp.zeros((4,), jnp.int8)})
    b = payload_checksum({"x": jnp.zeros((4,), jnp.int8),
                          "y": jnp.ones((4,), jnp.int8)})
    assert int(a) != int(b)


def test_tree_nbytes():
    assert tree_nbytes(_tiny_payload()) == 6 + 8


def test_fault_config_validation():
    with pytest.raises(ValueError):
        FaultConfig(drop_rate=1.5)
    with pytest.raises(ValueError):
        FaultConfig(byte_budget=0)
    with pytest.raises(ValueError):
        LinkPolicy(on_fail="explode")
    with pytest.raises(ValueError):
        LinkPolicy(max_retries=-1)
    assert not FaultConfig().enabled
    assert FaultConfig(byte_budget=1).enabled


def test_tier_controller_hysteresis():
    tc = TierController(3, degrade_after=2, recover_after=3)
    assert [tc.observe(c) for c in (True,)] == [0]  # 1 bad < degrade_after
    assert tc.observe(True) == 1      # 2 consecutive bad -> down
    assert tc.observe(True) == 1      # streak reset on switch
    assert tc.observe(True) == 2      # and again
    assert tc.observe(True) == 2      # floor
    assert [tc.observe(False) for _ in range(2)] == [2, 2]
    assert tc.observe(False) == 1     # 3 consecutive clean -> up
    assert tc.observe(True) == 1      # clean streak broken
    assert [tc.observe(False) for _ in range(3)] == [1, 1, 0]
    assert tc.switches == 4


# ---------- split runtime under faults ----------


def test_zero_fault_active_link_bit_exact(params, ids, mesh):
    """The whole sealed/verified/retry machinery at zero fault rate changes
    NOTHING: logits bit-identical to the plain runtime."""
    base = SplitRuntime(CFG, SPLIT, mesh)
    out0 = base.forward(base.place_params(params), ids)
    rt = SplitRuntime(CFG, SPLIT, mesh, faults=FaultConfig(byte_budget=10**9),
                      policy=LinkPolicy(max_retries=1))
    out1 = rt.forward(rt.place_params(params), ids, fault_step=3)
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(out1))
    c = _counters(rt)
    assert c["hops"] == [1] and c["detected"] == [0]
    assert c["substituted"] == [0] and c["budget_dropped"] == [0]


def test_disabled_config_builds_plain_graph(params, ids, mesh):
    rt = SplitRuntime(CFG, SPLIT, mesh, faults=FaultConfig())
    assert rt._link is None and rt.link_counters() is None
    base = SplitRuntime(CFG, SPLIT, mesh)
    np.testing.assert_array_equal(
        np.asarray(base.forward(base.place_params(params), ids)),
        np.asarray(rt.forward(rt.place_params(params), ids)))


def test_retry_recovers_and_counters_are_consistent(params, ids, mesh):
    rt = SplitRuntime(CFG, SPLIT, mesh,
                      faults=FaultConfig(drop_rate=0.5, seed=7),
                      policy=LinkPolicy(max_retries=4))
    placed = rt.place_params(params)
    for step in range(8):
        out = rt.forward(placed, ids, fault_step=step)
    assert np.isfinite(np.asarray(out)).all()
    c = _counters(rt)
    assert c["hops"] == [8]
    assert c["detected"][0] > 0 and c["recovered"][0] > 0
    # "detected" counts every failed attempt (retries included); each hop whose
    # first attempt failed ends as exactly one of recovered / substituted
    assert c["detected"][0] >= c["recovered"][0] + c["substituted"][0]
    assert c["recovered"][0] + c["substituted"][0] > 0
    assert c["retried"][0] >= c["recovered"][0]


def test_same_seed_same_faults_same_logits(params, ids, mesh):
    outs, counters = [], []
    for _ in range(2):
        rt = SplitRuntime(CFG, SPLIT, mesh,
                          faults=FaultConfig(drop_rate=0.5, seed=7),
                          policy=LinkPolicy(max_retries=4))
        placed = rt.place_params(params)
        acc = []
        for step in range(6):
            acc.append(np.asarray(rt.forward(placed, ids, fault_step=step)))
        outs.append(np.stack(acc))
        counters.append(_counters(rt))
    np.testing.assert_array_equal(outs[0], outs[1])
    assert counters[0] == counters[1]


def test_different_seed_different_faults(params, ids, mesh):
    got = []
    for seed in (1, 2):
        rt = SplitRuntime(CFG, SPLIT, mesh,
                          faults=FaultConfig(drop_rate=0.5, seed=seed))
        placed = rt.place_params(params)
        for step in range(6):
            rt.forward(placed, ids, fault_step=step)
        got.append(_counters(rt)["detected"][0])
    assert got[0] != got[1] or True  # drop sequences may coincide in count...
    # ...so assert on the full per-step stream instead
    streams = []
    for seed in (1, 2):
        rt = SplitRuntime(CFG, SPLIT, mesh,
                          faults=FaultConfig(drop_rate=0.5, seed=seed))
        placed = rt.place_params(params)
        stream = []
        for step in range(8):
            rt.forward(placed, ids, fault_step=step)
            stream.append(_counters(rt)["detected"][0])
        streams.append(stream)
    assert streams[0] != streams[1]


def test_total_loss_substitutes_finite_state(params, ids, mesh):
    rt = SplitRuntime(CFG, SPLIT, mesh, faults=FaultConfig(drop_rate=1.0))
    out = rt.forward(rt.place_params(params), ids)
    assert np.isfinite(np.asarray(out)).all()
    c = _counters(rt)
    assert c["detected"] == [1] and c["substituted"] == [1]
    assert c["recovered"] == [0]


def test_passthrough_policy_counts_but_decodes(params, ids, mesh):
    rt = SplitRuntime(CFG, SPLIT, mesh,
                      faults=FaultConfig(bitflip_rate=0.05, seed=2),
                      policy=LinkPolicy(on_fail="passthrough"))
    placed = rt.place_params(params)
    for step in range(4):
        out = rt.forward(placed, ids, fault_step=step)
    c = _counters(rt)
    assert c["detected"][0] > 0 and c["substituted"][0] > 0
    # passthrough accepts the corrupted decode (a flipped scale byte may even
    # be non-finite) — the contract is detection/counting, not clean output
    assert np.asarray(out).shape == (1, ids.shape[1], CFG.vocab_size)


def test_byte_budget_squeezes_hop(params, ids, mesh):
    rt = SplitRuntime(CFG, SPLIT, mesh, faults=FaultConfig(byte_budget=8))
    out = rt.forward(rt.place_params(params), ids)
    assert np.isfinite(np.asarray(out)).all()
    c = _counters(rt)
    assert c["budget_dropped"] == [1] and c["substituted"] == [1]


def test_faulty_decode_runs_and_zero_fault_decode_is_exact(params, ids, mesh):
    base = SplitRuntime(CFG, SPLIT, mesh)
    pb = base.place_params(params)
    logits0, cache0 = base.prefill_decode(pb, ids, capacity=32)
    tok = jnp.argmax(logits0[:, -1], -1).astype(jnp.int32)
    steps0 = []
    for _ in range(4):
        s, cache0 = base.decode_step(pb, cache0, tok)
        steps0.append(np.asarray(s))

    rt = SplitRuntime(CFG, SPLIT, mesh, faults=FaultConfig(byte_budget=10**9),
                      policy=LinkPolicy(max_retries=2))
    pz = rt.place_params(params)
    logits1, cache1 = rt.prefill_decode(pz, ids, capacity=32)
    np.testing.assert_array_equal(np.asarray(logits0), np.asarray(logits1))
    for i in range(4):
        s, cache1 = rt.decode_step(pz, cache1, tok)
        np.testing.assert_array_equal(steps0[i], np.asarray(s))
    assert _counters(rt)["detected"] == [0]

    rt_f = SplitRuntime(CFG, SPLIT, mesh,
                        faults=FaultConfig(drop_rate=0.5, seed=7),
                        policy=LinkPolicy(max_retries=4))
    pf = rt_f.place_params(params)
    logits, cache = rt_f.prefill_decode(pf, ids, capacity=32)
    for _ in range(4):
        logits, cache = rt_f.decode_step(pf, cache, tok)
    assert np.isfinite(np.asarray(logits)).all()
    assert _counters(rt_f)["hops"] == [5]  # prefill + 4 steps


def test_generate_split_zero_fault_bit_exact(params, ids, mesh):
    from edgellm_tpu.serve import generate_split

    base = SplitRuntime(CFG, SPLIT, mesh)
    out0 = generate_split(base, base.place_params(params), ids, 6)
    rt = SplitRuntime(CFG, SPLIT, mesh, faults=FaultConfig(byte_budget=10**9))
    st: dict = {}
    out1 = generate_split(rt, rt.place_params(params), ids, 6, stats=st)
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(out1))
    assert sum(st["link_counters"]["detected"]) == 0
    assert sum(st["link_counters"]["hops"]) == 6


# ---------- ring runtime under faults ----------


def test_ring_zero_fault_bit_exact_and_faulty_counters(params):
    from edgellm_tpu.parallel.ring import SplitRingRuntime, make_sp_stage_mesh

    mesh = make_sp_stage_mesh(2, 2)
    rng = np.random.default_rng(3)
    rids = jnp.asarray(rng.integers(0, CFG.vocab_size, (1, 16)))
    base = SplitRingRuntime(CFG, (2,), ["int8_per_token"], mesh)
    out0 = base.forward(base.place_params(params), rids)

    rt = SplitRingRuntime(CFG, (2,), ["int8_per_token"], mesh,
                          faults=FaultConfig(byte_budget=10**9),
                          policy=LinkPolicy(max_retries=1))
    out1 = rt.forward(rt.place_params(params), rids, fault_step=2)
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(out1))
    c = _counters(rt)
    assert c["hops"] == [2] and c["detected"] == [0]  # 1 hop x 2 seq shards

    rt_f = SplitRingRuntime(CFG, (2,), ["int8_per_token"], mesh,
                            faults=FaultConfig(drop_rate=0.5, seed=11),
                            policy=LinkPolicy(max_retries=3))
    pf = rt_f.place_params(params)
    for step in range(6):
        out = rt_f.forward(pf, rids, fault_step=step)
    assert np.isfinite(np.asarray(out)).all()
    cf = _counters(rt_f)
    assert cf["hops"] == [12] and cf["detected"][0] > 0
    assert cf["detected"][0] >= cf["recovered"][0] + cf["substituted"][0]
    assert cf["recovered"][0] + cf["substituted"][0] > 0


# ---------- eval integration ----------


def test_split_eval_faulty_reproducible_and_adaptive(params):
    from edgellm_tpu.eval.split_eval import run_split_eval

    toks = np.random.default_rng(0).integers(0, CFG.vocab_size, (1024,))
    kw = dict(cuts=(2,), hop_codecs=["int8_per_token"], max_length=64,
              stride=32, time_hops=False)

    base = run_split_eval(CFG, params, toks, **kw)
    act = run_split_eval(CFG, params, toks, faults={"byte_budget": 10**9},
                         **kw)
    assert act["ppl"] == base["ppl"]  # zero-fault active link: exact
    assert act["link_counters"]["detected"] == [0]

    runs = [run_split_eval(CFG, params, toks,
                           faults={"drop_rate": 0.4, "seed": 3},
                           link_policy={"max_retries": 2}, **kw)
            for _ in range(2)]
    assert runs[0]["ppl"] == runs[1]["ppl"]
    assert runs[0]["link_counters"] == runs[1]["link_counters"]
    assert runs[0]["link_counters"]["detected"][0] > 0

    ad = run_split_eval(CFG, params, toks,
                        faults={"bitflip_rate": 0.01, "seed": 1},
                        link_policy={"max_retries": 0,
                                     "tiers": ["int4_per_token",
                                               "ternary_per_token"],
                                     "degrade_after": 1, "recover_after": 50},
                        **kw)
    assert ad["final_tier"] > 0 and ad["degraded_chunks"] > 0
    assert ad["tier_ladder"][-1] == ["ternary_per_token"]
    assert ad["tier_switches"]  # (chunk, tier) trail is recorded
    assert np.isfinite(ad["ppl"])


def test_run_fault_sweep_rate_zero_is_exact_baseline(params):
    from edgellm_tpu.eval.split_eval import run_fault_sweep, run_split_eval

    toks = np.random.default_rng(0).integers(0, CFG.vocab_size, (512,))
    kw = dict(cuts=(2,), hop_codecs=["int8_per_token"], max_length=64,
              stride=32, time_hops=False)
    base = run_split_eval(CFG, params, toks, **kw)
    sweep = run_fault_sweep(CFG, params, toks, rates=[0.0, 0.5],
                            knob="drop_rate", **kw)
    assert sweep[0]["ppl"] == base["ppl"]
    assert "link_counters" not in sweep[0]
    assert sweep[1]["fault_rate"] == 0.5
    assert sweep[1]["link_counters"]["detected"][0] > 0
    with pytest.raises(ValueError):
        run_fault_sweep(CFG, params, toks, rates=[0.1], knob="gamma_rays",
                        **kw)
